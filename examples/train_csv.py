"""Distributed linear-regression SGD on a CSV file (BASELINE config #3
shape: CSV tabular allreduce SGD via dmlc-submit).

  dmlc-submit --cluster local --num-workers N -- \
      python examples/train_csv.py <uri> [epochs] [label_column]

Each worker reads InputSplit partition rank/world of the CSV through the
parser registry (format=csv, native multi-threaded chunk parse when the
C++ library is available), computes squared-loss gradients in JAX, and
synchronizes them with the tracker client's tree allreduce.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
# host-side SGD demo: many workers share one host, so default to the CPU
# backend (single-client accelerator tunnels can't serve N processes);
# export JAX_PLATFORMS yourself to target an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    uri = sys.argv[1] if len(sys.argv) > 1 else None
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    label_col = int(sys.argv[3]) if len(sys.argv) > 3 else 0
    assert uri, "usage: train_csv.py <uri> [epochs] [label_column]"

    import jax
    import jax.numpy as jnp

    from dmlc_tpu.data import create_row_iter
    from dmlc_tpu.feed.device_feed import pack_rowblock
    from dmlc_tpu.tracker.client import TrackerClient

    client = TrackerClient()
    client.start()
    rank, world = client.rank, client.world_size

    it = create_row_iter(f"{uri}?format=csv&label_column={label_col}",
                         rank, world, "auto")
    num_col = int(client.allreduce(
        np.array([it.num_col()], np.int64), op="max")[0])
    num_col = max(num_col, 1)

    @jax.jit
    def grad_step(w, value, index, mask, label):
        def loss_fn(w):
            pred = jnp.sum(value * mask * w[index], axis=1)
            return jnp.mean(jnp.square(pred - label))
        return jax.value_and_grad(loss_fn)(w)

    batches = []
    for blk in it:
        for lo in range(0, blk.size, 256):
            sub = blk.slice(lo, min(lo + 256, blk.size))
            batches.append(pack_rowblock(sub, 256, num_col, num_col))
    n_steps = int(client.allreduce(
        np.array([len(batches)], np.int64), op="max")[0])
    # explicit shapes: a rank with an EMPTY partition still needs padding
    # batches to stay in lockstep with the allreduce
    zero = {"label": np.zeros(256, np.float32),
            "value": np.zeros((256, num_col), np.float32),
            "index": np.zeros((256, num_col), np.int32),
            "mask": np.zeros((256, num_col), np.float32)}

    w = jnp.zeros(num_col, jnp.float32)
    lr = 0.1
    for epoch in range(epochs):
        total = 0.0
        for i in range(n_steps):
            b = batches[i] if i < len(batches) else zero
            loss, g = grad_step(w, b["value"], b["index"], b["mask"],
                                b["label"])
            g_sum = client.allreduce_sum(np.asarray(g, np.float64))
            w = w - lr * jnp.asarray(g_sum / world, jnp.float32)
            total += float(loss)
        client.log(f"rank {rank}: epoch {epoch} mse "
                   f"{total / max(len(batches), 1):.4f}")
    client.shutdown()


if __name__ == "__main__":
    main()
