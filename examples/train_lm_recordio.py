"""Train the transformer LM from RecordIO token shards — the full TPU
spine in one script (BASELINE configs #2/#5 shape): InputSplit →
device feed → sharded model → checkpoint/resume → metrics.

  python examples/train_lm_recordio.py <shards.rec> [steps] [ckpt_dir]

With a checkpoint dir the run resumes from the latest step-numbered
checkpoint (CheckpointManager over the Stream/URI layer, so the same
path works with gs://) and saves every 20 steps.

Each RecordIO record holds a fixed-length sequence of int32 token ids.
The packed device feed streams records into HBM; the model trains with
whatever mesh the local devices support (1 chip → trivial mesh; under a
multi-chip runtime the same code shards over dp).  Run
`python examples/train_lm_recordio.py --make-data out.rec` first to
generate a synthetic shard.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SEQ = 128
VOCAB = 512


def make_data(path, n_records=2048, seed=0):
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    rng = np.random.default_rng(seed)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(n_records):
            # a learnable distribution: arithmetic sequences mod VOCAB.
            # SEQ+1 tokens per record so ids/labels split without the
            # wrap-around garbage target a plain roll would create
            start, step = rng.integers(0, VOCAB), rng.integers(1, 7)
            ids = (start + step * np.arange(SEQ + 1)) % VOCAB
            w.write_record(ids.astype(np.int32).tobytes())
    print(f"wrote {n_records} records to {path}")


def main():
    if len(sys.argv) < 2:
        print("usage: train_lm_recordio.py (<shards.rec> [steps] "
              "[ckpt_dir] | --make-data <out.rec>)", file=sys.stderr)
        sys.exit(2)
    if sys.argv[1] == "--make-data":
        make_data(sys.argv[2])
        return
    uri = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None

    import jax
    import jax.numpy as jnp
    import optax

    from dmlc_tpu import metrics
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.models import (TransformerConfig, init_params,
                                 make_train_step)
    from dmlc_tpu.parallel import build_mesh
    from dmlc_tpu.parallel.collectives import initialize_distributed

    # under dmlc-submit with world > 1 this joins every launched process
    # into one jax.distributed job (coordinator allocated by the tracker,
    # DMLC_JAX_COORD_URI/PORT) so jax.devices() below spans the whole pod;
    # no-op single-process
    initialize_distributed()

    n_dev = len(jax.devices())
    mesh = build_mesh(n_dev, dp=n_dev, sp=1, tp=1, pp=1, ep=1)
    cfg = TransformerConfig(
        vocab=VOCAB, d_model=256, n_heads=4, head_dim=64, d_ff=512,
        n_layers=4, n_experts=1, microbatches=1,
        dtype="bfloat16" if jax.devices()[0].platform == "tpu"
        else "float32",
        remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    # ledger=False: this loop drives the step ledger ITSELF so the
    # batch fetch lands inside the step window — feed.wait is then
    # billed to the step's feed-wait share (make_train_step's built-in
    # ledger would only see the compute half)
    step, init_state = make_train_step(
        mesh, cfg, optimizer=optax.adamw(3e-4), ledger=False)
    opt_state = init_state(params)

    manager = start_at = None
    if ckpt_dir:
        from dmlc_tpu.checkpoint import CheckpointManager

        manager = CheckpointManager(ckpt_dir, max_to_keep=2)
        # faithful resume: params AND optimizer moments/step count travel
        # together (restoring params alone would reset AdamW's state)
        start_at, restored = manager.restore_latest(
            {"params": params, "opt": opt_state}, mesh=mesh)
        if start_at is not None:
            params, opt_state = restored["params"], restored["opt"]
            print(f"resumed from step {start_at}", flush=True)

    per_part = 8  # records per partition per batch
    feed = recordio_feed(uri, mesh, batch_records=per_part,
                         max_bytes=(SEQ + 1) * 4)
    from dmlc_tpu import telemetry
    from dmlc_tpu.models import train_flops_per_token

    telemetry.declare_flops_per_token(train_flops_per_token(cfg, SEQ))
    done = 0
    # data fast-forward: this feed is deterministic, so replaying
    # start_at batches puts the stream exactly where the saved run was
    # (a demo-grade skip — it pays full pipeline + transfer cost per
    # discarded batch; production resumes would skip at the host side)
    skip = start_at or 0
    feed_iter = iter(feed)
    while done < steps:
        # the step ledger opens BEFORE the batch pull so the feed's
        # consumer wait (feed.wait span) is billed to this step's
        # feed-wait share; skipped/tail batches abandon the open step
        # (the next step_begin unwinds it) and are never recorded
        telemetry.step_begin()
        batch = next(feed_iter, None)
        if batch is None:
            feed_iter = iter(feed)  # next epoch
            continue
        # epoch-tail short batch: its zero-padded rows would train on
        # all-zero tokens (garbage targets).  Dropped BEFORE the
        # resume fast-forward so never-trained batches don't consume
        # `skip` — step count stays equal to trained-batch count
        if np.any(np.asarray(batch["length"]) == 0):
            continue
        if skip > 0:
            skip -= 1
            continue
        with metrics.annotate("train_step"):
            data = jnp.asarray(batch["data"])
            toks = jax.lax.bitcast_convert_type(
                data.reshape(-1, SEQ + 1, 4), jnp.int32
            ).reshape(-1, SEQ + 1)
            ids, labels = toks[:, :-1], toks[:, 1:]
            params, opt_state, loss = step(params, opt_state, ids,
                                           labels)
        telemetry.step_end(tokens=int(ids.size))
        done += 1
        if done % 10 == 0 or done == 1:
            print(f"step {done}: loss {float(loss):.4f}", flush=True)
        if manager is not None and done % 20 == 0:
            manager.save((start_at or 0) + done,
                         {"params": params, "opt": opt_state})
    if manager is not None and done % 20 != 0:  # periodic save already hit
        manager.save((start_at or 0) + done,
                     {"params": params, "opt": opt_state})
    snap = metrics.snapshot()
    fed = snap.get("feed", {})
    led = telemetry.ledger().summary()
    print(f"final loss {float(loss):.4f}; feed moved "
          f"{fed.get('bytes_to_device', 0) / 1e6:.1f} MB in "
          f"{int(fed.get('batches', 0))} batches")
    if led:
        mfu = led.get("mfu")
        print(f"ledger: step p50 {led['step_time_p50'] * 1e3:.1f} ms, "
              f"p99 {led['step_time_p99'] * 1e3:.1f} ms, feed-wait "
              f"{led['feed_wait_fraction'] * 100:.0f}%, goodput "
              f"{led.get('goodput_tokens_per_s', 0):,.0f} tok/s"
              + (f", MFU {mfu * 100:.1f}%" if mfu is not None else ""))


if __name__ == "__main__":
    main()
