"""Train the transformer LM from RecordIO token shards — the full TPU
spine in one script (BASELINE configs #2/#5 shape): InputSplit →
device feed → sharded model → checkpoint/resume → metrics.

  python examples/train_lm_recordio.py <shards.rec> [steps] [ckpt_dir]

With a checkpoint dir the run resumes from the latest step-numbered
checkpoint (CheckpointManager over the Stream/URI layer, so the same
path works with gs://) and saves every 20 steps.

Each RecordIO record holds a fixed-length sequence of int32 token ids.
The packed device feed streams records into HBM; the model trains with
whatever mesh the local devices support (1 chip → trivial mesh; under a
multi-chip runtime the same code shards over dp).  Run
`python examples/train_lm_recordio.py --make-data out.rec` first to
generate a synthetic shard.

Elastic mode (DMLC_ELASTIC=1 under an elastic tracker, ckpt_dir
required): each process joins the tracker world, partitions data by
(rank, world) through the byte-range contract, averages gradients over
the host collective, and SURVIVES the world resizing mid-run — a
collective interrupted by a preempted peer raises WorldResized; the
loop re-enters rendezvous (possibly under a new rank), repartitions the
feed in place, restores params+optimizer state from the last COMMITTED
checkpoint onto the mesh, and keeps training without a process restart.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SEQ = 128
VOCAB = 512


def make_data(path, n_records=2048, seed=0):
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    rng = np.random.default_rng(seed)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(n_records):
            # a learnable distribution: arithmetic sequences mod VOCAB.
            # SEQ+1 tokens per record so ids/labels split without the
            # wrap-around garbage target a plain roll would create
            start, step = rng.integers(0, VOCAB), rng.integers(1, 7)
            ids = (start + step * np.arange(SEQ + 1)) % VOCAB
            w.write_record(ids.astype(np.int32).tobytes())
    print(f"wrote {n_records} records to {path}")


def _elastic_enabled() -> bool:
    from dmlc_tpu.base import get_env

    return get_env("DMLC_ELASTIC", False) \
        and bool(os.environ.get("DMLC_TRACKER_URI"))


class _ElasticTrainer:
    """The elastic half of the loop: tracker membership, host-collective
    gradient averaging, and the WorldResized recovery protocol."""

    def __init__(self, manager, mesh):
        from dmlc_tpu.telemetry import HeartbeatSender
        from dmlc_tpu.tracker.client import TrackerClient

        self.client = TrackerClient().start()
        self.hb = HeartbeatSender(self.client, interval=1.0)
        self.manager = manager
        self.mesh = mesh

    @property
    def world(self):
        return (self.client.rank, self.client.world_size)

    @staticmethod
    def _flatten(tree):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        flat = np.concatenate(
            [np.asarray(v, np.float64).ravel() for v in leaves])
        return leaves, treedef, flat

    @staticmethod
    def _unflatten(leaves, treedef, flat):
        import jax

        out, pos = [], 0
        for v in leaves:
            n = int(np.size(v))
            out.append(flat[pos: pos + n].reshape(np.shape(v)).astype(
                np.asarray(v).dtype))
            pos += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def allreduce_grads(self, grads, loss: float):
        """Average gradients (and the loss) over the elastic world via
        the host collective; raises WorldResized on membership change."""
        leaves, treedef, flat = self._flatten(grads)
        flat = np.concatenate([flat.astype(np.float32),
                               np.asarray([loss], np.float32)])
        total = self.client.allreduce_sum(flat)
        total /= float(self.client.world_size)
        return (self._unflatten(leaves, treedef, total[:-1]),
                float(total[-1]))

    def resync(self, feed, params, opt_state, done: int):
        """WorldResized recovery: re-enter rendezvous, repartition the
        feed, then make rank 0's state authoritative everywhere.

        Rank 0 restores the last COMMITTED checkpoint when one exists
        (its own memory otherwise — early preemptions before the first
        save) and broadcasts (params, opt_state, step) to the new
        world: the interrupted step's allreduce may have completed on
        some ranks and not others, so replicas are one step apart
        until this broadcast realigns them.  May itself raise
        WorldResized (another resize mid-recovery); callers loop."""
        self.client.resize()
        feed.resize(self.world)
        if self.client.rank == 0:
            step, restored = self.manager.restore_latest(
                {"params": params, "opt": opt_state}, mesh=self.mesh)
            if step is not None:
                params, opt_state, done = (restored["params"],
                                           restored["opt"], step)
        leaves, treedef, flat = self._flatten((params, opt_state))
        if self.client.rank != 0:
            flat = np.zeros_like(flat)  # shapes/dtypes are uniform
        flat = self.client.broadcast(
            np.concatenate([flat, [float(done)]]), root=0)
        params, opt_state = self._unflatten(leaves, treedef, flat[:-1])
        done = int(flat[-1])
        print(f"resized into rank {self.client.rank}/"
              f"{self.client.world_size} (gen {self.client.gen}); "
              f"resynced at step {done}", flush=True)
        return params, opt_state, done

    def close(self):
        self.hb.close()
        self.client.shutdown()


def main():
    if len(sys.argv) < 2:
        print("usage: train_lm_recordio.py (<shards.rec> [steps] "
              "[ckpt_dir] | --make-data <out.rec>)", file=sys.stderr)
        sys.exit(2)
    if sys.argv[1] == "--make-data":
        make_data(sys.argv[2])
        return
    uri = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None

    import jax
    import jax.numpy as jnp
    import optax

    from dmlc_tpu import metrics
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.models import (TransformerConfig, init_params,
                                 make_train_step, unsharded_loss)
    from dmlc_tpu.parallel import build_mesh
    from dmlc_tpu.parallel.collectives import initialize_distributed
    from dmlc_tpu.tracker.client import WorldResized

    elastic = _elastic_enabled()
    if not elastic:
        # under dmlc-submit with world > 1 this joins every launched
        # process into one jax.distributed job (coordinator allocated by
        # the tracker, DMLC_JAX_COORD_URI/PORT) so jax.devices() below
        # spans the whole pod; no-op single-process.  Elastic mode keeps
        # processes independent instead — jax.distributed gangs cannot
        # resize, the host collective can.
        initialize_distributed()

    n_dev = len(jax.devices())
    mesh = build_mesh(n_dev, dp=n_dev, sp=1, tp=1, pp=1, ep=1)
    cfg = TransformerConfig(
        vocab=VOCAB, d_model=256, n_heads=4, head_dim=64, d_ff=512,
        n_layers=4, n_experts=1, microbatches=1,
        dtype="bfloat16" if jax.devices()[0].platform == "tpu"
        else "float32",
        remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    optimizer = optax.adamw(3e-4)
    if not elastic:
        # ledger=False: this loop drives the step ledger ITSELF so the
        # batch fetch lands inside the step window — feed.wait is then
        # billed to the step's feed-wait share (make_train_step's
        # built-in ledger would only see the compute half)
        step, init_state = make_train_step(
            mesh, cfg, optimizer=optimizer, ledger=False)
        opt_state = init_state(params)
    else:
        # elastic mode shards nothing across processes at the XLA layer
        # (a jax.distributed gang cannot resize); every process holds a
        # full replica and the host collective averages gradients
        opt_state = optimizer.init(params)

    manager = start_at = None
    if ckpt_dir:
        from dmlc_tpu.checkpoint import CheckpointManager

        manager = CheckpointManager(ckpt_dir, max_to_keep=2)
        # faithful resume: params AND optimizer moments/step count travel
        # together (restoring params alone would reset AdamW's state)
        start_at, restored = manager.restore_latest(
            {"params": params, "opt": opt_state}, mesh=mesh)
        if start_at is not None:
            params, opt_state = restored["params"], restored["opt"]
            print(f"resumed from step {start_at}", flush=True)

    trainer = None
    if elastic:
        assert manager is not None, \
            "elastic mode needs a checkpoint dir (resize restores from it)"
        trainer = _ElasticTrainer(manager, mesh)
        # elastic gradient path: local loss+grads, host-allreduce mean,
        # then a jitted optax apply — the data plane XLA cannot resize,
        # the host collective can
        loss_and_grad = jax.jit(jax.value_and_grad(
            lambda p, ids, labels: unsharded_loss(p, ids, labels, cfg)))

        @jax.jit
        def apply_update(p, o, grads):
            updates, o2 = optimizer.update(grads, o, p)
            return optax.apply_updates(p, updates), o2

    per_part = 8  # records per partition per batch
    feed = recordio_feed(uri, mesh, batch_records=per_part,
                         max_bytes=(SEQ + 1) * 4,
                         world=trainer.world if trainer else None)
    from dmlc_tpu import telemetry
    from dmlc_tpu.models import train_flops_per_token

    telemetry.declare_flops_per_token(train_flops_per_token(cfg, SEQ))
    done = 0
    # non-elastic: done counts NEW steps this process trains; saves are
    # numbered base+done so a resumed run never re-commits old numbers
    base = start_at or 0
    # data fast-forward: this feed is deterministic, so replaying
    # start_at batches puts the stream exactly where the saved run was
    # (a demo-grade skip — it pays full pipeline + transfer cost per
    # discarded batch; production resumes would skip at the host side)
    skip = start_at or 0
    if elastic and start_at:
        # elastic restores are repartition points, not replays: done is
        # the ABSOLUTE step (base stays 0) and the stream restarts
        done = start_at
        skip = 0
    feed_iter = iter(feed)
    loss = float("nan")
    need_resync = False
    while done < steps:
        # the step ledger opens BEFORE the batch pull so the feed's
        # consumer wait (feed.wait span) is billed to this step's
        # feed-wait share; skipped/tail batches abandon the open step
        # (the next step_begin unwinds it) and are never recorded
        telemetry.step_begin()
        try:
            if trainer is not None:
                if need_resync:
                    params, opt_state, done = trainer.resync(
                        feed, params, opt_state, done)
                    feed_iter = iter(feed)
                    need_resync = False
                trainer.client.check_resized()
            batch = next(feed_iter, None)
            if batch is None:
                feed_iter = iter(feed)  # next epoch
                continue
            # epoch-tail short batch: its zero-padded rows would train on
            # all-zero tokens (garbage targets).  Dropped BEFORE the
            # resume fast-forward so never-trained batches don't consume
            # `skip` — step count stays equal to trained-batch count
            if np.any(np.asarray(batch["length"]) == 0):
                continue
            if skip > 0:
                skip -= 1
                continue
            with metrics.annotate("train_step"):
                data = jnp.asarray(batch["data"])
                toks = jax.lax.bitcast_convert_type(
                    data.reshape(-1, SEQ + 1, 4), jnp.int32
                ).reshape(-1, SEQ + 1)
                ids, labels = toks[:, :-1], toks[:, 1:]
                if trainer is None:
                    params, opt_state, loss = step(params, opt_state, ids,
                                                   labels)
                else:
                    local_loss, grads = loss_and_grad(params, ids, labels)
                    grads, loss = trainer.allreduce_grads(
                        grads, float(local_loss))
                    params, opt_state = apply_update(params, opt_state,
                                                     grads)
        except WorldResized:
            # recovery happens at the top of the next iteration (the
            # resync broadcast can itself hit another resize, and it
            # must run under this same handler)
            need_resync = True
            continue
        telemetry.step_end(tokens=int(ids.size))
        done += 1
        if done % 10 == 0 or done == 1:
            print(f"step {done}: loss {float(loss):.4f}", flush=True)
        if manager is not None and done % 20 == 0 \
                and (trainer is None or trainer.client.rank == 0):
            manager.save(base + done, {"params": params, "opt": opt_state})
    if manager is not None and done % 20 != 0 \
            and (trainer is None or trainer.client.rank == 0):
        # periodic save already hit on multiples of 20
        manager.save(base + done, {"params": params, "opt": opt_state})
    if trainer is not None:
        trainer.close()
    snap = metrics.snapshot()
    fed = snap.get("feed", {})
    led = telemetry.ledger().summary()
    print(f"final loss {float(loss):.4f}; feed moved "
          f"{fed.get('bytes_to_device', 0) / 1e6:.1f} MB in "
          f"{int(fed.get('batches', 0))} batches")
    if led:
        mfu = led.get("mfu")
        print(f"ledger: step p50 {led['step_time_p50'] * 1e3:.1f} ms, "
              f"p99 {led['step_time_p99'] * 1e3:.1f} ms, feed-wait "
              f"{led['feed_wait_fraction'] * 100:.0f}%, goodput "
              f"{led.get('goodput_tokens_per_s', 0):,.0f} tok/s"
              + (f", MFU {mfu * 100:.1f}%" if mfu is not None else ""))


if __name__ == "__main__":
    main()
