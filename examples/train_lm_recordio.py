"""Train the transformer LM from RecordIO token shards — the full TPU
spine in one script (BASELINE configs #2/#5 shape): InputSplit →
device feed → sharded model → checkpoint/resume → metrics.

  python examples/train_lm_recordio.py <shards.rec> [steps] [ckpt_dir]

With a checkpoint dir the run resumes from the latest step-numbered
checkpoint (CheckpointManager over the Stream/URI layer, so the same
path works with gs://) and saves every 20 steps.

Each RecordIO record holds a fixed-length sequence of int32 token ids.
The packed device feed streams records into HBM; the model trains with
whatever mesh the local devices support (1 chip → trivial mesh; under a
multi-chip runtime the same code shards over dp).  Run
`python examples/train_lm_recordio.py --make-data out.rec` first to
generate a synthetic shard.

Elastic mode (DMLC_ELASTIC=1 under an elastic tracker, ckpt_dir
required): each process joins the tracker world, partitions data by
(rank, world) through the byte-range contract, averages gradients over
the host collective, and SURVIVES the world resizing mid-run — a
collective interrupted by a preempted peer raises WorldResized; the
loop re-enters rendezvous (possibly under a new rank), repartitions the
feed in place, restores params+optimizer state from the last COMMITTED
checkpoint onto the mesh, and keeps training without a process restart.

Self-healing (resilience.selfheal): every step's loss and gradient
norm pass through a SelfHealGuard — a non-finite or EWMA-spiking step
is SKIPPED (jax arrays are immutable, so reverting to the pre-step
(params, opt_state) references is free); DMLC_SELFHEAL_MAX_SKIPS
consecutive skips trigger a ROLLBACK-AND-REPLAY to the last committed
checkpoint (the WorldResized recovery path's restore/resync machinery,
reused) with integrity-quarantined spans skip-listed out of the replay;
exhausted rollbacks ABORT with a postmortem naming the suspect spans.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SEQ = 128
VOCAB = 512


def make_data(path, n_records=2048, seed=0):
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    rng = np.random.default_rng(seed)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(n_records):
            # a learnable distribution: arithmetic sequences mod VOCAB.
            # SEQ+1 tokens per record so ids/labels split without the
            # wrap-around garbage target a plain roll would create
            start, step = rng.integers(0, VOCAB), rng.integers(1, 7)
            ids = (start + step * np.arange(SEQ + 1)) % VOCAB
            w.write_record(ids.astype(np.int32).tobytes())
    print(f"wrote {n_records} records to {path}")


def _elastic_enabled() -> bool:
    from dmlc_tpu.base import get_env

    return get_env("DMLC_ELASTIC", False) \
        and bool(os.environ.get("DMLC_TRACKER_URI"))


class _ElasticTrainer:
    """The elastic half of the loop: tracker membership, host-collective
    gradient averaging, and the WorldResized recovery protocol."""

    def __init__(self, manager, mesh):
        from dmlc_tpu.base import get_env
        from dmlc_tpu.parallel.overlap import GradientBucketer
        from dmlc_tpu.telemetry import HeartbeatSender
        from dmlc_tpu.tracker.client import TrackerClient

        self.client = TrackerClient().start()
        self.hb = HeartbeatSender(self.client, interval=1.0)
        self.manager = manager
        self.mesh = mesh
        # overlapped gradient reduction (DMLC_COLL_OVERLAP=0 opts out):
        # buckets allreduce on a background thread while later leaves
        # are still being fetched off-device and packed; a WorldResized
        # raised on that thread transports through the bucket futures
        # and re-raises at the join, inside the existing recovery path
        # in-place (out=a) on the bucket buffers the bucketer owns: the
        # steady-state gradient exchange allocates nothing per bucket
        self.bucketer = (
            GradientBucketer(lambda a: self.client.allreduce_sum(a, out=a))
            if get_env("DMLC_COLL_OVERLAP", True) else None)

    @property
    def world(self):
        return (self.client.rank, self.client.world_size)

    @staticmethod
    def _flatten(tree):
        import jax

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        flat = np.concatenate(
            [np.asarray(v, np.float64).ravel() for v in leaves])
        return leaves, treedef, flat

    @staticmethod
    def _unflatten(leaves, treedef, flat):
        import jax

        out, pos = [], 0
        for v in leaves:
            n = int(np.size(v))
            out.append(flat[pos: pos + n].reshape(np.shape(v)).astype(
                np.asarray(v).dtype))
            pos += n
        return jax.tree_util.tree_unflatten(treedef, out)

    def allreduce_grads(self, grads, loss: float):
        """Average gradients (and the loss) over the elastic world via
        the host collective; raises WorldResized on membership change.
        Also returns the global grad norm (computed on the AVERAGED
        gradients, so every rank reaches the same self-heal verdict)."""
        if self.bucketer is not None:
            return self._allreduce_grads_overlapped(grads, loss)
        leaves, treedef, flat = self._flatten(grads)
        flat = np.concatenate([flat.astype(np.float32),
                               np.asarray([loss], np.float32)])
        total = self.client.allreduce_sum(flat)
        total /= float(self.client.world_size)
        gnorm = float(np.sqrt(np.sum(np.square(total[:-1]),
                                     dtype=np.float64)))
        return (self._unflatten(leaves, treedef, total[:-1]),
                float(total[-1]), gnorm)

    def _allreduce_grads_overlapped(self, grads, loss: float):
        """Bucketed-overlapped version of ``allreduce_grads``: leaves
        are packed reverse-topologically into DMLC_COLL_BUCKET_MB
        buckets, each bucket's allreduce runs on the bucketer's
        background thread while later leaves are still converted and
        packed, and the join re-raises any collective-thread exception
        (incl. WorldResized) here.  All-or-nothing: on failure the
        input gradients are untouched."""
        import jax

        w = float(self.client.world_size)
        red_loss, red = self.bucketer.reduce_tree(
            (np.asarray([loss], np.float32), grads))
        gnorm = float(np.sqrt(sum(
            float(np.sum(np.square(np.asarray(r, np.float64) / w)))
            for r in jax.tree_util.tree_leaves(red))))
        avg = jax.tree_util.tree_map(
            lambda r, g: (r / w).astype(np.asarray(g).dtype), red, grads)
        return avg, float(red_loss[0]) / w, gnorm

    def _broadcast_state(self, params, opt_state, done: int):
        """Make rank 0's (params, opt_state, step) authoritative
        everywhere — the shared tail of resync and rollback.  Rank 0
        restores the last COMMITTED checkpoint when one exists (its own
        memory otherwise) and broadcasts to the world."""
        if self.client.rank == 0:
            step, restored = self.manager.restore_latest(
                {"params": params, "opt": opt_state}, mesh=self.mesh)
            if step is not None:
                params, opt_state, done = (restored["params"],
                                           restored["opt"], step)
        leaves, treedef, flat = self._flatten((params, opt_state))
        if self.client.rank != 0:
            flat = np.zeros_like(flat)  # shapes/dtypes are uniform
        flat = self.client.broadcast(
            np.concatenate([flat, [float(done)]]), root=0)
        params, opt_state = self._unflatten(leaves, treedef, flat[:-1])
        return params, opt_state, int(flat[-1])

    def resync(self, feed, params, opt_state, done: int):
        """WorldResized recovery: re-enter rendezvous, repartition the
        feed, then make rank 0's state authoritative everywhere.

        The interrupted step's allreduce may have completed on some
        ranks and not others, so replicas are one step apart until the
        broadcast realigns them.  May itself raise WorldResized
        (another resize mid-recovery); callers loop."""
        self.client.resize()
        feed.resize(self.world)
        params, opt_state, done = self._broadcast_state(
            params, opt_state, done)
        print(f"resized into rank {self.client.rank}/"
              f"{self.client.world_size} (gen {self.client.gen}); "
              f"resynced at step {done}", flush=True)
        return params, opt_state, done

    def rollback(self, feed, params, opt_state, done: int):
        """Self-heal rollback-and-replay: same restore/broadcast
        machinery as resync, but membership is unchanged — only the
        state rolls back (and the data stream restarts; quarantined
        spans are skip-listed out by the readers).  The guard's verdict
        is deterministic on the allreduced loss, so every rank calls
        this on the same step without coordination."""
        feed.close()  # abandon the in-flight epoch before re-iterating
        params, opt_state, done = self._broadcast_state(
            params, opt_state, done)
        print(f"selfheal: rolled back to committed step {done} "
              f"(rank {self.client.rank})", flush=True)
        return params, opt_state, done

    def close(self):
        if self.bucketer is not None:
            self.bucketer.close()
        self.hb.close()
        self.client.shutdown()


def main():
    if len(sys.argv) < 2:
        print("usage: train_lm_recordio.py (<shards.rec> [steps] "
              "[ckpt_dir] | --make-data <out.rec>)", file=sys.stderr)
        sys.exit(2)
    if sys.argv[1] == "--make-data":
        make_data(sys.argv[2])
        return
    uri = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 30
    ckpt_dir = sys.argv[3] if len(sys.argv) > 3 else None

    import jax
    import jax.numpy as jnp
    import optax

    from dmlc_tpu import metrics
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.models import (TransformerConfig, init_params,
                                 make_train_step, unsharded_loss)
    from dmlc_tpu.parallel import build_mesh
    from dmlc_tpu.parallel.collectives import initialize_distributed
    from dmlc_tpu.tracker.client import WorldResized

    elastic = _elastic_enabled()
    if not elastic:
        # under dmlc-submit with world > 1 this joins every launched
        # process into one jax.distributed job (coordinator allocated by
        # the tracker, DMLC_JAX_COORD_URI/PORT) so jax.devices() below
        # spans the whole pod; no-op single-process.  Elastic mode keeps
        # processes independent instead — jax.distributed gangs cannot
        # resize, the host collective can.
        initialize_distributed()

    n_dev = len(jax.devices())
    mesh = build_mesh(n_dev, dp=n_dev, sp=1, tp=1, pp=1, ep=1)
    cfg = TransformerConfig(
        vocab=VOCAB, d_model=256, n_heads=4, head_dim=64, d_ff=512,
        n_layers=4, n_experts=1, microbatches=1,
        dtype="bfloat16" if jax.devices()[0].platform == "tpu"
        else "float32",
        remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    optimizer = optax.adamw(3e-4)
    if not elastic:
        # ledger=False: this loop drives the step ledger ITSELF so the
        # batch fetch lands inside the step window — feed.wait is then
        # billed to the step's feed-wait share (make_train_step's
        # built-in ledger would only see the compute half).
        # grad_norm=True: the self-heal guard checks the global grad
        # norm each step, catching NaNs before the loss shows them
        step, init_state = make_train_step(
            mesh, cfg, optimizer=optimizer, ledger=False, grad_norm=True)
        opt_state = init_state(params)
    else:
        # elastic mode shards nothing across processes at the XLA layer
        # (a jax.distributed gang cannot resize); every process holds a
        # full replica and the host collective averages gradients
        opt_state = optimizer.init(params)

    def _restore_with_stream(mgr, tmpl, mesh, with_stream=True):
        """restore_latest including the persisted stream position (the
        count of quality batches consumed when the checkpoint
        committed); pre-PR checkpoints lack the leaf and restore with
        position unknown.  ``with_stream=False`` skips the probe —
        elastic checkpoints never carry the leaf, and probing would
        fully restore every shard before the miss is detected (2x
        checkpoint read I/O on every elastic resume)."""
        from dmlc_tpu.checkpoint import MissingLeaf

        if not with_stream:
            step, restored = mgr.restore_latest(dict(tmpl), mesh=mesh)
            return step, restored, None
        try:
            step, restored = mgr.restore_latest(
                dict(tmpl, stream=np.zeros(1, np.int64)), mesh=mesh)
        except MissingLeaf:
            step, restored = mgr.restore_latest(dict(tmpl), mesh=mesh)
            return step, restored, None
        if step is None:
            return None, None, None
        return step, restored, int(np.asarray(restored["stream"])[0])

    manager = start_at = stream_resume = None
    if ckpt_dir:
        from dmlc_tpu.checkpoint import CheckpointManager

        manager = CheckpointManager(ckpt_dir, max_to_keep=2)
        # faithful resume: params AND optimizer moments/step count travel
        # together (restoring params alone would reset AdamW's state)
        start_at, restored, stream_resume = _restore_with_stream(
            manager, {"params": params, "opt": opt_state}, mesh,
            with_stream=not elastic)
        if start_at is not None:
            params, opt_state = restored["params"], restored["opt"]
            print(f"resumed from step {start_at}", flush=True)

    trainer = None
    if elastic:
        assert manager is not None, \
            "elastic mode needs a checkpoint dir (resize restores from it)"
        trainer = _ElasticTrainer(manager, mesh)
        # elastic gradient path: local loss+grads, host-allreduce mean,
        # then a jitted optax apply — the data plane XLA cannot resize,
        # the host collective can
        loss_and_grad = jax.jit(jax.value_and_grad(
            lambda p, ids, labels: unsharded_loss(p, ids, labels, cfg)))

        @jax.jit
        def apply_update(p, o, grads):
            updates, o2 = optimizer.update(grads, o, p)
            return optax.apply_updates(p, updates), o2

    per_part = 8  # records per partition per batch
    feed = recordio_feed(uri, mesh, batch_records=per_part,
                         max_bytes=(SEQ + 1) * 4,
                         world=trainer.world if trainer else None)
    from dmlc_tpu import telemetry
    from dmlc_tpu.models import train_flops_per_token

    telemetry.declare_flops_per_token(train_flops_per_token(cfg, SEQ))
    done = 0
    # non-elastic: done counts NEW steps this process trains; saves are
    # numbered base+done so a resumed run never re-commits old numbers
    base = start_at or 0
    # data fast-forward: this feed is deterministic, so replaying the
    # checkpoint's persisted stream position puts the stream exactly
    # where the saved run was — including batches a self-heal skip
    # consumed without training (step count alone under-counts those).
    # Pre-PR checkpoints have no position; start_at approximates it.
    # (a demo-grade skip — it pays full pipeline + transfer cost per
    # discarded batch; production resumes would skip at the host side)
    skip = (start_at or 0) if stream_resume is None else stream_resume
    if elastic and start_at:
        # elastic restores are repartition points, not replays: done is
        # the ABSOLUTE step (base stays 0) and the stream restarts
        done = start_at
        skip = 0
    from dmlc_tpu.resilience import SelfHealGuard

    # without a checkpoint dir there is nothing to roll back to, so
    # the escalation ladder caps at skip -> abort
    guard = SelfHealGuard(**({} if manager is not None
                             else {"max_rollbacks": 0}))

    # rollback target when poison strikes before the first commit:
    # "replaying from step 0" must really mean the pre-training state
    # (jax arrays are immutable, so these references are a free undo) —
    # returning the already-trained params with done=0 would re-train
    # the consumed batches on top of them and desync step count from
    # optimizer state.  Dropped after the first commit (and never
    # captured in elastic mode, whose rollback restores via the
    # trainer) so it doesn't pin a second params+opt copy all run
    genesis = ((params, opt_state, done, skip)
               if trainer is None and manager is not None else None)

    feed_iter = iter(feed)
    loss = float("nan")
    need_resync = False
    # job-level goodput accounting (telemetry.goodput): explicit enter()
    # hooks mark the intervals the span surfaces can't see — the elastic
    # recovery window (WorldResized raise -> resync settled) and the
    # self-heal rollback + replay.  The resize path must RE-ENTER the
    # interval it was in before the raise (e.g. a feed wait), else the
    # whole recovery leaks into idle/unattributed
    from dmlc_tpu.telemetry import goodput as goodput_ledger

    goodput_ledger.ledger()  # opt this process into goodput heartbeats
    resize_active = False    # a resize episode is open
    resize_prev = None       # override to restore when it settles
    rollback_until = None    # replaying until done reaches this step
    rollback_prev = None
    # done-value at the current stream's batch 0: the deterministic
    # feed means "replay to step A" = fast-forward (A - stream_base)
    # quality batches from a fresh stream.  Non-elastic streams always
    # start at step 0; an elastic stream restarts at each resync (the
    # partitioning changed), so its base is the resync step
    stream_base = done if elastic else 0
    # exact stream position: quality batches consumed from the current
    # partitioning's deterministic sequence (self-heal skips consume a
    # batch WITHOUT advancing `done`, so the step count alone
    # under-counts the position).  `stream_gen` names the partitioning
    # (bumped at each elastic resync); `ckpt_consumed` snapshots the
    # position at every commit so a rollback replays the exact count
    consumed = 0
    stream_gen = 0
    ckpt_consumed = {}  # absolute committed step -> (stream_gen, consumed)

    def rollback_and_replay(params, opt_state, done, base, stream_base):
        """Self-heal rollback: restore the last committed checkpoint
        and set up the deterministic replay — the feed restarts and
        fast-forwards back to the restored step (a rollback, unlike a
        resize, changes no membership, so the per-rank stream is
        reproducible).  The replay count is the position snapshotted at
        commit (falling back to the step arithmetic for checkpoints
        from before this process / partitioning).  Quarantined spans
        are skip-listed out of the replay by the readers, which is
        exactly how the job routes around poisoned bytes."""
        if trainer is not None:
            params, opt_state, done = trainer.rollback(
                feed, params, opt_state, done)
            snap = ckpt_consumed.get(done)
            if snap is not None and snap[0] == stream_gen:
                print(f"selfheal: replaying {snap[1]} batches",
                      flush=True)
                return params, opt_state, done, snap[1], base, stream_base
            if done >= stream_base:
                print(f"selfheal: replaying {done - stream_base} batches",
                      flush=True)
                return (params, opt_state, done, done - stream_base,
                        base, stream_base)
            # restored state predates this stream (an older committed
            # step survived a resize): restart the stream at it
            return params, opt_state, done, 0, base, done
        restored_step, restored, stream_pos = _restore_with_stream(
            manager, {"params": params, "opt": opt_state}, mesh,
            with_stream=trainer is None)
        feed.close()  # abandon the in-flight epoch
        if restored_step is None:
            # poisoned before the first save: the genesis state replays
            if genesis is None:
                raise RuntimeError(
                    "selfheal: no committed checkpoint and no genesis "
                    "state to roll back to")
            g_params, g_opt, g_done, g_skip = genesis
            print("selfheal: no committed checkpoint; rolling back to "
                  "the genesis state", flush=True)
            return g_params, g_opt, g_done, g_skip, base, 0
        params, opt_state = restored["params"], restored["opt"]
        if restored_step < base:
            base = restored_step
        snap = ckpt_consumed.get(restored_step)
        if snap is not None and snap[0] == stream_gen:
            replay = snap[1]
        elif stream_pos is not None:
            replay = stream_pos
        else:
            replay = restored_step  # pre-position checkpoint
        print(f"selfheal: rolled back to committed step {restored_step};"
              f" replaying {replay} batches", flush=True)
        return (params, opt_state, restored_step - base, replay,
                base, 0)

    while done < steps:
        # the step ledger opens BEFORE the batch pull so the feed's
        # consumer wait (feed.wait span) is billed to this step's
        # feed-wait share; skipped/tail batches abandon the open step
        # (the next step_begin unwinds it) and are never recorded
        telemetry.step_begin()
        try:
            if trainer is not None:
                if need_resync:
                    params, opt_state, done = trainer.resync(
                        feed, params, opt_state, done)
                    feed_iter = iter(feed)
                    stream_base = done  # repartitioned: fresh stream
                    stream_gen += 1    # old positions are incomparable
                    consumed = 0
                    # a resize landing mid-rollback-replay voids the
                    # replay plan with it: a leftover skip would drop
                    # never-trained batches from the fresh stream
                    skip = 0
                    need_resync = False
                    if resize_active:
                        # generation settled: re-enter the pre-resize
                        # interval.  A voided rollback replay does NOT
                        # resume (skip was just reset) — its episode
                        # ends with the resize
                        if resize_prev == "rollback_replay":
                            rollback_until = None
                            resize_prev = rollback_prev
                        goodput_ledger.enter(resize_prev)
                        resize_active = False
                trainer.client.check_resized()
            batch = next(feed_iter, None)
            if batch is None:
                feed_iter = iter(feed)  # next epoch
                continue
            # epoch-tail short batch: its zero-padded rows would train on
            # all-zero tokens (garbage targets).  Dropped BEFORE the
            # resume fast-forward so never-trained batches don't consume
            # `skip` — step count stays equal to trained-batch count
            if np.any(np.asarray(batch["length"]) == 0):
                continue
            consumed += 1
            if skip > 0:
                skip -= 1
                continue
            # the pre-step references are the free undo for a skipped
            # (poisoned) step: jax arrays are immutable
            prev_params, prev_opt = params, opt_state
            with metrics.annotate("train_step"):
                data = jnp.asarray(batch["data"])
                toks = jax.lax.bitcast_convert_type(
                    data.reshape(-1, SEQ + 1, 4), jnp.int32
                ).reshape(-1, SEQ + 1)
                ids, labels = toks[:, :-1], toks[:, 1:]
                if trainer is None:
                    params, opt_state, loss, gnorm = step(
                        params, opt_state, ids, labels)
                else:
                    local_loss, grads = loss_and_grad(params, ids, labels)
                    grads, loss, gnorm = trainer.allreduce_grads(
                        grads, float(local_loss))
                    params, opt_state = apply_update(params, opt_state,
                                                     grads)
            action = guard.observe(float(loss), grad_norm=float(gnorm),
                                   step=done + 1)
            if action == "skip":
                params, opt_state = prev_params, prev_opt
                continue
            if action == "rollback":
                # rollback_replay covers the restore AND the re-executed
                # steps (work lost = steps redone x prior step time):
                # the override stays up until `done` regains this step
                if rollback_until is None:
                    rollback_prev = goodput_ledger.enter("rollback_replay")
                rollback_until = max(rollback_until or 0, done)
                (params, opt_state, done, skip, base,
                 stream_base) = rollback_and_replay(
                    prev_params, prev_opt, done, base, stream_base)
                feed_iter = iter(feed)
                consumed = 0  # fresh stream: the replay re-counts
                continue
            if action == "abort":
                guard.raise_abort(done + 1)
        except WorldResized:
            # recovery happens at the top of the next iteration (the
            # resync broadcast can itself hit another resize, and it
            # must run under this same handler)
            prev = goodput_ledger.enter("resize")
            if not resize_active:
                # only the FIRST raise of an episode captures the
                # pre-resize interval (a resize landing mid-resync
                # re-raises here with the override already "resize")
                resize_prev = prev
                resize_active = True
            need_resync = True
            continue
        telemetry.step_end(tokens=int(ids.size))
        done += 1
        if rollback_until is not None and done >= rollback_until:
            # replay caught back up: the lost work is repaid
            goodput_ledger.enter(rollback_prev)
            rollback_until = None
        if done % 10 == 0 or done == 1:
            print(f"step {done}: loss {float(loss):.4f}", flush=True)
        if manager is not None and done % 20 == 0:
            # every rank snapshots the stream position at the commit
            # boundary (a later rollback replays exactly this count);
            # non-elastic checkpoints persist it for exact resume
            ckpt_consumed[base + done] = (stream_gen, consumed)
            if trainer is None or trainer.client.rank == 0:
                tree = {"params": params, "opt": opt_state}
                if trainer is None:
                    tree["stream"] = np.asarray([consumed], np.int64)
                manager.save(base + done, tree)
                genesis = None  # a committed checkpoint outranks it
    if manager is not None and done % 20 != 0 \
            and (trainer is None or trainer.client.rank == 0):
        # periodic save already hit on multiples of 20
        tree = {"params": params, "opt": opt_state}
        if trainer is None:
            tree["stream"] = np.asarray([consumed], np.int64)
        manager.save(base + done, tree)
    if trainer is not None:
        trainer.close()
    snap = metrics.snapshot()
    fed = snap.get("feed", {})
    led = telemetry.ledger().summary()
    print(f"final loss {float(loss):.4f}; feed moved "
          f"{fed.get('bytes_to_device', 0) / 1e6:.1f} MB in "
          f"{int(fed.get('batches', 0))} batches")
    if led:
        mfu = led.get("mfu")
        print(f"ledger: step p50 {led['step_time_p50'] * 1e3:.1f} ms, "
              f"p99 {led['step_time_p99'] * 1e3:.1f} ms, feed-wait "
              f"{led['feed_wait_fraction'] * 100:.0f}%, collective "
              f"exposed {led['collective_exposed_fraction'] * 100:.0f}%"
              f" / overlapped "
              f"{led['collective_overlapped_fraction'] * 100:.0f}%, "
              f"goodput {led.get('goodput_tokens_per_s', 0):,.0f} tok/s"
              + (f", MFU {mfu * 100:.1f}%" if mfu is not None else ""))


if __name__ == "__main__":
    main()
