"""Distributed logistic-regression SGD on a LibSVM file — the minimum
end-to-end slice (SURVEY.md §7): every layer of the framework at once.

  dmlc-submit --cluster local --num-workers N -- \
      python examples/train_libsvm.py <uri> [epochs]

Each worker: rendezvous via the tracker (rank/world), reads InputSplit
partition rank/world of the file, computes logistic-loss gradients in
JAX, and synchronizes gradients with the tracker client's binomial-tree
allreduce (the host-side control-plane path; on a TPU pod the same step
runs under pjit with lax.psum over the mesh instead — parallel/).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    uri = sys.argv[1] if len(sys.argv) > 1 else None
    epochs = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    assert uri, "usage: train_libsvm.py <uri> [epochs]"

    import jax
    import jax.numpy as jnp

    from dmlc_tpu.data import create_row_iter
    from dmlc_tpu.feed.device_feed import pack_rowblock
    from dmlc_tpu.tracker.client import TrackerClient

    client = TrackerClient()
    client.start()
    rank, world = client.rank, client.world_size

    it = create_row_iter(uri, rank, world, "libsvm")
    # feature-count must agree across workers for the weight vector
    num_col = int(client.allreduce(
        np.array([it.num_col()], np.int64), op="max")[0])
    num_col = max(num_col, 1)

    @jax.jit
    def grad_step(w, value, index, mask, label):
        def loss_fn(w):
            x = (value * mask)  # [B, K]
            logits = jnp.sum(x * w[index], axis=1)
            p = jax.nn.sigmoid(logits)
            eps = 1e-7
            return -jnp.mean(
                label * jnp.log(p + eps) + (1 - label) * jnp.log(1 - p + eps)
            )
        return jax.value_and_grad(loss_fn)(w)

    # pack this partition's rows once; byte-range partitions are NOT
    # row-balanced, so workers agree on a global step count and pad with
    # zero-mask batches — otherwise allreduce calls desynchronize
    batches = []
    for blk in it:
        for lo in range(0, blk.size, 256):
            sub = blk.slice(lo, min(lo + 256, blk.size))
            batches.append(pack_rowblock(sub, 256, 64, num_col))
    n_steps = int(client.allreduce(
        np.array([len(batches)], np.int64), op="max")[0])
    zero = {"label": np.zeros(256, np.float32),
            "value": np.zeros((256, 64), np.float32),
            "index": np.zeros((256, 64), np.int32),
            "mask": np.zeros((256, 64), np.float32)}

    w = jnp.zeros(num_col, jnp.float32)
    lr = 0.5
    for epoch in range(epochs):
        total_loss = 0.0
        for i in range(n_steps):
            b = batches[i] if i < len(batches) else zero
            loss, g = grad_step(w, b["value"], b["index"], b["mask"],
                                b["label"])
            g_sum = client.allreduce_sum(np.asarray(g, np.float64))
            w = w - lr * jnp.asarray(g_sum / world, jnp.float32)
            total_loss += float(loss)
        client.log(
            f"rank {rank}: epoch {epoch} loss "
            f"{total_loss / max(len(batches), 1):.4f} "
            f"({len(batches)}/{n_steps} local batches)"
        )
    client.shutdown()


if __name__ == "__main__":
    main()
