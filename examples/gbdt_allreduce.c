/* Distributed histogram-gradient boosted trees over the dmlc_tpu
 * collective C ABI — BASELINE config #4: the XGBoost drop-in story
 * (reference README.md:9 "dmlc-core ... the bricks to build efficient
 * and scalable distributed machine learning libraries").
 *
 * dmlc_comm_allreduce is the ONLY transport: every worker holds a
 * row-slice of a deterministic synthetic dataset, builds per-node
 * (grad, hess) histograms locally, allreduces them, and every worker
 * grows the identical tree from the global histograms — exactly the
 * rabit allreduce pattern XGBoost's hist updater uses.  Run it under
 * the real launcher:
 *
 *   bin/dmlc-submit --cluster local --num-workers 4 -- ./gbdt_allreduce
 *
 * A single-process run produces the same model (up to fp reduction
 * order), so the multi-worker RMSE must match the world=1 RMSE —
 * tests/test_collective_abi.py asserts that.
 */
#define _POSIX_C_SOURCE 199309L
#include "dmlc_collective.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define N_SAMPLES 2000
#define N_FEAT 8
#define N_BIN 16
#define DEPTH 3
#define ROUNDS 10
#define ETA 0.5
#define LAMBDA 1.0
#define MAX_LEAVES (1 << DEPTH)

static unsigned long long lcg_state = 0x2545F4914F6CDD1DULL;
static double lcg_uniform(void) { /* deterministic across platforms */
  lcg_state = lcg_state * 6364136223846793005ULL + 1442695040888963407ULL;
  return (double)((lcg_state >> 11) & ((1ULL << 53) - 1)) / (double)(1ULL << 53);
}

typedef struct {
  int feat, bin;        /* split: go left if xbin[feat] <= bin */
  double weight;        /* leaf value (only at leaves) */
  int is_leaf;
} Node;

int main(void) {
  DmlcComm* c = dmlc_comm_init();
  if (c == NULL) {
    fprintf(stderr, "gbdt: init failed: %s\n", dmlc_comm_last_error(NULL));
    return 1;
  }
  const int rank = dmlc_comm_rank(c), world = dmlc_comm_world_size(c);

  /* Every worker generates the FULL dataset deterministically and works
   * on its row slice — the global model is a pure function of the
   * allreduced histograms. */
  static double x[N_SAMPLES][N_FEAT];
  static int xbin[N_SAMPLES][N_FEAT];
  static double y[N_SAMPLES], pred[N_SAMPLES];
  for (int i = 0; i < N_SAMPLES; ++i) {
    for (int f = 0; f < N_FEAT; ++f) {
      x[i][f] = lcg_uniform();
      xbin[i][f] = (int)(x[i][f] * N_BIN);
      if (xbin[i][f] >= N_BIN) xbin[i][f] = N_BIN - 1;
    }
    y[i] = (x[i][0] > 0.5 ? 2.0 : -1.0) + (x[i][1] > 0.3 ? x[i][2] : 0.0) +
           0.25 * x[i][3] + 0.01 * (lcg_uniform() - 0.5);
    pred[i] = 0.0;
  }
  const int lo = rank * N_SAMPLES / world, hi = (rank + 1) * N_SAMPLES / world;

  static Node tree[ROUNDS][2 * MAX_LEAVES]; /* heap layout, root at 1 */
  static int node_of[N_SAMPLES];

  for (int r = 0; r < ROUNDS; ++r) {
    Node* t = tree[r];
    for (int i = 0; i < 2 * MAX_LEAVES; ++i) {
      t[i].is_leaf = 0; t[i].weight = 0.0; t[i].feat = -1; t[i].bin = -1;
    }
    for (int i = 0; i < N_SAMPLES; ++i) node_of[i] = 1;
    int level_begin = 1, level_count = 1;
    for (int depth = 0; depth <= DEPTH; ++depth) {
      /* one histogram buffer for the whole level: [node][feat][bin][2] */
      static double hist[MAX_LEAVES * N_FEAT * N_BIN * 2];
      const long hn = (long)level_count * N_FEAT * N_BIN * 2;
      memset(hist, 0, hn * sizeof(double));
      for (int i = lo; i < hi; ++i) {
        const int nd = node_of[i];
        if (nd < level_begin || nd >= level_begin + level_count) continue;
        const double g = pred[i] - y[i], h = 1.0; /* squared loss */
        double* base = hist + (long)(nd - level_begin) * N_FEAT * N_BIN * 2;
        for (int f = 0; f < N_FEAT; ++f) {
          double* cell = base + ((long)f * N_BIN + xbin[i][f]) * 2;
          cell[0] += g; cell[1] += h;
        }
      }
      /* THE transport: global histograms via the tree allreduce */
      if (dmlc_comm_allreduce(c, hist, hn, DMLC_F64, DMLC_SUM) != 0) {
        fprintf(stderr, "gbdt FAIL rank=%d: allreduce: %s\n", rank,
                dmlc_comm_last_error(c));
        return 1;
      }
      /* grow every node of this level from the SAME global histograms */
      for (int n = 0; n < level_count; ++n) {
        const int nd = level_begin + n;
        double* base = hist + (long)n * N_FEAT * N_BIN * 2;
        double gt = 0.0, ht = 0.0;
        for (int b = 0; b < N_BIN; ++b) { /* feature 0 covers all rows */
          gt += base[(long)b * 2]; ht += base[(long)b * 2 + 1];
        }
        const double parent_score = gt * gt / (ht + LAMBDA);
        double best_gain = 1e-9; int best_f = -1, best_b = -1;
        for (int f = 0; f < N_FEAT; ++f) {
          double gl = 0.0, hl = 0.0;
          for (int b = 0; b < N_BIN - 1; ++b) {
            gl += base[((long)f * N_BIN + b) * 2];
            hl += base[((long)f * N_BIN + b) * 2 + 1];
            const double gr = gt - gl, hr = ht - hl;
            if (hl < 1.0 || hr < 1.0) continue;
            const double gain = gl * gl / (hl + LAMBDA) +
                                gr * gr / (hr + LAMBDA) - parent_score;
            if (gain > best_gain) { best_gain = gain; best_f = f; best_b = b; }
          }
        }
        if (depth == DEPTH || best_f < 0 || ht <= 0.0) {
          t[nd].is_leaf = 1;
          t[nd].weight = (ht + LAMBDA) > 0 ? -gt / (ht + LAMBDA) : 0.0;
        } else {
          t[nd].feat = best_f; t[nd].bin = best_b;
        }
      }
      /* route samples one level down (every rank routes its slice) */
      int next_begin = level_begin * 2, next_count = 0;
      for (int i = lo; i < hi; ++i) {
        const int nd = node_of[i];
        if (nd < level_begin || nd >= level_begin + level_count) continue;
        if (t[nd].is_leaf) continue;
        node_of[i] = 2 * nd + (xbin[i][t[nd].feat] <= t[nd].bin ? 0 : 1);
      }
      next_count = level_count * 2;
      level_begin = next_begin; level_count = next_count;
      if (level_begin >= 2 * MAX_LEAVES) break;
    }
    /* apply the round's tree to this rank's slice */
    for (int i = lo; i < hi; ++i) {
      int nd = 1;
      while (!t[nd].is_leaf) nd = 2 * nd + (xbin[i][t[nd].feat] <= t[nd].bin ? 0 : 1);
      pred[i] += ETA * t[nd].weight;
    }
  }

  /* global RMSE via the same transport */
  double acc[2] = {0.0, 0.0};
  for (int i = lo; i < hi; ++i) {
    const double e = pred[i] - y[i];
    acc[0] += e * e; acc[1] += 1.0;
  }
  if (dmlc_comm_allreduce(c, acc, 2, DMLC_F64, DMLC_SUM) != 0) {
    fprintf(stderr, "gbdt FAIL rank=%d: final allreduce\n", rank);
    return 1;
  }
  const double rmse = sqrt(acc[0] / acc[1]);
  char msg[128];
  snprintf(msg, sizeof msg, "rank %d/%d: gbdt rmse=%.6f", rank, world, rmse);
  dmlc_comm_log(c, msg);
  if (rank == 0) printf("gbdt rmse=%.6f n=%.0f\n", rmse, acc[1]);
  dmlc_comm_shutdown(c);
  return 0;
}
