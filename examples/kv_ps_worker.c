/* Parameter-server KV round trip over the dmlc_collective C ABI.
 *
 * One binary, three roles (DMLC_ROLE selects, exactly as the reference
 * PS jobs run): the scheduler brokers registration at DMLC_PS_ROOT,
 * servers aggregate pushes, workers push per-rank gradient vectors and
 * pull the full sum back with min_pushes = NUM_WORKER (the PS clock).
 *
 * Run under the launcher:
 *   dmlc-submit --cluster local --num-workers 3 --num-servers 2 \
 *       -- ./kv_ps_worker
 */
#include "dmlc_collective.h"

#include <math.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

#define N 257          /* per-key vector length (odd: exercises resize) */
#define KEYS 5         /* spread over the server shard space */

int main(void) {
  DmlcKV* kv = dmlc_kv_init();
  if (kv == NULL) {
    fprintf(stderr, "FAIL: dmlc_kv_init: %s\n", dmlc_kv_last_error(NULL));
    return 1;
  }
  int role = dmlc_kv_role(kv);
  if (role != DMLC_KV_WORKER) {
    int rc = dmlc_kv_serve(kv);
    if (rc != 0)
      fprintf(stderr, "FAIL: serve rc=%d: %s\n", rc,
              dmlc_kv_last_error(kv));
    dmlc_kv_shutdown(kv);
    return rc == 0 ? 0 : 1;
  }

  const char* tid = getenv("DMLC_TASK_ID");
  const int rank = tid ? atoi(tid) : 0;
  const char* nw = getenv("DMLC_NUM_WORKER");
  const int workers = nw ? atoi(nw) : 1;

  double val[N], out[N];
  int key, i, rc;
  for (key = 0; key < KEYS; ++key) {
    for (i = 0; i < N; ++i) val[i] = (double)(rank + 1) * (key + 1);
    rc = dmlc_kv_push(kv, key, val, N);
    if (rc != 0) {
      fprintf(stderr, "FAIL rank=%d: push key=%d rc=%d\n", rank, key, rc);
      return 1;
    }
  }
  /* full-clock pull: blocks until every worker's push landed */
  for (key = 0; key < KEYS; ++key) {
    rc = dmlc_kv_pull(kv, key, out, N, workers);
    if (rc != 0) {
      fprintf(stderr, "FAIL rank=%d: pull key=%d rc=%d\n", rank, key, rc);
      return 1;
    }
    const double want = (double)(key + 1) * workers * (workers + 1) / 2.0;
    for (i = 0; i < N; ++i) {
      if (fabs(out[i] - want) > 1e-9) {
        fprintf(stderr, "FAIL rank=%d: key=%d slot=%d got=%f want=%f\n",
                rank, key, i, out[i], want);
        return 1;
      }
    }
  }
  printf("kv OK rank=%d workers=%d\n", rank, workers);
  fflush(stdout);
  dmlc_kv_shutdown(kv);
  return 0;
}
