"""Minimal distributed worker: rendezvous through the DMLC env contract,
tree-allreduce a vector, report through the tracker's print relay.

Run under the launcher:
    bin/dmlc-submit --cluster local --num-workers 4 -- python examples/allreduce_worker.py

With ``bench <bytes> <reps>`` arguments it becomes the host-collective
microbench: every rank allreduces the same f64 payload through the
binomial tree, the chunked ring, and the hierarchical shm+ring path
(tracker/client.py) at a small/medium/full size sweep (the cutover
evidence for DMLC_COLL_RING_MIN_BYTES), then runs the bucketed-overlap
pass (parallel.overlap.GradientBucketer) under a step-ledger window so
the exposed-vs-overlapped collective split is measured by the same
machinery production uses.  Rank 0 prints one JSON line per
measurement in the test_collective.c convention
(busbw = 2·(n-1)/n · algbw) — scripts/bench_collective.py runs it to
report the algorithms side by side.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dmlc_tpu.tracker.client import TrackerClient  # noqa: E402


def _emit(client, payload):
    if client.rank == 0:
        print(json.dumps(payload), flush=True)


def bench(client, nbytes, reps):
    w = client.world_size
    # full payload + the cutover sweep: 64 KB sits under the 1 MB ring
    # cutover (tree territory), 1 MB right at it, `nbytes` far above
    sizes = sorted({1 << 16, 1 << 20, nbytes})
    for algo in ("tree", "ring", "hier"):
        for sz in sizes:
            arr = np.full(sz // 8, 1.0, np.float64)
            # out=arr: the steady-state in-place path — a fresh 64 MB
            # result allocation per op costs more in page faults than
            # the shm fold itself on an oversubscribed host, and no
            # production loop pays it either.  Values grow w× per rep.
            client.allreduce(arr, "sum", algo=algo, out=arr)  # warmup
            t0 = time.perf_counter()
            for _ in range(reps):
                client.allreduce(arr, "sum", algo=algo, out=arr)
            dt = time.perf_counter() - t0
            want = float(w) ** (reps + 1)
            assert abs(arr[0] - want) < 1e-9 * want, (arr[0], want)
            algbw = sz * reps / dt / 1e6
            _emit(client, {
                "op": f"host_allreduce_{algo}", "bytes": sz,
                "algbw_MBps": round(algbw, 1),
                "busbw_MBps": round(algbw * 2 * (w - 1) / w, 1),
                "world": w,
            })


def bench_overlap(client, nbytes, reps):
    """Bucketed-overlap pass: the same payload as 16 'gradient leaves'
    through a GradientBucketer (background collective thread, default
    DMLC_COLL_ALGO routing) inside a step-ledger window, against a
    synchronous single-allreduce step — the ledger's exposed vs
    overlapped collective split is the before/after."""
    from dmlc_tpu import telemetry
    from dmlc_tpu.parallel.overlap import GradientBucketer

    w = client.world_size
    n_leaves = 16
    leaves = [np.full(nbytes // n_leaves // 8, 1.0, np.float64)
              for _ in range(n_leaves)]
    flat = np.concatenate(leaves)

    # --- before: the serial step (allreduce fully exposed) ---
    client.allreduce_sum(flat, out=flat)  # warmup (hier setup)
    telemetry.step_begin()
    t0 = time.perf_counter()
    for _ in range(reps):
        client.allreduce_sum(flat, out=flat)
    sync_wall = time.perf_counter() - t0
    rec_sync = telemetry.step_end()
    want = float(w) ** (reps + 1)
    assert abs(flat[0] - want) < 1e-9 * want, (flat[0], want)

    # --- after: bucketed overlap (collectives hide under packing);
    # in-place on the bucket buffers the bucketer owns ---
    bucketer = GradientBucketer(lambda a: client.allreduce_sum(a, out=a),
                                dtype=np.float64)
    bucketer.reduce_leaves(leaves)  # warmup
    telemetry.step_begin()
    t0 = time.perf_counter()
    for _ in range(reps):
        red = bucketer.reduce_leaves(leaves)
    ov_wall = time.perf_counter() - t0
    rec_ov = telemetry.step_end()
    for r in red:
        assert abs(r[0] - w) < 1e-9, r[0]
    timings = bucketer.last_timings()
    bucketer.close()
    _emit(client, {
        "op": "host_allreduce_overlap", "bytes": nbytes, "world": w,
        "reps": reps, "n_leaves": n_leaves,
        "sync_wall_s": round(sync_wall, 4),
        "overlap_wall_s": round(ov_wall, 4),
        "sync_exposed_s": round(rec_sync["collective_s"], 4),
        "overlap_exposed_s": round(rec_ov["collective_s"], 4),
        "overlap_overlapped_s":
            round(rec_ov["collective_overlapped_s"], 4),
        "exposed_fraction_sync":
            round(rec_sync["collective_s"] / rec_sync["wall_s"], 3),
        "exposed_fraction_overlap":
            round(rec_ov["collective_s"] / rec_ov["wall_s"], 3),
        # last rep's per-bucket (bytes, seconds) — the bucket-granular
        # view of where collective time went
        "bucket_timings": [[b, round(s, 5)] for b, s in timings],
    })


def main():
    client = TrackerClient()
    client.start()
    if len(sys.argv) > 1 and sys.argv[1] == "bench":
        nbytes = int(sys.argv[2]) if len(sys.argv) > 2 else 64 << 20
        reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
        bench(client, nbytes, reps)
        bench_overlap(client, nbytes, reps)
    else:
        out = client.allreduce_sum(np.full(4, float(client.rank + 1)))
        expected = client.world_size * (client.world_size + 1) / 2
        assert np.allclose(out, expected), (out, expected)
        client.log(f"rank {client.rank}/{client.world_size}: "
                   f"allreduce OK -> {out[0]}")
    client.shutdown()


if __name__ == "__main__":
    main()
