"""Minimal distributed worker: rendezvous through the DMLC env contract,
tree-allreduce a vector, report through the tracker's print relay.

Run under the launcher:
    bin/dmlc-submit --cluster local --num-workers 4 -- python examples/allreduce_worker.py

With ``bench <bytes> <reps>`` arguments it becomes the host-collective
microbench: every rank allreduces the same f64 payload through the
binomial tree and the chunked ring (tracker/client.py), and rank 0
prints one JSON line per algorithm in the test_collective.c convention
(busbw = 2·(n-1)/n · algbw) — scripts/bench_collective.py runs it to
report tree-vs-ring side by side.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dmlc_tpu.tracker.client import TrackerClient  # noqa: E402


def bench(client, nbytes, reps):
    count = nbytes // 8
    arr = np.full(count, 1.0, np.float64)
    for algo in ("tree", "ring"):
        out = client.allreduce(arr, "sum", algo=algo)  # warmup + sync
        t0 = time.perf_counter()
        for _ in range(reps):
            out = client.allreduce(arr, "sum", algo=algo)
        dt = time.perf_counter() - t0
        assert abs(out[0] - client.world_size) < 1e-9, out[0]
        if client.rank == 0:
            algbw = nbytes * reps / dt / 1e6
            busbw = algbw * 2 * (client.world_size - 1) / client.world_size
            print(json.dumps({
                "op": f"host_allreduce_{algo}", "bytes": nbytes,
                "algbw_MBps": round(algbw, 1),
                "busbw_MBps": round(busbw, 1),
                "world": client.world_size,
            }), flush=True)


def main():
    client = TrackerClient()
    client.start()
    if len(sys.argv) > 1 and sys.argv[1] == "bench":
        nbytes = int(sys.argv[2]) if len(sys.argv) > 2 else 64 << 20
        reps = int(sys.argv[3]) if len(sys.argv) > 3 else 3
        bench(client, nbytes, reps)
    else:
        out = client.allreduce_sum(np.full(4, float(client.rank + 1)))
        expected = client.world_size * (client.world_size + 1) / 2
        assert np.allclose(out, expected), (out, expected)
        client.log(f"rank {client.rank}/{client.world_size}: "
                   f"allreduce OK -> {out[0]}")
    client.shutdown()


if __name__ == "__main__":
    main()
