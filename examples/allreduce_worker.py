"""Minimal distributed worker: rendezvous through the DMLC env contract,
tree-allreduce a vector, report through the tracker's print relay.

Run under the launcher:
    bin/dmlc-submit --cluster local --num-workers 4 -- python examples/allreduce_worker.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dmlc_tpu.tracker.client import TrackerClient  # noqa: E402


def main():
    client = TrackerClient()
    client.start()
    out = client.allreduce_sum(np.full(4, float(client.rank + 1)))
    expected = client.world_size * (client.world_size + 1) / 2
    assert np.allclose(out, expected), (out, expected)
    client.log(f"rank {client.rank}/{client.world_size}: allreduce OK -> {out[0]}")
    client.shutdown()


if __name__ == "__main__":
    main()
