"""Distributed JAX worker: tracker rendezvous + cross-process psum.

Proves the full data-plane story the reference's multi-node jobs rely on
(tracker/dmlc_tracker/tracker.py:410-433 launching real workers): each
process launched by dmlc-submit

  1. rendezvouses with the rabit tracker (host control plane),
  2. calls initialize_distributed() — jax.distributed over the
     tracker-allocated DMLC_JAX_COORD_URI/PORT (never the rabit socket),
  3. joins one global device mesh spanning all processes, and
  4. verifies a cross-process psum against the closed-form answer.

Run under the launcher:
    bin/dmlc-submit --cluster local --num-workers 2 -- \
        python examples/jax_psum_worker.py

On CPU hosts (CI) the gloo collectives implementation backs the psum; on
TPU pods the same code runs over ICI with no change.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Platform must be pinned before first backend use.  env alone is not
# enough on machines whose sitecustomize pre-imports jax (dev container),
# so go through jax.config as well.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import jax  # noqa: E402

if os.environ.get("JAX_PLATFORMS", "") == "cpu":
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402

from dmlc_tpu.parallel.collectives import initialize_distributed  # noqa: E402
from dmlc_tpu.tracker.client import TrackerClient  # noqa: E402


def main():
    client = TrackerClient()
    client.start()
    rank, world = client.rank, client.world_size

    initialize_distributed()
    assert jax.process_count() == world, (jax.process_count(), world)
    devs = jax.devices()  # global: spans every process in the job
    n_local = len(jax.local_devices())

    mesh = Mesh(np.array(devs), ("dp",))
    local = jnp.full((n_local,), float(rank + 1))
    garr = jax.make_array_from_single_device_arrays(
        (len(devs),), NamedSharding(mesh, P("dp")),
        [jax.device_put(local[i : i + 1], d)
         for i, d in enumerate(jax.local_devices())])
    total = jax.jit(lambda a: jnp.sum(a) / n_local,
                    out_shardings=NamedSharding(mesh, P()))(garr)
    got = float(total)
    want = world * (world + 1) / 2
    assert got == want, (got, want)
    client.log(f"rank {rank}/{world}: jax psum OK -> {got}")
    client.shutdown()


if __name__ == "__main__":
    main()
