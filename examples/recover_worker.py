"""Fault-tolerant worker: rides out a peer's death via tracker `recover`.

The worker whose DMLC_RECOVER_KILL_FLAG file does not exist yet and
whose rank is 1 kills itself mid-job (after rendezvous, before any
collective) — simulating a preempted host.  The launcher's per-task
retry restarts it; the restarted process gets its old rank back through
the tracker's jobid map, while the surviving ranks catch the dropped
link as an OSError and re-admit the newcomer with `recover` — the
reference's rabit restart story (tracker.py cmd='recover'), end to end.

Run under the launcher (needs >= 2 attempts so the killed task returns):
    bin/dmlc-submit --cluster local --num-workers 2 --max-attempts 2 \
        --env DMLC_RECOVER_KILL_FLAG=/tmp/kill.flag \
        -- python examples/recover_worker.py
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from dmlc_tpu.tracker.client import TrackerClient  # noqa: E402


def main():
    flag = os.environ["DMLC_RECOVER_KILL_FLAG"]
    client = TrackerClient()
    client.start()
    if client.rank == 1 and not os.path.exists(flag):
        with open(flag, "w") as f:
            f.write(str(os.getpid()))
        os._exit(137)  # die without shutdown: peers see a dropped link

    out = None
    for _ in range(8):
        try:
            out = client.allreduce_sum(np.full(4, float(client.rank + 1)))
            break
        except OSError:
            # a peer died mid-collective: drop all links, re-broker
            # through the tracker, retry once the gang re-forms
            client.recover()
    assert out is not None, "allreduce never completed after recover"
    expected = client.world_size * (client.world_size + 1) / 2
    assert np.allclose(out, expected), (out, expected)
    client.log(f"rank {client.rank}/{client.world_size}: "
               f"recovered allreduce OK -> {out[0]}")
    client.shutdown()


if __name__ == "__main__":
    main()
