"""Tracker tests the reference never had (SURVEY.md §4): topology
invariants, the full rendezvous protocol over real localhost sockets,
host-side tree collectives, recover, and the print relay."""

import threading

import numpy as np
import pytest

from dmlc_tpu.tracker import RabitTracker, TrackerClient, link_maps
from dmlc_tpu.tracker.protocol import binomial_tree


@pytest.mark.parametrize("n", [1, 2, 3, 4, 5, 7, 8, 13, 16])
def test_topology_invariants(n):
    tree, parent, ring = link_maps(n)
    assert set(tree) == set(range(n))
    # ring is the identity cycle after relabeling
    for r in range(n):
        assert ring[r] == ((r - 1) % n, (r + 1) % n)
    # tree edges symmetric, one root, parents consistent
    roots = [r for r in range(n) if parent[r] == -1]
    assert len(roots) == 1
    for r in range(n):
        for v in tree[r]:
            assert r in tree[v]
        if parent[r] >= 0:
            assert parent[r] in tree[r]
    # connected: BFS from root reaches everyone
    seen, stack = set(), [roots[0]]
    while stack:
        x = stack.pop()
        if x in seen:
            continue
        seen.add(x)
        stack.extend(tree[x])
    assert seen == set(range(n))


def test_binomial_tree_shape():
    tree, parent = binomial_tree(7)
    assert parent[0] == -1
    assert sorted(tree[0]) == [1, 2]
    assert parent[5] == 2 and parent[6] == 2


def _run_workers(n, fn):
    """Run fn(client, rank_slot) in n threads against a fresh tracker."""
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    results = [None] * n
    errors = []

    def work(i):
        try:
            c = TrackerClient("127.0.0.1", tracker.port, jobid=f"job{i}")
            c.start()
            results[i] = fn(c)
            c.shutdown()
        except Exception as e:  # pragma: no cover - surfaced by assert below
            errors.append((i, e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    tracker.join(timeout=30)
    tracker.close()
    return results


@pytest.mark.parametrize("n", [1, 2, 4, 5])
def test_rendezvous_assigns_unique_ranks(n):
    results = _run_workers(n, lambda c: (c.rank, c.world_size, sorted(c.links)))
    ranks = sorted(r for r, _, _ in results)
    assert ranks == list(range(n))
    for _, world, _ in results:
        assert world == n
    # links symmetric: if a has b, b has a
    link_sets = {r: set(ls) for r, _, ls in results}
    for r, ls in link_sets.items():
        for v in ls:
            assert r in link_sets[v], (r, v, link_sets)


def test_allreduce_and_broadcast():
    n = 5

    def fn(c):
        local = np.arange(4, dtype=np.float64) + c.rank
        total = c.allreduce_sum(local)
        bc = c.broadcast(np.full(3, c.rank, dtype=np.int64), root=0)
        return total, bc

    results = _run_workers(n, fn)
    want = sum(np.arange(4, dtype=np.float64) + r for r in range(n))
    for total, bc in results:
        np.testing.assert_allclose(total, want)
        np.testing.assert_array_equal(bc, np.zeros(3, dtype=np.int64))


def test_print_relay_and_walltime(caplog):
    import logging

    caplog.set_level(logging.INFO, logger="dmlc_tpu.tracker")

    def fn(c):
        c.log(f"hello from rank {c.rank}")
        return c.rank

    _run_workers(2, fn)
    assert any("hello from rank" in r.message for r in caplog.records)


@pytest.mark.parametrize("n", [2, 5, 8])
def test_recover_relinks_whole_world(n):
    """All workers recover concurrently: everyone keeps their rank, the
    full overlay re-establishes through the AcceptRegistry brokering,
    and a post-recovery allreduce still sums correctly."""
    barrier = threading.Barrier(n)

    def fn(c):
        pre = float(c.allreduce_sum(np.asarray([c.rank + 1.0], np.float64))[0])
        old_rank = c.rank
        old_links = sorted(c.links)
        barrier.wait(timeout=20)
        c.recover()
        post = float(c.allreduce_sum(np.asarray([c.rank + 1.0],
                                                np.float64))[0])
        return old_rank, c.rank, old_links, sorted(c.links), pre, post

    results = _run_workers(n, fn)
    want = n * (n + 1) / 2.0
    for old_rank, new_rank, old_links, new_links, pre, post in results:
        assert new_rank == old_rank
        assert new_links == old_links
        assert pre == want and post == want


def test_recover_single_worker():
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    c = TrackerClient("127.0.0.1", tracker.port, jobid="j0")
    c.start()
    assert c.rank == 0
    c.recover()
    assert c.rank == 0 and c.world_size == 1
    c.shutdown()
    tracker.join(timeout=10)
    tracker.close()


# ---------------------------------------------------------------------------
# Ring allreduce (reduce-scatter + allgather over the brokered ring links)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [2, 3, 5, 8])
def test_ring_allreduce_matches_tree(n):
    """Ring and tree must agree bit-for-bit on sum/max/min across odd
    and even world sizes, including payloads smaller than the world."""

    def fn(c):
        big = (np.arange(5000, dtype=np.float64) % 97) + c.rank
        ints = np.arange(64, dtype=np.int64) * (c.rank + 1)
        tiny = np.arange(3, dtype=np.float32) + c.rank
        return (c.allreduce(big, "sum", algo="ring"),
                c.allreduce(big, "sum", algo="tree"),
                c.allreduce(ints, "max", algo="ring"),
                c.allreduce(ints, "min", algo="ring"),
                c.allreduce(tiny, "sum", algo="ring"))

    results = _run_workers(n, fn)
    base = np.arange(5000, dtype=np.float64) % 97
    want_sum = base * n + n * (n - 1) / 2
    want_max = np.arange(64, dtype=np.int64) * n
    want_min = np.arange(64, dtype=np.int64)
    want_tiny = (np.arange(3, dtype=np.float32) * n
                 + n * (n - 1) / 2).astype(np.float32)
    for ring_sum, tree_sum, ring_max, ring_min, tiny in results:
        np.testing.assert_allclose(ring_sum, want_sum)
        np.testing.assert_allclose(tree_sum, want_sum)
        np.testing.assert_array_equal(ring_max, want_max)
        np.testing.assert_array_equal(ring_min, want_min)
        np.testing.assert_allclose(tiny, want_tiny, rtol=1e-6)


def test_ring_cutover_threshold(monkeypatch):
    """DMLC_COLL_RING_MIN_BYTES picks the algorithm: 0 rings everything,
    negative disables the ring, and either way the sum is right."""
    import dmlc_tpu.tracker.client as client_mod

    chosen = []
    orig_ring = client_mod.TrackerClient._ring_allreduce
    orig_tree = client_mod.TrackerClient._tree_allreduce

    def spy_ring(self, arr, op):
        chosen.append("ring")
        return orig_ring(self, arr, op)

    def spy_tree(self, arr, op):
        chosen.append("tree")
        return orig_tree(self, arr, op)

    monkeypatch.setattr(client_mod.TrackerClient, "_ring_allreduce",
                        spy_ring)
    monkeypatch.setattr(client_mod.TrackerClient, "_tree_allreduce",
                        spy_tree)

    def run_with(min_bytes):
        monkeypatch.setenv("DMLC_COLL_RING_MIN_BYTES", min_bytes)
        chosen.clear()
        results = _run_workers(
            3, lambda c: c.allreduce_sum(np.ones(8, np.float64)))
        for r in results:
            np.testing.assert_allclose(r, np.full(8, 3.0))
        return set(chosen)

    assert run_with("0") == {"ring"}
    assert run_with("-1") == {"tree"}
    assert run_with(str(1 << 30)) == {"tree"}  # 64 B payload < cutover


# ---------------------------------------------------------------------------
# Adversarial behavior (SURVEY.md §4: the reference tracker hangs or dies
# on a bare assert in every one of these scenarios)
# ---------------------------------------------------------------------------

def _raw_session(port, rank=-1, world=-1, jobid="NULL", cmd="start"):
    """Hand-rolled handshake so tests control exactly when the 'worker'
    stops cooperating."""
    import socket

    from dmlc_tpu.tracker.protocol import MAGIC, FrameSocket

    fs = FrameSocket(socket.create_connection(("127.0.0.1", port)))
    fs.send_int(MAGIC)
    assert fs.recv_int() == MAGIC
    fs.send_int(rank)
    fs.send_int(world)
    fs.send_str(jobid)
    fs.send_str(cmd)
    return fs


def test_worker_death_mid_brokering_errors_not_hangs():
    """A worker that closes its socket after the handshake (killed while
    the tracker brokers its links) must fail the tracker promptly with a
    diagnosable error — not leave assign_rank blocked forever."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    fs = _raw_session(tracker.port, world=1)
    fs.recv_int()  # topology starts arriving: brokering is in flight
    fs.close()     # die mid-brokering
    with pytest.raises(RuntimeError, match="died mid-brokering"):
        tracker.join(timeout=15)
    tracker.close()


def test_worker_silence_mid_brokering_times_out(monkeypatch):
    """A worker that goes silent without closing (SIGSTOP, dead host, no
    FIN) trips DMLC_TRACKER_TIMEOUT instead of hanging forever."""
    monkeypatch.setenv("DMLC_TRACKER_TIMEOUT", "1")
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    fs = _raw_session(tracker.port, world=1)
    # read the topology but never answer the brokering round
    for _ in range(6):
        fs.recv_int()
    with pytest.raises(RuntimeError, match="went silent"):
        tracker.join(timeout=15)
    fs.close()
    tracker.close()


def test_garbage_connections_rejected_job_succeeds():
    """Pre-registration garbage (port scans, bad magic, hostile frame
    lengths) must be rejected without poisoning the job: a real worker
    rendezvous completes on the same tracker afterwards."""
    import socket

    from dmlc_tpu.tracker.protocol import MAGIC, FrameSocket

    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    # bad magic
    g1 = FrameSocket(socket.create_connection(("127.0.0.1", tracker.port)))
    g1.send_int(0xDEAD)
    # valid magic, then a hostile negative string length for the jobid
    g2 = FrameSocket(socket.create_connection(("127.0.0.1", tracker.port)))
    g2.send_int(MAGIC)
    assert g2.recv_int() == MAGIC
    g2.send_int(-1)
    g2.send_int(-1)
    g2.send_int(-7)  # jobid "length"
    # valid magic, then torn off mid-handshake
    g3 = FrameSocket(socket.create_connection(("127.0.0.1", tracker.port)))
    g3.send_int(MAGIC)
    g3.close()

    c = TrackerClient("127.0.0.1", tracker.port, jobid="legit")
    c.start()
    assert c.rank == 0
    c.shutdown()
    tracker.join(timeout=15)
    g1.close()
    g2.close()
    tracker.close()


def test_extra_worker_beyond_world_size_fails_loudly():
    """An extra 'start' once all rank slots are assigned is a protocol
    violation that must surface, not deadlock the batch assignment."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    c = TrackerClient("127.0.0.1", tracker.port, jobid="only")
    c.start()
    assert c.rank == 0
    _raw_session(tracker.port, jobid="extra")  # one worker too many
    with pytest.raises(RuntimeError, match="slots are assigned"):
        tracker.join(timeout=15)
    # no c.shutdown(): the accept loop is dead, nobody would answer the
    # shutdown handshake — the launcher kills workers of an aborted job
    tracker.close()


def test_bad_announces_dropped_job_survives():
    """Malformed announces — an out-of-range rank, a recover without a
    rank, a world_size mismatch — are each DROPPED and counted
    (dmlc_tracker_rejected_announces) instead of taking down the accept
    loop: the registered worker keeps working and shuts down cleanly.
    (The reference tracker dies on a bare assert for every one of
    these.)"""
    from dmlc_tpu import telemetry

    telemetry.reset()
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    c = TrackerClient("127.0.0.1", tracker.port, jobid="w0")
    c.start()
    _raw_session(tracker.port, rank=99, cmd="recover")      # rank >= world
    _raw_session(tracker.port, rank=-1, cmd="recover")      # no rank
    _raw_session(tracker.port, rank=-1, world=7)            # world mismatch
    _raw_session(tracker.port, cmd="frobnicate")            # unknown cmd
    # the legit worker still works end to end on the same tracker
    c.log("still alive")
    c.shutdown()
    tracker.join(timeout=15)
    tracker.close()
    rejected = telemetry.snapshot()["counters"]["tracker"][
        "rejected_announces"]
    assert rejected == 4, rejected


def test_out_of_range_shutdown_fails_loudly():
    """A hostile rank beyond world size must not count toward the
    shutdown quorum (ending the job early) — unlike a malformed
    announce, a bogus shutdown corrupts the job's completion state and
    stays a named protocol violation."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    c = TrackerClient("127.0.0.1", tracker.port, jobid="w0")
    c.start()
    _raw_session(tracker.port, rank=99, cmd="shutdown")
    with pytest.raises(RuntimeError, match="out of range"):
        tracker.join(timeout=15)
    tracker.close()


def test_worker_death_during_batch_brokering():
    """n=2: one real client plus one fake that dies right after the
    batch assignment begins — the survivor must not hang forever and
    the tracker must error out."""
    tracker = RabitTracker("127.0.0.1", 2)
    tracker.start(2)
    errors = []

    def survivor():
        try:
            TrackerClient("127.0.0.1", tracker.port, jobid="sv").start()
        except Exception as e:
            errors.append(e)

    t = threading.Thread(target=survivor, daemon=True)
    t.start()
    fs = _raw_session(tracker.port, jobid="dier")
    fs.close()  # both workers are now pending; the dier is already gone
    with pytest.raises(RuntimeError,
                       match="mid-brokering|protocol violation"):
        tracker.join(timeout=15)
    tracker.close()


# ---------------------------------------------------------------------------
# Hierarchical allreduce (shm intra-host reduce-scatter/allgather +
# chunked ring across host leaders) and the flatten-up-front contract
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [2, 4, 5])
def test_hier_allreduce_matches_tree(n):
    """The hier path (all ranks on one host here: pure shm leg) must
    agree bit-for-bit with the tree on sum/max/min."""

    def fn(c):
        big = (np.arange(40000, dtype=np.float32) % 251) + c.rank
        return (c.allreduce(big, "sum", algo="hier"),
                c.allreduce(big, "sum", algo="tree"),
                c.allreduce(big, "max", algo="hier"),
                c.allreduce(big, "min", algo="hier"))

    results = _run_workers(n, fn)
    base = np.arange(40000, dtype=np.float32) % 251
    for h_sum, t_sum, h_max, h_min in results:
        np.testing.assert_array_equal(h_sum, t_sum)
        np.testing.assert_array_equal(h_max, base + (n - 1))
        np.testing.assert_array_equal(h_min, base)


def test_hier_leader_ring_with_explicit_groups(monkeypatch):
    """DMLC_COLL_HIER_GROUPS=2 splits one box into rank-block 'hosts':
    shm inside each pair, the chunked ring across group leaders, and a
    broadcast back — including a ragged singleton group at n=5."""
    monkeypatch.setenv("DMLC_COLL_HIER_GROUPS", "2")

    def fn(c):
        x = (np.arange(9000, dtype=np.float64) % 13) * (c.rank + 1)
        return c.allreduce(x, "sum", algo="hier")

    n = 5
    results = _run_workers(n, fn)
    want = (np.arange(9000, dtype=np.float64) % 13) * (n * (n + 1) / 2)
    for r in results:
        np.testing.assert_allclose(r, want)


def test_hier_vetoes_to_flat_path_when_shm_fails(monkeypatch):
    """One rank failing shm setup must flip the WHOLE gang to the flat
    path (gang-uniform MIN veto) — results stay correct, nobody hangs,
    and the veto is cached for the generation."""
    import dmlc_tpu.native.shm_collective as shmc

    def boom(*a, **k):
        raise shmc.ShmGroupError("forced setup failure")

    monkeypatch.setattr(shmc, "ShmCollective", boom)

    def fn(c):
        x = np.ones(5000, np.float64) * (c.rank + 1)
        first = c.allreduce(x, "sum", algo="hier")
        second = c.allreduce(x, "sum", algo="hier")
        return first, second

    n = 3
    results = _run_workers(n, fn)
    want = np.ones(5000, np.float64) * (n * (n + 1) / 2)
    for first, second in results:
        np.testing.assert_allclose(first, want)
        np.testing.assert_allclose(second, want)


def test_allreduce_arbitrary_shapes_and_strides():
    """Regression: non-C-contiguous / >1-D / 0-d inputs are flattened
    to one contiguous copy up front on EVERY algorithm — shapes come
    back intact and the values are right (the ring's uint8 reinterpret
    used to assume a flat contiguous input)."""
    n = 3

    def fn(c):
        m = np.arange(24, dtype=np.float64).reshape(4, 6) + c.rank
        big = np.arange(2 << 18, dtype=np.float64).reshape(2, -1) + c.rank
        return (c.allreduce_sum(m),              # 2-D
                c.allreduce_sum(m.T),            # transposed view
                c.allreduce_sum(m[:, ::2]),      # strided view
                c.allreduce_sum(np.asarray(2.0)),  # 0-d
                c.allreduce(big[:, ::2], "sum", algo="ring"),
                c.allreduce(big, "max", algo="hier"))

    results = _run_workers(n, fn)
    base = np.arange(24, dtype=np.float64).reshape(4, 6)
    bigb = np.arange(2 << 18, dtype=np.float64).reshape(2, -1)
    rsum = n * (n - 1) / 2
    for m, mt, ms, z, br, bm in results:
        assert m.shape == (4, 6) and mt.shape == (6, 4)
        assert ms.shape == (4, 3) and z.shape == ()
        assert br.shape == (2, bigb.shape[1] // 2)
        assert bm.shape == bigb.shape
        np.testing.assert_allclose(m, base * n + rsum)
        np.testing.assert_allclose(mt, (base * n + rsum).T)
        np.testing.assert_allclose(ms, (base * n + rsum)[:, ::2])
        assert float(z) == 2.0 * n
        np.testing.assert_allclose(br, (bigb * n + rsum)[:, ::2])
        np.testing.assert_allclose(bm, bigb + (n - 1))


def test_allreduce_out_buffer_and_in_place():
    """out= writes the reduction into a caller buffer (no fresh
    allocation); out=arr reduces truly in place; mismatched out
    raises."""
    n = 3

    def fn(c):
        a = np.arange(100, dtype=np.float64) + c.rank
        res = np.empty_like(a)
        got = c.allreduce_sum(a, out=res)
        assert got.base is res or got is res  # reshape view of res
        inp = c.allreduce_sum(a, out=a)
        with pytest.raises(ValueError, match="out="):
            c.allreduce_sum(a, out=np.empty(99, np.float64))
        with pytest.raises(ValueError, match="out="):
            c.allreduce_sum(a, out=np.empty(100, np.float32))
        return res, a, inp

    results = _run_workers(n, fn)
    want = np.arange(100, dtype=np.float64) * n + n * (n - 1) / 2
    for res, a, inp in results:
        np.testing.assert_allclose(res, want)
        np.testing.assert_allclose(a, want)   # in-place mutated
        np.testing.assert_allclose(inp, want)


def test_allreduce_out_with_2d_input():
    n = 2

    def fn(c):
        a = np.arange(24, dtype=np.float64).reshape(4, 6) + c.rank
        out = np.empty((4, 6), np.float64)
        got = c.allreduce_sum(a, out=out)
        return got, out

    for got, out in _run_workers(n, fn):
        want = np.arange(24, dtype=np.float64).reshape(4, 6) * n + 1
        assert got.shape == (4, 6)
        np.testing.assert_allclose(got, want)
        np.testing.assert_allclose(out, want)
