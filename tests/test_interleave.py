"""Deterministic interleaving explorer tests.

Layers: the explorer's own mechanics (racy-toy detection within a
bounded schedule budget, deterministic replay, deadlock and timeout
modeling), the shipped known-hairy-machine scenarios holding on the
current tree, and the PR 13 drain-race reproduction — the reverted fix
must be CAUGHT, deterministically, and the shipped fix must pass the
same budget.
"""

import logging

import pytest

from dmlc_tpu.analysis import interleave as ilv
from dmlc_tpu.analysis import scenarios as sc
from dmlc_tpu.concurrency import BufferPool, make_lock

logging.getLogger("dmlc_tpu.serving").setLevel(logging.ERROR)


# ---- the deliberately racy toy: detection + replay ----------------------

class _RacyCounter:
    """Lost-update bug: check-then-act with the lock dropped across
    the gap."""

    def __init__(self):
        self._lock = make_lock("_RacyCounter._lock")
        self.value = 0

    def racy_inc(self):
        with self._lock:
            v = self.value
        ilv.sched_point("gap")
        with self._lock:
            self.value = v + 1

    def safe_inc(self):
        with self._lock:
            self.value += 1


class _RacyScenario(ilv.Scenario):
    name = "racy-counter"

    def setup(self):
        return _RacyCounter()

    def bodies(self, c):
        return [("a", c.racy_inc), ("b", c.racy_inc)]

    def check(self, c):
        assert c.value == 2, f"lost update: value={c.value}"


class _SafeScenario(_RacyScenario):
    def bodies(self, c):
        return [("a", c.safe_inc), ("b", c.safe_inc)]


def test_racy_toy_caught_within_budget():
    res = ilv.explore(_RacyScenario, schedules=40, seed=1)
    assert not res.ok, "explorer missed the planted lost update"
    assert "lost update" in res.failures[0].error


def test_safe_toy_clean_over_same_budget():
    res = ilv.explore(_SafeScenario, schedules=40, seed=1)
    assert res.ok, res.failures


def test_failure_replays_deterministically():
    res = ilv.explore(_RacyScenario, schedules=40, seed=1)
    f = res.failures[0]
    # compare the stable first line: pytest's assertion introspection
    # appends object reprs (addresses) to the scenario's own asserts
    head = f.error.splitlines()[0]
    for _ in range(3):
        rep = ilv.replay(_RacyScenario, f.decisions)
        assert not rep.ok and rep.error.splitlines()[0] == head


def test_explore_is_deterministic_for_fixed_seed():
    a = ilv.explore(_RacyScenario, schedules=40, seed=7)
    b = ilv.explore(_RacyScenario, schedules=40, seed=7)
    assert a.runs == b.runs
    assert [f.decisions for f in a.failures] == \
        [f.decisions for f in b.failures]


# ---- deadlock + timeout modeling ----------------------------------------

class _DeadlockScenario(ilv.Scenario):
    name = "abba"

    def setup(self):
        return (make_lock("abba.A"), make_lock("abba.B"))

    def bodies(self, state):
        a, b = state

        def ab():
            with a:
                ilv.sched_point()
                with b:
                    pass

        def ba():
            with b:
                ilv.sched_point()
                with a:
                    pass

        return [("ab", ab), ("ba", ba)]


def test_abba_deadlock_detected():
    res = ilv.explore(_DeadlockScenario, schedules=30, seed=0)
    assert not res.ok
    assert "deadlock" in res.failures[0].error


def test_timed_acquire_timeout_is_a_schedulable_transition():
    """Some schedule delivers the timeout (acquire returns None) even
    though no real time passes; some schedule delivers the buffer."""
    outcomes = set()

    class S(ilv.Scenario):
        name = "timed-acquire"

        def setup(self):
            pool = BufferPool(object, capacity=1)
            held = pool.acquire()
            return pool, held

        def bodies(self, state):
            pool, held = state

            def taker():
                outcomes.add(pool.acquire(timeout=1.0) is None)

            def releaser():
                ilv.sched_point()
                pool.release(held)

            return [("take", taker), ("release", releaser)]

    res = ilv.explore(S, schedules=60, seed=3, stop_on_failure=False)
    assert res.ok, res.failures
    assert outcomes == {True, False}, outcomes


def test_foreign_blocking_trips_watchdog():
    """A controlled thread parking on a primitive the scheduler cannot
    see must produce a clear watchdog error, not a wedged run."""
    import queue

    class S(ilv.Scenario):
        name = "foreign-block"
        watchdog_s = 0.5

        def setup(self):
            return queue.Queue()

        def bodies(self, q):
            return [("blocker", lambda: q.get(timeout=30))]

    res = ilv.run_scenario(S(), ilv.PrefixPolicy())
    assert not res.ok
    assert "watchdog" in res.error


# ---- the shipped scenarios on the current tree --------------------------

@pytest.mark.parametrize("cls", sc.SCENARIOS,
                         ids=[c.name for c in sc.SCENARIOS])
def test_shipped_scenarios_hold(cls):
    res = ilv.explore(cls, schedules=60, seed=0)
    assert res.ok, res.failures[0].error


# ---- the PR 13 drain race: reverted fix caught, shipped fix holds -------

def test_reverted_drain_fix_is_caught_deterministically():
    res = ilv.explore(lambda: sc.DrainRaceScenario("pr13"),
                      schedules=400, seed=0)
    assert not res.ok, "explorer missed the reverted PR 13 drain race"
    f = res.failures[0]
    assert "swept by a concluding drain" in f.error
    rep = ilv.replay(lambda: sc.DrainRaceScenario("pr13"), f.decisions)
    assert not rep.ok and rep.error == f.error


def test_shipped_drain_holds_over_same_budget():
    res = ilv.explore(lambda: sc.DrainRaceScenario("fixed"),
                      schedules=400, seed=0)
    assert res.ok, res.failures[0].error


# ---- hygiene: patches are restored --------------------------------------

def test_patches_restored_after_scenario():
    import threading
    import time

    cond_before = threading.Condition
    event_before = threading.Event
    sleep_before = time.sleep
    ilv.run_scenario(_RacyScenario(), ilv.PrefixPolicy())
    assert threading.Condition is cond_before
    assert threading.Event is event_before
    assert time.sleep is sleep_before
    # and a lock built outside any scenario is a plain lock again
    lk = make_lock("post.scenario")
    assert not isinstance(lk, ilv.SchedLock)
