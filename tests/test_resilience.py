"""Resilience layer tests: RetryPolicy backoff/deadline/classification,
FaultInjector spec semantics, the unified retry wiring in the REST
backends, the S3/HDFS crash-window fixes, tracker failure detection +
replacement re-admission, client timeouts, and the launcher restart
budget (including the full fault-injected chaos smoke)."""

import os
import socket
import subprocess
import sys
import threading
import time
import urllib.error

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.base import DMLCError
from dmlc_tpu.resilience import (
    FaultInjected,
    FaultInjector,
    RetryPolicy,
    default_retryable,
    fault_point,
    install_injector,
    reset_injector,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_injector():
    reset_injector()
    yield
    reset_injector()


# ---------------------------------------------------------------------------
# RetryPolicy
# ---------------------------------------------------------------------------

def test_backoff_sequence_exponential_and_capped():
    p = RetryPolicy(attempts=6, base_s=0.25, multiplier=2.0, max_s=1.0,
                    jitter=0.0)
    assert [p.delay(i) for i in range(5)] == [0.25, 0.5, 1.0, 1.0, 1.0]


def test_jitter_bounded():
    p = RetryPolicy(base_s=1.0, jitter=0.5)
    for i in range(50):
        assert 1.0 <= p.delay(0) <= 1.5


def test_retries_transient_then_succeeds():
    sleeps = []
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise ConnectionError("torn")
        return "ok"

    p = RetryPolicy(attempts=4, base_s=0.01, jitter=0.0,
                    sleep=sleeps.append)
    assert p.call(flaky) == "ok"
    assert len(calls) == 3 and len(sleeps) == 2


def test_exhausts_attempts_raises_last_error():
    p = RetryPolicy(attempts=3, base_s=0.0, jitter=0.0, sleep=lambda _: None)
    calls = []

    def always():
        calls.append(1)
        raise ConnectionError("still torn")

    with pytest.raises(ConnectionError, match="still torn"):
        p.call(always)
    assert len(calls) == 3


def test_permanent_errors_raise_immediately():
    p = RetryPolicy(attempts=5, sleep=lambda _: None)
    for exc in (ValueError("nope"), FileNotFoundError("gone"),
                DMLCError("denied", status=403),
                DMLCError("flagged", transient=False)):
        calls = []

        def once(e=exc):
            calls.append(1)
            raise e

        with pytest.raises(type(exc)):
            p.call(once)
        assert len(calls) == 1, exc


def test_deadline_stops_retrying():
    p = RetryPolicy(attempts=10, base_s=5.0, jitter=0.0, deadline_s=1.0,
                    sleep=lambda _: None)
    calls = []

    def always():
        calls.append(1)
        raise ConnectionError("x")

    # first backoff (5s) would blow the 1s deadline: no retry happens
    with pytest.raises(ConnectionError):
        p.call(always)
    assert len(calls) == 1


def test_classification():
    assert default_retryable(ConnectionRefusedError())
    assert default_retryable(socket.timeout())
    assert default_retryable(urllib.error.URLError("dns"))
    assert default_retryable(DMLCError("x", status=503))
    assert default_retryable(DMLCError("x", transient=True))
    assert default_retryable(FaultInjected("chaos"))
    assert not default_retryable(DMLCError("x", status=404))
    assert not default_retryable(PermissionError())
    assert not default_retryable(KeyError("x"))


def test_retry_counters_reach_telemetry():
    telemetry.reset()
    p = RetryPolicy(attempts=3, base_s=0.0, jitter=0.0,
                    sleep=lambda _: None, name="unittest")
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise ConnectionError("x")

    p.call(flaky)
    counters = telemetry.counters_snapshot()["resilience"]
    assert counters["retries"] == 2
    assert counters["retries_unittest"] == 2


def test_from_env_reads_knobs(monkeypatch):
    monkeypatch.setenv("DMLC_S3_RETRIES", "7")
    monkeypatch.setenv("DMLC_RETRY_MAX_S", "2.5")
    monkeypatch.setenv("DMLC_RETRY_DEADLINE_S", "9")
    p = RetryPolicy.from_env(retries_env="DMLC_S3_RETRIES")
    assert p.attempts == 7 and p.max_s == 2.5 and p.deadline_s == 9.0


# ---------------------------------------------------------------------------
# FaultInjector
# ---------------------------------------------------------------------------

def test_fault_spec_counts_down():
    inj = FaultInjector("a.b=error:boom:2")
    for _ in range(2):
        with pytest.raises(FaultInjected, match="boom"):
            inj.fire("a.b")
    inj.fire("a.b")  # disarmed: no raise
    inj.fire("other.site")  # never armed


def test_fault_spec_predicates():
    inj = FaultInjector("barrier.x@rank:1@attempt:0=error")
    inj.fire("barrier.x", rank=0, attempt=0)  # wrong rank: no fire
    inj.fire("barrier.x", rank=1, attempt=1)  # wrong attempt: no fire
    with pytest.raises(FaultInjected):
        inj.fire("barrier.x", rank=1, attempt=0)


def test_fault_spec_unlimited_and_delay():
    inj = FaultInjector("slow.site=delay:0.01:*")
    t0 = time.monotonic()
    inj.fire("slow.site")
    inj.fire("slow.site")
    assert time.monotonic() - t0 >= 0.02


def test_fault_corrupt_flips_bytes():
    inj = FaultInjector("storage.response=corrupt")
    data = bytes(range(32))
    bad = inj.corrupt("storage.response", data)
    assert bad != data and len(bad) == len(data)
    assert bad[8:] == data[8:]  # only a prefix is flipped
    # disarmed after one firing
    assert inj.corrupt("storage.response", data) == data


def test_fault_spec_parse_errors():
    for bad in ("nonsense", "site=explode", "a@b=error"):
        with pytest.raises(ValueError):
            FaultInjector(bad)


def test_fault_point_tracks_env(monkeypatch):
    monkeypatch.setenv("DMLC_FAULT_SPEC", "env.site=error")
    with pytest.raises(FaultInjected):
        fault_point("env.site")
    monkeypatch.setenv("DMLC_FAULT_SPEC", "")
    fault_point("env.site")  # spec cleared: no fire


def test_install_injector_pins_over_env(monkeypatch):
    monkeypatch.setenv("DMLC_FAULT_SPEC", "env.site=error")
    install_injector("pinned.site=error")
    fault_point("env.site")  # env spec ignored while pinned
    with pytest.raises(FaultInjected):
        fault_point("pinned.site")


def test_kill_action_dies_without_cleanup(tmp_path):
    prog = (
        "import atexit, sys\n"
        f"sys.path.insert(0, {REPO!r})\n"
        "from dmlc_tpu.resilience import fault_point\n"
        "atexit.register(lambda: print('atexit-ran'))\n"
        "fault_point('die.here')\n"
        "print('survived')\n"
    )
    env = os.environ.copy()
    env["DMLC_FAULT_SPEC"] = "die.here=kill:9"
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 9
    assert "survived" not in r.stdout and "atexit-ran" not in r.stdout


# ---------------------------------------------------------------------------
# rest_request through the unified policy
# ---------------------------------------------------------------------------

def test_rest_request_retries_injected_faults(monkeypatch):
    from dmlc_tpu.io import rest

    class FakeResp:
        status = 200

    monkeypatch.setattr("urllib.request.urlopen",
                        lambda req, timeout=None: FakeResp())
    monkeypatch.setenv("DMLC_FAULT_SPEC", "svc.request=error::2")
    telemetry.reset()
    monkeypatch.setenv("DMLC_RETRY_MAX_S", "0.01")
    resp = rest.rest_request("SVC", "http://x/y", "GET",
                             retries_env="DMLC_TEST_RETRIES")
    assert resp.status == 200
    counters = telemetry.counters_snapshot()["resilience"]
    assert counters["retries_svc"] == 2
    assert counters["faults_injected"] == 2


def test_rest_request_gives_up_on_permanent(monkeypatch):
    from dmlc_tpu.io import rest

    calls = []

    def deny(req, timeout=None):
        calls.append(1)
        raise urllib.error.HTTPError(req.full_url, 403, "denied", {}, None)

    monkeypatch.setattr("urllib.request.urlopen", deny)
    with pytest.raises(DMLCError) as ei:
        rest.rest_request("SVC", "http://x/y", "GET")
    assert ei.value.status == 403
    assert len(calls) == 1  # permanent: no blind resend


def test_storage_response_corruption_hits_reads(monkeypatch):
    from dmlc_tpu.io.http_filesys import HttpReadStream

    payload = b"A" * 64

    class S(HttpReadStream):
        def __init__(self):
            super().__init__("http://x", size=len(payload))

        def _fill(self, start, size):
            return payload[start:start + size]

    assert S().read(64) == payload
    install_injector("storage.response=corrupt")
    assert S().read(64) != payload


# ---------------------------------------------------------------------------
# satellite fixes: S3 Complete-retry 404, HDFS overwrite backup
# ---------------------------------------------------------------------------

def _s3_stream_with_parts(monkeypatch, complete_behavior, head_len):
    from dmlc_tpu.io import s3_filesys

    log = []

    class Resp:
        def __init__(self, headers=None, body=b"<x><UploadId>u1</UploadId></x>"):
            self.headers = headers or {}
            self._body = body

        def read(self):
            return self._body

    def fake_request(url, method="GET", data=None, headers=None, ok=()):
        log.append((method, url.split("?")[-1][:20]))
        if "?uploads=" in url:
            return Resp()
        if "partNumber=" in url:
            return Resp(headers={"ETag": f"e{len(log)}"})
        if method == "POST" and "uploadId=" in url:
            return complete_behavior()
        if method == "HEAD":
            return Resp(headers={"Content-Length": str(head_len)})
        if method == "DELETE":
            log.append(("ABORT", ""))
            return Resp()
        raise AssertionError(f"unexpected {method} {url}")

    monkeypatch.setattr(s3_filesys, "_request", fake_request)
    monkeypatch.setenv("DMLC_S3_WRITE_BUFFER_MB", "1")
    s = s3_filesys.S3WriteStream("http://bucket/key")
    s._part = 4  # tiny parts without 5 MiB buffers
    s.write(b"abcdefgh")  # two parts committed
    return s, log


def test_s3_complete_404_after_committed_object_is_success(monkeypatch):
    def complete():
        raise DMLCError("NoSuchUpload", status=404)

    s, log = _s3_stream_with_parts(monkeypatch, complete, head_len=8)
    s.close()  # must NOT raise: HEAD says the 8 bytes are all there
    assert ("ABORT", "") not in log


def test_s3_complete_404_with_missing_object_still_fails(monkeypatch):
    def complete():
        raise DMLCError("NoSuchUpload", status=404)

    # HEAD reports the wrong size: the commit did NOT happen
    s, log = _s3_stream_with_parts(monkeypatch, complete, head_len=3)
    with pytest.raises(DMLCError, match="NoSuchUpload"):
        s.close()
    assert ("ABORT", "") in log  # upload aborted on genuine failure


def test_hdfs_overwrite_backs_up_old_version(monkeypatch):
    import json as _json

    from dmlc_tpu.io import hdfs_filesys

    ops = []

    class Resp:
        def __init__(self, body):
            self._body = body

        def read(self):
            return self._body

    def fake_request(url, method, data=None, ok=(), retry=False):
        from urllib.parse import unquote

        q = dict(p.split("=", 1) for p in url.split("?", 1)[1].split("&")
                 if "=" in p)
        path = unquote(url.split("?")[0].split("/webhdfs/v1", 1)[1])
        op = q["op"]
        ops.append((op, path, unquote(q.get("destination", ""))))
        if op == "RENAME":
            # refuse only temp -> destination while the destination
            # still exists (i.e. before the backup rename happened)
            dest = unquote(q["destination"])
            exists = not any(o == "RENAME" and d.startswith("/d/.f.old")
                             for o, _p, d in ops[:-1])
            if dest == "/d/f" and exists:
                return Resp(_json.dumps({"boolean": False}).encode())
            return Resp(_json.dumps({"boolean": True}).encode())
        return Resp(b"{}")

    monkeypatch.setattr(hdfs_filesys, "_request", fake_request)
    monkeypatch.setattr(hdfs_filesys, "_write_op",
                        lambda url, method, body, ok: None)
    s = hdfs_filesys.WebHdfsWriteStream("http://nn:9870", "/d/f")
    s.write(b"new contents")
    s.close()
    renames = [(p, d) for o, p, d in ops if o == "RENAME"]
    # 1: temp -> dest (refused), 2: dest -> .f.old backup,
    # 3: temp -> dest (succeeds)
    assert renames[0][1] == "/d/f"
    assert renames[1][0] == "/d/f" and renames[1][1].startswith("/d/.f.old")
    assert renames[2][1] == "/d/f" and renames[2][0].startswith("/d/.f.tmp")
    # the backup is garbage-collected afterwards
    deletes = [p for o, p, _d in ops if o == "DELETE"]
    assert any(p.startswith("/d/.f.old") for p in deletes)


# ---------------------------------------------------------------------------
# tracker failure detection + client timeouts
# ---------------------------------------------------------------------------

def test_tracker_declares_dead_after_miss_window():
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    telemetry.reset()
    tracker = RabitTracker("127.0.0.1", 1, miss_window_s=0.4)
    tracker.start(1)
    try:
        tracker.telemetry.update(0, {"counters": {}})  # one heartbeat
        deadline = time.time() + 5
        while 0 not in tracker.dead_ranks and time.time() < deadline:
            time.sleep(0.05)
        assert 0 in tracker.dead_ranks
        counters = telemetry.counters_snapshot()["resilience"]
        assert counters["worker_declared_dead"] == 1
        assert tracker.telemetry.healthz()["dead_ranks"] == [0]
    finally:
        tracker.close()


def test_tracker_readmits_replacement_after_death():
    """Heartbeat stops -> rank declared dead -> a replacement worker
    re-admitted under the same rank (job map) clears the flag and
    counts as a readmission."""
    from dmlc_tpu.tracker.client import TrackerClient
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    telemetry.reset()
    tracker = RabitTracker("127.0.0.1", 1, miss_window_s=0.4)
    tracker.start(1)
    c = TrackerClient("127.0.0.1", tracker.port, jobid="j0")
    c.start()
    assert c.rank == 0
    tracker.telemetry.update(0, {"counters": {}})
    deadline = time.time() + 5
    while 0 not in tracker.dead_ranks and time.time() < deadline:
        time.sleep(0.05)
    assert 0 in tracker.dead_ranks
    # the "replacement": same jobid, fresh process in real life
    c2 = TrackerClient("127.0.0.1", tracker.port, jobid="j0")
    c2.start()
    assert c2.rank == 0
    deadline = time.time() + 5
    while 0 in tracker.dead_ranks and time.time() < deadline:
        time.sleep(0.05)
    assert 0 not in tracker.dead_ranks
    counters = telemetry.counters_snapshot()["resilience"]
    assert counters["worker_readmitted"] == 1
    c2.shutdown()
    tracker.join(timeout=15)
    tracker.close()


def test_clean_shutdown_rank_never_declared_dead():
    """A rank that heartbeated and then finished CLEANLY (sent
    'shutdown') goes silent forever — the failure detector must not
    flag it while the rest of the job keeps running."""
    from dmlc_tpu.tracker.client import TrackerClient
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    telemetry.reset()
    tracker = RabitTracker("127.0.0.1", 2, miss_window_s=0.3)
    tracker.start(2)
    clients = []

    def join_worker(i):
        c = TrackerClient("127.0.0.1", tracker.port, jobid=f"cs{i}")
        c.start()
        clients.append(c)

    threads = [threading.Thread(target=join_worker, args=(i,))
               for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    finisher = clients[0]
    finisher.send_metrics('{"counters": {}}')  # it IS on the watch list
    finisher.shutdown()
    # 4x the miss window with the job still running; the survivor keeps
    # heartbeating (silence would make IT legitimately declared dead)
    for _ in range(8):
        clients[1].send_metrics('{"counters": {}}')
        time.sleep(0.15)
    assert tracker.dead_ranks == set()
    counters = telemetry.counters_snapshot().get("resilience", {})
    assert counters.get("worker_declared_dead", 0) == 0
    clients[1].shutdown()
    tracker.join(timeout=15)
    tracker.close()


def test_tracker_metrics_include_local_resilience_counters():
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    telemetry.reset()
    telemetry.inc("resilience", "task_restarts")
    tracker = RabitTracker("127.0.0.1", 1)
    try:
        text = tracker.telemetry.prometheus_text()
        assert 'dmlc_resilience_task_restarts{rank="tracker"} 1' in text
    finally:
        tracker.close()


def test_client_dead_tracker_fails_fast_with_backoff(monkeypatch):
    from dmlc_tpu.tracker.client import TrackerClient

    monkeypatch.setenv("DMLC_CLIENT_RETRIES", "2")
    monkeypatch.setenv("DMLC_CLIENT_RETRY_BASE_S", "0.01")
    probe = socket.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()  # nothing listens here now
    telemetry.reset()
    t0 = time.monotonic()
    with pytest.raises(OSError):
        TrackerClient("127.0.0.1", dead_port)._dial()
    assert time.monotonic() - t0 < 10
    assert telemetry.counters_snapshot()["resilience"]["retries"] == 1


def test_client_silent_tracker_times_out(monkeypatch):
    from dmlc_tpu.tracker.client import TrackerClient

    monkeypatch.setenv("DMLC_CLIENT_RETRIES", "1")
    monkeypatch.setenv("DMLC_CLIENT_OP_TIMEOUT_S", "0.5")
    silent = socket.socket()
    silent.bind(("127.0.0.1", 0))
    silent.listen(1)  # accepts but never answers the magic
    try:
        with pytest.raises(OSError):
            TrackerClient("127.0.0.1", silent.getsockname()[1])._dial()
    finally:
        silent.close()


# ---------------------------------------------------------------------------
# launcher restart budget
# ---------------------------------------------------------------------------

def test_max_restarts_opt_maps_to_attempts():
    from dmlc_tpu.tracker.opts import get_opts

    args = get_opts(["--cluster", "local", "--num-workers", "1",
                     "--max-restarts", "5", "--", "true"])
    assert args.max_attempts == 6
    args = get_opts(["--cluster", "local", "--num-workers", "1",
                     "--max-restarts", "0", "--", "true"])
    assert args.max_attempts == 1
    args = get_opts(["--cluster", "local", "--num-workers", "1",
                     "--max-attempts", "4", "--", "true"])
    assert args.max_attempts == 4  # legacy knob untouched


def test_gang_scheduler_counts_restarts_and_blacklists():
    from dmlc_tpu.tracker import launch

    telemetry.reset()
    calls = []

    def runner(host, role, task_id, env):
        calls.append(host)
        return 1 if host == "bad" else 0

    sched = launch.GangScheduler(["bad", "good"], runner,
                                 max_attempts=3, blacklist_after=2)
    sched.run_all(n_workers=2, n_servers=0,
                  envs={"DMLC_TRACKER_URI": "x", "DMLC_TRACKER_PORT": "1"},
                  cluster="tpu-vm")
    counters = telemetry.counters_snapshot()["resilience"]
    assert counters["task_restarts"] >= 1
    assert counters["hosts_blacklisted"] == 1
    assert "bad" in sched.blacklist


def test_gang_scheduler_budget_exhaustion_counted():
    from dmlc_tpu.tracker import launch

    telemetry.reset()
    sched = launch.GangScheduler(["h0"], lambda *a: 1,
                                 max_attempts=2, blacklist_after=99)
    with pytest.raises(RuntimeError, match="after 2 attempts"):
        sched.run_task("worker", 0, {}, "tpu-vm")
    counters = telemetry.counters_snapshot()["resilience"]
    assert counters["task_restarts"] == 1
    assert counters["task_budget_exhausted"] == 1


# ---------------------------------------------------------------------------
# the full chain: fault-injected death -> detection -> restart -> recover
# ---------------------------------------------------------------------------

def test_chaos_smoke_end_to_end():
    """Runs scripts/chaos_smoke.py (ci.sh stage 7) as a subprocess: a
    fault-injected kill of rank 1 at a barrier must end in a completed
    job with death/restart/readmission all visible on /metrics."""
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DMLC_FAULT_SPEC", None)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "chaos_smoke.py")],
        capture_output=True, text=True, timeout=150, env=env)
    assert r.returncode == 0, (r.stdout[-3000:], r.stderr[-3000:])
    assert "chaos smoke OK" in r.stdout


def test_recover_after_timeout_flagged_peer():
    """A peer socket that times out (not just closes) must surface as
    OSError so the recover path catches it — socket.timeout IS an
    OSError; guard the contract the chaos path relies on."""
    assert issubclass(socket.timeout, OSError)
    assert issubclass(FaultInjected, OSError)


def test_threads_dont_leak_from_failure_detector():
    from dmlc_tpu.tracker.rendezvous import RabitTracker

    before = threading.active_count()
    tracker = RabitTracker("127.0.0.1", 1, miss_window_s=0.2)
    tracker.start(1)
    tracker.close()
    deadline = time.time() + 5
    while threading.active_count() > before + 1 and time.time() < deadline:
        time.sleep(0.05)
    # accept thread may linger on its dying socket; the monitor must be
    # gone (stop event set by close)
    assert not any(t.name == "tracker-failure-detector" and t.is_alive()
                   and not tracker._monitor_stop.is_set()
                   for t in threading.enumerate())


# ---------------------------------------------------------------------------
# verified remote range reads (DMLC_INTEGRITY_VERIFY_READS)
# ---------------------------------------------------------------------------

def test_verified_read_catches_and_heals_injected_corruption(monkeypatch):
    """With verification on, one corrupted storage response is caught by
    the double-read compare and the CLEAN bytes are served."""
    from dmlc_tpu import telemetry
    from dmlc_tpu.io.http_filesys import HttpReadStream

    payload = bytes(range(256)) * 4

    class S(HttpReadStream):
        def __init__(self):
            super().__init__("http://x", size=len(payload))

        def _fill(self, start, size):
            return payload[start:start + size]

    monkeypatch.setenv("DMLC_INTEGRITY_VERIFY_READS", "1")
    install_injector("storage.response=corrupt::1")
    try:
        before = telemetry.counters_snapshot().get("integrity", {}).get(
            "read_verify_failures", 0)
        out = S().read(len(payload))
        after = telemetry.counters_snapshot().get("integrity", {}).get(
            "read_verify_failures", 0)
    finally:
        reset_injector()
    assert out == payload, "corrupted response was served, not healed"
    assert after == before + 1


def test_verified_read_persistent_corruption_raises(monkeypatch):
    """A source that never returns the same bytes twice is rotten; the
    verified read gives up loudly after its retry budget."""
    import os as _os

    from dmlc_tpu.base import DMLCError
    from dmlc_tpu.io.http_filesys import HttpReadStream

    class S(HttpReadStream):
        def __init__(self):
            super().__init__("http://x", size=64)

        def _fill(self, start, size):
            return _os.urandom(min(size, 64 - start))

    monkeypatch.setenv("DMLC_INTEGRITY_VERIFY_READS", "1")
    monkeypatch.setenv("DMLC_INTEGRITY_READ_RETRIES", "3")
    with pytest.raises(DMLCError, match="double-read"):
        S().read(64)


def test_verification_off_by_default_single_fetch(monkeypatch):
    """The default path must not pay the second fetch."""
    from dmlc_tpu.io.http_filesys import HttpReadStream

    monkeypatch.delenv("DMLC_INTEGRITY_VERIFY_READS", raising=False)
    calls = []

    class S(HttpReadStream):
        def __init__(self):
            super().__init__("http://x", size=64)

        def _fill(self, start, size):
            calls.append((start, size))
            return b"A" * min(size, 64 - start)

    assert S().read(64) == b"A" * 64
    assert len(calls) == 1
