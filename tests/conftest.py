"""Test configuration: force a deterministic 8-device virtual CPU mesh.

Multi-chip sharding is validated on a virtual CPU mesh
(xla_force_host_platform_device_count), per the TPU-rebuild test strategy;
real-chip benchmarks live in bench.py, not tests.

The container boots with an experimental TPU PJRT plugin pre-registered
(JAX_PLATFORMS=axon via sitecustomize), so an env-var setdefault is not
enough — we must override the platform through jax.config before first
backend use.
"""

import os

# must be set before jax is imported anywhere in the test session
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
