"""Bucketed-overlap gradient reduction (parallel/overlap.py): future
exception transport, bit-parity of the bucketed path against the
synchronous collective, elastic mid-bucket shrink safety, and the step
ledger's exposed-vs-overlapped collective split (ISSUE 9)."""

import threading
import time

import numpy as np
import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.parallel.overlap import (
    CollectiveFuture,
    GradientBucketer,
    bucket_bytes,
    reverse_topological,
)
from dmlc_tpu.tracker import RabitTracker, TrackerClient, WorldResized


# ---------------------------------------------------------------------------
# CollectiveFuture: the defined exception path off the worker thread
# ---------------------------------------------------------------------------

def test_future_result_and_exception_transport():
    fut = CollectiveFuture()
    assert not fut.done()
    with pytest.raises(TimeoutError):
        fut.result(timeout=0.01)
    fut.set_result(41)
    assert fut.done() and fut.result() == 41 and fut.exception() is None

    fut = CollectiveFuture()
    err = WorldResized("shrunk", gen=3)

    def worker():
        time.sleep(0.02)
        fut.set_exception(err)

    threading.Thread(target=worker, daemon=True).start()
    with pytest.raises(WorldResized) as ei:
        fut.result(timeout=5)
    assert ei.value is err and ei.value.gen == 3
    assert fut.exception() is err


def test_bucket_bytes_knob(monkeypatch):
    monkeypatch.setenv("DMLC_COLL_BUCKET_MB", "2")
    assert bucket_bytes() == 2 << 20
    monkeypatch.setenv("DMLC_COLL_BUCKET_MB", "0.25")
    assert bucket_bytes() == 1 << 18
    assert reverse_topological(4) == [3, 2, 1, 0]


# ---------------------------------------------------------------------------
# GradientBucketer against a local "collective" (no sockets): packing /
# unpacking round-trip, all-or-nothing failure, worker reuse
# ---------------------------------------------------------------------------

def test_bucketer_roundtrip_preserves_shapes_and_values():
    calls = []

    def fake_allreduce(buf):
        calls.append(buf.size)
        return buf * 2.0

    b = GradientBucketer(fake_allreduce, bucket_bytes_=4 * 4)  # 4 elems
    leaves = [np.arange(6, dtype=np.float32).reshape(2, 3),
              np.asarray(7.0, np.float32),  # 0-d leaf
              np.arange(5, dtype=np.float32)]
    out = b.reduce_leaves(leaves)
    assert [o.shape for o in out] == [(2, 3), (), (5,)]
    for o, leaf in zip(out, leaves):
        np.testing.assert_array_equal(o, np.asarray(leaf) * 2.0)
    # 12 elems / 4-elem buckets = 3 buckets, every bucket full
    assert calls == [4, 4, 4]
    b.close()


def test_bucketer_failure_is_all_or_nothing_and_reusable():
    boom = [True]

    def flaky(buf):
        if boom[0] and buf[0] >= 4:  # second bucket fails
            raise WorldResized("mid-bucket shrink", gen=1)
        return buf + 1.0

    b = GradientBucketer(flaky, bucket_bytes_=4 * 4)
    leaves = [np.arange(12, dtype=np.float32)]
    snapshot = leaves[0].copy()
    with pytest.raises(WorldResized):
        b.reduce_leaves(leaves)
    # inputs untouched, worker drained and immediately reusable
    np.testing.assert_array_equal(leaves[0], snapshot)
    boom[0] = False
    out = b.reduce_leaves(leaves)
    np.testing.assert_array_equal(out[0], snapshot + 1.0)
    b.close()


# ---------------------------------------------------------------------------
# Bit-parity against the synchronous collective through a REAL tracker
# ---------------------------------------------------------------------------

def _run_workers(n, fn, elastic=False):
    tracker = RabitTracker("127.0.0.1", n)
    tracker.start(n)
    results = [None] * n
    errors = []

    def work(i):
        try:
            c = TrackerClient("127.0.0.1", tracker.port, jobid=f"ov{i}")
            c.start()
            results[i] = fn(c)
            c.shutdown()
        except Exception as e:  # pragma: no cover - surfaced below
            errors.append((i, e))

    threads = [threading.Thread(target=work, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    assert not errors, errors
    tracker.join(timeout=30)
    tracker.close()
    return results


@pytest.mark.parametrize("n", [2, 3, 5])
@pytest.mark.parametrize("bucket_elems", [7, 64, 4096])
def test_bucketed_matches_sync_bitwise(n, bucket_elems):
    """Bucketed-overlapped allreduce must be bit-identical to the
    synchronous path for sum/max/min across odd worlds, world=2, and
    bucket sizes smaller than one gradient leaf (7 f32 elems = 28
    bytes against 100-elem leaves)."""

    def fn(c):
        rng = np.random.default_rng(c.rank)
        # integer-valued floats: exactly representable, so even a
        # reduction order change could not hide behind fp noise
        leaves = [rng.integers(-1000, 1000, (4, 25)).astype(np.float32),
                  rng.integers(-1000, 1000, 33).astype(np.float32),
                  rng.integers(-1000, 1000, (2, 2, 2)).astype(np.float32)]
        flat = np.concatenate([lf.reshape(-1) for lf in leaves])
        out = {}
        for op in ("sum", "max", "min"):
            sync = c.allreduce(flat, op)
            b = GradientBucketer(lambda a, op=op: c.allreduce(a, op),
                                 bucket_bytes_=bucket_elems * 4)
            red = b.reduce_leaves(leaves)
            b.close()
            out[op] = (sync, np.concatenate([r.reshape(-1) for r in red]))
        return out

    for res in _run_workers(n, fn):
        for op, (sync, bucketed) in res.items():
            np.testing.assert_array_equal(sync, bucketed, err_msg=op)


def test_reduce_tree_restores_structure():
    """reduce_tree packs reverse-topologically but returns the reduced
    pytree in the ORIGINAL structure with matching shapes."""
    jax = pytest.importorskip("jax")

    order_seen = []

    def fake_allreduce(buf):
        order_seen.append(buf.copy())
        return buf

    tree = {"a": np.full((2, 2), 1.0, np.float32),
            "b": [np.full(3, 2.0, np.float32),
                  np.full(1, 3.0, np.float32)]}
    b = GradientBucketer(fake_allreduce, bucket_bytes_=1 << 20)
    out = b.reduce_tree(tree)
    b.close()
    assert jax.tree_util.tree_structure(out) == \
        jax.tree_util.tree_structure(tree)
    np.testing.assert_array_equal(out["a"], tree["a"])
    np.testing.assert_array_equal(out["b"][0], tree["b"][0])
    np.testing.assert_array_equal(out["b"][1], tree["b"][1])
    # one bucket, filled in reverse flatten order: b[1], b[0], then a
    np.testing.assert_array_equal(
        order_seen[0], np.asarray([3, 2, 2, 2, 1, 1, 1, 1], np.float32))


# ---------------------------------------------------------------------------
# Elastic interplay: a WorldResized on the collective thread transports
# to the caller; a mid-bucket shrink neither hangs nor corrupts inputs
# ---------------------------------------------------------------------------

MISS = 0.5
GRACE = 0.5


def test_mid_bucket_world_shrink_propagates_and_recovers():
    tracker = RabitTracker("127.0.0.1", 3, miss_window_s=MISS,
                           elastic=True, elastic_grace_s=GRACE)
    tracker.start(3)
    barrier = threading.Barrier(3)
    results = {}
    errors = []

    class Worker(threading.Thread):
        def __init__(self, i):
            super().__init__(daemon=True)
            self.i = i
            self._halt = threading.Event()

        def _beats(self, c):
            while not self._halt.wait(0.1):
                try:
                    c.send_metrics('{"counters": {}}')
                except OSError:
                    return

        def run(self):
            try:
                c = TrackerClient("127.0.0.1", tracker.port,
                                  jobid=f"sh{self.i}").start()
                threading.Thread(target=self._beats, args=(c,),
                                 daemon=True).start()
                try:
                    results[self.i] = self.fn(c)
                finally:
                    self._halt.set()
            except BaseException as e:  # noqa: BLE001
                errors.append((self.i, e))

        def fn(self, c):
            leaves = [np.full(100, float(c.rank + 1), np.float32)
                      for _ in range(4)]
            snapshot = [lf.copy() for lf in leaves]
            b = GradientBucketer(c.allreduce_sum, bucket_bytes_=100 * 4)
            first = b.reduce_leaves(leaves)
            np.testing.assert_array_equal(first[0],
                                          np.full(100, 6.0, np.float32))
            barrier.wait(timeout=20)
            if c.rank == 2:
                c._links_down()  # vanish mid-job, no handshake
                b.close()
                return ("died",)
            # keep reducing until the shrink lands; the exception MUST
            # surface at the join (no hang) and leave inputs untouched
            deadline = time.monotonic() + 30
            while True:
                assert time.monotonic() < deadline, \
                    "mid-bucket shrink never surfaced"
                try:
                    b.reduce_leaves(leaves)
                    time.sleep(0.05)
                except WorldResized:
                    break
            for lf, snap in zip(leaves, snapshot):
                np.testing.assert_array_equal(lf, snap)
            c.resize()
            assert c.world_size == 2
            # the bucketer (and its worker thread) survives the resize
            post = b.reduce_leaves(leaves)
            np.testing.assert_array_equal(
                post[0], np.full(100, 3.0, np.float32))
            b.close()
            out = ("survived", c.rank)
            c.shutdown()
            return out

    workers = [Worker(i) for i in range(3)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(90)
    assert not errors, errors
    tracker.join(timeout=30)
    tracker.close()
    assert sorted(len(r) for r in results.values()) == [1, 2, 2]


# ---------------------------------------------------------------------------
# Step ledger: exposed vs overlapped collective split
# ---------------------------------------------------------------------------

def test_ledger_splits_exposed_vs_overlapped():
    telemetry.reset()
    telemetry.reset_steps()
    led = telemetry.ledger()

    def background_collective():
        with telemetry.core.span("collective.allreduce",
                                 stage="collective"):
            time.sleep(0.05)

    led.step_begin()
    th = threading.Thread(target=background_collective)
    th.start()
    time.sleep(0.04)  # stepping thread computes: the worker's span hides
    with telemetry.core.span("collective.join", stage="collective"):
        th.join()  # the remainder is paid here, exposed
    rec = led.step_end(tokens=10)
    # worker time under the stepping thread's compute is overlapped;
    # the join span (and the worker time underneath it) is exposed
    assert rec["collective_overlapped_s"] >= 0.02
    assert rec["collective_s"] >= 0.005
    summary = led.summary()
    assert summary["collective_overlapped_fraction"] > 0
    assert summary["collective_exposed_fraction"] > 0


def test_ledger_overlap_clipped_to_step_window():
    """A background collective span that started BEFORE the step only
    contributes the part inside the step window."""
    telemetry.reset()
    telemetry.reset_steps()
    led = telemetry.ledger()
    started = threading.Event()

    def long_collective():
        with telemetry.core.span("collective.allreduce",
                                 stage="collective"):
            started.set()
            time.sleep(0.1)

    th = threading.Thread(target=long_collective)
    th.start()
    started.wait(5)
    time.sleep(0.06)  # >half the span burns before the step opens
    led.step_begin()
    th.join()
    rec = led.step_end()
    assert 0 < rec["collective_overlapped_s"] < 0.06


class _SlowLeaf:
    """Array-like whose materialization sleeps — mimics the per-leaf
    device->host fetch the bucketer overlaps collectives under."""

    def __init__(self, a):
        self._a = a

    def __array__(self, dtype=None, copy=None):
        time.sleep(0.01)
        return self._a if dtype is None else self._a.astype(dtype)


def test_bucketer_drives_ledger_overlap_metrics():
    """End-to-end: a GradientBucketer reduction whose packing genuinely
    runs while earlier buckets reduce produces a nonzero overlapped
    share and the per-bucket counters."""
    telemetry.reset()
    telemetry.reset_steps()

    def slow_allreduce(buf):
        time.sleep(0.02)
        return buf.copy()

    b = GradientBucketer(slow_allreduce, bucket_bytes_=64)
    led = telemetry.ledger()
    led.step_begin()
    b.reduce_leaves([_SlowLeaf(np.zeros(16, np.float32))
                     for _ in range(4)])
    rec = led.step_end()
    b.close()
    assert rec["collective_overlapped_s"] > 0
    snap = telemetry.snapshot()
    assert snap["counters"]["collective"]["overlap_buckets"] >= 4
    timings = b.last_timings()
    assert len(timings) == 4 and all(s > 0 for _, s in timings)


def test_ledger_join_blocked_worker_time_is_not_overlapped():
    """A degenerate 'overlap' where the stepping thread immediately
    blocks in the join hides nothing: worker collective time spent
    while the stepping thread sits in a collective span of its own must
    count as EXPOSED, or a total loss of overlap would still report an
    overlapped share (and the perf-smoke overlap gate would pass
    vacuously)."""
    telemetry.reset()
    telemetry.reset_steps()
    led = telemetry.ledger()
    b = GradientBucketer(lambda a: (time.sleep(0.05), a)[1],
                         bucket_bytes_=1 << 20)
    led.step_begin()
    b.reduce_leaves([np.ones(8, np.float32)])  # packing is instant
    rec = led.step_end()
    b.close()
    assert rec["collective_s"] >= 0.04
    assert rec["collective_overlapped_s"] < 0.01


def test_bucketer_zero_size_leaves_roundtrip():
    """Zero-size leaves (an unused parameter's empty gradient) pack and
    unpack cleanly instead of tripping np.concatenate([])."""
    b = GradientBucketer(lambda a: a, bucket_bytes_=64)
    r = b.reduce_leaves([np.ones(3, np.float32),
                         np.zeros((0,), np.float32),
                         np.zeros((0, 3), np.float32),
                         np.full(2, 7.0, np.float32)])
    b.close()
    assert [x.shape for x in r] == [(3,), (0,), (0, 3), (2,)]
    assert np.array_equal(r[0], np.ones(3, np.float32))
    assert np.array_equal(r[3], np.full(2, 7.0, np.float32))
