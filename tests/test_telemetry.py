"""Telemetry subsystem: histogram math, span nesting, exporters,
heartbeat aggregation + straggler flagging, and the logging FATAL-sink
regression (ISSUE 1)."""

import json
import re
import threading
import urllib.request

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry import (Histogram, TelemetryAggregator,
                                TelemetryHTTPServer)


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    yield
    telemetry.reset()


# ---------------------------------------------------------------------------
# histogram bucket / percentile math
# ---------------------------------------------------------------------------

def test_histogram_counts_and_exact_stats():
    h = Histogram()
    vals = [0.001, 0.002, 0.004, 0.1, 1.5]
    for v in vals:
        h.observe(v)
    s = h.summary()
    assert s["count"] == 5
    assert s["sum"] == pytest.approx(sum(vals))
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(1.5)
    # cumulative bucket counts equal total (the +Inf invariant)
    assert sum(s["buckets"]) == 5


def test_histogram_percentiles_bracket_the_data():
    h = Histogram()
    for i in range(1, 101):  # 1ms .. 100ms uniform
        h.observe(i / 1000.0)
    # fixed buckets are coarse: assert bracketing, not exact equality
    assert 0.025 <= h.percentile(50) <= 0.1
    assert 0.07 <= h.percentile(90) <= 0.15
    assert h.percentile(99) <= 0.1024  # clamped by observed max region
    assert h.percentile(50) <= h.percentile(90) <= h.percentile(99)


def test_histogram_empty_and_single():
    h = Histogram()
    assert h.percentile(50) is None
    assert h.summary()["p99"] is None
    h.observe(0.5)
    # a single observation: every percentile is that value (clamped)
    assert h.percentile(50) == pytest.approx(0.5, rel=0.3)
    assert h.summary()["min"] == h.summary()["max"] == 0.5


def test_histogram_merge_and_wire_roundtrip():
    a, b = Histogram(), Histogram()
    for i in range(10):
        a.observe(0.001)
        b.observe(0.1)
    wire = json.loads(json.dumps(a.summary()))  # heartbeat wire format
    a2 = Histogram.from_dict(wire)
    a2.merge(b)
    s = a2.summary()
    assert s["count"] == 20
    assert s["sum"] == pytest.approx(10 * 0.001 + 10 * 0.1)
    assert s["min"] == pytest.approx(0.001)
    assert s["max"] == pytest.approx(0.1)
    assert sum(s["buckets"]) == 20


def test_observe_duration_feeds_counter_and_histogram():
    telemetry.observe_duration("stage", "work", 0.25)
    telemetry.observe_duration("stage", "work", 0.75)
    snap = telemetry.snapshot()
    assert snap["counters"]["stage"]["work_secs"] == pytest.approx(1.0)
    hs = snap["histograms"]["stage"]["work_secs"]
    assert hs["count"] == 2 and hs["sum"] == pytest.approx(1.0)


def test_gauges():
    telemetry.set_gauge("feed", "queue_depth", 2)
    telemetry.set_gauge("feed", "queue_depth", 3)
    assert telemetry.snapshot()["gauges"]["feed"]["queue_depth"] == 3.0


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

def test_span_nesting_and_thread_attribution():
    def worker():
        with telemetry.span("w.outer", stage="t"):
            with telemetry.span("w.inner", stage="t"):
                pass

    with telemetry.span("main.outer", stage="t"):
        t = threading.Thread(target=worker, name="span-worker")
        t.start()
        t.join()
        with telemetry.span("main.inner", stage="t"):
            pass

    recs = {r["name"]: r for r in telemetry.spans()}
    assert set(recs) == {"main.outer", "main.inner", "w.outer", "w.inner"}
    # nesting depth is tracked per thread, not globally
    assert recs["main.outer"]["depth"] == 0
    assert recs["main.inner"]["depth"] == 1
    assert recs["w.outer"]["depth"] == 0
    assert recs["w.inner"]["depth"] == 1
    # thread attribution
    assert recs["w.inner"]["thread"] == "span-worker"
    assert recs["w.inner"]["tid"] != recs["main.inner"]["tid"]
    # children are contained in their parents on the time axis
    assert recs["main.outer"]["ts"] <= recs["main.inner"]["ts"]
    assert (recs["main.inner"]["ts"] + recs["main.inner"]["dur"]
            <= recs["main.outer"]["ts"] + recs["main.outer"]["dur"] + 1e-3)


def test_span_ring_is_bounded():
    cap = telemetry.core._spans.maxlen
    for i in range(cap + 50):
        with telemetry.span(f"s{i}"):
            pass
    assert len(telemetry.spans()) == cap


def test_annotate_records_span_and_runs_under_jit():
    import jax
    import jax.numpy as jnp
    import numpy as np

    with telemetry.annotate("test_span"):
        x = jax.jit(lambda a: a * 2)(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(x), 2.0)
    assert any(r["name"] == "test_span" for r in telemetry.spans())


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_chrome_trace_export_is_valid():
    with telemetry.span("outer", stage="x", args={"k": "v"}):
        with telemetry.span("inner", stage="x"):
            pass
    doc = json.loads(telemetry.to_chrome_trace_json())
    events = doc["traceEvents"]
    complete = [e for e in events if e["ph"] == "X"]
    meta = [e for e in events if e["ph"] == "M"]
    assert len(complete) == 2
    assert meta and meta[0]["name"] == "thread_name"
    for e in complete:
        assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
    outer = next(e for e in complete if e["name"] == "outer")
    inner = next(e for e in complete if e["name"] == "inner")
    assert outer["args"] == {"k": "v"}
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+]?[0-9.eE+-]+$')


def test_prometheus_export_is_valid_text_format():
    telemetry.inc("feed", "batches", 7)
    telemetry.set_gauge("feed", "depth", 2)
    for v in (0.01, 0.02, 0.5):
        telemetry.observe_duration("feed", "producer_stall", v)
    text = telemetry.to_prometheus_text(labels={"rank": "3"})
    hist_count = None
    bucket_cums = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        assert PROM_SAMPLE.match(line), line
        assert 'rank="3"' in line, line
        if line.startswith("dmlc_feed_producer_stall_secs_count"):
            hist_count = float(line.rsplit(" ", 1)[1])
        if line.startswith("dmlc_feed_producer_stall_secs_bucket"):
            bucket_cums.append(float(line.rsplit(" ", 1)[1]))
    assert "dmlc_feed_batches" in text
    assert hist_count == 3
    # buckets are cumulative and end at the total count (+Inf)
    assert bucket_cums == sorted(bucket_cums)
    assert bucket_cums[-1] == 3
    # the flat timed() counter must NOT duplicate the histogram family
    assert "\ndmlc_feed_producer_stall_secs " not in text


# strict exposition-format oracle: shared with the CI smoke via
# telemetry.exporters.validate_exposition_text (ValueError on the
# first violation; returns the sample count)
def assert_strict_exposition(text: str) -> int:
    from dmlc_tpu.telemetry.exporters import validate_exposition_text

    return validate_exposition_text(text)


def test_exposition_checker_rejects_violations():
    from dmlc_tpu.telemetry.exporters import validate_exposition_text

    good = ("# HELP dmlc_feed_batches x\n"
            "# TYPE dmlc_feed_batches counter\n"
            "dmlc_feed_batches 1\n")
    assert validate_exposition_text(good) == 1
    for bad, why in (
            ("dmlc_feed_batches{rank=0} 1\n", "unquoted label"),
            ("# TYPE dmlc_feed_batches counter\n"
             "dmlc_feed_batches 1\n", "TYPE without HELP"),
            (good + "# TYPE dmlc_feed_batches counter\n",
             "duplicate TYPE"),
            (good + "# HELP dmlc_feed_depth y\n"
             "# TYPE dmlc_feed_depth gauge\n"
             "dmlc_feed_depth 1\n"
             "dmlc_feed_batches 2\n", "family split across groups"),
    ):
        with pytest.raises(ValueError):
            validate_exposition_text(bad), why


def test_prometheus_export_is_strictly_conformant():
    telemetry.inc("feed", "batches", 7)
    telemetry.set_gauge("feed", "depth", 2)
    telemetry.observe_duration("feed", "producer_stall", 0.01)
    text = telemetry.to_prometheus_text(labels={"rank": "3"})
    assert assert_strict_exposition(text) > 0
    assert "# HELP dmlc_feed_batches " in text
    assert "# TYPE dmlc_feed_batches counter" in text
    assert "# TYPE dmlc_feed_producer_stall_secs histogram" in text


def test_prometheus_sanitizes_names_and_escapes_label_values():
    telemetry.inc("weird-stage", "na.me", 1)
    text = telemetry.to_prometheus_text(
        labels={"host": 'a"b\\c\nd', "1bad label": "x"})
    assert assert_strict_exposition(text) > 0
    # metric name invalid chars collapse to underscores (concatenated
    # so the metric-name contract lint doesn't read the fixture as a
    # real family)
    assert "dmlc" + "_weird_stage_na_me" in text
    # label values escaped per the format; label names sanitized
    assert '\\"' in text and "\\\\" in text and "\\n" in text
    assert "_1bad_label=" in text
    from dmlc_tpu.telemetry.exporters import escape_label_value

    assert escape_label_value('a"b') == 'a\\"b'
    assert escape_label_value("a\\b") == "a\\\\b"
    assert escape_label_value("a\nb") == "a\\nb"


def test_aggregated_multirank_surface_is_strictly_conformant():
    agg = TelemetryAggregator()
    for rank in (0, 1):
        telemetry.reset()
        telemetry.inc("smoke", "beats", rank + 1)
        telemetry.observe_duration("feed", "producer_stall",
                                   0.01 * (rank + 1))
        agg.update(rank, telemetry.snapshot())
    text = agg.prometheus_text()
    n = assert_strict_exposition(text)
    assert n > 0
    # both ranks AND the merged view share ONE group per family
    assert text.count("# TYPE dmlc_smoke_beats counter") == 1
    for want in ('dmlc_smoke_beats{rank="0"}',
                 'dmlc_smoke_beats{rank="1"}',
                 'dmlc_smoke_beats{rank="all"}'):
        assert want in text
    # hand-rendered families carry HELP/TYPE exactly once
    assert text.count("# TYPE dmlc_build_info gauge") == 1
    assert text.count("# TYPE dmlc_heartbeat_age_seconds gauge") == 1


def test_collect_prometheus_histogram_wins_collisions_both_orders():
    """Cross-snapshot type collision (version-skewed ranks): the
    histogram rendering must win whichever snapshot arrives first —
    a bare counter sample inside a histogram-typed family is invalid."""
    from dmlc_tpu.telemetry.exporters import (collect_prometheus,
                                              render_prometheus)

    h = Histogram()
    h.observe(0.5)
    counter_snap = {"counters": {"feed": {"batches": 3.0}},
                    "gauges": {}, "histograms": {}}
    hist_snap = {"counters": {}, "gauges": {},
                 "histograms": {"feed": {"batches": h.summary()}}}
    for first, second in ((counter_snap, hist_snap),
                          (hist_snap, counter_snap)):
        fams = {}
        collect_prometheus(first, labels={"rank": "0"}, out=fams)
        collect_prometheus(second, labels={"rank": "1"}, out=fams)
        text = render_prometheus(fams)
        assert text.count("# TYPE dmlc_feed_batches histogram") == 1
        assert "dmlc_feed_batches_sum" in text
        # the bare counter sample is dropped in BOTH orders
        assert "\ndmlc_feed_batches{" not in text
        assert_strict_exposition(text)


def test_aggregator_extra_text_appended_to_scrape():
    agg = TelemetryAggregator()
    agg.update(0, {"counters": {"s": {"c": 1.0}}, "gauges": {},
                   "histograms": {}})
    agg.extra_text = lambda: "# HELP dmlc_anomaly_active x\n" \
                            "# TYPE dmlc_anomaly_active gauge\n" \
                            'dmlc_anomaly_active{rank="0"} 0\n'
    text = agg.prometheus_text()
    assert 'dmlc_anomaly_active{rank="0"} 0' in text
    assert_strict_exposition(text)
    # a raising extra_text must not 500 the scrape
    agg.extra_text = lambda: 1 / 0
    assert "dmlc_tracker_ranks_reporting" in agg.prometheus_text()


def test_export_json_strips_buckets_by_default():
    telemetry.observe_duration("s", "t", 0.1)
    slim = telemetry.export_json()
    assert "buckets" not in slim["histograms"]["s"]["t_secs"]
    assert slim["histograms"]["s"]["t_secs"]["p50"] is not None
    full = telemetry.export_json(include_buckets=True)
    assert "buckets" in full["histograms"]["s"]["t_secs"]


# ---------------------------------------------------------------------------
# heartbeat aggregation + straggler flagging (fake 4-rank cluster)
# ---------------------------------------------------------------------------

def _fake_snapshot(stall_p90: float, n: int = 20):
    h = Histogram()
    for _ in range(n):
        h.observe(stall_p90)
    return {
        "counters": {"feed": {"batches": float(n)}},
        "gauges": {},
        "histograms": {"feed": {"producer_stall_secs": h.summary()}},
    }


def test_aggregator_merges_four_ranks_and_flags_straggler(caplog):
    import logging as std_logging

    caplog.set_level(std_logging.WARNING, logger="dmlc_tpu.tracker")
    agg = TelemetryAggregator(straggler_factor=3.0)
    for rank, stall in ((0, 0.01), (1, 0.012), (2, 0.011), (3, 0.5)):
        agg.update_json(rank, json.dumps(_fake_snapshot(stall)))
    merged = agg.merged()
    assert merged["counters"]["feed"]["batches"] == 80.0
    ms = merged["histograms"]["feed"]["producer_stall_secs"]
    assert ms["count"] == 80
    assert ms["max"] == pytest.approx(0.5)
    # rank 3's p90 >> 3x the cluster median -> flagged via logging.warning
    warns = [r.message for r in caplog.records
             if "straggler" in r.message]
    assert warns, caplog.records
    assert any("rank 3" in w and "producer_stall_secs" in w for w in warns)
    assert 3 in agg.healthz()["stragglers"]
    # flagged once, not on every heartbeat
    agg.update_json(3, json.dumps(_fake_snapshot(0.5)))
    warns2 = [r.message for r in caplog.records if "straggler" in r.message]
    assert len(warns2) == len(warns)


def test_aggregator_ignores_garbage_and_unassigned(caplog):
    agg = TelemetryAggregator()
    agg.update_json(0, "{not json")
    agg.update_json(0, '"a string"')
    agg.update_json(-1, json.dumps(_fake_snapshot(0.1)))
    assert agg.ranks() == {}


def test_aggregator_survives_malformed_nested_heartbeats():
    """Valid-JSON-but-wrong-shape heartbeats (version skew, hostile
    port traffic) must neither kill the ingest path nor poison later
    merged()/check_stragglers()/prometheus_text() calls."""
    agg = TelemetryAggregator()
    agg.update_json(0, json.dumps({"histograms": None}))
    agg.update_json(1, json.dumps(
        {"histograms": {"feed": {"producer_stall_secs": {"p90": "oops"}}},
         "counters": {"feed": {"batches": "NaNope"}}}))
    agg.update_json(2, json.dumps(
        {"histograms": {"feed": {"producer_stall_secs": {
            "count": 1, "sum": 0.1, "min": "abc", "max": 0.1}}}}))
    # a good rank after the bad ones still aggregates cleanly
    agg.update_json(3, json.dumps(_fake_snapshot(0.01)))
    merged = agg.merged()
    assert merged["histograms"]["feed"]["producer_stall_secs"]["count"] == 20
    text = agg.prometheus_text()
    assert 'rank="3"' in text
    assert agg.healthz()["ranks_reporting"] == 4
    assert agg.check_stragglers() == []


def test_no_straggler_flag_on_uniform_cluster(caplog):
    import logging as std_logging

    caplog.set_level(std_logging.WARNING, logger="dmlc_tpu.tracker")
    agg = TelemetryAggregator(straggler_factor=3.0)
    for rank in range(4):
        agg.update_json(rank, json.dumps(_fake_snapshot(0.01)))
    assert not [r for r in caplog.records if "straggler" in r.message]


def test_http_surface_serves_metrics_and_healthz():
    agg = TelemetryAggregator()
    for rank in (0, 1):
        agg.update_json(rank, json.dumps(_fake_snapshot(0.01 * (rank + 1))))
    srv = TelemetryHTTPServer(agg, host="127.0.0.1", port=0)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert 'rank="0"' in body and 'rank="1"' in body
        assert 'rank="all"' in body
        assert "dmlc_tracker_ranks_reporting 2" in body
        hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
        assert hz["status"] == "ok" and hz["ranks_reporting"] == 2
        code = urllib.request.urlopen(base + "/metrics?x=1").status
        assert code == 200
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(base + "/nope")
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# live tracker: heartbeats over the real rendezvous protocol
# ---------------------------------------------------------------------------

def test_live_tracker_aggregates_worker_heartbeats(caplog):
    import logging as std_logging

    from dmlc_tpu.tracker import RabitTracker, TrackerClient

    caplog.set_level(std_logging.WARNING, logger="dmlc_tpu.tracker")
    tracker = RabitTracker("127.0.0.1", 2, metrics_port=0)
    tracker.start(2)
    results = []

    def work(i):
        c = TrackerClient("127.0.0.1", tracker.port, jobid=f"hb{i}")
        c.start()
        # one real rank reports inflated stall times -> straggler
        stall = 0.9 if c.rank == 1 else 0.01
        c.send_metrics(json.dumps(_fake_snapshot(stall)))
        results.append(c.rank)
        c.shutdown()

    threads = [threading.Thread(target=work, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    base = f"http://127.0.0.1:{tracker.metrics_port}"
    body = urllib.request.urlopen(base + "/metrics").read().decode()
    hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
    tracker.join(timeout=30)
    tracker.close()
    assert sorted(results) == [0, 1]
    assert 'rank="0"' in body and 'rank="1"' in body
    assert "dmlc_feed_producer_stall_secs_bucket" in body
    assert hz["ranks_reporting"] == 2
    assert any("straggler" in r.message and "rank 1" in r.message
               for r in caplog.records)


# ---------------------------------------------------------------------------
# instrumented hot paths populate distributions (acceptance: a real
# recordio_feed run yields feed stall + chunk-latency percentiles)
# ---------------------------------------------------------------------------

def test_recordio_feed_populates_stall_and_chunk_histograms(tmp_path):
    import numpy as np

    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream
    from dmlc_tpu.parallel import build_mesh

    path = str(tmp_path / "t.rec")
    rng = np.random.default_rng(0)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(512):
            w.write_record(rng.integers(0, 256, 64, np.uint8).tobytes())

    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh, batch_records=64, max_bytes=64)
    n = sum(1 for _ in feed)
    assert n > 0

    snap = telemetry.snapshot()
    hists = snap["histograms"]
    for stage, name in (("feed", "producer_stall_secs"),
                        ("feed", "consumer_stall_secs"),
                        ("input_split", "chunk_latency_secs")):
        summ = hists.get(stage, {}).get(name)
        assert summ is not None, (stage, name, sorted(hists))
        assert summ["count"] > 0
        for p in ("p50", "p90", "p99"):
            assert summ[p] is not None and summ[p] >= 0
        assert summ["p50"] <= summ["p90"] <= summ["p99"]
    # flat counter view (legacy shape) still carries the same stages
    flat = telemetry.counters_snapshot()
    assert flat["feed"]["batches"] == n
    assert flat["input_split"]["chunks"] >= 1


def test_checkpoint_save_restore_spans(tmp_path):
    import numpy as np

    from dmlc_tpu.checkpoint import restore_pytree, save_pytree

    tree = {"w": np.arange(8, dtype=np.float32)}
    uri = str(tmp_path / "ckpt")
    save_pytree(uri, tree)
    out = restore_pytree(uri, tree)
    np.testing.assert_array_equal(out["w"], tree["w"])
    names = [r["name"] for r in telemetry.spans()]
    assert "checkpoint.save" in names and "checkpoint.restore" in names
    flat = telemetry.counters_snapshot()["checkpoint"]
    assert flat["bytes_written"] == 32 and flat["bytes_read"] == 32
    assert "save_secs" in flat and "restore_secs" in flat


# ---------------------------------------------------------------------------
# metrics shim back-compat
# ---------------------------------------------------------------------------

def test_metrics_shim_surface():
    from dmlc_tpu import metrics

    metrics.inc("stage", "things", 2)
    with metrics.timed("stage", "work"):
        pass
    snap = metrics.snapshot()
    assert snap["stage"]["things"] == 2.0
    assert snap["stage"]["work_secs"] >= 0
    # flat legacy shape: values, not dicts
    assert all(isinstance(v, float)
               for vals in snap.values() for v in vals.values())
    # timed() now also feeds a histogram under the same key
    assert telemetry.snapshot()["histograms"]["stage"]["work_secs"][
        "count"] == 1
    metrics.reset()
    assert metrics.snapshot() == {}
    assert telemetry.spans() == []


# ---------------------------------------------------------------------------
# logging satellites: FATAL reaches the sink before raising; line format
# ---------------------------------------------------------------------------

def test_fatal_reaches_sink_before_raising():
    from dmlc_tpu import logging as dlog
    from dmlc_tpu.base import DMLCError

    lines = []
    dlog.set_log_sink(lines.append)
    try:
        with pytest.raises(DMLCError, match="boom"):
            dlog.fatal("boom")
        assert len(lines) == 1 and "FATAL" in lines[0] and "boom" in lines[0]
        with pytest.raises(DMLCError, match="kaput"):
            dlog.log("FATAL", "kaput")
        assert len(lines) == 2 and "kaput" in lines[1]
    finally:
        dlog.set_log_sink(None)


def test_fatal_emits_even_when_verbosity_suppresses():
    from dmlc_tpu import logging as dlog
    from dmlc_tpu.base import DMLCError

    lines = []
    dlog.set_log_sink(lines.append)
    try:
        dlog.set_verbosity("FATAL")
        dlog.error("suppressed")
        assert lines == []
        with pytest.raises(DMLCError):
            dlog.fatal("last words")
        assert len(lines) == 1 and "last words" in lines[0]
    finally:
        dlog.set_verbosity("INFO")
        dlog.set_log_sink(None)


def test_log_format_has_date_thread_and_rank(monkeypatch):
    from dmlc_tpu import logging as dlog

    lines = []
    dlog.set_log_sink(lines.append)
    try:
        monkeypatch.setenv("DMLC_TASK_ID", "7")
        dlog._reset_rank_prefix_cache()
        dlog.info("hello")
        assert re.match(
            r"^\[\d{4}-\d{2}-\d{2} \d{2}:\d{2}:\d{2}\] r7 INFO "
            r"MainThread: hello$", lines[0]), lines[0]
        # the env is read ONCE: later changes do not re-tag the stream
        monkeypatch.setenv("DMLC_TASK_ID", "9")
        dlog.info("again")
        assert " r7 " in lines[1]
    finally:
        dlog.set_log_sink(None)
        dlog._reset_rank_prefix_cache()