"""Serializer round trips (mirrors reference test/unittest/unittest_serializer.cc)."""

import numpy as np
import pytest

from dmlc_tpu.io.stream import MemoryBytesStream, MemoryFixedSizeStream
from dmlc_tpu import serializer as ser
from dmlc_tpu.base import DMLCError


def roundtrip(value, spec, factory=None):
    s = MemoryBytesStream()
    ser.write(s, value, spec)
    s.seek(0)
    return ser.read(s, spec, factory)


def test_scalars():
    assert roundtrip(42, "i32") == 42
    assert roundtrip(-7, "i64") == -7
    assert roundtrip(2**63 - 1, "i64") == 2**63 - 1
    assert roundtrip(3.5, "f32") == 3.5
    assert roundtrip(True, "bool") is True


def test_string_and_bytes():
    assert roundtrip("héllo wörld", "str") == "héllo wörld"
    assert roundtrip(b"\x00\xff\x01", "bytes") == b"\x00\xff\x01"


def test_pod_vector_fast_path():
    v = np.arange(1000, dtype=np.float32)
    out = roundtrip(v, ("vec", "f32"))
    np.testing.assert_array_equal(v, out)


def test_vector_of_strings():
    v = ["a", "bb", "", "dddd"]
    assert roundtrip(v, ("vec", "str")) == v


def test_map_of_vectors():
    # the exact shape used in reference call stack 3.4 (map<k, vector<v>>)
    m = {"x": np.array([1, 2, 3], dtype=np.int32), "y": np.array([], dtype=np.int32)}
    out = roundtrip(m, ("map", "str", ("vec", "i32")))
    assert set(out) == {"x", "y"}
    np.testing.assert_array_equal(out["x"], m["x"])
    assert out["y"].size == 0


def test_nested_composites():
    v = [{"a": [(1, 2.5)]}, {}]
    spec = ("vec", ("map", "str", ("vec", ("pair", "i32", "f64"))))
    assert roundtrip(v, spec) == v


def test_custom_saveload_class():
    class MyObj:
        def __init__(self, x=0, tags=None):
            self.x = x
            self.tags = tags or []

        def save(self, strm):
            ser.write(strm, self.x, "i32")
            ser.write(strm, self.tags, ("vec", "str"))

        def load(self, strm):
            self.x = ser.read(strm, "i32")
            self.tags = ser.read(strm, ("vec", "str"))

    obj = MyObj(5, ["p", "q"])
    out = roundtrip(obj, "obj", factory=MyObj)
    assert out.x == 5 and out.tags == ["p", "q"]


def test_wire_format_is_dmlc_compatible():
    """uint64 little-endian length prefix + raw data (serializer.h:105-170)."""
    s = MemoryBytesStream()
    ser.write(s, "ab", "str")
    raw = s.getvalue()
    assert raw == b"\x02\x00\x00\x00\x00\x00\x00\x00ab"
    s2 = MemoryBytesStream()
    ser.write(s2, np.array([1], dtype=np.uint32), ("vec", "u32"))
    assert s2.getvalue() == b"\x01\x00\x00\x00\x00\x00\x00\x00\x01\x00\x00\x00"


def test_truncated_stream_raises():
    s = MemoryBytesStream(b"\x08\x00\x00\x00\x00\x00\x00\x00ab")  # claims 8, has 2
    with pytest.raises(DMLCError):
        ser.read(s, "str")


def test_fixed_size_stream_overflow():
    buf = bytearray(4)
    s = MemoryFixedSizeStream(buf)
    s.write(b"abcd")
    with pytest.raises(DMLCError):
        s.write(b"e")
    s.seek(0)
    assert s.read(4) == b"abcd"
