"""Device feed: InputSplit partitions → sharded jax.Arrays on the
8-device virtual mesh, with prefetch and correct partition placement."""

import numpy as np
import pytest

from dmlc_tpu.feed import (DeviceFeed, libsvm_feed, pack_rowblock,
                           recordio_feed, recordio_packed_feed)
from dmlc_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh():
    # dp=4, sp=2 -> 8 data partitions, tp/pp/ep trivial
    return build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)


def _write_libsvm(tmp_path, rows=64):
    lines = []
    for i in range(rows):
        lines.append(f"{i % 2} 0:{i}.0 3:{i + 0.5}")
    p = tmp_path / "train.libsvm"
    p.write_text("\n".join(lines) + "\n")
    return str(p)


def test_pack_rowblock_shapes():
    from dmlc_tpu.data.row_block import RowBlockContainer

    c = RowBlockContainer()
    c.push_arrays(
        labels=np.array([1.0, 0.0], np.float32),
        offsets=np.array([0, 2, 5], np.uint64),
        index=np.array([0, 3, 1, 2, 4], np.uint32),
        value=np.array([1, 2, 3, 4, 5], np.float32),
    )
    blk = c.get_block()
    out = pack_rowblock(blk, batch_size=4, max_nnz=3, num_col=5)
    assert out["value"].shape == (4, 3)
    np.testing.assert_allclose(out["label"], [1, 0, 0, 0])
    np.testing.assert_allclose(out["value"][0], [1, 2, 0])
    np.testing.assert_allclose(out["mask"][1], [1, 1, 1])  # truncated row
    np.testing.assert_allclose(out["value"][1], [3, 4, 5])


def test_libsvm_feed_shards_batches(tmp_path, mesh):
    uri = _write_libsvm(tmp_path, rows=64)
    feed = libsvm_feed(uri, mesh, batch_size=2, max_nnz=4)
    batches = list(feed)
    assert batches, "no batches produced"
    for b in batches:
        # global leading dim = 8 parts * 2 per-part rows
        assert b["value"].shape == (16, 4)
        assert b["value"].sharding.is_equivalent_to(feed.sharding, 2)
        # every shard sits on a distinct device
        assert len(b["value"].sharding.device_set) == 8
        assert set(np.unique(np.asarray(b["label"]))) <= {0.0, 1.0}
    assert feed.bytes_fed > 0


def test_libsvm_feed_covers_all_rows(tmp_path, mesh):
    # labels encode row parity; check the feed covers every partition's rows
    uri = _write_libsvm(tmp_path, rows=64)
    feed = libsvm_feed(uri, mesh, batch_size=8, max_nnz=4)
    values = []
    for b in feed:
        v = np.asarray(b["value"])
        m = np.asarray(b["mask"])
        values.append(v[:, 0][m[:, 0] > 0])
    seen = np.concatenate(values)
    # every row i carries feature value i.0 at position 0
    assert set(seen.astype(int)) == set(range(64))


def test_recordio_feed(tmp_path, mesh):
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    path = str(tmp_path / "data.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for i in range(128):
            w.write_record(bytes([i % 256]) * (10 + i % 7))
    feed = recordio_feed(path, mesh, batch_records=4, max_bytes=32)
    total = 0
    for b in feed:
        assert b["data"].shape == (32, 32)
        assert len(b["data"].sharding.device_set) == 8
        total += int(np.sum(np.asarray(b["length"]) > 0))
    assert total == 128


def test_recordio_feed_content_exact(tmp_path, mesh):
    """Vectorized chunk assembly must reproduce every record byte-for-byte,
    including escaped-magic (multi-segment) records and truncation of
    records longer than max_bytes."""
    from dmlc_tpu.io.recordio import KMAGIC, RecordIOWriter
    from dmlc_tpu.io.stream import Stream
    import struct

    rng = np.random.default_rng(7)
    magic = struct.pack("<I", KMAGIC)
    recs = []
    for i in range(97):
        if i % 10 == 3:  # payload containing the magic → multi-segment
            body = b"A" * (4 * (i % 5)) + magic + b"B" * (4 + 4 * (i % 3))
        elif i % 17 == 5:  # longer than max_bytes → truncated
            body = rng.integers(0, 256, 100, dtype=np.uint8).tobytes()
        else:
            body = rng.integers(0, 256, 8 + i % 40, dtype=np.uint8).tobytes()
        recs.append(body)
    path = str(tmp_path / "exact.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for r in recs:
            w.write_record(r)

    max_bytes = 64
    # single-partition mesh view: read back in order on a dp=1 mesh
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh1, batch_records=8, max_bytes=max_bytes)
    got = []
    for b in feed:
        data = np.asarray(b["data"])
        length = np.asarray(b["length"])
        for row, n in zip(data, length):
            if n > 0 or len(got) < len(recs):
                got.append(bytes(row[:n]))
    got = got[: len(recs)]
    assert len(got) == len(recs)
    for i, (g, want) in enumerate(zip(got, recs)):
        assert g == want[:max_bytes], f"record {i} mismatch"


def test_recordio_packed_feed_content_exact(tmp_path):
    """Packed feed: records back-to-back with offsets, no per-record
    padding; every record byte-exact incl. escaped-magic ones."""
    from dmlc_tpu.io.recordio import KMAGIC, RecordIOWriter
    from dmlc_tpu.io.stream import Stream
    import struct

    rng = np.random.default_rng(11)
    magic = struct.pack("<I", KMAGIC)
    recs = []
    for i in range(73):
        if i % 9 == 4:
            body = b"x" * (4 * (i % 4)) + magic + b"y" * (4 + 4 * (i % 3))
        else:
            body = rng.integers(0, 256, 5 + i % 50, dtype=np.uint8).tobytes()
        recs.append(body)
    path = str(tmp_path / "packed.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for r in recs:
            w.write_record(r)

    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feed = recordio_packed_feed(path, mesh1, buf_bytes=512, max_records=16)
    got = []
    for b in feed:
        data = np.asarray(b["data"])
        offsets = np.asarray(b["offsets"])
        n = int(np.asarray(b["count"])[0])
        for i in range(n):
            got.append(bytes(data[offsets[i]:offsets[i + 1]]))
    assert got == recs


def test_recordio_packed_feed_native_fallback_parity(tmp_path, monkeypatch):
    """The native dmlc_pack_spans path and the numpy fallback must emit
    IDENTICAL batch streams — including oversized records (truncated to
    buf_bytes), exact-fit batches, slot exhaustion, and escaped-magic
    records."""
    import struct

    import dmlc_tpu.native as native_mod
    from dmlc_tpu.io.recordio import KMAGIC, RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    rng = np.random.default_rng(23)
    magic = struct.pack("<I", KMAGIC)
    recs = []
    for i in range(60):
        if i == 7 or i == 31:
            body = bytes(rng.integers(0, 256, 700, dtype=np.uint8))  # > buf
        elif i % 11 == 5:
            body = b"a" * 4 + magic + b"b" * 8  # escaped magic
        elif i % 13 == 6:
            body = b""  # empty record
        else:
            body = bytes(rng.integers(0, 256, 1 + i % 90, dtype=np.uint8))
        recs.append(body)
    path = str(tmp_path / "parity.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for r in recs:
            w.write_record(r)

    def run(disable_native):
        if disable_native:
            monkeypatch.setenv("DMLC_TPU_DISABLE_NATIVE", "1")
        else:
            monkeypatch.delenv("DMLC_TPU_DISABLE_NATIVE", raising=False)
        # force the loader to re-decide with the new env
        monkeypatch.setattr(native_mod, "_tried", False)
        monkeypatch.setattr(native_mod, "_lib", None)
        mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
        feed = recordio_packed_feed(path, mesh1, buf_bytes=256,
                                    max_records=8)
        out = []
        for b in feed:
            out.append((np.asarray(b["data"]).tobytes(),
                        np.asarray(b["offsets"]).tobytes(),
                        int(np.asarray(b["count"])[0])))
        return out

    native_out = run(False)
    fallback_out = run(True)
    assert native_out == fallback_out
    # and the stream decodes back to the records (truncated where > buf)
    got = []
    for data_b, offs_b, n in native_out:
        data = np.frombuffer(data_b, np.uint8)
        offsets = np.frombuffer(offs_b, np.int32)
        for i in range(n):
            got.append(bytes(data[offsets[i]:offsets[i + 1]]))
    assert got == [r[:256] for r in recs]


def test_feed_epoch_ends_cleanly(tmp_path, mesh):
    uri = _write_libsvm(tmp_path, rows=16)
    feed = libsvm_feed(uri, mesh, batch_size=2, max_nnz=4)
    n1 = len(list(feed))
    feed2 = libsvm_feed(uri, mesh, batch_size=2, max_nnz=4)
    n2 = len(list(feed2))
    assert n1 == n2 > 0


def test_feed_producer_error_propagates(tmp_path, mesh):
    # malformed libsvm: producer must surface the error, not hang
    p = tmp_path / "bad.libsvm"
    p.write_text("1 abc:def\n" * 20)
    feed = libsvm_feed(str(p), mesh, batch_size=2, max_nnz=4)
    with pytest.raises(Exception):
        list(feed)


def test_feed_multi_epoch_same_feed(tmp_path, mesh):
    """One feed object serves multiple epochs (fresh partition iterators
    per epoch) and yields identical data each time."""
    uri = _write_libsvm(tmp_path, rows=32)
    feed = libsvm_feed(uri, mesh, batch_size=2, max_nnz=4)
    e1 = [{k: np.asarray(v) for k, v in b.items()} for b in feed]
    e2 = [{k: np.asarray(v) for k, v in b.items()} for b in feed]
    assert len(e1) == len(e2) > 0
    for b1, b2 in zip(e1, e2):
        for k in b1:
            np.testing.assert_array_equal(b1[k], b2[k])


def test_feed_close_joins_producer(tmp_path, mesh):
    """close() mid-epoch must leave no live producer thread, even one
    blocked on a full queue."""
    uri = _write_libsvm(tmp_path, rows=64)
    feed = libsvm_feed(uri, mesh, batch_size=2, max_nnz=4, queue_depth=1)
    it = iter(feed)
    next(it)  # start the producer; with depth 1 it will block on put
    feed.close()
    assert feed._thread is None
    # an immediate new epoch must start cleanly after close()
    n = len(list(feed))
    assert n > 0


# ---------------------------------------------------------------------------
# Overlapped pipeline: multi-worker assembly, epoch-tail masking, buffer
# pool reuse/no-aliasing, producer-error propagation
# ---------------------------------------------------------------------------

def _synthetic_feed(mesh, steps_per_part, *, batch=4, workers=3, depth=2):
    """DeviceFeed over synthetic factories: partition p yields
    ``steps_per_part[p]`` batches whose data rows all equal
    1000*p + step (labels likewise), so partition placement, ordering
    and epoch-tail padding are all checkable from the output."""
    from dmlc_tpu.feed import DeviceFeed

    def factory(p):
        def it():
            for s in range(steps_per_part[p]):
                yield {"x": np.full((batch, 3), 1000 * p + s, np.float32),
                       "y": np.full(batch, 1000 * p + s, np.int32)}
        return it

    return DeviceFeed(mesh, [factory(p) for p in range(len(steps_per_part))],
                      queue_depth=depth, num_workers=workers)


def test_multiworker_assembly_preserves_partition_order(mesh):
    steps = [5] * 8
    feed = _synthetic_feed(mesh, steps, workers=3)
    got = list(feed)
    assert len(got) == 5
    for s, b in enumerate(got):
        x = np.asarray(b["x"])
        y = np.asarray(b["y"])
        assert x.shape == (32, 3)
        np.testing.assert_array_equal(b["parts_alive"], np.ones(8, np.float32))
        for p in range(8):
            np.testing.assert_array_equal(
                x[p * 4:(p + 1) * 4], 1000 * p + s)
            np.testing.assert_array_equal(
                y[p * 4:(p + 1) * 4], 1000 * p + s)


def test_epoch_tail_masks_drained_partitions(mesh):
    # partitions drain at different steps; drained slices must read zero
    # and parts_alive must flag exactly the live ones
    steps = [1, 3, 2, 3, 1, 2, 3, 1]
    feed = _synthetic_feed(mesh, steps, workers=4)
    got = list(feed)
    assert len(got) == max(steps)
    for s, b in enumerate(got):
        x = np.asarray(b["x"])
        alive = b["parts_alive"]
        assert alive.dtype == np.float32
        for p in range(8):
            if s < steps[p]:
                assert alive[p] == 1.0
                np.testing.assert_array_equal(
                    x[p * 4:(p + 1) * 4], 1000 * p + s)
            else:
                assert alive[p] == 0.0
                np.testing.assert_array_equal(x[p * 4:(p + 1) * 4], 0.0)


def test_buffer_pool_reuses_without_aliasing(mesh):
    # depth-2 pool over a 9-step epoch: every staging buffer is recycled
    # ~4x; previously-yielded device batches must keep their own data
    steps = [9] * 8
    feed = _synthetic_feed(mesh, steps, workers=2, depth=2)
    got = list(feed)
    assert len(got) == 9
    assert feed._pool.created <= 2  # pooled staging, not per-step allocs
    for s, b in enumerate(got):  # re-check AFTER the buffers were reused
        x = np.asarray(b["x"])
        for p in range(8):
            np.testing.assert_array_equal(
                x[p * 4:(p + 1) * 4], 1000 * p + s)


def test_worker_error_mid_epoch_propagates(mesh):
    from dmlc_tpu.feed import DeviceFeed

    def factory(p):
        def it():
            for s in range(10):
                if p == 5 and s == 3:
                    raise RuntimeError("partition 5 exploded")
                yield {"x": np.full((2, 2), p, np.float32)}
        return it

    feed = DeviceFeed(mesh, [factory(p) for p in range(8)], num_workers=3)
    with pytest.raises(RuntimeError, match="partition 5 exploded"):
        list(feed)
    feed.close()
    assert feed._thread is None  # close() reaped the pipeline threads


def test_feed_worker_and_depth_knobs(tmp_path, mesh, monkeypatch):
    from dmlc_tpu.feed import DeviceFeed

    monkeypatch.setenv("DMLC_FEED_WORKERS", "3")
    monkeypatch.setenv("DMLC_FEED_DEPTH", "4")
    feed = DeviceFeed(mesh, [lambda: iter(())] * 8)
    assert feed._workers == 3 and feed._depth == 4
    # constructor args override the env
    feed = DeviceFeed(mesh, [lambda: iter(())] * 8, queue_depth=1,
                      num_workers=2)
    assert feed._workers == 2 and feed._depth == 1
    # the env must flow through the public factory wrappers too
    feed = libsvm_feed(_write_libsvm(tmp_path), mesh, batch_size=2,
                       max_nnz=4)
    assert feed._workers == 3 and feed._depth == 4


def test_empty_sources_yield_empty_epoch(mesh):
    from dmlc_tpu.feed import DeviceFeed

    feed = DeviceFeed(mesh, [lambda: iter(())] * 8, num_workers=3)
    assert list(feed) == []
    assert list(feed) == []  # and again: multi-epoch restart stays clean


def test_pack_rowblock_out_reuse_matches_fresh():
    from dmlc_tpu.data.row_block import RowBlockContainer

    rng = np.random.default_rng(3)
    out = None
    for trial in range(3):
        nnz = 50 + trial * 17
        c = RowBlockContainer()
        offs = np.sort(rng.integers(0, nnz, 9))
        c.push_arrays(
            labels=rng.random(10).astype(np.float32),
            offsets=np.concatenate([[0], offs, [nnz]]).astype(np.uint64),
            index=rng.integers(0, 30, nnz).astype(np.uint32),
            value=rng.random(nnz).astype(np.float32),
        )
        blk = c.get_block()
        fresh = pack_rowblock(blk, batch_size=12, max_nnz=5, num_col=30)
        out = pack_rowblock(blk, batch_size=12, max_nnz=5, num_col=30,
                            out=out)
        assert out is not fresh
        for k in fresh:
            np.testing.assert_array_equal(out[k], fresh[k])
            assert out[k].dtype == fresh[k].dtype


def test_pack_rowblock_vectorized_matches_reference_loop():
    from dmlc_tpu.data.row_block import RowBlockContainer

    rng = np.random.default_rng(0)
    nrows, nnz = 200, 1000
    offs = np.sort(rng.integers(0, nnz, nrows - 1))
    offsets = np.concatenate([[0], offs, [nnz]]).astype(np.uint64)
    c = RowBlockContainer()
    c.push_arrays(
        labels=rng.random(nrows).astype(np.float32),
        offsets=offsets,
        index=rng.integers(0, 50, nnz).astype(np.uint32),
        value=rng.random(nnz).astype(np.float32),
    )
    blk = c.get_block()
    out = pack_rowblock(blk, batch_size=nrows, max_nnz=8, num_col=50)
    # python reference loop
    want_v = np.zeros((nrows, 8), np.float32)
    want_i = np.zeros((nrows, 8), np.int32)
    want_m = np.zeros((nrows, 8), np.float32)
    for i in range(nrows):
        lo, hi = int(offsets[i]), int(offsets[i + 1])
        k = min(hi - lo, 8)
        want_v[i, :k] = np.asarray(blk.value[lo:lo + k])
        want_i[i, :k] = np.minimum(np.asarray(blk.index[lo:lo + k]), 49)
        want_m[i, :k] = 1.0
    np.testing.assert_array_equal(out["value"], want_v)
    np.testing.assert_array_equal(out["index"], want_i)
    np.testing.assert_array_equal(out["mask"], want_m)


# ---------------------------------------------------------------------------
# Elastic feed resize (ISSUE 7): shrink mid-epoch, exactly-once coverage
# ---------------------------------------------------------------------------

def _make_indexed_rec(tmp_path, n=60, body_bytes=24, name="el.rec"):
    """RecordIO file whose record i's first 4 bytes encode i."""
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    rng = np.random.default_rng(11)
    path = str(tmp_path / name)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for i in range(n):
            body = (np.int32(i).tobytes()
                    + rng.integers(0, 256, body_bytes - 4,
                                   dtype=np.uint8).tobytes())
            w.write_record(body)
    return path


def _drain_ids(feed, max_batches=None):
    """Record ids seen in one full (or truncated) epoch of the feed."""
    ids = []
    for k, b in enumerate(feed):
        data = np.asarray(b["data"])
        length = np.asarray(b["length"])
        for row, ln in zip(data, length):
            if ln > 0:
                ids.append(int(np.frombuffer(row[:4].tobytes(),
                                             np.int32)[0]))
        if max_batches is not None and k + 1 >= max_batches:
            feed.close()
            break
    return ids


def test_feed_world_partitions_cover_exactly(tmp_path):
    """world=(rank, W): each rank's feed serves its byte-range part;
    the union over ranks is every record exactly once."""
    path = _make_indexed_rec(tmp_path)
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    seen = []
    for rank in range(3):
        feed = recordio_feed(path, mesh1, batch_records=4, max_bytes=32,
                             world=(rank, 3))
        seen.extend(_drain_ids(feed))
    assert sorted(seen) == list(range(60))
    assert len(seen) == len(set(seen))


def test_feed_resize_shrink_mid_epoch_exactly_once(tmp_path):
    """Shrink 3 -> 2 mid-epoch: the abandoned partial epoch is
    superseded; the FIRST full epoch after resize() covers every record
    of the new partition exactly once — no loss, no dup across the
    generation boundary."""
    path = _make_indexed_rec(tmp_path)
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feeds = [recordio_feed(path, mesh1, batch_records=4, max_bytes=32,
                           world=(r, 3)) for r in range(3)]
    # rank 0 and 1 consume part of an epoch; rank 2 is then "preempted"
    _drain_ids(feeds[0], max_batches=2)
    _drain_ids(feeds[1], max_batches=1)
    feeds[2].close()
    # survivors resize in place to the dense 2-rank world
    feeds[0].resize((0, 2))
    feeds[1].resize((1, 2))
    assert feeds[0].world == (0, 2) and feeds[1].world == (1, 2)
    post = _drain_ids(feeds[0]) + _drain_ids(feeds[1])
    assert sorted(post) == list(range(60))
    assert len(post) == len(set(post))
    # and the feeds stay multi-epoch after a resize
    again = _drain_ids(feeds[0]) + _drain_ids(feeds[1])
    assert sorted(again) == sorted(post)


def test_feed_resize_grow_and_determinism(tmp_path):
    """Grow 2 -> 3 and re-shrink: every world's epoch coverage equals
    the deterministic byte-range contract (two independently built
    feeds of the same (rank, W) see identical record streams)."""
    path = _make_indexed_rec(tmp_path)
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh1, batch_records=4, max_bytes=32,
                         world=(0, 2))
    first = _drain_ids(feed)
    feed.resize((1, 3))
    grown = _drain_ids(feed)
    fresh = recordio_feed(path, mesh1, batch_records=4, max_bytes=32,
                          world=(1, 3))
    assert grown == _drain_ids(fresh)
    feed.resize((0, 2))
    assert _drain_ids(feed) == first


def test_feed_resize_requires_builder(tmp_path):
    """Feeds built from explicit part_sources cannot resize."""
    from dmlc_tpu.base import DMLCError

    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)

    def factory():
        def it():
            yield {"x": np.zeros(4, np.float32)}
        return it()

    feed = DeviceFeed(mesh1, [factory])
    with pytest.raises(DMLCError, match="source_builder"):
        feed.resize((0, 1))
    feed.close()
