"""WebHDFS + Azure Blob backends against local in-process emulators.

Same hermetic strategy as tests/test_gcs_http.py: a stdlib HTTP server
implements the protocol slice each backend speaks — including the
namenode 307 datanode-redirect dance for WebHDFS and Shared Key
signature verification for Azure — and the SAME Stream/InputSplit code
paths run over hdfs:// and azure:// URIs.
"""

import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_tpu.io import input_split
from dmlc_tpu.io.filesys import FileSystem
from dmlc_tpu.io.stream import Stream
from dmlc_tpu.io.uri import URI


def _drop_cached_instances(*protocols):
    for key in [k for k in FileSystem._instances
                if any(k.startswith(p) for p in protocols)]:
        del FileSystem._instances[key]


# ---------------------------------------------------------------------------
# WebHDFS
# ---------------------------------------------------------------------------

class _FakeNameNode(BaseHTTPRequestHandler):
    """Namenode + datanode in one server: data-bearing CREATE/APPEND/OPEN
    arrive first WITHOUT a /dn/ prefix and get a 307 redirect, exactly
    like a real namenode brokering to a datanode."""

    store = {}  # "/abs/path" -> bytearray
    fail_next_append = [False]  # one-shot: 500 the next APPEND payload

    def log_message(self, *a):
        pass

    def _reply(self, code, body=b"", headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _redirect_to_dn(self):
        host = self.headers.get("Host")
        self._reply(307, headers=[("Location",
                                   f"http://{host}/dn{self.path}")])

    def _parse(self):
        u = urllib.parse.urlparse(self.path)
        q = {k: v[0] for k, v in urllib.parse.parse_qs(u.query).items()}
        path = u.path
        on_dn = path.startswith("/dn/")
        if on_dn:
            path = path[len("/dn"):]
        assert path.startswith("/webhdfs/v1")
        return path[len("/webhdfs/v1"):] or "/", q, on_dn

    def _status(self, path, data=None):
        import json

        name = path.rstrip("/").rsplit("/", 1)[-1]
        if data is None:  # directory
            return {"pathSuffix": name, "type": "DIRECTORY", "length": 0}
        return {"pathSuffix": name, "type": "FILE", "length": len(data)}

    def _children(self, path):
        prefix = path.rstrip("/") + "/"
        kids = {}
        for p, data in self.store.items():
            if not p.startswith(prefix):
                continue
            rest = p[len(prefix):]
            if "/" in rest:
                kids.setdefault(rest.split("/")[0], None)
            else:
                kids[rest] = data
        return kids

    def do_GET(self):
        import json

        path, q, on_dn = self._parse()
        op = q.get("op")
        if op == "GETFILESTATUS":
            if path in self.store:
                st = self._status(path, self.store[path])
            elif self._children(path) or path == "/":
                st = self._status(path)
            else:
                self._reply(404)
                return
            self._reply(200, json.dumps({"FileStatus": st}).encode())
        elif op == "LISTSTATUS":
            if path in self.store:
                sts = [dict(self._status(path, self.store[path]),
                            pathSuffix="")]
            else:
                kids = self._children(path)
                if not kids and path != "/":
                    self._reply(404)
                    return
                sts = [self._status(f"{path.rstrip('/')}/{k}", v)
                       for k, v in sorted(kids.items())]
            body = json.dumps(
                {"FileStatuses": {"FileStatus": sts}}).encode()
            self._reply(200, body)
        elif op == "OPEN":
            if not on_dn:
                self._redirect_to_dn()
                return
            data = self.store.get(path)
            if data is None:
                self._reply(404)
                return
            off = int(q.get("offset", 0))
            ln = int(q.get("length", len(data)))
            self._reply(200, bytes(data[off: off + ln]))
        else:
            self._reply(400)

    def do_PUT(self):
        import json

        path, q, on_dn = self._parse()
        op = q.get("op")
        if op == "RENAME":
            # namenode metadata op: no datanode redirect; refuses an
            # existing destination, exactly like real HDFS
            dst = q["destination"]
            if path not in self.store or dst in self.store:
                self._reply(200, json.dumps({"boolean": False}).encode())
                return
            self.store[dst] = self.store.pop(path)
            self._reply(200, json.dumps({"boolean": True}).encode())
            return
        if op != "CREATE":
            self._reply(400)
            return
        if not on_dn:
            self._redirect_to_dn()
            return
        n = int(self.headers.get("Content-Length", 0))
        self.store[path] = bytearray(self.rfile.read(n))
        self._reply(201)

    def do_DELETE(self):
        import json

        path, q, _on_dn = self._parse()
        if q.get("op") != "DELETE":
            self._reply(400)
            return
        existed = self.store.pop(path, None) is not None
        self._reply(200, json.dumps({"boolean": existed}).encode())

    def do_POST(self):
        path, q, on_dn = self._parse()
        if q.get("op") != "APPEND":
            self._reply(400)
            return
        if not on_dn:
            self._redirect_to_dn()
            return
        if self.fail_next_append[0]:
            self.fail_next_append[0] = False
            n = int(self.headers.get("Content-Length", 0))
            self.rfile.read(n)
            self._reply(500)
            return
        if path not in self.store:
            self._reply(404)
            return
        n = int(self.headers.get("Content-Length", 0))
        self.store[path] += self.rfile.read(n)
        self._reply(200)


@pytest.fixture(scope="module")
def hdfs_server():
    _FakeNameNode.store.clear()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeNameNode)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    old = os.environ.get("DMLC_WEBHDFS_ENDPOINT")
    os.environ["DMLC_WEBHDFS_ENDPOINT"] = f"127.0.0.1:{srv.server_port}"
    _drop_cached_instances("hdfs://")
    yield srv
    if old is None:
        os.environ.pop("DMLC_WEBHDFS_ENDPOINT", None)
    else:
        os.environ["DMLC_WEBHDFS_ENDPOINT"] = old
    _drop_cached_instances("hdfs://")
    srv.shutdown()


def test_hdfs_write_read_roundtrip(hdfs_server):
    import numpy as np

    payload = bytes(np.random.default_rng(1).integers(
        0, 256, 200_000, dtype=np.uint8))
    os.environ["DMLC_HDFS_WRITE_BUFFER_MB"] = "1"  # CREATE + APPENDs
    try:
        with Stream.create("hdfs://nn/data/blob.bin", "w") as s:
            for lo in range(0, len(payload), 60_000):
                s.write(payload[lo: lo + 60_000])
    finally:
        os.environ.pop("DMLC_HDFS_WRITE_BUFFER_MB")
    strm = Stream.create_for_read("hdfs://nn/data/blob.bin")
    assert strm.read(len(payload) + 1) == payload
    strm.seek(123_456)
    assert strm.read(16) == payload[123_456:123_472]


def test_hdfs_write_is_invisible_until_close(hdfs_server):
    """The temp+RENAME dance: readers never see a torn partial at the
    destination path; content appears only (and fully) at close."""
    fs = FileSystem.get_instance(URI("hdfs://nn/torn"))
    os.environ["DMLC_HDFS_WRITE_BUFFER_MB"] = "1"  # read at construction
    try:
        s = Stream.create("hdfs://nn/torn/out.bin", "w")
        s.write(b"x" * (2 << 20))  # forces a CREATE flush mid-write
        with pytest.raises(FileNotFoundError):
            fs.get_path_info(URI("hdfs://nn/torn/out.bin"))
        s.close()
    finally:
        os.environ.pop("DMLC_HDFS_WRITE_BUFFER_MB")
    assert fs.get_path_info(URI("hdfs://nn/torn/out.bin")).size == 2 << 20
    # no temp litter after a clean close
    names = [e.path.name for e in
             fs.list_directory(URI("hdfs://nn/torn"))]
    assert names == ["/torn/out.bin"]


def test_hdfs_failed_flush_poisons_stream(hdfs_server):
    """A lost chunk must never let close() rename a truncated temp over
    the destination; the temp is cleaned up and the original error
    stands (close() raises nothing new)."""
    from dmlc_tpu.base import DMLCError

    os.environ["DMLC_HDFS_WRITE_BUFFER_MB"] = "1"
    try:
        s = Stream.create("hdfs://nn/poison/f.bin", "w")
        s.write(b"a" * (1 << 20))  # CREATE flush lands
        _FakeNameNode.fail_next_append[0] = True
        with pytest.raises(DMLCError):
            s.write(b"b" * (1 << 20))
        s.close()  # must not publish, must not raise
    finally:
        os.environ.pop("DMLC_HDFS_WRITE_BUFFER_MB")
    fs = FileSystem.get_instance(URI("hdfs://nn/poison"))
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("hdfs://nn/poison/f.bin"))
    assert not [p for p in _FakeNameNode.store if ".tmp." in p], \
        "temp litter after failed write"


def test_azure_failed_block_poisons_stream(azure_server):
    from dmlc_tpu.base import DMLCError

    os.environ["DMLC_AZURE_BLOCK_MB"] = "1"
    os.environ["DMLC_AZURE_RETRIES"] = "1"
    try:
        s = Stream.create("azure://cont/poison/b.bin", "w")
        s.write(b"a" * (1 << 20))  # block 0 stages fine
        _FakeAzure.fail_next_block[0] = True
        with pytest.raises(DMLCError):
            s.write(b"b" * (1 << 20))
        s.close()  # must not commit a block list with a hole
    finally:
        os.environ.pop("DMLC_AZURE_BLOCK_MB")
        os.environ.pop("DMLC_AZURE_RETRIES")
    fs = FileSystem.get_instance(URI("azure://cont/poison"))
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("azure://cont/poison/b.bin"))
    # the abandoned staged block is uncommitted server state that real
    # Azure GCs after 7 days; drop it so later tests see a clean slate
    _FakeAzure.blocks.clear()


def test_hdfs_overwrite_existing_destination(hdfs_server):
    for payload in (b"first version", b"second, longer version!"):
        with Stream.create("hdfs://nn/ow/f.bin", "w") as s:
            s.write(payload)
        assert Stream.create_for_read(
            "hdfs://nn/ow/f.bin").read(100) == payload


def test_hdfs_stat_and_list(hdfs_server):
    with Stream.create("hdfs://nn/dir/a.txt", "w") as s:
        s.write(b"hello")
    with Stream.create("hdfs://nn/dir/sub/b.txt", "w") as s:
        s.write(b"world!")
    fs = FileSystem.get_instance(URI("hdfs://nn/dir"))
    assert fs.get_path_info(URI("hdfs://nn/dir/a.txt")).size == 5
    assert fs.get_path_info(URI("hdfs://nn/dir")).type == "directory"
    names = {e.path.name: e.type for e in fs.list_directory(URI("hdfs://nn/dir"))}
    assert names.get("/dir/a.txt") == "file"
    assert names.get("/dir/sub") == "directory"
    rec = fs.list_directory_recursive(URI("hdfs://nn/dir"))
    assert sum(e.size for e in rec) == 11
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("hdfs://nn/absent"))


def test_inputsplit_over_hdfs(hdfs_server):
    lines = [f"{i} row-{i}" for i in range(150)]
    with Stream.create("hdfs://nn/ds/part.txt", "w") as s:
        s.write(("\n".join(lines) + "\n").encode())
    got = []
    for part in range(3):
        sp = input_split.create("hdfs://nn/ds/part.txt", part, 3, "text")
        got += [bytes(r).decode() for r in sp]
        sp.close()
    assert sorted(got) == sorted(lines)


def test_inputsplit_directory_skips_hidden_files(hdfs_server):
    """An in-flight writer temp (or _SUCCESS marker) inside a sharded
    directory must never be sharded as data — the torn-read hazard the
    dot-prefixed temp convention exists to prevent."""
    lines = [f"r{i}" for i in range(40)]
    with Stream.create("hdfs://nn/hid/part-0.txt", "w") as s:
        s.write(("\n".join(lines) + "\n").encode())
    # hidden siblings, directly into the emulator store
    _FakeNameNode.store["/hid/.part-1.txt.tmp.999.1"] = \
        bytearray(b"torn partial\n")
    _FakeNameNode.store["/hid/_SUCCESS"] = bytearray(b"marker\n")
    sp = input_split.create("hdfs://nn/hid", 0, 1, "text")
    got = [bytes(r).decode() for r in sp]
    sp.close()
    assert sorted(got) == sorted(lines)


# ---------------------------------------------------------------------------
# Azure Blob
# ---------------------------------------------------------------------------

class _FakeAzure(BaseHTTPRequestHandler):
    store = {}   # (container, blob) -> bytes
    blocks = {}  # (container, blob) -> {blockid: bytes}, uncommitted
    require_auth = True
    fail_next_block = [False]  # one-shot: 500 the next Put Block

    def log_message(self, *a):
        pass

    def _verify_auth(self, body_len=0):
        """Countersign with the client's own x-ms headers; reject a
        missing or mismatched Shared Key signature."""
        from dmlc_tpu.io.azure_filesys import sign_request

        got = self.headers.get("Authorization")
        if not self.require_auth:
            return True
        host = self.headers.get("Host")
        url = f"http://{host}{self.path}"
        hdrs = {k: v for k, v in self.headers.items()
                if k.lower().startswith("x-ms-")
                or k.lower() in ("range", "content-type")}
        want = sign_request(self.command, url, hdrs,
                            content_length=body_len).get("Authorization")
        if got is None or got != want:
            self.send_error(403, "signature mismatch")
            return False
        return True

    def _reply(self, code, body=b"", headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _key(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        container = parts[0]
        blob = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        return container, blob, {k: v[0] for k, v in
                                 urllib.parse.parse_qs(u.query).items()}

    def do_HEAD(self):
        if not self._verify_auth():
            return
        container, blob, _ = self._key()
        data = self.store.get((container, blob))
        if data is None:
            self._reply(404)
            return
        # HEAD: declare the blob's true length, send no body
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if not self._verify_auth():
            return
        container, blob, q = self._key()
        if q.get("comp") == "list":
            prefix = q.get("prefix", "")
            delim = q.get("delimiter")
            blobs, prefixes = [], set()
            for (c, name), data in sorted(self.store.items()):
                if c != container or not name.startswith(prefix):
                    continue
                rest = name[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim)[0] + delim)
                else:
                    blobs.append(
                        f"<Blob><Name>{name}</Name><Properties>"
                        f"<Content-Length>{len(data)}</Content-Length>"
                        f"</Properties></Blob>")
            pres = "".join(f"<BlobPrefix><Name>{p}</Name></BlobPrefix>"
                           for p in sorted(prefixes))
            xml = (f"<?xml version='1.0'?><EnumerationResults><Blobs>"
                   f"{''.join(blobs)}{pres}</Blobs>"
                   f"<NextMarker/></EnumerationResults>")
            self._reply(200, xml.encode())
            return
        data = self.store.get((container, blob))
        if data is None:
            self._reply(404)
            return
        rng = self.headers.get("Range")
        if rng:
            lo, hi = rng.split("=")[1].split("-")
            self._reply(206, data[int(lo): int(hi) + 1])
        else:
            self._reply(200, data)

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        if not self._verify_auth(body_len=n):
            self.rfile.read(n)
            return
        container, blob, q = self._key()
        if q.get("comp") == "block":
            if self.fail_next_block[0]:
                self.fail_next_block[0] = False
                self._reply(500)
                return
            # staged, invisible until a blocklist commit
            bid = q["blockid"]
            self.blocks.setdefault((container, blob), {})[bid] = \
                self.rfile.read(n)
            self._reply(201)
            return
        if q.get("comp") == "blocklist":
            import xml.etree.ElementTree as ET

            staged = self.blocks.pop((container, blob), {})
            root = ET.fromstring(self.rfile.read(n))
            try:
                body = b"".join(staged[el.text] for el in root)
            except KeyError:
                self._reply(400)
                return
            self.store[(container, blob)] = body
            self._reply(201)
            return
        if self.headers.get("x-ms-blob-type") != "BlockBlob":
            self._reply(400)
            return
        self.store[(container, blob)] = self.rfile.read(n)
        self._reply(201)


@pytest.fixture(scope="module")
def azure_server():
    _FakeAzure.store.clear()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeAzure)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    saved = {k: os.environ.get(k) for k in
             ("DMLC_AZURE_ENDPOINT", "AZURE_STORAGE_ACCOUNT",
              "AZURE_STORAGE_ACCESS_KEY", "AZURE_STORAGE_SAS_TOKEN")}
    os.environ["DMLC_AZURE_ENDPOINT"] = f"127.0.0.1:{srv.server_port}"
    os.environ["AZURE_STORAGE_ACCOUNT"] = "testacct"
    os.environ["AZURE_STORAGE_ACCESS_KEY"] = \
        "c2VjcmV0LWtleS1mb3ItdGVzdHM="  # base64("secret-key-for-tests")
    os.environ.pop("AZURE_STORAGE_SAS_TOKEN", None)
    _drop_cached_instances("azure://")
    yield srv
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    _drop_cached_instances("azure://")
    srv.shutdown()


def test_azure_write_read_roundtrip(azure_server):
    import numpy as np

    payload = bytes(np.random.default_rng(2).integers(
        0, 256, 150_000, dtype=np.uint8))
    with Stream.create("azure://cont/dir/blob.bin", "w") as s:
        s.write(payload[:70_000])
        s.write(payload[70_000:])
    strm = Stream.create_for_read("azure://cont/dir/blob.bin")
    assert strm.read(len(payload) + 1) == payload
    strm.seek(99_000)
    assert strm.read(32) == payload[99_000:99_032]


def test_azure_block_upload_large_object(azure_server):
    """Above one block the writer switches to staged Put Block + Put
    Block List: memory stays bounded, the object is invisible until the
    commit, and the committed bytes are exact."""
    import numpy as np

    payload = bytes(np.random.default_rng(3).integers(
        0, 256, 2_500_000, dtype=np.uint8))
    os.environ["DMLC_AZURE_BLOCK_MB"] = "1"
    try:
        s = Stream.create("azure://cont/big/blob.bin", "w")
        for lo in range(0, len(payload), 700_000):
            s.write(payload[lo: lo + 700_000])
        # blocks are staged but uncommitted: blob must not exist yet
        fs = FileSystem.get_instance(URI("azure://cont/big"))
        with pytest.raises(FileNotFoundError):
            fs.get_path_info(URI("azure://cont/big/blob.bin"))
        s.close()
    finally:
        os.environ.pop("DMLC_AZURE_BLOCK_MB")
    strm = Stream.create_for_read("azure://cont/big/blob.bin")
    assert strm.read(len(payload) + 1) == payload
    assert not _FakeAzure.blocks  # commit consumed the staged blocks


def test_azure_signature_rejected_without_key(azure_server):
    from dmlc_tpu.base import DMLCError

    with Stream.create("azure://cont/x.bin", "w") as s:
        s.write(b"data")
    key = os.environ.pop("AZURE_STORAGE_ACCESS_KEY")
    try:
        with pytest.raises(DMLCError, match="403"):
            Stream.create_for_read("azure://cont/x.bin").read(4)
    finally:
        os.environ["AZURE_STORAGE_ACCESS_KEY"] = key


def test_azure_list_directory(azure_server):
    for name, data in [("d/a.bin", b"xx"), ("d/b.bin", b"yyy"),
                       ("d/sub/c.bin", b"z")]:
        with Stream.create(f"azure://cont/{name}", "w") as s:
            s.write(data)
    fs = FileSystem.get_instance(URI("azure://cont/d"))
    entries = fs.list_directory(URI("azure://cont/d"))
    names = {e.path.name: (e.type, e.size) for e in entries}
    assert names.get("/d/a.bin") == ("file", 2)
    assert names.get("/d/b.bin") == ("file", 3)
    assert names.get("/d/sub") == ("directory", 0)
    rec = fs.list_directory_recursive(URI("azure://cont/d"))
    assert sum(e.size for e in rec) == 6
    # stat: blob, directory-as-prefix, and missing
    assert fs.get_path_info(URI("azure://cont/d/a.bin")).size == 2
    assert fs.get_path_info(URI("azure://cont/d")).type == "directory"
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("azure://cont/nope"))


def test_inputsplit_over_azure(azure_server):
    lines = [f"az-{i}" for i in range(120)]
    with Stream.create("azure://cont/ds/t.txt", "w") as s:
        s.write(("\n".join(lines) + "\n").encode())
    got = []
    for part in range(2):
        sp = input_split.create("azure://cont/ds/t.txt", part, 2, "text")
        got += [bytes(r).decode() for r in sp]
        sp.close()
    assert sorted(got) == sorted(lines)
