"""InputSplit partition/determinism tests (mirror reference
test/split_repeat_read_test.cc and split_read_test.cc, plus the coverage the
reference lacks: exhaustive part/num_parts sweeps on text and recordio)."""

import os
import random

import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.io import input_split as isplit
from dmlc_tpu.io.input_split_shuffle import create_shuffled
from dmlc_tpu.io.recordio import RecordIOWriter
from dmlc_tpu.io.stream import MemoryBytesStream


# ---------- fixtures ----------------------------------------------------

def make_text_files(tmp_path, n_files=3, lines_per_file=57, seed=0):
    rng = random.Random(seed)
    all_lines = []
    paths = []
    for i in range(n_files):
        p = tmp_path / f"data{i}.txt"
        lines = [
            f"file{i}-line{j}-" + "x" * rng.randint(0, 40) for j in range(lines_per_file)
        ]
        p.write_bytes(("\n".join(lines) + "\n").encode())
        all_lines.extend(lines)
        paths.append(str(p))
    return ";".join(paths), all_lines


def make_recordio_file(tmp_path, n=211, seed=1, name="data.rec"):
    rng = random.Random(seed)
    recs = []
    strm = MemoryBytesStream()
    w = RecordIOWriter(strm)
    import struct

    magic = struct.pack("<I", 0xCED7230A)
    for i in range(n):
        body = bytes(rng.getrandbits(8) for _ in range(rng.randint(0, 120)))
        if rng.random() < 0.3 and len(body) >= 8:
            pos = ((rng.randrange(0, len(body) - 4) >> 2) << 2)
            body = body[:pos] + magic + body[pos + 4 :]
        recs.append(body)
        w.write_record(body)
    p = tmp_path / name
    p.write_bytes(strm.getvalue())
    return str(p), recs


def read_all(split):
    return [bytes(r) for r in split]


# ---------- text splits -------------------------------------------------

def test_text_single_part_reads_all_lines(tmp_path):
    uri, lines = make_text_files(tmp_path)
    sp = isplit.create(uri, 0, 1, "text", threaded=False)
    assert [r.decode() for r in read_all(sp)] == lines


@pytest.mark.parametrize("num_parts", [2, 3, 4, 7, 16])
def test_text_partitions_cover_exactly(tmp_path, num_parts):
    """No loss, no dup, order preserved within parts (split_repeat_read_test)."""
    uri, lines = make_text_files(tmp_path)
    got = []
    for part in range(num_parts):
        sp = isplit.create(uri, part, num_parts, "text", threaded=False)
        got.extend(r.decode() for r in read_all(sp))
        sp.close()
    assert got == lines, f"partition mismatch at num_parts={num_parts}"


def test_text_repeat_read_deterministic(tmp_path):
    """before_first + re-read must be byte-identical (split_repeat_read_test.cc:8-57)."""
    uri, _ = make_text_files(tmp_path)
    sp = isplit.create(uri, 1, 3, "text", threaded=False)
    first = read_all(sp)
    for _ in range(3):
        sp.before_first()
        assert read_all(sp) == first


def test_text_tiny_chunks_force_overflow_carry(tmp_path):
    """Small chunk size exercises the overflow path heavily."""
    uri, lines = make_text_files(tmp_path, n_files=1, lines_per_file=100)
    sp = isplit.create(uri, 0, 1, "text", threaded=False)
    sp.hint_chunk_size(64)
    assert [r.decode() for r in read_all(sp)] == lines


def test_text_chunk_smaller_than_record_grows(tmp_path):
    p = tmp_path / "long.txt"
    long_line = "a" * 10000
    p.write_bytes((long_line + "\nshort\n").encode())
    sp = isplit.create(str(p), 0, 1, "text", threaded=False)
    sp.hint_chunk_size(16)  # much smaller than the record
    out = [r.decode() for r in read_all(sp)]
    assert out == [long_line, "short"]


def test_text_crlf_and_blank_lines(tmp_path):
    p = tmp_path / "crlf.txt"
    p.write_bytes(b"a\r\nb\n\nc\r")
    sp = isplit.create(str(p), 0, 1, "text", threaded=False)
    # consecutive EOL chars are skipped as one separator (line_split.cc:41-44)
    assert [bytes(r) for r in sp] == [b"a", b"b", b"c"]


def test_directory_uri(tmp_path):
    d = tmp_path / "dir"
    d.mkdir()
    (d / "a.txt").write_bytes(b"1\n2\n")
    (d / "b.txt").write_bytes(b"3\n")
    sp = isplit.create(str(d), 0, 1, "text", threaded=False)
    assert sorted(bytes(r).decode() for r in sp) == ["1", "2", "3"]


def test_directory_hidden_file_skip_is_logged(tmp_path, caplog):
    """The '.'/'_' hidden-file filter (a documented deviation from the
    reference, which reads those entries) must announce what it dropped
    — silent data loss on migrated datasets is the failure mode."""
    import logging

    d = tmp_path / "dir"
    d.mkdir()
    (d / "a.txt").write_bytes(b"1\n")
    (d / "_SUCCESS").write_bytes(b"marker\n")
    (d / ".part.tmp.123").write_bytes(b"partial\n")
    caplog.set_level(logging.INFO, logger="dmlc_tpu.io")
    sp = isplit.create(str(d), 0, 1, "text", threaded=False)
    assert [bytes(r).decode() for r in sp] == ["1"]
    msgs = [r.message for r in caplog.records if "hidden" in r.message]
    assert msgs, "hidden-file skip was not logged"
    assert "_SUCCESS" in msgs[0] and ".part.tmp.123" in msgs[0]
    assert "2" in msgs[0]  # the count


def test_regex_uri(tmp_path):
    d = tmp_path / "rx"
    d.mkdir()
    (d / "part-001").write_bytes(b"a\n")
    (d / "part-002").write_bytes(b"b\n")
    (d / "other").write_bytes(b"c\n")
    sp = isplit.create(str(d / "part-.*"), 0, 1, "text", threaded=False)
    assert sorted(bytes(r).decode() for r in sp) == ["a", "b"]


def test_missing_uri_raises(tmp_path):
    with pytest.raises(DMLCError, match="Cannot find"):
        isplit.create(str(tmp_path / "nope" / "*.txt"), 0, 1, "text", threaded=False)


def test_get_total_size(tmp_path):
    uri, _ = make_text_files(tmp_path)
    sp = isplit.create(uri, 0, 1, "text", threaded=False)
    total = sum(
        os.path.getsize(u) for u in uri.split(";")
    )
    assert sp.get_total_size() == total


# ---------- recordio splits --------------------------------------------

def test_recordio_single_part(tmp_path):
    path, recs = make_recordio_file(tmp_path)
    sp = isplit.create(path, 0, 1, "recordio", threaded=False)
    assert read_all(sp) == recs


@pytest.mark.parametrize("num_parts", [2, 3, 5, 8])
def test_recordio_partitions_cover_exactly(tmp_path, num_parts):
    path, recs = make_recordio_file(tmp_path)
    got = []
    for part in range(num_parts):
        sp = isplit.create(path, part, num_parts, "recordio", threaded=False)
        got.extend(read_all(sp))
        sp.close()
    assert got == recs


def test_recordio_multi_file(tmp_path):
    p1, r1 = make_recordio_file(tmp_path, n=83, seed=5, name="a.rec")
    p2, r2 = make_recordio_file(tmp_path, n=91, seed=6, name="b.rec")
    got = []
    for part in range(4):
        sp = isplit.create(f"{p1};{p2}", part, 4, "recordio", threaded=False)
        got.extend(read_all(sp))
    assert got == r1 + r2


def test_recordio_small_chunks(tmp_path):
    path, recs = make_recordio_file(tmp_path, n=60)
    sp = isplit.create(path, 0, 1, "recordio", threaded=False)
    sp.hint_chunk_size(128)
    assert read_all(sp) == recs


# ---------- wrappers ----------------------------------------------------

def test_threaded_wrapper_matches_plain(tmp_path):
    uri, lines = make_text_files(tmp_path)
    sp = isplit.create(uri, 0, 1, "text", threaded=True)
    assert [r.decode() for r in read_all(sp)] == lines
    sp.before_first()
    assert [r.decode() for r in read_all(sp)] == lines
    sp.close()


def test_threaded_reset_partition(tmp_path):
    uri, lines = make_text_files(tmp_path)
    sp = isplit.create(uri, 0, 2, "text", threaded=True)
    part0 = read_all(sp)
    sp.reset_partition(1, 2)
    part1 = read_all(sp)
    assert [r.decode() for r in part0 + part1] == lines
    sp.close()


def test_cached_wrapper(tmp_path):
    uri, lines = make_text_files(tmp_path, n_files=1)
    cache = str(tmp_path / "cache.bin")
    sp = isplit.create(f"{uri}#{cache}", 0, 1, "text")
    first = [r.decode() for r in read_all(sp)]
    assert first == lines
    sp.before_first()
    assert os.path.exists(cache + ".split1.part0") or os.path.exists(cache)
    second = [r.decode() for r in read_all(sp)]
    assert second == lines
    with pytest.raises(DMLCError):
        sp.reset_partition(0, 2)
    sp.close()


def test_cached_wrapper_replay_from_existing_cache(tmp_path):
    """Regression: replay path must open the cache before the producer runs."""
    uri, lines = make_text_files(tmp_path, n_files=1)
    cache = str(tmp_path / "cache2.bin")
    sp = isplit.create(f"{uri}#{cache}", 0, 1, "text")
    assert [bytes(r).decode() for r in read_all(sp)] == lines  # single epoch only
    sp.close()
    # cache must exist after a single-epoch run (finalized at EOF)
    assert os.path.exists(cache)
    sp2 = isplit.create(f"{uri}#{cache}", 0, 1, "text")
    assert [bytes(r).decode() for r in read_all(sp2)] == lines
    sp2.close()


def test_single_file_split_chunks_cover_whole_file(tmp_path):
    """Regression: next_chunk must not drop bytes past the first 4MiB."""
    p = tmp_path / "big.txt"
    blob = (b"z" * 255 + b"\n") * ((5 << 20) // 256)  # ~5 MiB
    p.write_bytes(blob)
    sp = isplit.SingleFileSplit(str(p))
    total = 0
    while True:
        c = sp.next_chunk()
        if c is None:
            break
        total += len(c)
    assert total == len(blob)


def test_indexed_out_of_range_rank_is_empty(tmp_path):
    """Regression: an out-of-range rank must serve zero records."""
    path, idx, recs = make_indexed_recordio(tmp_path, n=4)
    sp = isplit.create(path, 0, 1, "indexed_recordio", index_uri=idx)
    assert len(read_all(sp)) == 4
    sp.reset_partition(5, 6)  # nstep=1, rank 5 >= 4 records
    assert read_all(sp) == []


def test_recordio_tiny_hint_does_not_crash(tmp_path):
    path, recs = make_recordio_file(tmp_path, n=20)
    sp = isplit.create(path, 0, 1, "recordio", threaded=False)
    sp.hint_chunk_size(4)  # clamped to the safe floor
    assert read_all(sp) == recs


def test_shuffle_split_covers_all_and_reshuffles(tmp_path):
    uri, lines = make_text_files(tmp_path, n_files=2, lines_per_file=40)
    sp = create_shuffled(uri, 0, 1, "text", num_shuffle_parts=4, shuffle_seed=3)
    epoch1 = [r.decode() for r in read_all(sp)]
    assert sorted(epoch1) == sorted(lines)
    sp.before_first()
    epoch2 = [r.decode() for r in read_all(sp)]
    assert sorted(epoch2) == sorted(lines)
    # with 4 sub-splits the visit order should differ between epochs (w.h.p.)
    assert epoch1 != lines or epoch2 != lines or epoch1 != epoch2


# ---------- indexed recordio -------------------------------------------

def make_indexed_recordio(tmp_path, n=50, seed=9):
    rng = random.Random(seed)
    strm = MemoryBytesStream()
    w = RecordIOWriter(strm)
    offsets = []
    recs = []
    for i in range(n):
        offsets.append(len(strm.getvalue()))
        body = f"record-{i}-".encode() + bytes(
            rng.getrandbits(8) for _ in range(rng.randint(0, 50))
        )
        recs.append(body)
        w.write_record(body)
    path = tmp_path / "indexed.rec"
    path.write_bytes(strm.getvalue())
    idx_path = tmp_path / "indexed.idx"
    idx_path.write_text("".join(f"{i} {off}\n" for i, off in enumerate(offsets)))
    return str(path), str(idx_path), recs


def test_indexed_sequential(tmp_path):
    path, idx, recs = make_indexed_recordio(tmp_path)
    sp = isplit.create(path, 0, 1, "indexed_recordio", index_uri=idx)
    assert read_all(sp) == recs


@pytest.mark.parametrize("num_parts", [2, 3, 7])
def test_indexed_record_granular_partition(tmp_path, num_parts):
    path, idx, recs = make_indexed_recordio(tmp_path)
    got = []
    for part in range(num_parts):
        sp = isplit.create(
            path, part, num_parts, "indexed_recordio", index_uri=idx
        )
        got.extend(read_all(sp))
    assert got == recs  # record-granular: exact cover in order


def test_indexed_shuffle_covers_and_differs(tmp_path):
    path, idx, recs = make_indexed_recordio(tmp_path)
    sp = isplit.create(
        path, 0, 1, "indexed_recordio", index_uri=idx, shuffle=True, seed=5
    )
    epoch1 = read_all(sp)
    assert sorted(epoch1) == sorted(recs)
    assert epoch1 != recs  # shuffled order differs w.h.p. for 50 records
    sp.before_first()
    epoch2 = read_all(sp)
    assert sorted(epoch2) == sorted(recs)
    assert epoch2 != epoch1  # fresh permutation each epoch


def test_indexed_shuffle_seed_reproducible(tmp_path):
    path, idx, recs = make_indexed_recordio(tmp_path)
    a = read_all(
        isplit.create(path, 0, 1, "indexed_recordio", index_uri=idx, shuffle=True, seed=7)
    )
    b = read_all(
        isplit.create(path, 0, 1, "indexed_recordio", index_uri=idx, shuffle=True, seed=7)
    )
    assert a == b


# ---------- single file / stdin ----------------------------------------

def test_single_file_split(tmp_path):
    p = tmp_path / "single.txt"
    p.write_bytes(b"x\ny\nz")
    sp = isplit.SingleFileSplit(str(p))
    assert [bytes(r) for r in sp] == [b"x", b"y", b"z"]
    sp.before_first()
    assert sp.next_record() is not None


# ---------- zero-copy (mmap) fast path vs generic copy path -------------

def _read_with_mode(monkeypatch, uri, typ, num_parts, mmap_on, hint=None):
    if mmap_on:
        monkeypatch.delenv("DMLC_TPU_DISABLE_MMAP", raising=False)
    else:
        monkeypatch.setenv("DMLC_TPU_DISABLE_MMAP", "1")
    out = []
    for part in range(num_parts):
        sp = isplit.create(uri, part, num_parts, typ, threaded=False)
        if hint:
            sp.hint_chunk_size(hint)
        out.append(read_all(sp))
        sp.close()
    return out


@pytest.mark.parametrize("num_parts", [1, 2, 3, 5])
def test_mmap_matches_copy_path_text(tmp_path, monkeypatch, num_parts):
    uri, lines = make_text_files(tmp_path)
    fast = _read_with_mode(monkeypatch, uri, "text", num_parts, True)
    slow = _read_with_mode(monkeypatch, uri, "text", num_parts, False)
    assert fast == slow
    assert [r.decode() for part in fast for r in part] == lines


@pytest.mark.parametrize("num_parts", [1, 2, 4])
def test_mmap_matches_copy_path_recordio(tmp_path, monkeypatch, num_parts):
    paths = []
    recs = []
    for i in range(3):
        p, r = make_recordio_file(tmp_path, n=97, seed=10 + i, name=f"f{i}.rec")
        paths.append(p)
        recs.extend(r)
    uri = ";".join(paths)
    fast = _read_with_mode(monkeypatch, uri, "recordio", num_parts, True)
    slow = _read_with_mode(monkeypatch, uri, "recordio", num_parts, False)
    assert fast == slow
    assert [r for part in fast for r in part] == recs


def test_mmap_text_line_crosses_file_seam(tmp_path, monkeypatch):
    # file A has no trailing newline: its last line joins file B's first
    # line in the concatenated byte space (reference Read() semantics)
    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_bytes(b"alpha\nbeta\ngam")
    b.write_bytes(b"ma\ndelta\n")
    uri = f"{a};{b}"
    fast = _read_with_mode(monkeypatch, uri, "text", 1, True)
    slow = _read_with_mode(monkeypatch, uri, "text", 1, False)
    assert fast == slow
    assert fast[0] == [b"alpha", b"beta", b"gamma", b"delta"]


def test_mmap_seam_with_tiny_chunks(tmp_path, monkeypatch):
    # tiny hint forces many windows + the stitch path right at the seam
    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_bytes(b"one\ntwo\nthree-is-longer-than-the-hint")
    b.write_bytes(b"...continued\nfour\n")
    uri = f"{a};{b}"
    fast = _read_with_mode(monkeypatch, uri, "text", 1, True, hint=8)
    slow = _read_with_mode(monkeypatch, uri, "text", 1, False, hint=8)
    assert fast == slow
    assert fast[0][2] == b"three-is-longer-than-the-hint...continued"


def test_read_chunk_respects_max_size_at_seam(tmp_path):
    # bytes API contract: read_chunk(max_size) never returns more than
    # max_size bytes, even when a record crosses a file seam (stitch path)
    from dmlc_tpu.io import input_split

    a = tmp_path / "a.txt"
    b = tmp_path / "b.txt"
    a.write_bytes(b"one\ntwo\nthree-is-longer-than-max" )
    b.write_bytes(b"...over-the-seam\nfour\n")
    split = input_split.create(f"{a};{b}", 0, 1, "text", threaded=False)
    try:
        max_size, chunks = 16, []
        while True:
            c = split.read_chunk(max_size)
            if c is None:
                break
            if c == b"":
                max_size *= 2
                continue
            assert len(c) <= max_size, (len(c), max_size)
            chunks.append(bytes(c))
    finally:
        split.close()
    joined = b"".join(chunks)
    assert b"three-is-longer-than-max...over-the-seam" in joined


def test_mmap_recordio_tiny_hint(tmp_path, monkeypatch):
    path, recs = make_recordio_file(tmp_path, n=61, seed=3)
    fast = _read_with_mode(monkeypatch, path, "recordio", 2, True, hint=16)
    slow = _read_with_mode(monkeypatch, path, "recordio", 2, False, hint=16)
    assert fast == slow
    assert [r for part in fast for r in part] == recs


def test_mmap_before_first_rereads_identically(tmp_path):
    uri, lines = make_text_files(tmp_path, n_files=2)
    sp = isplit.create(uri, 0, 2, "text", threaded=False)
    first = read_all(sp)
    sp.before_first()
    second = read_all(sp)
    sp.close()
    assert first == second


def test_unknown_protocols_give_guidance():
    from dmlc_tpu.io.filesys import FileSystem
    from dmlc_tpu.io.uri import URI

    with pytest.raises(DMLCError, match="unknown filesystem protocol"):
        FileSystem.get_instance(URI("xyz://whatever"))


def test_builtin_network_protocols_resolve():
    from dmlc_tpu.io.filesys import FileSystem
    from dmlc_tpu.io.uri import URI

    # hdfs:// and azure:// gained real backends in round 4 (WebHDFS /
    # Blob REST) and s3:// in round 5 (SigV4 REST)
    for proto in ("hdfs://nn/path", "azure://c/b", "http://h/p",
                  "gs://b/k", "s3://b/k"):
        assert FileSystem.get_instance(URI(proto)) is not None


# ---------- elastic repartition contract (ISSUE 7) ----------------------

@pytest.mark.parametrize("fmt,maker", [
    ("recordio", make_recordio_file),
])
@pytest.mark.parametrize("old_parts,new_parts",
                         [(1, 3), (3, 1), (2, 5), (5, 2), (4, 3), (3, 7)])
def test_repartition_covers_exactly_once(tmp_path, fmt, maker, old_parts,
                                         new_parts):
    """The elastic resize property: for ANY num_parts -> num_parts'
    change, the union of the new byte-range partitions equals the old
    coverage — every record exactly once, order preserved within each
    partition — with no coordination between worlds."""
    uri, recs = maker(tmp_path)

    def partition_records(num_parts):
        out = []
        for part in range(num_parts):
            sp = isplit.create(uri, part, num_parts, fmt, threaded=False)
            out.append(read_all(sp))
            sp.close()
        return out

    old = partition_records(old_parts)
    new = partition_records(new_parts)
    flat_old = [r for part in old for r in part]
    flat_new = [r for part in new for r in part]
    assert flat_old == recs
    assert flat_new == recs  # exactly once, global order preserved


@pytest.mark.parametrize("num_parts", [1, 2, 3, 5, 8, 16])
def test_partition_spans_tile_byte_space(tmp_path, num_parts):
    """partition_spans is the pure form of the repartition contract:
    spans tile [first record, total] exactly and match what
    reset_partition actually reads."""
    uri, recs = make_recordio_file(tmp_path)
    sp = isplit.create(uri, 0, 1, "recordio", threaded=False)
    spans = sp.partition_spans(num_parts)
    assert len(spans) == num_parts
    total = sp.get_total_size()
    assert spans[0][0] == 0
    assert spans[-1][1] == total
    for (b0, e0), (b1, e1) in zip(spans, spans[1:]):
        assert e0 == b1, "spans must tile with no gap or overlap"
        assert b0 <= e0
    # spans agree with the partitions reset_partition serves: a part
    # yields records iff its span is non-empty, and the concatenation
    # over spans reproduces the dataset in order
    got = []
    for part, (b, e) in enumerate(spans):
        sp.reset_partition(part, num_parts)
        part_recs = read_all(sp)
        assert bool(part_recs) == (e > b)
        got.extend(part_recs)
    assert got == recs
    sp.close()


def test_partition_spans_deterministic_across_instances(tmp_path):
    """Two independent split instances (two worlds) agree on every
    span for every num_parts — the no-coordination guarantee."""
    uri, _ = make_recordio_file(tmp_path)
    a = isplit.create(uri, 0, 1, "recordio", threaded=False)
    b = isplit.create(uri, 0, 1, "recordio", threaded=False)
    for n in (1, 2, 3, 5, 9):
        assert a.partition_spans(n) == b.partition_spans(n)
    a.close()
    b.close()


# ---------------------------------------------------------------------------
# epoch-cache CRC32C footer (io.cached_input_split)
# ---------------------------------------------------------------------------

def _write_rec_file(path, recs):
    from dmlc_tpu.io.stream import Stream

    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s, checksum=True)
        for r in recs:
            w.write_record(r)


def test_cache_crc_footer_roundtrip(tmp_path):
    recs = [bytes([i]) * 32 for i in range(20)]
    rec = str(tmp_path / "src.rec")
    cache = str(tmp_path / "epoch.cache")
    _write_rec_file(rec, recs)
    sp = isplit.create(f"{rec}#{cache}", 0, 1, "recordio")
    first = [bytes(r) for r in sp]
    sp.before_first()  # switch to replay
    second = [bytes(r) for r in sp]
    sp.close()
    assert first == recs and second == recs
    assert open(cache, "rb").read(8) == b"dmlcCC01"


def test_corrupted_cache_detected_and_rebuilt(tmp_path):
    """A rotted cache is counted and discarded; the epoch re-parses from
    the source instead of failing (or serving the rot)."""
    from dmlc_tpu import telemetry

    recs = [bytes([i]) * 32 for i in range(20)]
    rec = str(tmp_path / "src.rec")
    cache = str(tmp_path / "epoch.cache")
    _write_rec_file(rec, recs)
    sp = isplit.create(f"{rec}#{cache}", 0, 1, "recordio")
    assert len([bytes(r) for r in sp]) == 20
    sp.close()
    raw = bytearray(open(cache, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(cache, "wb").write(bytes(raw))
    before = telemetry.counters_snapshot().get(
        "io_cache", {}).get("integrity_failures", 0)
    sp = isplit.create(f"{rec}#{cache}", 0, 1, "recordio")
    got = [bytes(r) for r in sp]
    sp.close()
    assert got == recs
    after = telemetry.counters_snapshot().get(
        "io_cache", {}).get("integrity_failures", 0)
    assert after > before
    # the rebuilt cache is valid again
    sp = isplit.create(f"{rec}#{cache}", 0, 1, "recordio")
    assert [bytes(r) for r in sp] == recs
    sp.close()


def test_legacy_cache_without_footer_still_replays(tmp_path):
    """Pre-footer caches (u64 size + bytes, no header) replay unchanged."""
    import struct as _struct

    recs = [bytes([i]) * 16 for i in range(8)]
    rec = str(tmp_path / "src.rec")
    cache = str(tmp_path / "legacy.cache")
    _write_rec_file(rec, recs)
    chunk = open(rec, "rb").read()
    with open(cache, "wb") as f:
        f.write(_struct.pack("<Q", len(chunk)))
        f.write(chunk)
    sp = isplit.create(f"{rec}#{cache}", 0, 1, "recordio")
    assert [bytes(r) for r in sp] == recs
    sp.close()
