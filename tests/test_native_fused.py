"""The PR 11 fused single-pass feed: native vs Python scanner parity
(all corruption shapes x all DMLC_INTEGRITY_POLICY values), native
pad-pack parity, packed-transport padded feed, and the ledger-driven
feed autotuner."""

import struct

import numpy as np
import pytest

import dmlc_tpu.native as native_mod
from dmlc_tpu.feed.device_feed import (_chunk_spans, _gather_rows_into,
                                       _py_chunk_spans, pack_rowblock)
from dmlc_tpu.io import integrity
from dmlc_tpu.io.recordio import KMAGIC, RecordIOWriter
from dmlc_tpu.io.stream import MemoryBytesStream, Stream

MAGIC = struct.pack("<I", KMAGIC)


@pytest.fixture(autouse=True)
def _clean_quarantine():
    integrity.reset_quarantine()
    yield
    integrity.reset_quarantine()


def _force_fallback(monkeypatch, disable: bool):
    if disable:
        monkeypatch.setenv("DMLC_TPU_DISABLE_NATIVE", "1")
    else:
        monkeypatch.delenv("DMLC_TPU_DISABLE_NATIVE", raising=False)
    monkeypatch.setattr(native_mod, "_tried", False)
    monkeypatch.setattr(native_mod, "_lib", None)


def _write_records(recs, checksum):
    s = MemoryBytesStream()
    w = RecordIOWriter(s, checksum=checksum)
    for r in recs:
        w.write_record(r)
    return bytearray(s.getvalue())


def _base_records(checksum):
    rng = np.random.default_rng(11)
    recs = []
    for i in range(24):
        if i % 7 == 3:  # escaped magic -> multi-segment record
            recs.append(b"P" * (4 * (i % 3)) + MAGIC + b"Q" * (4 + 4 * (i % 2)))
        elif i % 5 == 2:
            recs.append(b"")  # empty record
        else:
            recs.append(bytes(rng.integers(0, 256, 5 + i * 3,
                                           dtype=np.uint8)))
    return _write_records(recs, checksum)


def _corruption_cases():
    """(name, chunk bytes) for every corruption shape the scanners
    classify — incl. the PR 8 stray-aligned-word-at-chunk-tail case."""
    cases = []
    for ck in (False, True):
        tag = "crc" if ck else "plain"
        clean = _base_records(ck)
        cases.append((f"clean-{tag}", bytes(clean)))
        b = bytearray(clean)
        b[0:4] = b"\xde\xad\xbe\xef"  # head magic destroyed
        cases.append((f"bad-magic-{tag}", bytes(b)))
        cases.append((f"truncated-{tag}", bytes(clean[: len(clean) - 6])))
        # stray ALIGNED word at the chunk tail: a writer killed one word
        # into the next header passes the splitter's %4 admission
        cases.append((f"stray-word-{tag}", bytes(clean) + MAGIC))
        b = bytearray(clean)
        # overwrite a record head's cflag with a continuation flag
        lrec = struct.unpack_from("<I", b, 4)[0]
        struct.pack_into("<I", b, 4, (lrec & ((1 << 29) - 1)) | (2 << 29))
        cases.append((f"head-cflag-{tag}", bytes(b)))
    # crc payload flips (checksummed only): single-segment and the
    # multi-segment region
    ckbuf = _base_records(True)
    sp = _py_chunk_spans(memoryview(bytes(ckbuf)))
    single = next(i for i in range(sp.shape[0]) if sp[i, 2] == 2
                  and sp[i, 1] > 0)
    b = bytearray(ckbuf)
    b[int(sp[single, 0])] ^= 0xFF
    cases.append(("crc-flip-single", bytes(b)))
    multi = next(i for i in range(sp.shape[0]) if sp[i, 2] == 3)
    b = bytearray(ckbuf)
    b[int(sp[multi, 0]) + 12] ^= 0xFF  # first segment payload byte
    cases.append(("crc-flip-multiseg", bytes(b)))
    # torn multi-segment: cut inside the region
    b = bytes(ckbuf[: int(sp[multi, 0]) + 16])
    cases.append(("torn-multiseg", b))
    return cases


@pytest.mark.parametrize("name,chunk", _corruption_cases())
def test_scanner_parity(name, chunk):
    """The native fused scanner and the Python fallback walker emit
    IDENTICAL triple tables — good spans AND typed rejects — for every
    corruption shape, so the two walkers can never drift."""
    if not native_mod.available():
        pytest.skip("native library unavailable")
    sp_native = native_mod.recordio_spans(memoryview(chunk), KMAGIC,
                                         verify=True)
    sp_py = _py_chunk_spans(memoryview(chunk))
    assert sp_native.shape == sp_py.shape, name
    assert (sp_native == sp_py).all(), (
        f"{name}: native {sp_native.tolist()} != py {sp_py.tolist()}")
    if name.startswith("clean"):
        assert (sp_native[:, 2] < 8).all(), name
    else:
        assert (sp_native[:, 2] >= 8).any(), name
    if name.startswith("stray-word"):
        # the satellite case: exactly one torn-tail reject covering the
        # stray aligned word
        tail = sp_native[sp_native[:, 2] == 14]
        assert tail.shape[0] == 1 and int(tail[0, 1]) == 4, name


@pytest.mark.parametrize("policy", ["raise", "skip", "quarantine"])
@pytest.mark.parametrize("disable_native", [False, True])
@pytest.mark.parametrize(
    "name,chunk",
    [c for c in _corruption_cases() if not c[0].startswith("clean")])
def test_chunk_spans_policy_differential(monkeypatch, policy,
                                         disable_native, name, chunk):
    """End-to-end differential matrix (the satellite-1 gate): native vs
    DMLC_TPU_DISABLE_NATIVE=1 must agree on kept spans, raised error,
    quarantined spans, and counters under all three integrity
    policies."""
    _force_fallback(monkeypatch, disable_native)
    if not disable_native and not native_mod.available():
        pytest.skip("native library unavailable")
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    integrity.reset_quarantine()
    from dmlc_tpu import telemetry

    before = telemetry.counters_snapshot().get("integrity", {})
    if policy == "raise":
        with pytest.raises(integrity.CorruptRecord):
            _chunk_spans(memoryview(chunk), source=f"t-{name}", base=0)
        return
    sp = _chunk_spans(memoryview(chunk), source=f"t-{name}", base=0)
    after = telemetry.counters_snapshot().get("integrity", {})
    assert (sp[:, 2] < 8).all()  # rejects never escape
    corrupt = (after.get("corrupt_records", 0)
               - before.get("corrupt_records", 0))
    assert corrupt >= 1
    spans = integrity.quarantined_spans(f"t-{name}")
    if policy == "quarantine":
        assert spans, name
    else:
        assert not spans

    # the differential core: the OTHER walker must produce the same
    # kept spans and the same quarantine keys
    _force_fallback(monkeypatch, not disable_native)
    if disable_native and not native_mod.available():
        return
    integrity.reset_quarantine()
    sp2 = _chunk_spans(memoryview(chunk), source=f"t-{name}", base=0)
    assert (sp == sp2).all(), name
    assert integrity.quarantined_spans(f"t-{name}") == spans, name


def test_fused_verify_quarantine_replay(monkeypatch):
    """A crc-corrupt record under policy=quarantine: first pass reports
    + quarantines, the REPLAY drops it via the skip-list (counted as a
    skiplist drop, not a fresh corrupt-record report) — on both
    walkers."""
    from dmlc_tpu import telemetry

    for disable in (False, True):
        _force_fallback(monkeypatch, disable)
        if not disable and not native_mod.available():
            pytest.skip("native library unavailable")
        monkeypatch.setenv("DMLC_INTEGRITY_POLICY", "quarantine")
        integrity.reset_quarantine()
        chunk = dict(_corruption_cases())["crc-flip-single"]
        src = f"replay-{disable}"
        sp1 = _chunk_spans(memoryview(chunk), source=src, base=0)
        assert integrity.quarantined_spans(src)
        before = telemetry.counters_snapshot().get("integrity", {})
        sp2 = _chunk_spans(memoryview(chunk), source=src, base=0)
        after = telemetry.counters_snapshot().get("integrity", {})
        assert (sp1 == sp2).all()
        assert (after.get("skiplist_drops", 0)
                - before.get("skiplist_drops", 0)) >= 1
        assert (after.get("corrupt_records", 0)
                == before.get("corrupt_records", 0))


def test_pad_pack_rows_native_matches_numpy(monkeypatch):
    """dmlc_pad_pack_rows == the numpy broadcast gather, byte for byte,
    incl. escaped-magic reassembly and truncation at max_bytes."""
    if not native_mod.available():
        pytest.skip("native library unavailable")
    chunk = bytes(_base_records(False))
    mv = memoryview(chunk)
    sp = _chunk_spans(mv)
    g = sp.shape[0]
    for max_bytes in (8, 64):
        a_rows = np.full((g, max_bytes), 7, np.uint8)
        a_lens = np.full(g, -1, np.int32)
        _gather_rows_into(mv, sp, 0, g, max_bytes, a_rows, a_lens)
        b_rows = np.full((g, max_bytes), 9, np.uint8)
        b_lens = np.full(g, -2, np.int32)
        monkeypatch.setattr(native_mod, "_lib", None)
        monkeypatch.setattr(native_mod, "_tried", True)  # force fallback
        _gather_rows_into(mv, sp, 0, g, max_bytes, b_rows, b_lens)
        monkeypatch.undo()
        assert (a_rows == b_rows).all(), max_bytes
        assert (a_lens == b_lens).all(), max_bytes


def test_pack_rowblock_native_matches_numpy(monkeypatch):
    """dmlc_pad_pack_csr == the numpy pack_rowblock, byte for byte:
    truncated rows, short blocks, empty blocks, num_col clamping."""
    if not native_mod.available():
        pytest.skip("native library unavailable")
    from dmlc_tpu.data.row_block import RowBlockContainer

    c = RowBlockContainer()
    c.push_arrays(
        labels=np.array([1.0, 0.0, 1.0], np.float32),
        offsets=np.array([0, 2, 2, 7], np.uint64),
        index=np.array([0, 3, 1, 2, 4, 9, 5], np.uint32),
        value=np.array([1, 2, 3, 4, 5, 6, 7], np.float32),
    )
    blk = c.get_block()
    empty = RowBlockContainer()
    empty.push_arrays(labels=np.empty(0, np.float32),
                      offsets=np.array([0], np.uint64),
                      index=np.empty(0, np.uint32),
                      value=np.empty(0, np.float32))

    def run(b, **kw):
        return pack_rowblock(b, **kw)

    nan = RowBlockContainer()
    nan.push_arrays(  # NaN/Inf must never leak into masked padding
        labels=np.array([1.0, 0.0], np.float32),
        offsets=np.array([0, 1, 2], np.uint64),
        index=np.array([0, 1], np.uint32),
        value=np.array([np.nan, np.inf], np.float32),
    )
    for b, kw in [
        (blk, dict(batch_size=4, max_nnz=3, num_col=6)),  # clamp + trunc
        (blk, dict(batch_size=2, max_nnz=8, num_col=0)),  # b < size
        (blk.slice(1, 3), dict(batch_size=4, max_nnz=2, num_col=10)),
        (empty.get_block(), dict(batch_size=3, max_nnz=2, num_col=4)),
        (nan.get_block(), dict(batch_size=3, max_nnz=3, num_col=0)),
    ]:
        nat = run(b, **kw)
        monkeypatch.setattr(native_mod, "_lib", None)
        monkeypatch.setattr(native_mod, "_tried", True)
        py = run(b, **kw)
        monkeypatch.undo()
        for k in ("label", "value", "index", "mask"):
            assert np.array_equal(nat[k], py[k], equal_nan=True), (k, kw)
            assert nat[k].dtype == py[k].dtype
        # masked padding cells are EXACT zeros on both paths, even when
        # real cells hold NaN/Inf (the clamped-gather leak regression)
        for out in (nat, py):
            masked = out["mask"] == 0.0
            assert (out["value"][masked] == 0.0).all(), kw


def _write_rec_file(tmp_path, recs, name="data.rec", checksum=False):
    path = str(tmp_path / name)
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s, checksum=checksum)
        for r in recs:
            w.write_record(r)
    return path


def test_padded_packed_transport_parity(tmp_path):
    """recordio_feed(pack_bytes=...) must deliver the exact record
    stream of the classic padded staging — the on-device expansion is a
    transport optimization, not a contract change."""
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.parallel import build_mesh

    rng = np.random.default_rng(5)
    recs = []
    for i in range(90):
        if i % 9 == 4:
            recs.append(b"x" * 4 + MAGIC + b"y" * 8)  # escaped magic
        else:
            recs.append(bytes(rng.integers(0, 256, 10 + i % 70,
                                           dtype=np.uint8)))
    path = _write_rec_file(tmp_path, recs, checksum=True)
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)

    def collect(**kw):
        out = []
        feed = recordio_feed(path, mesh1, batch_records=8, max_bytes=48,
                             **kw)
        for b in feed:
            data = np.asarray(b["data"])
            lens = np.asarray(b["length"])
            assert data.shape == (8, 48)
            for row, n in zip(data, lens):
                if n > 0:
                    out.append(bytes(row[:n]))
                # padded tail beyond length must be zero
                assert not row[n:].any()
        return out

    want = [r[:48] for r in recs if r]
    got_legacy = [r for r in collect()]
    got_packed = [r for r in collect(pack_bytes=512)]
    assert [r for r in got_legacy if r] == want
    assert [r for r in got_packed if r] == want


def test_padded_packed_transport_epoch_tail_masking(tmp_path):
    """Epoch-tail parts_alive masking on the 8-part mesh: drained
    partitions pad with zero rows and parts_alive=0, same as the
    classic path; empty partitions work."""
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.parallel import build_mesh

    # few records: several of the 8 partitions end up EMPTY
    recs = [bytes([i]) * (6 + i) for i in range(5)]
    path = _write_rec_file(tmp_path, recs)
    mesh = build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh, batch_records=2, max_bytes=16,
                         pack_bytes=64)
    total = 0
    for b in feed:
        alive = np.asarray(b["parts_alive"])
        assert alive.shape == (8,)
        data = np.asarray(b["data"]).reshape(8, 2, 16)
        lens = np.asarray(b["length"]).reshape(8, 2)
        for p in range(8):
            if alive[p] == 0.0:
                assert not data[p].any() and not lens[p].any()
        total += int((lens > 0).sum())
    assert total == len(recs)
    # multi-epoch: the expander and staging survive a second epoch
    total2 = sum(int((np.asarray(b["length"]) > 0).sum()) for b in feed)
    assert total2 == len(recs)


def test_libsvm_fused_parity_with_classic(tmp_path, monkeypatch):
    """The fused native libsvm path (dmlc_parse_libsvm_into) and the
    classic parser+pack_rowblock path emit IDENTICAL batch streams,
    incl. the zero-padded epoch tail."""
    if not native_mod.available():
        pytest.skip("native library unavailable")
    lines = []
    for i in range(43):
        # float-exact values so both float parsers agree bit-for-bit
        lines.append(f"{i % 2} 0:{i}.5 3:{i} 7:0.25 11:1")
    lines.append("")  # blank line ignored
    p = tmp_path / "t.libsvm"
    p.write_text("\n".join(lines) + "\n")
    from dmlc_tpu.feed import libsvm_feed
    from dmlc_tpu.parallel import build_mesh

    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)

    def collect(disable):
        _force_fallback(monkeypatch, disable)
        out = []
        for b in libsvm_feed(str(p), mesh1, batch_size=8, max_nnz=3):
            out.append(tuple(np.asarray(b[k]).tobytes()
                             for k in ("label", "value", "index", "mask")))
        return out

    assert collect(False) == collect(True)


def test_pack_rowblock_foreign_dtype_out_uses_numpy_path():
    """A caller-provided out dict with non-canonical dtypes (legal on
    the pre-PR numpy path, which casts on assignment) must NOT take the
    native branch — float64/int64 buffers reinterpreted as f32/i32
    would be silent data corruption."""
    from dmlc_tpu.data.row_block import RowBlockContainer

    c = RowBlockContainer()
    c.push_arrays(labels=np.array([1.0, 0.0], np.float32),
                  offsets=np.array([0, 2, 3], np.uint64),
                  index=np.array([0, 3, 1], np.uint32),
                  value=np.array([1, 2, 3], np.float32))
    blk = c.get_block()
    out64 = {"label": np.empty(4, np.float64),
             "value": np.empty((4, 2), np.float64),
             "index": np.empty((4, 2), np.int64),
             "mask": np.empty((4, 2), np.float64)}
    got = pack_rowblock(blk, 4, 2, 5, out=out64)
    ref = pack_rowblock(blk, 4, 2, 5)  # canonical dtypes
    for k in ("label", "value", "index", "mask"):
        assert got[k] is out64[k]
        np.testing.assert_array_equal(got[k], ref[k].astype(got[k].dtype))
    # a WRONG-SHAPED out dict must never reach the native writer (the
    # numpy path raises a clean broadcast error; heap corruption is not
    # an acceptable alternative)
    small = {k: np.empty(v.shape, v.dtype) for k, v in ref.items()}
    with pytest.raises(ValueError):
        pack_rowblock(blk, 64, 8, out=small)


def test_pad_pack_csr_non_monotone_offsets_zero_fill():
    """Corrupt (non-monotone) CSR offsets wrap the row-length math; the
    native path must zero-fill such rows like the numpy twin instead of
    writing out of bounds."""
    from dmlc_tpu.data.row_block import RowBlock

    blk = RowBlock(offset=np.array([2, 1, 3], np.uint64),  # 2 -> 1 !
                   label=np.array([1.0, 0.0], np.float32),
                   weight=None, qid=None, field=None,
                   index=np.array([0, 1, 2], np.uint32),
                   value=np.array([5, 6, 7], np.float32))
    nat = pack_rowblock(blk, 3, 2, 0)
    assert (nat["value"][0] == 0).all() and (nat["mask"][0] == 0).all()
    assert nat["label"][0] == 1.0  # labels untouched by the bad row


def test_padded_packed_transport_rejects_small_pack_bytes(tmp_path):
    """pack_bytes < max_bytes would silently truncate records below the
    padded contract — refused at construction."""
    from dmlc_tpu.base import DMLCError
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.parallel import build_mesh

    path = _write_rec_file(tmp_path, [b"x" * 8])
    mesh1 = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    with pytest.raises(DMLCError, match="pack_bytes"):
        recordio_feed(path, mesh1, batch_records=2, max_bytes=64,
                      pack_bytes=32)


def test_autotune_accumulates_across_short_epochs(tmp_path, monkeypatch):
    """Epochs shorter than the decision window must ACCUMULATE ledger
    evidence across boundaries, not discard it."""
    from dmlc_tpu import telemetry
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.parallel import build_mesh

    monkeypatch.setenv("DMLC_FEED_AUTOTUNE", "1")
    monkeypatch.setenv("DMLC_FEED_WORKERS", "1")
    monkeypatch.setenv("DMLC_FEED_WORKERS_MAX", "3")
    path = _write_rec_file(tmp_path, [b"r" * 20] * 16)
    mesh = build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh, batch_records=2, max_bytes=32)
    telemetry.reset_steps()
    led = telemetry.ledger()

    def epoch_with_steps(n):
        for _ in range(n):
            led.step_begin()
            led.step_end(tokens=1)
        with led._lock:
            for rec in led._records:
                rec["wall_s"] = max(rec["wall_s"], 1e-3)
                rec["feed_wait_s"] = 0.9 * rec["wall_s"]
        for _ in feed:
            pass

    epoch_with_steps(2)  # below window: held, not discarded
    assert feed._workers == 1
    epoch_with_steps(2)  # still below
    assert feed._workers == 1
    epoch_with_steps(2)  # cumulative 6 >= window: applied
    assert feed._workers == 2, feed._workers


def test_feed_autotuner_converges_and_holds():
    """Synthetic ledger trace: the controller grows until feed-wait
    drops below the high-water mark, then HOLDS — and a punished shrink
    raises the floor so it cannot oscillate."""
    from dmlc_tpu.feed import FeedAutotuner

    t = FeedAutotuner(workers=1, depth=2, min_workers=1, max_workers=6,
                      max_depth=4)
    trace = []
    for _ in range(30):
        fw = max(0.0, 0.6 - 0.12 * t.workers)  # more workers -> less wait
        trace.append(t.observe(fw))
    assert trace[-1] == trace[-2] == trace[-3], trace[-6:]
    w, d = trace[-1]
    assert 1 <= w <= 6 and 2 <= d <= 4
    assert max(0.0, 0.6 - 0.12 * w) <= t.high  # converged under the mark

    # oscillation guard: a shrink that starves the device is undone and
    # never retried
    t2 = FeedAutotuner(workers=4, depth=2, min_workers=1, max_workers=6,
                       max_depth=4)
    hist = []
    for _ in range(20):
        fw = 0.0 if t2.workers >= 4 else 0.5
        hist.append(t2.observe(fw))
    tail = hist[-8:]
    assert all(x == (4, 2) for x in tail), (
        f"controller kept oscillating: {hist}")

    # a punished DEPTH shrink must undo depth (not grow workers): the
    # device starves whenever depth < 3 here, regardless of workers
    t3 = FeedAutotuner(workers=2, depth=2, min_workers=1, max_workers=6,
                       max_depth=4)
    t3.depth = 4  # as if earlier traffic grew depth
    hist3 = []
    for _ in range(24):
        fw = 0.0 if t3.depth >= 3 else 0.5
        hist3.append(t3.observe(fw))
    w3, d3 = hist3[-1]
    assert d3 >= 3, f"depth shrink not undone: {hist3}"
    assert all(x == hist3[-1] for x in hist3[-6:]), hist3
    assert w3 <= 3, f"punished depth shrink ratcheted workers: {hist3}"


def test_feed_autotune_applies_between_epochs(tmp_path, monkeypatch):
    """DMLC_FEED_AUTOTUNE=1: a high feed-wait fraction in the step
    ledger grows the worker count at the next epoch boundary, within
    the registered bounds."""
    from dmlc_tpu import telemetry
    from dmlc_tpu.feed import recordio_feed
    from dmlc_tpu.parallel import build_mesh

    monkeypatch.setenv("DMLC_FEED_AUTOTUNE", "1")
    monkeypatch.setenv("DMLC_FEED_WORKERS", "1")
    monkeypatch.setenv("DMLC_FEED_WORKERS_MAX", "3")
    path = _write_rec_file(tmp_path, [b"r" * 20] * 40)
    mesh = build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)
    feed = recordio_feed(path, mesh, batch_records=2, max_bytes=32)
    assert feed._autotuner is not None
    telemetry.reset_steps()
    for _ in feed:  # epoch 1: no ledger evidence -> no change
        pass
    assert feed._workers == 1
    led = telemetry.ledger()
    for _ in range(6):
        led.step_begin()
        led.step_end(tokens=1)
    for rec in led.records():
        pass
    with led._lock:
        for rec in led._records:  # synthetic: 90% feed-wait steps
            rec["wall_s"] = max(rec["wall_s"], 1e-3)
            rec["feed_wait_s"] = 0.9 * rec["wall_s"]
    for _ in feed:  # epoch 2 applies the controller
        pass
    assert feed._workers == 2, feed._workers
