"""Sharded checkpoint round trips: local and gs://, full model state."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_tpu.checkpoint import CheckpointManager, restore_pytree, save_pytree
from dmlc_tpu.models import TransformerConfig, init_params, param_specs
from dmlc_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(8, pp=2, sp=2, tp=2, dp=1, ep=1)


def _sharded_tree(mesh):
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            d_ff=32, n_layers=2, n_experts=2)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=2)
    specs = param_specs()
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs
    ), params


def test_roundtrip_local_sharded(tmp_path, mesh):
    sharded, host = _sharded_tree(mesh)
    uri = str(tmp_path / "ckpt")
    save_pytree(uri, sharded)
    got = restore_pytree(uri, sharded, mesh=mesh)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(got)[0],
        jax.tree_util.tree_flatten_with_path(host)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))
        # restored sharding matches the recorded spec
    # restore without a mesh -> plain numpy
    np_tree = restore_pytree(uri, sharded, mesh=None)
    leaf = jax.tree.leaves(np_tree)[0]
    assert isinstance(leaf, np.ndarray)


def test_roundtrip_gcs(tmp_path, mesh):
    # reuse the GCS emulator from test_gcs_http
    import os
    import threading
    from http.server import ThreadingHTTPServer

    from tests.test_gcs_http import _FakeGCS

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    old = os.environ.get("STORAGE_EMULATOR_HOST")
    os.environ["STORAGE_EMULATOR_HOST"] = f"127.0.0.1:{srv.server_port}"
    try:
        x = jnp.arange(64.0).reshape(8, 8)
        sharded = jax.device_put(
            x, NamedSharding(mesh, P(("pp", "sp"), "tp")))
        tree = {"w": sharded, "b": np.ones(3, np.float32)}
        save_pytree("gs://ckpts/run1/step1", tree)
        got = restore_pytree("gs://ckpts/run1/step1", tree, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
        np.testing.assert_array_equal(np.asarray(got["b"]), tree["b"])
    finally:
        if old is None:
            os.environ.pop("STORAGE_EMULATOR_HOST", None)
        else:
            os.environ["STORAGE_EMULATOR_HOST"] = old
        srv.shutdown()


def test_roundtrip_s3_and_hdfs(tmp_path, mesh):
    """Sharded checkpoints are backend-agnostic: the same save/restore
    rides the s3:// SigV4 writer (single-PUT at these shard sizes; the
    multipart lifecycle is covered by test_s3) and the hdfs://
    temp+RENAME writer through their hermetic emulators."""
    import os
    import threading
    from http.server import ThreadingHTTPServer

    from tests.test_hdfs_azure import _FakeNameNode, _drop_cached_instances
    from tests.test_s3 import _FakeS3

    x = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(x, NamedSharding(mesh, P(("pp", "sp"), "tp")))
    tree = {"w": sharded, "b": np.ones(3, np.float32)}

    _FakeS3.store.clear()
    s3srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=s3srv.serve_forever, daemon=True).start()
    _FakeNameNode.store.clear()
    nnsrv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeNameNode)
    threading.Thread(target=nnsrv.serve_forever, daemon=True).start()
    keys = ("DMLC_S3_ENDPOINT", "AWS_ACCESS_KEY_ID",
            "AWS_SECRET_ACCESS_KEY", "AWS_REGION",
            "DMLC_WEBHDFS_ENDPOINT")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ["DMLC_S3_ENDPOINT"] = f"127.0.0.1:{s3srv.server_port}"
    os.environ["AWS_ACCESS_KEY_ID"] = "AKIACKPT"
    os.environ["AWS_SECRET_ACCESS_KEY"] = "ckpt-secret"
    os.environ["AWS_REGION"] = "us-test-1"
    os.environ["DMLC_WEBHDFS_ENDPOINT"] = f"127.0.0.1:{nnsrv.server_port}"
    _drop_cached_instances("s3://", "hdfs://")
    try:
        for uri in ("s3://ckpts/run1/step1", "hdfs://nn/ckpts/step1"):
            save_pytree(uri, tree)
            got = restore_pytree(uri, tree, mesh=mesh)
            np.testing.assert_array_equal(np.asarray(got["w"]),
                                          np.asarray(x))
            np.testing.assert_array_equal(np.asarray(got["b"]), tree["b"])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _drop_cached_instances("s3://", "hdfs://")
        s3srv.shutdown()
        nnsrv.shutdown()


def test_checkpoint_manager_retention(tmp_path, mesh):
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    tree = {"w": np.arange(10, dtype=np.float32)}
    assert mgr.latest_step() is None
    for step in (1, 2, 3, 4):
        tree["w"] = tree["w"] + 1
        mgr.save(step, tree)
    assert mgr.latest_step() == 4
    step, got = mgr.restore_latest(tree)
    assert step == 4
    np.testing.assert_array_equal(got["w"], np.arange(10) + 4)
    import os

    kept = sorted(d for d in os.listdir(tmp_path / "run")
                  if d.startswith("step_"))
    assert kept == ["step_00000003", "step_00000004"]


def test_restore_missing_leaf_raises(tmp_path):
    from dmlc_tpu.checkpoint import MissingLeaf

    save_pytree(str(tmp_path / "c"), {"a": np.ones(2)})
    with pytest.raises(MissingLeaf, match="missing leaf"):
        restore_pytree(str(tmp_path / "c"),
                       {"a": np.ones(2), "zz": np.ones(2)})


def test_restore_with_partial_manifest_multi_host(tmp_path):
    """Multi-host saves: the manifest lists only process-0 shards; restore
    must derive shard filenames deterministically (advisor finding)."""
    import json

    mesh = build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    sharded = jax.device_put(
        x, jax.sharding.NamedSharding(mesh, P("dp", None)))
    uri = str(tmp_path / "ckpt")
    save_pytree(uri, {"w": sharded})

    # simulate process-0's view: drop all but one shard from the manifest
    mpath = tmp_path / "ckpt" / "manifest.json"
    man = json.loads(mpath.read_text())
    (key, entry), = man["leaves"].items()
    first = dict(list(entry["shards"].items())[:1])
    assert len(first) < len(entry["shards"])
    entry["shards"] = first
    mpath.write_text(json.dumps(man))

    # mesh restore: callback derives filenames, no manifest lookup
    got = restore_pytree(uri, {"w": x}, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
    # host restore: directory listing recovers the other processes' shards
    got_host = restore_pytree(uri, {"w": x})
    np.testing.assert_array_equal(got_host["w"], np.asarray(x))


def test_checkpoint_manager_rejects_zero_retention(tmp_path):
    from dmlc_tpu.base import DMLCError

    with pytest.raises(DMLCError):
        CheckpointManager(str(tmp_path), max_to_keep=0)


def test_restore_ignores_stale_shards_when_manifest_covers(tmp_path):
    """Stale shard files from an older differently-sharded save must not
    leak into a restore whose manifest fully covers the array."""
    uri = str(tmp_path / "ckpt2")
    x = np.arange(16, dtype=np.float32).reshape(4, 4)
    save_pytree(uri, {"w": x})
    # plant a stale half-shard from a hypothetical earlier layout
    (tmp_path / "ckpt2" / "w.0-2_0-4").write_bytes(
        np.full((2, 4), -1, np.float32).tobytes())
    got = restore_pytree(uri, {"w": x})
    np.testing.assert_array_equal(got["w"], x)


def test_restore_dot_prefixed_leaf_keys_do_not_collide(tmp_path):
    uri = str(tmp_path / "ckpt3")
    tree = {"w": np.ones((2, 2), np.float32),
            "w.scale": np.full((3,), 2.0, np.float32)}
    save_pytree(uri, tree)
    # force the listing path by pruning both manifests' shard dicts
    import json
    mpath = tmp_path / "ckpt3" / "manifest.json"
    man = json.loads(mpath.read_text())
    for entry in man["leaves"].values():
        entry["shards"] = {}
    mpath.write_text(json.dumps(man))
    got = restore_pytree(uri, tree)
    np.testing.assert_array_equal(got["w"], tree["w"])
    np.testing.assert_array_equal(got["w.scale"], tree["w.scale"])


# ---------------------------------------------------------------------------
# Crash-consistent commits (ISSUE 7): shards first, manifest last + atomic
# ---------------------------------------------------------------------------

def test_torn_save_is_skipped_by_restore_latest(tmp_path):
    """A step dir with shards but no committed manifest (preemption
    mid-save) must be invisible: latest_step/restore_latest land on the
    previous committed step, whatever LATEST claims."""
    from dmlc_tpu.checkpoint import CheckpointManager

    base = str(tmp_path / "mgr")
    mgr = CheckpointManager(base, max_to_keep=5)
    t1 = {"w": np.full((4,), 1.0, np.float32)}
    t2 = {"w": np.full((4,), 2.0, np.float32)}
    mgr.save(1, t1)
    mgr.save(2, t2)
    # simulate the preemption: step 3's shards landed, manifest did not,
    # but LATEST was (wrongly) advanced by some other failure mode
    import shutil
    shutil.copytree(tmp_path / "mgr" / "step_00000002",
                    tmp_path / "mgr" / "step_00000003")
    (tmp_path / "mgr" / "step_00000003" / "manifest.json").unlink()
    (tmp_path / "mgr" / "LATEST").write_text("3")

    assert mgr.latest_step() == 2
    step, got = mgr.restore_latest(t1)
    assert step == 2
    np.testing.assert_array_equal(got["w"], t2["w"])


def test_fault_injected_commit_preserves_previous_step(tmp_path,
                                                       monkeypatch):
    """Kill the save at the manifest-commit fault point: the interrupted
    step never becomes restorable and the previous one survives."""
    from dmlc_tpu.checkpoint import CheckpointManager
    from dmlc_tpu.resilience import reset_injector

    base = str(tmp_path / "mgr2")
    mgr = CheckpointManager(base)
    t1 = {"w": np.full((4,), 1.0, np.float32)}
    mgr.save(7, t1)
    monkeypatch.setenv("DMLC_FAULT_SPEC", "checkpoint.commit=error")
    reset_injector()
    with pytest.raises(ConnectionError):  # FaultInjected's torn-I/O shape
        mgr.save(8, {"w": np.full((4,), 8.0, np.float32)})
    monkeypatch.setenv("DMLC_FAULT_SPEC", "")
    reset_injector()
    assert mgr.latest_step() == 7
    step, got = mgr.restore_latest(t1)
    assert step == 7
    np.testing.assert_array_equal(got["w"], t1["w"])
    # the next successful save supersedes the torn dir and retention
    # clears the litter
    mgr.save(9, {"w": np.full((4,), 9.0, np.float32)})
    assert mgr.latest_step() == 9
    import os
    assert not os.path.isdir(os.path.join(base, "step_00000008"))


def test_manifest_commit_leaves_no_temp(tmp_path):
    """The atomic rename path must not leave manifest temp files."""
    uri = str(tmp_path / "atomic")
    save_pytree(uri, {"w": np.zeros((2,), np.float32)})
    names = os.listdir(uri)
    assert "manifest.json" in names
    assert not [n for n in names if ".tmp." in n]


def test_retention_counts_committed_only(tmp_path):
    """A torn (manifest-less) newer dir must not push a committed step
    out of the max_to_keep window."""
    from dmlc_tpu.checkpoint import CheckpointManager

    base = str(tmp_path / "mgr3")
    mgr = CheckpointManager(base, max_to_keep=2)
    for step in (1, 2):
        mgr.save(step, {"w": np.full((2,), float(step), np.float32)})
    # torn future dir (in-flight save of another process)
    torn = tmp_path / "mgr3" / "step_00000005"
    torn.mkdir()
    (torn / "w.0-2").write_bytes(b"\0" * 8)
    mgr.save(3, {"w": np.full((2,), 3.0, np.float32)})
    assert mgr.latest_step() == 3
    # committed steps 2 and 3 kept; 1 retired; torn future dir untouched
    names = sorted(os.listdir(base))
    assert "step_00000001" not in names
    assert {"step_00000002", "step_00000003",
            "step_00000005"} <= set(names)


# ---------------------------------------------------------------------------
# shard digests (CRC32C in the manifest) + corrupt-shard fallback
# ---------------------------------------------------------------------------

def _flip_byte(path, at=0):
    raw = bytearray(open(path, "rb").read())
    raw[at] ^= 0x01
    open(path, "wb").write(bytes(raw))


def test_manifest_records_shard_digests(tmp_path):
    import json

    save_pytree(str(tmp_path / "ck"), {"w": np.arange(16, dtype=np.float32)})
    man = json.load(open(tmp_path / "ck" / "manifest.json"))
    from dmlc_tpu.io.integrity import crc32c

    crcs = man["leaves"]["w"]["crc32c"]
    assert crcs == {"0-16": crc32c(
        np.arange(16, dtype=np.float32).tobytes())}


def test_flipped_shard_fails_restore_loudly(tmp_path):
    from dmlc_tpu.base import DMLCError

    save_pytree(str(tmp_path / "ck"), {"w": np.arange(16, dtype=np.float32)})
    _flip_byte(tmp_path / "ck" / "w.0-16")
    with pytest.raises(DMLCError, match="CRC32C"):
        restore_pytree(str(tmp_path / "ck"),
                       {"w": np.zeros(16, np.float32)})


def test_restore_latest_falls_back_past_flipped_shard(tmp_path):
    """A corrupt newest checkpoint costs one checkpoint interval, not
    the job: restore_latest falls back to the previous committed step."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, {"w": np.arange(16, dtype=np.float32)})
    mgr.save(2, {"w": np.arange(16, dtype=np.float32) * 2})
    _flip_byte(tmp_path / "step_00000002" / "w.0-16")
    step, restored = mgr.restore_latest({"w": np.zeros(16, np.float32)})
    assert step == 1
    np.testing.assert_array_equal(restored["w"],
                                  np.arange(16, dtype=np.float32))


def test_restore_latest_falls_back_past_corrupt_manifest(tmp_path):
    """Manifest rot is CorruptCheckpoint too: the fallback covers the
    digest root of trust itself, not just the shards it digests."""
    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, {"w": np.arange(16, dtype=np.float32)})
    mgr.save(2, {"w": np.arange(16, dtype=np.float32) * 2})
    man = tmp_path / "step_00000002" / "manifest.json"
    for rotted in ('{"format": 1', '{"format": 1}', "[]"):
        man.write_text(rotted)  # torn JSON / lost leaves / wrong shape
        step, restored = mgr.restore_latest({"w": np.zeros(16, np.float32)})
        assert step == 1
        np.testing.assert_array_equal(restored["w"],
                                      np.arange(16, dtype=np.float32))


def test_all_checkpoints_corrupt_raises(tmp_path):
    from dmlc_tpu.base import DMLCError

    mgr = CheckpointManager(str(tmp_path), max_to_keep=3)
    mgr.save(1, {"w": np.arange(8, dtype=np.float32)})
    _flip_byte(tmp_path / "step_00000001" / "w.0-8")
    with pytest.raises(DMLCError, match="no committed checkpoint"):
        mgr.restore_latest({"w": np.zeros(8, np.float32)})


def test_pre_digest_manifest_restores_unverified(tmp_path):
    """Old checkpoints (no crc32c field) keep restoring — the digest is
    an additive manifest field, not a format break."""
    import json

    save_pytree(str(tmp_path / "ck"), {"w": np.arange(8, dtype=np.float32)})
    mpath = tmp_path / "ck" / "manifest.json"
    man = json.load(open(mpath))
    for leaf in man["leaves"].values():
        leaf.pop("crc32c", None)
    open(mpath, "w").write(json.dumps(man))
    out = restore_pytree(str(tmp_path / "ck"),
                         {"w": np.zeros(8, np.float32)})
    np.testing.assert_array_equal(out["w"], np.arange(8, dtype=np.float32))
