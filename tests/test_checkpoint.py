"""Sharded checkpoint round trips: local and gs://, full model state."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_tpu.checkpoint import CheckpointManager, restore_pytree, save_pytree
from dmlc_tpu.models import TransformerConfig, init_params, param_specs
from dmlc_tpu.parallel import build_mesh


@pytest.fixture(scope="module")
def mesh():
    return build_mesh(8, pp=2, sp=2, tp=2, dp=1, ep=1)


def _sharded_tree(mesh):
    cfg = TransformerConfig(vocab=64, d_model=32, n_heads=4, head_dim=8,
                            d_ff=32, n_layers=2, n_experts=2)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=2)
    specs = param_specs()
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)), params, specs
    ), params


def test_roundtrip_local_sharded(tmp_path, mesh):
    sharded, host = _sharded_tree(mesh)
    uri = str(tmp_path / "ckpt")
    save_pytree(uri, sharded)
    got = restore_pytree(uri, sharded, mesh=mesh)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_flatten_with_path(got)[0],
        jax.tree_util.tree_flatten_with_path(host)[0],
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=str(pa))
        # restored sharding matches the recorded spec
    # restore without a mesh -> plain numpy
    np_tree = restore_pytree(uri, sharded, mesh=None)
    leaf = jax.tree.leaves(np_tree)[0]
    assert isinstance(leaf, np.ndarray)


def test_roundtrip_gcs(tmp_path, mesh):
    # reuse the GCS emulator from test_gcs_http
    import os
    import threading
    from http.server import ThreadingHTTPServer

    from tests.test_gcs_http import _FakeGCS

    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    old = os.environ.get("STORAGE_EMULATOR_HOST")
    os.environ["STORAGE_EMULATOR_HOST"] = f"127.0.0.1:{srv.server_port}"
    try:
        x = jnp.arange(64.0).reshape(8, 8)
        sharded = jax.device_put(
            x, NamedSharding(mesh, P(("pp", "sp"), "tp")))
        tree = {"w": sharded, "b": np.ones(3, np.float32)}
        save_pytree("gs://ckpts/run1/step1", tree)
        got = restore_pytree("gs://ckpts/run1/step1", tree, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
        np.testing.assert_array_equal(np.asarray(got["b"]), tree["b"])
    finally:
        if old is None:
            os.environ.pop("STORAGE_EMULATOR_HOST", None)
        else:
            os.environ["STORAGE_EMULATOR_HOST"] = old
        srv.shutdown()


def test_roundtrip_s3_and_hdfs(tmp_path, mesh):
    """Sharded checkpoints are backend-agnostic: the same save/restore
    rides the s3:// SigV4 writer (single-PUT at these shard sizes; the
    multipart lifecycle is covered by test_s3) and the hdfs://
    temp+RENAME writer through their hermetic emulators."""
    import os
    import threading
    from http.server import ThreadingHTTPServer

    from tests.test_hdfs_azure import _FakeNameNode, _drop_cached_instances
    from tests.test_s3 import _FakeS3

    x = jnp.arange(64.0).reshape(8, 8)
    sharded = jax.device_put(x, NamedSharding(mesh, P(("pp", "sp"), "tp")))
    tree = {"w": sharded, "b": np.ones(3, np.float32)}

    _FakeS3.store.clear()
    s3srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=s3srv.serve_forever, daemon=True).start()
    _FakeNameNode.store.clear()
    nnsrv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeNameNode)
    threading.Thread(target=nnsrv.serve_forever, daemon=True).start()
    keys = ("DMLC_S3_ENDPOINT", "AWS_ACCESS_KEY_ID",
            "AWS_SECRET_ACCESS_KEY", "AWS_REGION",
            "DMLC_WEBHDFS_ENDPOINT")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ["DMLC_S3_ENDPOINT"] = f"127.0.0.1:{s3srv.server_port}"
    os.environ["AWS_ACCESS_KEY_ID"] = "AKIACKPT"
    os.environ["AWS_SECRET_ACCESS_KEY"] = "ckpt-secret"
    os.environ["AWS_REGION"] = "us-test-1"
    os.environ["DMLC_WEBHDFS_ENDPOINT"] = f"127.0.0.1:{nnsrv.server_port}"
    _drop_cached_instances("s3://", "hdfs://")
    try:
        for uri in ("s3://ckpts/run1/step1", "hdfs://nn/ckpts/step1"):
            save_pytree(uri, tree)
            got = restore_pytree(uri, tree, mesh=mesh)
            np.testing.assert_array_equal(np.asarray(got["w"]),
                                          np.asarray(x))
            np.testing.assert_array_equal(np.asarray(got["b"]), tree["b"])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        _drop_cached_instances("s3://", "hdfs://")
        s3srv.shutdown()
        nnsrv.shutdown()


def test_checkpoint_manager_retention(tmp_path, mesh):
    mgr = CheckpointManager(str(tmp_path / "run"), max_to_keep=2)
    tree = {"w": np.arange(10, dtype=np.float32)}
    assert mgr.latest_step() is None
    for step in (1, 2, 3, 4):
        tree["w"] = tree["w"] + 1
        mgr.save(step, tree)
    assert mgr.latest_step() == 4
    step, got = mgr.restore_latest(tree)
    assert step == 4
    np.testing.assert_array_equal(got["w"], np.arange(10) + 4)
    import os

    kept = sorted(d for d in os.listdir(tmp_path / "run")
                  if d.startswith("step_"))
    assert kept == ["step_00000003", "step_00000004"]


def test_restore_missing_leaf_raises(tmp_path):
    from dmlc_tpu.base import DMLCError

    save_pytree(str(tmp_path / "c"), {"a": np.ones(2)})
    with pytest.raises(DMLCError, match="missing leaf"):
        restore_pytree(str(tmp_path / "c"),
                       {"a": np.ones(2), "zz": np.ones(2)})


def test_restore_with_partial_manifest_multi_host(tmp_path):
    """Multi-host saves: the manifest lists only process-0 shards; restore
    must derive shard filenames deterministically (advisor finding)."""
    import json

    mesh = build_mesh(8, dp=4, sp=2, tp=1, pp=1, ep=1)
    x = jnp.arange(32, dtype=jnp.float32).reshape(8, 4)
    sharded = jax.device_put(
        x, jax.sharding.NamedSharding(mesh, P("dp", None)))
    uri = str(tmp_path / "ckpt")
    save_pytree(uri, {"w": sharded})

    # simulate process-0's view: drop all but one shard from the manifest
    mpath = tmp_path / "ckpt" / "manifest.json"
    man = json.loads(mpath.read_text())
    (key, entry), = man["leaves"].items()
    first = dict(list(entry["shards"].items())[:1])
    assert len(first) < len(entry["shards"])
    entry["shards"] = first
    mpath.write_text(json.dumps(man))

    # mesh restore: callback derives filenames, no manifest lookup
    got = restore_pytree(uri, {"w": x}, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.asarray(x))
    # host restore: directory listing recovers the other processes' shards
    got_host = restore_pytree(uri, {"w": x})
    np.testing.assert_array_equal(got_host["w"], np.asarray(x))


def test_checkpoint_manager_rejects_zero_retention(tmp_path):
    from dmlc_tpu.base import DMLCError

    with pytest.raises(DMLCError):
        CheckpointManager(str(tmp_path), max_to_keep=0)


def test_restore_ignores_stale_shards_when_manifest_covers(tmp_path):
    """Stale shard files from an older differently-sharded save must not
    leak into a restore whose manifest fully covers the array."""
    uri = str(tmp_path / "ckpt2")
    x = np.arange(16, dtype=np.float32).reshape(4, 4)
    save_pytree(uri, {"w": x})
    # plant a stale half-shard from a hypothetical earlier layout
    (tmp_path / "ckpt2" / "w.0-2_0-4").write_bytes(
        np.full((2, 4), -1, np.float32).tobytes())
    got = restore_pytree(uri, {"w": x})
    np.testing.assert_array_equal(got["w"], x)


def test_restore_dot_prefixed_leaf_keys_do_not_collide(tmp_path):
    uri = str(tmp_path / "ckpt3")
    tree = {"w": np.ones((2, 2), np.float32),
            "w.scale": np.full((3,), 2.0, np.float32)}
    save_pytree(uri, tree)
    # force the listing path by pruning both manifests' shard dicts
    import json
    mpath = tmp_path / "ckpt3" / "manifest.json"
    man = json.loads(mpath.read_text())
    for entry in man["leaves"].values():
        entry["shards"] = {}
    mpath.write_text(json.dumps(man))
    got = restore_pytree(uri, tree)
    np.testing.assert_array_equal(got["w"], tree["w"])
    np.testing.assert_array_equal(got["w.scale"], tree["w.scale"])
