"""Cluster flight recorder (ISSUE 3): clock-offset estimation, merged
clock-corrected /trace, structured event log, crash postmortems, and the
monotonic-heartbeat + build-info/staleness-gauge satellites."""

import json
import os
import threading
import time
import urllib.request

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry import (ClockOffsetEstimator, FlightRecorder,
                                TelemetryAggregator, events, postmortem)
from dmlc_tpu.telemetry.clock import offset_from_timestamps


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    telemetry.reset_events()
    yield
    telemetry.reset()
    telemetry.reset_events()
    postmortem.uninstall()


# ---------------------------------------------------------------------------
# clock-offset estimation
# ---------------------------------------------------------------------------

def test_offset_from_timestamps_recovers_known_skew():
    # worker clock runs 5.0s BEHIND the tracker; symmetric 10ms wire
    skew, wire = 5.0, 0.010
    t0 = 1000.0                      # worker clock
    t1 = t0 + skew + wire            # tracker receives
    t2 = t1 + 0.001                  # tracker replies 1ms later
    t3 = t2 - skew + wire            # worker receives
    off, rtt = offset_from_timestamps(t0, t1, t2, t3)
    assert off == pytest.approx(skew, abs=1e-9)
    assert rtt == pytest.approx(2 * wire, abs=1e-9)


def test_offset_exact_even_with_asymmetric_error_bounded_by_rtt():
    # asymmetric path (3ms out, 17ms back): NTP's error bound is rtt/2
    skew = -2.5
    t0 = 50.0
    t1 = t0 + skew + 0.003
    t2 = t1 + 0.0005
    t3 = t2 - skew + 0.017
    off, rtt = offset_from_timestamps(t0, t1, t2, t3)
    assert abs(off - skew) <= rtt / 2 + 1e-12


def test_estimator_prefers_low_rtt_and_windows_out_stale_samples():
    est = ClockOffsetEstimator(window=4)
    est.update(0, offset_s=1.00, rtt_s=0.050)   # loose early sample
    est.update(0, offset_s=1.20, rtt_s=0.002)   # tight: wins
    est.update(0, offset_s=0.90, rtt_s=0.030)
    assert est.offset(0) == pytest.approx(1.20)
    assert est.rtt(0) == pytest.approx(0.002)
    # slide the tight sample out of the window: best follows the window
    for _ in range(4):
        est.update(0, offset_s=2.0, rtt_s=0.010)
    assert est.offset(0) == pytest.approx(2.0)
    # garbage and impossible samples are rejected
    est.update(1, offset_s="nope", rtt_s=0.001)
    est.update(1, offset_s=0.5, rtt_s=-0.001)
    est.update(-1, offset_s=0.5, rtt_s=0.001)
    assert est.offset(1) is None and est.offset(-1) is None
    est.drop(0)
    assert est.offset(0) is None


def test_negative_offset_worker_ahead_of_tracker():
    # worker clock runs 3.2s AHEAD of the tracker: offset must come out
    # negative and the estimator must accept it (only negative RTT is
    # impossible, not negative offset)
    skew, wire = -3.2, 0.004
    t0 = 200.0
    t1 = t0 + skew + wire
    t2 = t1 + 0.0002
    t3 = t2 - skew + wire
    off, rtt = offset_from_timestamps(t0, t1, t2, t3)
    assert off == pytest.approx(skew, abs=1e-9)
    assert rtt > 0
    est = ClockOffsetEstimator()
    est.update(0, offset_s=off, rtt_s=rtt)
    assert est.offset(0) == pytest.approx(skew, abs=1e-9)


def test_equal_rtt_tie_keeps_earlier_sample():
    # two samples with IDENTICAL rtt: min() is stable, so the EARLIER
    # sample stays the estimate — deterministic, and the earlier sample
    # has had longer to prove itself against the window
    est = ClockOffsetEstimator()
    est.update(0, offset_s=1.5, rtt_s=0.010)
    est.update(0, offset_s=9.9, rtt_s=0.010)
    assert est.offset(0) == pytest.approx(1.5)


def test_restart_anchor_change_resets_clock_estimate():
    # a restarted worker ships a NEW anchor; the flight recorder must
    # drop the dead incarnation's clock relation so its lucky low-RTT
    # sample cannot pin the replacement's estimate
    fr = FlightRecorder()
    fr.ingest(0, {"anchor": 100.0, "spans": [],
                  "clock": {"offset_s": 5.0, "rtt_s": 0.0001}})
    assert fr.clock.offset(0) == pytest.approx(5.0)
    fr.ingest(0, {"anchor": 200.0, "spans": [],
                  "clock": {"offset_s": -2.0, "rtt_s": 0.5}})
    # the new (much looser) sample wins because the old estimate died
    # with the old incarnation
    assert fr.clock.offset(0) == pytest.approx(-2.0)
    assert fr.clock.rtt(0) == pytest.approx(0.5)


def test_offset_error_bound_rtt_half_over_asymmetry_sweep():
    # the NTP error bound |est - true| <= rtt/2 must hold for EVERY
    # delay asymmetry, including fully one-sided paths
    skew = 7.75
    for out_ms in (0.0, 0.5, 3.0, 20.0):
        for back_ms in (0.0, 1.0, 9.0, 40.0):
            t0 = 10.0
            t1 = t0 + skew + out_ms / 1e3
            t2 = t1 + 0.0003
            t3 = t2 - skew + back_ms / 1e3
            off, rtt = offset_from_timestamps(t0, t1, t2, t3)
            assert abs(off - skew) <= rtt / 2 + 1e-12, (out_ms, back_ms)


# ---------------------------------------------------------------------------
# flight recorder: merged clock-corrected chrome trace
# ---------------------------------------------------------------------------

def _ship(fr, rank, anchor, offset, names, step_s=1.0, seq0=0):
    spans = [{"name": n, "ts": i * step_s * 1e6, "dur": 1000.0,
              "tid": 7, "seq": seq0 + i + 1, "cat": "t",
              "thread": f"w{rank}"}
             for i, n in enumerate(names)]
    fr.ingest(rank, {
        "anchor": anchor, "seq": seq0 + len(names), "spans": spans,
        "clock": {"offset_s": offset, "rtt_s": 0.001},
    }, host=f"host{rank}")


def test_merged_trace_distinct_pids_and_corrected_monotone_timestamps():
    fr = FlightRecorder()
    # two ranks whose wall clocks disagree by 100s; events REALLY
    # happened interleaved: rank0 at tracker-time 1000+0,2; rank1 at
    # 1000+1,3 (anchor+offset both = 1000 after correction)
    _ship(fr, 0, anchor=1000.0, offset=0.0, names=["a0", "a1"], step_s=2.0)
    _ship(fr, 1, anchor=901.0, offset=100.0, names=["b0", "b1"],
          step_s=2.0)
    doc = fr.to_chrome_trace()
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    pids = {e["pid"] for e in evs}
    assert pids == {1, 2}  # one pid per rank, rank r -> pid r+1
    by_name = {e["name"]: e for e in evs}
    # corrected interleave: a0 < b0 < a1 < b1, each 1s apart
    order = sorted(by_name, key=lambda n: by_name[n]["ts"])
    assert order == ["a0", "b0", "a1", "b1"]
    ts = [by_name[n]["ts"] for n in order]
    assert ts == sorted(ts)
    assert ts[0] == 0.0  # rebased to start at 0
    for a, b in zip(ts, ts[1:]):
        assert b - a == pytest.approx(1e6, rel=1e-6)  # 1s in µs
    # rank metadata rows are present and labeled
    meta = {(e["pid"], e["name"]): e for e in doc["traceEvents"]
            if e["ph"] == "M"}
    assert meta[(1, "process_name")]["args"]["name"] == "rank 0 (host0)"
    assert meta[(2, "process_name")]["args"]["name"] == "rank 1 (host1)"
    assert (2, "thread_name") in meta


def test_merged_trace_within_tolerance_of_true_skew():
    # the estimator's error is bounded by rtt/2: corrected timestamps of
    # simultaneous events on two skewed clocks must land within that
    fr = FlightRecorder()
    true_off0, true_off1 = 3.0, -7.0
    meas_err = 0.004  # 8ms rtt -> ±4ms worst case
    _ship(fr, 0, anchor=500.0 - true_off0, offset=true_off0 + meas_err,
          names=["x"])
    _ship(fr, 1, anchor=500.0 - true_off1, offset=true_off1 - meas_err,
          names=["y"])
    evs = {e["name"]: e for e in fr.to_chrome_trace()["traceEvents"]
           if e["ph"] == "X"}
    # both events happened at tracker-time 500.0 exactly
    dt_us = abs(evs["x"]["ts"] - evs["y"]["ts"])
    assert dt_us <= 2 * meas_err * 1e6 + 1


def test_flight_ingest_dedups_by_seq_and_bounds_per_rank():
    fr = FlightRecorder(max_spans_per_rank=8)
    _ship(fr, 0, anchor=100.0, offset=0.0, names=["s0", "s1"])
    _ship(fr, 0, anchor=100.0, offset=0.0, names=["s0", "s1"])  # re-ship
    assert fr.span_counts()[0] == 2  # dedup'd, not doubled
    _ship(fr, 0, anchor=100.0, offset=0.0,
          names=[f"t{i}" for i in range(20)], seq0=2)
    assert fr.span_counts()[0] == 8  # bounded ring per rank


def test_flight_ingest_restart_resets_rank_store():
    fr = FlightRecorder()
    _ship(fr, 0, anchor=100.0, offset=5.0, names=["old0", "old1"])
    # replacement incarnation: NEW anchor, seq restarts at 1
    _ship(fr, 0, anchor=333.0, offset=0.5, names=["new0"])
    evs = [e["name"] for e in fr.to_chrome_trace()["traceEvents"]
           if e["ph"] == "X"]
    assert evs == ["new0"]  # dead incarnation's spans dropped


def test_remap_ranks_moves_request_rows_without_collision():
    # elastic generation change: rank 1 dies, rank 2 survives as the
    # new rank 1.  Synthetic request-row tids (1<<48 + req_id) and
    # fleet trace_ids name LOGICAL entities and must survive the
    # renumbering verbatim, while the store key (merged-trace pid)
    # moves with the surviving process — no collision with the rank
    # that previously owned the number, no mislabeled rows.
    from dmlc_tpu.telemetry.requests import REQUEST_ROW_TID_BASE

    fr = FlightRecorder()
    for r in (0, 1, 2):
        spans = [{"name": f"req.r{r}", "ts": 1.0, "dur": 5.0,
                  "tid": REQUEST_ROW_TID_BASE + 100 + r, "seq": 1,
                  "cat": "serving", "thread": f"req {100 + r}",
                  "args": {"trace_id": f"{r:032x}"}}]
        fr.ingest(r, {"anchor": 100.0 + r, "spans": spans,
                      "clock": {"offset_s": float(r), "rtt_s": 0.001}},
                  host=f"host{r}")
    fr.remap_ranks({0: 0, 2: 1})
    assert fr.ranks() == [0, 1]

    doc = fr.to_chrome_trace()
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_pid = {e["pid"]: e for e in evs}
    assert set(by_pid) == {1, 2}  # pid = new rank + 1; rank 1 dropped
    # rank 0 untouched; the survivor's row moved intact: same request
    # tid, same trace id, same name — only the process row changed
    assert by_pid[1]["name"] == "req.r0"
    assert by_pid[2]["name"] == "req.r2"
    assert by_pid[2]["tid"] == REQUEST_ROW_TID_BASE + 102
    assert by_pid[2]["args"]["trace_id"] == f"{2:032x}"
    tids = [e["tid"] for e in evs]
    assert len(tids) == len(set((e["pid"], e["tid"]) for e in evs))
    meta = {(e["pid"], e["name"]): e for e in doc["traceEvents"]
            if e["ph"] == "M"}
    assert meta[(2, "process_name")]["args"]["name"] == "rank 1 (host2)"

    # the clock relation travels with the surviving PROCESS (its
    # physical clock did not change when its rank number did)
    assert fr.clock.offset(1) == pytest.approx(2.0)
    assert fr.clock.offset(2) is None

    # seq high-water followed the move: re-shipping the survivor's
    # already-ingested span under its NEW rank id dedups, and its
    # anchor is recognized (no phantom-restart reset)
    fr.ingest(1, {"anchor": 102.0, "spans": [
        {"name": "req.r2", "ts": 1.0, "dur": 5.0,
         "tid": REQUEST_ROW_TID_BASE + 102, "seq": 1,
         "args": {"trace_id": f"{2:032x}"}}]})
    assert fr.span_counts()[1] == 1


def test_flight_ingest_survives_garbage():
    fr = FlightRecorder()
    fr.ingest_json(0, "{not json")
    fr.ingest_json(0, json.dumps({"trace": {"spans": "nope"}}))
    fr.ingest_json(0, json.dumps({"trace": {"anchor": "NaNope",
                                            "spans": []}}))
    fr.ingest_json(1, json.dumps(
        {"trace": {"anchor": 1.0,
                   "spans": [{"bogus": 1}, "str", None,
                             {"name": "ok", "ts": 0.0, "dur": 1.0,
                              "tid": 1, "seq": 1}]}}))
    fr.ingest(-1, {"anchor": 1.0, "spans": []})
    counts = fr.span_counts()
    assert counts.get(1) == 1 and 0 not in counts
    assert json.loads(fr.to_chrome_trace_json())["traceEvents"]


def test_local_spans_ride_along_as_tracker_pid():
    from dmlc_tpu.telemetry.flight import TRACKER_PID

    with telemetry.span("tracker.side", stage="t"):
        pass
    fr = FlightRecorder(local_spans=telemetry.spans)
    _ship(fr, 0, anchor=time.time(), offset=0.0, names=["w"])
    evs = [e for e in fr.to_chrome_trace()["traceEvents"]
           if e["ph"] == "X"]
    assert {e["pid"] for e in evs} == {TRACKER_PID, 1}
    assert any(e["name"] == "tracker.side" and e["pid"] == TRACKER_PID
               for e in evs)


# ---------------------------------------------------------------------------
# span core additions: seq, incremental shipping, open spans
# ---------------------------------------------------------------------------

def test_spans_since_is_incremental_and_bounded():
    with telemetry.span("a"):
        pass
    first, seq1 = telemetry.spans_since(0)
    assert [r["name"] for r in first] == ["a"]
    with telemetry.span("b"):
        pass
    fresh, seq2 = telemetry.spans_since(seq1)
    assert [r["name"] for r in fresh] == ["b"] and seq2 > seq1
    assert telemetry.spans_since(seq2)[0] == []
    for i in range(10):
        with telemetry.span(f"c{i}"):
            pass
    # a truncating limit keeps the OLDEST and hands back a resumable
    # cursor: repeated calls catch up without losing the middle
    capped, cur = telemetry.spans_since(seq2, limit=3)
    assert [r["name"] for r in capped] == ["c0", "c1", "c2"]
    rest, cur = telemetry.spans_since(cur, limit=1000)
    assert [r["name"] for r in rest] == [f"c{i}" for i in range(3, 10)]
    assert telemetry.spans_since(cur)[0] == []


def test_open_spans_sees_inside_of_running_spans():
    ready = threading.Event()
    release = threading.Event()

    def worker():
        with telemetry.span("w.outer", stage="t"):
            with telemetry.span("w.stuck", stage="t", args={"k": 1}):
                ready.set()
                release.wait(5)

    t = threading.Thread(target=worker, name="stuck-worker")
    t.start()
    assert ready.wait(5)
    try:
        opened = {s["name"]: s for s in telemetry.open_spans()}
        assert {"w.outer", "w.stuck"} <= set(opened)
        assert opened["w.stuck"]["depth"] == 1
        assert opened["w.stuck"]["thread"] == "stuck-worker"
        assert opened["w.stuck"]["open_us"] >= 0
        assert opened["w.stuck"]["args"] == {"k": 1}
    finally:
        release.set()
        t.join()
    assert "w.stuck" not in {s["name"] for s in telemetry.open_spans()}


# ---------------------------------------------------------------------------
# event log
# ---------------------------------------------------------------------------

def test_event_log_records_bounded_ordered_jsonl():
    telemetry.record_event("retry", policy="s3", error="timeout")
    telemetry.record_event("fault_injected", site="barrier.x",
                           action="kill")
    tail = telemetry.events_tail(10)
    assert [e["kind"] for e in tail] == ["retry", "fault_injected"]
    assert tail[0]["policy"] == "s3" and tail[0]["seq"] < tail[1]["seq"]
    assert all("t" in e and "mono" in e for e in tail)
    lines = events.to_jsonl(tail).splitlines()
    assert len(lines) == 2 and json.loads(lines[0])["kind"] == "retry"
    cap = events._MAX_EVENTS
    for i in range(cap + 10):
        telemetry.record_event("spam", i=i)
    assert len(events.events()) == cap


def test_resilience_paths_land_in_event_log():
    from dmlc_tpu.resilience import RetryPolicy, fault_point
    from dmlc_tpu.resilience.fault import install_injector, reset_injector

    calls = []
    policy = RetryPolicy(attempts=3, base_s=0.0, jitter=0.0,
                         sleep=lambda s: None, name="evt")
    policy.call(lambda: calls.append(1) or (None if len(calls) > 1
                                            else (_ for _ in ()).throw(
                                                ConnectionError("x"))))
    install_injector("barrier.evt@rank:0=delay:0")
    try:
        fault_point("barrier.evt", rank=0, attempt=0)
    finally:
        reset_injector()
    kinds = [e["kind"] for e in telemetry.events_tail(10)]
    assert "retry" in kinds
    assert "barrier_enter" in kinds
    assert "fault_injected" in kinds


# ---------------------------------------------------------------------------
# postmortem
# ---------------------------------------------------------------------------

def test_postmortem_dump_contains_snapshot_open_spans_and_events(
        tmp_path, monkeypatch):
    monkeypatch.setenv(postmortem.ENV_DIR, str(tmp_path))
    telemetry.inc("train", "steps", 7)
    telemetry.record_event("barrier_enter", site="barrier.z", rank="0")
    with telemetry.span("dying.op", stage="t"):
        path = postmortem.dump("unit test")
    assert path and os.path.exists(path)
    doc = json.load(open(path))
    assert doc["reason"] == "unit test"
    assert doc["telemetry"]["counters"]["train"]["steps"] == 7.0
    assert [s["name"] for s in doc["open_spans"]] == ["dying.op"]
    assert any(e["kind"] == "barrier_enter" for e in doc["events"])
    assert doc["spans"] is not None and "anchor_epoch" in doc
    assert path in postmortem.list_dumps()


def test_postmortem_noop_without_dir(monkeypatch):
    monkeypatch.delenv(postmortem.ENV_DIR, raising=False)
    assert postmortem.dump("nothing") is None
    assert postmortem.install() is False
    assert postmortem.list_dumps() == []


def test_postmortem_excepthook_and_fatal_hook(tmp_path, monkeypatch):
    import sys

    from dmlc_tpu import logging as dlog
    from dmlc_tpu.base import DMLCError

    monkeypatch.setenv(postmortem.ENV_DIR, str(tmp_path))
    assert postmortem.install() is True
    try:
        # the chained excepthook dumps, then defers to the previous hook
        sys.excepthook(ValueError, ValueError("boom"), None)
        dumps = postmortem.list_dumps()
        assert len(dumps) == 1
        assert "ValueError" in json.load(open(dumps[0]))["reason"]
        with pytest.raises(DMLCError):
            dlog.fatal("last words")
        dumps = postmortem.list_dumps()
        assert len(dumps) == 2
        assert "last words" in json.load(open(dumps[-1]))["reason"]
    finally:
        postmortem.uninstall()


def test_fault_injector_kill_dumps_postmortem(tmp_path):
    """The injected-kill path (os._exit, no cleanup) must leave a flight
    record behind — run in a subprocess since it really dies."""
    import subprocess
    import sys

    code = f"""
import os, sys
sys.path.insert(0, {json.dumps(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))})
os.environ["DMLC_POSTMORTEM_DIR"] = {json.dumps(str(tmp_path))}
os.environ["DMLC_FAULT_SPEC"] = "barrier.die=kill:7"
from dmlc_tpu import telemetry
from dmlc_tpu.resilience import fault_point
telemetry.record_event("retry", policy="x")
with telemetry.span("about.to.die", stage="t"):
    fault_point("barrier.die", rank=0)
"""
    p = subprocess.run([sys.executable, "-c", code], timeout=60)
    assert p.returncode == 7
    dumps = postmortem.list_dumps(str(tmp_path))
    assert len(dumps) == 1
    doc = json.load(open(dumps[0]))
    assert "fault.kill" in doc["reason"]
    assert [s["name"] for s in doc["open_spans"]] == ["about.to.die"]
    kinds = [e["kind"] for e in doc["events"]]
    assert kinds == ["retry", "barrier_enter", "fault_injected"]


def test_launcher_collects_postmortems(tmp_path, monkeypatch, caplog):
    import logging as std_logging

    from dmlc_tpu.tracker.launch import collect_postmortems

    caplog.set_level(std_logging.WARNING, logger="dmlc_tpu.tracker")
    monkeypatch.setenv(postmortem.ENV_DIR, str(tmp_path))
    telemetry.record_event("fault_injected", site="barrier.q",
                           action="kill")
    with telemetry.span("mid.flight", stage="t"):
        postmortem.dump("crash A")
    seen: set = set()
    fresh = collect_postmortems(seen, "worker", 1)
    assert len(fresh) == 1
    assert collect_postmortems(seen, "worker", 1) == []  # already seen
    assert telemetry.counters_snapshot()[
        "resilience"]["postmortems_collected"] == 1.0
    rec = [r.message for r in caplog.records if "postmortem" in r.message]
    assert rec and "crash A" in rec[0] and "mid.flight" in rec[0]


# ---------------------------------------------------------------------------
# satellites: monotonic heartbeat ages, build info / staleness gauges
# ---------------------------------------------------------------------------

def test_heartbeat_ages_use_monotonic_clock(monkeypatch):
    agg = TelemetryAggregator()
    agg.update(0, {"counters": {}, "gauges": {}, "histograms": {}})
    # step the WALL clock back an hour: ages must not move — on the old
    # time.time() bookkeeping this produced negative (or, forward-step,
    # mass-dead) ages through the failure detector
    real_monotonic = time.monotonic
    monkeypatch.setattr(
        "dmlc_tpu.telemetry.heartbeat.time.time",
        lambda: real_monotonic() - 3600.0)
    age = agg.ranks()[0]
    assert 0 <= age < 5.0
    agg.touch(0)
    assert 0 <= agg.ranks()[0] <= age + 1.0


def test_prometheus_surface_has_build_info_and_age_gauges():
    import dmlc_tpu

    agg = TelemetryAggregator()
    agg.update(0, {"counters": {"s": {"c": 1.0}}, "gauges": {},
                   "histograms": {}})
    agg.update(3, {"counters": {}, "gauges": {}, "histograms": {}})
    text = agg.prometheus_text()
    assert "# TYPE dmlc_build_info gauge" in text
    assert f'version="{dmlc_tpu.__version__}"' in text
    assert 'platform="' in text
    assert "# TYPE dmlc_heartbeat_age_seconds gauge" in text
    assert 'dmlc_heartbeat_age_seconds{rank="0"}' in text
    assert 'dmlc_heartbeat_age_seconds{rank="3"}' in text


# ---------------------------------------------------------------------------
# end to end: live tracker serves a merged 2-rank /trace
# ---------------------------------------------------------------------------

def test_live_tracker_serves_clock_corrected_merged_trace():
    from dmlc_tpu.telemetry import HeartbeatSender
    from dmlc_tpu.tracker import RabitTracker, TrackerClient

    tracker = RabitTracker("127.0.0.1", 2, metrics_port=0)
    tracker.start(2)
    errors = []

    def work(i):
        try:
            c = TrackerClient("127.0.0.1", tracker.port, jobid=f"tr{i}")
            c.start()
            off, rtt = c.clock_ping()  # same host: offset ~ 0
            assert rtt >= 0 and abs(off) < 60.0
            with telemetry.span(f"work.r{c.rank}", stage="e2e"):
                time.sleep(0.01)
            hb = HeartbeatSender(c, interval=30.0, auto_start=False)
            hb.send_once()
            c.shutdown()
        except Exception as e:  # noqa: BLE001 - surfaced below
            errors.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors, errors
    base = f"http://127.0.0.1:{tracker.metrics_port}"
    doc = json.loads(urllib.request.urlopen(base + "/trace").read())
    hz = json.loads(urllib.request.urlopen(base + "/healthz").read())
    tracker.join(timeout=30)
    tracker.close()
    evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    pids = {e["pid"] for e in evs}
    assert {1, 2} <= pids  # both ranks present under distinct pids
    names = {e["name"] for e in evs}
    assert "work.r0" in names and "work.r1" in names
    ts = sorted(e["ts"] for e in evs)
    assert ts[0] >= 0.0  # rebased, monotone by construction of sort
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any(p.startswith("rank 0") for p in procs)
    assert any(p.startswith("rank 1") for p in procs)
    assert "clock_offsets" in hz
