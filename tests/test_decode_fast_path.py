"""Decode fast path: paged-vs-gather parity, spec-decode bit-parity.

The op-level matrix checks the paged attention op against the model's
dense gather-path window attention on identical cache state — the
1e-5 logits-parity contract, swept where a length matrix is cheapest.
The engine-level tests pin the end-to-end contract instead: greedy
outputs bit-identical with the fast path on, off, and with speculative
decoding enabled, each through a forced preemption episode (the
resume path is where a paged/spec bookkeeping bug would corrupt
output).  The cache tests guard the host-mirror twins the fast path
leans on: freed blocks' bytes never reach a live gather row, and the
batched commit write is byte-equivalent to the per-row writes it
replaced.
"""

import json
import urllib.request

import numpy as np
import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.base import DMLCError
from dmlc_tpu.ops.paged_attention import paged_attention, supports
from dmlc_tpu.serving import (InferenceEngine, PagedKVCache, Request,
                              ServingHTTPServer)


# ---------------------------------------------------------------------------
# op-level parity matrix: paged vs gather window attention
# ---------------------------------------------------------------------------

def _rand(rng, *shape):
    return rng.standard_normal(shape).astype(np.float32)


def _parity_case(rng, *, n_blocks, bs, w, h, d, s_w, lengths):
    """Build one batch of paged state plus its dense gather-path view.

    Returns ``(paged_out, dense_out)`` for the same queries: the paged
    op attends the scattered pool through block tables; the dense path
    is the model's ``_cached_window_attention`` over the gathered view
    with the window riding as a concatenated tail (exactly how the
    gather decode program sees it)."""
    from dmlc_tpu.models.transformer import _cached_window_attention

    b = len(lengths)
    lengths = np.asarray(lengths, np.int32)
    span = w * bs
    k_pool = _rand(rng, n_blocks, bs, h, d)
    v_pool = _rand(rng, n_blocks, bs, h, d)
    # disjoint physical blocks per row (sequences never share blocks),
    # deliberately non-contiguous within each row
    assert n_blocks >= b * w
    tables = rng.permutation(n_blocks)[:b * w].reshape(b, w).astype(np.int32)
    q = _rand(rng, b, s_w, h, d)
    k_new = _rand(rng, b, s_w, h, d)
    v_new = _rand(rng, b, s_w, h, d)
    # paged path: scatter-then-attend at each row's real paged address
    kp, vp = k_pool.copy(), v_pool.copy()
    for i in range(b):
        for s in range(s_w):
            p = int(lengths[i]) + s
            kp[tables[i, p // bs], p % bs] = k_new[i, s]
            vp[tables[i, p // bs], p % bs] = v_new[i, s]
    paged = np.asarray(paged_attention(q, kp, vp, tables, lengths,
                                       impl="lax"))
    # gather path: the PRE-scatter pool is the cache (positions >=
    # length are garbage the mask hides), window as explicit tail
    k_cache = k_pool[tables].reshape(b, span, h, d)
    v_cache = v_pool[tables].reshape(b, span, h, d)
    dense = np.asarray(_cached_window_attention(q, k_new, v_new,
                                                k_cache, v_cache, lengths))
    return paged, dense


@pytest.mark.parametrize("s_w", [1, 3])
def test_paged_vs_gather_parity_matrix(s_w):
    """Single-block, boundary-straddling, and max-length rows in one
    batch: the paged op matches the gather-path oracle to 1e-5."""
    bs, w = 4, 4
    span = w * bs
    lengths = [1, bs - 1, bs, bs + 1, 2 * bs + 1, span - s_w]
    paged, dense = _parity_case(np.random.default_rng(0), n_blocks=24,
                                bs=bs, w=w, h=2, d=8, s_w=s_w,
                                lengths=lengths)
    np.testing.assert_allclose(paged, dense, rtol=1e-5, atol=1e-5)


def test_paged_attention_pallas_interpret_parity():
    """The Pallas kernel (interpret mode on CPU) agrees with the lax
    fallback on supported shapes — same matrix of lengths."""
    bs, w, d = 8, 3, 128
    assert supports(d, bs)
    lengths = [1, bs, bs + 1, w * bs - 1]
    rng = np.random.default_rng(1)
    n_blocks, h, s_w = 6, 1, 1
    k_pool = _rand(rng, n_blocks, bs, h, d)
    v_pool = _rand(rng, n_blocks, bs, h, d)
    tables = np.stack([rng.permutation(n_blocks)[:w]
                       for _ in lengths]).astype(np.int32)
    q = _rand(rng, len(lengths), s_w, h, d)
    lens = np.asarray(lengths, np.int32)
    ref = np.asarray(paged_attention(q, k_pool, v_pool, tables, lens,
                                     impl="lax"))
    got = np.asarray(paged_attention(q, k_pool, v_pool, tables, lens,
                                     impl="pallas", interpret=True))
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)


def test_paged_attention_rejects_unknown_impl():
    z = np.zeros((1, 1, 1, 8), np.float32)
    pool = np.zeros((2, 4, 1, 8), np.float32)
    with pytest.raises(ValueError):
        paged_attention(z, pool, pool, np.zeros((1, 2), np.int32),
                        np.zeros((1,), np.int32), impl="cuda")


# ---------------------------------------------------------------------------
# host-mirror hardening: freed bytes, batched writes
# ---------------------------------------------------------------------------

def _kv(rng, n, *, layers=2, heads=2, dim=3):
    shape = (layers, n, heads, dim)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


def test_gather_never_reads_freed_blocks_bytes():
    """Property: under interleaved alloc/free churn, a live row's valid
    prefix never contains a freed block's bytes.  Every free block is
    poisoned with a sentinel each iteration; any table/gather indexing
    bug that routed a live row through a freed block would surface it."""
    sent = np.float32(12345.0)
    cache = PagedKVCache(2, 2, 3, n_blocks=12, block_size=4)
    rng = np.random.default_rng(11)
    live, sid = {}, 0
    for _ in range(60):
        if live and (len(live) >= 4 or rng.random() < 0.5):
            victim = int(rng.choice(sorted(live)))
            cache.free(victim)
            del live[victim]
        else:
            sid += 1
            n = int(rng.integers(1, 13))
            if cache.allocate(sid, n):
                k, v = _kv(rng, n)
                cache.write(sid, k, v)
                live[sid] = (n, k, v)
        used = set()
        for s in live:
            used.update(cache.block_table(s))
        for blk in set(range(12)) - used:
            cache.k_pool[:, blk] = sent
            cache.v_pool[:, blk] = sent
        if not live:
            continue
        ids = sorted(live)
        pad_len = -(-max(live[s][0] for s in ids) // 4) * 4
        gk, gv, lens = cache.gather(ids, pad_batch=len(ids) + 2,
                                    pad_len=pad_len)
        for row, s in enumerate(ids):
            n, k, v = live[s]
            assert lens[row] == n
            np.testing.assert_array_equal(gk[:, row, :n], k)
            np.testing.assert_array_equal(gv[:, row, :n], v)
        # dead pad rows are zero-filled, never a freed block's bytes
        assert not gk[:, len(ids):].any()
        assert not gv[:, len(ids):].any()


def test_write_many_matches_per_row_writes():
    """The batched commit write (one lock for the whole batch) is
    byte- and bookkeeping-equivalent to per-row appends, including a
    window that straddles a block boundary."""
    a = PagedKVCache(2, 2, 3, n_blocks=8, block_size=4)
    b = PagedKVCache(2, 2, 3, n_blocks=8, block_size=4)
    rng = np.random.default_rng(5)
    prefixes = {1: 3, 2: 5}           # 3+2 straddles a block boundary
    windows = {1: 2, 2: 3}
    init = {s: _kv(np.random.default_rng(s), n)
            for s, n in prefixes.items()}
    for cache in (a, b):
        for s, n in prefixes.items():
            assert cache.allocate(s, n + windows[s])
            cache.write(s, *init[s])
    upd = {s: _kv(rng, n) for s, n in windows.items()}
    for s in prefixes:
        a.write(s, *upd[s])           # append semantics (start=None)
    b.write_many([(s, k, v) for s, (k, v) in upd.items()])
    np.testing.assert_array_equal(a.k_pool, b.k_pool)
    np.testing.assert_array_equal(a.v_pool, b.v_pool)
    for s, n in prefixes.items():
        assert a.length(s) == b.length(s) == n + windows[s]
    assert a.stats() == b.stats()
    # empty batch is a no-op; over-reservation still raises
    b.write_many([])
    k_big, v_big = _kv(rng, 32)
    with pytest.raises(DMLCError):
        b.write_many([(1, k_big, v_big)])


# ---------------------------------------------------------------------------
# engine-level bit-parity (real jitted compute, tiny config)
# ---------------------------------------------------------------------------

def _tiny_model():
    import jax

    from dmlc_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=2, head_dim=8,
                                d_ff=64, n_layers=2, n_experts=1,
                                microbatches=1)
    return tfm.init_params(jax.random.PRNGKey(0), cfg), cfg


def _greedy_oracle(params, cfg, prompt, n):
    from dmlc_tpu.models import transformer as tfm

    ctx = list(prompt)
    for _ in range(n):
        lg, _, _ = tfm.forward_prefill(
            params, np.array([ctx], np.int32), cfg)
        ctx.append(int(np.argmax(np.asarray(lg[0, -1]))))
    return ctx[len(prompt):]


def _run_requests(params, cfg, *, n_blocks=6, max_new=10):
    """3 requests through a pool too small for them to coexist: forces
    preemption + recompute-resume.  Returns their outputs."""
    eng = InferenceEngine(params, cfg, n_blocks=n_blocks, block_size=4,
                          max_active=3, queue_depth=8)
    eng.start()
    try:
        reqs = [eng.submit([i + 1] * 4, max_new_tokens=max_new)
                for i in range(3)]
        for r in reqs:
            assert r.wait(300), f"request {r.id} never finished"
            assert r.error is None
            assert r.n_generated == max_new
        return [list(r.generated) for r in reqs]
    finally:
        eng.close()


def test_paged_on_off_bit_identical_through_preemption(monkeypatch):
    """DMLC_SERVE_PAGED_ATTN=on vs =off produce bit-identical greedy
    output across a preemption episode, and both match the no-cache
    oracle — the fast path is output-invisible end to end."""
    params, cfg = _tiny_model()
    before = telemetry.snapshot()["counters"].get(
        "serving", {}).get("preemptions", 0)
    outs = {}
    for mode in ("on", "off"):
        monkeypatch.setenv("DMLC_SERVE_PAGED_ATTN", mode)
        outs[mode] = _run_requests(params, cfg)
    after = telemetry.snapshot()["counters"]["serving"]["preemptions"]
    assert after > before, "tiny pool must have forced preemption"
    assert outs["on"] == outs["off"]
    for i in range(3):
        assert outs["on"][i] == _greedy_oracle(params, cfg, [i + 1] * 4, 10)


def test_spec_decode_bit_parity_through_preemption(monkeypatch):
    """Speculative decoding (k=3) through the same preemption-forcing
    pool: greedy output stays bit-identical to the oracle, and the
    drafter actually proposed (the accept walk, not drafter silence,
    is what kept the output exact)."""
    params, cfg = _tiny_model()
    monkeypatch.setenv("DMLC_SERVE_SPEC_K", "3")
    monkeypatch.setenv("DMLC_SERVE_SPEC_MIN_CTX", "4")
    snap = telemetry.snapshot()["counters"].get("serving", {})
    before_prop = snap.get("spec_proposed", 0)
    before_pre = snap.get("preemptions", 0)
    outs = _run_requests(params, cfg, max_new=12)
    counters = telemetry.snapshot()["counters"]["serving"]
    assert counters.get("spec_proposed", 0) > before_prop, \
        "drafter never proposed — the spec path was not exercised"
    assert counters["preemptions"] > before_pre
    for i in range(3):
        assert outs[i] == _greedy_oracle(params, cfg, [i + 1] * 4, 12)


def test_ngram_drafter_proposes_from_own_context(monkeypatch):
    monkeypatch.setenv("DMLC_SERVE_SPEC_K", "3")
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=8, block_size=4,
                          max_active=2, queue_depth=4)
    try:
        # rightmost fully-in-prefix occurrence of suffix [3,1,2] is at
        # offset 2, so the drafter replays what followed it
        assert eng._draft_tokens(
            Request([1, 2, 3, 1, 2, 3, 1, 2], 4)) == [3, 1, 2]
        # below DMLC_SERVE_SPEC_MIN_CTX (default 4): no proposal
        assert eng._draft_tokens(Request([1, 2], 4)) == []
        # no recurring suffix anywhere: no proposal
        assert eng._draft_tokens(Request([1, 2, 3, 4, 5, 6, 7], 4)) == []
    finally:
        eng.close()


def test_fast_path_metric_families_registered():
    from dmlc_tpu.telemetry.metric_names import METRIC_NAMES

    for fam in ("dmlc_serving_paged_active",
                "dmlc_serving_paged_decode_steps",
                "dmlc_serving_spec_proposed",
                "dmlc_serving_spec_accepted",
                "dmlc_serving_spec_accept_rate",
                "dmlc_serving_spec_tokens_per_step",
                "dmlc_step_spec_accept_rate_pct"):
        assert fam in METRIC_NAMES, f"{fam} missing from metric registry"


# ---------------------------------------------------------------------------
# loadgen CLI (the out-of-process bench driver)
# ---------------------------------------------------------------------------

def test_loadgen_cli_drives_server_and_emits_summary(capsys):
    from dmlc_tpu.serving.loadgen import _cli

    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    try:
        rc = _cli(["--url", srv.url, "--streams", "2",
                   "--requests-per-stream", "1", "--prompt-len", "2", "4",
                   "--max-tokens", "3", "--vocab", str(cfg.vocab)])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert doc["n_requests_ok"] == 2 and doc["n_requests_failed"] == 0
        assert doc["failures"] == []
        # the server really served them
        reqs = json.loads(urllib.request.urlopen(
            srv.url + "/requests", timeout=30).read())
        assert reqs["summary"]["requests_done"] >= 2
    finally:
        srv.close()
        eng.close()
