"""Data-layer tests (mirror reference libsvm_parser_test.cc,
csv_parser_test.cc, libfm_parser_test.cc, dataiter_test.cc and the
RowBlockContainer save/load round trip)."""

import numpy as np
import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.data import (
    CSVParserParam,
    RowBlockContainer,
    create_parser,
    create_row_iter,
)
from dmlc_tpu.io.stream import MemoryBytesStream


LIBSVM_SAMPLE = b"""1 0:0.5 3:1.2 7:-4
0 1:2 2:3.5
1 4:1
0
1:0.5 5:1.5
"""


def write(tmp_path, name, data):
    p = tmp_path / name
    p.write_bytes(data)
    return str(p)


# ---------- libsvm ------------------------------------------------------

def test_libsvm_basic(tmp_path):
    uri = write(tmp_path, "a.libsvm", LIBSVM_SAMPLE)
    it = create_row_iter(uri, 0, 1, "libsvm")
    blocks = list(it)
    assert len(blocks) == 1
    b = blocks[0]
    assert b.size == 5
    np.testing.assert_allclose(b.label, [1, 0, 1, 0, 1])
    r0 = b[0]
    np.testing.assert_array_equal(r0.index, [0, 3, 7])
    np.testing.assert_allclose(r0.value, [0.5, 1.2, -4])
    assert b[3].length == 0          # empty row
    assert it.num_col() == 8


def test_libsvm_implicit_value_one(tmp_path):
    uri = write(tmp_path, "b.libsvm", b"1 3 5:2.5\n")
    (blk,) = list(create_row_iter(uri, 0, 1, "libsvm"))
    r = blk[0]
    np.testing.assert_array_equal(r.index, [3, 5])
    np.testing.assert_allclose(r.value, [1.0, 2.5])


def test_libsvm_instance_weight(tmp_path):
    uri = write(tmp_path, "w.libsvm", b"1:0.25 0:1\n0:2.0 1:1\n")
    (blk,) = list(create_row_iter(uri, 0, 1, "libsvm"))
    np.testing.assert_allclose(blk.weight, [0.25, 2.0])
    np.testing.assert_allclose(blk.label, [1, 0])


def test_libsvm_partitions_cover(tmp_path):
    lines = [
        (f"{i % 2} " + " ".join(f"{j}:{i * 0.1 + j}" for j in range(i % 5))).strip()
        for i in range(100)
    ]
    uri = write(tmp_path, "part.libsvm", ("\n".join(lines) + "\n").encode())
    total = 0
    labels = []
    for part in range(3):
        parser = create_parser(uri, part, 3, "libsvm")
        for blk in parser:
            total += blk.size
            labels.extend(blk.label.tolist())
    assert total == 100
    np.testing.assert_allclose(labels, [i % 2 for i in range(100)])


def test_libsvm_sdot():
    c = RowBlockContainer()
    c.push(1.0, [0, 2], [2.0, 3.0])
    blk = c.get_block()
    w = np.array([1.0, 10.0, 100.0], dtype=np.float32)
    assert blk[0].sdot(w) == pytest.approx(302.0)


# ---------- csv ---------------------------------------------------------

def test_csv_with_label_column(tmp_path):
    uri = write(tmp_path, "c.csv", b"1,0.5,2.5\n0,1.5,3.5\n")
    it = create_row_iter(uri + "?format=csv&label_column=0", 0, 1, "auto")
    (blk,) = list(it)
    np.testing.assert_allclose(blk.label, [1, 0])
    np.testing.assert_allclose(blk[0].value, [0.5, 2.5])
    np.testing.assert_array_equal(blk[0].index, [0, 1])
    assert it.num_col() == 2


def test_csv_no_label(tmp_path):
    uri = write(tmp_path, "d.csv", b"1.5,2.5\n3.5,4.5\n")
    (blk,) = list(create_row_iter(uri, 0, 1, "csv"))
    np.testing.assert_allclose(blk.label, [0, 0])
    np.testing.assert_allclose(blk[1].value, [3.5, 4.5])


def test_csv_param_validation():
    p = CSVParserParam()
    p.init({"label_column": "2"})
    assert p.label_column == 2


def test_csv_inconsistent_columns_raises(tmp_path):
    uri = write(tmp_path, "bad.csv", b"1,2\n3\n")
    with pytest.raises((DMLCError, ValueError)):
        list(create_row_iter(uri, 0, 1, "csv"))


def test_csv_ragged_with_coincident_token_count_raises(tmp_path):
    # 6 tokens == 3 lines * 2 cols: the flat fast path must not silently
    # reassign cells across row boundaries (regression)
    uri = write(tmp_path, "bad2.csv", b"1,2\n3,4,5\n6\n")
    with pytest.raises((DMLCError, ValueError)):
        list(create_row_iter(uri, 0, 1, "csv"))


def test_csv_non_numeric_cell_raises_framework_error(tmp_path):
    uri = write(tmp_path, "bad3.csv", b"1,abc\n2,3\n")
    with pytest.raises((DMLCError, ValueError)):
        list(create_row_iter(uri, 0, 1, "csv"))


# ---------- libfm -------------------------------------------------------

def test_libfm(tmp_path):
    uri = write(tmp_path, "e.libfm", b"1 2:3:0.5 4:7:1.5\n0 1:0:2\n")
    (blk,) = list(create_row_iter(uri, 0, 1, "libfm"))
    np.testing.assert_allclose(blk.label, [1, 0])
    r0 = blk[0]
    np.testing.assert_array_equal(r0.field, [2, 4])
    np.testing.assert_array_equal(r0.index, [3, 7])
    np.testing.assert_allclose(r0.value, [0.5, 1.5])


def test_libfm_bad_triple(tmp_path):
    uri = write(tmp_path, "bad.libfm", b"1 2:3\n")
    with pytest.raises(DMLCError):
        list(create_parser(uri, 0, 1, "libfm", threaded=False))


# ---------- factory -----------------------------------------------------

def test_auto_format_defaults_to_libsvm(tmp_path):
    uri = write(tmp_path, "f.txt", b"1 0:1\n")
    (blk,) = list(create_parser(uri, 0, 1, "auto"))
    assert blk.size == 1


def test_unknown_format(tmp_path):
    uri = write(tmp_path, "g.txt", b"x\n")
    with pytest.raises(DMLCError, match="unknown data format"):
        create_parser(uri, 0, 1, "parquet")


# ---------- RowBlock mechanics -----------------------------------------

def test_rowblock_slice_and_memcost():
    c = RowBlockContainer()
    for i in range(10):
        c.push(float(i), [i, i + 1], [1.0, 2.0])
    blk = c.get_block()
    s = blk.slice(2, 5)
    assert s.size == 3
    np.testing.assert_allclose(s.label, [2, 3, 4])
    np.testing.assert_array_equal(s[0].index, [2, 3])
    assert blk.mem_cost_bytes() > 0
    assert c.max_index == 10


def test_rowblock_container_save_load_roundtrip():
    c = RowBlockContainer()
    c.push(1.0, [1, 5], [0.5, 1.5], weight=2.0)
    c.push(0.0, [2], [3.0], weight=1.0)
    s = MemoryBytesStream()
    c.save(s)
    s.seek(0)
    d = RowBlockContainer()
    assert d.load(s)
    assert d.offset == c.offset
    np.testing.assert_allclose(d.label, c.label)
    np.testing.assert_allclose(d.value, c.value)
    assert d.max_index == c.max_index
    assert not d.load(s)  # clean EOF


# ---------- disk row iter ----------------------------------------------

def test_disk_row_iter_cache(tmp_path):
    lines = "\n".join(f"{i % 2} 0:{i} 1:{i * 2}" for i in range(50)) + "\n"
    base = write(tmp_path, "h.libsvm", lines.encode())
    cache = str(tmp_path / "h.cache")
    it = create_row_iter(base + "#" + cache, 0, 1, "libsvm")
    import os

    epoch1 = [blk.label.tolist() for blk in it]
    assert os.path.exists(cache)  # num_parts==1: no .splitN.partI suffix
    epoch2 = [blk.label.tolist() for blk in it]
    assert epoch1 == epoch2
    assert sum(len(x) for x in epoch1) == 50
    assert it.num_col() == 2
    it.close()


def test_disk_row_iter_reuses_existing_cache(tmp_path):
    lines = "\n".join(f"1 0:{i}" for i in range(20)) + "\n"
    base = write(tmp_path, "i.libsvm", lines.encode())
    cache = str(tmp_path / "i.cache")
    it1 = create_row_iter(base + "#" + cache, 0, 1, "libsvm")
    n1 = sum(blk.size for blk in it1)
    it1.close()
    # second iter must load from cache (delete source to prove it)
    import os

    os.remove(base)
    it2 = create_row_iter(base + "#" + cache, 0, 1, "libsvm")
    n2 = sum(blk.size for blk in it2)
    assert n1 == n2 == 20
    it2.close()


def test_csv_tab_delimiter_falls_back(tmp_path):
    # whitespace delimiters must keep working (native gate falls back to
    # the Python path, which handles any single-byte delimiter)
    from dmlc_tpu.data.text_parsers import CSVParser
    from dmlc_tpu.io import input_split

    uri = write(tmp_path, "t.tsv", b"1\t2.5\n3\t4.5\n")
    split = input_split.create(uri, 0, 1, "text")
    parser = CSVParser(split, {"delimiter": "\t"})
    containers = parser.parse_next()
    blk = containers[0].get_block()
    np.testing.assert_allclose(blk[0].value, [1, 2.5])
    np.testing.assert_allclose(blk[1].value, [3, 4.5])
    parser.close()


# ---------- native parallel chunk parse (text_parser.h:89-118 analog) ----

def _collect_blocks(uri, fmt, nthread, **kw):
    parser = create_parser(uri, type=fmt, threaded=False, nthread=nthread, **kw)
    rows = []
    for blk in parser:
        for i in range(blk.size):
            row = blk[i]
            rows.append((row.label, row.weight,
                         tuple(row.index.tolist()),
                         tuple(np.asarray(row.value).tolist()) if row.value is not None else None))
    if hasattr(parser, "close"):
        parser.close()
    return rows


@pytest.mark.parametrize("fmt,sample", [
    ("libsvm", None),
    ("csv", b"1.0,2.0,3.0\n4.0,5.0,6.0\n7.5,8.5,9.5\n" * 50),
    ("libfm", b"1 1:3:0.5 2:7:1.5\n0 1:2:2.0\n" * 70),
])
def test_parse_nthread_identical_output(tmp_path, fmt, sample):
    if sample is None:
        import random
        rng = random.Random(7)
        lines = []
        for i in range(500):
            feats = " ".join(
                f"{rng.randrange(0, 100)}:{rng.uniform(-5, 5):.4f}"
                for _ in range(rng.randrange(0, 12))
            )
            lines.append(f"{rng.randrange(0, 2)} {feats}".strip())
        sample = ("\n".join(lines) + "\n").encode()
    p = write(tmp_path, f"data.{fmt}", sample)
    one = _collect_blocks(p, fmt, nthread=1)
    four = _collect_blocks(p, fmt, nthread=4)
    assert len(one) > 0
    assert one == four
