"""Memory pools (dmlc_tpu/memory.py — reference memory.h:22-261 role)."""

import threading

import numpy as np
import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.memory import BufferPool, MemoryPool, ThreadLocalPool
from dmlc_tpu.io.stream import Stream


def test_memory_pool_recycles_and_arenas():
    pool = MemoryPool(128, arena_objects=4)
    bufs = [pool.alloc() for _ in range(6)]  # spans two arenas
    assert all(b.nbytes == 128 for b in bufs)
    # distinct live buffers never alias
    for i, a in enumerate(bufs):
        a[:] = i
    for i, a in enumerate(bufs):
        assert (np.asarray(a) == i).all()
    for b in bufs:
        pool.free(b)
    again = [pool.alloc() for _ in range(6)]
    assert pool.recycled >= 6  # all served from the freelist
    del again
    with pytest.raises(DMLCError):
        pool.free(np.empty(64, np.uint8))


def test_buffer_pool_size_classes_and_bound():
    pool = BufferPool(max_bytes=1 << 20)
    a = pool.acquire(1000)
    assert a.nbytes == 1024  # next power of two
    pool.release(a)
    b = pool.acquire(900)    # same class: must be the recycled buffer
    assert b is a
    assert pool.hits == 1
    # the retention bound drops overflow instead of pinning memory
    big = [pool.acquire(512 << 10) for _ in range(4)]
    for x in big:
        pool.release(x)
    assert pool.held_bytes <= 1 << 20


def test_buffer_pool_rejects_foreign_buffers():
    """Only whole owning uint8 arrays come back: a foreign dtype would
    be handed out by a later acquire(), and a sliced view would pin its
    whole base array while held_bytes counts just the slice."""
    pool = BufferPool()
    pool.release(np.zeros(128, np.float64))     # 1024 bytes, wrong dtype
    assert pool.held_bytes == 0
    base = np.zeros(1 << 20, np.uint8)
    pool.release(base[:64])                     # view: would pin 1 MB
    assert pool.held_bytes == 0
    pool.release(np.zeros((32, 32), np.uint8))  # 2-D
    assert pool.held_bytes == 0
    got = pool.acquire(1000)
    assert got.dtype == np.uint8 and got.ndim == 1


def test_buffer_pool_thread_safety():
    pool = BufferPool()
    errors = []

    def work():
        try:
            for _ in range(200):
                buf = pool.acquire(4096)
                buf[:8] = 7
                pool.release(buf)
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    assert pool.hits + pool.misses == 8 * 200


def test_thread_local_pool_isolated_per_thread():
    tlp = ThreadLocalPool()
    main_buf = tlp.acquire(2048)
    tlp.release(main_buf)
    seen = {}

    def work():
        b = tlp.acquire(2048)
        seen["other"] = b is main_buf  # different thread: different pool
        tlp.release(b)

    t = threading.Thread(target=work)
    t.start()
    t.join()
    assert seen["other"] is False
    assert tlp.acquire(2048) is main_buf  # same thread: recycled


def test_stream_as_file_text_and_binary(tmp_path):
    """The dmlc::ostream/istream role: Python's io stack over any
    Stream/URI — csv/json/line-iteration consumers work unchanged."""
    import csv
    import json

    path = str(tmp_path / "t.csv")
    with Stream.create(path, "w") as s:
        f = s.as_file("w")
        w = csv.writer(f)
        w.writerow(["a", "b"])
        w.writerow([1, 2])
        f.close()  # flushes; close_stream=False leaves s open
        s.write(b"3,4\n")
    with Stream.create_for_read(path).as_file("r") as f:
        rows = list(csv.reader(f))
    assert rows == [["a", "b"], ["1", "2"], ["3", "4"]]

    jpath = str(tmp_path / "t.json")
    with Stream.create(jpath, "w") as s:
        f = s.as_file("w")
        json.dump({"k": [1, 2, 3]}, f)
        f.close()  # explicit: flush must not depend on refcount timing
    got = json.load(Stream.create_for_read(jpath).as_file("r"))
    assert got == {"k": [1, 2, 3]}


def test_stream_as_file_seek(tmp_path):
    path = str(tmp_path / "b.bin")
    with Stream.create(path, "w") as s:
        s.write(bytes(range(100)))
    f = Stream.create_for_read(path).as_file("rb", close_stream=True)
    assert f.read(3) == b"\x00\x01\x02"
    f.seek(50)
    assert f.read(2) == b"\x32\x33"
    assert f.tell() == 52
    f.close()
