"""Fleet router: health circuit, least-loaded routing, idempotent
retry/failover, hedging, drain shift, and honest backpressure.

The router is pure HTTP policy (no jax), so the replicas here are
scriptable stand-ins whose behavior flips per phase (ok / die / slow /
saturated / draining) — deterministic and millisecond-fast.  The real
engine-under-router path is covered end to end by
``scripts/fleet_smoke.py`` (CI stage 12) and the engine-side dedupe
tests in test_serving.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.resilience import fault
from dmlc_tpu.serving.router import (DOWN, DRAINING, HEALTHY, Router,
                                     RouterHTTPServer, TenantGovernor,
                                     discover_replicas,
                                     parse_tenant_weights)


class FakeReplica:
    """Scriptable replica endpoint: ``mode`` flips its behavior."""

    def __init__(self, name):
        self.name = name
        self.mode = "ok"        # ok | die | slow | s429 | s503drain
        self.slow_s = 0.8
        self.draining = False
        self.waiting = 0
        self.hits = []          # request_ids seen on /generate
        self._lock = threading.Lock()
        fake = self

        class H(BaseHTTPRequestHandler):
            def _send(self, code, doc, headers=None):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path != "/healthz" or fake.mode == "die":
                    self.connection.close()
                    return
                self._send(200, {
                    "status": "ok", "active": 0,
                    "waiting": fake.waiting, "max_active": 4,
                    "draining": fake.draining,
                    "requests": {"live_requests": fake.waiting,
                                 "live_waiting": fake.waiting}})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                with fake._lock:
                    fake.hits.append(doc.get("request_id"))
                if fake.mode == "die":
                    self.connection.close()
                    return
                if fake.mode == "slow":
                    time.sleep(fake.slow_s)
                if fake.mode == "s429":
                    self._send(429, {"error": "admission queue full"},
                               {"Retry-After": "1"})
                elif fake.mode == "s503drain":
                    self._send(503, {"error": "server draining"},
                               {"Retry-After": "5"})
                else:
                    self._send(200, {"state": "done",
                                     "output_ids": [1, 2, 3],
                                     "n_generated": 3,
                                     "served": fake.name,
                                     "ttft_s": 0.01,
                                     "request_id": doc.get("request_id")})

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def fleet():
    a, b = FakeReplica("a"), FakeReplica("b")
    r = Router([a.url, b.url], health_interval_s=0.05, probe_base_s=0.05,
               probe_max_s=0.5, retries=3, dispatch_timeout_s=5.0,
               request_timeout_s=10.0, start_health_thread=False)
    r.poll_once()
    try:
        yield a, b, r
    finally:
        r.close()
        a.close()
        b.close()


def _load(router, url, depth):
    """Pin a replica's polled queue depth (placement steering)."""
    with router._lock:
        for rep in router.replicas:
            if rep.url == url:
                rep.queue_depth = depth


def _counters():
    return telemetry.counters_snapshot().get("router", {})


# ---------------------------------------------------------------------------
# placement + health
# ---------------------------------------------------------------------------

def test_routes_least_loaded_and_carries_request_id(fleet):
    a, b, r = fleet
    _load(r, a.url, 5)  # a busier -> b must win
    code, doc, _ = r.route({"prompt": [1, 2], "max_tokens": 2})
    assert code == 200 and doc["served"] == "b"
    assert doc["served_by"] == b.url
    # an idempotency key was minted and forwarded
    assert b.hits and isinstance(b.hits[-1], str) and b.hits[-1]
    assert doc["request_id"] == b.hits[-1]
    # a client-supplied key is forwarded verbatim
    code, doc, _ = r.route({"prompt": [1], "request_id": "my-key"})
    assert code == 200 and doc["request_id"] == "my-key"
    assert "my-key" in (a.hits + b.hits)


def test_idle_live_waiting_zero_overrides_stale_iteration_depth(fleet):
    """live_waiting == 0 is a real idle reading: a stale nonzero
    decode_queue_depth from the last iteration record must not repel
    traffic from an idle replica."""
    a, b, r = fleet
    # hand the router a healthz doc shaped like an idle replica whose
    # last decode iteration still says waiting=3
    rep = next(x for x in r.replicas if x.url == a.url)
    r._mark_alive(rep, {"active": 0, "waiting": 0, "max_active": 4,
                        "draining": False,
                        "requests": {"live_requests": 0,
                                     "live_waiting": 0,
                                     "decode_queue_depth": 3}})
    assert rep.queue_depth == 0
    # an OLDER replica without live_waiting still falls back
    r._mark_alive(rep, {"active": 0, "waiting": 0, "max_active": 4,
                        "requests": {"decode_queue_depth": 3}})
    assert rep.queue_depth == 3


def test_health_poll_marks_down_and_circuit_reprobes(fleet):
    a, b, r = fleet
    a.mode = "die"
    r.poll_once()
    states = {v["url"]: v["state"] for v in r.replica_views()}
    assert states[a.url] == DOWN and states[b.url] == HEALTHY
    down_total = _counters().get("replica_down_total", 0)
    # circuit open: an immediate re-poll must NOT probe a again
    hits_before = len(a.hits)
    r.poll_once()
    assert _counters().get("replica_down_total", 0) == down_total
    # backoff expires -> probe -> recovery closes the circuit
    a.mode = "ok"
    time.sleep(0.08)
    r.poll_once()
    assert r.counts()[HEALTHY] == 2
    assert len(a.hits) == hits_before  # probes hit /healthz, not /generate


def test_probe_backoff_grows_exponentially(fleet):
    a, b, r = fleet
    a.mode = "die"
    r.poll_once()
    rep = next(x for x in r.replicas if x.url == a.url)
    first = rep.next_probe_t - time.monotonic()
    time.sleep(0.08)
    r.poll_once()  # second failed probe doubles the backoff
    second = rep.next_probe_t - time.monotonic()
    assert second > first
    assert rep.fail_streak >= 2


# ---------------------------------------------------------------------------
# retry / failover
# ---------------------------------------------------------------------------

def test_failover_on_dead_replica_is_client_invisible(fleet):
    a, b, r = fleet
    before = _counters().get("failovers_total", 0)
    a.mode = "die"
    _load(r, b.url, 10)  # steer the primary dispatch onto dead a
    code, doc, _ = r.route({"prompt": [1], "request_id": "fo-1"})
    assert code == 200 and doc["served"] == "b"
    assert _counters()["failovers_total"] == before + 1
    # the retry reused the SAME idempotency key
    assert a.hits[-1] == "fo-1" and b.hits[-1] == "fo-1"
    # and the dead replica's circuit opened passively (no poll needed)
    assert next(x for x in r.replicas if x.url == a.url).state == DOWN


def test_dispatch_timeout_retries_without_opening_circuit(fleet):
    """Slow is not dead: a dispatch timeout retries elsewhere but must
    NOT mark the replica down (the health prober owns liveness) and
    must not count as a failover."""
    a, b, r = fleet
    r.dispatch_timeout_s = 0.2
    a.mode = "slow"
    a.slow_s = 1.0  # outlives the dispatch timeout
    _load(r, b.url, 10)  # primary goes to slow a
    before = _counters().get("failovers_total", 0)
    code, doc, _ = r.route({"prompt": [1]})
    assert code == 200 and doc["served"] == "b"
    assert next(x for x in r.replicas if x.url == a.url).state == HEALTHY
    assert _counters().get("failovers_total", 0) == before


def test_no_new_dispatch_into_a_sliver_of_deadline(fleet):
    """A retry launched into <1s of remaining deadline would be a
    guaranteed timeout: the router gives up cleanly instead of
    poisoning a replica with doomed work."""
    a, b, r = fleet
    a.mode = b.mode = "slow"
    a.slow_s = b.slow_s = 5.0
    r.request_timeout_s = 0.8  # below the launch floor after t0
    code, doc, _ = r.route({"prompt": [1]})
    assert code == 503 and "deadline" in doc["error"]
    # only the primary dispatch ever launched
    assert len(a.hits) + len(b.hits) == 1


def test_injected_dispatch_fault_drives_retry(fleet):
    """The router.dispatch fault site: an armed error rule simulates a
    torn dispatch and the retry path absorbs it deterministically."""
    a, b, r = fleet
    fault.install_injector(f"router.dispatch@replica:{a.url}=error::1")
    try:
        _load(r, b.url, 10)  # primary goes to a, whose dispatch is torn
        code, doc, _ = r.route({"prompt": [1]})
        assert code == 200 and doc["served"] == "b"
    finally:
        fault.reset_injector()


def test_client_errors_pass_through_without_retry():
    # a 400 is deterministic on any replica: the router must hand it
    # straight back instead of burning retries on it
    c = FakeReplica("c")

    def do_post_400(handler_self):
        body = json.dumps({"error": "bad request: boom"}).encode()
        handler_self.send_response(400)
        handler_self.send_header("Content-Length", str(len(body)))
        handler_self.end_headers()
        handler_self.wfile.write(body)

    c.httpd.RequestHandlerClass.do_POST = do_post_400
    r2 = Router([c.url], retries=3, request_timeout_s=5.0,
                start_health_thread=False)
    try:
        code, doc, _ = r2.route({"prompt": "bad"})
        assert code == 400 and "bad request" in doc["error"]
    finally:
        r2.close()
        c.close()


def test_all_replicas_down_yields_503_with_retry_after(fleet):
    a, b, r = fleet
    a.mode = b.mode = "die"
    r.poll_once()
    assert r.counts()[DOWN] == 2
    code, doc, headers = r.route({"prompt": [1]})
    assert code == 503 and "Retry-After" in headers
    assert "no healthy replica" in doc["error"]


# ---------------------------------------------------------------------------
# backpressure + drain
# ---------------------------------------------------------------------------

def test_all_saturated_yields_429_with_aggregate_retry_after(fleet):
    a, b, r = fleet
    a.mode = b.mode = "s429"
    before = _counters().get("rejected_busy", 0)
    code, doc, headers = r.route({"prompt": [1]})
    assert code == 429
    assert "saturated" in doc["error"]
    assert int(headers["Retry-After"]) >= 1
    assert _counters()["rejected_busy"] == before + 1
    # both replicas were tried before giving up
    assert a.hits and b.hits


def test_retry_after_scales_with_aggregate_queue_depth(fleet):
    a, b, r = fleet
    for _ in range(4):  # pin the service-time evidence
        r._record_latency(0.5)
    shallow = r.retry_after_s()
    _load(r, a.url, 300)
    _load(r, b.url, 300)
    with r._lock:
        for rep in r.replicas:
            rep.live = 300
    deep = r.retry_after_s()
    assert deep > shallow
    assert 1 <= shallow <= 60 and 1 <= deep <= 60


def test_draining_replica_sheds_traffic(fleet):
    a, b, r = fleet
    a.draining = True
    r.poll_once()
    assert r.counts() == {HEALTHY: 1, DOWN: 0, DRAINING: 1}
    for _ in range(4):
        code, doc, _ = r.route({"prompt": [1]})
        assert code == 200 and doc["served"] == "b"
    # a 503-draining answer ALSO flips the state without a poll
    a.draining = False
    r.poll_once()
    a.mode = "s503drain"
    _load(r, b.url, 10)
    before = _counters().get("drain_shifts", 0)
    code, doc, _ = r.route({"prompt": [1]})
    assert code == 200 and doc["served"] == "b"
    assert _counters()["drain_shifts"] == before + 1
    assert next(x for x in r.replicas if x.url == a.url).state == DRAINING


# ---------------------------------------------------------------------------
# hedging
# ---------------------------------------------------------------------------

def test_hedge_fires_after_p99_mult_and_first_wins(fleet):
    a, b, r = fleet
    r.hedge_after_p99_mult = 3.0
    r.hedge_min_samples = 4
    assert r.hedge_after_s() is None  # no evidence yet: hedging armed off
    for _ in range(6):
        assert r.route({"prompt": [1]})[0] == 200
    threshold = r.hedge_after_s()
    assert threshold is not None and threshold < 0.5
    a.mode = "slow"  # tail request: primary outlives the threshold
    _load(r, a.url, 0)
    _load(r, b.url, 5)
    before = _counters().get("hedge_wins", 0)
    t0 = time.monotonic()
    code, doc, _ = r.route({"prompt": [1], "request_id": "hedge-1"})
    assert code == 200 and doc["served"] == "b"
    assert time.monotonic() - t0 < a.slow_s  # did not wait out the tail
    assert _counters()["hedge_wins"] == before + 1
    # both replicas saw the SAME idempotency key (no double-serving:
    # the client got exactly one response; the loser was abandoned)
    assert a.hits[-1] == "hedge-1" and b.hits[-1] == "hedge-1"


def test_hedge_disabled_by_default(fleet):
    a, b, r = fleet
    assert r.hedge_after_p99_mult == 0.0
    for _ in range(20):
        r._record_latency(0.01)
    assert r.hedge_after_s() is None


# ---------------------------------------------------------------------------
# HTTP surface + discovery + exposition
# ---------------------------------------------------------------------------

def test_router_http_surface(fleet):
    a, b, r = fleet
    srv = RouterHTTPServer(r, port=0)
    try:
        req = urllib.request.Request(
            srv.url + "/generate",
            data=json.dumps({"prompt": [1, 2]}).encode(),
            headers={"Content-Type": "application/json"})
        doc = json.loads(urllib.request.urlopen(req, timeout=10).read())
        assert doc["state"] == "done" and doc["served_by"] in (a.url,
                                                               b.url)
        hz = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=5).read())
        assert hz["status"] == "ok" and hz["healthy"] == 2
        assert len(hz["replicas"]) == 2
        reps = json.loads(urllib.request.urlopen(
            srv.url + "/replicas", timeout=5).read())
        assert {v["url"] for v in reps} == {a.url, b.url}
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/generate", data=b"{bad json",
                headers={"Content-Type": "application/json"}),
                timeout=5)
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(urllib.request.Request(
                srv.url + "/generate",
                data=json.dumps({"prompt": [1],
                                 "request_id": 7}).encode()), timeout=5)
        assert e.value.code == 400  # non-string idempotency key
        text = urllib.request.urlopen(
            srv.url + "/metrics", timeout=5).read().decode()
        from dmlc_tpu.telemetry.exporters import validate_exposition_text

        validate_exposition_text(text)
        for fam in ("dmlc_router_requests", "dmlc_router_dispatches",
                    "dmlc_router_replicas_healthy",
                    "dmlc_router_replica_health",
                    "dmlc_router_replica_queue_depth",
                    "dmlc_router_http_200"):
            assert fam in text, f"{fam} missing from router /metrics"
        assert f'replica="{a.url}"' in text
    finally:
        srv.close()


def test_discover_replicas_from_tracker_job_map(monkeypatch):
    from dmlc_tpu.tracker import client as tclient

    def fake_hostmap(self):
        return {"gen": 0, "world": 3,
                "hosts": {"0": ["10.0.0.1", 4000],
                          "2": ["10.0.0.2", 4002],
                          "1": ["10.0.0.1", 4001]}}

    monkeypatch.setattr(tclient.TrackerClient, "_query_hostmap",
                        fake_hostmap)
    urls = discover_replicas("10.0.0.9", 9091, 8901)
    assert urls == ["http://10.0.0.1:8901", "http://10.0.0.1:8902",
                    "http://10.0.0.2:8903"]


def test_router_rejects_empty_or_duplicate_fleets():
    with pytest.raises(ValueError):
        Router([])
    with pytest.raises(ValueError):
        Router(["http://h:1", "http://h:1/"],
               start_health_thread=False)


# ---------------------------------------------------------------------------
# per-tenant fairness (TenantGovernor)
# ---------------------------------------------------------------------------

def test_parse_tenant_weights_skips_malformed_entries():
    assert parse_tenant_weights(None) == {}
    assert parse_tenant_weights("") == {}
    assert parse_tenant_weights("paid=4, free=1") == {
        "paid": 4.0, "free": 1.0}
    # malformed entries are dropped, valid ones survive
    assert parse_tenant_weights(
        "paid=4,broken,=2,neg=-1,zero=0,free=nan3,ok=2") == {
        "paid": 4.0, "ok": 2.0}


def test_tenant_governor_accounting_only_by_default():
    g = TenantGovernor(rate=0.0, burst_s=10.0)
    for _ in range(500):
        admitted, retry = g.admit("anyone")
        assert admitted and retry == 0.0
    by_name = {v["tenant"]: v for v in g.views()}
    assert by_name["anyone"]["requests"] == 500
    assert by_name["anyone"]["admitted"] == 500
    assert by_name["anyone"]["rejected"] == 0
    assert g.stats()["enforcing"] is False


def test_tenant_governor_weighted_rejection_and_honest_retry_after():
    g = TenantGovernor(rate=1.0, burst_s=2.0,
                       weights={"paid": 4.0, "free": 1.0})
    t0 = 1000.0
    # drain free's bucket at one instant (burst = 1*1*2 = 2 tokens)
    n_ok = 0
    while g.admit("free", now=t0)[0]:
        n_ok += 1
    assert n_ok == 2
    admitted, retry = g.admit("free", now=t0)
    assert not admitted
    # honest Retry-After: free refills at 1 token/s, bucket is empty
    assert retry == pytest.approx(1.0, abs=0.05)
    # paid's bucket (burst 8, fill 4/s) is untouched by free's storm
    assert g.admit("paid", now=t0)[0]
    # after 0.5 s free has half a token → retry is the remaining half
    admitted, retry = g.admit("free", now=t0 + 0.5)
    assert not admitted and retry == pytest.approx(0.5, abs=0.05)
    # a full second later one token is available again
    assert g.admit("free", now=t0 + 1.6)[0]
    by_name = {v["tenant"]: v for v in g.views()}
    assert by_name["free"]["rejected"] == 3  # loop exit + 2 probes
    assert by_name["paid"]["rejected"] == 0


def test_tenant_governor_retry_after_is_clamped():
    g = TenantGovernor(rate=0.001, burst_s=1000.0, default_weight=1.0)
    t0 = 0.0
    while g.admit("slow", now=t0)[0]:
        pass
    admitted, retry = g.admit("slow", now=t0)
    # 1 token at 0.001/s would be 1000 s — clamped to the 60 s cap
    assert not admitted and retry == 60.0


def test_tenant_governor_overflow_folds_unknown_tenants():
    g = TenantGovernor(rate=0.0, max_tenants=2,
                       weights={"vip": 4.0})
    g.admit("t1")
    g.admit("t2")
    for i in range(10):
        g.admit(f"minted-{i}")   # hostile key minting
    # configured tenants always get their own bucket, even past the cap
    g.admit("vip")
    by_name = {v["tenant"]: v for v in g.views()}
    assert set(by_name) == {"t1", "t2", TenantGovernor.OVERFLOW, "vip"}
    assert by_name[TenantGovernor.OVERFLOW]["requests"] == 10
    assert by_name["vip"]["weight"] == 4.0


def test_tenant_governor_prometheus_text_is_strict_and_labeled():
    from dmlc_tpu.telemetry.exporters import validate_exposition_text

    g = TenantGovernor(rate=1.0, burst_s=2.0, weights={"paid": 4.0})
    assert g.prometheus_text() == ""   # no tenants yet → no families
    g.admit("paid")
    g.admit("free")
    g.observe_completion("paid", 7)
    text = g.prometheus_text()
    validate_exposition_text(text)
    assert 'dmlc_tenant_requests_total{tenant="paid"} 1' in text
    assert 'dmlc_tenant_tokens_generated_total{tenant="paid"} 7' in text
    assert 'dmlc_tenant_weight{tenant="paid"} 4.0' in text
    assert 'dmlc_tenant_weight{tenant="free"} 1.0' in text


# ---------------------------------------------------------------------------
# dynamic registry (the autoscaler's surface)
# ---------------------------------------------------------------------------

def test_dynamic_registry_add_remove_and_draining(fleet):
    a, b, r = fleet
    c = FakeReplica("c")
    try:
        rep = r.add_replica(c.url)
        assert rep.state == HEALTHY          # optimistic until next sweep
        assert len(r.replica_views()) == 3
        with pytest.raises(ValueError):
            r.add_replica(c.url + "/")       # duplicate is a caller bug
        assert r.set_draining(c.url)
        assert r.counts()[DRAINING] == 1
        # DRAINING sheds new placement to the remaining healthy pair
        for i in range(6):
            code, out, _ = r.route({"prompt": [1], "request_id": f"d{i}"})
            assert code == 200 and out["served"] in ("a", "b")
        assert not c.hits
        assert r.remove_replica(c.url)
        assert len(r.replica_views()) == 2
        assert not r.remove_replica(c.url)   # already gone → False
        assert not r.set_draining("http://nowhere:1")
        cnt = _counters()
        assert cnt.get("replicas_added", 0) >= 1
        assert cnt.get("replicas_removed", 0) >= 1
    finally:
        c.close()


def test_utilization_tracks_live_load_over_capacity(fleet):
    a, b, r = fleet
    assert r.utilization() == 0.0
    a.waiting = 6                     # live_requests=6 over 2×4 slots
    r.poll_once()
    assert r.utilization() == pytest.approx(6 / 8)
    b.mode = "die"                    # DOWN capacity leaves the pool
    r.poll_once()
    assert r.utilization() == pytest.approx(6 / 4)


# ---------------------------------------------------------------------------
# HTTP tenant gate + /fleet endpoint
# ---------------------------------------------------------------------------

class _FakeFleetSource:
    """Stands in for the Autoscaler on the router's HTTP surface."""

    def report(self):
        return {"replicas": 2, "owned": [], "saturated": False}

    def prometheus_text(self):
        return ("# HELP dmlc_fleet_replicas replicas the router routes to\n"
                "# TYPE dmlc_fleet_replicas gauge\n"
                "dmlc_fleet_replicas 2\n")


def test_http_tenant_gate_and_fleet_endpoint():
    a = FakeReplica("a")
    gov = TenantGovernor(rate=1.0, burst_s=1.0,
                         weights={"paid": 100.0, "free": 1.0})
    r = Router([a.url], health_interval_s=3600, retries=2,
               dispatch_timeout_s=5.0, request_timeout_s=10.0,
               tenants=gov, start_health_thread=False)
    r.poll_once()
    fleet_src = _FakeFleetSource()
    srv = RouterHTTPServer(r, port=0, fleet_source=lambda: fleet_src)

    def post(doc):
        req = urllib.request.Request(
            srv.url + "/generate", data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"})
        return json.loads(urllib.request.urlopen(req, timeout=10).read())

    try:
        # free's bucket holds one token (burst floor): 1 admit, then 429
        doc = post({"prompt": [1, 2], "tenant": "free"})
        assert doc["state"] == "done"
        with pytest.raises(urllib.error.HTTPError) as e:
            post({"prompt": [1, 2], "tenant": "free"})
        assert e.value.code == 429
        assert float(e.value.headers["Retry-After"]) >= 0.1
        body = json.loads(e.value.read())
        assert body["tenant"] == "free" and "over budget" in body["error"]
        # paid rides its own bucket, unaffected by free's rejection
        for i in range(3):
            assert post({"prompt": [1], "tenant": "paid",
                         "request_id": f"p{i}"})["state"] == "done"
        # invalid tenant keys are 400s, not silent folds
        for bad in (42, "", "x" * 65):
            with pytest.raises(urllib.error.HTTPError) as e:
                post({"prompt": [1], "tenant": bad})
            assert e.value.code == 400
        # completion accounting flowed back per tenant (3 tokens/req)
        by_name = {v["tenant"]: v for v in gov.views()}
        assert by_name["paid"]["tokens_generated"] == 9
        assert by_name["free"]["tokens_generated"] == 3
        # /fleet renders the fleet_source report, augmented with the
        # per-replica availability shipped on health polls (None here:
        # FakeReplica's /healthz carries no availability ledger)
        fl = json.loads(urllib.request.urlopen(
            srv.url + "/fleet", timeout=5).read())
        assert fl.pop("replica_availability") == {a.url: None}
        assert fl == {"replicas": 2, "owned": [], "saturated": False}
        # /metrics concatenates router + tenant + fleet families
        from dmlc_tpu.telemetry.exporters import validate_exposition_text

        text = urllib.request.urlopen(
            srv.url + "/metrics", timeout=5).read().decode()
        validate_exposition_text(text)
        assert 'dmlc_tenant_rejected_total{tenant="free"} 1' in text
        assert "dmlc_fleet_replicas 2" in text
    finally:
        srv.close()
        r.close()
        a.close()
