"""Native-consumer collective C ABI (cpp/dmlc_collective.{h,cc}).

Builds libdmlc_collective.so + the pure-C driver and runs it under the
real local launcher + tracker, proving a C program with zero
NCCL/MPI/Python dependency can rendezvous and allreduce through the
DMLC env contract — the substrate role the reference played for
XGBoost/rabit (SURVEY.md §7 step 9).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "dmlc_tpu", "cpp")


@pytest.fixture(scope="module")
def driver(tmp_path_factory):
    work = tmp_path_factory.mktemp("collective")
    lib = str(work / "libdmlc_collective.so")
    exe = str(work / "test_collective")
    r = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
         os.path.join(CPP, "dmlc_collective.cc"), "-o", lib],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    # the driver is plain C, compiled with a C compiler: proves ABI purity
    r = subprocess.run(
        ["gcc", "-O2", "-std=c99", "-I", CPP,
         os.path.join(CPP, "test_collective.c"),
         lib, "-o", exe, "-lm", f"-Wl,-rpath,{work}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return exe


@pytest.mark.parametrize("world", [1, 2, 5, 8])
def test_c_driver_collectives_under_local_launcher(driver, world):
    env = os.environ.copy()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, "-m", "dmlc_tpu.tracker.submit",
         "--cluster", "local", "--num-workers", str(world), "--", driver],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "FAIL" not in r.stderr
    # every rank logged through the tracker print relay
    for rank in range(world):
        assert f"rank {rank}/{world}: collective ABI OK" in r.stderr, r.stderr
