"""Native-consumer collective C ABI (cpp/dmlc_collective.{h,cc}).

Builds libdmlc_collective.so + the pure-C driver and runs it under the
real local launcher + tracker, proving a C program with zero
NCCL/MPI/Python dependency can rendezvous and allreduce through the
DMLC env contract — the substrate role the reference played for
XGBoost/rabit (SURVEY.md §7 step 9).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "dmlc_tpu", "cpp")


@pytest.fixture(scope="module")
def collective_lib(tmp_path_factory):
    """One shared libdmlc_collective.so build for every C consumer."""
    work = tmp_path_factory.mktemp("collective")
    lib = str(work / "libdmlc_collective.so")
    # -lrt: shm_open lives in librt on glibc < 2.34 (a no-op stub after)
    r = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
         os.path.join(CPP, "dmlc_collective.cc"), "-o", lib, "-lrt"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return lib


def _build_c_consumer(lib, src, exe):
    # plain C, compiled with a C compiler: proves ABI purity
    r = subprocess.run(
        ["gcc", "-O2", "-std=c99", "-I", CPP, src, lib, "-o", exe,
         "-lm", "-lrt", f"-Wl,-rpath,{os.path.dirname(lib)}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return exe


@pytest.fixture(scope="module")
def driver(collective_lib):
    return _build_c_consumer(
        collective_lib, os.path.join(CPP, "test_collective.c"),
        os.path.join(os.path.dirname(collective_lib), "test_collective"))


@pytest.fixture(scope="module")
def gbdt(collective_lib):
    """BASELINE config #4 consumer: hist-GBDT with dmlc_comm_allreduce
    as the only transport (the XGBoost drop-in role)."""
    return _build_c_consumer(
        collective_lib, os.path.join(REPO, "examples", "gbdt_allreduce.c"),
        os.path.join(os.path.dirname(collective_lib), "gbdt_allreduce"))


def _submit(args, env=None, timeout=180):
    """Run dmlc-submit with the repo importable; returns CompletedProcess
    after asserting a clean exit and no worker-side FAIL lines."""
    penv = os.environ.copy()
    penv["PYTHONPATH"] = REPO + os.pathsep + penv.get("PYTHONPATH", "")
    penv.update(env or {})
    r = subprocess.run(
        [sys.executable, "-m", "dmlc_tpu.tracker.submit",
         "--cluster", "local", *args],
        capture_output=True, text=True, timeout=timeout, env=penv, cwd=REPO)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "FAIL" not in r.stderr
    return r


def _run_gbdt(exe, world):
    r = _submit(["--num-workers", str(world), "--", exe])
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("gbdt rmse="))
    return float(line.split("rmse=")[1].split()[0])


def test_gbdt_allreduce_matches_single_process(gbdt):
    """Training through the distributed transport must reproduce the
    single-process model: same deterministic dataset, histograms
    allreduced instead of locally summed."""
    single = _run_gbdt(gbdt, 1)
    multi = _run_gbdt(gbdt, 4)
    assert single < 0.3, single          # the model actually learned
    # fp reduction order differs between tree-allreduce and a local sum
    assert abs(multi - single) < 1e-4 * max(single, 1e-9), (single, multi)


@pytest.mark.parametrize("env", [
    {"DMLC_COLL_SHM": "1"},            # shm, default 512 KB chunks
    {"DMLC_COLL_SHM": "1",
     "DMLC_COLL_SHM_CHUNK_KB": "4"},   # shm, heavy multi-chunk + parity
    {"DMLC_COLL_SHM": "0"},            # TCP tree/ring fallback
])
def test_randomized_mixed_op_stress(driver, env):
    """Every rank derives the same random op/size/root sequence from a
    broadcast seed: 40 rounds of mixed f64 allreduce / rotating-root
    broadcast / allgather at sizes up to ~1.5 MB — slot reuse across op
    types and announce-slot parity flips, the shm generation
    discipline's hardest inputs."""
    r = _submit(["--num-workers", "4", "--max-attempts", "1",
                 "--host-ip", "127.0.0.1", "--", driver, "stress", "40"],
                env=env)
    assert "stress OK rounds=40 world=4" in r.stdout, r.stdout


@pytest.fixture(scope="module")
def kv_ps(collective_lib):
    """PS KV role-model consumer: worker/server/scheduler in one binary
    (reference env contract, tracker.py:336-386)."""
    return _build_c_consumer(
        collective_lib, os.path.join(REPO, "examples", "kv_ps_worker.c"),
        os.path.join(os.path.dirname(collective_lib), "kv_ps_worker"))


@pytest.mark.parametrize("workers,servers", [(1, 1), (3, 2)])
def test_kv_parameter_server_end_to_end(kv_ps, workers, servers):
    """dmlc-submit --num-servers launches scheduler + servers + workers;
    each worker pushes per-rank vectors, then pulls with the full PS
    clock (min_pushes = workers) and must read the exact cross-worker
    sum on every key/slot."""
    r = _submit(["--num-workers", str(workers), "--num-servers",
                 str(servers), "--max-attempts", "1",
                 "--host-ip", "127.0.0.1", "--", kv_ps], timeout=120)
    for rank in range(workers):
        assert f"kv OK rank={rank} workers={workers}" in r.stdout, r.stdout


@pytest.mark.parametrize("world", [1, 2, 5, 8])
@pytest.mark.parametrize("shm", ["1", "0"])
def test_c_driver_collectives_under_local_launcher(driver, world, shm):
    """Both transports: the same-host shared-memory fast path (default
    on a local gang) and the TCP tree/ring fallback (DMLC_COLL_SHM=0 —
    what cross-host links ride)."""
    r = _submit(["--num-workers", str(world), "--", driver],
                env={"DMLC_COLL_SHM": shm}, timeout=120)
    # every rank logged through the tracker print relay
    for rank in range(world):
        assert f"rank {rank}/{world}: collective ABI OK" in r.stderr, r.stderr
