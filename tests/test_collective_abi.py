"""Native-consumer collective C ABI (cpp/dmlc_collective.{h,cc}).

Builds libdmlc_collective.so + the pure-C driver and runs it under the
real local launcher + tracker, proving a C program with zero
NCCL/MPI/Python dependency can rendezvous and allreduce through the
DMLC env contract — the substrate role the reference played for
XGBoost/rabit (SURVEY.md §7 step 9).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CPP = os.path.join(REPO, "dmlc_tpu", "cpp")


@pytest.fixture(scope="module")
def collective_lib(tmp_path_factory):
    """One shared libdmlc_collective.so build for every C consumer."""
    work = tmp_path_factory.mktemp("collective")
    lib = str(work / "libdmlc_collective.so")
    # -lrt: shm_open lives in librt on glibc < 2.34 (a no-op stub after)
    r = subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
         os.path.join(CPP, "dmlc_collective.cc"), "-o", lib, "-lrt"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return lib


def _build_c_consumer(lib, src, exe):
    # plain C, compiled with a C compiler: proves ABI purity
    r = subprocess.run(
        ["gcc", "-O2", "-std=c99", "-I", CPP, src, lib, "-o", exe,
         "-lm", "-lrt", f"-Wl,-rpath,{os.path.dirname(lib)}"],
        capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    return exe


@pytest.fixture(scope="module")
def driver(collective_lib):
    return _build_c_consumer(
        collective_lib, os.path.join(CPP, "test_collective.c"),
        os.path.join(os.path.dirname(collective_lib), "test_collective"))


@pytest.fixture(scope="module")
def gbdt(collective_lib):
    """BASELINE config #4 consumer: hist-GBDT with dmlc_comm_allreduce
    as the only transport (the XGBoost drop-in role)."""
    return _build_c_consumer(
        collective_lib, os.path.join(REPO, "examples", "gbdt_allreduce.c"),
        os.path.join(os.path.dirname(collective_lib), "gbdt_allreduce"))


def _submit(args, env=None, timeout=180):
    """Run dmlc-submit with the repo importable; returns CompletedProcess
    after asserting a clean exit and no worker-side FAIL lines."""
    penv = os.environ.copy()
    penv["PYTHONPATH"] = REPO + os.pathsep + penv.get("PYTHONPATH", "")
    penv.update(env or {})
    r = subprocess.run(
        [sys.executable, "-m", "dmlc_tpu.tracker.submit",
         "--cluster", "local", *args],
        capture_output=True, text=True, timeout=timeout, env=penv, cwd=REPO)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "FAIL" not in r.stderr
    return r


def _run_gbdt(exe, world):
    r = _submit(["--num-workers", str(world), "--", exe])
    line = next(ln for ln in r.stdout.splitlines()
                if ln.startswith("gbdt rmse="))
    return float(line.split("rmse=")[1].split()[0])


def test_gbdt_allreduce_matches_single_process(gbdt):
    """Training through the distributed transport must reproduce the
    single-process model: same deterministic dataset, histograms
    allreduced instead of locally summed."""
    single = _run_gbdt(gbdt, 1)
    multi = _run_gbdt(gbdt, 4)
    assert single < 0.3, single          # the model actually learned
    # fp reduction order differs between tree-allreduce and a local sum
    assert abs(multi - single) < 1e-4 * max(single, 1e-9), (single, multi)


@pytest.mark.parametrize("env", [
    {"DMLC_COLL_SHM": "1"},            # shm, default 512 KB chunks
    {"DMLC_COLL_SHM": "1",
     "DMLC_COLL_SHM_CHUNK_KB": "4"},   # shm, heavy multi-chunk + parity
    {"DMLC_COLL_SHM": "0"},            # TCP tree/ring fallback
])
def test_randomized_mixed_op_stress(driver, env):
    """Every rank derives the same random op/size/root sequence from a
    broadcast seed: 40 rounds of mixed f64 allreduce / rotating-root
    broadcast / allgather at sizes up to ~1.5 MB — slot reuse across op
    types and announce-slot parity flips, the shm generation
    discipline's hardest inputs."""
    r = _submit(["--num-workers", "4", "--max-attempts", "1",
                 "--host-ip", "127.0.0.1", "--", driver, "stress", "40"],
                env=env)
    assert "stress OK rounds=40 world=4" in r.stdout, r.stdout


@pytest.fixture(scope="module")
def kv_ps(collective_lib):
    """PS KV role-model consumer: worker/server/scheduler in one binary
    (reference env contract, tracker.py:336-386)."""
    return _build_c_consumer(
        collective_lib, os.path.join(REPO, "examples", "kv_ps_worker.c"),
        os.path.join(os.path.dirname(collective_lib), "kv_ps_worker"))


@pytest.mark.parametrize("workers,servers", [(1, 1), (3, 2)])
def test_kv_parameter_server_end_to_end(kv_ps, workers, servers):
    """dmlc-submit --num-servers launches scheduler + servers + workers;
    each worker pushes per-rank vectors, then pulls with the full PS
    clock (min_pushes = workers) and must read the exact cross-worker
    sum on every key/slot."""
    r = _submit(["--num-workers", str(workers), "--num-servers",
                 str(servers), "--max-attempts", "1",
                 "--host-ip", "127.0.0.1", "--", kv_ps], timeout=120)
    for rank in range(workers):
        assert f"kv OK rank={rank} workers={workers}" in r.stdout, r.stdout


@pytest.mark.parametrize("world", [1, 2, 5, 8])
@pytest.mark.parametrize("shm", ["1", "0"])
def test_c_driver_collectives_under_local_launcher(driver, world, shm):
    """Both transports: the same-host shared-memory fast path (default
    on a local gang) and the TCP tree/ring fallback (DMLC_COLL_SHM=0 —
    what cross-host links ride)."""
    r = _submit(["--num-workers", str(world), "--", driver],
                env={"DMLC_COLL_SHM": shm}, timeout=120)
    # every rank logged through the tracker print relay
    for rank in range(world):
        assert f"rank {rank}/{world}: collective ABI OK" in r.stderr, r.stderr


# ---------------------------------------------------------------------------
# Standalone shm collective group (dmlc_shm_coll_*): the intra-host leg
# of the hierarchical allreduce, driven through the ctypes binding
# across REAL processes sharing one segment
# ---------------------------------------------------------------------------

def _shm_group_child(name, rank, world, q):
    import numpy as np

    from dmlc_tpu.native.shm_collective import ShmCollective

    try:
        g = ShmCollective(name, rank, world)
        out = {}
        for dtype in (np.float32, np.float64, np.int32, np.int64):
            arr = (np.arange(1000).astype(dtype) % 97) * (rank + 1)
            g.reduce_scatter(arr, "sum")
            g.allgather(arr)
            out[f"sum_{np.dtype(dtype).name}"] = arr
        arr = np.arange(1000, dtype=np.float32) + rank
        g.allreduce(arr, "max")
        out["max"] = arr
        arr = np.arange(1000, dtype=np.float32) + rank
        g.allreduce(arr, "min")
        out["min"] = arr
        b = (np.full(257, rank, np.float64) if rank != 1
             else np.arange(257, dtype=np.float64))
        g.broadcast(b, root=1)
        out["bcast"] = b
        g.close()
        q.put((rank, out))
    except BaseException as e:  # noqa: BLE001 - surfaced by the parent
        q.put((rank, e))


@pytest.mark.parametrize("world", [2, 3, 5])
def test_shm_group_collectives_across_processes(world):
    import multiprocessing as mp

    import numpy as np

    from dmlc_tpu.native import shm_collective as shmc

    if not shmc.available():
        pytest.skip("native collective library unavailable")
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    name = f"dmlc-test-grp-{os.getpid()}-{world}"
    procs = [ctx.Process(target=_shm_group_child,
                         args=(name, r, world, q)) for r in range(world)]
    for p in procs:
        p.start()
    results = {}
    for _ in range(world):
        rank, out = q.get(timeout=90)
        assert not isinstance(out, BaseException), (rank, out)
        results[rank] = out
    for p in procs:
        p.join(30)
    scale = world * (world + 1) // 2
    for rank, out in results.items():
        for dtype in ("float32", "float64", "int32", "int64"):
            want = ((np.arange(1000) % 97) * scale).astype(dtype)
            np.testing.assert_array_equal(out[f"sum_{dtype}"], want,
                                          err_msg=f"{rank} {dtype}")
        np.testing.assert_array_equal(
            out["max"], np.arange(1000, dtype=np.float32) + world - 1)
        np.testing.assert_array_equal(
            out["min"], np.arange(1000, dtype=np.float32))
        np.testing.assert_array_equal(
            out["bcast"], np.arange(257, dtype=np.float64))


def _shm_abort_child(name, rank, q):
    import numpy as np

    from dmlc_tpu.native.shm_collective import ShmCollective, ShmGroupError

    try:
        g = ShmCollective(name, rank, 2)
        if rank == 1:
            # never participate: poison the group instead, then vanish
            g.abort()
            g.close()
            q.put((rank, "aborted"))
            return
        try:
            g.allreduce(np.ones(64, np.float32), "sum")
            q.put((rank, "unexpected success"))
        except ShmGroupError:
            q.put((rank, "woke"))
        g.close()
    except BaseException as e:  # noqa: BLE001
        q.put((rank, e))


def test_shm_group_abort_wakes_blocked_peer():
    """abort() is the shm analog of tearing TCP links: a peer blocked
    in a collective must error out promptly instead of spinning to the
    full DMLC_COLL_SHM_TIMEOUT_S."""
    import multiprocessing as mp
    import time

    from dmlc_tpu.native import shm_collective as shmc

    if not shmc.available():
        pytest.skip("native collective library unavailable")
    ctx = mp.get_context("fork")
    q = ctx.Queue()
    name = f"dmlc-test-abort-{os.getpid()}"
    procs = [ctx.Process(target=_shm_abort_child, args=(name, r, q))
             for r in range(2)]
    t0 = time.monotonic()
    for p in procs:
        p.start()
    results = dict(q.get(timeout=60) for _ in range(2))
    for p in procs:
        p.join(30)
    assert results[1] == "aborted" and results[0] == "woke", results
    assert time.monotonic() - t0 < 30, "abort did not wake the peer"
