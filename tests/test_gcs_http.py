"""GCS + HTTP backends against a local in-process emulator.

The reference tests S3 by hand against live buckets (test/README.md);
here the resumable-upload/ranged-GET protocol is exercised hermetically:
a stdlib HTTP server implements the slice of the GCS JSON API the
backend uses, and the SAME InputSplit/Stream code paths run over gs://
URIs — including byte-range partitioned reads.
"""

import json
import os
import re
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from dmlc_tpu.io import input_split
from dmlc_tpu.io.stream import Stream
from dmlc_tpu.io.uri import URI


class _FakeGCS(BaseHTTPRequestHandler):
    store = {}       # (bucket, name) -> bytes
    sessions = {}    # sid -> {bucket, name, data}
    _sid = [0]
    # fault injection: every data-bearing session PUT fails once with 500
    # BEFORE committing (client must recover via the 308-range probe)
    fail_each_put = False
    _failed_once = set()  # (sid, declared_start) already failed

    def log_message(self, *a):  # quiet
        pass

    def _json(self, obj, code=200):
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("Content-Type", "application/json")
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        m = re.match(r"^/upload/storage/v1/b/([^/]+)/o$", u.path)
        if m and q.get("uploadType") == ["resumable"]:
            self._sid[0] += 1
            sid = str(self._sid[0])
            self.sessions[sid] = {
                "bucket": m.group(1),
                "name": q["name"][0],
                "data": bytearray(),
            }
            self.send_response(200)
            host = self.headers.get("Host")
            self.send_header("Location", f"http://{host}/session/{sid}")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_error(404)

    def do_PUT(self):
        m = re.match(r"^/session/(\d+)$", self.path)
        if not m or m.group(1) not in self.sessions:
            self.send_error(404)
            return
        sid = m.group(1)
        sess = self.sessions[sid]
        n = int(self.headers.get("Content-Length", "0"))
        body = self.rfile.read(n)
        crange = self.headers.get("Content-Range", "")
        m2 = re.match(r"^bytes (\d+)-(\d+)/", crange)
        if body and self.fail_each_put:
            key = (sid, m2.group(1) if m2 else crange)
            if key not in self._failed_once:
                self._failed_once.add(key)
                self.send_error(500, "injected transient failure")
                return
        if m2:
            declared = int(m2.group(1))
            committed = len(sess["data"])
            if declared > committed:
                self.send_error(400, "Content-Range offset gap")
                return
            if declared < committed:  # overlap resend: drop known bytes
                body = body[committed - declared:]
        if body:
            sess["data"] += body
        if crange.endswith("/*"):  # intermediate chunk or status query
            self.send_response(308)
            if sess["data"]:
                self.send_header("Range", f"bytes=0-{len(sess['data']) - 1}")
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        # final chunk: commit the object
        self.store[(sess["bucket"], sess["name"])] = bytes(sess["data"])
        self._json({"name": sess["name"], "size": str(len(sess["data"]))})

    def do_DELETE(self):
        m = re.match(r"^/session/(\d+)$", self.path)
        if m and m.group(1) in self.sessions:
            del self.sessions[m.group(1)]
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_error(404)

    def do_HEAD(self):
        self.do_GET(head=True)

    def do_GET(self, head=False):
        u = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(u.query)
        m = re.match(r"^/download/storage/v1/b/([^/]+)/o/(.+)$", u.path)
        if m:  # media download (with Range)
            key = (m.group(1), urllib.parse.unquote(m.group(2)))
            if key not in self.store:
                self.send_error(404)
                return
            data = self.store[key]
            rng = self.headers.get("Range")
            code = 200
            if rng:
                lo, hi = rng.split("=")[1].split("-")
                data = data[int(lo): int(hi) + 1]
                code = 206
            self.send_response(code)
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            if not head:
                self.wfile.write(data)
            return
        m = re.match(r"^/storage/v1/b/([^/]+)/o/(.+)$", u.path)
        if m:  # stat
            key = (m.group(1), urllib.parse.unquote(m.group(2)))
            if key not in self.store:
                self.send_error(404)
                return
            self._json({"name": key[1], "size": str(len(self.store[key]))})
            return
        m = re.match(r"^/storage/v1/b/([^/]+)/o$", u.path)
        if m:  # list
            bucket = m.group(1)
            prefix = q.get("prefix", [""])[0]
            delim = q.get("delimiter", [None])[0]
            items, prefixes = [], set()
            for (b, name), data in sorted(self.store.items()):
                if b != bucket or not name.startswith(prefix):
                    continue
                rest = name[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim)[0] + delim)
                else:
                    items.append({"name": name, "size": str(len(data))})
            self._json({"items": items, "prefixes": sorted(prefixes)})
            return
        self.send_error(404)


@pytest.fixture(scope="module")
def gcs_server():
    _FakeGCS.store.clear()
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeGCS)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    old = os.environ.get("STORAGE_EMULATOR_HOST")
    os.environ["STORAGE_EMULATOR_HOST"] = f"127.0.0.1:{srv.server_port}"
    yield srv
    if old is None:
        os.environ.pop("STORAGE_EMULATOR_HOST", None)
    else:
        os.environ["STORAGE_EMULATOR_HOST"] = old
    srv.shutdown()


def test_gcs_write_read_roundtrip(gcs_server):
    payload = bytes(np.random.default_rng(0).integers(0, 256, 300_000,
                                                      dtype=np.uint8))
    # small buffer forces multiple resumable chunk PUTs
    os.environ["DMLC_GCS_WRITE_BUFFER_MB"] = "1"
    try:
        with Stream.create("gs://bkt/dir/blob.bin", "w") as s:
            for lo in range(0, len(payload), 70_000):
                s.write(payload[lo: lo + 70_000])
    finally:
        os.environ.pop("DMLC_GCS_WRITE_BUFFER_MB")
    strm = Stream.create_for_read("gs://bkt/dir/blob.bin")
    got = strm.read(len(payload) + 10)
    assert got == payload
    strm.seek(100_000)
    assert strm.read(16) == payload[100_000:100_016]


def test_gcs_stat_and_list(gcs_server):
    from dmlc_tpu.io.filesys import FileSystem

    with Stream.create("gs://bkt/dir/a.txt", "w") as s:
        s.write(b"hello")
    with Stream.create("gs://bkt/dir/sub/b.txt", "w") as s:
        s.write(b"world!")
    fs = FileSystem.get_instance(URI("gs://bkt/dir"))
    info = fs.get_path_info(URI("gs://bkt/dir/a.txt"))
    assert info.size == 5
    entries = fs.list_directory(URI("gs://bkt/dir"))
    names = {e.path.name.lstrip("/"): e.type for e in entries}
    assert names.get("dir/a.txt") == "file"
    assert any(v == "directory" for v in names.values())
    rec = fs.list_directory_recursive(URI("gs://bkt/dir"))
    assert sum(e.size for e in rec) >= 11


def test_inputsplit_over_gcs(gcs_server):
    # partitioned text reads over gs:// exercise the same ResetPartition/
    # seam logic as local files (BASELINE north star: shard straight from
    # object storage)
    lines = [f"{i} line-{i}" for i in range(200)]
    with Stream.create("gs://bkt/data/part.txt", "w") as s:
        s.write(("\n".join(lines) + "\n").encode())
    got = []
    for part in range(3):
        sp = input_split.create("gs://bkt/data/part.txt", part, 3, "text")
        got += [bytes(r).decode() for r in sp]
        sp.close()
    assert sorted(got) == sorted(lines)


def test_inputsplit_over_gcs_directory(gcs_server):
    # sharding a DIRECTORY of gs:// objects: listing + per-file sizes
    lines = []
    for f in range(3):
        chunk = [f"f{f}-{i}" for i in range(40)]
        lines += chunk
        with Stream.create(f"gs://bkt/shards/f{f}.txt", "w") as s:
            s.write(("\n".join(chunk) + "\n").encode())
    got = []
    for part in range(2):
        sp = input_split.create("gs://bkt/shards", part, 2, "text")
        got += [bytes(r).decode() for r in sp]
        sp.close()
    assert sorted(got) == sorted(lines)


def test_http_read_stream(gcs_server):
    # plain http:// read of a stored object via the media endpoint
    with Stream.create("gs://bkt/raw.bin", "w") as s:
        s.write(b"0123456789" * 1000)
    port = gcs_server.server_port
    url = (f"http://127.0.0.1:{port}/download/storage/v1/b/bkt/o/raw.bin"
           f"?alt=media")
    strm = Stream.create_for_read(url)
    assert strm.read(10) == b"0123456789"
    strm.seek(9995)
    assert strm.read(100) == b"56789"


def test_gcs_write_retries_through_injected_500s(gcs_server):
    """Every chunk PUT fails once with a 500; the writer must recover via
    the 308 committed-range probe and commit byte-identical content."""
    payload = bytes(np.random.default_rng(7).integers(0, 256, 5 * 70_000,
                                                      dtype=np.uint8))
    os.environ["DMLC_GCS_WRITE_BUFFER_MB"] = "1"   # floor: 256KiB chunks
    os.environ["DMLC_GCS_RETRY_BASE_S"] = "0.01"
    _FakeGCS.fail_each_put = True
    _FakeGCS._failed_once.clear()
    try:
        with Stream.create("gs://bkt/faulty/blob.bin", "w") as s:
            for lo in range(0, len(payload), 70_000):
                s.write(payload[lo: lo + 70_000])
    finally:
        _FakeGCS.fail_each_put = False
        os.environ.pop("DMLC_GCS_WRITE_BUFFER_MB")
        os.environ.pop("DMLC_GCS_RETRY_BASE_S")
    assert _FakeGCS.store[("bkt", "faulty/blob.bin")] == payload


def test_gcs_abort_deletes_session_and_commits_nothing(gcs_server):
    from dmlc_tpu.io.gcs_filesys import GCSWriteStream

    s = GCSWriteStream("bkt", "aborted/blob.bin")
    s.write(b"partial data that must never become visible")
    before = len(_FakeGCS.sessions)
    s.abort()
    assert ("bkt", "aborted/blob.bin") not in _FakeGCS.store
    assert len(_FakeGCS.sessions) == before - 1
    # closing after abort is a no-op, not a commit
    s.close()
    assert ("bkt", "aborted/blob.bin") not in _FakeGCS.store


def test_gcs_exception_in_with_block_aborts(gcs_server):
    with pytest.raises(RuntimeError):
        with Stream.create("gs://bkt/ctx/blob.bin", "w") as s:
            s.write(b"doomed bytes")
            raise RuntimeError("simulated trainer crash")
    assert ("bkt", "ctx/blob.bin") not in _FakeGCS.store


def test_gcs_read_api_retries_transient_500(gcs_server, monkeypatch):
    # one-shot 500 on a GET: _api retries and succeeds

    with Stream.create("gs://bkt/retry/read.bin", "w") as s:
        s.write(b"abcdef")
    real = urllib.request.urlopen
    state = {"failed": False}

    def flaky(req, timeout=None):
        if not state["failed"] and "retry%2Fread.bin" in req.full_url:
            state["failed"] = True
            raise urllib.error.HTTPError(req.full_url, 503, "flaky", {}, None)
        return real(req, timeout=timeout)

    monkeypatch.setenv("DMLC_GCS_RETRY_BASE_S", "0.01")
    monkeypatch.setattr(urllib.request, "urlopen", flaky)
    from dmlc_tpu.io.filesys import FileSystem
    info = FileSystem.get_instance(URI("gs://bkt")).get_path_info(
        URI("gs://bkt/retry/read.bin"))
    assert state["failed"] and info.size == 6
