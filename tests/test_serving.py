"""Serving plane: paged KV cache, continuous batching, HTTP surface.

The allocator/cache tests are pure bookkeeping (no jax compute); the
engine tests run the real jitted prefill/decode on a tiny model (the
jit wrappers are process-cached, so the whole file pays each shape's
compile once).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.base import DMLCError
from dmlc_tpu.serving import (
    AdmissionFull,
    BlockAllocator,
    ContinuousBatchScheduler,
    InferenceEngine,
    PagedKVCache,
    Request,
    RequestTooLarge,
    ServingHTTPServer,
)
from dmlc_tpu.serving.scheduler import (ACTIVE, DONE, WAITING,
                                        PRIORITY_CLASSES, coerce_priority)


# ---------------------------------------------------------------------------
# block allocator
# ---------------------------------------------------------------------------

def test_allocator_exhaustion_is_all_or_nothing():
    a = BlockAllocator(4)
    got = a.alloc_many(3)
    assert got is not None and len(got) == 3 and a.n_free == 1
    # over-ask must not partially drain the free list
    assert a.alloc_many(2) is None
    assert a.n_free == 1
    assert a.alloc() is not None
    assert a.alloc() is None


def test_allocator_free_reuse_and_double_free():
    a = BlockAllocator(2)
    got = a.alloc_many(2)
    a.free(got)
    assert a.n_free == 2 and a.n_in_use == 0
    again = a.alloc_many(2)
    assert sorted(again) == sorted(got)  # same physical blocks recycle
    with pytest.raises(DMLCError):
        a.free([99])  # foreign block
    with pytest.raises(DMLCError):
        a.free([again[0], 99])  # atomic: valid id must NOT free either
    assert a.n_in_use == 2
    a.free(again)
    with pytest.raises(DMLCError):
        a.free([again[0]])  # double free


# ---------------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------------

def _mk_cache(**kw):
    kw.setdefault("n_blocks", 8)
    kw.setdefault("block_size", 4)
    return PagedKVCache(2, 2, 3, **kw)  # L=2, H=2, D=3


def _seq_kv(cache, n, seed):
    rng = np.random.default_rng(seed)
    shape = (cache.n_layers, n, cache.n_heads, cache.head_dim)
    return rng.standard_normal(shape).astype(np.float32), \
        rng.standard_normal(shape).astype(np.float32)


def test_kv_write_gather_roundtrip_across_blocks():
    cache = _mk_cache()
    k, v = _seq_kv(cache, 10, seed=0)  # 10 tokens = 2.5 blocks
    assert cache.allocate(1, 10)
    cache.write(1, k, v, start=0)
    gk, gv, lens = cache.gather([1])
    assert lens.tolist() == [10]
    assert gk.shape[2] % cache.block_size == 0
    np.testing.assert_array_equal(gk[:, 0, :10], k)
    np.testing.assert_array_equal(gv[:, 0, :10], v)
    # append one token lands at position 10 (same block reservation is
    # insufficient: 11 tokens need a 3rd block, so extend first)
    assert cache.extend(1, 1)
    k1, v1 = _seq_kv(cache, 1, seed=1)
    cache.append(1, k1[:, 0], v1[:, 0])
    gk, gv, lens = cache.gather([1])
    assert lens.tolist() == [11]
    np.testing.assert_array_equal(gk[:, 0, 10], k1[:, 0])


def test_kv_exhaustion_then_free_then_reuse_without_aliasing():
    cache = _mk_cache(n_blocks=4, block_size=4)  # 16 tokens total
    ka, va = _seq_kv(cache, 8, seed=0)
    kc, vc = _seq_kv(cache, 8, seed=2)
    assert cache.allocate(1, 8)          # seq A: blocks 0-1
    cache.write(1, ka, va)
    assert cache.allocate(3, 8)          # seq C: blocks 2-3
    cache.write(3, kc, vc)
    assert not cache.allocate(2, 4)      # pool exhausted
    assert not cache.extend(1, 1)
    cache.free(1)                        # eviction frees A's blocks
    reused = set()
    assert cache.allocate(2, 8)          # seq B reuses A's blocks
    reused = set(cache.block_table(2)) & set([0, 1, 2, 3])
    assert reused, "freed blocks must be reused"
    kb, vb = _seq_kv(cache, 8, seed=1)
    cache.write(2, kb, vb)
    # B reads back B's data, and surviving C is untouched (no aliasing)
    gk, gv, lens = cache.gather([2, 3])
    np.testing.assert_array_equal(gk[:, 0, :8], kb)
    np.testing.assert_array_equal(gk[:, 1, :8], kc)
    np.testing.assert_array_equal(gv[:, 1, :8], vc)


def test_kv_fragmentation_bounded_under_mixed_length_churn():
    cache = _mk_cache(n_blocks=16, block_size=4)
    rng = np.random.default_rng(7)
    live = {}
    sid = 0
    for it in range(120):
        if live and (len(live) >= 5 or rng.random() < 0.45):
            victim = int(rng.choice(list(live)))
            cache.free(victim)
            del live[victim]
        else:
            sid += 1
            n = int(rng.integers(1, 14))
            if cache.allocate(sid, n):
                k, v = _seq_kv(cache, n, seed=sid)
                cache.write(sid, k, v)
                live[sid] = (n, k)
        # invariants every iteration: conservation + bounded usage
        s = cache.stats()
        assert s["blocks_in_use"] + s["blocks_free"] == 16
        assert s["blocks_in_use"] == sum(
            cache.blocks_for(n) for n, _ in live.values())
        # the O(1) running token counter matches the ground truth sum,
        # and waste = allocated slots minus cached tokens
        assert s["cached_tokens"] == sum(n for n, _ in live.values())
        assert s["waste_tokens"] == (s["blocks_in_use"] * 4
                                     - s["cached_tokens"])
    # every surviving sequence still reads back its own data
    for seq, (n, k) in live.items():
        gk, _, lens = cache.gather([seq])
        assert lens[0] == n
        np.testing.assert_array_equal(gk[:, 0, :n], k)
    for seq in list(live):
        cache.free(seq)
    assert cache.n_free_blocks == 16  # no leaked blocks after churn
    assert cache.n_blocks_in_use == 0


def test_kv_gather_pads_batch_with_dead_rows():
    cache = _mk_cache()
    k, v = _seq_kv(cache, 3, seed=0)
    assert cache.allocate(1, 3)
    cache.write(1, k, v)
    gk, gv, lens = cache.gather([1], pad_batch=4, pad_len=8)
    assert gk.shape[1] == 4 and gk.shape[2] == 8
    assert lens.tolist() == [3, 0, 0, 0]
    assert not gk[:, 1:].any()
    # an explicit pad_len pins the jit shape: insufficiency / bad
    # granularity must raise, never silently widen
    with pytest.raises(ValueError):
        cache.gather([1], pad_len=6)  # not a block multiple
    assert cache.extend(1, 6)
    k9, v9 = _seq_kv(cache, 6, seed=3)
    cache.write(1, k9, v9)  # now 9 tokens > pad_len 8
    with pytest.raises(ValueError):
        cache.gather([1], pad_len=8)


def test_kv_write_past_reservation_raises():
    cache = _mk_cache()
    assert cache.allocate(1, 4)
    k, v = _seq_kv(cache, 5, seed=0)
    with pytest.raises(DMLCError):
        cache.write(1, k, v)  # 5 tokens into a 1-block reservation


# ---------------------------------------------------------------------------
# scheduler policy (no jax)
# ---------------------------------------------------------------------------

def test_scheduler_admission_respects_slots_and_blocks():
    cache = _mk_cache(n_blocks=4, block_size=4)
    sched = ContinuousBatchScheduler(cache, max_active=1)
    r1 = Request([1] * 4, 4)
    r2 = Request([2] * 4, 4)
    sched.enqueue(r1)
    sched.enqueue(r2)
    got = sched.next_prefill()
    assert got is r1
    assert cache.allocate(r1.id, 4)
    sched.activate(r1)
    assert sched.next_prefill() is None  # max_active reached
    sched.finish(r1)
    assert r1.state == DONE and r1.wait(0)
    # blocks freed by finish → r2 admissible
    big = Request([3] * 100, 4)  # needs 26 blocks > 4 free: blocked
    sched._waiting.appendleft(big)
    assert sched.next_prefill() is None
    sched._waiting.popleft()
    assert sched.next_prefill() is r2


def test_scheduler_preempts_youngest_and_requeues_front():
    cache = _mk_cache(n_blocks=8, block_size=4)
    sched = ContinuousBatchScheduler(cache, max_active=4)
    old = Request([1, 2], 4)
    young = Request([3, 4], 4)
    for r in (old, young):
        sched.enqueue(r)
        assert sched.next_prefill() is r
        assert cache.allocate(r.id, 2)
        sched.activate(r)
    young.generated = [7, 8]
    victim = sched.preempt_youngest()
    assert victim is young and young.state == WAITING
    assert young.preemptions == 1
    assert old.state == ACTIVE
    assert young.id not in cache.live_sequences()
    # resumes from the FRONT, context keeps generated-but-unconsumed
    assert sched.next_prefill() is young
    assert young.context_ids() == [3, 4, 7]  # last token not yet consumed


def test_coerce_priority_contract():
    assert PRIORITY_CLASSES == {"batch": 0, "standard": 1, "interactive": 2}
    assert coerce_priority(None, 3, 1) == 1          # None → default
    assert coerce_priority("interactive", 3, 1) == 2
    assert coerce_priority("batch", 3, 1) == 0
    assert coerce_priority(0, 3, 1) == 0
    assert coerce_priority(2, 3, 1) == 2
    # a named class above the configured level count is out of range
    with pytest.raises(ValueError):
        coerce_priority("interactive", 2, 0)
    for bad in ("gold", "", 3, -1, True, False, 1.5, [1], {"p": 1}):
        with pytest.raises(ValueError):
            coerce_priority(bad, 3, 1)


def test_scheduler_never_evicts_high_priority_over_low():
    """Satellite regression: a high-priority request is NEVER the
    eviction victim while any lower-priority request holds blocks,
    even when the high-priority one is the youngest."""
    cache = _mk_cache(n_blocks=16, block_size=4)
    sched = ContinuousBatchScheduler(cache, max_active=4)
    lo_old = Request([1, 2], 4, priority=0)
    lo_young = Request([3, 4], 4, priority=0)
    hi = Request([5, 6], 4, priority=2)       # youngest of the three
    lo_young.submit_t = lo_old.submit_t + 1.0
    hi.submit_t = lo_old.submit_t + 2.0
    for r in (lo_old, lo_young, hi):
        sched.enqueue(r)
    for _ in range(3):
        r = sched.next_prefill()
        assert cache.allocate(r.id, 2)
        sched.activate(r)
    # victims: youngest within the LOWEST class first, high class last
    assert sched.preempt_youngest() is lo_young
    assert hi.state == ACTIVE
    assert sched.preempt_youngest() is lo_old
    assert hi.state == ACTIVE, "high priority evicted before low"
    assert sched.preempt_youngest() is hi    # only when nothing lower
    assert sched.preempt_youngest() is None


def test_scheduler_admits_high_priority_first_fifo_within_class():
    cache = _mk_cache(n_blocks=16, block_size=4)
    sched = ContinuousBatchScheduler(cache, max_active=4)
    lo1 = Request([1], 4, priority=0)
    hi1 = Request([2], 4, priority=2)
    lo2 = Request([3], 4, priority=0)
    hi2 = Request([4], 4, priority=2)
    for r in (lo1, hi1, lo2, hi2):
        sched.enqueue(r)
    order = []
    while True:
        r = sched.next_prefill()
        if r is None:
            break
        assert cache.allocate(r.id, 1)
        sched.activate(r)
        order.append(r)
    assert order == [hi1, hi2, lo1, lo2]


# ---------------------------------------------------------------------------
# engine + model (real jitted compute, tiny config)
# ---------------------------------------------------------------------------

def _tiny_model():
    import jax

    from dmlc_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=2, head_dim=8,
                                d_ff=64, n_layers=2, n_experts=1,
                                microbatches=1)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    return params, cfg


def _greedy_oracle(params, cfg, prompt, n):
    """Greedy continuation via repeated full forward (no cache)."""
    from dmlc_tpu.models import transformer as tfm

    ctx = list(prompt)
    for _ in range(n):
        lg, _, _ = tfm.forward_prefill(
            params, np.array([ctx], np.int32), cfg)
        ctx.append(int(np.argmax(np.asarray(lg[0, -1]))))
    return ctx[len(prompt):]


def test_engine_continuous_batching_end_to_end():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8, admit_timeout_s=2.0)
    eng.start()
    try:
        reqs = [eng.submit([i + 1, i + 2, i + 3], max_new_tokens=5)
                for i in range(4)]  # 4 requests over 3 active slots
        for r in reqs:
            assert r.wait(300), f"request {r.id} never finished"
            assert r.error is None
            assert r.n_generated == 5
            assert r.ttft_s is not None and r.ttft_s > 0
        # greedy parity through the paged cache for one of them
        assert reqs[0].generated == _greedy_oracle(
            params, cfg, [1, 2, 3], 5)
        st = eng.stats()
        assert st["kv"]["blocks_in_use"] == 0  # all returned
        assert st["ledger"].get("steps", 0) > 0  # ledger was driven
    finally:
        eng.close()


def test_engine_single_step_interleaves_admission():
    """Iteration-level scheduling: a request submitted mid-generation
    joins the running batch instead of waiting for drain."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8)
    r1 = eng.submit([1, 2, 3], max_new_tokens=8)
    eng.step()   # prefill r1
    eng.step()   # decode r1
    assert r1.n_generated >= 2 and r1.state == ACTIVE
    r2 = eng.submit([4, 5, 6], max_new_tokens=2)
    eng.step()   # prefill r2 AND decode r1 in one iteration
    assert r2.n_generated >= 1
    assert r1.state == ACTIVE  # r1 still going: no drain barrier
    for _ in range(12):
        if r1.wait(0) and r2.wait(0):
            break
        eng.step()
    assert r1.n_generated == 8 and r2.n_generated == 2
    eng.close()


def test_engine_preemption_under_kv_pressure_still_completes():
    params, cfg = _tiny_model()
    before = telemetry.snapshot()["counters"].get(
        "serving", {}).get("preemptions", 0)
    # 6 blocks × 4 slots = 24 cached tokens; 3 × (4 prompt + 10 gen)
    # cannot coexist, so decode must evict and resume
    eng = InferenceEngine(params, cfg, n_blocks=6, block_size=4,
                          max_active=3, queue_depth=8)
    eng.start()
    try:
        reqs = [eng.submit([i + 1] * 4, max_new_tokens=10)
                for i in range(3)]
        for r in reqs:
            assert r.wait(300)
            assert r.error is None
            assert r.n_generated == 10
        after = telemetry.snapshot()["counters"]["serving"]["preemptions"]
        assert after > before, "tiny pool must have forced preemption"
        assert eng.cache.n_blocks_in_use == 0
        # preemption must be output-invisible: resume recomputes the
        # context without re-sampling, so every request still matches
        # the no-cache greedy oracle (a resume that re-derived its last
        # token would duplicate it and drop the final one)
        for i, r in enumerate(reqs):
            assert r.generated == _greedy_oracle(
                params, cfg, [i + 1] * 4, 10), (
                f"request {i} output corrupted by preemption "
                f"(preemptions={r.preemptions})")
    finally:
        eng.close()


def test_decode_capacity_eviction_of_already_checked_survivor():
    """Regression: activation order is not age order once a preempted
    request resumes.  When a LATER request's extend evicts an EARLIER
    survivor of the same capacity pass, that survivor must not reach
    the decode batch (its cache sequence is gone)."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=5, block_size=4,
                          max_active=4, queue_depth=8)
    x = Request([1, 2], 4)   # younger (submitted later) but FIRST in
    y = Request([3, 4], 4)   # the active list, older second: inversion
    y.submit_t = x.submit_t - 10.0
    assert eng.cache.allocate(x.id, 13)   # 4 blocks; extend stays inside
    eng.cache.write(x.id, *_seq_kv_model(cfg, 13))
    assert eng.cache.allocate(y.id, 4)    # 1 full block; extend needs +1
    eng.cache.write(y.id, *_seq_kv_model(cfg, 4))
    eng.scheduler.activate(x)
    eng.scheduler.activate(y)
    alive, n_preempted = eng._ensure_decode_capacity([x, y])
    assert alive == [y], "evicted survivor leaked into the decode batch"
    assert n_preempted == 1
    assert x.state == WAITING and x.preemptions == 1
    assert x.id not in eng.cache.live_sequences()
    eng.close()


def _seq_kv_model(cfg, n):
    rng = np.random.default_rng(n)
    shape = (cfg.n_layers, n, cfg.n_heads, cfg.head_dim)
    return (rng.standard_normal(shape).astype(np.float32),
            rng.standard_normal(shape).astype(np.float32))


def test_engine_rejects_oversized_and_overflowing_requests():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=4, block_size=4,
                          max_active=2, queue_depth=2,
                          admit_timeout_s=0.05)
    # could never fit even an empty cache → 413-shaped, not a slot
    with pytest.raises(RequestTooLarge):
        eng.submit([1] * 10, max_new_tokens=20)
    # bad content is the client's ValueError (HTTP 400), not a size issue
    with pytest.raises(ValueError):
        eng.submit([cfg.vocab + 5], max_new_tokens=1)
    # queue_depth=2 slots drain only when the engine runs; it is NOT
    # started, so the third submit must time out with AdmissionFull
    eng.submit([1, 2], max_new_tokens=1)
    eng.submit([3, 4], max_new_tokens=1)
    before = telemetry.snapshot()["counters"].get(
        "serving", {}).get("rejected", 0)
    with pytest.raises(AdmissionFull):
        eng.submit([5, 6], max_new_tokens=1)
    after = telemetry.snapshot()["counters"]["serving"]["rejected"]
    assert after == before + 1
    eng.close()


def test_engine_priority_and_tenant_validation_and_plumbing():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8, admit_timeout_s=2.0)
    try:
        # invalid classes are the client's ValueError (HTTP 400)
        for bad_prio in ("gold", 7, -1, True):
            with pytest.raises(ValueError):
                eng.submit([1, 2], max_new_tokens=2, priority=bad_prio)
        for bad_tenant in ("", 42, "x" * 65):
            with pytest.raises(ValueError):
                eng.submit([1, 2], max_new_tokens=2, tenant=bad_tenant)
        r = eng.submit([1, 2, 3], max_new_tokens=2,
                       priority="interactive", tenant="paid")
        while not r.wait(0):
            eng.step()
        doc = r.result()
        assert doc["priority"] == 2 and doc["tenant"] == "paid"
        # defaults: configured default class + the "default" tenant
        r2 = eng.submit([4, 5], max_new_tokens=1)
        assert r2.priority == eng.priority_default
        assert r2.tenant == "default"
    finally:
        eng.close()


def test_jit_program_cache_ignores_scenario_lock_hook():
    """The process-wide prefill/decode jit cache outlives any one
    engine: if the first engine of the process is built inside an
    interleaving-explorer scenario (the explorer's lock-factory hook
    active), the cached profiled wrappers must NOT capture
    scheduler-owned SchedLocks — a later engine would inherit a lock
    wired to a finished controller and park forever."""
    from dmlc_tpu import concurrency
    from dmlc_tpu.serving import engine as eng_mod

    offered = []

    def hook(name, reentrant):
        offered.append(name)
        return None

    saved = dict(eng_mod._JIT_CACHE)
    eng_mod._JIT_CACHE.clear()
    concurrency.set_lock_factory_hook(hook)
    try:
        eng_mod._jitted_programs()
        assert offered == [], (
            f"program-cache locks were offered to the scenario lock "
            f"hook: {offered}")
        # and the hook is back in place afterwards for the scenario
        assert concurrency._lock_factory_hook is hook
    finally:
        concurrency.set_lock_factory_hook(None)
        eng_mod._JIT_CACHE.clear()
        eng_mod._JIT_CACHE.update(saved)


def test_engine_close_fails_pending_requests():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=16, block_size=4,
                          max_active=2, queue_depth=4)
    req = eng.submit([1, 2, 3], max_new_tokens=50)  # engine never started
    eng.close()
    assert req.wait(5)
    assert req.state == "failed" and "shut down" in req.error


# ---------------------------------------------------------------------------
# HTTP surface
# ---------------------------------------------------------------------------

def _post(url, doc, timeout=300):
    req = urllib.request.Request(
        url + "/generate", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read())


def test_http_generate_metrics_healthz():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    try:
        doc = _post(srv.url, {"prompt": [1, 2, 3], "max_tokens": 4})
        assert doc["state"] == "done" and doc["n_generated"] == 4
        assert doc["ttft_s"] > 0 and len(doc["output_ids"]) == 4
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": "not a list"})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [1] * 500, "max_tokens": 500})
        assert e.value.code == 413
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [cfg.vocab + 7], "max_tokens": 2})
        assert e.value.code == 400  # bad content, NOT 413
        text = urllib.request.urlopen(
            srv.url + "/metrics", timeout=30).read().decode()
        from dmlc_tpu.telemetry.exporters import validate_exposition_text

        assert validate_exposition_text(text) > 0
        for fam in ("dmlc_serving_requests", "dmlc_serving_ttft_secs",
                    "dmlc_serving_tokens_generated",
                    "dmlc_serving_kv_blocks_in_use", "dmlc_step_count"):
            assert fam in text, f"{fam} missing from /metrics"
        hz = json.loads(urllib.request.urlopen(
            srv.url + "/healthz", timeout=30).read())
        assert hz["status"] == "ok" and "kv" in hz and "ledger" in hz
    finally:
        srv.close()
        eng.close()


def test_http_429_when_admission_queue_full():
    params, cfg = _tiny_model()
    # engine NOT started: slots never drain, so the queue fills
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=1,
                          admit_timeout_s=0.05)
    srv = ServingHTTPServer(eng, port=0)
    try:
        eng.submit([1, 2], max_new_tokens=1)  # occupies the only slot
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [3, 4], "max_tokens": 1}, timeout=30)
        assert e.value.code == 429
        assert e.value.headers.get("Retry-After") == "1"
    finally:
        srv.close()
        eng.close()


def test_concurrent_http_streams_complete():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=64, block_size=4,
                          max_active=4, queue_depth=16)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    results = []
    lock = threading.Lock()

    def client(i):
        doc = _post(srv.url, {"prompt": [i + 1, i + 2], "max_tokens": 3})
        with lock:
            results.append(doc)

    try:
        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
        assert time.monotonic() - t0 < 300
        assert len(results) == 6
        assert all(r["n_generated"] == 3 for r in results)
    finally:
        srv.close()
        eng.close()


# ---------------------------------------------------------------------------
# graceful drain (ISSUE 7): preemption notice must not drop in-flight work
# ---------------------------------------------------------------------------

def test_drain_finishes_active_rejects_new():
    """drain(): already-submitted generations complete; new /generate
    requests get 503 + Retry-After for the whole drain window."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=64, block_size=4,
                          max_active=4, queue_depth=16)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    results = {}
    try:
        # a long-ish generation in flight when the notice lands
        req = eng.submit([1, 2, 3], max_new_tokens=12)

        def draining():
            results["clean"] = srv.drain(timeout_s=60)

        t = threading.Thread(target=draining, daemon=True)
        t.start()
        # wait for the drain to take effect, then poke the front door
        deadline = time.monotonic() + 10
        while not eng.draining and time.monotonic() < deadline:
            time.sleep(0.01)
        assert eng.draining
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [5, 6], "max_tokens": 1},
                  timeout=30)
        assert e.value.code == 503
        assert e.value.headers.get("Retry-After") == "5"
        t.join(120)
        assert results["clean"] is True
        # the in-flight generation was finished, not dropped
        assert req.wait(5)
        assert req.error is None and len(req.generated) == 12
        # direct submits are refused too (embedded users)
        from dmlc_tpu.serving.engine import EngineDraining

        with pytest.raises(EngineDraining):
            eng.submit([1], max_new_tokens=1)
    finally:
        srv.close()
        eng.close()


def test_drain_deadline_fails_leftovers():
    """An engine that cannot finish (never started) hits the drain
    deadline: drain() returns False and the backlog is failed, not
    leaked."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    # NOT started: the queued request can never decode
    req = eng.submit([1, 2], max_new_tokens=4)
    srv = ServingHTTPServer(eng, port=0)
    try:
        assert srv.drain(timeout_s=0.3) is False
        assert req.wait(5)
        assert req.error is not None
    finally:
        srv.close()
        eng.close()


# ---------------------------------------------------------------------------
# idempotency dedupe (ISSUE 13): the router retry/hedge primitive
# ---------------------------------------------------------------------------

def test_dedupe_duplicate_while_live_returns_same_request():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    before = telemetry.counters_snapshot().get("serving", {}).get(
        "dedupe_hits", 0)
    r1 = eng.submit([1, 2, 3], max_new_tokens=4, request_id="dup-live")
    r2 = eng.submit([9, 9, 9], max_new_tokens=9, request_id="dup-live")
    assert r2 is r1, "duplicate while live must not start a second " \
        "generation"
    after = telemetry.counters_snapshot()["serving"]["dedupe_hits"]
    assert after == before + 1
    eng.start()
    assert r1.wait(300) and r1.error is None
    # duplicate after a successful finish: same finished request from
    # the dedupe ring, same output — not a new generation
    r3 = eng.submit([1, 2, 3], max_new_tokens=4, request_id="dup-live")
    assert r3 is r1 and r3.generated == r1.generated
    # a DIFFERENT id is fresh work
    r4 = eng.submit([1, 2, 3], max_new_tokens=4, request_id="other")
    assert r4 is not r1
    assert r4.wait(300)
    eng.close()


def test_dedupe_ring_is_bounded_and_failed_ids_are_fresh():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    eng._dedupe.capacity = 2
    eng.start()
    reqs = {}
    for key in ("k1", "k2", "k3"):
        reqs[key] = eng.submit([1, 2], max_new_tokens=2, request_id=key)
        assert reqs[key].wait(300)
    # ring capacity 2: k1 was evicted, so its id is fresh work again
    assert eng.submit([1, 2], max_new_tokens=2,
                      request_id="k3") is reqs["k3"]
    r1b = eng.submit([1, 2], max_new_tokens=2, request_id="k1")
    assert r1b is not reqs["k1"]
    assert r1b.wait(300)
    eng.close()
    # FAILED requests leave the table: a retry is a fresh attempt
    eng2 = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    rf = eng2.submit([1, 2], max_new_tokens=2, request_id="will-fail")
    eng2.close()  # engine never ran: the sweep fails it
    assert rf.wait(5) and rf.error is not None
    assert eng2._dedupe.get("will-fail") is None


def test_dedupe_admission_failure_wakes_duplicates_then_resets():
    """A claimed id whose admission then fails (queue full) must (a)
    wake any duplicate parked on it with the busy verdict and (b)
    leave the table so a later retry is a fresh attempt."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=1,
                          admit_timeout_s=0.05)  # NOT started
    eng.submit([1, 2], max_new_tokens=1)  # occupies the only slot
    with pytest.raises(AdmissionFull):
        eng.submit([3, 4], max_new_tokens=1, request_id="busy-key")
    assert eng._dedupe.get("busy-key") is None
    eng.close()


def test_http_request_id_dedupes_and_echoes():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    try:
        d1 = _post(srv.url, {"prompt": [1, 2, 3], "max_tokens": 4,
                             "request_id": "http-key"})
        d2 = _post(srv.url, {"prompt": [1, 2, 3], "max_tokens": 4,
                             "request_id": "http-key"})
        assert d1["request_id"] == d2["request_id"] == "http-key"
        assert d1["id"] == d2["id"]  # same internal request, not a rerun
        assert d1["output_ids"] == d2["output_ids"]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [1], "request_id": 42})
        assert e.value.code == 400  # non-string key is the client's bug
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(srv.url, {"prompt": [1], "request_id": "x" * 200})
        assert e.value.code == 400
    finally:
        srv.close()
        eng.close()


# ---------------------------------------------------------------------------
# requeue-on-crash (ISSUE 13): an engine-iteration crash is
# output-invisible up to the crash budget
# ---------------------------------------------------------------------------

def test_crash_requeue_resumes_and_matches_oracle():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8)
    real = eng._decode
    crashes = []

    def crashing(*a, **kw):
        if not crashes:
            crashes.append(1)
            raise RuntimeError("simulated decode crash")
        return real(*a, **kw)

    eng._decode = crashing
    before = telemetry.counters_snapshot().get("serving", {}).get(
        "crash_requeues", 0)
    eng.start()
    try:
        reqs = [eng.submit([i + 1, i + 2], max_new_tokens=6)
                for i in range(2)]
        for r in reqs:
            assert r.wait(300), f"request {r.id} never finished"
            assert r.error is None, r.error
            assert r.n_generated == 6
        after = telemetry.counters_snapshot()["serving"]["crash_requeues"]
        assert after > before, "crash must requeue, not fail"
        # recompute-resume is output-invisible: greedy parity holds
        # straight through the crash episode
        for i, r in enumerate(reqs):
            assert r.generated == _greedy_oracle(
                params, cfg, [i + 1, i + 2], 6)
        assert eng.cache.n_blocks_in_use == 0
    finally:
        eng.close()


def test_crash_requeue_during_drain_still_completes():
    """A crash requeue moves a request active -> waiting BACKWARD
    through drain()'s flow-order scan; the re-read of the wait queue
    must keep drain honest: the requeued request completes (resumed,
    not swept) and drain reports clean."""
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    real = eng._decode
    crashes = []

    def crash_once_draining(*a, **kw):
        if eng.draining and not crashes:
            crashes.append(1)
            raise RuntimeError("crash during drain")
        return real(*a, **kw)

    eng._decode = crash_once_draining
    eng.start()
    req = eng.submit([1, 2, 3], max_new_tokens=10)
    clean = eng.drain(timeout_s=120)
    assert crashes, "the crash never fired while draining"
    assert clean is True
    assert req.wait(5)
    assert req.error is None, req.error
    assert req.n_generated == 10
    assert req.crash_requeues == 1


def test_crash_requeue_budget_bounds_poisonous_request():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=2, queue_depth=8)
    eng._crash_requeue_max = 2

    def always_crash(*a, **kw):
        raise RuntimeError("poisoned decode")

    eng._decode = always_crash
    eng.start()
    try:
        r = eng.submit([1, 2, 3], max_new_tokens=4)
        assert r.wait(60), "poisonous request must FAIL, not loop forever"
        assert r.error is not None and "iteration failed" in r.error
        assert r.crash_requeues == 2  # budget fully spent first
        assert eng.cache.n_blocks_in_use == 0
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# drain admission race (ISSUE 13): requests hitting the window between
# begin_drain() and the 503 path either complete or get a clean 503
# ---------------------------------------------------------------------------

def test_drain_admission_race_never_hangs():
    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=64, block_size=4,
                          max_active=4, queue_depth=32,
                          admit_timeout_s=0.2)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    outcomes = []
    lock = threading.Lock()
    stop = threading.Event()

    def hammer(i):
        j = 0
        while not stop.is_set():
            j += 1
            try:
                _post(srv.url, {"prompt": [i + 1, j % 16 + 1],
                                "max_tokens": 2}, timeout=60)
                code = 200
            except urllib.error.HTTPError as e:
                code = e.code
            except (urllib.error.URLError, OSError):
                code = -1  # listener already closed: clean refusal
            with lock:
                outcomes.append(code)

    threads = [threading.Thread(target=hammer, args=(i,), daemon=True)
               for i in range(6)]
    drained = {}
    try:
        for t in threads:
            t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with lock:
                if len(outcomes) >= 6:
                    break  # traffic is flowing; drain mid-burst
            time.sleep(0.01)
        drain_t = threading.Thread(
            target=lambda: drained.setdefault(
                "clean", srv.drain(timeout_s=60)), daemon=True)
        drain_t.start()
        drain_t.join(120)
        assert not drain_t.is_alive(), "drain wedged"
    finally:
        stop.set()
        for t in threads:
            # a hung handler would park the client past the drain: the
            # join timeout IS the no-hang assertion
            t.join(90)
            assert not t.is_alive(), \
                "a client hung across the drain window"
        srv.close()
        eng.close()
    assert drained.get("clean") is True
    with lock:
        seen = list(outcomes)
    assert seen.count(200) >= 6, f"no traffic completed: {seen[:20]}"
    bad = [c for c in seen if c not in (200, 503, 429, -1)]
    assert not bad, f"non-clean statuses across the drain window: {bad}"


# ---------------------------------------------------------------------------
# loadgen (ISSUE 13): Retry-After honored, retried-then-ok counted
# ---------------------------------------------------------------------------

class _BackpressureOnce:
    """Answers each distinct request_id with one 429/503 (Retry-After
    set), then 200 — the loadgen retry contract in miniature."""

    def __init__(self, code=429, retry_after="0.4"):
        import json as _json
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)

        outer = self
        self.seen = {}
        self.sleeps = []

        class H(BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = _json.loads(self.rfile.read(n))
                rid = doc.get("request_id")
                outer.seen[rid] = outer.seen.get(rid, 0) + 1
                if outer.seen[rid] == 1:
                    body = _json.dumps({"error": "busy"}).encode()
                    self.send_response(code)
                    if retry_after is not None:
                        self.send_header("Retry-After", retry_after)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                body = _json.dumps(
                    {"state": "done", "output_ids": [1],
                     "n_generated": 1, "ttft_s": 0.01,
                     "latency_s": 0.02}).encode()
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_loadgen_honors_retry_after_and_counts_retried_ok():
    from dmlc_tpu.serving import LoadGenerator

    fake = _BackpressureOnce(code=429, retry_after="0.4")
    try:
        gen = LoadGenerator(fake.url, n_streams=2, requests_per_stream=1,
                            prompt_len=(2, 4), max_tokens=1,
                            retry_429_s=0.01)
        t0 = time.monotonic()
        summary = gen.run()
        elapsed = time.monotonic() - t0
        assert summary["n_requests_ok"] == 2
        assert summary["n_requests_failed"] == 0
        assert summary["n_requests_retried_ok"] == 2
        assert summary["n_rejections_429"] == 2
        # the header value (0.4s), not the 0.01s fallback, was honored
        assert elapsed >= 0.4, f"Retry-After ignored ({elapsed:.3f}s)"
        # every retry reused its request's idempotency key
        assert all(n == 2 for n in fake.seen.values())
    finally:
        fake.close()


def test_loadgen_retries_503_and_counts_separately():
    from dmlc_tpu.serving import LoadGenerator

    fake = _BackpressureOnce(code=503, retry_after="0.05")
    try:
        gen = LoadGenerator(fake.url, n_streams=1, requests_per_stream=2,
                            prompt_len=(2, 4), max_tokens=1,
                            retry_429_s=0.01)
        summary = gen.run()
        assert summary["n_requests_ok"] == 2
        assert summary["n_requests_failed"] == 0
        assert summary["n_requests_retried_ok"] == 2
        assert summary["n_backoffs_503"] == 2
        assert summary["n_rejections_429"] == 0
    finally:
        fake.close()


def test_loadgen_terminal_503_fails_once_with_error_body():
    """A 503 WITHOUT Retry-After is a terminal per-request verdict
    (engine failure, generation timeout): no retry amplification, and
    the server's error body survives into the failure record."""
    from dmlc_tpu.serving import LoadGenerator

    fake = _BackpressureOnce(code=503, retry_after=None)
    try:
        gen = LoadGenerator(fake.url, n_streams=1, requests_per_stream=1,
                            prompt_len=(2, 4), max_tokens=1,
                            retry_429_s=0.01)
        summary = gen.run()
        assert summary["n_requests_ok"] == 0
        assert summary["n_requests_failed"] == 1
        assert summary["n_backoffs_503"] == 0
        assert "busy" in gen.failures[0]["error"]  # body preserved
        # exactly ONE attempt: no fresh-generation amplification
        assert all(n == 1 for n in fake.seen.values())
    finally:
        fake.close()


def test_engine_fails_only_nonfinite_logit_request():
    """A non-finite logit row fails exactly that request with a clear
    error; the other request in the same decode batch (and the engine)
    keep serving."""
    from dmlc_tpu import telemetry

    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8)
    r1 = eng.submit([1, 2, 3], max_new_tokens=6)
    r2 = eng.submit([4, 5, 6], max_new_tokens=3)
    eng.step()  # prefill r1
    eng.step()  # prefill r2 (+ decode r1)
    real = eng._decode
    fired = []

    def poisoned(*a):
        # signature-agnostic: works for both the gather decode program
        # (7 args, 3 outputs) and the paged one (8 args, 5 outputs)
        out = real(*a)
        lg = np.asarray(out[0]).copy()
        if not fired:
            lg[0] = np.nan  # r1's row (activation order)
            fired.append(True)
        return (lg,) + tuple(out[1:])

    eng._decode = poisoned
    before = telemetry.counters_snapshot().get("serving", {}).get(
        "nonfinite_failures", 0)
    for _ in range(20):
        if r1.wait(0) and r2.wait(0):
            break
        eng.step()
    assert r1.error is not None and "non-finite" in r1.error
    assert r2.error is None and r2.n_generated == 3
    after = telemetry.counters_snapshot().get("serving", {}).get(
        "nonfinite_failures", 0)
    assert after == before + 1
    st = eng.stats()
    assert st["kv"]["blocks_in_use"] == 0  # failed request freed its blocks
    eng.close()
