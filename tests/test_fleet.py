"""Fleet control plane: the autoscaler's control law, the
training-preempting host provider's sequencing, and the watchdog's
fleet-saturation ingest (ISSUE 17 tentpole).

All deterministic: the router is faked, ``tick(now=...)`` injects the
clock, and the preemption transport is recorded callables.
"""

import json
import time

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.fleet import (Autoscaler, CallbackProvider, ResizeClient,
                            TrainingPreemptingProvider)
from dmlc_tpu.telemetry.anomaly import FLEET_KINDS, Watchdog
from dmlc_tpu.telemetry.exporters import validate_exposition_text


class FakeRouter:
    """Just enough Router for the control law: a utilization dial and
    a recording registry."""

    def __init__(self, n=1, util=0.0):
        self.util = util
        self._urls = [f"http://seed-{i}:1" for i in range(n)]
        self.calls = []

    def utilization(self):
        return self.util

    def replica_views(self):
        return [{"url": u, "state": "healthy"} for u in self._urls]

    def add_replica(self, url):
        self.calls.append(("add", url))
        self._urls.append(url)

    def set_draining(self, url):
        self.calls.append(("drain", url))
        return url in self._urls

    def remove_replica(self, url):
        self.calls.append(("remove", url))
        if url in self._urls:
            self._urls.remove(url)
            return True
        return False


def _mk(router, capacity=8, **kw):
    counter = [0]

    def acquire():
        counter[0] += 1
        return f"http://scaled-{counter[0]}:1"

    prov = CallbackProvider(acquire, lambda url: None, capacity=capacity)
    kw.setdefault("interval_s", 0.01)
    kw.setdefault("high_water", 0.8)
    kw.setdefault("low_water", 0.3)
    kw.setdefault("hysteresis", 3)
    kw.setdefault("cooldown_s", 10.0)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("slo_poll", lambda url: {})
    return Autoscaler(router, prov, **kw)


# ---------------------------------------------------------------------------
# control law
# ---------------------------------------------------------------------------

def test_autoscaler_config_validation():
    r = FakeRouter()
    with pytest.raises(ValueError):
        _mk(r, high_water=0.3, low_water=0.8)
    with pytest.raises(ValueError):
        _mk(r, min_replicas=3, max_replicas=2)


def test_hysteresis_cooldown_and_scale_cycle():
    r = FakeRouter(n=1, util=0.95)
    a = _mk(r)
    # hysteresis: two over-water ticks hold, the third scales up
    assert a.tick(now=0.0) == "hold"
    assert a.tick(now=1.0) == "hold"
    assert a.tick(now=2.0) == "scale_up"
    assert r.calls == [("add", "http://scaled-1:1")]
    # cooldown: still overloaded, but no second action inside 10 s
    assert a.tick(now=3.0) == "hold"
    assert a.tick(now=4.0) == "hold"
    # the streak kept building through the cooldown, so the first
    # post-cooldown tick acts at once
    assert a.tick(now=13.0) == "scale_up"
    assert len(r._urls) == 3
    # load drops: underloaded streak drains the NEWEST owned replica
    r.util = 0.1
    assert a.tick(now=26.0) == "hold"
    assert a.tick(now=27.0) == "hold"
    assert a.tick(now=28.0) == "scale_down"
    # drain at the router FIRST (no new work), removal last
    assert r.calls[-2:] == [("drain", "http://scaled-2:1"),
                            ("remove", "http://scaled-2:1")]
    assert r._urls == ["http://seed-0:1", "http://scaled-1:1"]
    rep = a.report()
    assert rep["counters"]["scale_ups"] == 2
    assert rep["counters"]["scale_downs"] == 1
    assert rep["owned"] == ["http://scaled-1:1"]


def test_scale_down_never_touches_unowned_or_min_replicas():
    # two seed replicas, idle forever: nothing is owned, nothing drains
    r = FakeRouter(n=2, util=0.0)
    a = _mk(r, hysteresis=1)
    for i in range(5):
        assert a.tick(now=float(i)) == "hold"
    assert r.calls == []
    # one owned replica, but the fleet sits AT min_replicas: held
    r2 = FakeRouter(n=1, util=0.95)
    a2 = _mk(r2, hysteresis=1, min_replicas=2, cooldown_s=1.0)
    assert a2.tick(now=0.0) == "scale_up"     # fleet now 2 == min
    r2.util = 0.0
    assert a2.tick(now=5.0) == "hold"
    assert len(r2._urls) == 2


def test_saturation_flags_once_and_clears_with_pressure():
    r = FakeRouter(n=1, util=0.95)
    a = _mk(r, hysteresis=1, max_replicas=1, cooldown_s=0.0)
    assert a.tick(now=0.0) == "saturated"
    assert a.tick(now=1.0) == "saturated"
    rep = a.report()
    assert rep["saturated"] is True
    assert rep["counters"]["saturations"] == 1   # transition-gated
    assert a.status()["saturated"] is True
    # pressure gone: the verdict clears without an action
    r.util = 0.5
    assert a.tick(now=2.0) == "hold"
    assert a.report()["saturated"] is False
    # provider exhaustion saturates too (capacity 0)
    prov = CallbackProvider(lambda: None, lambda u: None, capacity=0)
    a2 = Autoscaler(FakeRouter(n=1, util=0.95), prov, hysteresis=1,
                    cooldown_s=0.0, high_water=0.8, low_water=0.3,
                    max_replicas=4, slo_poll=lambda url: {})
    assert a2.tick(now=0.0) == "saturated"


def test_slo_burn_marks_fleet_hot_despite_low_utilization():
    polled = []

    def slo_poll(url):
        polled.append(url)
        return {"active": ["slo_ttft"]}

    r = FakeRouter(n=1, util=0.1)   # well under water by queue depth
    a = _mk(r, hysteresis=1, slo_poll=slo_poll)
    assert a.tick(now=0.0) == "scale_up"
    assert polled == ["http://seed-0:1"]
    assert a.report()["slo_hot"] is True


def test_report_status_and_prometheus_text():
    r = FakeRouter(n=1, util=0.95)
    a = _mk(r, hysteresis=1)
    a.tick(now=0.0)
    rep = a.report()
    assert rep["replicas"] == 2 and rep["owned"] == ["http://scaled-1:1"]
    assert rep["config"]["hysteresis"] == 1
    assert rep["provider"] == {"kind": "callback", "capacity": 8,
                               "leased": 1}
    st = a.status()
    assert st["replicas"] == 2 and "owned" in st["detail"]
    text = a.prometheus_text()
    validate_exposition_text(text)
    for fam in ("dmlc_fleet_replicas 2", "dmlc_fleet_owned_replicas 1",
                "dmlc_fleet_ticks_total 1", "dmlc_fleet_scale_ups_total 1",
                "dmlc_fleet_saturated 0"):
        assert fam in text, f"{fam} missing:\n{text}"


def test_autoscaler_thread_lifecycle():
    r = FakeRouter(n=1, util=0.0)
    a = _mk(r, interval_s=0.01)
    a.start()
    a.start()   # idempotent
    deadline = 200
    while a.report()["counters"]["ticks"] < 3 and deadline:
        deadline -= 1
        time.sleep(0.01)
    a.close()
    assert a.report()["counters"]["ticks"] >= 3
    assert a._thread is None


# ---------------------------------------------------------------------------
# host providers
# ---------------------------------------------------------------------------

def test_callback_provider_capacity_bound():
    made = []
    p = CallbackProvider(lambda: (made.append(1), f"u{len(made)}")[1],
                         lambda u: None, capacity=2)
    assert p.acquire() == "u1"
    assert p.acquire() == "u2"
    assert p.acquire() is None          # capacity exhausted
    p.release("u1")
    assert p.acquire() == "u3"
    assert p.stats() == {"kind": "callback", "capacity": 2, "leased": 2}


class _RecordingResize:
    def __init__(self):
        self.calls = []

    def resize(self, world, remove=None):
        self.calls.append(("resize", world, remove))
        return {"requested": True, "world_target": world}


def test_training_preemption_kills_then_resizes_then_launches():
    rz = _RecordingResize()
    seq = []
    p = TrainingPreemptingProvider(
        rz, full_world=3,
        kill_rank=lambda r: seq.append(("kill", r)),
        launch_replica=lambda r: (seq.append(("launch", r)),
                                  f"http://freed-{r}:1")[1],
        stop_replica=lambda u: seq.append(("stop", u)),
        relaunch_rank=lambda r: seq.append(("relaunch", r)),
        min_world=1)
    url = p.acquire()
    assert url == "http://freed-2:1"
    # the contract: victim killed FIRST, then shrink WITH remove list,
    # then the replica launch on the freed host
    assert seq == [("kill", 2), ("launch", 2)]
    assert rz.calls == [("resize", 2, [2])]
    assert seq.index(("kill", 2)) == 0
    url2 = p.acquire()
    assert url2 == "http://freed-1:1"
    assert p.stats()["training_world"] == 1
    assert p.acquire() is None          # min_world floor: rank 0 stays
    # release reverses: drain replica, relaunch worker, grow resize
    seq.clear()
    rz.calls.clear()
    p.release(url2)
    assert seq == [("stop", "http://freed-1:1"), ("relaunch", 1)]
    assert rz.calls == [("resize", 2, None)]
    with pytest.raises(KeyError):
        p.release("http://never-leased:1")
    st = p.stats()
    assert st["preemptions"] == 2 and st["restores"] == 1
    assert st["leases"] == {"http://freed-2:1": 2}


def test_training_preemption_validates_worlds():
    rz = _RecordingResize()
    with pytest.raises(ValueError):
        TrainingPreemptingProvider(rz, full_world=0, kill_rank=None,
                                   launch_replica=None, stop_replica=None,
                                   relaunch_rank=None)
    with pytest.raises(ValueError):
        TrainingPreemptingProvider(rz, full_world=2, kill_rank=None,
                                   launch_replica=None, stop_replica=None,
                                   relaunch_rank=None, min_world=3)


def test_resize_client_against_elastic_tracker():
    from dmlc_tpu.tracker import RabitTracker

    tracker = RabitTracker("127.0.0.1", 1, metrics_port=0, elastic=True)
    tracker.start(1)
    try:
        rc = ResizeClient(f"http://127.0.0.1:{tracker.metrics_port}")
        doc = rc.resize(2)
        assert doc["requested"] is True and doc["world_target"] == 2
        doc = rc.resize(2, remove=[1])
        assert doc["remove"] == [1]
        el = rc.elastic_status()
        assert el.get("enabled") is True or "gen" in el
    finally:
        tracker.close()


# ---------------------------------------------------------------------------
# watchdog ingest
# ---------------------------------------------------------------------------

def test_watchdog_ingest_fleet_flags_and_clears():
    assert FLEET_KINDS == ("fleet_saturated",)
    wd = Watchdog(window=3)
    before = telemetry.snapshot()["counters"].get(
        "anomaly", {}).get("fleet_saturated_flags", 0)
    wd.ingest_json(0, json.dumps(
        {"fleet": {"saturated": True, "detail": "replica cap reached"}}))
    rep = wd.report()
    assert rep["ranks"]["0"]["flags"] == ["fleet_saturated"]
    assert any(a["kind"] == "fleet_saturated" for a in rep["active"])
    after = telemetry.snapshot()["counters"]["anomaly"][
        "fleet_saturated_flags"]
    assert after == before + 1
    assert 'kind="fleet_saturated"' in wd.prometheus_text()
    # verdict withdrawn: clears without re-counting
    wd.ingest_fleet(0, {"saturated": False})
    assert wd.report()["ranks"]["0"]["flags"] == []
    assert telemetry.snapshot()["counters"]["anomaly"][
        "fleet_saturated_flags"] == after
    # malformed docs are dropped, never raise
    wd.ingest_fleet(-1, {"saturated": True})
    wd.ingest_fleet(0, "nope")
