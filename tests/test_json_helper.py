"""Declarative JSON binding (reference json.h JSONObjectReadHelper)."""

import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.json_helper import JSONObjectReadHelper


def make_helper():
    h = JSONObjectReadHelper()
    h.declare_field("name", str)
    h.declare_field("lr", float)
    h.declare_field("steps", int)
    h.declare_field("tags", list, required=False, default=[])
    return h


def test_read_valid():
    out = make_helper().read_object(
        '{"name": "sgd", "lr": 0.1, "steps": 10, "tags": ["a"]}')
    assert out == {"name": "sgd", "lr": 0.1, "steps": 10, "tags": ["a"]}


def test_optional_default_and_int_to_float():
    out = make_helper().read_object('{"name": "x", "lr": 1, "steps": 2}')
    assert out["lr"] == 1.0 and isinstance(out["lr"], float)
    assert out["tags"] == []
    # defaults are copied, not shared
    out["tags"].append("mutate")
    assert make_helper().read_object(
        '{"name": "x", "lr": 1, "steps": 2}')["tags"] == []


def test_missing_required_and_unknown_keys():
    with pytest.raises(DMLCError, match="missing required"):
        make_helper().read_object('{"name": "x", "lr": 1}')
    with pytest.raises(DMLCError, match="unknown JSON keys"):
        make_helper().read_object(
            '{"name": "x", "lr": 1, "steps": 2, "zzz": 0}')
    # non-strict mode tolerates unknown keys (kAllowUnknown analog)
    h = JSONObjectReadHelper(strict=False)
    h.declare_field("name", str)
    assert h.read_object('{"name": "x", "zzz": 1}') == {"name": "x"}


def test_type_errors():
    with pytest.raises(DMLCError, match="expected str"):
        make_helper().read_object('{"name": 3, "lr": 1, "steps": 2}')
    with pytest.raises(DMLCError, match="expected int, got bool"):
        make_helper().read_object('{"name": "x", "lr": 1, "steps": true}')
    with pytest.raises(DMLCError, match="invalid JSON"):
        make_helper().read_object("{nope")
    with pytest.raises(DMLCError, match="expected a JSON object"):
        make_helper().read_object("[1,2]")


def test_nested_helper_and_read_into():
    inner = JSONObjectReadHelper()
    inner.declare_field("dim", int)
    outer = JSONObjectReadHelper()
    outer.declare_field("model", inner)
    outer.declare_field("epochs", int)

    class Cfg:
        pass

    cfg = outer.read_into(Cfg(), '{"model": {"dim": 8}, "epochs": 3}')
    assert cfg.model == {"dim": 8}
    assert cfg.epochs == 3


def test_write_omits_absent_optional():
    h = make_helper()
    text = h.write_object({"name": "x", "lr": 1.0, "steps": 2})
    assert "tags" not in text
    # and the round trip restores the declared default
    assert h.read_object(text)["tags"] == []
    with pytest.raises(DMLCError, match="missing field"):
        h.write_object({"name": "x"})


def test_write_round_trip():
    h = make_helper()
    text = h.write_object({"name": "sgd", "lr": 0.5, "steps": 7,
                           "tags": ["x"]})
    assert h.read_object(text) == {"name": "sgd", "lr": 0.5, "steps": 7,
                                   "tags": ["x"]}
