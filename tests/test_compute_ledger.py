"""Compute observability (telemetry.compute): compile ledger, XLA
cost/roofline, HBM accounting, phase decomposition (PR 16).

Everything runs on the virtual CPU mesh: the AOT compile path,
cost_analysis extraction, the host-RSS memory fallback and the storm
detector are all backend-agnostic, which is exactly the property the
profiling layer must keep (profiling can never be allowed to break the
model on ANY backend).
"""

import importlib.util
import json
import logging
import os
import time

import jax
import jax.numpy as jnp
import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.base import DMLCError
from dmlc_tpu.telemetry import compute
from dmlc_tpu.telemetry.anomaly import COMPUTE_KINDS, Watchdog


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    telemetry.reset_events()
    compute.reset_compute()
    yield
    telemetry.reset()
    telemetry.reset_events()
    compute.reset_compute()


def _load_top():
    spec = importlib.util.spec_from_file_location(
        "compute_top_fixture", os.path.join(
            os.path.dirname(__file__), "..", "scripts", "dmlc_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    return top


# ---------------------------------------------------------------------------
# compile ledger: hit/trace counting + recompile attribution
# ---------------------------------------------------------------------------

def test_profiled_jit_counts_hits_and_traces():
    pj = compute.profiled_jit(lambda x: x * 2.0, site="t.basic")
    x = jnp.arange(4, dtype=jnp.float32)
    for _ in range(3):
        assert float(pj(x)[0]) == 0.0
    st = pj.stats()
    assert st["traces"] == 1 and st["hits"] == 2
    assert st["recompiles"] == 0 and st["signatures"] == 1
    assert compute.sites()["t.basic"] is pj
    assert compute.recompiles_total() == 0


def test_recompile_attributed_to_signature():
    pj = compute.profiled_jit(lambda x: x + 1, site="t.attr")
    pj(jnp.zeros((4,), jnp.float32))
    pj(jnp.zeros((8,), jnp.float32))     # new shape -> recompile
    pj(jnp.zeros((8,), jnp.int32))       # new dtype -> recompile
    st = pj.stats()
    assert st["traces"] == 3 and st["recompiles"] == 2
    # the LAST recompile is attributed to the (shape, dtype) that
    # triggered it, human-readably
    assert "8" in st["last_signature"] and "int32" in st["last_signature"]
    assert compute.recompiles_total() == 2


def test_static_args_split_signatures():
    calls = []

    def f(x, n):
        calls.append(n)
        return x * n

    pj = compute.profiled_jit(f, site="t.static", static_argnums=(1,))
    x = jnp.ones((2,), jnp.float32)
    assert float(pj(x, 2)[0]) == 2.0
    assert float(pj(x, 3)[0]) == 3.0     # same aval, new static value
    assert float(pj(x, 2)[0]) == 2.0     # cache hit on the first
    st = pj.stats()
    assert st["traces"] == 2 and st["hits"] == 1


def test_unhashable_static_falls_back_like_plain_jit():
    pj = compute.profiled_jit(lambda x, n: x, site="t.unhash",
                              static_argnums=(1,))
    with pytest.raises(Exception):  # jax's own unhashable-static error
        pj(jnp.ones((2,)), [1, 2])
    assert pj.stats()["aot_fallbacks"] >= 1


def test_signature_cap_raises_dmlc_error():
    pj = compute.profiled_jit(lambda x: x, site="t.cap",
                              max_signatures=2)
    pj(jnp.zeros((1,), jnp.float32))
    pj(jnp.zeros((2,), jnp.float32))
    with pytest.raises(DMLCError, match="signature cap"):
        pj(jnp.zeros((3,), jnp.float32))
    # the capped site still serves its existing signatures
    assert float(pj(jnp.zeros((2,), jnp.float32))[0]) == 0.0


def test_compile_span_lands_on_flight_recorder():
    pj = compute.profiled_jit(lambda x: x * x, site="t.span")
    pj(jnp.ones((3,), jnp.float32))
    trace = json.loads(telemetry.to_chrome_trace_json())
    names = {e["name"] for e in trace["traceEvents"]
             if e.get("ph") == "X"}
    assert "compile:t.span" in names


def test_reregister_survives_reset():
    pj = compute.profiled_jit(lambda x: x, site="t.rereg")
    pj(jnp.zeros((2,), jnp.float32))
    compute.reset_compute()
    assert compute.sites() == {}
    pj.reregister()   # what the engine's process-wide program cache does
    assert compute.sites()["t.rereg"] is pj
    assert pj.stats()["traces"] == 1  # ledger state rode along


# ---------------------------------------------------------------------------
# XLA cost extraction + roofline verdicts
# ---------------------------------------------------------------------------

def test_cost_extraction_on_cpu():
    pj = compute.profiled_jit(lambda a, b: a @ b, site="t.cost")
    a = jnp.ones((16, 16), jnp.float32)
    pj(a, a)
    cost = pj.stats()["last_cost"]
    assert cost is not None
    # a 16x16x16 matmul is ~2*16^3 = 8192 flops; XLA may fuse a bit
    # around it but the figure must be in that ballpark, not zero
    assert cost["flops"] >= 4096
    assert cost["bytes_accessed"] > 0


def test_roofline_both_verdicts():
    # intensity 100 flops/byte against balance 10 -> compute-bound
    r = compute.roofline(flops=1e6, bytes_accessed=1e4, wall_s=1.0,
                         peak_flops=1e7, peak_bw=1e6)
    assert r["bound"] == "compute"
    assert r["mfu"] == pytest.approx(0.1)
    # intensity 0.1 against the same balance -> memory-bound
    r = compute.roofline(flops=1e3, bytes_accessed=1e4, wall_s=1.0,
                         peak_flops=1e7, peak_bw=1e6)
    assert r["bound"] == "memory"
    assert r["membw_util"] == pytest.approx(0.01)
    assert r["intensity"] == pytest.approx(0.1)


def test_roofline_degrades_to_none():
    r = compute.roofline(None, None, 1.0, None, None)
    assert r["bound"] is None and r["mfu"] is None
    r = compute.roofline(1e6, 1e4, 0.0, 1e7, 1e6)  # bad wall
    assert r["bound"] is None


def test_step_ledger_carries_membw_and_bound(monkeypatch):
    monkeypatch.setenv("DMLC_PEAK_FLOPS", "1e9")
    monkeypatch.setenv("DMLC_PEAK_HBM_GBPS", "1")  # 1e9 B/s, balance=1
    telemetry.reset_steps()
    telemetry.step_begin()
    time.sleep(0.001)
    telemetry.step_end(tokens=128, flops=1e5, bytes_accessed=1e7)
    summ = telemetry.ledger().summary()
    assert summ["bound"] == "memory"       # intensity 0.01 < balance 1
    assert summ["membw_util"] is not None and summ["membw_util"] > 0
    roof = telemetry.ledger().roofline_summary()
    assert roof["bound"] == "memory"
    assert roof["peak_flops"] == pytest.approx(1e9)
    assert roof["peak_membw_bytes_per_s"] == pytest.approx(1e9)


# ---------------------------------------------------------------------------
# HBM accounting
# ---------------------------------------------------------------------------

def test_sample_hbm_reports_peak_and_gauges():
    doc = compute.sample_hbm()
    assert doc["source"] in ("device", "host_rss")
    assert doc["peak_bytes"] and doc["peak_bytes"] > 0
    snap = telemetry.export_json()
    assert snap["gauges"]["compute"]["hbm_peak_bytes"] > 0


def test_sample_hbm_host_rss_fallback(monkeypatch):
    # a backend whose devices report no memory_stats: the sample must
    # degrade to the host-RSS proxy, flagged as such, never go dark
    monkeypatch.setattr(jax, "local_devices",
                        lambda: (_ for _ in ()).throw(RuntimeError("x")))
    doc = compute.sample_hbm(publish=False)
    assert doc["source"] == "host_rss" and not doc["available"]
    assert doc["peak_bytes"] and doc["peak_bytes"] > 0
    assert doc["limit_bytes"] and doc["limit_bytes"] > doc["peak_bytes"]
    assert doc["headroom_bytes"] is not None


# ---------------------------------------------------------------------------
# phase decomposition
# ---------------------------------------------------------------------------

def test_phase_shares_mix_measured_and_estimated():
    with compute.phase("gather"):
        time.sleep(0.002)
    # analytic split of a 10ms device residual by FLOP fractions
    compute.phase_estimate({"attention": 3.0, "mlp": 6.0,
                            "unembed": 1.0}, 0.010)
    shares = compute.phase_shares()
    assert set(shares) == set(compute.PHASES)
    assert sum(shares.values()) == pytest.approx(1.0)
    assert shares["mlp"] > shares["attention"] > shares["unembed"]
    assert shares["gather"] > 0
    assert shares["sampling"] == 0.0


def test_phase_estimate_ignores_garbage():
    compute.phase_estimate({}, 1.0)
    compute.phase_estimate({"attention": 0.0}, 1.0)
    compute.phase_estimate({"attention": 1.0}, -1.0)
    assert compute.phase_shares() == {}


def test_decode_phase_flops_sums_to_decode_flops():
    from dmlc_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=2,
                                head_dim=8, d_ff=64, n_layers=2,
                                n_experts=1, dtype="float32")
    shares = tfm.decode_phase_flops(cfg, ctx=40)
    assert set(shares) == {"attention", "mlp", "unembed"}
    assert sum(shares.values()) == pytest.approx(
        tfm.decode_flops_per_token(cfg, 40))


# ---------------------------------------------------------------------------
# views: status / report / prometheus text
# ---------------------------------------------------------------------------

def test_status_empty_without_sites():
    assert compute.status() == {}


def test_status_and_report_schema():
    pj = compute.profiled_jit(lambda x: x + 1, site="t.schema")
    pj(jnp.zeros((2,), jnp.float32))
    pj(jnp.zeros((4,), jnp.float32))
    compute.sample_hbm()
    st = compute.status()
    assert st["traces"] == 2 and st["recompiles"] == 1
    assert "storm" in st and st["hbm_peak_bytes"] > 0
    rep = compute.report()
    assert rep["enabled"] and "t.schema" in rep["sites"]
    assert rep["traces_total"] == 2
    assert rep["recompiles_total"] == 1
    assert rep["storm"]["threshold"] >= 1
    assert rep["hbm"]["peak_bytes"] > 0
    assert set(rep["phases"]) == {"shares", "estimated", "measured"}
    assert "bound" in rep["roofline"]


def test_storm_detector_trips_on_churn(monkeypatch):
    monkeypatch.setenv("DMLC_COMPUTE_STORM_TRACES", "3")
    pj = compute.profiled_jit(lambda x: x, site="t.storm")
    for n in range(1, 5):
        pj(jnp.zeros((n,), jnp.float32))
    storm = compute.status()["storm"]
    assert storm["active"]
    assert storm["sites"][0]["site"] == "t.storm"
    assert storm["sites"][0]["traces_in_window"] == 4


def test_prometheus_text_per_site_families():
    pj = compute.profiled_jit(lambda x: x, site="t.prom")
    pj(jnp.zeros((2,), jnp.float32))
    pj(jnp.zeros((3,), jnp.float32))
    text = compute.prometheus_text()
    assert '# TYPE dmlc_compute_recompiles_total counter' in text
    assert 'dmlc_compute_recompiles_total{site="t.prom"} 1' in text
    assert 'dmlc_compute_traces_total{site="t.prom"} 2' in text
    assert 'dmlc_compute_cache_hits_total{site="t.prom"} 0' in text


# ---------------------------------------------------------------------------
# dark-cheap contract: DMLC_COMPUTE_PROFILE=0
# ---------------------------------------------------------------------------

def test_disabled_returns_plain_jit(monkeypatch):
    monkeypatch.setenv("DMLC_COMPUTE_PROFILE", "0")
    pj = compute.profiled_jit(lambda x: x * 2.0, site="t.off",
                              static_argnums=())
    assert not hasattr(pj, "stats")  # the plain jax.jit object
    assert float(pj(jnp.ones((2,), jnp.float32))[0]) == 2.0
    assert compute.sites() == {}     # no registry entry
    assert compute.status() == {}


def test_disabled_phase_scope_accumulates_nothing(monkeypatch):
    monkeypatch.setenv("DMLC_COMPUTE_PROFILE", "0")
    with compute.phase("gather"):
        time.sleep(0.001)
    compute.phase_estimate({"attention": 1.0}, 1.0)
    assert compute.phase_shares() == {}


# ---------------------------------------------------------------------------
# watchdog integration + dmlc-top pane
# ---------------------------------------------------------------------------

def _storm_status_doc(active=True):
    return {"traces": 6, "hits": 0, "recompiles": 5,
            "hbm_peak_bytes": 1 << 30,
            "storm": {"active": active, "window_s": 60.0, "threshold": 4,
                      "sites": [{"site": "smoke.churn",
                                 "traces_in_window": 6}]}}


def test_watchdog_ingest_compute_flags_and_clears():
    w = Watchdog(log=logging.getLogger("t"))
    w.ingest_json(1, json.dumps({"compute": _storm_status_doc()}))
    rep = w.report()
    assert rep["ranks"]["1"]["flags"] == ["recompile_storm"]
    assert rep["ranks"]["1"]["compute"]["recompiles"] == 5
    assert rep["ranks"]["1"]["compute"]["storm_sites"] == ["smoke.churn"]
    assert "recompile_storm" in COMPUTE_KINDS
    creport = w.compute_report()
    assert creport["storming_ranks"] == [1]
    assert creport["ranks"]["1"]["traces"] == 6
    # the worker's window slides past the churn: the flag clears
    w.ingest_compute(1, _storm_status_doc(active=False))
    assert w.report()["ranks"]["1"]["flags"] == []
    assert w.compute_report()["storming_ranks"] == []


def test_watchdog_ingest_compute_sanitizes():
    w = Watchdog(log=logging.getLogger("t"))
    w.ingest_compute(1, {"traces": "NaN-ish", "recompiles": 2,
                         "storm": "not-a-dict"})
    comp = w.report()["ranks"]["1"]["compute"]
    assert comp == {"recompiles": 2}
    assert w.report()["ranks"]["1"]["flags"] == []
    w.ingest_compute(-1, _storm_status_doc())   # bad rank: dropped
    assert "-1" not in w.report()["ranks"]


def test_render_compute_pane_replica_shape():
    top = _load_top()
    pj = compute.profiled_jit(lambda x: x, site="t.pane")
    pj(jnp.zeros((2,), jnp.float32))
    compute.phase_estimate({"attention": 1.0, "mlp": 2.0}, 0.01)
    compute.sample_hbm()
    lines = top.render_compute_pane({"compute": compute.report()})
    text = "\n".join(lines)
    assert "compute  traces=1" in text
    assert "storm=ok" in text
    assert "phases" in text and "mlp=67%" in text


def test_render_compute_pane_tracker_shape():
    top = _load_top()
    doc = {"compute": {"ranks": {"0": {"recompiles": 0},
                                 "1": {"recompiles": 5}},
                       "storming_ranks": [1]}}
    (line,) = top.render_compute_pane(doc)
    assert "r0:0" in line and "r1:5" in line
    assert "STORM ranks=[1]" in line
    assert top.render_compute_pane({}) == []
