"""Flagship transformer: sharded SPMD loss must match the unsharded
oracle, and the full 5-way-parallel train step must run and learn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from dmlc_tpu.models import (
    TransformerConfig,
    init_params,
    make_train_step,
    param_specs,
    unsharded_loss,
)
from dmlc_tpu.parallel import build_mesh

CFG = TransformerConfig(
    vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=32,
    n_layers=2, n_experts=2, microbatches=2,
)


def _data(key, b=4, t=16, vocab=64):
    ids = jax.random.randint(key, (b, t), 0, vocab)
    labels = jnp.roll(ids, -1, axis=1)
    return ids, labels


@pytest.fixture(scope="module")
def mesh():
    # pp=2, sp=2, tp=2: every interesting axis non-trivial on 8 devices
    return build_mesh(8, pp=2, sp=2, tp=2, dp=1, ep=1)


def test_sharded_loss_matches_oracle(mesh):
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    ids, labels = _data(jax.random.PRNGKey(1))
    want = float(unsharded_loss(params, ids, labels, CFG))

    from dmlc_tpu.models.transformer import SHARDED_AXES, forward_local

    specs = param_specs()
    fn = jax.shard_map(
        lambda p, i, l: forward_local(p, i, l, CFG, SHARDED_AXES),
        mesh=mesh, in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=P(),
    )
    got = float(jax.jit(fn)(params, ids, labels))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_train_step_learns(mesh):
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    step, init_state = make_train_step(mesh, CFG)
    opt_state = init_state(params)
    ids, labels = _data(jax.random.PRNGKey(2))
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_gradients_match_oracle(mesh):
    """Sharded grads (via VMA transposes) == unsharded autodiff grads."""
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    ids, labels = _data(jax.random.PRNGKey(3))

    from dmlc_tpu.models.transformer import SHARDED_AXES, forward_local

    specs = param_specs()
    gfn = jax.shard_map(
        lambda p, i, l: jax.grad(
            lambda q: forward_local(q, i, l, CFG, SHARDED_AXES)
        )(p),
        mesh=mesh, in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=specs,
    )
    got = jax.jit(gfn)(params, ids, labels)
    want = jax.grad(lambda q: unsharded_loss(q, ids, labels, CFG))(params)
    flat_g, _ = jax.tree.flatten(got)
    paths = jax.tree.flatten_with_path(want)[0]
    for (path, w), g in zip(paths, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-5, rtol=1e-3,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )
