"""Flagship transformer: sharded SPMD loss must match the unsharded
oracle, and the full 5-way-parallel train step must run and learn."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dmlc_tpu.models import (
    TransformerConfig,
    init_params,
    make_train_step,
    param_specs,
    unsharded_loss,
)
from dmlc_tpu.parallel import build_mesh

CFG = TransformerConfig(
    vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=32,
    n_layers=2, n_experts=2, microbatches=2,
)


def _data(key, b=4, t=16, vocab=64):
    ids = jax.random.randint(key, (b, t), 0, vocab)
    labels = jnp.roll(ids, -1, axis=1)
    return ids, labels


@pytest.fixture(scope="module")
def mesh():
    # pp=2, sp=2, tp=2: every interesting axis non-trivial on 8 devices
    return build_mesh(8, pp=2, sp=2, tp=2, dp=1, ep=1)


def test_sharded_loss_matches_oracle(mesh):
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    ids, labels = _data(jax.random.PRNGKey(1))
    want = float(unsharded_loss(params, ids, labels, CFG))

    from dmlc_tpu.models.transformer import SHARDED_AXES, forward_local

    specs = param_specs()
    fn = jax.shard_map(
        lambda p, i, l: forward_local(p, i, l, CFG, SHARDED_AXES),
        mesh=mesh, in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=P(),
    )
    got = float(jax.jit(fn)(params, ids, labels))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_train_step_learns(mesh):
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    step, init_state = make_train_step(mesh, CFG)
    opt_state = init_state(params)
    ids, labels = _data(jax.random.PRNGKey(2))
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


TOPK_CFG = TransformerConfig(
    vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=32,
    n_layers=2, n_experts=4, microbatches=2, moe_topk=2,
    moe_capacity_factor=100.0,  # ample: no drops → exactly equals masked
)


def test_topk_moe_matches_masked_dense_oracle():
    """With ample capacity, top-k routing must equal the dense combine
    with probs zeroed outside the top-k and renormalized."""
    from dmlc_tpu.models.transformer import _moe_topk_ffn
    from dmlc_tpu.ops.core import ShardAxes

    cfg = TOPK_CFG
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    layer_p = jax.tree.map(lambda a: a[0][0], params["blocks"])
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, cfg.d_model),
                          jnp.float32)
    got = _moe_topk_ffn(x, layer_p, ShardAxes(), cfg)

    # oracle: dense path with a hand-built top-k-masked renormalized gate
    logits = jnp.einsum("bte,ex->btx", x, layer_p["gate"])
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    topv, topi = jax.lax.top_k(probs, cfg.moe_topk)
    topv = topv / jnp.sum(topv, axis=-1, keepdims=True)
    sel = jax.nn.one_hot(topi, cfg.n_experts) * topv[..., None]
    mprobs = jnp.sum(sel, axis=-2)                 # [B,T,X]

    from dmlc_tpu.ops.core import swiglu_ffn

    def one_expert(w_in, w_gate, w_out):
        return swiglu_ffn(x, w_in, w_gate, w_out, ShardAxes(), reduce=False)

    ys = jax.vmap(one_expert)(layer_p["w_in"], layer_p["w_gate"],
                              layer_p["w_out"])
    want = jnp.einsum("xbte,btx->bte", ys, mprobs.astype(ys.dtype))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)


def test_topk_moe_sharded_matches_oracle():
    """ep=4-sharded routed MoE (local capacity dispatch) == unsharded."""
    mesh = build_mesh(8, pp=1, sp=1, tp=2, dp=1, ep=4)
    cfg = TOPK_CFG
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    ids, labels = _data(jax.random.PRNGKey(4))
    want = float(unsharded_loss(params, ids, labels, cfg))

    from dmlc_tpu.models.transformer import SHARDED_AXES, forward_local

    specs = param_specs()
    fn = jax.shard_map(
        lambda p, i, l: forward_local(p, i, l, cfg, SHARDED_AXES),
        mesh=mesh, in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=P(),
    )
    got = float(jax.jit(fn)(params, ids, labels))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_topk_moe_overflow_counter():
    """moe_debug_overflow=True must record the dropped-choice fraction
    in the metrics stage 'moe' (silent drops are undiagnosable)."""
    from dmlc_tpu import metrics

    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=32,
        n_layers=1, n_experts=4, microbatches=1, moe_topk=2,
        moe_capacity_factor=0.25,  # force overflow
        moe_debug_overflow=True)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    ids, labels = _data(jax.random.PRNGKey(9), b=4, t=16)
    before = metrics.snapshot().get("moe", {})
    float(unsharded_loss(params, ids, labels, cfg))
    after = metrics.snapshot().get("moe", {})
    checks = after.get("overflow_checks", 0) - before.get(
        "overflow_checks", 0)
    frac = after.get("overflow_fraction_sum", 0.0) - before.get(
        "overflow_fraction_sum", 0.0)
    assert checks >= 1
    assert frac > 0.0  # capacity 0.25 must actually drop choices


def test_topk_moe_train_step_learns():
    mesh = build_mesh(8, pp=1, sp=2, tp=1, dp=2, ep=2)
    cfg = TransformerConfig(
        vocab=64, d_model=32, n_heads=4, head_dim=8, d_ff=32,
        n_layers=2, n_experts=4, microbatches=2, moe_topk=2,
        moe_capacity_factor=2.0, remat=True)
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    step, init_state = make_train_step(mesh, cfg)
    opt_state = init_state(params)
    ids, labels = _data(jax.random.PRNGKey(5), b=8, t=16)
    losses = []
    for _ in range(8):
        params, opt_state, loss = step(params, opt_state, ids, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


def test_gradients_match_oracle(mesh):
    """Sharded grads (via VMA transposes) == unsharded autodiff grads."""
    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    ids, labels = _data(jax.random.PRNGKey(3))

    from dmlc_tpu.models.transformer import SHARDED_AXES, forward_local

    specs = param_specs()
    gfn = jax.shard_map(
        lambda p, i, l: jax.grad(
            lambda q: forward_local(q, i, l, CFG, SHARDED_AXES)
        )(p),
        mesh=mesh, in_specs=(specs, P("dp", "sp"), P("dp", "sp")),
        out_specs=specs,
    )
    got = jax.jit(gfn)(params, ids, labels)
    want = jax.grad(lambda q: unsharded_loss(q, ids, labels, CFG))(params)
    flat_g, _ = jax.tree.flatten(got)
    paths = jax.tree.flatten_with_path(want)[0]
    for (path, w), g in zip(paths, flat_g):
        np.testing.assert_allclose(
            np.asarray(g), np.asarray(w), atol=5e-5, rtol=1e-3,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )


def test_remat_policies_are_math_neutral():
    """remat and its policies trade memory for recompute — never math:
    loss and gradients must be bitwise-comparable across full /
    save_flash / save_flash_mlp and remat off."""
    import dataclasses

    ids, labels = _data(jax.random.PRNGKey(5))
    results = []
    for remat, policy in [(False, "save_flash"), (True, "full"),
                          (True, "save_flash"), (True, "save_flash_mlp")]:
        cfg = dataclasses.replace(CFG, remat=remat, remat_policy=policy)
        params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
        loss, grads = jax.value_and_grad(
            lambda p: unsharded_loss(p, ids, labels, cfg))(params)
        gnorm = sum(float(jnp.sum(jnp.abs(g)))
                    for g in jax.tree.leaves(grads))
        results.append((float(loss), gnorm))
    base = results[0]
    for got in results[1:]:
        np.testing.assert_allclose(got[0], base[0], rtol=1e-6)
        np.testing.assert_allclose(got[1], base[1], rtol=1e-5)


def test_unknown_remat_policy_rejected():
    import dataclasses

    cfg = dataclasses.replace(CFG, remat=True, remat_policy="bogus")
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    ids, labels = _data(jax.random.PRNGKey(6))
    with pytest.raises(ValueError, match="remat_policy"):
        unsharded_loss(params, ids, labels, cfg)


def test_prefill_matches_training_forward():
    """Serving prefill is the same math as the training forward: the
    cross entropy of its logits equals unsharded_loss, and right-padding
    must not perturb positions before the true length (causality)."""
    from dmlc_tpu.models import forward_prefill
    from dmlc_tpu.ops.core import ShardAxes, softmax_xent

    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    ids, labels = _data(jax.random.PRNGKey(3), b=2, t=12)
    want = float(unsharded_loss(params, ids, labels, CFG))
    logits, k, v = forward_prefill(params, ids, CFG)
    got = float(jnp.mean(softmax_xent(logits, labels, ShardAxes())))
    np.testing.assert_allclose(got, want, rtol=1e-6)
    assert k.shape == (CFG.n_layers, 2, 12, CFG.n_heads, CFG.head_dim)
    # pad two extra columns: everything at t<12 must be unchanged
    ids_pad = jnp.pad(ids, ((0, 0), (0, 2)))
    lp, kp, vp = forward_prefill(params, ids_pad, CFG)
    np.testing.assert_allclose(np.asarray(lp[:, :12]), np.asarray(logits),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(kp[:, :, :12]), np.asarray(k),
                               rtol=1e-5, atol=1e-6)
    # the serving engine's last-position head: same logits, no [B,T,V]
    from dmlc_tpu.models import forward_prefill_last

    ll, kl, _ = forward_prefill_last(
        params, ids_pad, jnp.array([11, 11]), CFG)
    np.testing.assert_allclose(np.asarray(ll), np.asarray(logits[:, 11]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(kl), np.asarray(kp),
                               rtol=1e-6)


def test_decode_step_matches_full_forward():
    """The satellite contract: single-token decode against an external
    KV cache reproduces the full-sequence forward's logits position by
    position — including when the cache view is padded with garbage
    past each sequence's true length."""
    from dmlc_tpu.models import forward_decode, forward_prefill

    params = init_params(jax.random.PRNGKey(0), CFG, n_stages=2)
    t_total, n0, pad = 10, 4, 16
    ids, _ = _data(jax.random.PRNGKey(4), b=2, t=t_total)
    logits_full, k_full, v_full = forward_prefill(params, ids, CFG)

    shape = (CFG.n_layers, 2, pad, CFG.n_heads, CFG.head_dim)
    # garbage sentinel past the valid region: the length mask must make
    # these slots invisible, so parity proves masking, not luck
    k_cache = np.full(shape, 7.7, np.float32)
    v_cache = np.full(shape, -7.7, np.float32)
    _, k0, v0 = forward_prefill(params, ids[:, :n0], CFG)
    k_cache[:, :, :n0] = np.asarray(k0)
    v_cache[:, :, :n0] = np.asarray(v0)
    for pos in range(n0, t_total):
        lengths = np.full(2, pos, np.int32)
        positions = np.full(2, pos, np.int32)
        lg, kn, vn = forward_decode(
            params, np.asarray(ids[:, pos], np.int32), positions,
            k_cache, v_cache, lengths, CFG)
        np.testing.assert_allclose(
            np.asarray(lg), np.asarray(logits_full[:, pos]),
            rtol=1e-5, atol=1e-5,
            err_msg=f"decode logits diverge at position {pos}")
        np.testing.assert_allclose(
            np.asarray(kn), np.asarray(k_full[:, :, pos]),
            rtol=1e-5, atol=1e-6)
        k_cache[:, :, pos] = np.asarray(kn)
        v_cache[:, :, pos] = np.asarray(vn)


def test_decode_flops_per_token_is_forward_third():
    from dmlc_tpu.models import decode_flops_per_token, train_flops_per_token

    ctx = 128
    got = decode_flops_per_token(CFG, ctx)
    assert got == pytest.approx(train_flops_per_token(CFG, ctx,
                                                      causal=False) / 3.0)
    # more context strictly costs more attention FLOPs
    assert decode_flops_per_token(CFG, 256) > got
