"""Self-heal guard: classification, the skip/rollback/abort escalation
ladder, the chaos injection hook, and the shipped status doc."""

import math
import os

import pytest

from dmlc_tpu.resilience import install_injector, reset_injector
from dmlc_tpu.resilience.selfheal import (
    ABORT,
    OK,
    ROLLBACK,
    SKIP,
    SelfHealAbort,
    SelfHealGuard,
    reset_selfheal,
    status,
)

NAN = float("nan")


@pytest.fixture(autouse=True)
def _clean():
    reset_selfheal()
    reset_injector()
    yield
    reset_selfheal()
    reset_injector()


def test_healthy_steps_are_ok_and_update_ewma():
    g = SelfHealGuard(max_skips=2)
    for i in range(5):
        assert g.observe(1.0 - 0.1 * i, grad_norm=0.5, step=i) == OK
    assert g.finite_steps == 5
    assert g.ewma is not None and 0.5 < g.ewma < 1.0


def test_nonfinite_loss_escalation_ladder():
    """skip x max_skips, then rollback; rollbacks exhausted -> abort."""
    g = SelfHealGuard(max_skips=2, max_rollbacks=1)
    g.observe(1.0, step=0)
    assert g.observe(NAN, step=1) == SKIP
    assert g.observe(NAN, step=1) == SKIP
    assert g.observe(NAN, step=1) == ROLLBACK     # 3rd consecutive
    assert g.rollbacks == 1 and g.consecutive_bad == 0
    # still poisoned after the rollback: ladder repeats, then aborts
    assert g.observe(NAN, step=1) == SKIP
    assert g.observe(NAN, step=1) == SKIP
    assert g.observe(NAN, step=1) == ABORT
    with pytest.raises(SelfHealAbort):
        g.raise_abort(1)


def test_recovery_resets_consecutive_count():
    g = SelfHealGuard(max_skips=2)
    g.observe(1.0, step=0)
    assert g.observe(NAN, step=1) == SKIP
    assert g.observe(1.0, step=1) == OK          # healed
    assert g.consecutive_bad == 0
    assert g.observe(NAN, step=2) == SKIP        # a fresh episode skips
    assert g.observe(NAN, step=2) == SKIP


def test_nonfinite_grad_norm_detected_before_loss():
    g = SelfHealGuard(max_skips=3)
    assert g.observe(0.7, grad_norm=float("inf"), step=1) == SKIP


def test_ewma_spike_gate_after_warmup():
    g = SelfHealGuard(max_skips=3, spike_factor=10.0, warmup=4)
    for i in range(6):
        assert g.observe(1.0, step=i) == OK
    assert g.observe(1.5, step=6) == OK           # ordinary wobble
    assert g.observe(50.0, step=7) == SKIP        # 50x the EWMA
    # a spike is not folded into the baseline
    assert g.ewma < 2.0


def test_spike_gate_disabled_below_factor_one():
    g = SelfHealGuard(max_skips=3, spike_factor=0.0, warmup=0)
    for i in range(5):
        g.observe(1.0, step=i)
    assert g.observe(1e9, step=9) == OK


def test_fault_spec_injection_hook_targets_exact_step():
    install_injector("selfheal.loss@step:7=corrupt::2")
    g = SelfHealGuard(max_skips=5)
    assert g.observe(1.0, step=6) == OK
    assert g.observe(1.0, step=7) == SKIP   # injected
    assert g.observe(1.0, step=7) == SKIP   # budget 2
    assert g.observe(1.0, step=7) == OK     # exhausted
    assert math.isfinite(g.ewma)


def test_status_doc_ships_last_action():
    g = SelfHealGuard(max_skips=1, max_rollbacks=1)
    g.observe(1.0, step=3)
    g.observe(NAN, step=4)
    doc = status()
    assert doc["last_action"] == SKIP
    assert doc["step"] == 4 and doc["skips"] == 1
    g.observe(NAN, step=4)
    assert status()["last_action"] == ROLLBACK


def test_abort_writes_postmortem_naming_suspect_spans(tmp_path,
                                                     monkeypatch):
    import json

    from dmlc_tpu.io import integrity

    monkeypatch.setenv("DMLC_POSTMORTEM_DIR", str(tmp_path))
    integrity.reset_quarantine()
    integrity.record_quarantine("poison.rec", 128, 192)
    try:
        g = SelfHealGuard(max_skips=0, max_rollbacks=0)
        g.observe(1.0, step=0)
        assert g.observe(NAN, step=1) == ABORT
        dumps = [f for f in os.listdir(tmp_path)
                 if f.startswith("postmortem")]
        assert dumps, "abort wrote no postmortem"
        doc = json.load(open(tmp_path / dumps[0]))
        assert "selfheal abort" in doc["reason"]
        assert "poison.rec[128:192]" in doc["reason"]
    finally:
        integrity.reset_quarantine()


def test_selfheal_counters():
    from dmlc_tpu import telemetry

    before = telemetry.counters_snapshot().get("selfheal", {})
    g = SelfHealGuard(max_skips=1, max_rollbacks=1)
    g.observe(1.0, step=0)
    g.observe(NAN, step=1)   # skip
    g.observe(NAN, step=1)   # rollback
    after = telemetry.counters_snapshot().get("selfheal", {})

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    assert delta("skips") == 1
    assert delta("rollbacks") == 1
    assert delta("nonfinite_steps") == 2
