"""SLO burn-rate monitor (telemetry.slo) + watchdog integration.

All tests drive explicit monotonic clocks through observe/evaluate, so
window edges, zero-traffic behavior, and recovery are checked exactly.
"""

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry.anomaly import ANOMALY_KINDS, Watchdog
from dmlc_tpu.telemetry.slo import (MIN_EVENTS, SLO_KINDS, SLOMonitor,
                                    monitor, reset_slo, status)


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    telemetry.reset_events()
    reset_slo()
    yield
    telemetry.reset()
    telemetry.reset_events()
    reset_slo()


def _mon(**kw):
    kw.setdefault("ttft_p99_s", 0.5)
    kw.setdefault("fast_window_s", 60.0)
    kw.setdefault("slow_window_s", 300.0)
    return SLOMonitor(**kw)


# ---------------------------------------------------------------------------
# burn-rate math
# ---------------------------------------------------------------------------

def test_burn_rate_is_bad_fraction_over_budget():
    m = _mon()
    t = 1000.0
    for i in range(8):
        m.observe_ttft(0.1, t=t + i)      # good
    for i in range(2):
        m.observe_ttft(1.0, t=t + 8 + i)  # bad
    out = m.evaluate(now=t + 20)
    o = out["ttft_p99"]
    # 2 bad of 10 over budget 0.01 -> burn 20x
    assert o["burn_fast"] == pytest.approx(20.0)
    assert o["burn_slow"] == pytest.approx(20.0)
    assert o["events_fast"] == 10


def test_violation_needs_both_windows_over_threshold():
    # old bad traffic only in the slow window: fast burn is clean, so
    # no violation even though the slow window still remembers the burn
    m = _mon()
    t = 10_000.0
    for i in range(10):
        m.observe_ttft(1.0, t=t + i)       # bad burst
    for i in range(10):
        m.observe_ttft(0.1, t=t + 200 + i)  # recent clean traffic
    out = m.evaluate(now=t + 250)          # burst left the fast window
    o = out["ttft_p99"]
    assert o["burn_fast"] == 0.0
    assert o["burn_slow"] == pytest.approx(50.0)
    assert not o["violating"]
    assert m.active() == []


def test_window_edges_expire_events():
    m = _mon()
    t = 5000.0
    for i in range(10):
        m.observe_ttft(1.0, t=t + i)
    # just inside the fast window: still violating
    out = m.evaluate(now=t + 9 + 59.0)
    assert out["ttft_p99"]["events_fast"] > 0
    # beyond the slow window: events expired entirely
    out = m.evaluate(now=t + 9 + 301.0)
    assert out["ttft_p99"]["events_slow"] == 0
    assert out["ttft_p99"]["burn_slow"] == 0.0


def test_min_events_guard_blocks_thin_evidence():
    m = _mon()
    t = 100.0
    for i in range(MIN_EVENTS - 1):
        m.observe_ttft(9.0, t=t + i)   # 100% bad, but too few
    out = m.evaluate(now=t + 10)
    assert out["ttft_p99"]["burn_fast"] == pytest.approx(100.0)
    assert not out["ttft_p99"]["violating"]
    m.observe_ttft(9.0, t=t + 9)       # the MIN_EVENTS-th event
    out = m.evaluate(now=t + 10)
    assert out["ttft_p99"]["violating"]


def test_zero_traffic_burns_nothing():
    m = _mon()
    out = m.evaluate(now=1234.0)
    assert out["ttft_p99"]["burn_fast"] == 0.0
    assert out["ttft_p99"]["events_slow"] == 0
    assert m.active() == []


def test_violation_fires_once_and_recovery_clears():
    m = _mon()
    t = 2000.0
    for i in range(10):
        m.observe_ttft(2.0, t=t + i)
    m.evaluate(now=t + 10)
    assert m.active() == ["slo_ttft"]
    before = telemetry.snapshot()["counters"]["slo"]["violations"]
    m.evaluate(now=t + 11)  # still violating: no re-fire
    assert telemetry.snapshot()["counters"]["slo"]["violations"] == before
    # recovery: the burst ages past both windows + traffic stops
    m.evaluate(now=t + 400)
    assert m.active() == []
    kinds = [e for e in telemetry.events_tail()
             if e["kind"] == "slo_recovered"]
    assert kinds and kinds[-1]["anomaly"] == "slo_ttft"
    # re-violation re-fires
    for i in range(10):
        m.observe_ttft(2.0, t=t + 500 + i)
    m.evaluate(now=t + 511)
    assert telemetry.snapshot()["counters"]["slo"]["violations"] \
        == before + 1


def test_objectives_are_independent_kinds():
    m = SLOMonitor(ttft_p99_s=0.5, tbt_p99_s=0.2, error_rate=0.05,
                   fast_window_s=60, slow_window_s=300)
    t = 3000.0
    for i in range(10):
        m.observe_ttft(2.0, t=t + i)     # only TTFT is violated
        m.observe_tbt(0.01, t=t + i)
        m.observe_outcome(True, t=t + i)
    m.evaluate(now=t + 10)
    assert m.active() == ["slo_ttft"]    # exactly one kind
    events = [e for e in telemetry.events_tail() if e["kind"] == "anomaly"]
    assert len(events) == 1 and events[0]["anomaly"] == "slo_ttft"


def test_error_rate_budget_is_the_configured_rate():
    m = SLOMonitor(error_rate=0.1, fast_window_s=60, slow_window_s=300)
    t = 100.0
    for i in range(8):
        m.observe_outcome(True, t=t + i)
    for i in range(2):
        m.observe_outcome(False, t=t + 8 + i)
    out = m.evaluate(now=t + 20)
    # 20% failed over a 10% budget -> burn 2.0
    assert out["error_rate"]["burn_fast"] == pytest.approx(2.0)
    assert not out["error_rate"]["violating"]


def test_generous_budget_still_fires_via_burn_cap():
    # burn is capped at 1/budget (100% bad), so with a 10% error
    # budget the max burn is 10x — below the default 14.4 threshold.
    # The per-objective clamp keeps the objective reachable: total
    # failure MUST fire, not be silently inert.
    m = SLOMonitor(error_rate=0.1, fast_window_s=60, slow_window_s=300)
    t = 500.0
    for i in range(10):
        m.observe_outcome(False, t=t + i)   # 100% failed
    out = m.evaluate(now=t + 15)
    assert out["error_rate"]["burn_fast"] == pytest.approx(10.0)
    assert out["error_rate"]["violating"]
    assert m.active() == ["slo_error_rate"]


def test_disabled_objectives_keep_nothing():
    m = SLOMonitor(ttft_p99_s=None, tbt_p99_s=None, error_rate=None)
    assert not m.enabled
    m.observe_ttft(99.0)
    m.observe_outcome(False)
    assert m.evaluate(now=10.0) == {}
    assert m.report()["objectives"] == {}
    assert m.prometheus_text() == ""
    assert m.status() is None


def test_report_and_markers_and_prometheus_shape():
    from dmlc_tpu.telemetry.exporters import validate_exposition_text

    m = _mon(tbt_p99_s=0.2)
    t = 100.0
    for i in range(10):
        m.observe_ttft(2.0, t=t + i)
    m.evaluate(now=t + 10)
    rep = m.report()
    assert rep["objectives"]["ttft_p99"]["violating"]
    assert rep["active"] == ["slo_ttft"]
    assert rep["recent_violations"][-1]["objective"] == "ttft_p99"
    marks = m.trace_markers()
    assert marks and marks[-1]["name"] == "slo:slo_ttft"
    text = m.prometheus_text()
    validate_exposition_text(text)
    assert 'dmlc_slo_violation_active{objective="ttft_p99"} 1' in text
    assert 'dmlc_slo_burn_rate{objective="ttft_p99",window="fast"}' in text


def test_status_subdoc_shape():
    import time as _time

    m = _mon()
    # events stamped near the REAL monotonic clock: status()
    # re-evaluates on it, and a still-fresh burst must stay flagged
    t = _time.monotonic()
    for i in range(10):
        m.observe_ttft(2.0, t=t - 10 + i)
    m.evaluate(now=t)
    st = m.status()
    assert st["active"] == ["slo_ttft"]
    assert st["burn"]["ttft_p99"]["fast"] == pytest.approx(100.0)


def test_status_reevaluates_so_stale_violations_clear():
    # the heartbeat ships status(); with no decode iterations driving
    # maybe_evaluate, the shipped doc must still notice the burst aged
    # out of both windows (the min_eval_interval throttle is bypassed
    # by using a tiny one here)
    import time as _time

    m = _mon(min_eval_interval_s=0.0)
    t = _time.monotonic() - 400.0   # a burst that aged past both windows
    for i in range(10):
        m.observe_ttft(2.0, t=t + i)
    m.evaluate(now=t + 10)          # evaluated AT the burst: violating
    assert m.active() == ["slo_ttft"]
    # status() re-evaluates on the real clock, which sees the burst as
    # expired — the shipped doc clears instead of going stale
    st = m.status()
    assert st["active"] == []


def test_default_monitor_env_and_status(monkeypatch):
    monkeypatch.setenv("DMLC_SLO_TTFT_P99_S", "0.75")
    reset_slo()
    assert status() is None            # never built: nothing ships
    m = monitor()
    assert m.enabled
    assert monitor() is m              # process-wide singleton
    assert status() is not None        # built + configured: ships
    monkeypatch.setenv("DMLC_SLO_TTFT_P99_S", "")
    reset_slo()
    assert monitor().enabled is False
    assert status() is None            # unconfigured: ships nothing


# ---------------------------------------------------------------------------
# watchdog integration (tracker side)
# ---------------------------------------------------------------------------

def test_watchdog_ingest_slo_sets_and_clears_flags():
    wd = Watchdog(window=3)
    wd.ingest_slo(2, {"active": ["slo_ttft"],
                      "burn": {"ttft_p99": {"fast": 50.0, "slow": 20.0}}})
    rep = wd.report()
    assert rep["ranks"]["2"]["flags"] == ["slo_ttft"]
    assert any(a["kind"] == "slo_ttft" for a in rep["active"])
    snap = telemetry.snapshot()
    assert snap["counters"]["anomaly"]["slo_ttft_flags"] == 1
    text = wd.prometheus_text()
    assert 'dmlc_anomaly_active{rank="2",kind="slo_ttft"} 1' in text
    # clearing: an empty active list clears, and does not re-count
    wd.ingest_slo(2, {"active": []})
    rep = wd.report()
    assert rep["ranks"]["2"]["flags"] == []
    assert telemetry.snapshot()["counters"]["anomaly"][
        "slo_ttft_flags"] == 1


def test_watchdog_step_ingest_does_not_clear_slo_flags():
    wd = Watchdog(window=2)
    wd.ingest_slo(0, {"active": ["slo_error_rate"]})
    # healthy step records flow in: the step-driven clear loop covers
    # ANOMALY_KINDS only, so the SLO flag must survive
    wd.ingest(0, [{"seq": i + 1, "wall_s": 0.1} for i in range(10)])
    flags = wd.report()["ranks"]["0"]["flags"]
    assert flags == ["slo_error_rate"]
    assert all(k in ANOMALY_KINDS or k in SLO_KINDS for k in flags)


def test_watchdog_ingest_json_picks_up_slo_subdoc():
    import json as _json

    wd = Watchdog(window=2)
    wd.ingest_json(1, _json.dumps(
        {"slo": {"active": ["slo_tbt"], "burn": {}},
         "trace": {"anchor": 123.0, "steps": []}}))
    assert wd.report()["ranks"]["1"]["flags"] == ["slo_tbt"]
    # malformed docs are dropped, never raise
    wd.ingest_slo(1, {"active": "nope"})
    wd.ingest_slo(1, ["not", "a", "dict"])
    wd.ingest_slo(-1, {"active": []})
    assert wd.report()["ranks"]["1"]["flags"] == ["slo_tbt"]
