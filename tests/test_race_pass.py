"""Guarded-by race pass + DMLC_RACECHECK runtime cross-check tests.

Same shape as test_analysis.py: a seeded-bad and a clean fixture per
check, plus the annotation contract, the held-lock inference, the
static region map, and the runtime attribute→lock pairing check.
"""

import threading

import pytest

from dmlc_tpu import concurrency
from dmlc_tpu.analysis.core import RepoIndex, default_paths, repo_root
from dmlc_tpu.analysis.race_pass import RacePass, guarded_region_map

REPO = repo_root()


def _index(tmp_path, files):
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    return RepoIndex(paths, str(tmp_path))


def _checks(findings, check):
    return [f for f in findings if f.check == check]


def _run(tmp_path, src):
    return RacePass().run(_index(tmp_path, {"dmlc_tpu/mod.py": src}))


# ---- unguarded-access ---------------------------------------------------

MIXED = '''\
from dmlc_tpu.concurrency import make_lock


class Counter:
    def __init__(self):
        self._lock = make_lock("Counter._lock")
        self._n = 0

    def bump(self):
        with self._lock:
            self._n += 1

    def peek(self):
        return self._n
'''

CLEAN = MIXED.replace(
    "    def peek(self):\n        return self._n\n",
    "    def peek(self):\n        with self._lock:\n"
    "            return self._n\n")


def test_mixed_access_caught(tmp_path):
    found = _checks(_run(tmp_path, MIXED), "unguarded-access")
    assert found and "Counter._n" in found[0].message, found


def test_all_locked_clean(tmp_path):
    assert not _run(tmp_path, CLEAN)


def test_immutable_after_init_clean(tmp_path):
    src = '''\
from dmlc_tpu.concurrency import make_lock


class Conf:
    def __init__(self, n):
        self._lock = make_lock("Conf._lock")
        self.n = int(n)
        self._items = []

    def read(self):
        return self.n  # never written post-init: unlocked read is safe

    def peek(self):
        return len(self._items)  # never mutated either
'''
    assert not _run(tmp_path, src)


def test_event_threaded_class_in_scope(tmp_path):
    """A class with no lock but a Thread/Event is still threaded: its
    unsynchronized mutable state needs annotations."""
    src = '''\
import threading


class Loop:
    def __init__(self):
        self._stop = threading.Event()
        self._count = 0

    def run(self):
        while not self._stop.is_set():
            self._count += 1
'''
    found = _checks(_run(tmp_path, src), "unguarded-access")
    assert found and "Loop._count" in found[0].message


def test_container_mutator_counts_as_write(tmp_path):
    src = '''\
from dmlc_tpu.concurrency import make_lock


class Ring:
    def __init__(self):
        self._lock = make_lock("Ring._lock")
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)

    def drain(self):
        return list(self._items)
'''
    found = _checks(_run(tmp_path, src), "unguarded-access")
    assert found and found[0].line == 14, found


# ---- annotations --------------------------------------------------------

def test_attr_level_unguarded_annotation_silences(tmp_path):
    src = MIXED.replace(
        "        self._n = 0",
        "        # dmlc-check: unguarded(peek is a monitor estimate)\n"
        "        self._n = 0")
    assert not _run(tmp_path, src)


def test_site_level_guarded_by_annotation(tmp_path):
    src = MIXED.replace(
        "    def peek(self):\n        return self._n\n",
        "    def peek(self):\n"
        "        # dmlc-check: guarded-by(_lock)\n"
        "        return self._n\n")
    assert not _run(tmp_path, src)


def test_unguarded_without_reason_is_bad_annotation(tmp_path):
    src = MIXED.replace(
        "        self._n = 0",
        "        # dmlc-check: unguarded()\n        self._n = 0")
    found = RacePass().run(_index(tmp_path, {"dmlc_tpu/mod.py": src}))
    assert _checks(found, "bad-annotation")


def test_guarded_by_unknown_lock_is_bad_annotation(tmp_path):
    src = MIXED.replace(
        "        self._n = 0",
        "        # dmlc-check: guarded-by(_nope)\n        self._n = 0")
    found = RacePass().run(_index(tmp_path, {"dmlc_tpu/mod.py": src}))
    assert _checks(found, "bad-annotation")


# ---- divergent-guard ----------------------------------------------------

DIVERGENT = '''\
from dmlc_tpu.concurrency import make_lock


class Split:
    def __init__(self):
        self._a = make_lock("Split._a")
        self._b = make_lock("Split._b")
        self._n = 0

    def via_a(self):
        with self._a:
            self._n += 1

    def via_b(self):
        with self._b:
            self._n += 1
'''


def test_divergent_guard_caught(tmp_path):
    found = _checks(_run(tmp_path, DIVERGENT), "divergent-guard")
    assert found and "_a" in found[0].message \
        and "_b" in found[0].message \
        and "Split._n" in found[0].message, found


def test_one_common_lock_clean(tmp_path):
    src = DIVERGENT.replace("with self._b:", "with self._a:")
    assert not _checks(_run(tmp_path, src), "divergent-guard")


# ---- leaked-guarded-ref -------------------------------------------------

def test_leaked_guarded_container_ref_caught(tmp_path):
    src = '''\
from dmlc_tpu.concurrency import make_lock


class Store:
    def __init__(self):
        self._lock = make_lock("Store._lock")
        self._items = []

    def add(self, x):
        with self._lock:
            self._items.append(x)

    def snapshot(self):
        with self._lock:
            return self._items
'''
    found = _checks(_run(tmp_path, src), "leaked-guarded-ref")
    assert found, found
    ok = src.replace("return self._items", "return list(self._items)")
    assert not _checks(_run(tmp_path, ok), "leaked-guarded-ref")


# ---- held-lock inference ------------------------------------------------

def test_locked_helper_inference(tmp_path):
    """A private helper whose every intra-class call site holds the
    lock runs under it — no annotation needed."""
    src = '''\
from dmlc_tpu.concurrency import make_lock


class Q:
    def __init__(self):
        self._lock = make_lock("Q._lock")
        self._items = []

    def push(self, x):
        with self._lock:
            self._items.append(x)
            self._trim_locked()

    def pop(self):
        with self._lock:
            self._trim_locked()
            return self._items.pop()

    def _trim_locked(self):
        while len(self._items) > 4:
            del self._items[0]
'''
    assert not _run(tmp_path, src)


def test_condition_alias_collapses_to_lock(tmp_path):
    src = '''\
import threading

from dmlc_tpu.concurrency import make_lock


class W:
    def __init__(self):
        self._lock = make_lock("W._lock")
        self._cv = threading.Condition(self._lock)
        self._ready = False

    def set(self):
        with self._cv:
            self._ready = True
            self._cv.notify_all()

    def get(self):
        with self._lock:
            return self._ready
'''
    assert not _run(tmp_path, src)


# ---- the shipped tree ---------------------------------------------------

def test_repo_race_pass_zero_findings():
    idx = RepoIndex(default_paths(["dmlc_tpu"], REPO), REPO)
    found = RacePass().run(idx)
    assert not found, "\n".join(str(f) for f in found[:25])


def test_guarded_region_map_names_real_sites():
    idx = RepoIndex(default_paths(["dmlc_tpu"], REPO), REPO)
    m = guarded_region_map(idx)
    assert m, "no guarded regions found in the package"
    names = {v for v in m.values() if v is not None}
    # a few load-bearing locks must be mapped under their class names
    for expect in ("BufferPool._lock", "Router._lock",
                   "ContinuousBatchScheduler._lock"):
        assert expect in names, sorted(names)[:20]
    # make_lock names across the repo agree with the static node names
    # (the convention the runtime cross-check rides on)


# ---- DMLC_RACECHECK runtime cross-check ---------------------------------

@pytest.fixture
def racecheck(monkeypatch):
    monkeypatch.setenv("DMLC_RACECHECK", "1")
    concurrency.lockcheck_reset()
    yield
    concurrency.lockcheck_reset()


def test_racecheck_implies_lockcheck(racecheck):
    lk = concurrency.make_lock("x")
    assert isinstance(lk, concurrency.CheckedLock)


def test_racecheck_records_and_cross_checks_clean(racecheck):
    pool = concurrency.BufferPool(object, capacity=2)
    a = pool.acquire()
    pool.release(a)
    pool.kill()
    obs = concurrency.racecheck_observed()
    assert any(base == "concurrency.py" for base, _ in obs), obs
    concurrency.racecheck_assert_clean()


def test_racecheck_flags_wrong_lock_at_known_site(racecheck):
    """An observed acquire whose runtime lock name contradicts the
    static guarded-by analysis is a violation."""
    idx = RepoIndex(default_paths(["dmlc_tpu"], REPO), REPO)
    m = guarded_region_map(idx)
    (base, line), expected = next(
        (k, v) for k, v in sorted(m.items()) if v is not None)
    with concurrency._lc_graph_lock:
        concurrency._rc_sites[(base, line)] = {"Bogus._lock"}
    bad = concurrency.racecheck_report()
    assert bad and bad[0]["kind"] == "attr-lock-mismatch"
    assert bad[0]["expected"] == expected
    with pytest.raises(Exception, match="mismatch"):
        concurrency.racecheck_assert_clean()


def test_racecheck_off_records_nothing(monkeypatch):
    monkeypatch.delenv("DMLC_RACECHECK", raising=False)
    monkeypatch.setenv("DMLC_LOCKCHECK", "1")
    concurrency.lockcheck_reset()
    lk = concurrency.make_lock("plain.lock")
    with lk:
        pass
    assert concurrency.racecheck_observed() == {}
    assert concurrency.racecheck_report() == []
    concurrency.lockcheck_reset()


def test_racecheck_site_bound(racecheck, monkeypatch):
    monkeypatch.setenv("DMLC_RACECHECK_MAX_SITES", "1")
    a = concurrency.make_lock("A.l")
    b = concurrency.make_lock("B.l")
    with a:
        pass
    with b:
        pass
    assert len(concurrency.racecheck_observed()) <= 1


def _ab_ba(a, b):
    with a:
        with b:
            pass


def test_lockcheck_still_works_under_racecheck(racecheck):
    a = concurrency.make_lock("rc.A")
    b = concurrency.make_lock("rc.B")
    for first, second in ((a, b), (b, a)):
        t = threading.Thread(target=_ab_ba, args=(first, second),
                             daemon=True)
        t.start()
        t.join()
    kinds = [v["kind"] for v in concurrency.lockcheck_report()]
    assert "order-inversion" in kinds
