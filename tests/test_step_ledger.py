"""Step ledger + anomaly watchdog (ISSUE 5): wall-time attribution,
goodput/MFU accounting, incremental shipping, online anomaly verdicts,
beat-size capping, and the dmlc-top renderer."""

import json
import time

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry import StepLedger, Watchdog
from dmlc_tpu.telemetry.anomaly import ANOMALY_KINDS


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    telemetry.reset_steps()
    yield
    telemetry.reset()
    telemetry.reset_steps()


# ---------------------------------------------------------------------------
# StepLedger: records, attribution, goodput/MFU
# ---------------------------------------------------------------------------

def test_step_record_decomposes_wall_time():
    led = StepLedger(peak_flops=1e9)
    led.step_begin()
    with telemetry.span("feed.wait", stage="feed"):
        time.sleep(0.02)
    with telemetry.span("collective.allreduce", stage="collective"):
        time.sleep(0.01)
    time.sleep(0.02)  # "compute"
    rec = led.step_end(tokens=1000, flops=5e6)
    assert rec["wall_s"] >= 0.05
    assert 0.015 <= rec["feed_wait_s"] <= rec["wall_s"]
    assert 0.005 <= rec["collective_s"] <= rec["wall_s"]
    # residual compute >= the bare sleep
    assert rec["compute_s"] >= 0.015
    # decomposition sums to wall exactly (compute is the residual)
    total = rec["feed_wait_s"] + rec["collective_s"] + rec["compute_s"]
    assert total == pytest.approx(rec["wall_s"], rel=1e-6)
    assert rec["goodput_tokens_per_s"] == pytest.approx(
        1000 / rec["wall_s"], rel=1e-6)
    assert rec["mfu"] == pytest.approx(5e6 / rec["wall_s"] / 1e9, rel=1e-6)


def test_step_ignores_other_threads_feed_spans():
    """Producer-side feed spans on OTHER threads must not be billed to
    the step — overlap is the feed pipeline's whole point."""
    import threading

    led = StepLedger()
    led.step_begin()

    def producer():
        with telemetry.span("feed.parse", stage="feed"):
            time.sleep(0.05)

    t = threading.Thread(target=producer)
    t.start()
    time.sleep(0.01)
    t.join()
    rec = led.step_end()
    assert rec["feed_wait_s"] == 0.0


def test_declared_flops_derive_step_flops():
    led = StepLedger(peak_flops=1e9)
    led.declare_flops_per_token(100.0)
    led.step_begin()
    rec = led.step_end(tokens=50)
    assert rec["flops"] == pytest.approx(5000.0)
    assert rec["mfu"] is not None


def test_ledger_records_step_span_in_ring():
    led = StepLedger()
    led.step_begin()
    led.step_end()
    names = [s["name"] for s in telemetry.spans()]
    assert "step" in names


def test_abandoned_step_does_not_leak_span_stack():
    led = StepLedger()
    led.step_begin()  # never ended (raising train step)
    led.step_begin()  # must unwind the dangling one
    rec = led.step_end()
    assert rec["seq"] == 1
    assert telemetry.open_spans() == []


def test_records_since_incremental_ship_contract():
    led = StepLedger()
    for _ in range(6):
        led.step_begin()
        led.step_end()
    recs, last = led.records_since(0, limit=4)
    assert [r["seq"] for r in recs] == [1, 2, 3, 4]
    assert last == 4  # truncated: cursor stops at last returned
    recs, last = led.records_since(last)
    assert [r["seq"] for r in recs] == [5, 6]
    assert last == 6
    assert led.records_since(6) == ([], 6)


def test_ledger_bounded_and_summary_keys():
    led = StepLedger(capacity=4)
    for _ in range(10):
        led.step_begin()
        led.step_end(tokens=10)
    assert len(led.records()) == 4
    s = led.summary()
    assert s["steps"] == 4
    assert s["step_time_p50"] <= s["step_time_p99"]
    assert s["goodput_tokens_per_s"] > 0
    assert "mfu" in s


def test_ledger_publishes_local_registry_families():
    led = StepLedger()
    led.step_begin()
    led.step_end(tokens=10)
    snap = telemetry.snapshot()
    assert snap["counters"]["step"]["count"] == 1
    assert "time_secs" in snap["histograms"]["step"]
    assert snap["gauges"]["step"]["goodput_tokens_per_s"] > 0


def test_bytes_fed_defaults_to_feed_counter_delta():
    led = StepLedger()
    led.step_begin()
    telemetry.inc("feed", "bytes_to_device", 4096)
    rec = led.step_end()
    assert rec["bytes_fed"] == 4096.0


def test_peak_flops_env_override(monkeypatch):
    from dmlc_tpu.telemetry import steps

    monkeypatch.setenv("DMLC_PEAK_FLOPS", "123.0")
    assert steps.detect_peak_flops() == 123.0
    monkeypatch.setenv("DMLC_PEAK_FLOPS", "garbage")
    assert steps.detect_peak_flops() is None


# ---------------------------------------------------------------------------
# heartbeat shipping: steps sub-doc + beat byte cap
# ---------------------------------------------------------------------------

class _FakeClient:
    rank = 0

    def __init__(self):
        self.payloads = []

    def send_metrics(self, payload):
        self.payloads.append(payload)


def _beat(client, **kw):
    from dmlc_tpu.telemetry.heartbeat import HeartbeatSender

    hb = HeartbeatSender(client, auto_start=False, ship_trace=True, **kw)
    hb.send_once()
    return hb, json.loads(client.payloads[-1])


def test_heartbeat_ships_step_records_incrementally():
    telemetry.step_begin()
    telemetry.step_end(tokens=5)
    c = _FakeClient()
    hb, doc = _beat(c)
    assert [r["seq"] for r in doc["trace"]["steps"]] == [1]
    assert doc["trace"]["step_seq"] == 1
    # nothing new: next beat ships no steps
    hb.send_once()
    doc2 = json.loads(c.payloads[-1])
    assert doc2["trace"]["steps"] == []
    telemetry.step_begin()
    telemetry.step_end()
    hb.send_once()
    doc3 = json.loads(c.payloads[-1])
    assert [r["seq"] for r in doc3["trace"]["steps"]] == [2]


def test_beat_byte_cap_truncates_oldest_first(monkeypatch):
    monkeypatch.setenv("DMLC_TELEMETRY_MAX_BEAT_BYTES", "20000")
    for i in range(500):  # a span storm
        with telemetry.span(f"storm.{i}", stage="smoke"):
            pass
    for _ in range(8):
        telemetry.step_begin()
        telemetry.step_end(tokens=1)
    c = _FakeClient()
    _hb, doc = _beat(c)
    assert len(c.payloads[-1]) <= 20000
    spans = doc["trace"]["spans"]
    # truncation drops the OLDEST: the newest span must survive
    kept = [s["name"] for s in spans if s["name"].startswith("storm.")]
    assert "storm.499" in kept and "storm.0" not in kept
    # the shrink is counted where /metrics can see it
    assert telemetry.counters_snapshot()["telemetry"][
        "beats_truncated"] == 1


def test_beat_under_cap_not_truncated():
    telemetry.step_begin()
    telemetry.step_end()
    c = _FakeClient()
    _hb, doc = _beat(c)
    assert doc["trace"]["steps"]
    assert "telemetry" not in telemetry.counters_snapshot()


# ---------------------------------------------------------------------------
# Watchdog verdicts
# ---------------------------------------------------------------------------

def _steps(n, wall, start=1, feed=0.0, goodput=None, t0=1000.0):
    out = []
    for i in range(n):
        out.append({"seq": start + i, "wall_s": wall,
                    "feed_wait_s": feed, "t_wall": t0 + i,
                    "goodput_tokens_per_s": goodput})
    return out


def test_watchdog_flags_straggler_rank_only():
    w = Watchdog(k=4, window=3)
    w.ingest(0, _steps(20, 0.01), anchor=1.0)
    w.ingest(1, _steps(20, 0.05), anchor=1.0)
    rep = w.report()
    assert rep["ranks"]["1"]["flags"] == ["straggler"]
    assert rep["ranks"]["0"]["flags"] == []
    assert {(a["rank"], a["kind"]) for a in rep["active"]} == {
        (1, "straggler")}
    assert rep["recent_verdicts"]
    # verdict counters + event ring + markers all fired
    assert telemetry.counters_snapshot()["anomaly"][
        "straggler_flags"] == 1
    kinds = [e["kind"] for e in telemetry.events_tail()]
    assert "anomaly" in kinds
    assert any("straggler rank 1" in m["name"]
               for m in w.trace_markers())


def test_watchdog_straggler_clears_when_rank_recovers():
    w = Watchdog(k=4, window=3)
    w.ingest(0, _steps(20, 0.01), anchor=1.0)
    w.ingest(1, _steps(20, 0.05), anchor=1.0)
    assert w.report()["ranks"]["1"]["flags"] == ["straggler"]
    w.ingest(1, _steps(20, 0.01, start=21), anchor=1.0)
    assert w.report()["ranks"]["1"]["flags"] == []


def test_watchdog_single_spike_not_flagged():
    w = Watchdog(k=4, window=3)
    w.ingest(0, _steps(20, 0.01), anchor=1.0)
    w.ingest(1, _steps(19, 0.01) + _steps(1, 0.5, start=20), anchor=1.0)
    assert w.report()["ranks"]["1"]["flags"] == []


def test_watchdog_regression_on_sustained_slowdown():
    w = Watchdog(window=3)
    w.ingest(0, _steps(30, 0.01), anchor=1.0)
    w.ingest(0, _steps(10, 0.03, start=31), anchor=1.0)
    assert "regression" in w.report()["ranks"]["0"]["flags"]


def test_watchdog_feed_stall_dominance():
    w = Watchdog(window=3)
    recs = _steps(30, 0.02, feed=0.015)
    w.ingest(0, recs, anchor=1.0)
    assert "feed_stall" in w.report()["ranks"]["0"]["flags"]


def test_watchdog_goodput_collapse():
    w = Watchdog(window=3)
    w.ingest(0, _steps(30, 0.01, goodput=1000.0), anchor=1.0)
    w.ingest(0, _steps(10, 0.01, start=31, goodput=100.0), anchor=1.0)
    assert "goodput_collapse" in w.report()["ranks"]["0"]["flags"]


def test_watchdog_dedups_reshipped_records():
    w = Watchdog(window=3)
    recs = _steps(10, 0.01)
    w.ingest(0, recs, anchor=1.0)
    w.ingest(0, recs, anchor=1.0)  # torn-beat reship
    assert w.report()["ranks"]["0"]["steps"] == 10


def test_watchdog_restart_resets_baselines():
    w = Watchdog(window=3)
    w.ingest(0, _steps(30, 0.01), anchor=1.0)
    # restarted worker: new anchor, seq restarts at 1 — records must be
    # accepted (not dropped by the old seq high-water mark)
    w.ingest(0, _steps(5, 0.02), anchor=2.0)
    assert w.report()["ranks"]["0"]["steps"] == 5


def test_watchdog_ingest_json_and_malformed_payloads():
    w = Watchdog(window=2)
    payload = json.dumps({"trace": {"anchor": 1.0,
                                    "steps": _steps(3, 0.01)}})
    w.ingest_json(0, payload)
    assert w.report()["ranks"]["0"]["steps"] == 3
    w.ingest_json(0, "not json")
    w.ingest_json(0, json.dumps({"trace": {"steps": [
        {"wall_s": "garbage"}, 17, {"seq": 9, "wall_s": 0.01,
                                    "t_wall": 1.0}]}}))
    assert w.report()["ranks"]["0"]["steps"] == 4


def test_watchdog_drop_forgets_rank():
    w = Watchdog(window=3)
    w.ingest(0, _steps(10, 0.01), anchor=1.0)
    w.drop(0)
    assert w.report()["ranks"] == {}


def test_watchdog_prometheus_gauges():
    w = Watchdog(k=4, window=3)
    w.ingest(0, _steps(20, 0.01), anchor=1.0)
    w.ingest(1, _steps(20, 0.05), anchor=1.0)
    text = w.prometheus_text()
    assert '# TYPE dmlc_anomaly_active gauge' in text
    assert 'dmlc_anomaly_active{rank="1",kind="straggler"} 1' in text
    assert 'dmlc_anomaly_active{rank="0",kind="straggler"} 0' in text
    for kind in ANOMALY_KINDS:
        assert f'kind="{kind}"' in text


# ---------------------------------------------------------------------------
# flight-recorder anomaly markers
# ---------------------------------------------------------------------------

def test_flight_trace_includes_anomaly_markers():
    from dmlc_tpu.telemetry import FlightRecorder

    fr = FlightRecorder()
    t0 = time.time()
    fr.ingest(0, {"anchor": t0, "spans": [
        {"seq": 1, "name": "work", "cat": "x", "ts": 0.0,
         "dur": 5.0, "tid": 1}]})
    fr.marker_source = lambda: [{"t": t0 + 1.0, "name": "anomaly:x"}]
    doc = fr.to_chrome_trace()
    markers = [e for e in doc["traceEvents"] if e.get("ph") == "i"]
    assert len(markers) == 1
    assert markers[0]["name"] == "anomaly:x"
    assert markers[0]["ts"] == pytest.approx(1e6, rel=0.01)
    assert markers[0]["s"] == "g"


# ---------------------------------------------------------------------------
# dmlc-top renderer
# ---------------------------------------------------------------------------

def test_dmlc_top_render_table():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "dmlc_top", os.path.join(os.path.dirname(__file__), "..",
                                 "scripts", "dmlc_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    doc = {
        "anomalies": {
            "cluster": {"median_step_s": 0.02},
            "ranks": {
                "0": {"step_time_s": 0.02, "step_time_ewma_s": 0.021,
                      "goodput_tokens_per_s": 12000.0, "mfu": 0.41,
                      "feed_stall_frac": 0.05, "flags": []},
                "1": {"step_time_s": 0.17, "step_time_ewma_s": 0.171,
                      "goodput_tokens_per_s": 1500.0, "mfu": None,
                      "feed_stall_frac": None,
                      "flags": ["straggler"]},
            },
            "active": [{"rank": 1, "kind": "straggler"}],
            "recent_verdicts": [{"rank": 1, "kind": "straggler",
                                 "detail": "slow"}],
        },
        "healthz": {"ranks_reporting": 2,
                    "ranks": {"0": 0.1, "1": 4.2},
                    "dead_ranks": [1]},
    }
    text = top.render_table(doc, "http://t:1")
    lines = text.splitlines()
    assert "RANK" in lines[1]
    row0 = next(line for line in lines if line.strip().startswith("0 "))
    row1 = next(line for line in lines if line.strip().startswith("1 "))
    assert "41.0" in row0 and "12,000" in row0
    assert "straggler" in row1 and "DEAD" in row1
    # None fields render as "-", never crash
    assert " - " in row1 or row1.rstrip().endswith("-") or "-" in row1
    assert any("! rank 1 straggler" in line for line in lines)


def test_dmlc_top_render_empty_doc():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "top_view_fixture", os.path.join(os.path.dirname(__file__), "..",
                                         "scripts", "dmlc_top.py"))
    top = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(top)
    text = top.render_table({"anomalies": {}, "healthz": {}}, "u")
    assert "RANK" in text  # header renders even with nothing to show
