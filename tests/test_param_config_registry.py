"""Parameter/Config/Registry tests (mirror reference unittest_param.cc,
unittest_config.cc, test/registry_test.cc)."""

import pytest

from dmlc_tpu import Config, DMLCError, ParamError, Parameter, Registry, field
from dmlc_tpu.base import get_env
from dmlc_tpu.io.stream import MemoryBytesStream
from dmlc_tpu.param import ParamInitOption


class LearningParam(Parameter):
    float_param = field(float, 0.01).set_range(0.0, 1.0).set_describe("a float")
    int_param = field(int, 5).set_lower_bound(0)
    name = field(str, "sgd")
    opt = field(str, "adam").add_enum("adam").add_enum("sgd").add_alias("optimizer")
    flag = field(bool, False)


def test_defaults():
    p = LearningParam()
    assert p.float_param == 0.01 and p.int_param == 5 and p.opt == "adam"


def test_init_kwargs_with_string_coercion():
    p = LearningParam()
    p.init({"float_param": "0.5", "int_param": "7", "flag": "true"})
    assert p.float_param == 0.5 and p.int_param == 7 and p.flag is True


def test_out_of_range_raises():
    # mirrors unittest_param.cc:9-21 (float out of range -> ParamError)
    p = LearningParam()
    with pytest.raises(ParamError, match="float_param"):
        p.init({"float_param": "2.5"})
    with pytest.raises(ParamError, match="int_param"):
        p.init({"int_param": -1})


def test_bad_type_raises():
    with pytest.raises(ParamError):
        LearningParam().init({"int_param": "not_an_int"})


def test_enum_and_alias():
    p = LearningParam()
    p.init({"optimizer": "sgd"})
    assert p.opt == "sgd"
    with pytest.raises(ParamError, match="opt"):
        p.init({"opt": "rmsprop"})


def test_unknown_key_policies():
    p = LearningParam()
    unknown = p.init({"mystery": 1}, ParamInitOption.ALLOW_UNKNOWN)
    assert unknown == {"mystery": 1}
    with pytest.raises(ParamError, match="mystery"):
        p.init({"mystery": 1}, ParamInitOption.ALL_MATCH)
    # hidden keys are dunder-shaped and skipped (parameter.h:399-404)
    assert p.init({"__hidden__": 1}, ParamInitOption.ALLOW_HIDDEN) == {}
    with pytest.raises(ParamError, match="_notdunder"):
        p.init({"_notdunder": 1}, ParamInitOption.ALLOW_HIDDEN)


def test_required_field():
    class Req(Parameter):
        must = field(int)

    with pytest.raises(ParamError, match="must"):
        Req().init({})
    r = Req()
    r.init({"must": 3})
    assert r.must == 3


def test_dict_json_roundtrip():
    p = LearningParam()
    p.init({"float_param": 0.25})
    s = MemoryBytesStream()
    p.save(s)
    s.seek(0)
    q = LearningParam()
    q.load(s)
    assert q.float_param == 0.25
    assert set(p.to_dict()) == {"float_param", "int_param", "name", "opt", "flag"}


def test_doc_string():
    doc = LearningParam.doc_string()
    assert "float_param" in doc and "range=[0.0, 1.0]" in doc and "a float" in doc


def test_update_dict():
    p = LearningParam()
    kw = {"float_param": "0.125", "extra": "x"}
    p.update_dict(kw)
    assert kw["float_param"] == 0.125 and kw["extra"] == "x"


def test_get_env(monkeypatch):
    monkeypatch.setenv("DMLC_TEST_ENV_I", "42")
    monkeypatch.setenv("DMLC_TEST_ENV_B", "true")
    assert get_env("DMLC_TEST_ENV_I", 0) == 42
    assert get_env("DMLC_TEST_ENV_B", False) is True
    assert get_env("DMLC_TEST_ENV_MISSING", 7) == 7


# ---- Config (unittest_config.cc:115) -----------------------------------

def test_config_basic():
    cfg = Config("k1 = v1\n# comment\nk2=3.5\n\nk3 = \"quoted # not comment\"\n")
    assert cfg.get_param("k1") == "v1"
    assert cfg.get_param("k2") == "3.5"
    assert cfg.get_param("k3") == "quoted # not comment"
    assert "k4" not in cfg


def test_config_trailing_comment_and_override():
    cfg = Config("a = 1 # one\na = 2\n")
    assert cfg.get_param("a") == "2"
    assert cfg.items() == [("a", "2")]


def test_config_multi_value():
    cfg = Config("a=1\na=2\n", multi_value=True)
    assert cfg.get_all("a") == ["1", "2"]
    assert cfg.items() == [("a", "1"), ("a", "2")]


def test_config_proto_string():
    cfg = Config('x = a"b\n')
    assert cfg.to_proto_string() == 'x : "a\\"b"\n'


def test_config_bad_line():
    with pytest.raises(DMLCError):
        Config("not_a_kv_line\n")


# ---- Registry ----------------------------------------------------------

def test_registry_register_find_alias():
    reg = Registry.get("test_kind_a")

    @reg.register("tree")
    def make_tree(depth=3):
        return ("tree", depth)

    reg.entry("tree").describe("a tree factory").add_argument("depth", "int", "max depth")
    reg.add_alias("tree", "gbtree")
    assert reg.create("tree", depth=5) == ("tree", 5)
    assert reg.create("gbtree") == ("tree", 3)
    assert reg.find("nope") is None
    assert reg.list_all_names() == ["gbtree", "tree"]
    assert reg.entry("tree").description == "a tree factory"


def test_registry_duplicate_and_unknown():
    reg = Registry.get("test_kind_b")
    reg.register("x", lambda: 1)
    with pytest.raises(DMLCError):
        reg.register("x", lambda: 2)
    reg.register("x", lambda: 2, override=True)
    assert reg.create("x") == 2
    with pytest.raises(DMLCError, match="unknown"):
        reg.create("zzz")
