"""Tests for the parallelism layer on an 8-device virtual CPU mesh.

Covers mesh factorization, the collective surface, ring attention vs the
unsharded oracle, Ulysses all-to-all attention, and the SPMD pipeline —
the multi-chip machinery the reference delegated to rabit/ps-lite
(SURVEY.md §2.7), rebuilt on XLA collectives.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dmlc_tpu.parallel import (
    all_gather,
    all_reduce,
    all_to_all,
    broadcast,
    build_mesh,
    factorize_devices,
    pipeline,
    ppermute_ring,
    reduce_scatter,
    ring_attention_reference,
    ulysses_attention,
)
from dmlc_tpu.parallel.mesh import MESH_AXES, mesh_config
from dmlc_tpu.parallel.ring_attention import make_sharded_ring_attention


def test_factorize_exact():
    shape = factorize_devices(8)
    assert np.prod(list(shape.values())) == 8
    assert shape["tp"] == 2 and shape["sp"] == 2 and shape["pp"] == 2
    shape = factorize_devices(8, tp=4, pp=1)
    assert shape["tp"] == 4
    with pytest.raises(ValueError):
        factorize_devices(8, tp=3)


def test_build_mesh_and_part_contract():
    mesh = build_mesh(8)
    assert mesh.axis_names == MESH_AXES
    cfg = mesh_config(mesh)
    assert cfg.n_devices == 8
    assert cfg.data_parts == cfg.axis_size("dp") * cfg.axis_size("sp")
    # part_index enumerates (dp, sp) row-major
    seen = set()
    for d in range(cfg.axis_size("dp")):
        for s in range(cfg.axis_size("sp")):
            seen.add(cfg.part_index({"dp": d, "sp": s}))
    assert seen == set(range(cfg.data_parts))


@pytest.fixture(scope="module")
def mesh8():
    return build_mesh(8, tp=1, sp=8, pp=1)  # one flat ring for collective tests


def _smap(mesh, fn, in_specs, out_specs):
    return jax.shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)


def test_collectives_numerics(mesh8):
    x = jnp.arange(8.0)

    out = _smap(mesh8, lambda v: all_reduce(v, "sp"), (P("sp"),), P("sp"))(x)
    np.testing.assert_allclose(out, np.full(8, 28.0))

    out = _smap(mesh8, lambda v: all_gather(v, "sp"), (P("sp"),), P("sp"))(x)
    assert out.shape == (64,)
    np.testing.assert_allclose(out[:8], np.arange(8.0))

    out = _smap(mesh8, lambda v: reduce_scatter(v, "sp"), (P(None),), P("sp"))(
        jnp.ones(8)
    )
    np.testing.assert_allclose(out, np.full(8, 8.0))

    out = _smap(mesh8, lambda v: broadcast(v, "sp", root=3), (P("sp"),), P("sp"))(x)
    np.testing.assert_allclose(out, np.full(8, 3.0))

    out = _smap(mesh8, lambda v: ppermute_ring(v, "sp", 1), (P("sp"),), P("sp"))(x)
    np.testing.assert_allclose(out, np.roll(np.arange(8.0), 1))


def test_all_to_all(mesh8):
    # a2a re-shards rows→columns: rank i starts with row i ([1,8]) and ends
    # with column i ([8,1]); the global value is unchanged.
    x = jnp.arange(64.0).reshape(8, 8)
    out = _smap(
        mesh8,
        lambda v: all_to_all(v, "sp", split_axis=1, concat_axis=0),
        (P("sp", None),),
        P(None, "sp"),
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = build_mesh(8, sp=4, tp=2, pp=1, dp=1)
    b, t, h, d = 2, 32, 4, 8
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)

    want = ring_attention_reference(q, k, v, causal=causal)
    fn = make_sharded_ring_attention(mesh, causal=causal)
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_ring_attention_grads_flow():
    mesh = build_mesh(8, sp=4, tp=2, pp=1, dp=1)
    b, t, h, d = 1, 16, 2, 4
    q = jax.random.normal(jax.random.PRNGKey(1), (b, t, h, d))
    fn = make_sharded_ring_attention(mesh, causal=True)

    def loss(q):
        return jnp.sum(fn(q, q, q) ** 2)

    def loss_ref(q):
        return jnp.sum(ring_attention_reference(q, q, q, causal=True) ** 2)

    g = jax.grad(loss)(q)
    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4)


def test_ulysses_matches_reference():
    # local heads (h/tp = 4) must be divisible by sp (4) for the a2a re-shard
    mesh = build_mesh(8, sp=4, tp=2, pp=1, dp=1)
    b, t, h, d = 2, 32, 8, 8
    key = jax.random.PRNGKey(2)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d))
    k = jax.random.normal(kk, (b, t, h, d))
    v = jax.random.normal(kv, (b, t, h, d))
    want = ring_attention_reference(q, k, v, causal=True)

    spec = P(None, "sp", "tp", None)
    fn = jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis_name="sp", causal=True),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
    )
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_pipeline_matches_sequential():
    n_stage, m, mb, dim = 4, 8, 2, 16
    mesh = build_mesh(8, pp=4, tp=2, sp=1, dp=1)
    key = jax.random.PRNGKey(3)
    ws = jax.random.normal(key, (n_stage, dim, dim)) / np.sqrt(dim)
    x = jax.random.normal(jax.random.PRNGKey(4), (m, mb, dim))

    def stage_fn(w, h):
        return jnp.tanh(h @ w)

    # sequential oracle
    want = x
    for s in range(n_stage):
        want = stage_fn(ws[s], want)

    def inner(w_local, x_mb):
        return pipeline.pipeline_spmd(stage_fn, w_local[0], x_mb, axis_name="pp")

    fn = jax.shard_map(
        inner, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(), check_vma=False,
    )
    got = jax.jit(fn)(ws, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-5)


def test_ring_attention_degenerate_ring_uses_flash(monkeypatch):
    """sp axis of size 1 must route to the standalone flash kernel
    (kernel backward + remat policy) and still match the oracle."""
    import numpy as np

    from dmlc_tpu.parallel import build_mesh
    from dmlc_tpu.parallel.ring_attention import (
        make_sharded_ring_attention, ring_attention_reference)

    import dmlc_tpu.ops.flash_attention as _flash

    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    b, t, h, d = 1, 64, 2, 128
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q, k, v = [jax.random.normal(k_, (b, t, h, d), jnp.float32) for k_ in ks]
    want = ring_attention_reference(q, k, v, causal=True)
    calls = []
    orig = _flash.flash_attention
    monkeypatch.setattr(
        _flash, "flash_attention",
        lambda *a, **kw: calls.append(1) or orig(*a, **kw))
    got = make_sharded_ring_attention(mesh, causal=True, impl="flash")(q, k, v)
    assert calls, "n==1 ring must route to the standalone flash kernel"
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)
    # and gradients flow through the standalone custom_vjp path
    g = jax.grad(lambda q_: jnp.sum(make_sharded_ring_attention(
        mesh, causal=True, impl="flash")(q_, k, v)))(q)
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_flash_impl_matches_reference(causal):
    # the Pallas kernel (interpret mode on CPU) wired into the ring loop
    mesh = build_mesh(8, sp=4, tp=2, pp=1, dp=1)
    b, t, h, d = 1, 64, 2, 128  # d aligned for the kernel's lane gate
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)

    want = ring_attention_reference(q, k, v, causal=causal)
    fn = make_sharded_ring_attention(mesh, causal=causal, impl="flash")
    got = jax.jit(fn)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


# ---------------------------------------------------------------------------
# bucketed_psum_mean (parallel/overlap.py device path): one lax.psum per
# reverse-topological bucket must equal the fused pmean — vmap's named
# axis exercises the psum semantics without needing shard_map
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bucket_bytes_", [16, 64, 1 << 20])
def test_bucketed_psum_mean_matches_fused(bucket_bytes_):
    from dmlc_tpu.parallel.overlap import bucketed_psum_mean

    n = 4
    rng = np.random.default_rng(0)
    tree = {
        "w": rng.normal(size=(n, 3, 5)).astype(np.float32),
        "b": rng.normal(size=(n, 7)).astype(np.float32),
        "scale": rng.normal(size=(n, 1)).astype(np.float32),
    }

    out = jax.vmap(lambda t: bucketed_psum_mean(
        t, "i", bucket_bytes_=bucket_bytes_), axis_name="i")(tree)
    for key in tree:
        want = np.broadcast_to(tree[key].mean(axis=0, keepdims=True),
                               tree[key].shape)
        np.testing.assert_allclose(np.asarray(out[key]), want,
                                   rtol=1e-6, atol=1e-6)


def test_bucketed_psum_mean_splits_on_dtype_boundary():
    """Mixed-dtype leaves cannot share a concatenated bucket — the
    bucketer must split them, and both dtypes still reduce correctly."""
    from dmlc_tpu.parallel.overlap import bucketed_psum_mean

    n = 2
    tree = [jnp.arange(2 * n, dtype=jnp.float32).reshape(n, 2),
            jnp.arange(3 * n, dtype=jnp.bfloat16).reshape(n, 3)]
    out = jax.vmap(lambda t: bucketed_psum_mean(t, "i", bucket_bytes_=1 << 20),
                   axis_name="i")(tree)
    for got, src in zip(out, tree):
        assert got.dtype == src.dtype
        want = np.broadcast_to(
            np.asarray(src, np.float32).mean(axis=0, keepdims=True),
            src.shape)
        np.testing.assert_allclose(np.asarray(got, np.float32), want,
                                   rtol=1e-2)


def test_make_train_step_overlap_arg_validation():
    from dmlc_tpu.models import TransformerConfig, make_train_step
    from dmlc_tpu.parallel import build_mesh

    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    cfg = TransformerConfig(vocab=32, d_model=16, n_heads=2, head_dim=8,
                            d_ff=32, n_layers=1, n_experts=1)
    with pytest.raises(ValueError, match="overlap"):
        make_train_step(mesh, cfg, overlap="bogus")
