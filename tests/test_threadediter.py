"""ThreadedIter lifecycle under a racy producer (mirrors reference
test/unittest/unittest_threaditer.cc — randomized producer delays to shake
out races/deadlocks, BeforeFirst mid-stream)."""

import random
import time

import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.concurrency import ConcurrentBlockingQueue, MultiThreadedIter, ThreadedIter


class Source:
    """Produces boxed ints 0..n-1 with random delays (unittest_threaditer.cc:9-16)."""

    def __init__(self, n, seed=0, max_delay=0.002):
        self.n = n
        self.i = 0
        self.rng = random.Random(seed)
        self.max_delay = max_delay
        self.recycled_hits = 0

    def next(self, recycled):
        if self.max_delay:
            time.sleep(self.rng.random() * self.max_delay)
        if self.i >= self.n:
            return None
        if recycled is not None:
            self.recycled_hits += 1
            recycled[0] = self.i
            out = recycled
        else:
            out = [self.i]
        self.i += 1
        return out

    def before_first(self):
        self.i = 0


def drain(it):
    out = []
    while True:
        ok, v = it.next()
        if not ok:
            return out
        out.append(v[0])
        it.recycle(v)


def test_basic_order_and_recycle():
    src = Source(200, max_delay=0)
    it = ThreadedIter(src.next, src.before_first, max_capacity=4)
    assert drain(it) == list(range(200))
    assert src.recycled_hits > 0, "free-list recycling never engaged"
    it.destroy()


def test_racy_producer():
    src = Source(100, seed=42)
    it = ThreadedIter(src.next, src.before_first, max_capacity=2)
    assert drain(it) == list(range(100))
    it.destroy()


def test_before_first_mid_stream():
    src = Source(50, max_delay=0.001)
    it = ThreadedIter(src.next, src.before_first, max_capacity=2)
    got = []
    for _ in range(10):
        ok, v = it.next()
        assert ok
        got.append(v[0])
        it.recycle(v)
    assert got == list(range(10))
    it.before_first()
    assert drain(it) == list(range(50))
    it.destroy()


def test_repeated_epochs():
    src = Source(30, max_delay=0)
    it = ThreadedIter(src.next, src.before_first, max_capacity=8)
    for _ in range(5):
        assert drain(it) == list(range(30))
        it.before_first()
    it.destroy()


def test_producer_exception_propagates():
    def bad_next(recycled):
        raise ValueError("boom")

    it = ThreadedIter(bad_next, None, max_capacity=2)
    with pytest.raises(DMLCError, match="boom"):
        it.next()
    it.destroy()


def test_destroy_while_blocked():
    """destroy with a full queue and no consumer progress must not hang
    (threadediter.h:236-269 destroy-while-blocked)."""
    src = Source(10_000, max_delay=0)
    it = ThreadedIter(src.next, src.before_first, max_capacity=2)
    ok, v = it.next()
    assert ok
    start = time.time()
    it.destroy()
    assert time.time() - start < 5.0


def test_concurrent_queue_fifo_and_kill():
    q = ConcurrentBlockingQueue(max_size=4)
    for i in range(4):
        assert q.push(i)
    assert q.pop() == (True, 0)
    q.signal_for_kill()
    assert q.push(99) is False
    # drain remaining then fail
    assert q.pop()[0] is True
    assert q.pop()[0] is True
    assert q.pop()[0] is True
    assert q.pop() == (False, None)


def test_concurrent_queue_priority():
    q = ConcurrentBlockingQueue(priority=True)
    q.push("low", priority=1)
    q.push("high", priority=10)
    q.push("mid", priority=5)
    assert q.pop() == (True, "high")
    assert q.pop() == (True, "mid")
    assert q.pop() == (True, "low")


def test_multithreaded_iter():
    items = list(range(100))
    idx = [0]

    def source_next():
        if idx[0] >= len(items):
            return None
        v = items[idx[0]]
        idx[0] += 1
        return v

    mit = MultiThreadedIter(source_next, lambda x: x * 2, num_threads=3)
    out = []
    while True:
        ok, v = mit.next()
        if not ok:
            break
        out.append(v)
    assert sorted(out) == [2 * i for i in range(100)]
    # exhausted iterator keeps returning end-of-stream, never blocks
    assert mit.next() == (False, None)
    mit.destroy()


def test_multithreaded_iter_worker_exception():
    idx = [0]

    def source_next():
        if idx[0] >= 10:
            return None
        idx[0] += 1
        return idx[0]

    def bad_work(x):
        if x == 5:
            raise ValueError("worker boom")
        return x

    mit = MultiThreadedIter(source_next, bad_work, num_threads=2)
    with pytest.raises(DMLCError, match="worker boom"):
        while True:
            ok, _ = mit.next()
            if not ok:
                break
    mit.destroy()


# ---------------------------------------------------------------------------
# BufferPool: the staging-buffer recycle contract behind DeviceFeed
# ---------------------------------------------------------------------------

def test_buffer_pool_lazy_creation_and_reuse():
    from dmlc_tpu.concurrency import BufferPool

    built = []

    def factory():
        built.append(object())
        return built[-1]

    pool = BufferPool(factory, capacity=2)
    a = pool.acquire()
    b = pool.acquire()
    assert len(built) == 2 and pool.created == 2
    pool.release(a)
    c = pool.acquire()
    assert c is a          # recycled, not rebuilt
    assert len(built) == 2  # capacity bounds total construction


def test_buffer_pool_blocks_until_release():
    import threading

    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=1)
    first = pool.acquire()
    got = []

    def taker():
        got.append(pool.acquire())

    t = threading.Thread(target=taker)
    t.start()
    t.join(0.15)
    assert t.is_alive() and not got  # blocked: capacity exhausted
    pool.release(first)
    t.join(5)
    assert got == [first]


def test_buffer_pool_acquire_timeout_and_kill():
    import threading

    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=1)
    pool.acquire()
    assert pool.acquire(timeout=0.05) is None  # timed out, not deadlocked
    results = []

    def taker():
        results.append(pool.acquire())

    t = threading.Thread(target=taker)
    t.start()
    t.join(0.1)
    assert t.is_alive()
    pool.kill()
    t.join(5)
    assert results == [None]       # kill wakes blocked acquirers
    assert pool.acquire() is None  # and poisons future acquires


def test_buffer_pool_factory_failure_releases_capacity():
    from dmlc_tpu.concurrency import BufferPool

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("boom")
        return object()

    pool = BufferPool(flaky, capacity=1)
    with pytest.raises(RuntimeError):
        pool.acquire()
    # the failed build must not leak its capacity slot
    assert pool.acquire() is not None


def test_buffer_pool_kill_wakes_timed_waiter_before_deadline():
    """The serving admission path parks submitters with a timeout;
    kill() (engine shutdown) must wake them with None immediately, not
    leave them burning the rest of their deadline."""
    import threading
    import time

    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=1)
    pool.acquire()
    results = []

    def taker():
        t0 = time.monotonic()
        results.append((pool.acquire(timeout=30.0), time.monotonic() - t0))

    t = threading.Thread(target=taker)
    t.start()
    time.sleep(0.1)
    pool.kill()
    t.join(5)
    assert not t.is_alive()
    got, waited = results[0]
    assert got is None
    assert waited < 5.0, f"kill took {waited:.1f}s to wake a timed waiter"


def test_buffer_pool_timeout_zero_is_nonblocking():
    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=1)
    first = pool.acquire(timeout=0)
    assert first is not None          # capacity available: no wait needed
    assert pool.acquire(timeout=0) is None  # exhausted: immediate None
    pool.release(first)
    assert pool.acquire(timeout=0) is first  # freed: immediate success


def test_buffer_pool_release_during_timed_wait_hands_over():
    import threading
    import time

    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=1)
    held = pool.acquire()
    results = []

    def taker():
        results.append(pool.acquire(timeout=30.0))

    t = threading.Thread(target=taker)
    t.start()
    time.sleep(0.05)
    pool.release(held)
    t.join(5)
    assert results == [held]  # the waiter got the released buffer


def test_buffer_pool_timeout_expiry_does_not_leak_capacity():
    """A timed-out acquire must leave the pool fully usable: the next
    release still satisfies the next acquire (no phantom slot)."""
    from dmlc_tpu.concurrency import BufferPool

    pool = BufferPool(lambda: object(), capacity=2)
    a = pool.acquire()
    b = pool.acquire()
    for _ in range(3):
        assert pool.acquire(timeout=0.01) is None
    pool.release(a)
    assert pool.acquire(timeout=0.01) is a
    pool.release(b)
    pool.release(a)
    assert pool.acquire() is not None
    assert pool.acquire() is not None
    assert pool.created == 2  # timeouts never minted extra buffers
