"""dmlc-trace: fleet trace context, decision audit log, and the
router-side FleetTraceStore (telemetry.tracecontext).

The unit tests drive synthetic span-increment docs through the store
so the join/merge/summarize contracts are checked exactly; one test
runs a real Router against a scriptable replica with tracing OFF and
the id-minting functions booby-trapped, proving the documented
zero-overhead off path (the ``profiled_jit`` discipline).
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry import tracecontext
from dmlc_tpu.telemetry.requests import RequestLedger
from dmlc_tpu.telemetry.tracecontext import (DecisionLog, FleetTraceStore,
                                             TRACE_HEADER, format_header,
                                             mint_trace_id, new_span_id,
                                             parse_header)


@pytest.fixture(autouse=True)
def _clean():
    telemetry.reset()
    tracecontext.reset_decisions()
    yield
    telemetry.reset()
    tracecontext.reset_decisions()


# ---------------------------------------------------------------------------
# context propagation primitives
# ---------------------------------------------------------------------------

def test_header_roundtrip_and_tolerant_parse():
    tid, sid = mint_trace_id("req-1"), new_span_id()
    assert parse_header(format_header(tid, sid)) == (tid, sid)
    # tolerant: case and surrounding whitespace are normalized
    assert parse_header(f"  {tid.upper()}-{sid.upper()} ") == (tid, sid)
    # a bad tracer upstream must never fail a request
    for garbage in (None, "", "nope", tid, f"{tid}-{sid}-extra",
                    f"{tid[:-1]}-{sid}", f"{tid}-{sid[:-1]}",
                    f"{tid[:-1]}g-{sid}", 7):
        assert parse_header(garbage) is None


def test_mint_is_deterministic_and_span_ids_are_not():
    a, b = mint_trace_id("req-1"), mint_trace_id("req-1")
    assert a == b and len(a) == 32 and int(a, 16) >= 0
    assert mint_trace_id("req-2") != a
    s1, s2 = new_span_id(), new_span_id()
    assert len(s1) == 16 and int(s1, 16) >= 0
    assert s1 != s2


# ---------------------------------------------------------------------------
# decision audit log
# ---------------------------------------------------------------------------

def test_decision_log_incremental_export_contract():
    log = DecisionLog(capacity=8)
    for i in range(5):
        rec = log.record("scale_up", replica=f"r{i}")
        assert rec["seq"] == i + 1 and rec["kind"] == "scale_up"
    recs, last = log.records_since(0)
    assert last == 5 and [r["seq"] for r in recs] == [1, 2, 3, 4, 5]
    # the ?since= cursor never re-reads history
    recs, last = log.records_since(3)
    assert [r["seq"] for r in recs] == [4, 5] and last == 5
    recs, _ = log.records_since(5)
    assert recs == []
    # limit caps at the OLDEST records (the poller catches up in order)
    recs, _ = log.records_since(0, limit=2)
    assert [r["seq"] for r in recs] == [1, 2]


def test_decision_log_capacity_bounds_ring_but_seq_is_monotone():
    log = DecisionLog(capacity=4)
    for i in range(10):
        log.record("k", i=i)
    recs, last = log.records_since(0)
    assert last == 10
    assert [r["seq"] for r in recs] == [7, 8, 9, 10]  # oldest evicted
    assert [r["t"] <= time.time() for r in recs] == [True] * 4
    assert log.tail(2)[-1]["seq"] == 10
    log.reset()
    assert log.records_since(0) == ([], 10)  # seq keeps going
    assert log.record("k")["seq"] == 11


def test_default_ring_singleton_and_reset():
    tracecontext.record_decision("tenant_rejected", tenant="free")
    recs, last = tracecontext.decision_log().records_since(0)
    assert last == 1 and recs[0]["tenant"] == "free"
    tracecontext.reset_decisions()
    assert tracecontext.decision_log().records_since(0) == ([], 0)


# ---------------------------------------------------------------------------
# fleet trace assembly
# ---------------------------------------------------------------------------

TID = mint_trace_id("req-join")


def _span(name, ts_us, dur_us=1000.0, cat="serving", tid=1, **args):
    rec = {"name": name, "ts": ts_us, "dur": dur_us, "cat": cat,
           "tid": tid, "seq": 0}
    if args:
        rec["args"] = args
    return rec


def test_store_keeps_only_the_trace_join():
    st = FleetTraceStore(max_spans_per_source=64)
    kept = st.ingest("router", {"anchor_epoch": 100.0, "last_seq": 3,
                                "spans": [
        _span("router.dispatch", 0.0, cat="router", trace_id=TID,
              replica="http://r1"),
        _span("router.circuit_open", 10.0, cat="router"),  # control plane
        _span("engine.step", 20.0, cat="engine"),          # not a join span
        "garbage",
    ]})
    assert kept == 2
    assert st.cursor("router") == 3 and st.sources() == ["router"]
    # only the trace-stamped span names a trace
    assert st.trace_ids() == [TID]


def test_timeline_summary_and_slowest_first_ordering():
    st = FleetTraceStore(max_spans_per_source=64)
    # router: primary dispatch + a later hedge to a second replica
    st.ingest("router", {"anchor_epoch": 100.0, "last_seq": 2, "spans": [
        _span("router.dispatch", 0.0, 50e4, cat="router", trace_id=TID,
              replica="http://r1", kind="primary"),
        _span("router.dispatch", 20e4, 30e4, cat="router", trace_id=TID,
              replica="http://r2", kind="hedge"),
    ]})
    # r1 saw queue+prefill before dying; r2 finished it
    st.ingest("http://r1", {"anchor_epoch": 100.0, "last_seq": 2,
                            "spans": [
        _span("serving.queue", 1e4, 2e4, trace_id=TID),
        _span("serving.prefill", 3e4, 4e4, trace_id=TID),
    ]})
    st.ingest("http://r2", {"anchor_epoch": 100.2, "last_seq": 1,
                            "spans": [
        _span("serving.decode", 1e4, 25e4, trace_id=TID),
    ]})
    # a second, faster trace -> must sort AFTER the slow one
    tid2 = mint_trace_id("req-fast")
    st.ingest("router", {"anchor_epoch": 100.0, "last_seq": 3, "spans": [
        _span("router.dispatch", 90e4, 1e4, cat="router", trace_id=tid2,
              replica="http://r1"),
    ]})

    tracecontext.record_decision("scale_up", replica="http://r2",
                                 trace_id=TID)
    tracecontext.record_decision("scale_down", replica="http://r9")

    tl = st.timeline(TID)
    assert tl["trace_id"] == TID
    # wall-clock sorted across sources (r2's anchor is 0.2s later)
    walls = [e["t_wall"] for e in tl["events"]]
    assert walls == sorted(walls) and len(walls) == 5
    assert tl["sources"] == ["http://r1", "http://r2", "router"]
    # only the decision naming this trace rides along
    assert [d["kind"] for d in tl["decisions"]] == ["scale_up"]

    s = tl["summary"]
    assert s["attempts"] == 2 and s["hedged"] is True
    assert s["attempt_replicas"] == ["http://r1", "http://r2"]
    assert s["replicas"] == ["http://r1", "http://r2"]
    # phases aggregate serving span durations by suffix
    assert s["queue_s"] == pytest.approx(0.02)
    assert s["prefill_s"] == pytest.approx(0.04)
    assert s["ttft_s"] == pytest.approx(0.06)
    assert s["latency_s"] > 0

    summaries = st.trace_summaries()
    assert [x["trace_id"] for x in summaries] == [TID, tid2]  # slowest 1st
    assert st.trace_ids()[0] == tid2  # most recently STARTED first


def test_chrome_trace_has_flow_arrows_and_decision_instants():
    st = FleetTraceStore(max_spans_per_source=64)
    st.ingest("router", {"anchor_epoch": 100.0, "last_seq": 1, "spans": [
        _span("router.dispatch", 0.0, 50e4, cat="router", trace_id=TID,
              replica="http://r1", kind="primary"),
    ]})
    st.ingest("http://r1", {"anchor_epoch": 100.0, "last_seq": 1,
                            "spans": [
        _span("serving.queue", 1e4, 2e4, trace_id=TID),
    ]})
    tracecontext.record_decision("autoscale_verdict", verdict="scale_up")

    evs = st.to_chrome_trace()
    names = {e.get("name") for e in evs if e.get("ph") == "M"}
    assert {"process_name", "process_sort_index"} <= names
    labels = {e["args"]["name"] for e in evs
              if e.get("name") == "process_name"}
    assert labels == {"router", "replica http://r1"}
    xs = [e for e in evs if e.get("ph") == "X"]
    assert {e["name"] for e in xs} == {"router.dispatch", "serving.queue"}
    assert all(e["ts"] >= 0 for e in xs)  # rebased to the earliest span
    # the decision instant lands on the router's process row
    router_pid = next(e["pid"] for e in evs
                      if e.get("name") == "process_name"
                      and e["args"]["name"] == "router")
    inst = [e for e in evs if e.get("ph") == "i"]
    assert inst and inst[0]["name"] == "decision:autoscale_verdict"
    assert inst[0]["pid"] == router_pid
    # the journey arrow: one s/f pair sharing an id, start on the
    # router's dispatch, finish on the replica's earliest serving span
    s = [e for e in evs if e.get("ph") == "s"]
    f = [e for e in evs if e.get("ph") == "f"]
    assert len(s) == 1 and len(f) == 1
    assert s[0]["id"] == f[0]["id"] and f[0]["bp"] == "e"
    assert s[0]["pid"] == router_pid and f[0]["pid"] != router_pid


def test_replica_restart_rewinds_cursor_but_keeps_history():
    st = FleetTraceStore(max_spans_per_source=64)
    st.ingest("http://r1", {"anchor_epoch": 100.0, "last_seq": 5,
                            "spans": [_span("serving.queue", 1e4, 2e4,
                                            trace_id=TID)]})
    assert st.cursor("http://r1") == 5
    # the replica restarted: new anchor, seq counter reset.  A batch
    # fetched with the stale cursor may be gapped -> dropped whole.
    kept = st.ingest("http://r1", {"anchor_epoch": 200.0, "last_seq": 9,
                                   "spans": [_span("serving.queue", 1e4,
                                                   2e4, trace_id=TID)]})
    assert kept == 0 and st.cursor("http://r1") == 0
    assert st.anchor("http://r1") == 200.0
    # the dead incarnation's spans ARE the post-SIGKILL history
    assert st.trace_ids() == [TID]
    # the next poll re-reads the fresh ring from 0 and lands normally
    kept = st.ingest("http://r1", {"anchor_epoch": 200.0, "last_seq": 2,
                                   "spans": [_span("serving.decode", 3e4,
                                                   1e4, trace_id=TID)]})
    assert kept == 1 and st.cursor("http://r1") == 2


# ---------------------------------------------------------------------------
# the zero-overhead off path
# ---------------------------------------------------------------------------

class _OkReplica:
    """Minimal healthy replica for the off-path router test."""

    def __init__(self):
        outer = self

        class H(BaseHTTPRequestHandler):
            def _send(self, doc):
                body = json.dumps(doc).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._send({"status": "ok", "active": 0, "waiting": 0,
                            "max_active": 4, "draining": False})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                doc = json.loads(self.rfile.read(n))
                outer.trace_headers.append(
                    self.headers.get(TRACE_HEADER))
                self._send({"state": "done", "output_ids": [1],
                            "n_generated": 1,
                            "request_id": doc.get("request_id")})

            def log_message(self, *a):
                pass

        self.trace_headers = []
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_disabled_tracing_is_zero_overhead_on_the_request_path(
        monkeypatch):
    """With DMLC_TRACE_FLEET off, ``enabled()`` must be the ONLY
    tracecontext call on the hot path: minting and span-id functions
    are booby-trapped and a request still routes fine."""
    from dmlc_tpu.serving.router import Router

    monkeypatch.delenv("DMLC_TRACE_FLEET", raising=False)
    assert tracecontext.enabled() is False

    def boom(*a, **k):
        raise AssertionError("tracecontext touched on the off path")

    monkeypatch.setattr(tracecontext, "mint_trace_id", boom)
    monkeypatch.setattr(tracecontext, "new_span_id", boom)
    monkeypatch.setattr(tracecontext, "parse_header", boom)

    rep = _OkReplica()
    r = Router([rep.url], retries=2, dispatch_timeout_s=5.0,
               request_timeout_s=10.0, start_health_thread=False)
    try:
        r.poll_once()
        code, doc, _ = r.route({"prompt": [1], "request_id": "off-1"},
                               trace_parent=f"{TID}-{'0' * 16}")
        assert code == 200 and doc["request_id"] == "off-1"
        assert r.trace_store is None        # dark: no store, no pulls
        assert rep.trace_headers == [None]  # no header forwarded
    finally:
        r.close()
        rep.close()

    # the replica-side ledger is equally dark: no trace_id -> no
    # serving.admitted instant, no trace_id stamped anywhere
    led = RequestLedger(capacity=8, trace_rows=True)
    led.on_submit(1, n_prompt=3, t=0.0)
    led.on_prefill_begin(1, t=0.1)
    led.on_first_token(1, t=0.2)
    rec = led.on_finish(1, t=0.3)
    assert "trace_id" not in rec
    spans, _ = telemetry.spans_since(0)
    assert all((s.get("args") or {}).get("trace_id") is None
               for s in spans)
    assert not any(s["name"] == "serving.admitted" for s in spans)
