"""Goodput/badput ledger + incident forensics (ISSUE 20): the wall-clock
partition invariant, span/override attribution priority, tracker-side
aggregation across elastic renumbering, the serving availability twin,
and the incident builder joining badput intervals with decision chains."""

import time

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry import (
    AvailabilityLedger,
    GoodputAggregator,
    GoodputLedger,
    StepLedger,
    Watchdog,
    exporters,
)
from dmlc_tpu.telemetry.forensics import (
    IncidentReporter,
    build_incidents,
    watchdog_anomaly_records,
)
from dmlc_tpu.telemetry.goodput import BADPUT_BUCKETS, BUCKETS


@pytest.fixture(autouse=True)
def fresh_telemetry():
    telemetry.reset()
    telemetry.reset_steps()
    telemetry.reset_goodput()
    yield
    telemetry.reset()
    telemetry.reset_steps()
    telemetry.reset_goodput()


def _assert_partition(doc):
    """The tentpole invariant: every instant in exactly one bucket."""
    assert set(doc["buckets"]) <= set(BUCKETS)
    assert sum(doc["buckets"].values()) == pytest.approx(
        doc["wall_s"], abs=1e-6)


# ---------------------------------------------------------------------------
# GoodputLedger: the partition invariant + attribution priority
# ---------------------------------------------------------------------------

def test_partition_sums_to_wall_with_mixed_evidence():
    led = GoodputLedger()
    with telemetry.span("step", stage="step"):
        time.sleep(0.02)
        with telemetry.span("checkpoint.save", stage="checkpoint"):
            time.sleep(0.02)
    prev = led.enter("resize")
    assert prev is None
    time.sleep(0.02)
    led.enter(prev)
    led.on_step(tokens=1000, step_s=0.04)
    doc = led.status()
    _assert_partition(doc)
    # specific badput carved out of the step's productive window
    assert doc["buckets"]["checkpoint_save"] >= 0.015
    assert doc["buckets"]["productive"] >= 0.015
    assert doc["buckets"]["resize"] >= 0.015
    # pre-ledger process time classifies as startup, not unattributed
    assert doc["buckets"].get("startup", 0.0) > 0.0
    assert doc["goodput_fraction"] == pytest.approx(
        doc["buckets"]["productive"] / doc["wall_s"], rel=1e-6)
    assert doc["tokens"] == 1000
    assert doc["effective_tokens_per_s"] == pytest.approx(
        1000 / doc["wall_s"], rel=1e-6)


def test_partition_holds_at_every_call_and_buckets_are_monotone():
    led = GoodputLedger()
    led.on_step(tokens=1, step_s=0.001)  # pin the startup boundary
    prior = {}
    for i in range(4):
        if i == 1:
            with telemetry.span("feed.wait", stage="feed"):
                time.sleep(0.01)
        if i == 2:
            led.enter("rollback_replay")
            time.sleep(0.01)
            led.enter(None)
        time.sleep(0.005)
        doc = led.status()
        _assert_partition(doc)
        for b, s in prior.items():
            assert doc["buckets"].get(b, 0.0) >= s - 1e-6, b
        prior = dict(doc["buckets"])
    assert prior["feed_stall"] >= 0.008
    assert prior["rollback_replay"] >= 0.008


def test_open_span_is_not_double_counted_across_samples():
    """A span still open at a sample must classify provisionally and
    then settle once — total stays a partition throughout."""
    led = GoodputLedger()
    with telemetry.span("checkpoint.restore", stage="checkpoint"):
        time.sleep(0.02)
        mid = led.status()          # span open: provisional tail
        _assert_partition(mid)
        assert mid["buckets"].get("checkpoint_restore", 0.0) >= 0.015
        assert mid["current"] == "checkpoint_restore"
        time.sleep(0.02)
    done = led.status()
    _assert_partition(done)
    assert done["buckets"]["checkpoint_restore"] >= 0.035
    assert done["buckets"]["checkpoint_restore"] < mid["wall_s"] + 0.1


def test_resize_mid_feed_wait_attributes_both(monkeypatch):
    """Regression (satellite 2): a WorldResized landing while blocked in
    feed.wait must attribute the recovery to ``resize`` and the
    surrounding wait to ``feed_stall`` — nothing leaks to unattributed."""
    led = GoodputLedger()
    led.on_step(tokens=1, step_s=0.001)
    with telemetry.span("feed.wait", stage="feed"):
        time.sleep(0.02)
        # the example's except WorldResized: path
        prev = led.enter("resize")
        time.sleep(0.02)
        led.enter(prev)  # resync done: re-enter the pre-resize interval
        time.sleep(0.02)
    doc = led.status()
    _assert_partition(doc)
    assert doc["buckets"]["resize"] >= 0.015
    assert doc["buckets"]["feed_stall"] >= 0.03
    assert doc["buckets"].get("unattributed", 0.0) < 0.01


def test_enter_restore_chain_preserves_rollback_override():
    """enter() returns the previous override so a resize landing inside
    rollback_replay restores it instead of clearing it."""
    led = GoodputLedger()
    led.enter("rollback_replay")
    time.sleep(0.01)
    prev = led.enter("resize")
    assert prev == "rollback_replay"
    time.sleep(0.01)
    led.enter(prev)
    time.sleep(0.01)
    led.enter(None)
    doc = led.status()
    _assert_partition(doc)
    assert doc["buckets"]["rollback_replay"] >= 0.015
    assert doc["buckets"]["resize"] >= 0.008


def test_enter_rejects_unknown_bucket():
    with pytest.raises(ValueError):
        GoodputLedger().enter("coffee_break")


def test_badput_intervals_recorded_for_forensics():
    led = GoodputLedger(max_intervals=8)
    led.enter("resize")
    time.sleep(0.02)
    led.enter(None)
    with telemetry.span("checkpoint.save", stage="checkpoint"):
        time.sleep(0.015)
    doc = led.status()
    ivs = doc["intervals"]
    assert [iv["bucket"] for iv in ivs] == ["resize", "checkpoint_save"]
    now = time.time()
    for iv in ivs:
        assert iv["t1"] > iv["t0"]
        assert iv["dur_s"] == pytest.approx(iv["t1"] - iv["t0"], abs=1e-6)
        assert abs(iv["t1"] - now) < 60  # epoch-stamped, not monotonic
    assert ivs[0]["seq"] < ivs[1]["seq"]


def test_window_doc_tracks_recent_rate():
    led = GoodputLedger(window_s=0.05)
    led.on_step(tokens=100, step_s=0.01)
    time.sleep(0.06)
    led.on_step(tokens=900, step_s=0.01)
    doc = led.status()
    win = doc["window"]
    assert win["wall_s"] <= 0.2
    assert win["tokens"] == pytest.approx(900)
    assert win["effective_tokens_per_s"] > 0


# ---------------------------------------------------------------------------
# step ledger coupling: checkpoint stall family + goodput feed
# ---------------------------------------------------------------------------

def test_step_record_carves_checkpoint_stall():
    led = StepLedger()
    led.step_begin()
    with telemetry.span("checkpoint.save", stage="checkpoint"):
        time.sleep(0.02)
    time.sleep(0.01)
    rec = led.step_end(tokens=10)
    assert rec["checkpoint_stall_s"] >= 0.015
    total = (rec["feed_wait_s"] + rec["checkpoint_stall_s"]
             + rec["collective_s"] + rec["compute_s"])
    assert total == pytest.approx(rec["wall_s"], rel=1e-6)
    assert led.summary()["checkpoint_stall_fraction"] > 0.0


def test_step_end_feeds_goodput_ledger_when_opted_in():
    from dmlc_tpu.telemetry import goodput as goodput_mod

    goodput_mod.ledger()  # opt in
    led = StepLedger()
    led.step_begin()
    time.sleep(0.01)
    led.step_end(tokens=123)
    doc = goodput_mod.status()
    assert doc is not None
    assert doc["tokens"] == pytest.approx(123)
    assert doc["steps"] == 1
    _assert_partition(doc)


def test_goodput_status_is_none_without_opt_in():
    from dmlc_tpu.telemetry import goodput as goodput_mod

    led = StepLedger()
    led.step_begin()
    led.step_end(tokens=5)  # module-level on_step must not create one
    assert goodput_mod.status() is None


# ---------------------------------------------------------------------------
# GoodputAggregator: ingest, death gaps, elastic renumbering
# ---------------------------------------------------------------------------

def _doc(anchor=100.0, wall=10.0, productive=6.0, tokens=600.0, seqs=()):
    buckets = {b: 0.0 for b in BUCKETS}
    buckets["productive"] = productive
    buckets["startup"] = wall - productive
    return {
        "t": time.time(), "anchor": anchor, "wall_s": wall,
        "buckets": buckets, "goodput_fraction": productive / wall,
        "tokens": tokens, "steps": 3, "in_step_s": productive,
        "effective_tokens_per_s": tokens / wall,
        "in_step_tokens_per_s": tokens / productive,
        "window": {"wall_s": wall, "tokens": tokens,
                   "effective_tokens_per_s": tokens / wall,
                   "in_step_tokens_per_s": tokens / productive},
        "current": "productive",
        "intervals": [{"seq": s, "bucket": "resize",
                       "t0": 50.0 + s, "t1": 51.0 + s, "dur_s": 1.0}
                      for s in seqs],
    }


def test_aggregator_report_and_fractions():
    agg = GoodputAggregator()
    agg.ingest(0, _doc(wall=10.0, productive=6.0))
    agg.ingest(1, _doc(wall=10.0, productive=8.0))
    rep = agg.report()
    assert rep["ranks"] == 2
    cl = rep["cluster"]
    assert cl["wall_s"] == pytest.approx(20.0)
    assert cl["goodput_fraction"] == pytest.approx(0.7)
    assert sum(cl["fractions"].values()) == pytest.approx(1.0)
    assert cl["effective_tokens_per_s"] == pytest.approx(
        cl["tokens"] / cl["wall_s"])


def test_aggregator_dead_rank_accrues_preempted_until_relaunch():
    agg = GoodputAggregator()
    agg.ingest(0, _doc(anchor=100.0))
    agg.mark_dead(0)
    time.sleep(0.05)
    rep = agg.report()
    assert rep["per_rank"]["0"]["buckets"]["preempted"] >= 0.04
    # relaunch under the same rank (new anchor) closes the gap
    agg.ingest(0, _doc(anchor=222.0))
    gap1 = agg.report()["per_rank"]["0"]["buckets"]["preempted"]
    assert gap1 >= 0.04
    time.sleep(0.02)
    gap2 = agg.report()["per_rank"]["0"]["buckets"]["preempted"]
    assert gap2 == pytest.approx(gap1, abs=0.01)  # stopped accruing


def test_aggregator_remap_ranks_moves_survivor_and_drops_dead():
    # mirrors tests/test_flight_recorder.py: rank 1 dies, rank 2
    # survives as the new rank 1 — cumulative seconds and the interval
    # dedup high-water follow the surviving process.
    agg = GoodputAggregator()
    for r in (0, 1, 2):
        agg.ingest(r, _doc(anchor=100.0 + r, wall=10.0 + r,
                           productive=5.0 + r, seqs=(1,)))
    agg.remap_ranks({0: 0, 2: 1})
    rep = agg.report()
    assert sorted(rep["per_rank"]) == ["0", "1"]
    assert rep["per_rank"]["0"]["wall_s"] == pytest.approx(10.0)
    # survivor's data moved intact under its new number
    assert rep["per_rank"]["1"]["wall_s"] == pytest.approx(12.0)
    assert rep["per_rank"]["1"]["buckets"]["productive"] == pytest.approx(7.0)
    # re-shipping the survivor's already-seen interval under the NEW
    # rank dedups by seq instead of duplicating the episode
    agg.ingest(1, _doc(anchor=102.0, wall=12.5, productive=7.2,
                       seqs=(1, 2)))
    ivs = [iv for iv in agg.badput_intervals() if iv["rank"] == 1]
    assert sorted(iv["seq"] for iv in ivs) == [1, 2]
    # one fresh beat after the remap restores truth (self-correcting)
    assert agg.report()["per_rank"]["1"]["wall_s"] == pytest.approx(12.5)


def test_aggregator_badput_intervals_are_rank_tagged_and_ordered():
    agg = GoodputAggregator()
    agg.ingest(0, _doc(seqs=(2,)))
    agg.ingest(1, _doc(seqs=(1,)))
    ivs = agg.badput_intervals()
    assert [iv["rank"] for iv in ivs] == [1, 0]  # wall-ordered by t0
    assert all(iv["bucket"] == "resize" for iv in ivs)


def test_aggregator_prometheus_text_validates():
    agg = GoodputAggregator()
    agg.ingest(0, _doc())
    agg.ingest(1, _doc(wall=20.0, productive=4.0))
    text = agg.prometheus_text()
    exporters.validate_exposition_text(text)
    assert 'dmlc_goodput_bucket_seconds{rank="0",bucket="productive"}' in text
    assert "dmlc_goodput_cluster_fraction" in text
    assert 'dmlc_goodput_fraction{rank="1"} 0.2' in text


def test_aggregator_ignores_garbage():
    agg = GoodputAggregator()
    agg.ingest(0, None)
    agg.ingest(0, {"no": "buckets"})
    garbage = _doc()
    garbage["intervals"] = [{"seq": "NaN"}, {"bucket": "resize"}, "nope"]
    agg.ingest(0, garbage)
    assert agg.report()["ranks"] == 1
    assert agg.badput_intervals() == []


# ---------------------------------------------------------------------------
# AvailabilityLedger: the serving twin
# ---------------------------------------------------------------------------

def test_availability_fractions_sum_to_one():
    led = AvailabilityLedger()
    time.sleep(0.02)
    led.set_state("draining")
    time.sleep(0.02)
    led.set_state("serving")
    time.sleep(0.01)
    rep = led.report()
    assert sum(rep["fractions"].values()) == pytest.approx(1.0)
    assert sum(rep["states"].values()) == pytest.approx(
        rep["wall_s"], abs=1e-6)
    assert rep["states"]["draining"] >= 0.015
    assert rep["state"] == "serving"
    assert 0.0 < rep["availability"] < 1.0


def test_availability_tracks_capacity_tokens():
    led = AvailabilityLedger()
    led.note_tokens(100)
    time.sleep(0.6)
    led.note_tokens(300)
    rep = led.report()
    assert rep["tokens_served"] == pytest.approx(400)
    assert rep["capacity_tokens_per_s"] > 0
    assert rep["capacity_tokens"] >= rep["tokens_served"] * 0.5
    exporters.validate_exposition_text(led.prometheus_text())


def test_availability_rejects_unknown_state():
    with pytest.raises(ValueError):
        AvailabilityLedger().set_state("on_fire")


# ---------------------------------------------------------------------------
# Watchdog: effective-goodput-collapse anomaly
# ---------------------------------------------------------------------------

def _goodput_subdoc(eff, in_step):
    return {"goodput_fraction": 0.5, "effective_tokens_per_s": eff,
            "in_step_tokens_per_s": in_step, "current": "feed_stall",
            "window": {"wall_s": 30.0, "tokens": eff * 30.0,
                       "effective_tokens_per_s": eff,
                       "in_step_tokens_per_s": in_step}}


def test_watchdog_flags_effective_goodput_collapse():
    wd = Watchdog()
    before = telemetry.snapshot()["counters"].get(
        "anomaly", {}).get("effective_goodput_collapse_flags", 0)
    wd.ingest_goodput(0, _goodput_subdoc(eff=10.0, in_step=100.0))
    rep = wd.report()
    assert "effective_goodput_collapse" in rep["ranks"]["0"]["flags"]
    assert rep["ranks"]["0"]["goodput"]["effective_tokens_per_s"] == 10.0
    assert telemetry.snapshot()["counters"]["anomaly"][
        "effective_goodput_collapse_flags"] == before + 1
    text = wd.prometheus_text()
    exporters.validate_exposition_text(text)
    assert 'kind="effective_goodput_collapse"' in text
    # recovery above the threshold clears the flag (direct-apply)
    wd.ingest_goodput(0, _goodput_subdoc(eff=90.0, in_step=100.0))
    assert wd.report()["ranks"]["0"]["flags"] == []


def test_watchdog_goodput_threshold_env(monkeypatch):
    monkeypatch.setenv("DMLC_GOODPUT_MIN_FRACTION", "0.05")
    wd = Watchdog()
    wd.ingest_goodput(0, _goodput_subdoc(eff=10.0, in_step=100.0))
    assert wd.report()["ranks"]["0"]["flags"] == []


def test_watchdog_routes_goodput_from_heartbeat_json():
    import json

    wd = Watchdog()
    wd.ingest_json(0, json.dumps(
        {"goodput": _goodput_subdoc(eff=1.0, in_step=100.0)}))
    assert "effective_goodput_collapse" in wd.report()["ranks"]["0"]["flags"]


# ---------------------------------------------------------------------------
# forensics: incidents from intervals + decision chains
# ---------------------------------------------------------------------------

def test_build_incidents_joins_intervals_and_decisions():
    t = 1000.0
    incidents = build_incidents(
        intervals=[{"bucket": "resize", "t0": t, "t1": t + 3.0,
                    "dur_s": 3.0, "rank": 2}],
        decisions=[{"kind": "preempt_kill_rank", "t": t + 1.0, "seq": 7},
                   {"kind": "unrelated", "t": t + 500.0, "seq": 8}],
        events=[{"kind": "world_resized", "t": t + 2.0, "seq": 3}],
        anomalies=[{"kind": "straggler", "rank": 2, "t": t + 1.5}],
    )
    assert len(incidents) == 1
    inc = incidents[0]
    assert inc["kinds"] == ["preempt_kill_rank", "resize"]
    assert inc["ranks"] == [2]
    assert inc["badput_s"] == pytest.approx(3.0)
    assert inc["decision_kinds"] == ["preempt_kill_rank"]
    assert [r["what"] for r in inc["timeline"]] == ["decision", "event"]
    assert inc["anomalies"] == [{"kind": "straggler", "rank": 2}]
    assert "badput" in inc["summary"]


def test_build_incidents_merges_decision_chain_into_one_episode():
    t = 2000.0
    chain = ["autoscale_verdict", "preempt_acquire", "preempt_kill_rank",
             "preempt_resize", "preempt_replica_added", "scale_up"]
    decisions = [{"kind": k, "t": t + i, "seq": i}
                 for i, k in enumerate(chain)]
    incidents = build_incidents(decisions=decisions)
    assert len(incidents) == 1
    assert incidents[0]["decision_kinds"] == chain


def test_build_incidents_bridges_open_chains_past_gap():
    """A chain kind awaiting its causal successor holds the incident
    open past gap_s (replica gang-launch between preempt_resize and
    preempt_replica_added can take tens of seconds) — but two terminal
    decisions the same distance apart stay separate incidents."""
    t = 3000.0
    incidents = build_incidents(decisions=[
        {"kind": "preempt_resize", "t": t, "seq": 1},
        {"kind": "preempt_replica_added", "t": t + 30.0, "seq": 2},
        {"kind": "scale_up", "t": t + 31.0, "seq": 3}])
    assert len(incidents) == 1
    assert incidents[0]["decision_kinds"] == [
        "preempt_resize", "preempt_replica_added", "scale_up"]
    incidents = build_incidents(decisions=[
        {"kind": "scale_up", "t": t, "seq": 1},
        {"kind": "scale_down", "t": t + 30.0, "seq": 2}])
    assert len(incidents) == 2


def test_build_incidents_separates_distant_episodes_newest_first():
    incidents = build_incidents(
        intervals=[{"bucket": "resize", "t0": 100.0, "t1": 101.0,
                    "dur_s": 1.0},
                   {"bucket": "preempted", "t0": 500.0, "t1": 502.0,
                    "dur_s": 2.0}])
    assert len(incidents) == 2
    assert incidents[0]["kinds"] == ["preempted"]   # newest first
    assert incidents[1]["kinds"] == ["resize"]


def test_incident_reporter_survives_failing_sources():
    rep = IncidentReporter(
        intervals_source=lambda: (_ for _ in ()).throw(RuntimeError()),
        decisions_source=lambda: [{"kind": "scale_up", "t": 10.0,
                                   "seq": 1}])
    doc = rep.report()
    assert doc["count"] == 1
    assert doc["incidents"][0]["decision_kinds"] == ["scale_up"]


def test_watchdog_anomaly_records_flatten():
    recs = watchdog_anomaly_records(
        {"active": [{"rank": 3, "kind": "straggler", "since": 42.0}]})
    assert recs == [{"kind": "straggler", "rank": 3, "t": 42.0}]
    assert watchdog_anomaly_records({}) == []
    assert watchdog_anomaly_records(None) == []


def test_badput_buckets_exclude_productive():
    assert "productive" not in BADPUT_BUCKETS
    assert set(BADPUT_BUCKETS) | {"productive"} == set(BUCKETS)
