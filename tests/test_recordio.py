"""RecordIO round trips, incl. the adversarial magic-collision generator
(mirrors reference test/recordio_test.cc:6-60 — the de-facto fuzzer for the
escape protocol), plus the CRC32C record variant and its corruption
paths under all three DMLC_INTEGRITY_POLICY values."""

import random
import struct

import numpy as np
import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.io import integrity
from dmlc_tpu.io.recordio import (
    KMAGIC,
    RecordIOChunkReader,
    RecordIOReader,
    RecordIOWriter,
    decode_flag,
    decode_length,
    encode_lrec,
)
from dmlc_tpu.io.stream import MemoryBytesStream

MAGIC_BYTES = struct.pack("<I", KMAGIC)

POLICIES = ("raise", "skip", "quarantine")


@pytest.fixture(autouse=True)
def _clean_integrity_state():
    integrity.reset_quarantine()
    yield
    integrity.reset_quarantine()


def make_adversarial_records(n, seed=0):
    """Random payloads with deliberately embedded magic numbers at aligned
    and unaligned positions (recordio_test.cc:14-34)."""
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        length = rng.randint(0, 200)
        body = bytearray(rng.getrandbits(8) for _ in range(length))
        # sprinkle magic at aligned positions
        for _ in range(rng.randint(0, 3)):
            if length >= 4:
                pos = rng.randrange(0, max(1, length - 3))
                pos_aligned = (pos >> 2) << 2
                body[pos_aligned : pos_aligned + 4] = MAGIC_BYTES
        # and at deliberately unaligned positions
        if length >= 6 and rng.random() < 0.5:
            pos = ((rng.randrange(0, length - 5) >> 2) << 2) + 1
            body[pos : pos + 4] = MAGIC_BYTES
        recs.append(bytes(body))
    # edge cases: empty record, record that is exactly the magic, magic runs
    recs += [b"", MAGIC_BYTES, MAGIC_BYTES * 5, MAGIC_BYTES * 2 + b"xy"]
    return recs


def write_all(recs):
    strm = MemoryBytesStream()
    writer = RecordIOWriter(strm)
    for r in recs:
        writer.write_record(r)
    return strm.getvalue(), writer


def test_lrec_encoding():
    assert decode_flag(encode_lrec(3, 17)) == 3
    assert decode_length(encode_lrec(3, 17)) == 17
    # (kMagic >> 29) & 7 > 3 guarantee (recordio.h:42-45)
    assert (KMAGIC >> 29) & 7 > 3


def test_roundtrip_adversarial():
    recs = make_adversarial_records(300, seed=1)
    data, writer = write_all(recs)
    assert writer.except_counter > 0, "generator failed to trigger escape path"
    reader = RecordIOReader(MemoryBytesStream(data))
    out = list(reader)
    assert out == recs


def test_roundtrip_chunk_reader_single_part():
    recs = make_adversarial_records(100, seed=2)
    data, _ = write_all(recs)
    out = [bytes(r) for r in RecordIOChunkReader(data)]
    assert out == recs


def test_chunk_reader_partitions_cover_all_records():
    """Union of all parts == all records, no dup, no loss (recordio.cc:101-112)."""
    recs = make_adversarial_records(200, seed=3)
    data, _ = write_all(recs)
    for num_parts in (1, 2, 3, 7):
        got = []
        for part in range(num_parts):
            got.extend(bytes(r) for r in RecordIOChunkReader(data, part, num_parts))
        assert got == recs, f"partition mismatch at num_parts={num_parts}"


def test_alignment_invariant():
    """Every record segment starts at a 4-byte boundary in the file."""
    recs = make_adversarial_records(50, seed=4)
    data, _ = write_all(recs)
    assert len(data) % 4 == 0
    # walk headers
    pos = 0
    while pos < len(data):
        magic, lrec = struct.unpack_from("<II", data, pos)
        assert magic == KMAGIC
        assert pos % 4 == 0
        length = decode_length(lrec)
        pos += 8 + (((length + 3) >> 2) << 2)


def test_large_record_rejected():
    strm = MemoryBytesStream()
    w = RecordIOWriter(strm)

    class FakeBytes(bytes):
        def __len__(self):
            return 1 << 29

    with pytest.raises(DMLCError):
        w.write_record(FakeBytes())


def test_corrupt_magic_raises():
    recs = [b"hello world!"]
    data, _ = write_all(recs)
    corrupted = b"\x00" + data[1:]
    with pytest.raises(DMLCError):
        RecordIOReader(MemoryBytesStream(corrupted)).next_record()


def test_numpy_payload_roundtrip():
    """RecordIO is the tensor-shard container for the TPU feed path; check a
    binary tensor payload round-trips exactly."""
    arr = np.random.default_rng(0).standard_normal((32, 16)).astype(np.float32)
    data, _ = write_all([arr.tobytes()])
    (out,) = list(RecordIOReader(MemoryBytesStream(data)))
    np.testing.assert_array_equal(np.frombuffer(out, np.float32).reshape(32, 16), arr)


def test_many_zero_length_records(tmp_path):
    # >16 empty records per chunk exercises the native span-capacity retry
    from dmlc_tpu.io.recordio import RecordIOWriter, RecordIOReader
    from dmlc_tpu.io.stream import Stream
    from dmlc_tpu.io import input_split

    path = str(tmp_path / "zeros.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(100):
            w.write_record(b"")
        w.write_record(b"tail")
    split = input_split.create(path, 0, 1, "recordio")
    recs = [bytes(r) for r in split]
    assert len(recs) == 101
    assert recs[-1] == b"tail"
    assert all(r == b"" for r in recs[:-1])
    split.close()


# ---------------------------------------------------------------------------
# CRC32C record variant + corruption paths (DMLC_INTEGRITY_POLICY)
# ---------------------------------------------------------------------------

def write_all_checksummed(recs):
    strm = MemoryBytesStream()
    writer = RecordIOWriter(strm, checksum=True)
    for r in recs:
        writer.write_record(r)
    return strm.getvalue(), writer


def _payload_offset(data: bytes, record: int) -> int:
    """Byte offset of record ``record``'s first payload byte in a
    checksummed file (walks the 12-byte headers)."""
    pos = 0
    k = 0
    while pos < len(data):
        magic, lrec = struct.unpack_from("<II", data, pos)
        assert magic == KMAGIC
        ln = decode_length(lrec)
        if k == record and decode_flag(lrec) >= 4:
            return pos + 12
        pos += 12 + (((ln + 3) >> 2) << 2)
        k += 1
    raise AssertionError(f"record {record} not found")


def test_checksummed_roundtrip_adversarial():
    recs = make_adversarial_records(300, seed=11)
    data, writer = write_all_checksummed(recs)
    assert writer.except_counter > 0
    assert list(RecordIOReader(MemoryBytesStream(data))) == recs
    assert [bytes(r) for r in RecordIOChunkReader(data)] == recs


def test_checksummed_partitions_cover_all_records():
    recs = make_adversarial_records(120, seed=12)
    data, _ = write_all_checksummed(recs)
    for num_parts in (1, 2, 5):
        got = []
        for part in range(num_parts):
            got.extend(bytes(r)
                       for r in RecordIOChunkReader(data, part, num_parts))
        assert got == recs


def test_unchecksummed_bytes_identical_to_reference_layout():
    """Pre-PR files stay bit-exact: checksum=False must produce the
    reference wire bytes, header by header."""
    s = MemoryBytesStream()
    RecordIOWriter(s, checksum=False).write_record(b"hello")
    want = MAGIC_BYTES + struct.pack("<I", encode_lrec(0, 5)) \
        + b"hello\x00\x00\x00"
    assert s.getvalue() == want


def test_old_reader_shape_rejects_checksummed_cflags():
    """The versioned cflag is what makes new files LOUD on old readers:
    cflags 4-7 were 'invalid RecordIO' before this variant existed."""
    data, _ = write_all_checksummed([b"x" * 9])
    lrec = struct.unpack_from("<I", data, 4)[0]
    assert decode_flag(lrec) == 4  # checksummed complete


@pytest.mark.parametrize("policy", POLICIES)
def test_fault_spec_flip_through_stream_reader(policy, monkeypatch):
    """A DMLC_FAULT_SPEC storage.response bit-flip lands on record 0's
    header; the stream reader resyncs (or raises) per policy."""
    from dmlc_tpu.resilience import install_injector, reset_injector

    recs = [b"alpha" * 3, b"beta" * 4, b"gamma" * 5]
    data, _ = write_all_checksummed(recs)
    inj = install_injector("storage.response=corrupt")
    try:
        bad = inj.corrupt("storage.response", data)
    finally:
        reset_injector()
    assert bad != data
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    if policy == "raise":
        with pytest.raises(DMLCError):
            list(RecordIOReader(MemoryBytesStream(bad)))
        return
    got = list(RecordIOReader(MemoryBytesStream(bad), source="s.rec"))
    assert got == recs[1:]
    spans = integrity.quarantined_spans("s.rec")
    if policy == "quarantine":
        assert spans, "no span quarantined"
        # replay over CLEAN bytes drops the quarantined record again
        got = list(RecordIOReader(MemoryBytesStream(data), source="s.rec"))
        assert got == recs[1:]
    else:
        assert not spans


@pytest.mark.parametrize("policy", POLICIES)
def test_fault_spec_flip_through_chunk_reader(policy, monkeypatch):
    """The same injected flip aimed at a mid-file payload; ChunkReader
    verifies the CRC and skips/raises per policy."""
    from dmlc_tpu.resilience import install_injector, reset_injector

    recs = [bytes([65 + i]) * 20 for i in range(5)]
    data, _ = write_all_checksummed(recs)
    off = _payload_offset(data, 2)
    inj = install_injector("storage.response=corrupt")
    try:
        bad = data[:off] + inj.corrupt("storage.response", data[off:])
    finally:
        reset_injector()
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    if policy == "raise":
        with pytest.raises(DMLCError):
            list(RecordIOChunkReader(bad))
        return
    got = [bytes(r) for r in RecordIOChunkReader(bad, source="c.rec")]
    assert got == recs[:2] + recs[3:]
    assert bool(integrity.quarantined_spans("c.rec")) == \
        (policy == "quarantine")


@pytest.mark.parametrize("policy", POLICIES)
def test_fault_spec_flip_through_packed_feed(policy, monkeypatch, tmp_path):
    """Bit-flipped bytes on disk driven through the packed device feed:
    the span scan verifies CRCs and the batch stream skips (or the
    epoch fails) per policy."""
    from dmlc_tpu.feed import recordio_packed_feed
    from dmlc_tpu.io.stream import Stream
    from dmlc_tpu.parallel import build_mesh
    from dmlc_tpu.resilience import install_injector, reset_injector

    recs = [bytes([i] * 24) for i in range(32)]
    path = str(tmp_path / "p.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s, checksum=True)
        for r in recs:
            w.write_record(r)
    raw = open(path, "rb").read()
    off = _payload_offset(raw, 7)
    inj = install_injector("storage.response=corrupt")
    try:
        bad = raw[:off] + inj.corrupt("storage.response", raw[off:])
    finally:
        reset_injector()
    open(path, "wb").write(bad)
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)

    def read_all():
        feed = recordio_packed_feed(path, mesh, buf_bytes=512)
        got = []
        for b in feed:
            d = np.asarray(b["data"])
            offs = np.asarray(b["offsets"])
            cnt = int(np.asarray(b["count"])[0])
            got.extend(d[offs[i]:offs[i + 1]].tobytes()
                       for i in range(cnt))
        return got

    if policy == "raise":
        with pytest.raises(DMLCError):
            read_all()
        return
    assert read_all() == recs[:7] + recs[8:]
    if policy == "quarantine":
        assert integrity.quarantined_spans(path)
        # the skip-list survives the epoch: a clean rewrite of the same
        # path still skips the poisoned span (rollback-and-replay path)
        open(path, "wb").write(raw)
        assert read_all() == recs[:7] + recs[8:]


@pytest.mark.parametrize("policy", POLICIES)
def test_torn_tail_word_through_packed_feed(policy, monkeypatch, tmp_path):
    """A writer killed exactly one word into the next header leaves an
    aligned stray magic word at EOF (sizes that misalign by 1-3 bytes
    are rejected at split admission).  The feed span scan must follow
    the policy — loud under 'raise', counted and dropped otherwise —
    not silently serve the file as clean."""
    from dmlc_tpu import telemetry
    from dmlc_tpu.feed import recordio_packed_feed
    from dmlc_tpu.io.stream import Stream
    from dmlc_tpu.parallel import build_mesh

    recs = [bytes([i] * 24) for i in range(8)]
    path = str(tmp_path / "t.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s, checksum=True)
        for r in recs:
            w.write_record(r)
    with open(path, "ab") as f:
        f.write(MAGIC_BYTES)
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)

    def read_all():
        feed = recordio_packed_feed(path, mesh, buf_bytes=512)
        got = []
        for b in feed:
            d = np.asarray(b["data"])
            offs = np.asarray(b["offsets"])
            cnt = int(np.asarray(b["count"])[0])
            got.extend(d[offs[i]:offs[i + 1]].tobytes()
                       for i in range(cnt))
        return got

    def corrupt_count():
        return telemetry.counters_snapshot().get("integrity", {}).get(
            "corrupt_records", 0)

    if policy == "raise":
        with pytest.raises(DMLCError, match="torn tail"):
            read_all()
        return
    before = corrupt_count()
    assert read_all() == recs
    assert corrupt_count() == before + 1
    if policy == "quarantine":
        assert integrity.quarantined_spans(path)


@pytest.mark.parametrize("policy", ("skip", "quarantine"))
def test_torn_tail_resync(policy, monkeypatch):
    """A file truncated mid-record: the tail is dropped and counted,
    never parsed as data."""
    recs = [b"first" * 10, b"second" * 10]
    data, _ = write_all_checksummed(recs)
    torn = data[: len(data) - 7]
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    got = list(RecordIOReader(MemoryBytesStream(torn)))
    assert got == recs[:1]
    got = [bytes(r) for r in RecordIOChunkReader(torn)]
    assert got == recs[:1]


def test_torn_tail_raises_by_default():
    recs = [b"first" * 10, b"second" * 10]
    data, _ = write_all_checksummed(recs)
    with pytest.raises(DMLCError):
        list(RecordIOReader(MemoryBytesStream(data[:-7])))


def test_sub_word_torn_tail_raises_by_default():
    """A writer killed 1-3 bytes into the next header leaves a sub-word
    tail after a cleanly-parsing record; the word-aligned scans cannot
    reach those bytes, but policy=raise must still report them."""
    recs = [b"first" * 10, b"second" * 10]
    data, _ = write_all_checksummed(recs)
    torn = data + MAGIC_BYTES[:2]
    with pytest.raises(DMLCError, match="sub-word"):
        list(RecordIOChunkReader(torn))
    with pytest.raises(DMLCError):
        list(RecordIOReader(MemoryBytesStream(torn)))


@pytest.mark.parametrize("policy", ("skip", "quarantine"))
def test_sub_word_torn_tail_counted_once(policy, monkeypatch):
    """Under skip/quarantine the stray tail is dropped but counted, and
    exactly one part of a partitioned chunk (the tail owner) reports."""
    from dmlc_tpu import telemetry

    recs = [b"first" * 10, b"second" * 10]
    data, _ = write_all_checksummed(recs)
    torn = data + MAGIC_BYTES[:2]
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)

    def corrupt_count():
        return telemetry.counters_snapshot().get("integrity", {}).get(
            "corrupt_records", 0)

    before = corrupt_count()
    got = [bytes(r) for r in RecordIOChunkReader(torn)]
    assert got == recs
    assert corrupt_count() == before + 1
    before = corrupt_count()
    got = [bytes(r)
           for part in range(3)
           for r in RecordIOChunkReader(torn, part, 3)]
    assert got == recs
    assert corrupt_count() == before + 1


@pytest.mark.parametrize("policy", ("skip", "quarantine"))
def test_corrupted_magic_resync(policy, monkeypatch):
    """A flipped magic word mid-file: the reader resyncs to the next
    record head and serves everything after it."""
    recs = [bytes([66 + i]) * 17 for i in range(4)]
    data, _ = write_all_checksummed(recs)
    head = _payload_offset(data, 2) - 12
    bad = bytearray(data)
    bad[head] ^= 0xFF
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", policy)
    got = list(RecordIOReader(MemoryBytesStream(bytes(bad))))
    assert got == recs[:2] + recs[3:]
    got = [bytes(r) for r in RecordIOChunkReader(bytes(bad))]
    assert got == recs[:2] + recs[3:]


def test_corruption_metrics_counted(monkeypatch):
    from dmlc_tpu import telemetry

    recs = [b"m" * 40, b"n" * 40]
    data, _ = write_all_checksummed(recs)
    off = _payload_offset(data, 1)
    bad = bytearray(data)
    bad[off] ^= 0x04
    monkeypatch.setenv("DMLC_INTEGRITY_POLICY", "quarantine")
    before = telemetry.counters_snapshot().get("integrity", {})
    list(RecordIOReader(MemoryBytesStream(bytes(bad)), source="q.rec"))
    after = telemetry.counters_snapshot().get("integrity", {})
    assert after.get("corrupt_records", 0) > before.get(
        "corrupt_records", 0)
    assert after.get("quarantined_spans", 0) > before.get(
        "quarantined_spans", 0)
