"""RecordIO round trips, incl. the adversarial magic-collision generator
(mirrors reference test/recordio_test.cc:6-60 — the de-facto fuzzer for the
escape protocol)."""

import random
import struct

import numpy as np
import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.io.recordio import (
    KMAGIC,
    RecordIOChunkReader,
    RecordIOReader,
    RecordIOWriter,
    decode_flag,
    decode_length,
    encode_lrec,
)
from dmlc_tpu.io.stream import MemoryBytesStream

MAGIC_BYTES = struct.pack("<I", KMAGIC)


def make_adversarial_records(n, seed=0):
    """Random payloads with deliberately embedded magic numbers at aligned
    and unaligned positions (recordio_test.cc:14-34)."""
    rng = random.Random(seed)
    recs = []
    for i in range(n):
        length = rng.randint(0, 200)
        body = bytearray(rng.getrandbits(8) for _ in range(length))
        # sprinkle magic at aligned positions
        for _ in range(rng.randint(0, 3)):
            if length >= 4:
                pos = rng.randrange(0, max(1, length - 3))
                pos_aligned = (pos >> 2) << 2
                body[pos_aligned : pos_aligned + 4] = MAGIC_BYTES
        # and at deliberately unaligned positions
        if length >= 6 and rng.random() < 0.5:
            pos = ((rng.randrange(0, length - 5) >> 2) << 2) + 1
            body[pos : pos + 4] = MAGIC_BYTES
        recs.append(bytes(body))
    # edge cases: empty record, record that is exactly the magic, magic runs
    recs += [b"", MAGIC_BYTES, MAGIC_BYTES * 5, MAGIC_BYTES * 2 + b"xy"]
    return recs


def write_all(recs):
    strm = MemoryBytesStream()
    writer = RecordIOWriter(strm)
    for r in recs:
        writer.write_record(r)
    return strm.getvalue(), writer


def test_lrec_encoding():
    assert decode_flag(encode_lrec(3, 17)) == 3
    assert decode_length(encode_lrec(3, 17)) == 17
    # (kMagic >> 29) & 7 > 3 guarantee (recordio.h:42-45)
    assert (KMAGIC >> 29) & 7 > 3


def test_roundtrip_adversarial():
    recs = make_adversarial_records(300, seed=1)
    data, writer = write_all(recs)
    assert writer.except_counter > 0, "generator failed to trigger escape path"
    reader = RecordIOReader(MemoryBytesStream(data))
    out = list(reader)
    assert out == recs


def test_roundtrip_chunk_reader_single_part():
    recs = make_adversarial_records(100, seed=2)
    data, _ = write_all(recs)
    out = [bytes(r) for r in RecordIOChunkReader(data)]
    assert out == recs


def test_chunk_reader_partitions_cover_all_records():
    """Union of all parts == all records, no dup, no loss (recordio.cc:101-112)."""
    recs = make_adversarial_records(200, seed=3)
    data, _ = write_all(recs)
    for num_parts in (1, 2, 3, 7):
        got = []
        for part in range(num_parts):
            got.extend(bytes(r) for r in RecordIOChunkReader(data, part, num_parts))
        assert got == recs, f"partition mismatch at num_parts={num_parts}"


def test_alignment_invariant():
    """Every record segment starts at a 4-byte boundary in the file."""
    recs = make_adversarial_records(50, seed=4)
    data, _ = write_all(recs)
    assert len(data) % 4 == 0
    # walk headers
    pos = 0
    while pos < len(data):
        magic, lrec = struct.unpack_from("<II", data, pos)
        assert magic == KMAGIC
        assert pos % 4 == 0
        length = decode_length(lrec)
        pos += 8 + (((length + 3) >> 2) << 2)


def test_large_record_rejected():
    strm = MemoryBytesStream()
    w = RecordIOWriter(strm)

    class FakeBytes(bytes):
        def __len__(self):
            return 1 << 29

    with pytest.raises(DMLCError):
        w.write_record(FakeBytes())


def test_corrupt_magic_raises():
    recs = [b"hello world!"]
    data, _ = write_all(recs)
    corrupted = b"\x00" + data[1:]
    with pytest.raises(DMLCError):
        RecordIOReader(MemoryBytesStream(corrupted)).next_record()


def test_numpy_payload_roundtrip():
    """RecordIO is the tensor-shard container for the TPU feed path; check a
    binary tensor payload round-trips exactly."""
    arr = np.random.default_rng(0).standard_normal((32, 16)).astype(np.float32)
    data, _ = write_all([arr.tobytes()])
    (out,) = list(RecordIOReader(MemoryBytesStream(data)))
    np.testing.assert_array_equal(np.frombuffer(out, np.float32).reshape(32, 16), arr)


def test_many_zero_length_records(tmp_path):
    # >16 empty records per chunk exercises the native span-capacity retry
    from dmlc_tpu.io.recordio import RecordIOWriter, RecordIOReader
    from dmlc_tpu.io.stream import Stream
    from dmlc_tpu.io import input_split

    path = str(tmp_path / "zeros.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for _ in range(100):
            w.write_record(b"")
        w.write_record(b"tail")
    split = input_split.create(path, 0, 1, "recordio")
    recs = [bytes(r) for r in split]
    assert len(recs) == 101
    assert recs[-1] == b"tail"
    assert all(r == b"" for r in recs[:-1])
    split.close()
