"""dmlc-check static-analysis suite tests.

Three layers:
  * fixture snippets per pass — each seeded-bad snippet is caught and
    its clean counterpart passes (the framework's regression suite);
  * whole-repo invariants — the real tree runs clean, and the knob
    registry is cross-checked against an independent grep of every
    ``DMLC_*`` env read (so the registry cannot silently miss a knob);
  * the runtime lock-order watchdog (``DMLC_LOCKCHECK=1``) — a
    provoked inversion across two threads and a held-while-blocked
    acquire are both recorded, clean runs record nothing.
"""

import os
import re
import threading
import time

import pytest

from dmlc_tpu import concurrency, config_registry
from dmlc_tpu.analysis import ALL_PASSES, run_passes
from dmlc_tpu.analysis.concurrency_pass import ConcurrencyPass
from dmlc_tpu.analysis.contract_pass import ContractPass
from dmlc_tpu.analysis.core import RepoIndex, default_paths, repo_root
from dmlc_tpu.analysis.knob_pass import KnobPass
from dmlc_tpu.analysis.metrics_pass import MetricsPass
from dmlc_tpu.analysis.style_pass import StylePass

REPO = repo_root()


# ---------------------------------------------------------------------------
# fixture harness: a throwaway mini-repo so path-scoped rules apply
# ---------------------------------------------------------------------------

def _index(tmp_path, files):
    paths = []
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        paths.append(str(p))
    return RepoIndex(paths, str(tmp_path))


def _checks(findings, check):
    return [f for f in findings if f.check == check]


# ---- concurrency pass --------------------------------------------------

BAD_BLOCKING = '''\
import threading
import time

_lock = threading.Lock()


def slow():
    with _lock:
        time.sleep(1.0)
'''

CLEAN_BLOCKING = '''\
import threading
import time

_lock = threading.Lock()


def fast():
    with _lock:
        x = 1
    time.sleep(1.0)
    return x
'''


def test_blocking_under_lock_caught(tmp_path):
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": BAD_BLOCKING})
    found = ConcurrencyPass().run(idx)
    assert _checks(found, "blocking-under-lock"), found


def test_blocking_under_lock_clean(tmp_path):
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": CLEAN_BLOCKING})
    assert not _checks(ConcurrencyPass().run(idx), "blocking-under-lock")


BAD_INVERSION = '''\
import threading


class M:
    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                return 1

    def two(self):
        with self._b_lock:
            with self._a_lock:
                return 2
'''

CLEAN_NESTING = BAD_INVERSION.replace(
    "        with self._b_lock:\n            with self._a_lock:",
    "        with self._a_lock:\n            with self._b_lock:")


def test_lock_inversion_caught(tmp_path):
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": BAD_INVERSION})
    found = _checks(ConcurrencyPass().run(idx), "lock-cycle")
    assert found and "M._a_lock" in str(found[0]), found


def test_lock_nesting_consistent_clean(tmp_path):
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": CLEAN_NESTING})
    assert not _checks(ConcurrencyPass().run(idx), "lock-cycle")


def test_lock_cycle_via_call_propagation(tmp_path):
    src = '''\
import threading


class A:
    def __init__(self, b):
        self._a_lock = threading.Lock()
        self.b = b

    def go(self):
        with self._a_lock:
            self.b.poke()


class B:
    def __init__(self, a):
        self._b_lock = threading.Lock()
        self.a = a

    def poke(self):
        with self._b_lock:
            return 1

    def back(self):
        with self._b_lock:
            self.a.go()
'''
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert _checks(ConcurrencyPass().run(idx), "lock-cycle")


def test_non_daemon_thread_caught(tmp_path):
    bad = ("import threading\n\n\n"
           "def spawn(fn):\n"
           "    t = threading.Thread(target=fn)\n"
           "    t.start()\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": bad})
    assert _checks(ConcurrencyPass().run(idx), "non-daemon-thread")
    ok = bad.replace("target=fn)", "target=fn, daemon=True)")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": ok})
    assert not _checks(ConcurrencyPass().run(idx), "non-daemon-thread")
    joined = bad + "    t.join()\n"
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": joined})
    assert not _checks(ConcurrencyPass().run(idx), "non-daemon-thread")


# ---- knob pass ---------------------------------------------------------

def test_unregistered_knob_caught(tmp_path):
    src = ("from dmlc_tpu.base import get_env\n\n"
           "v = get_env(\"DMLC_NO_SUCH_KNOB_EVER\", 1)\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert _checks(KnobPass().run(idx), "unregistered-knob")
    ok = src.replace("DMLC_NO_SUCH_KNOB_EVER", "DMLC_FEED_DEPTH")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": ok})
    assert not KnobPass().run(idx)


def test_raw_env_read_caught_in_package_only(tmp_path):
    src = "import os\n\nv = os.environ.get(\"DMLC_FEED_DEPTH\")\n"
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert _checks(KnobPass().run(idx), "raw-env-read")
    # the same read in scripts/ is allowed (package-only invariant)
    idx = _index(tmp_path, {"scripts/mod.py": src})
    assert not _checks(KnobPass().run(idx), "raw-env-read")


def test_unknown_knob_token_caught(tmp_path):
    src = 'DOC = "set DMLC_TOTALLY_MADE_UP to tune nothing"\n'
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert _checks(KnobPass().run(idx), "unknown-knob-token")
    # family-prefix mentions of real knobs are fine
    ok = 'DOC = "the DMLC_COLL_ knobs must be gang-uniform"\n'
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": ok})
    assert not KnobPass().run(idx)


def test_pass_envs_missing_caught(tmp_path):
    launch = ('PASS_ENVS = [\n    "DMLC_INTERFACE",\n]\n')
    idx = _index(tmp_path, {"dmlc_tpu/tracker/launch.py": launch})
    missing = _checks(KnobPass().run(idx), "pass-envs-missing")
    # every other pass_to_workers knob is reported missing
    assert len(missing) == len(config_registry.pass_env_names()) - 1


def test_pass_envs_unknown_caught(tmp_path):
    launch = ('PASS_ENVS = [\n    "DMLC_BOGUS_FORWARD",\n]\n')
    idx = _index(tmp_path, {"dmlc_tpu/tracker/launch.py": launch})
    assert _checks(KnobPass().run(idx), "pass-envs-unknown")


# ---- contract pass -----------------------------------------------------

SWALLOW = '''\
def pull(sock):
    try:
        return sock.recv_thing()
    except Exception:
        return None
'''


def test_swallowed_exception_caught_in_protected_path(tmp_path):
    idx = _index(tmp_path, {"dmlc_tpu/tracker/client.py": SWALLOW})
    assert _checks(ContractPass().run(idx), "swallowed-exception")
    # same handler outside the protected paths is fine
    idx = _index(tmp_path, {"dmlc_tpu/telemetry/foo.py": SWALLOW})
    assert not _checks(ContractPass().run(idx), "swallowed-exception")


def test_swallow_ok_when_protected_type_handled_first(tmp_path):
    src = '''\
from ..base import DMLCError
from .client import WorldResized


def pull(sock):
    try:
        return sock.recv_thing()
    except WorldResized:
        raise
    except Exception:
        return None
'''
    idx = _index(tmp_path, {"dmlc_tpu/tracker/client.py": src})
    assert not _checks(ContractPass().run(idx), "swallowed-exception")


def test_swallow_ok_when_transported(tmp_path):
    src = '''\
def pull(sock, fut):
    try:
        return sock.recv_thing()
    except BaseException as e:
        fut.set_exception(e)
'''
    idx = _index(tmp_path, {"dmlc_tpu/tracker/client.py": src})
    assert not _checks(ContractPass().run(idx), "swallowed-exception")


def test_socket_no_timeout_caught(tmp_path):
    bad = ("import socket\n\n\n"
           "def dial():\n"
           "    s = socket.socket()\n"
           "    return s\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": bad})
    assert _checks(ContractPass().run(idx), "socket-no-timeout")
    ok = bad.replace("    return s\n",
                     "    s.settimeout(5.0)\n    return s\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": ok})
    assert not _checks(ContractPass().run(idx), "socket-no-timeout")


def test_typod_fault_site_caught(tmp_path):
    bad = 'SPEC = "tracker.dail=error::2"\n'  # typo'd tracker.dial
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": bad})
    assert _checks(ContractPass().run(idx), "unknown-fault-site")


def test_fault_site_resolves_against_instrumented_calls(tmp_path):
    src = ('from dmlc_tpu.resilience import fault_point\n\n'
           'SPEC = "my.site@rank:1=kill:137"\n\n\n'
           'def go(rank):\n'
           '    fault_point("my.site", rank=rank)\n')
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert not _checks(ContractPass().run(idx), "unknown-fault-site")


def test_fault_site_in_embedded_worker_source_counts(tmp_path):
    src = ("WORKER = '''\n"
           "from dmlc_tpu.resilience import fault_point\n"
           'fault_point("embedded.site", rank=0)\n'
           "'''\n"
           'SPEC = "embedded.site=delay:0.1"\n')
    idx = _index(tmp_path, {"scripts/smoke.py": src})
    assert not _checks(ContractPass().run(idx), "unknown-fault-site")


# ---- style / metrics passes (absorbed lint.py) -------------------------

def test_style_pass_catches_classics(tmp_path):
    src = ("import os\n\n\n"
           "def f(x=[]):\n"
           "    try:\n"
           "        return x\n"
           "    except:\n"
           "        pass\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    found = StylePass().run(idx)
    for check in ("unused-import", "mutable-default", "bare-except"):
        assert _checks(found, check), (check, found)


def test_metrics_pass_catches_unregistered_family(tmp_path):
    src = ('from dmlc_tpu import telemetry\n\n'
           'telemetry.inc("bogus_stage", "bogus_name")\n')
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    assert _checks(MetricsPass().run(idx), "metric-name")


def test_suppression_comment_and_counting(tmp_path):
    src = ("import threading\n"
           "import time\n\n"
           "_lock = threading.Lock()\n\n\n"
           "def slow():\n"
           "    with _lock:\n"
           "        # dmlc-check: disable=blocking-under-lock -- test\n"
           "        time.sleep(1.0)\n")
    idx = _index(tmp_path, {"dmlc_tpu/mod.py": src})
    findings, suppressed = run_passes(idx, [ConcurrencyPass()])
    assert not findings
    assert [s.check for s in suppressed] == ["blocking-under-lock"]


# ---------------------------------------------------------------------------
# whole-repo invariants
# ---------------------------------------------------------------------------

def _repo_index():
    roots = ["dmlc_tpu", "tests", "scripts", "examples", "bench.py",
             "__graft_entry__.py", "bin"]
    return RepoIndex(default_paths(roots, REPO), REPO)


def test_repo_runs_clean():
    """The shipped tree passes every dmlc-check pass (suppressions
    allowed — they are inline-visible and counted)."""
    idx = _repo_index()
    findings, _suppressed = run_passes(idx, [cls() for cls in ALL_PASSES])
    assert not findings, "\n".join(str(f) for f in findings[:40])


_READ_RE = re.compile(
    r"(?:os\.environ(?:\.get)?\s*[\[\(]|os\.getenv\(|get_env\()"
    r"\s*[\"'](DMLC_[A-Z0-9_]+)[\"']")


def test_registry_covers_every_env_read_grep():
    """Independent cross-check: a raw regex grep over dmlc_tpu/ (no AST,
    no shared code with the knob pass) finds no env read the registry
    does not know."""
    known = set(config_registry.names())
    unknown = {}
    for dirpath, dirnames, filenames in os.walk(
            os.path.join(REPO, "dmlc_tpu")):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, encoding="utf-8") as f:
                src = f.read()
            for key in _READ_RE.findall(src):
                if key not in known:
                    unknown.setdefault(key, path)
    assert not unknown, unknown


def test_pass_envs_matches_registry():
    from dmlc_tpu.tracker.launch import PASS_ENVS

    missing = [k for k in config_registry.pass_env_names()
               if k not in PASS_ENVS]
    assert not missing, missing
    bogus = [k for k in PASS_ENVS if k.startswith("DMLC_")
             and config_registry.get(k) is None]
    assert not bogus, bogus


def test_readme_knob_table_current():
    from dmlc_tpu.analysis.knob_pass import readme_with_table

    with open(os.path.join(REPO, "README.md"), encoding="utf-8") as f:
        src = f.read()
    want = readme_with_table(src, config_registry.render_markdown_table())
    assert want == src, ("README knob table drifted — run "
                         "scripts/dmlc_check.py --write-knob-table")


def test_registry_table_lists_every_knob():
    table = config_registry.render_markdown_table()
    for k in config_registry.names():
        assert f"`{k}`" in table, k


# ---------------------------------------------------------------------------
# runtime lock-order watchdog
# ---------------------------------------------------------------------------

@pytest.fixture
def lockcheck(monkeypatch):
    monkeypatch.setenv("DMLC_LOCKCHECK", "1")
    concurrency.lockcheck_reset()
    yield
    concurrency.lockcheck_reset()


def test_make_lock_plain_when_disabled(monkeypatch):
    monkeypatch.delenv("DMLC_LOCKCHECK", raising=False)
    lk = concurrency.make_lock("x")
    assert not isinstance(lk, concurrency.CheckedLock)
    with lk:
        pass


def test_watchdog_flags_inversion_across_threads(lockcheck):
    a = concurrency.make_lock("test.A")
    b = concurrency.make_lock("test.B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    # the two threads never overlap in time — a stress test would pass;
    # the order graph still convicts the pair
    t1 = threading.Thread(target=ab, daemon=True)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba, daemon=True)
    t2.start()
    t2.join()
    kinds = [v["kind"] for v in concurrency.lockcheck_report()]
    assert "order-inversion" in kinds
    with pytest.raises(Exception, match="order-inversion"):
        concurrency.lockcheck_assert_clean()


def test_watchdog_clean_on_consistent_order(lockcheck):
    a = concurrency.make_lock("test.C")
    b = concurrency.make_lock("test.D")

    def ab():
        with a:
            with b:
                pass

    for _ in range(2):
        t = threading.Thread(target=ab, daemon=True)
        t.start()
        t.join()
    with a:
        with b:
            pass
    assert concurrency.lockcheck_report() == []
    concurrency.lockcheck_assert_clean()


def test_watchdog_flags_held_while_blocked(lockcheck, monkeypatch):
    monkeypatch.setenv("DMLC_LOCKCHECK_BLOCK_S", "0.1")
    x = concurrency.make_lock("test.X")
    y = concurrency.make_lock("test.Y")
    release = threading.Event()

    def holder():
        with x:
            release.wait(2.0)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    time.sleep(0.05)
    got = []

    def contender():
        with y:
            with x:
                got.append(1)

    t2 = threading.Thread(target=contender, daemon=True)
    t2.start()
    time.sleep(0.3)
    release.set()
    t2.join(5.0)
    t.join(5.0)
    assert got == [1]
    kinds = [v["kind"] for v in concurrency.lockcheck_report()]
    assert "held-while-blocked" in kinds


def test_watchdog_reentrant_lock_not_self_edge(lockcheck):
    r = concurrency.make_rlock("test.R")
    with r:
        with r:
            pass
    assert concurrency.lockcheck_report() == []


def test_condition_over_checked_lock_wait_notify(lockcheck):
    cv = threading.Condition(concurrency.make_rlock("test.CV"))
    done = []

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
            done.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    time.sleep(0.1)
    with cv:
        cv.notify_all()
    t.join(5.0)
    assert done == [1]


def test_watchdog_same_class_instances_abba(lockcheck):
    """Two locks sharing a class-level NAME are still distinct graph
    nodes: q1->q2 vs q2->q1 is a real deadlock pair, not a self-edge."""
    q1 = concurrency.make_lock("Queue._lock")
    q2 = concurrency.make_lock("Queue._lock")

    def order(a, b):
        with a:
            with b:
                pass

    t = threading.Thread(target=order, args=(q1, q2), daemon=True)
    t.start()
    t.join()
    t = threading.Thread(target=order, args=(q2, q1), daemon=True)
    t.start()
    t.join()
    kinds = [v["kind"] for v in concurrency.lockcheck_report()]
    assert "order-inversion" in kinds


def test_watchdog_witness_site_is_user_frame(lockcheck):
    a = concurrency.make_lock("site.A")
    b = concurrency.make_lock("site.B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join()
    (v,) = concurrency.lockcheck_report()
    # the witness must point at THIS file, not threading.py internals
    assert "test_analysis.py" in v["detail"], v
    assert "threading.py" not in v["detail"], v


def test_get_env_empty_value_means_unset(monkeypatch):
    from dmlc_tpu.base import get_env

    monkeypatch.setenv("DMLC_RETRY_MAX_S", "")
    assert get_env("DMLC_RETRY_MAX_S", 30.0) == 30.0
    monkeypatch.setenv("DMLC_ELASTIC", "")
    assert get_env("DMLC_ELASTIC", True) is True
    # str knobs keep the empty string (callers use `or fallback`)
    monkeypatch.setenv("DMLC_TRACKER_URI", "")
    assert get_env("DMLC_TRACKER_URI", "x") == ""


def test_bufferpool_clean_under_lockcheck(lockcheck):
    pool = concurrency.BufferPool(lambda: object(), capacity=2)
    a = pool.acquire()
    b = pool.acquire()
    pool.release(a)
    pool.release(b)
    pool.kill()
    assert pool.acquire() is None
    concurrency.lockcheck_assert_clean()
