"""RequestLedger lifecycle accounting (telemetry.requests).

The unit tests drive explicit clocks through every hook, so the
accounting identities are checked EXACTLY: TTFT ≡ queue + prefill,
token counts survive preempt/resume episodes, failures carry their
reason.  One integration test runs the real engine + HTTP surface and
re-checks the identity and the new endpoints end to end.
"""

import json
import urllib.request

import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.telemetry.requests import (FAIL_REASONS,
                                         REQUEST_ROW_TID_BASE,
                                         RequestLedger)


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.reset()
    telemetry.reset_steps()
    yield
    telemetry.reset()
    telemetry.reset_steps()


def _full_lifecycle(led, rid=1, t0=100.0):
    led.on_submit(rid, n_prompt=5, max_new_tokens=8, t=t0)
    led.on_prefill_begin(rid, t=t0 + 0.4)
    led.on_first_token(rid, t=t0 + 0.7)
    led.on_token(rid, t=t0 + 0.8)
    led.on_token(rid, t=t0 + 0.95)
    return led.on_finish(rid, t=t0 + 1.0)


# ---------------------------------------------------------------------------
# lifecycle accounting
# ---------------------------------------------------------------------------

def test_ttft_decomposes_exactly_into_queue_plus_prefill():
    led = RequestLedger(capacity=16, trace_rows=False)
    rec = _full_lifecycle(led)
    assert rec["queue_s"] == pytest.approx(0.4, abs=1e-12)
    assert rec["prefill_s"] == pytest.approx(0.3, abs=1e-12)
    # the identity is by construction, not within a tolerance: all
    # three derive from the same three stamps
    assert rec["ttft_s"] == rec["queue_s"] + rec["prefill_s"]
    assert rec["state"] == "done" and rec["reason"] is None
    assert rec["n_generated"] == 3
    assert rec["latency_s"] == pytest.approx(1.0, abs=1e-12)


def test_tbt_gaps_recorded_per_token():
    led = RequestLedger(capacity=16, trace_rows=False)
    rec = _full_lifecycle(led)
    # gaps: 0.1 (first->second), 0.15 (second->third)
    assert rec["tbt_max_s"] == pytest.approx(0.15, abs=1e-9)
    assert rec["tbt_mean_s"] == pytest.approx(0.125, abs=1e-9)
    summ = led.summary()
    assert summ["tbt_p99_s"] == pytest.approx(0.15, abs=1e-9)
    # the registry histogram rode along
    snap = telemetry.snapshot()
    assert snap["histograms"]["serving"]["tbt_secs"]["count"] == 2


def test_preempt_resume_keeps_token_counts_exact():
    led = RequestLedger(capacity=16, trace_rows=False)
    led.on_submit(1, n_prompt=4, t=10.0)
    led.on_prefill_begin(1, t=10.2)
    led.on_first_token(1, t=10.5)
    led.on_token(1, t=10.6)
    led.on_token(1, t=10.7)          # 3 tokens so far
    led.on_preempt(1, t=10.75)
    # resume: re-prefill recomputes context, NO new first token
    led.on_prefill_begin(1, t=11.0, resume=True)
    led.on_prefill_end(1, t=11.2)
    led.on_token(1, t=11.3)          # 4th token
    led.on_token(1, t=11.4)          # 5th
    rec = led.on_finish(1, t=11.45)
    assert rec["n_generated"] == 5
    assert rec["preemptions"] == 1
    assert rec["resumes"] == 1
    # ttft is from the FIRST episode only (resume must not reset it)
    assert rec["ttft_s"] == pytest.approx(0.5, abs=1e-12)
    assert rec["ttft_s"] == rec["queue_s"] + rec["prefill_s"]
    # the cross-preemption gap (10.7 -> 11.3) IS a TBT observation:
    # that stall is what a streaming user experiences
    assert rec["tbt_max_s"] == pytest.approx(0.6, abs=1e-9)
    snap = telemetry.snapshot()
    assert snap["counters"]["serving"]["resumes"] == 1


def test_failed_request_records_reason_and_counter():
    led = RequestLedger(capacity=16, trace_rows=False)
    led.on_submit(1, n_prompt=4, t=0.0)
    led.on_prefill_begin(1, t=0.1)
    rec = led.on_finish(1, error="prefill failed: boom",
                        reason="prefill", t=0.2)
    assert rec["state"] == "failed"
    assert rec["reason"] == "prefill"
    assert rec["error"] == "prefill failed: boom"
    assert rec["ttft_s"] is None  # never produced a token
    snap = telemetry.snapshot()
    assert snap["counters"]["serving"]["failed_prefill"] == 1
    assert led.summary()["fail_reasons"] == {"prefill": 1}


def test_draining_shutdown_reason_and_unknown_reason_folds_to_other():
    led = RequestLedger(capacity=16, trace_rows=False)
    led.on_submit(1, n_prompt=2, t=0.0)
    rec = led.on_finish(1, error="engine shut down",
                        reason="shutdown", t=0.5)
    assert rec["reason"] == "shutdown" and "shutdown" in FAIL_REASONS
    led.on_submit(2, n_prompt=2, t=1.0)
    rec2 = led.on_finish(2, error="weird", reason="not-a-slug", t=1.5)
    assert rec2["reason"] == "other"
    assert led.summary()["fail_reasons"] == {"shutdown": 1, "other": 1}


def test_unknown_and_double_finish_are_noops():
    led = RequestLedger(capacity=16, trace_rows=False)
    assert led.on_finish(99) is None
    led.on_prefill_begin(98)      # never submitted: ignored
    led.on_token(97)
    led.on_preempt(96)
    rec = _full_lifecycle(led, rid=1)
    assert rec is not None
    assert led.on_finish(1) is None  # already moved to the ring
    assert led.summary()["requests_done"] == 1


def test_ring_bounded_and_records_since_contract():
    led = RequestLedger(capacity=4, trace_rows=False)
    for i in range(1, 8):
        _full_lifecycle(led, rid=i, t0=float(i) * 10)
    assert len(led.records()) == 4  # ring evicted the oldest
    recs, last = led.records_since(0)
    assert [r["seq"] for r in recs] == [4, 5, 6, 7]
    assert last == 7  # high-water mark includes evicted records
    # truncation: last returned seq so the remainder ships next beat
    recs, last = led.records_since(4, limit=2)
    assert [r["seq"] for r in recs] == [5, 6] and last == 6
    recs, last = led.records_since(7)
    assert recs == [] and last == 7


def test_live_view_tracks_states():
    led = RequestLedger(capacity=16, trace_rows=False)
    led.on_submit(1, n_prompt=3, t=0.0)
    assert led.live()[0]["state"] == "queued"
    led.on_prefill_begin(1, t=0.1)
    led.on_first_token(1, t=0.2)
    view = led.live()[0]
    assert view["state"] == "active" and view["n_generated"] == 1
    assert led.summary()["live_requests"] == 1
    led.on_finish(1, t=0.3)
    assert led.live() == []


def test_iteration_ring_carries_kv_pressure():
    led = RequestLedger(capacity=16, trace_rows=False)
    for i in range(5):
        led.on_iteration(active=3, waiting=i, preempted=i % 2, tokens=3,
                         kv_stats={"blocks_in_use": 10, "n_blocks": 32,
                                   "occupancy": 10 / 32,
                                   "waste_tokens": 7,
                                   "cached_tokens": 153})
    its = led.iterations()
    assert len(its) == 5
    assert its[-1]["kv_occupancy"] == pytest.approx(10 / 32)
    assert its[-1]["kv_waste_tokens"] == 7
    assert its[-1]["waiting"] == 4
    summ = led.summary()
    assert summ["decode_queue_depth"] == 4
    assert summ["kv_occupancy"] == pytest.approx(10 / 32)


def test_trace_rows_land_in_span_ring_with_request_tids():
    led = RequestLedger(capacity=16, trace_rows=True)
    _full_lifecycle(led, rid=7)
    spans = [s for s in telemetry.spans()
             if s["tid"] == REQUEST_ROW_TID_BASE + 7]
    names = [s["name"] for s in spans]
    assert names == ["serving.queue", "serving.prefill", "serving.decode"]
    assert all(s["thread"] == "req 7" for s in spans)
    assert all(s["args"]["req"] == 7 for s in spans)
    # queue span covers submit -> prefill begin (0.4s), prefill span
    # prefill begin -> first token (0.3s)
    assert spans[0]["dur"] == pytest.approx(0.4e6, rel=1e-9)
    assert spans[1]["dur"] == pytest.approx(0.3e6, rel=1e-9)


def test_queue_wait_histogram_published():
    led = RequestLedger(capacity=16, trace_rows=False)
    _full_lifecycle(led)
    snap = telemetry.snapshot()
    h = snap["histograms"]["serving"]["queue_wait_secs"]
    assert h["count"] == 1
    assert h["max"] == pytest.approx(0.4, abs=1e-9)


def test_summary_percentiles_over_many_requests():
    led = RequestLedger(capacity=64, trace_rows=False)
    for i in range(1, 11):
        t0 = i * 100.0
        led.on_submit(i, n_prompt=4, t=t0)
        led.on_prefill_begin(i, t=t0 + 0.01 * i)   # queue 0.01*i
        led.on_first_token(i, t=t0 + 0.01 * i + 0.2)
        led.on_finish(i, t=t0 + 1.0)
    summ = led.summary()
    assert summ["requests_done"] == 10
    # nearest-rank percentiles (the StepLedger/loadgen convention):
    # p50 of 10 ordered values is the 6th (index int(5.0))
    assert summ["queue_wait_p50_s"] == pytest.approx(0.06, abs=1e-6)
    assert summ["queue_wait_p99_s"] == pytest.approx(0.10, abs=1e-6)
    assert summ["prefill_p99_s"] == pytest.approx(0.2, abs=1e-6)
    assert summ["preemption_rate"] == 0.0


# ---------------------------------------------------------------------------
# engine + HTTP integration
# ---------------------------------------------------------------------------

def _tiny_model():
    import jax

    from dmlc_tpu.models import transformer as tfm

    cfg = tfm.TransformerConfig(vocab=64, d_model=32, n_heads=2, head_dim=8,
                                d_ff=64, n_layers=2, n_experts=1,
                                microbatches=1)
    return tfm.init_params(jax.random.PRNGKey(0), cfg), cfg


def test_engine_request_ledger_end_to_end():
    from dmlc_tpu.serving import InferenceEngine, ServingHTTPServer
    from dmlc_tpu.telemetry.slo import SLOMonitor

    params, cfg = _tiny_model()
    mon = SLOMonitor(ttft_p99_s=60.0, error_rate=0.5)
    eng = InferenceEngine(params, cfg, n_blocks=32, block_size=4,
                          max_active=3, queue_depth=8,
                          admit_timeout_s=2.0, slo_monitor=mon)
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    try:
        body = json.dumps({"prompt": [1, 2, 3], "max_tokens": 5}).encode()
        req = urllib.request.Request(
            srv.url + "/generate", data=body,
            headers={"Content-Type": "application/json"})
        out = json.loads(urllib.request.urlopen(req, timeout=120).read())
        assert out["state"] == "done" and out["n_generated"] == 5

        doc = json.loads(urllib.request.urlopen(
            srv.url + "/requests", timeout=30).read())
        rec = doc["recent"][-1]
        assert rec["state"] == "done" and rec["n_generated"] == 5
        # the headline identity, measured on the real engine
        assert rec["ttft_s"] == pytest.approx(
            rec["queue_s"] + rec["prefill_s"], abs=1e-9)
        assert doc["summary"]["requests_done"] == 1
        assert doc["iterations"], "decode iterations not recorded"
        assert "kv_occupancy" in doc["iterations"][-1]

        slo_doc = json.loads(urllib.request.urlopen(
            srv.url + "/slo", timeout=30).read())
        assert slo_doc["enabled"]
        assert slo_doc["objectives"]["ttft_p99"]["events_slow"] >= 1
        assert slo_doc["active"] == []

        # per-status counter: exactly one 200 answered
        snap = telemetry.snapshot()
        assert snap["counters"]["serving"]["http_200"] == 1

        # the request drew its own /trace row
        tr = json.loads(urllib.request.urlopen(
            srv.url + "/trace", timeout=30).read())
        rows = [e for e in tr["traceEvents"]
                if e.get("ph") == "M" and e["name"] == "thread_name"
                and str(e["args"].get("name", "")).startswith("req ")]
        assert rows, "no per-request trace rows on /trace"
    finally:
        srv.close()
        eng.close()


def test_engine_http_400_and_413_counted():
    from dmlc_tpu.serving import InferenceEngine, ServingHTTPServer
    from dmlc_tpu.telemetry.slo import SLOMonitor

    params, cfg = _tiny_model()
    eng = InferenceEngine(params, cfg, n_blocks=8, block_size=4,
                          max_active=2, queue_depth=4,
                          slo_monitor=SLOMonitor())
    eng.start()
    srv = ServingHTTPServer(eng, port=0)
    try:
        def post(doc):
            body = json.dumps(doc).encode()
            req = urllib.request.Request(
                srv.url + "/generate", data=body,
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                return 200
            except urllib.error.HTTPError as e:
                return e.code

        import urllib.error

        assert post({"prompt": "nope"}) == 400
        assert post({"prompt": [1] * 1000, "max_tokens": 4}) == 413
        # a POST to an unknown path is a misrouted client → counted;
        # a GET probe (monitoring tools poll optional endpoints) is not
        for method, data in (("POST", b"{}"), ("GET", None)):
            try:
                urllib.request.urlopen(urllib.request.Request(
                    srv.url + "/nope", data=data, method=method),
                    timeout=10)
            except urllib.error.HTTPError as e:
                assert e.code == 404
        snap = telemetry.snapshot()
        assert snap["counters"]["serving"]["http_400"] == 1
        assert snap["counters"]["serving"]["http_413"] == 1
        assert snap["counters"]["serving"]["http_404"] == 1
    finally:
        srv.close()
        eng.close()
