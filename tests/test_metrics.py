"""Per-stage metrics counters (SURVEY §5 tracing/profiling rebuild)."""

import numpy as np
import pytest

from dmlc_tpu import metrics
from dmlc_tpu.parallel import build_mesh


@pytest.fixture(autouse=True)
def fresh_metrics():
    metrics.reset()
    yield
    metrics.reset()


def test_counters_and_timers():
    metrics.inc("stage", "things", 3)
    metrics.inc("stage", "things", 2)
    with metrics.timed("stage", "work"):
        pass
    snap = metrics.snapshot()
    assert snap["stage"]["things"] == 5
    assert snap["stage"]["work_secs"] >= 0
    # snapshot is a copy: mutating it does not affect live counters
    snap["stage"]["things"] = 0
    assert metrics.snapshot()["stage"]["things"] == 5
    metrics.reset()
    assert metrics.snapshot() == {}


def test_input_split_and_parser_counters(tmp_path):
    from dmlc_tpu.data import create_row_iter
    from dmlc_tpu.io import input_split
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    path = str(tmp_path / "m.rec")
    with Stream.create(path, "w") as s:
        w = RecordIOWriter(s)
        for i in range(300):
            w.write_record(bytes([i % 251]) * 32)

    split = input_split.create(path, 0, 1, "recordio")
    n = 0
    while split.next_record() is not None:
        n += 1
    split.close()
    snap = metrics.snapshot()["input_split"]
    assert snap["records"] == n == 300
    assert snap["chunks"] >= 1
    assert snap["bytes"] > 300 * 32  # payload + headers

    # parser counters on the libsvm path
    lib = tmp_path / "m.libsvm"
    lib.write_text("".join(f"{i % 2} 0:{i}.0\n" for i in range(64)))
    it = create_row_iter(str(lib), 0, 1, "libsvm")
    rows = sum(blk.size for blk in it)
    psnap = metrics.snapshot()["parser"]
    assert psnap["rows"] == rows == 64
    assert psnap["blocks"] >= 1
    assert psnap["bytes"] > 0
    assert "parse_secs" in psnap


def test_feed_counters(tmp_path):
    from dmlc_tpu.feed import libsvm_feed

    lib = tmp_path / "f.libsvm"
    lib.write_text("".join(f"{i % 2} 0:{i}.0 3:1.5\n" for i in range(64)))
    mesh = build_mesh(1, dp=1, sp=1, tp=1, pp=1, ep=1)
    feed = libsvm_feed(str(lib), mesh, batch_size=4, max_nnz=4)
    batches = list(feed)
    snap = metrics.snapshot()["feed"]
    assert snap["batches"] == len(batches) > 0
    assert snap["bytes_to_device"] > 0
    assert "device_put_secs" in snap and "consumer_stall_secs" in snap


def test_annotate_is_usable_under_jit():
    import jax
    import jax.numpy as jnp

    with metrics.annotate("test_span"):
        x = jax.jit(lambda a: a * 2)(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(x), 2.0)
