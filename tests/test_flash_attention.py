"""Pallas flash-attention kernel vs the exact oracle (interpret mode on
the CPU mesh; the same kernel compiles for TPU)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dmlc_tpu.ops.flash_attention import (
    block_attend_flash,
    flash_attention,
    supports,
)
from dmlc_tpu.parallel.ring_attention import (
    _block_attend,
    ring_attention_reference,
)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_matches_oracle(causal):
    b, t, h, d = 2, 64, 2, 128
    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)
    want = ring_attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_block_attend_matches_lax_with_offsets():
    """The ring-step contract: partial (pv, m, l) with global offsets."""
    b, tq, tk, h, d = 1, 32, 32, 2, 128
    key = jax.random.PRNGKey(1)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, tq, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, tk, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, tk, h, d), jnp.float32)
    scale = 1.0 / (d ** 0.5)

    # emulate ring step: q and kv blocks at the SAME global offset, so the
    # mask is genuinely triangular and the causal path is exercised
    q_pos = np.arange(tq)
    gq = 32 + q_pos[:, None]
    gk = 32 + q_pos[None, :]
    mask = jnp.asarray(gq >= gk)
    assert bool(mask.all()) is False  # partially masked, not all-visible
    pv_l, m_l, l_l = _block_attend(q, k, v, scale=scale, mask=mask)
    pv_f, m_f, l_f = block_attend_flash(
        q, k, v, scale=scale, causal=True, q_offset=32, kv_offset=32,
        block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(pv_f), np.asarray(pv_l), atol=2e-5)
    np.testing.assert_allclose(np.asarray(m_f), np.asarray(m_l), atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_l), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("t", [200, 77])
def test_flash_attention_unaligned_tail(causal, t):
    """T not a multiple of block sizes must pad-and-mask, not silently
    drop tail blocks (rows past the last full block were uncomputed)."""
    b, h, d = 1, 2, 128
    key = jax.random.PRNGKey(3)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)
    want = ring_attention_reference(q, k, v, causal=causal)
    got = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5)


def test_block_attend_unaligned_kv_shard():
    """Ring-step shape: KV shard length not a block multiple; the (pv,m,l)
    partials must exclude the padded KV rows."""
    b, tq, tk, h, d = 1, 32, 40, 1, 128
    key = jax.random.PRNGKey(4)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, tq, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, tk, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, tk, h, d), jnp.float32)
    scale = 1.0 / (d ** 0.5)
    mask = jnp.ones((tq, tk), bool)
    pv_l, m_l, l_l = _block_attend(q, k, v, scale=scale, mask=mask)
    pv_f, m_f, l_f = block_attend_flash(
        q, k, v, scale=scale, causal=False, q_offset=0, kv_offset=0,
        block_q=16, block_k=16, interpret=True)
    np.testing.assert_allclose(np.asarray(pv_f), np.asarray(pv_l), atol=2e-5)
    np.testing.assert_allclose(np.asarray(m_f), np.asarray(m_l), atol=2e-5)
    np.testing.assert_allclose(np.asarray(l_f), np.asarray(l_l), atol=2e-5)


@pytest.mark.parametrize("t", [64, 200])
@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_gradients_match_oracle(causal, t):
    """custom_vjp: d/dq,k,v of the flash path must equal the dense oracle
    (pallas_call itself has no autodiff rule)."""
    b, h, d = 1, 2, 128
    key = jax.random.PRNGKey(5)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, t, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, t, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, t, h, d), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(6), (b, t, h, d), jnp.float32)

    def f_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                            interpret=True)
        return jnp.sum(o * w)

    def f_ref(q, k, v):
        return jnp.sum(ring_attention_reference(q, k, v, causal=causal) * w)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=3e-4, rtol=1e-3)


def test_block_attend_flash_gradients_with_offsets():
    """Ring-step VJP: grads through (pv, m, l) with nonzero global offsets
    must match differentiating the lax oracle directly (kernel fwd + lax
    twin bwd must stay in sync)."""
    b, tq, tk, h, d = 1, 32, 32, 2, 128
    key = jax.random.PRNGKey(8)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (b, tq, h, d), jnp.float32)
    k = jax.random.normal(kk, (b, tk, h, d), jnp.float32)
    v = jax.random.normal(kv, (b, tk, h, d), jnp.float32)
    scale = 1.0 / (d ** 0.5)
    qoff, kvoff = 64, 32  # Q block strictly after KV: partially masked

    def scalar_of(pv, m, l):
        # touch all three outputs so every cotangent path is exercised
        return (jnp.sum(pv * pv) + jnp.sum(jnp.exp(m - 2.0))
                + jnp.sum(l * l) * 0.1)

    def f_flash(q, k, v):
        pv, m, l = block_attend_flash(
            q, k, v, scale=scale, causal=True, q_offset=qoff,
            kv_offset=kvoff, block_q=16, block_k=16, interpret=True)
        return scalar_of(pv, m, l)

    def f_lax(q, k, v):
        gq = qoff + np.arange(tq)
        gk = kvoff + np.arange(tk)
        mask = jnp.asarray(gq[:, None] >= gk[None, :])
        pv, m, l = _block_attend(q, k, v, scale=scale, mask=mask)
        return scalar_of(pv, m, l)

    gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(f_lax, argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   atol=3e-4, rtol=1e-3)


def test_supports_gate():
    assert supports((1, 64, 2, 128), (1, 64, 2, 128))
    assert not supports((1, 64, 2, 96), (1, 64, 2, 96))  # lane
    # unaligned seq lengths are padded-and-masked in-kernel, so supported
    assert supports((1, 200, 2, 128), (1, 200, 2, 128))
    assert not supports((1, 4, 2, 128), (1, 4, 2, 128))  # tiny


def test_flash_under_jit_with_traced_offsets():
    b, t, h, d = 1, 32, 1, 128
    q = jax.random.normal(jax.random.PRNGKey(2), (b, t, h, d))

    @jax.jit
    def run(q, off):
        pv, m, l = block_attend_flash(
            q, q, q, scale=0.1, causal=True, q_offset=off, kv_offset=0,
            block_q=16, block_k=16, interpret=True)
        return pv

    # q_offset=0 vs kv at 0 is triangular; q_offset=320 is fully visible —
    # the same compiled kernel must produce different results (proving the
    # offsets are traced, not baked in at trace time)
    a = run(q, jnp.int32(0))
    b2 = run(q, jnp.int32(320))
    assert not np.allclose(np.asarray(a), np.asarray(b2))
    # and each run matches the lax oracle for its mask
    q_pos = np.arange(t)
    for off, out in ((0, a), (320, b2)):
        mask = jnp.asarray(off + q_pos[:, None] >= q_pos[None, :])
        pv_l, _, _ = _block_attend(q, q, q, scale=0.1, mask=mask)
        np.testing.assert_allclose(np.asarray(out), np.asarray(pv_l),
                                   atol=2e-5)
