"""Launcher tests: local backend end-to-end through real subprocesses,
GangScheduler retry/blacklist semantics, command builders, CLI opts."""

import os
import subprocess
import numpy as np
import sys
from types import SimpleNamespace

import pytest

from dmlc_tpu.tracker import launch
from dmlc_tpu.tracker.opts import get_opts, parse_memory_mb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_opts_parsing():
    args = get_opts([
        "--cluster", "local", "--num-workers", "3",
        "--worker-memory", "2g", "--env", "FOO=bar", "--",
        "python", "x.py", "--flag",
    ])
    assert args.num_workers == 3
    assert args.worker_memory_mb == 2048
    assert args.extra_env == {"FOO": "bar"}
    assert args.command == ["python", "x.py", "--flag"]
    assert parse_memory_mb("512m") == 512


def test_local_submit_end_to_end():
    args = get_opts([
        "--cluster", "local", "--num-workers", "3", "--host-ip", "127.0.0.1",
        "--", sys.executable, os.path.join(REPO, "examples",
                                           "allreduce_worker.py"),
    ])
    tracker = launch.submit_local(args)
    assert tracker is not None and not tracker.alive()
    assert tracker.start_time is not None and tracker.end_time is not None
    tracker.close()


def test_cli_end_to_end():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dmlc-submit"),
         "--cluster", "local", "--num-workers", "2", "--host-ip", "127.0.0.1",
         "--", sys.executable,
         os.path.join(REPO, "examples", "allreduce_worker.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    assert "allreduce OK" in r.stderr


def test_jax_distributed_bridge_end_to_end():
    """The headline capability: dmlc-submit → N processes →
    jax.distributed over the tracker-allocated coordinator → one global
    mesh → a verified cross-process psum (reference role:
    tracker.py:410-433 driving real multi-node workers)."""
    env = os.environ.copy()
    env["JAX_PLATFORMS"] = "cpu"
    # one local CPU device per process: the global mesh must span
    # PROCESSES, not virtual devices within one
    env["XLA_FLAGS"] = ""
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dmlc-submit"),
         "--cluster", "local", "--num-workers", "2", "--host-ip",
         "127.0.0.1", "--", sys.executable,
         os.path.join(REPO, "examples", "jax_psum_worker.py")],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert r.stderr.count("jax psum OK") == 2, r.stderr[-2000:]


def test_coordinator_port_distinct_from_tracker():
    """The jax coordinator must never reuse the rabit tracker's bound
    port (the round-3 collision)."""
    from dmlc_tpu.tracker import rendezvous

    seen = {}

    def fun_submit(n_workers, n_servers, envs):
        seen.update(envs)

    tracker = rendezvous.submit_job(1, 0, fun_submit,
                                    host_ip="127.0.0.1", join=False)
    try:
        assert seen["DMLC_JAX_COORD_URI"] == "127.0.0.1"
        assert seen["DMLC_JAX_COORD_PORT"] != seen["DMLC_TRACKER_PORT"]
    finally:
        tracker.close()


PS_PROG = '''
import os, socket, sys, time

role = os.environ["DMLC_ROLE"]
uri = os.environ["DMLC_PS_ROOT_URI"]
port = int(os.environ["DMLC_PS_ROOT_PORT"])
n = int(os.environ["DMLC_NUM_WORKER"]) + int(os.environ["DMLC_NUM_SERVER"])
if role == "scheduler":
    s = socket.socket()
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((uri, port))
    s.listen(n)
    for _ in range(n):
        c, _ = s.accept()
        c.sendall(b"k")
        c.close()
else:
    assert role in ("worker", "server"), role
    assert "DMLC_TASK_ID" in os.environ
    for _ in range(200):  # scheduler may not be up yet
        try:
            c = socket.create_connection((uri, port), 2)
            break
        except OSError:
            time.sleep(0.1)
    else:
        sys.exit(3)
    assert c.recv(1) == b"k"
'''


def test_ps_role_end_to_end(tmp_path):
    """--num-servers > 0 job: PSTracker spawns the scheduler (the user
    command with DMLC_ROLE=scheduler, reference tracker.py:336-386) and
    every worker/server gets the DMLC_PS_ROOT_URI/PORT contract and can
    reach the scheduler socket."""
    prog = tmp_path / "ps_prog.py"
    prog.write_text(PS_PROG)
    args = get_opts([
        "--cluster", "local", "--num-workers", "2", "--num-servers", "1",
        "--host-ip", "127.0.0.1", "--", sys.executable, str(prog),
    ])
    # raises (via failures) if any role's env contract or socket fails
    launch.submit_local(args)


def test_ps_scheduler_failure_aborts_fast(tmp_path):
    """A scheduler that dies at startup must abort the job, not leave
    workers hanging on DMLC_PS_ROOT_PORT forever."""
    prog = tmp_path / "ps_bad.py"
    prog.write_text(
        "import os, sys, time\n"
        "if os.environ['DMLC_ROLE'] == 'scheduler':\n"
        "    sys.exit(7)\n"
        "time.sleep(60)\n"  # workers would hang without the abort
    )
    args = get_opts([
        "--cluster", "local", "--num-workers", "1", "--num-servers", "1",
        "--host-ip", "127.0.0.1", "--max-attempts", "1",
        "--", sys.executable, str(prog),
    ])
    with pytest.raises(RuntimeError, match="tracker failed"):
        launch.submit_local(args)


def test_local_retry_then_fail(tmp_path):
    # a command that always fails must exhaust max_attempts then raise
    args = get_opts([
        "--cluster", "local", "--num-workers", "1", "--host-ip", "127.0.0.1",
        "--max-attempts", "2", "--", sys.executable, "-c", "exit(1)",
    ])
    with pytest.raises(Exception):
        launch.submit_local(args)


class _FakeRunner:
    def __init__(self, bad_hosts):
        self.bad_hosts = set(bad_hosts)
        self.calls = []

    def __call__(self, host, role, task_id, env):
        self.calls.append((host, role, task_id, int(env["DMLC_NUM_ATTEMPT"])))
        return 1 if host in self.bad_hosts else 0


def test_gang_scheduler_retries_and_blacklists():
    runner = _FakeRunner(bad_hosts=["bad"])
    sched = launch.GangScheduler(["bad", "good"], runner,
                                 max_attempts=3, blacklist_after=2)
    envs = {"DMLC_TRACKER_URI": "x", "DMLC_TRACKER_PORT": "1"}
    sched.run_all(n_workers=3, n_servers=0, envs=envs, cluster="tpu-vm")
    # every task eventually succeeded on 'good' (exactly one ok per task)
    oks = [c for c in runner.calls if c[0] == "good"]
    assert sorted(tid for _, _, tid, _ in oks) == [0, 1, 2]
    assert "bad" in sched.blacklist


def test_gang_scheduler_exhausts_attempts():
    runner = _FakeRunner(bad_hosts=["h0", "h1"])
    sched = launch.GangScheduler(["h0", "h1"], runner, max_attempts=2,
                                 blacklist_after=99)
    with pytest.raises(RuntimeError):
        sched.run_task("worker", 0, {}, "tpu-vm")
    assert len(runner.calls) == 2
    assert [c[3] for c in runner.calls] == [0, 1]  # DMLC_NUM_ATTEMPT counts up


def test_gang_scheduler_real_process_tree(tmp_path):
    """Beyond stub runners (VERDICT r4): a subprocess-backed runner whose
    task genuinely dies once — the scheduler must count the failure
    against the host, retry, and succeed on the second attempt (the
    YARN-AM container re-request behavior)."""
    marker = tmp_path / "died-once"
    prog = ("import os, sys\n"
            "m = sys.argv[1]\n"
            "if not os.path.exists(m) and os.environ['FAKE_HOST'] == 'h0':\n"
            "    open(m, 'w').close()\n"
            "    os._exit(9)\n"
            "print('task ok on', os.environ['FAKE_HOST'])\n")
    script = tmp_path / "task.py"
    script.write_text(prog)

    hosts_used = []

    def runner(host, role, task_id, env):
        hosts_used.append((host, int(env["DMLC_NUM_ATTEMPT"])))
        penv = os.environ.copy()
        penv.update(env)
        penv["FAKE_HOST"] = host
        return subprocess.call(
            [sys.executable, str(script), str(marker)], env=penv)

    sched = launch.GangScheduler(["h0", "h1"], runner,
                                 max_attempts=3, blacklist_after=1)
    # task 0 pins to live[0] == h0, so the first attempt is guaranteed
    # to land on the host that dies once
    sched.run_task("worker", 0, {"DMLC_TRACKER_URI": "x",
                                 "DMLC_TRACKER_PORT": "1"}, "tpu-vm")
    # first attempt really ran and really died (exit 9, marker written),
    # h0 got blacklisted, the retry landed on h1 and succeeded
    assert marker.exists()
    assert hosts_used[0] == ("h0", 0)
    assert "h0" in sched.blacklist
    assert hosts_used[-1][0] == "h1"


def test_local_submit_worker_killed_midjob_recovers(tmp_path):
    """Kill a REAL worker process mid-job (after rendezvous, no
    shutdown): the launcher's per-task retry restarts it, the tracker
    re-admits it under its old rank via the jobid map, and the survivor
    rides out the dropped link with `recover` — allreduce completes."""
    flag = tmp_path / "kill.flag"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dmlc-submit"),
         "--cluster", "local", "--num-workers", "2", "--max-attempts", "2",
         "--host-ip", "127.0.0.1",
         "--env", f"DMLC_RECOVER_KILL_FLAG={flag}",
         "--", sys.executable,
         os.path.join(REPO, "examples", "recover_worker.py")],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    assert flag.exists(), "the worker was never killed — test proved nothing"
    assert r.stderr.count("recovered allreduce OK") == 2, r.stderr[-2000:]


def test_command_builders():
    args = SimpleNamespace(
        host_file=None, extra_env={"FOO": "1"}, command=["python", "w.py"],
        queue="q", sge_log_dir=None, slurm_worker_nodes=2,
        slurm_server_nodes=None, sync_dst_dir=None, jobname="j1",
        worker_cores=2, server_cores=1, worker_memory_mb=1024,
        server_memory_mb=512,
    )
    envs = {"DMLC_TRACKER_URI": "10.0.0.1", "DMLC_TRACKER_PORT": "9091"}

    mpi = launch.build_mpi_cmd(args, envs, 4, "worker", openmpi=True)
    assert mpi[:3] == ["mpirun", "-n", "4"]
    assert "-x" in mpi and any("DMLC_TRACKER_URI=10.0.0.1" in t for t in mpi)

    slurm = launch.build_slurm_cmd(args, envs, "worker", 4)
    assert slurm[:3] == ["srun", "-n", "4"]
    assert "-N" in slurm and "2" in slurm
    assert any(t.startswith("--export=ALL,") and "DMLC_ROLE=worker" in t
               for t in slurm)

    sge = launch.build_sge_script(args, envs, "worker")
    assert "SGE_TASK_ID - 1" in sge and "python w.py" in sge

    ssh = launch.build_ssh_cmd("host1:2222", ["python", "w.py"],
                               {"DMLC_ROLE": "worker", "SECRET": "no"})
    assert ssh[:2] == ["ssh", "-o"]
    assert "-p" in ssh and "2222" in ssh
    remote = ssh[-1]
    assert "DMLC_ROLE" in remote and "SECRET" not in remote


def test_train_libsvm_end_to_end(tmp_path):
    """SURVEY §7 minimum slice: launcher + partitioned ingest + JAX grads
    + tracker allreduce, 2 workers."""
    rng = np.random.default_rng(0)
    lines = []
    for _ in range(200):
        x = rng.normal(size=4)
        y = int(x @ [1.0, -2.0, 0.5, 1.5] > 0)
        feats = " ".join(f"{j}:{x[j]:.3f}" for j in range(4))
        lines.append(f"{y} {feats}")
    data = tmp_path / "train.libsvm"
    data.write_text("\n".join(lines) + "\n")

    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dmlc-submit"),
         "--cluster", "local", "--num-workers", "2", "--host-ip",
         "127.0.0.1", "--", sys.executable,
         os.path.join(REPO, "examples", "train_libsvm.py"), str(data), "2"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "epoch 1 loss" in r.stderr


def test_train_csv_end_to_end(tmp_path):
    """BASELINE config #3 shape: CSV tabular allreduce SGD, 2 workers."""
    rng = np.random.default_rng(1)
    X = rng.normal(size=(200, 4))
    y = X @ [1.0, -2.0, 0.5, 1.5]
    data = tmp_path / "tab.csv"
    with open(data, "w") as f:
        for yi, xi in zip(y, X):
            f.write(",".join([f"{yi:.4f}"] + [f"{v:.4f}" for v in xi]) + "\n")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bin", "dmlc-submit"),
         "--cluster", "local", "--num-workers", "2", "--host-ip",
         "127.0.0.1", "--", sys.executable,
         os.path.join(REPO, "examples", "train_csv.py"), str(data), "3"],
        capture_output=True, text=True, timeout=300,
    )
    assert r.returncode == 0, r.stderr[-3000:]
    assert "epoch 2 mse" in r.stderr


def test_cache_file_set_rewrites_command(tmp_path, monkeypatch):
    from dmlc_tpu.tracker.opts import get_opts

    script = tmp_path / "sub" / "worker.py"
    script.parent.mkdir()
    script.write_text("print('hi')\n")
    extra = tmp_path / "model.conf"
    extra.write_text("k = v\n")
    monkeypatch.chdir(tmp_path)
    args = get_opts(["--cluster", "ssh", "--num-workers", "1",
                     "--host-file", "/dev/null", "--files", "model.conf",
                     "--", "python", "sub/worker.py", "--epochs", "3"])
    from dmlc_tpu.tracker.opts import cache_file_set

    fset, cmds = cache_file_set(args)
    assert fset == {"sub/worker.py", "model.conf"}
    assert cmds == ["python", "./worker.py", "--epochs", "3"]

    args.auto_file_cache = False
    fset, cmds = cache_file_set(args)
    assert fset == {"model.conf"}
    assert cmds == ["python", "sub/worker.py", "--epochs", "3"]


def test_ssh_file_cache_end_to_end(tmp_path, monkeypatch):
    """ssh-mode localhost job: a script submitted by RELATIVE path is
    shipped to the job cache dir and runs there via the bootstrap (the
    transport is faked — no sshd in this container — but the staging,
    env contract, bootstrap exec, and rendezvous are all real)."""
    import shutil

    from dmlc_tpu.tracker.opts import get_opts

    workdir = tmp_path / "submitdir"
    workdir.mkdir()
    out_file = tmp_path / "ran.txt"
    (workdir / "worker.py").write_text(
        "import os, sys\n"
        "sys.path.insert(0, os.environ['DMLC_TPU_REPO'])\n"
        "from dmlc_tpu.tracker.client import TrackerClient\n"
        "c = TrackerClient().start()\n"
        f"open({str(out_file)!r}, 'a').write(os.getcwd() + '\\n')\n"
        "c.shutdown()\n"
    )
    hosts = tmp_path / "hosts.txt"
    hosts.write_text("127.0.0.1\n")
    jobname = f"t{os.getpid()}"
    cache_dir = f"/tmp/dmlc-cache-{jobname}"

    def fake_copy(host, paths, dest):
        assert host == "127.0.0.1"
        os.makedirs(dest, exist_ok=True)
        for p in paths:
            shutil.copy(p, dest)

    def fake_ssh(cmd):
        assert cmd[0] == "ssh"
        return subprocess.call(["bash", "-c", cmd[-1]])

    monkeypatch.setattr(launch, "_copy_to_host", fake_copy)
    monkeypatch.setattr(launch, "_ssh_call", fake_ssh)
    monkeypatch.setenv("DMLC_TPU_REPO", REPO)
    monkeypatch.chdir(workdir)

    args = get_opts(["--cluster", "ssh", "--num-workers", "2",
                     "--host-ip", "127.0.0.1",
                     "--host-file", str(hosts),
                     "--jobname", jobname,
                     "--env", f"DMLC_TPU_REPO={REPO}",
                     "--", "python3", "worker.py"])
    try:
        tracker = launch.submit_ssh(args)
        assert tracker is not None and not tracker.alive()
        ran_from = out_file.read_text().strip().splitlines()
        assert len(ran_from) == 2
        assert all(os.path.realpath(d) == os.path.realpath(cache_dir)
                   for d in ran_from), ran_from
        assert os.path.exists(os.path.join(cache_dir, "worker.py"))
        assert os.path.exists(os.path.join(cache_dir, "bootstrap.py"))
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)


def test_bootstrap_unpacks_archives_and_sets_paths(tmp_path):
    import zipfile

    cache = tmp_path / "cache"
    cache.mkdir()
    with zipfile.ZipFile(cache / "lib.zip", "w") as z:
        z.writestr("shipped_lib/mod.py", "VALUE = 7\n")
    probe = cache / "probe.py"
    probe.write_text(
        "import os, sys\n"
        "sys.path.insert(0, '.')\n"
        "from shipped_lib.mod import VALUE\n"
        "assert VALUE == 7\n"
        "assert os.getcwd() == os.environ['DMLC_JOB_CACHE_DIR']\n"
        "assert os.environ['LD_LIBRARY_PATH'].endswith(os.getcwd())\n"
        "print('bootstrap-ok')\n"
    )
    env = os.environ.copy()
    env.update({
        "DMLC_JOB_CLUSTER": "ssh",
        "DMLC_JOB_CACHE_DIR": str(cache),
        "DMLC_JOB_ARCHIVES": "lib.zip",
    })
    r = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "dmlc_tpu", "tracker", "bootstrap.py"),
         "--", sys.executable, "probe.py"],
        capture_output=True, text=True, env=env, timeout=60)
    assert r.returncode == 0, r.stderr
    assert "bootstrap-ok" in r.stdout


def test_bootstrap_rejects_traversal_archive(tmp_path):
    """A shipped tarball must not escape the cache dir — even on
    pythons whose tarfile lacks extractall(filter=...)."""
    import io
    import tarfile

    from dmlc_tpu.tracker import bootstrap

    cache = tmp_path / "cache"
    cache.mkdir()
    with tarfile.open(cache / "evil.tar", "w") as t:
        info = tarfile.TarInfo("../escape.txt")
        data = b"pwned"
        info.size = len(data)
        t.addfile(info, io.BytesIO(data))
    try:
        bootstrap.unpack_archives(["evil.tar"], str(cache))
    except Exception:
        pass  # filter="data" raises; the manual screen raises ValueError
    assert not (tmp_path / "escape.txt").exists()


def test_submit_dispatch_routes_all_clusters():
    from dmlc_tpu.tracker.submit import DISPATCH

    for c in ["local", "ssh", "mpi", "sge", "slurm", "tpu-vm", "yarn",
              "mesos"]:
        assert c in DISPATCH


FAKE_MESOS_EXECUTE = '''#!/usr/bin/env python3
"""Fake mesos-execute: runs --command locally with --env applied, the
way a mesos agent would, so the whole tracker rendezvous is exercised."""
import json
import os
import subprocess
import sys

opts = dict(a.split("=", 1) for a in sys.argv[1:] if a.startswith("--"))
assert "--master" in opts and ":" in opts["--master"], opts
assert "cpus:" in opts["--resources"] and "mem:" in opts["--resources"]
env = os.environ.copy()
env.update(json.loads(opts["--env"]))
sys.exit(subprocess.call(opts["--command"], shell=True, env=env))
'''


def test_mesos_submit_end_to_end(tmp_path):
    """mesos backend against a fake mesos-execute on PATH: per-task
    launch with env JSON + resources, full rendezvous to completion
    (reference role: tracker/dmlc_tracker/mesos.py:30-91)."""
    fake = tmp_path / "mesos-execute"
    fake.write_text(FAKE_MESOS_EXECUTE)
    fake.chmod(0o755)
    old_path = os.environ["PATH"]
    os.environ["PATH"] = f"{tmp_path}:{old_path}"
    try:
        args = get_opts([
            "--cluster", "mesos", "--num-workers", "2", "--host-ip",
            "127.0.0.1", "--mesos-master", "127.0.0.1",
            "--", sys.executable,
            os.path.join(REPO, "examples", "allreduce_worker.py"),
        ])
        tracker = launch.submit_mesos(args)
        assert tracker is not None and not tracker.alive()
        tracker.close()
    finally:
        os.environ["PATH"] = old_path


def test_mesos_requires_binary(tmp_path):
    args = get_opts(["--cluster", "mesos", "--num-workers", "1",
                     "--mesos-master", "m", "--", "true"])
    old_path = os.environ["PATH"]
    os.environ["PATH"] = str(tmp_path)  # empty dir: no mesos-execute
    try:
        with pytest.raises(RuntimeError, match="mesos-execute"):
            launch.submit_mesos(args)
    finally:
        os.environ["PATH"] = old_path
