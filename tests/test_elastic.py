"""Elastic world resize: the tracker's resize generations, the client's
WorldResized/resize() path, stale-generation frame rejection, and the
scale-up join flows (ISSUE 7 tentpole)."""

import json
import threading
import time
import urllib.request

import numpy as np
import pytest

from dmlc_tpu import telemetry
from dmlc_tpu.tracker import RabitTracker, TrackerClient, WorldResized

MISS = 0.5    # failure-detector miss window
GRACE = 0.5   # elastic eviction grace past the death declaration


def _elastic_tracker(n, metrics_port=None):
    t = RabitTracker("127.0.0.1", n, metrics_port=metrics_port,
                     miss_window_s=MISS, elastic=True,
                     elastic_grace_s=GRACE)
    t.start(n)
    return t


def _client(tracker, jobid):
    return TrackerClient("127.0.0.1", tracker.port, jobid=jobid)


class _Worker(threading.Thread):
    """One in-thread elastic worker: rendezvous + manual heartbeats on a
    side thread (so the tracker's failure detector sees it alive)."""

    def __init__(self, tracker, jobid, fn):
        super().__init__(daemon=True)
        self.tracker = tracker
        self.jobid = jobid
        self.fn = fn
        self.result = None
        self.error = None
        self._hb_stop = threading.Event()
        self._hb = None

    def _beat_loop(self, client):
        while not self._hb_stop.wait(0.1):
            try:
                client.send_metrics('{"counters": {}}')
            except OSError:
                return

    def run(self):
        try:
            c = _client(self.tracker, self.jobid).start()
            self._hb = threading.Thread(target=self._beat_loop, args=(c,),
                                        daemon=True)
            self._hb.start()
            self.result = self.fn(c)
        except BaseException as e:  # noqa: BLE001 - surfaced by the test
            self.error = e
        finally:
            self._hb_stop.set()


def test_gen_query_and_defaults():
    """Every rendezvous learns the generation; non-elastic trackers
    report elastic=False and collectives keep OSError semantics."""
    tracker = RabitTracker("127.0.0.1", 1)
    tracker.start(1)
    c = _client(tracker, "solo").start()
    assert c.gen == 0 and c.elastic is False
    c.shutdown()
    tracker.join(timeout=15)
    tracker.close()

    tracker = _elastic_tracker(1)
    c = _client(tracker, "solo").start()
    assert c.gen == 0 and c.elastic is True
    c.shutdown()
    tracker.join(timeout=15)
    tracker.close()


def test_shrink_on_death_renumbers_survivors():
    """Kill one of three ranks (no shutdown, heartbeats stop): the
    tracker declares it dead, the grace window evicts it, survivors'
    collectives raise WorldResized, resize() renumbers them into a
    dense [0, 2) world, and a post-resize allreduce sums correctly —
    with no survivor process/thread restart."""
    telemetry.reset()
    tracker = _elastic_tracker(3)
    dead_rank = {}
    barrier = threading.Barrier(3)

    def fn(c):
        first = float(c.allreduce_sum(
            np.asarray([c.rank + 1.0], np.float64))[0])
        assert first == 6.0
        barrier.wait(timeout=20)
        if c.rank == 2:
            # preempted: vanish without a shutdown handshake
            dead_rank[c.jobid] = c.rank
            c._links_down()
            return ("died", c.rank)
        old_rank, old_gen = c.rank, c.gen
        # keep folding until the world changes under us; the dead
        # peer's closed links (or our own cascade) surface in-bound
        for _ in range(200):
            try:
                c.allreduce_sum(np.ones(4, np.float64))
                time.sleep(0.05)
            except WorldResized:
                break
        else:
            raise AssertionError("never saw WorldResized after the kill")
        c.resize()
        assert c.gen > old_gen
        assert c.world_size == 2
        post = float(c.allreduce_sum(
            np.asarray([c.rank + 1.0], np.float64))[0])
        assert post == 3.0  # dense [0,2) renumbering
        out = ("survived", old_rank, c.rank, c.gen)
        c.shutdown()
        return out

    workers = [_Worker(tracker, f"el{i}", fn) for i in range(3)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(60)
    errors = [w.error for w in workers if w.error is not None]
    assert not errors, errors
    tracker.join(timeout=30)
    tracker.close()
    survived = sorted(w.result for w in workers
                      if w.result and w.result[0] == "survived")
    died = [w.result for w in workers if w.result and w.result[0] == "died"]
    assert len(survived) == 2 and len(died) == 1
    new_ranks = sorted(r[2] for r in survived)
    assert new_ranks == [0, 1]
    assert tracker.gen >= 1
    counters = telemetry.snapshot()["counters"]
    assert counters["elastic"]["resizes_total"] >= 1
    assert counters["elastic"]["shrinks_total"] >= 1


def test_grow_via_request_resize_and_join():
    """Operator scale-up: request_resize(world=3) + a fresh joiner.
    The survivors learn the new generation from the heartbeat reply
    (resize_pending), resize into the grown world, and a 3-way
    allreduce completes."""
    telemetry.reset()
    tracker = _elastic_tracker(2)
    grown = threading.Event()

    def fn(c):
        assert float(c.allreduce_sum(
            np.asarray([1.0], np.float64))[0]) == 2.0
        grown.wait(timeout=20)
        # heartbeat piggyback flips resize_pending; the next collective
        # raises instead of folding a stale 2-rank world
        for _ in range(200):
            try:
                c.check_resized()
                c.send_metrics('{"counters": {}}')
                time.sleep(0.05)
            except WorldResized:
                break
        else:
            raise AssertionError("grow never reached the survivor")
        c.resize()
        assert c.world_size == 3
        out = float(c.allreduce_sum(
            np.asarray([c.rank + 1.0], np.float64))[0])
        assert out == 6.0
        c.shutdown()
        return ("ok", c.rank)

    workers = [_Worker(tracker, f"gw{i}", fn) for i in range(2)]
    for w in workers:
        w.start()
    time.sleep(0.5)  # let the initial world form
    tracker.request_resize(world=3, reason="test_grow")
    grown.set()

    def joiner(c):
        assert c.world_size == 3
        out = float(c.allreduce_sum(
            np.asarray([c.rank + 1.0], np.float64))[0])
        assert out == 6.0
        c.shutdown()
        return ("ok", c.rank)

    j = _Worker(tracker, "gw2", joiner)
    j.start()
    for w in workers + [j]:
        w.join(60)
    errors = [w.error for w in workers + [j] if w.error is not None]
    assert not errors, errors
    ranks = sorted(w.result[1] for w in workers + [j])
    assert ranks == [0, 1, 2]
    tracker.join(timeout=30)
    tracker.close()
    counters = telemetry.snapshot()["counters"]
    assert counters["elastic"]["grows_total"] >= 1


def test_bare_join_grows_world_by_one():
    """A join announce against a full elastic world is an implicit
    scale-up generation of +1 (the gang-rescheduled-slice path)."""
    tracker = _elastic_tracker(1)
    c0 = _client(tracker, "bj0").start()
    assert c0.world_size == 1
    hb_stop = threading.Event()

    def beat():
        while not hb_stop.wait(0.1):
            try:
                c0.send_metrics('{"counters": {}}')
            except OSError:
                return

    hb = threading.Thread(target=beat, daemon=True)
    hb.start()
    done = {}

    def join_late():
        c1 = _client(tracker, "bj1").start(world_size=-1)
        done["rank"] = c1.rank
        done["world"] = c1.world_size
        out = c1.allreduce_sum(np.asarray([c1.rank + 1.0], np.float64))
        done["sum"] = float(out[0])
        c1.shutdown()

    t = threading.Thread(target=join_late, daemon=True)
    t.start()
    # c0 discovers the grow via its heartbeat piggyback
    deadline = time.monotonic() + 20
    while not c0.resize_pending:
        assert time.monotonic() < deadline, "grow never announced"
        time.sleep(0.05)
    with pytest.raises(WorldResized):
        c0.check_resized()
    c0.resize()
    assert c0.world_size == 2
    out = float(c0.allreduce_sum(
        np.asarray([c0.rank + 1.0], np.float64))[0])
    assert out == 3.0
    c0.shutdown()
    t.join(30)
    hb_stop.set()
    assert done == {"rank": 1, "world": 2, "sum": 3.0}
    tracker.join(timeout=30)
    tracker.close()


def test_stale_generation_frame_rejected():
    """A frame stamped with another generation must raise WorldResized
    on the receiver instead of being folded into the reduction."""
    tracker = _elastic_tracker(2)
    results = {}
    ready = threading.Barrier(2)

    def fn_sender(c):
        ready.wait(timeout=20)
        peer = next(iter(c.links))
        c.gen += 7  # forge a stale/future generation
        try:
            c._send_array(c.links[peer], np.ones(2, np.float64))
        except OSError:
            pass  # receiver tore the link down mid-send: the cascade
        return "sent"

    def fn_receiver(c):
        ready.wait(timeout=20)
        peer = next(iter(c.links))
        with pytest.raises(WorldResized, match="stale-generation"):
            c._recv_array(c.links[peer], np.ones(2, np.float64))
        results["links_after"] = len(c.links)
        return "rejected"

    w0 = _Worker(tracker, "sg0", lambda c: (fn_sender if c.rank == 0
                                            else fn_receiver)(c))
    w1 = _Worker(tracker, "sg1", lambda c: (fn_sender if c.rank == 0
                                            else fn_receiver)(c))
    w0.start()
    w1.start()
    w0.join(30)
    w1.join(30)
    assert not w0.error and not w1.error, (w0.error, w1.error)
    # the receiver tore down its links as part of the resize cascade
    assert results["links_after"] == 0
    tracker.close()


def test_http_resize_endpoint():
    """POST /resize on the metrics server records a grow request; a
    non-elastic tracker answers 409."""
    tracker = _elastic_tracker(1, metrics_port=0)
    c = _client(tracker, "hr0").start()
    body = json.dumps({"world": 2}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{tracker.metrics_port}/resize", data=body,
        headers={"Content-Type": "application/json"})
    doc = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert doc["requested"] is True and doc["world_target"] == 2

    def join_late():
        c1 = _client(tracker, "hr1").start(world_size=-1)
        c1.shutdown()

    t = threading.Thread(target=join_late, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while not c.resize_pending:
        assert time.monotonic() < deadline, "resize never applied"
        try:
            c.send_metrics('{"counters": {}}')
        except OSError:
            pass
        time.sleep(0.05)
    c.resize()
    assert c.world_size == 2
    c.shutdown()
    t.join(30)
    tracker.join(timeout=30)
    tracker.close()

    plain = RabitTracker("127.0.0.1", 1, metrics_port=0)
    plain.start(1)
    req = urllib.request.Request(
        f"http://127.0.0.1:{plain.metrics_port}/resize", data=b"{}",
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 409
    plain.close()


def test_http_resize_handler_validates_world_and_remove():
    """_http_resize contract (handler-level): bad worlds and bad
    remove lists are ValueErrors (the HTTP edge's 400), good requests
    echo the merged plan."""
    tracker = _elastic_tracker(1)
    try:
        for bad_world in (0, -3, 70000, "two", 2.5, True, False):
            with pytest.raises(ValueError):
                tracker._http_resize({"world": bad_world})
        for bad_remove in ("1", {"rank": 1}, [1, "2"], [True],
                          [1.5], [-1], [70000]):
            with pytest.raises(ValueError):
                tracker._http_resize({"remove": bad_remove})
        doc = tracker._http_resize({"world": 3, "remove": [2, 2, 1],
                                    "reason": "contract-test"})
        assert doc["requested"] is True
        assert doc["world_target"] == 3
        assert doc["remove"] == [1, 2]          # deduped, sorted
        assert isinstance(doc["gen"], int)
        assert doc["current_world"] == 1
        # remove-only request (the autoscaler's preemption shape)
        doc = tracker._http_resize({"remove": [0]})
        assert doc["requested"] is True and doc["world_target"] is None
    finally:
        tracker.close()

    plain = RabitTracker("127.0.0.1", 1)
    plain.start(1)
    try:
        with pytest.raises(RuntimeError):
            plain._http_resize({"world": 2})
    finally:
        plain.close()


def test_http_resize_bad_requests_are_400s():
    tracker = _elastic_tracker(1, metrics_port=0)
    url = f"http://127.0.0.1:{tracker.metrics_port}/resize"
    try:
        for bad in ({"world": 0}, {"world": -1}, {"world": "two"},
                    {"world": 123456}, {"remove": "1"},
                    {"remove": [True]}, {"remove": [-1]}):
            req = urllib.request.Request(
                url, data=json.dumps(bad).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 400, bad
    finally:
        tracker.close()


def test_http_resize_retargets_unformed_world():
    """A resize posted BEFORE any worker announces re-targets the
    initial world size: the tracker was started expecting 2 but a
    single worker forms a world of 1."""
    tracker = _elastic_tracker(2, metrics_port=0)
    body = json.dumps({"world": 1, "reason": "pre-start"}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{tracker.metrics_port}/resize", data=body,
        headers={"Content-Type": "application/json"})
    doc = json.loads(urllib.request.urlopen(req, timeout=10).read())
    assert doc["requested"] is True and doc["current_world"] == 2
    c = _client(tracker, "rt0").start()
    assert c.world_size == 1 and c.rank == 0
    assert float(c.allreduce_sum(np.asarray([2.0], np.float64))[0]) == 2.0
    c.shutdown()
    tracker.join(timeout=30)
    tracker.close()


def test_late_replacement_joins_as_scale_up():
    """A rank evicted past grace whose process finally comes back
    (recover@old-gen) is re-admitted as a scale-up join with a fresh
    rank — the gang-rescheduled slice, not a world restart."""
    tracker = _elastic_tracker(2)

    def fn(c):
        if c.rank == 1:
            c._links_down()
            return ("died", c.rank, c.gen)
        for _ in range(200):
            try:
                c.allreduce_sum(np.ones(2, np.float64))
                time.sleep(0.05)
            except WorldResized:
                break
        c.resize()
        assert c.world_size == 1 and c.rank == 0
        return ("survived", c.rank, c.gen, c)

    workers = [_Worker(tracker, f"lr{i}", fn) for i in range(2)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(60)
    assert not any(w.error for w in workers), [w.error for w in workers]
    survivor = next(w.result for w in workers
                    if w.result[0] == "survived")
    c0 = survivor[3]
    hb_stop = threading.Event()

    def beat():
        while not hb_stop.wait(0.1):
            try:
                c0.send_metrics('{"counters": {}}')
            except OSError:
                return

    threading.Thread(target=beat, daemon=True).start()
    # the dead rank's process reappears long after eviction, announcing
    # its stale generation-0 identity
    late = _client(tracker, "lr-late")
    late.rank = 1   # its old rank in gen 0
    done = {}

    def come_back():
        late.gen = 0
        late.resize(timeout_s=30)
        done["rank"] = late.rank
        done["world"] = late.world_size
        late.shutdown()

    t = threading.Thread(target=come_back, daemon=True)
    t.start()
    deadline = time.monotonic() + 20
    while not c0.resize_pending:
        assert time.monotonic() < deadline, "late join never grew world"
        time.sleep(0.05)
    c0.resize()
    assert c0.world_size == 2
    c0.shutdown()
    t.join(30)
    hb_stop.set()
    assert done["world"] == 2 and done["rank"] == 1
    tracker.join(timeout=30)
    tracker.close()


def test_launcher_budget_exhaustion_not_fatal_in_elastic(monkeypatch):
    """A permanently-lost task (restart budget exhausted) fails the job
    in a fixed-size world but NOT in an elastic one — the world resized
    past it and the survivors carry the job."""
    from dmlc_tpu.tracker.launch import GangScheduler

    calls = []

    def runner(host, role, task_id, env):
        calls.append(host)
        return 137  # every attempt dies (preempted capacity gone)

    monkeypatch.delenv("DMLC_ELASTIC", raising=False)
    sched = GangScheduler(["h0", "h1"], runner, max_attempts=2)
    with pytest.raises(RuntimeError, match="failed after 2 attempts"):
        sched.run_task("worker", 1, {}, "tpu-vm")

    monkeypatch.setenv("DMLC_ELASTIC", "1")
    sched2 = GangScheduler(["h0", "h1"], runner, max_attempts=2)
    sched2.run_task("worker", 1, {}, "tpu-vm")  # must NOT raise
    counters = telemetry.snapshot()["counters"]
    assert counters["elastic"]["gang_reschedules"] >= 1


def test_stale_generation_shutdown_translated():
    """A survivor that finishes WITHOUT re-brokering into the newest
    generation shuts down with a stale rank: the gen-stamped shutdown
    is translated into the right completion slot (and an evicted
    worker's shutdown is ignored) — the job completes instead of the
    tracker dying or a live worker's slot being marked finished."""
    tracker = _elastic_tracker(3)

    def fn(c):
        if c.rank == 0:
            # preempted: rank 0's death forces a renumbering of 1,2
            c._links_down()
            return ("died",)
        old = c.rank
        for _ in range(200):
            try:
                c.allreduce_sum(np.ones(2, np.float64))
                time.sleep(0.05)
            except WorldResized:
                break
        c.resize()
        assert c.world_size == 2
        if old == 1:
            # this survivor finishes and shuts down under its NEW rank
            c.shutdown()
            return ("new-gen-shutdown", old, c.rank)
        # this survivor pretends it never learned of the resize: it
        # announces its OLD rank with the OLD generation stamp
        c.rank, c.gen = old, 0
        c.shutdown()
        return ("stale-shutdown", old)

    workers = [_Worker(tracker, f"ss{i}", fn) for i in range(3)]
    for w in workers:
        w.start()
    for w in workers:
        w.join(60)
    assert not any(w.error for w in workers), [w.error for w in workers]
    # the stale gen-0 rank 2 translated to gen-1 rank 1: quorum filled,
    # the accept loop exits cleanly
    tracker.join(timeout=30)
    tracker.close()
