"""S3 backend against a local in-process emulator.

Same hermetic strategy as tests/test_hdfs_azure.py: a stdlib HTTP
server implements the protocol slice the backend speaks — SigV4
signature verification by countersigning with the client's own
x-amz-date, ListObjectsV2 XML, and the multipart upload lifecycle —
and the SAME Stream/InputSplit code paths run over s3:// URIs.
"""

import os
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from dmlc_tpu.base import DMLCError
from dmlc_tpu.io import input_split
from dmlc_tpu.io.filesys import FileSystem
from dmlc_tpu.io.stream import Stream
from dmlc_tpu.io.uri import URI


def _drop_cached_instances():
    for key in [k for k in FileSystem._instances if k.startswith("s3://")]:
        del FileSystem._instances[key]


class _FakeS3(BaseHTTPRequestHandler):
    store = {}      # (bucket, key) -> bytes
    uploads = {}    # upload_id -> {"target": (bucket, key), parts: {n: bytes}}
    aborted = []    # upload ids that got AbortMultipartUpload
    next_upload = [0]
    require_auth = True
    fail_next_part = [False]  # one-shot: 500 the next UploadPart
    fail_next_init = [False]  # one-shot: 500 the next ?uploads= POST

    def log_message(self, *a):
        pass

    def _reply(self, code, body=b"", headers=()):
        self.send_response(code)
        for k, v in headers:
            self.send_header(k, v)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _verify_auth(self, body=b""):
        """Countersign with the client's own x-amz-date + signed header
        set; reject a missing or mismatched SigV4 signature."""
        import hashlib

        from dmlc_tpu.io.s3_filesys import sign_request

        if not self.require_auth:
            return True
        got = self.headers.get("Authorization")
        if got is None:
            self.send_error(403, "missing signature")
            return False
        signed = got.split("SignedHeaders=")[1].split(",")[0].split(";")
        hdrs = {k: v for k, v in self.headers.items()
                if k.lower() in signed and k.lower() != "host"}
        url = f"http://{self.headers.get('Host')}{self.path}"
        want = sign_request(
            self.command, url, hdrs,
            payload_hash=hashlib.sha256(body).hexdigest(),
        ).get("Authorization")
        if got != want:
            self.send_error(403, "signature mismatch")
            return False
        return True

    def _key(self):
        u = urllib.parse.urlparse(self.path)
        parts = u.path.lstrip("/").split("/", 1)
        bucket = parts[0]
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        q = {k: v[0] for k, v in
             urllib.parse.parse_qs(u.query, keep_blank_values=True).items()}
        return bucket, key, q

    def do_HEAD(self):
        if not self._verify_auth():
            return
        bucket, key, _ = self._key()
        data = self.store.get((bucket, key))
        if data is None:
            self._reply(404)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()

    def do_GET(self):
        if not self._verify_auth():
            return
        bucket, key, q = self._key()
        if q.get("list-type") == "2":
            prefix = q.get("prefix", "")
            delim = q.get("delimiter")
            objs, prefixes = [], set()
            for (b, k), data in sorted(self.store.items()):
                if b != bucket or not k.startswith(prefix):
                    continue
                rest = k[len(prefix):]
                if delim and delim in rest:
                    prefixes.add(prefix + rest.split(delim)[0] + delim)
                else:
                    objs.append(f"<Contents><Key>{k}</Key>"
                                f"<Size>{len(data)}</Size></Contents>")
            pres = "".join(f"<CommonPrefixes><Prefix>{p}</Prefix>"
                           f"</CommonPrefixes>" for p in sorted(prefixes))
            xml = ("<?xml version='1.0'?><ListBucketResult>"
                   + "".join(objs) + pres + "</ListBucketResult>")
            self._reply(200, xml.encode())
            return
        data = self.store.get((bucket, key))
        if data is None:
            self._reply(404)
            return
        rng = self.headers.get("Range")
        if rng:
            lo, hi = rng.split("=")[1].split("-")
            self._reply(206, data[int(lo): int(hi) + 1])
        else:
            self._reply(200, data)

    def do_POST(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if not self._verify_auth(body):
            return
        bucket, key, q = self._key()
        if "uploads" in q:
            if self.fail_next_init[0]:
                self.fail_next_init[0] = False
                self._reply(500)
                return
            self.next_upload[0] += 1
            uid = f"up-{self.next_upload[0]}"
            self.uploads[uid] = {"target": (bucket, key), "parts": {}}
            xml = (f"<?xml version='1.0'?><InitiateMultipartUploadResult>"
                   f"<UploadId>{uid}</UploadId>"
                   f"</InitiateMultipartUploadResult>")
            self._reply(200, xml.encode())
            return
        if "uploadId" in q:
            import xml.etree.ElementTree as ET

            up = self.uploads.pop(q["uploadId"], None)
            if up is None:
                self._reply(404)
                return
            root = ET.fromstring(body)
            nums = [int(p.findtext("PartNumber")) for p in root]
            assert nums == sorted(nums)
            data = b"".join(up["parts"][i] for i in nums)
            self.store[up["target"]] = data
            self._reply(200, b"<CompleteMultipartUploadResult/>")
            return
        self._reply(400)

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if not self._verify_auth(body):
            return
        bucket, key, q = self._key()
        if "partNumber" in q:
            if self.fail_next_part[0]:
                self.fail_next_part[0] = False
                self._reply(500)
                return
            up = self.uploads.get(q["uploadId"])
            if up is None:
                self._reply(404)
                return
            num = int(q["partNumber"])
            up["parts"][num] = body
            self._reply(200, headers=[("ETag", f'"etag-{num}"')])
            return
        self.store[(bucket, key)] = body
        self._reply(200)

    def do_DELETE(self):
        if not self._verify_auth():
            return
        _bucket, _key, q = self._key()
        if "uploadId" in q:
            if self.uploads.pop(q["uploadId"], None) is not None:
                self.aborted.append(q["uploadId"])
                self._reply(204)
            else:
                self._reply(404)
            return
        self._reply(400)


@pytest.fixture(scope="module")
def s3_server():
    _FakeS3.store.clear()
    _FakeS3.uploads.clear()
    del _FakeS3.aborted[:]
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _FakeS3)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    keys = ("DMLC_S3_ENDPOINT", "AWS_ACCESS_KEY_ID",
            "AWS_SECRET_ACCESS_KEY", "AWS_SESSION_TOKEN", "AWS_REGION")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ["DMLC_S3_ENDPOINT"] = f"127.0.0.1:{srv.server_port}"
    os.environ["AWS_ACCESS_KEY_ID"] = "AKIATEST"
    os.environ["AWS_SECRET_ACCESS_KEY"] = "test-secret-key"
    os.environ["AWS_REGION"] = "us-test-1"
    os.environ.pop("AWS_SESSION_TOKEN", None)
    _drop_cached_instances()
    yield srv
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v
    _drop_cached_instances()
    srv.shutdown()


def test_s3_write_read_roundtrip(s3_server):
    import numpy as np

    payload = bytes(np.random.default_rng(4).integers(
        0, 256, 180_000, dtype=np.uint8))
    with Stream.create("s3://bkt/dir/obj.bin", "w") as s:
        s.write(payload[:90_000])
        s.write(payload[90_000:])
    strm = Stream.create_for_read("s3://bkt/dir/obj.bin")
    assert strm.read(len(payload) + 1) == payload
    strm.seek(123_000)
    assert strm.read(64) == payload[123_000:123_064]


def test_s3_multipart_upload(s3_server):
    """Above one part the writer switches to multipart: the object is
    invisible until CompleteMultipartUpload and the bytes are exact."""
    import numpy as np

    payload = bytes(np.random.default_rng(5).integers(
        0, 256, 2_750_000, dtype=np.uint8))
    os.environ["DMLC_S3_WRITE_BUFFER_MB"] = "1"
    # the 5 MiB AWS floor would swallow a 1 MB test part; drop it via
    # the module's own clamp by patching the env knob only
    from dmlc_tpu.io import s3_filesys

    orig = s3_filesys.S3WriteStream.__init__

    def patched(self, url):
        orig(self, url)
        self._part = 1 << 20

    s3_filesys.S3WriteStream.__init__ = patched
    try:
        s = Stream.create("s3://bkt/big/model.bin", "w")
        for lo in range(0, len(payload), 600_000):
            s.write(payload[lo: lo + 600_000])
        fs = FileSystem.get_instance(URI("s3://bkt/big"))
        with pytest.raises(FileNotFoundError):
            fs.get_path_info(URI("s3://bkt/big/model.bin"))
        s.close()
    finally:
        s3_filesys.S3WriteStream.__init__ = orig
        os.environ.pop("DMLC_S3_WRITE_BUFFER_MB")
    strm = Stream.create_for_read("s3://bkt/big/model.bin")
    assert strm.read(len(payload) + 1) == payload
    assert not _FakeS3.uploads  # commit consumed the upload session


def test_s3_failed_upload_is_aborted(s3_server):
    from dmlc_tpu.io import s3_filesys

    orig = s3_filesys.S3WriteStream.__init__

    def patched(self, url):
        orig(self, url)
        self._part = 1 << 20

    s3_filesys.S3WriteStream.__init__ = patched
    os.environ["DMLC_S3_RETRIES"] = "1"  # make the injected 500 fatal
    try:
        s = Stream.create("s3://bkt/fail/x.bin", "w")
        s.write(b"a" * (1 << 20))  # part 1 lands, multipart started
        _FakeS3.fail_next_part[0] = True
        with pytest.raises(DMLCError):
            s.write(b"b" * (1 << 20))
        # the stream is poisoned: the with-block exit's close() must not
        # publish an object missing the lost part, and must not raise a
        # second error that would mask the original one
        s.close()
    finally:
        s3_filesys.S3WriteStream.__init__ = orig
        os.environ.pop("DMLC_S3_RETRIES")
    assert _FakeS3.aborted, "failed multipart upload was not aborted"
    assert not _FakeS3.uploads
    fs = FileSystem.get_instance(URI("s3://bkt/fail"))
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("s3://bkt/fail/x.bin"))


def test_s3_failed_init_poisons_stream(s3_server):
    """A failed InitiateMultipartUpload must poison the stream too:
    close() must NOT fall back to the single-shot PUT branch and publish
    the partial buffer as a complete object."""
    from dmlc_tpu.io import s3_filesys

    orig = s3_filesys.S3WriteStream.__init__

    def patched(self, url):
        orig(self, url)
        self._part = 1 << 20

    s3_filesys.S3WriteStream.__init__ = patched
    os.environ["DMLC_S3_RETRIES"] = "1"
    _FakeS3.fail_next_init[0] = True
    try:
        s = Stream.create("s3://bkt/noinit/x.bin", "w")
        with pytest.raises(DMLCError):
            s.write(b"c" * (1 << 20))
        s.close()  # must not single-shot-PUT the partial buffer
    finally:
        s3_filesys.S3WriteStream.__init__ = orig
        os.environ.pop("DMLC_S3_RETRIES")
        _FakeS3.fail_next_init[0] = False
    fs = FileSystem.get_instance(URI("s3://bkt/noinit"))
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("s3://bkt/noinit/x.bin"))


def test_s3_signature_rejected_without_key(s3_server):
    # client and emulator share this process's env, so a WRONG key would
    # countersign identically; dropping the key makes the client go
    # anonymous and the server reject the missing signature
    with Stream.create("s3://bkt/sec/y.bin", "w") as s:
        s.write(b"payload")
    key = os.environ.pop("AWS_SECRET_ACCESS_KEY")
    try:
        with pytest.raises(DMLCError, match="403"):
            Stream.create_for_read("s3://bkt/sec/y.bin").read(7)
    finally:
        os.environ["AWS_SECRET_ACCESS_KEY"] = key


def test_s3_stat_and_list(s3_server):
    for name, data in [("d/a.bin", b"xx"), ("d/b.bin", b"yyy"),
                       ("d/sub/c.bin", b"z")]:
        with Stream.create(f"s3://bkt/{name}", "w") as s:
            s.write(data)
    fs = FileSystem.get_instance(URI("s3://bkt/d"))
    entries = fs.list_directory(URI("s3://bkt/d"))
    names = {e.path.name: (e.type, e.size) for e in entries}
    assert names.get("/d/a.bin") == ("file", 2)
    assert names.get("/d/b.bin") == ("file", 3)
    assert names.get("/d/sub") == ("directory", 0)
    rec = fs.list_directory_recursive(URI("s3://bkt/d"))
    assert sum(e.size for e in rec) == 6
    assert fs.get_path_info(URI("s3://bkt/d/a.bin")).size == 2
    assert fs.get_path_info(URI("s3://bkt/d")).type == "directory"
    with pytest.raises(FileNotFoundError):
        fs.get_path_info(URI("s3://bkt/nope"))


def test_inputsplit_over_s3(s3_server):
    """The round-trip that makes existing DMLC data URIs work unchanged:
    s3:// straight into InputSplit sharding."""
    lines = [f"s3-{i}" for i in range(140)]
    with Stream.create("s3://bkt/ds/t.txt", "w") as s:
        s.write(("\n".join(lines) + "\n").encode())
    got = []
    for part in range(3):
        sp = input_split.create("s3://bkt/ds/t.txt", part, 3, "text")
        got += [bytes(r).decode() for r in sp]
        sp.close()
    assert sorted(got) == sorted(lines)
