#!/usr/bin/env python
"""Benchmarks vs the reference, printed as ONE JSON line on stdout.

Primary metric (vs_baseline is measured, same-hardware, same-file):
  recordio_inputsplit_read_MBps — the #1 hot path (SURVEY.md §3.1),
  measured the way the reference's own harness does
  (test/split_read_test.cc): iterate every record of a RecordIO file
  through InputSplit.  The baseline is the reference C++ compiled from
  /root/reference on this machine reading the same file (which our
  writer produced — every run re-proves bit-exact format compat).

extra_metrics:
  indexed_shuffled_read_MBps — shuffled IndexedRecordIO batch reads,
      ours vs the reference's indexed path (vs in
      indexed_shuffled_vs_baseline).
  transformer_tokens_per_s / transformer_mfu_pct — full AdamW train
      step of the flagship 1B bf16 LM (models.flagship_config) on the
      real chip; MFU = tokens/s × train FLOPs/token ÷ chip peak
      (causal-halved attention accounting, models.train_flops_per_token).
  recordio_feed_to_hbm_MBps — RecordIO payload bytes landed in device
      HBM per second via feed.recordio_feed (BASELINE config #2).
"""

import json
import os
import subprocess
import sys
import time

WORK = "/tmp/dmlc_tpu_bench"
DATA = os.path.join(WORK, "data.rec")
INDEX = os.path.join(WORK, "data.idx")
TARGET_PAYLOAD = 128 << 20  # 128 MB
TRIALS = 3

REF_MAIN = r"""
#include <dmlc/io.h>
#include <dmlc/timer.h>
#include <cstdio>
#include <cstring>
#include <memory>
int main(int argc, char *argv[]) {
  if (argc < 2) { fprintf(stderr, "usage: prog uri [index_uri]\n"); return 1; }
  std::unique_ptr<dmlc::InputSplit> split(
      argc > 2 ? dmlc::InputSplit::Create(argv[1], argv[2], 0, 1,
                                          "indexed_recordio", true, 0, 256)
               : dmlc::InputSplit::Create(argv[1], 0, 1, "recordio"));
  dmlc::InputSplit::Blob blob;
  double start = dmlc::GetTime();
  size_t bytes = 0, n = 0;
  while (split->NextRecord(&blob)) { bytes += blob.size; ++n; }
  double dt = dmlc::GetTime() - start;
  printf("%.3f %zu %zu\n", bytes / 1.0e6 / dt, bytes, n);
  return 0;
}
"""

REF_SOURCES = [
    "src/io.cc",
    "src/io/input_split_base.cc",
    "src/io/line_split.cc",
    "src/io/recordio_split.cc",
    "src/io/indexed_recordio_split.cc",
    "src/io/local_filesys.cc",
    "src/io/filesys.cc",
    "src/recordio.cc",
]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def repo_path():
    return os.path.dirname(os.path.abspath(__file__))


def ensure_data():
    if (os.path.exists(DATA) and os.path.getsize(DATA) > TARGET_PAYLOAD
            and os.path.exists(INDEX)):
        return
    import numpy as np

    sys.path.insert(0, repo_path())
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    log(f"bench: writing {TARGET_PAYLOAD >> 20} MB RecordIO to {DATA}")
    rng = np.random.default_rng(0)
    with Stream.create(DATA, "w") as s:
        w = RecordIOWriter(s)
        total = 0
        while total < TARGET_PAYLOAD:
            n = int(rng.integers(32 << 10, 96 << 10))
            w.write_record(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            total += n

    # index file (record head offsets) via the span scanner — the same
    # format the reference's ReadIndexFile consumes: "<index> <offset>".
    # _chunk_spans falls back to a Python header walk without the .so.
    from dmlc_tpu.feed.device_feed import _chunk_spans

    with open(DATA, "rb") as f:
        buf = f.read()
    sp = _chunk_spans(memoryview(buf))
    with open(INDEX, "w") as f:
        for i, (off, _ln, flag) in enumerate(sp.tolist()):
            head = off - 8 if flag == 0 else off
            f.write(f"{i} {head}\n")


# cache key includes the harness source: a stale binary from an earlier
# bench version would silently measure the wrong reference path
import hashlib

REFBIN = os.path.join(
    WORK, "refbench_" + hashlib.md5(REF_MAIN.encode()).hexdigest()[:10])


def ensure_refbin():
    if os.path.exists(REFBIN):
        return True
    main_cc = os.path.join(WORK, "ref_main.cc")
    with open(main_cc, "w") as f:
        f.write(REF_MAIN)
    cmd = (
        ["g++", "-O3", "-std=c++11", "-I/root/reference/include",
         "-DDMLC_USE_HDFS=0", "-DDMLC_USE_S3=0", "-DDMLC_USE_AZURE=0",
         main_cc]
        + [os.path.join("/root/reference", s) for s in REF_SOURCES]
        + ["-o", REFBIN, "-pthread"]
    )
    log("bench: compiling reference baseline harness")
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        log(f"bench: reference build failed:\n{r.stderr[:2000]}")
        return False
    return True


def run_reference(indexed=False):
    best = 0.0
    args = [REFBIN, DATA] + ([INDEX] if indexed else [])
    for _ in range(TRIALS):
        out = subprocess.run(
            args, capture_output=True, text=True, check=True
        ).stdout.split()
        best = max(best, float(out[0]))
    return best


def run_ours():
    sys.path.insert(0, repo_path())
    from dmlc_tpu.io import input_split

    best = 0.0
    for _ in range(TRIALS):
        split = input_split.create(DATA, 0, 1, "recordio")
        t0 = time.perf_counter()
        nbytes = 0
        while True:
            rec = split.next_record()
            if rec is None:
                break
            nbytes += len(rec)
        dt = time.perf_counter() - t0
        split.close()
        best = max(best, nbytes / 1.0e6 / dt)
    return best


def run_ours_indexed_shuffled():
    sys.path.insert(0, repo_path())
    from dmlc_tpu.io import input_split

    best = 0.0
    for _ in range(TRIALS):
        split = input_split.create(
            DATA, 0, 1, "indexed_recordio", index_uri=INDEX, shuffle=True,
            seed=0, batch_size=256)
        t0 = time.perf_counter()
        nbytes = 0
        while True:
            rec = split.next_record()
            if rec is None:
                break
            nbytes += len(rec)
        dt = time.perf_counter() - t0
        split.close()
        best = max(best, nbytes / 1.0e6 / dt)
    return best


def bench_transformer():
    """Flagship 1B bf16 LM: full AdamW train step on the real chip."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    sys.path.insert(0, repo_path())
    from dmlc_tpu.models import (flagship_config, init_params,
                                 train_flops_per_token, unsharded_loss)

    if jax.devices()[0].platform != "tpu":
        log("bench: no TPU visible, skipping transformer bench")
        return None

    import contextlib

    from dmlc_tpu import metrics

    from dmlc_tpu import telemetry

    cfg = flagship_config()
    opt = optax.adamw(1e-4)
    kind = jax.devices()[0].device_kind
    # dense bf16 peak FLOP/s per chip — one table shared with the step
    # ledger's MFU accounting (DMLC_PEAK_FLOPS overrides both)
    peak = telemetry.detect_peak_flops()

    def measure(B, T, n_steps):
        params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
        opt_state = opt.init(params)

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def step(p, s, ids, labels):
            loss, g = jax.value_and_grad(
                lambda p_: unsharded_loss(p_, ids, labels, cfg))(p)
            up, s = opt.update(g, s, p)
            return optax.apply_updates(p, up), s, loss

        ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0,
                                 cfg.vocab)
        labels = jnp.roll(ids, -1, axis=1)
        for _ in range(2):  # compile + settle
            params, opt_state, loss = step(params, opt_state, ids, labels)
        # NB: on tunneled platforms block_until_ready() can return before
        # the remote compute finishes; a scalar VALUE fetch is the only
        # reliable synchronization point, so the clock brackets
        # float(loss) fetches.
        float(loss)
        trace_dir = os.environ.get("DMLC_BENCH_TRACE")
        fpt = train_flops_per_token(cfg, T, causal=True)
        telemetry.reset_steps()  # ledger records for THIS run only
        with contextlib.ExitStack() as stack:
            if trace_dir:  # guarantees stop_trace even on a failing step
                stack.enter_context(metrics.trace(trace_dir))
                log(f"bench: capturing jax profiler trace to {trace_dir}")
            t0 = time.perf_counter()
            for _ in range(n_steps):
                telemetry.step_begin()
                with metrics.annotate("dmlc_train_step"):
                    params, opt_state, loss = step(params, opt_state, ids,
                                                   labels)
                telemetry.step_end(tokens=B * T, flops=fpt * B * T)
            final_loss = float(loss)  # forces the whole chain
            dt = time.perf_counter() - t0
        assert jnp.isfinite(final_loss)
        tok_s = B * T * n_steps / dt
        mfu = round(tok_s * fpt / peak * 100, 1) if peak else None
        log(f"bench: transformer {tok_s:,.0f} tok/s, MFU={mfu}% on {kind} "
            f"(B={B} T={T}, {fpt / 1e9:.2f} GFLOP/token)")
        return tok_s, mfu, telemetry.ledger().summary()

    # same tokens/step at both contexts; T=8192 is the long-context
    # capability claim (flash kernels, save_flash remat) and is recorded
    # in the artifact so prose can never outrun the measurement
    tok_s, mfu, ledger = measure(8, 1024, 16)
    tok_s_long, mfu_long, _ = measure(1, 8192, 8)
    out = {"transformer_tokens_per_s": round(tok_s, 1),
           "transformer_mfu_pct": mfu,
           "transformer_tokens_per_s_long": round(tok_s_long, 1),
           "transformer_mfu_long_pct": mfu_long}
    out.update(_ledger_keys(ledger))
    return out


def _ledger_keys(summary):
    """Step-ledger summary → BENCH artifact keys (the attribution data
    regressions are diagnosed from: where did step wall time go, what
    goodput/MFU did the ledger actually account)."""
    if not summary:
        return {}
    out = {
        "step_time_p50": round(summary["step_time_p50"], 6),
        "step_time_p99": round(summary["step_time_p99"], 6),
        "step_feed_wait_fraction": round(summary["feed_wait_fraction"], 4),
        "mfu": (round(summary["mfu"], 4)
                if summary.get("mfu") is not None else None),
    }
    if summary.get("goodput_tokens_per_s") is not None:
        out["goodput_tokens_per_s"] = round(
            summary["goodput_tokens_per_s"], 1)
    if summary.get("membw_util") is not None:
        out["membw_util"] = round(summary["membw_util"], 4)
    if summary.get("bound") is not None:
        out["bound"] = summary["bound"]
    return out


def _goodput_keys(g0, g1):
    """Goodput-ledger delta over the benched window → artifact keys:
    the job-level wall-clock decomposition (goodput_fraction + named
    per-bucket badput seconds) for the same steps the step ledger
    accounted, so a perf regression shows up as a *named* badput
    bucket, not just a lower tokens/s."""
    if not g0 or not g1:
        return {}
    wall = g1["wall_s"] - g0["wall_s"]
    if wall <= 0:
        return {}
    buckets = {b: max(g1["buckets"].get(b, 0.0)
                      - g0["buckets"].get(b, 0.0), 0.0)
               for b in g1["buckets"]}
    out = {"goodput_fraction":
           round(buckets.get("productive", 0.0) / wall, 4)}
    for b, s in sorted(buckets.items()):
        if b != "productive" and s > 0.0005:
            out[f"goodput_badput_{b}_s"] = round(s, 4)
    return out


def bench_step_ledger():
    """Ledger-derived step keys on ANY backend: a small synced train
    loop through the step ledger.  When the flagship TPU transformer
    bench runs, its own ledger summary overwrites these keys — this
    keeps `step_time_*`/`goodput`/`mfu` in the artifact even on hosts
    where the flagship model cannot run."""
    import functools

    import jax
    import jax.numpy as jnp
    import optax

    sys.path.insert(0, repo_path())
    from dmlc_tpu import telemetry
    from dmlc_tpu.models import (TransformerConfig, init_params,
                                 train_step_flops, unsharded_loss)

    cfg = TransformerConfig(vocab=256, d_model=64, n_heads=2, head_dim=16,
                            d_ff=128, n_layers=2, n_experts=1,
                            dtype="float32")
    B, T, n_steps = 2, 64, 8
    params = init_params(jax.random.PRNGKey(0), cfg, n_stages=1)
    opt = optax.adamw(1e-3)
    opt_state = opt.init(params)

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(p, s, ids, labels):
        loss, g = jax.value_and_grad(
            lambda p_: unsharded_loss(p_, ids, labels, cfg))(p)
        up, s = opt.update(g, s, p)
        return optax.apply_updates(p, up), s, loss

    ids = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab)
    labels = jnp.roll(ids, -1, axis=1)
    params, opt_state, loss = step(params, opt_state, ids, labels)
    float(loss)  # compile + settle outside the ledgered window
    telemetry.reset_steps()
    from dmlc_tpu.telemetry import goodput as goodput_mod
    gled = goodput_mod.ledger()  # opt in: step_end feeds the ledger
    g0 = gled.status()
    flops = train_step_flops(cfg, B, T)
    for _ in range(n_steps):
        telemetry.step_begin()
        params, opt_state, loss = step(params, opt_state, ids, labels)
        float(loss)  # sync per step: walls are step times, not dispatch
        telemetry.step_end(tokens=B * T, flops=flops)
    g1 = gled.status()
    summ = telemetry.ledger().summary()
    log(f"bench: step ledger p50={summ.get('step_time_p50', 0):.4f}s "
        f"p99={summ.get('step_time_p99', 0):.4f}s "
        f"goodput={summ.get('goodput_tokens_per_s', 0):,.0f} tok/s "
        f"mfu={summ.get('mfu')}")
    out = _ledger_keys(summ)
    out.update(_goodput_keys(g0, g1))
    return out


def bench_feed_to_hbm():
    """RecordIO shards → device HBM payload MB/s (BASELINE config #2).

    Measures both the padded [B, max_bytes] feed and the packed
    zero-padding feed, plus the raw device_put ceiling of this link so
    feed efficiency is attributable (on a tunneled dev chip the link,
    not the host pipeline, is the bottleneck)."""
    import jax
    import numpy as np

    sys.path.insert(0, repo_path())
    from dmlc_tpu.feed import recordio_feed, recordio_packed_feed
    from dmlc_tpu.parallel import build_mesh

    if jax.devices()[0].platform != "tpu":
        log("bench: no TPU visible, skipping feed bench")
        return None

    # raw host→HBM ceiling at the packed feed's transfer size (6 MB,
    # matching buf_bytes below so per-transfer dispatch overhead is
    # priced into the ceiling the same way the feed pays it)
    buf = 6 << 20
    x = np.random.randint(0, 256, (buf,), dtype=np.uint8)
    dev = jax.devices()[0]
    a = jax.device_put(x, dev)
    int(np.asarray(a[0]))
    t0 = time.perf_counter()
    for _ in range(16):
        a = jax.device_put(x, dev)
    int(np.asarray(a[0]))
    ceiling = 16 * buf / 1.0e6 / (time.perf_counter() - t0)

    mesh = build_mesh(1, devices=jax.devices()[:1], dp=1, sp=1, tp=1,
                      pp=1, ep=1)

    from dmlc_tpu import metrics

    def run(make_feed, payload_of):
        best, best_steady, stalls, eff, stages = 0.0, 0.0, {}, None, {}
        for _ in range(2):
            before = metrics.snapshot().get("feed", {})
            feed = make_feed()
            t0 = time.perf_counter()
            payload = 0
            last = None
            t_warm = warm_payload = None
            for b in feed:
                payload += payload_of(b)
                last = b
                if t_warm is None:
                    # first batch landed: warmup (feed spin-up + JAX
                    # dispatch/compile) ends HERE — sync it so the
                    # steady-state clock starts from a drained pipe
                    arr = b["data"]
                    int(np.asarray(arr[(0,) * arr.ndim]))
                    t_warm = time.perf_counter()
                    warm_payload = payload
            if last is not None:
                # value fetch, not block_until_ready: see bench_transformer.
                # Index on DEVICE first — np.asarray(whole array) would
                # pull the full buffer back through the link inside dt.
                arr = last["data"]
                int(np.asarray(arr[(0,) * arr.ndim]))
            t_end = time.perf_counter()
            dt = t_end - t0
            after = metrics.snapshot().get("feed", {})
            # bytes ACTUALLY shipped over the link, from the feed's own
            # counter: cached zero shards ship nothing, and the padded
            # layout's packed transport ships offsets + payload — the
            # on-device expansion never touches the link
            shipped = (after.get("bytes_to_device", 0.0)
                       - before.get("bytes_to_device", 0.0))
            if payload / 1.0e6 / dt > best:
                best = payload / 1.0e6 / dt
                # steady state excludes the first batch and its warmup
                if t_warm is not None and payload > warm_payload:
                    best_steady = ((payload - warm_payload) / 1.0e6
                                   / (t_end - t_warm))
                eff = payload / shipped if shipped else None
                # producer stall = waiting on a full queue (consumer is
                # the bottleneck); consumer stall = waiting on an empty
                # one (host pipeline / link is) — overlap attribution
                stalls = {
                    k: round(after.get(f"{k}_secs", 0.0)
                             - before.get(f"{k}_secs", 0.0), 3)
                    for k in ("producer_stall", "consumer_stall")}
                # producer-side stage split: parse_native = the fused
                # scan+verify (+ fused libsvm tokenize), pack = batch
                # assembly (pad-pack / pack_spans), crc = residual
                # integrity work OUTSIDE the fused scan (reject and
                # skip-list routing; ≈ 0 proves single-pass integrity)
                stages = {
                    k: round(after.get(f"{k}_secs", 0.0)
                             - before.get(f"{k}_secs", 0.0), 3)
                    for k in ("parse_native", "pack", "crc")}
        return best, best_steady, stalls, eff, stages

    # padded contract, packed transport: records stage back-to-back in a
    # 6 MB buffer per batch and a jitted on-device gather materializes
    # the [B, max_bytes] padded layout AFTER the link, so the padded
    # path ships payload (not padding) and tracks the same ceiling as
    # the packed layout
    padded, padded_steady, padded_stalls, padded_eff, padded_stages = run(
        lambda: recordio_feed(DATA, mesh, batch_records=256,
                              max_bytes=96 << 10, pack_bytes=buf),
        lambda b: int(np.sum(np.asarray(b["length"]))))
    # 6 MB batches: small enough that the epoch-tail partial batch costs
    # < 5% shipped efficiency (24 MB batches left 11% on the table),
    # large enough that per-transfer dispatch overhead stays invisible
    # next to a ~0.2 s transfer on this link
    packed, packed_steady, packed_stalls, packed_eff, packed_stages = run(
        lambda: recordio_packed_feed(DATA, mesh, buf_bytes=buf,
                                     max_records=1024),
        lambda b: int(np.asarray(b["offsets"])[int(np.asarray(b["count"])[0])]))
    # Payload ÷ shipped bytes: what each layout costs a NON-compressing
    # link (real PCIe/DMA).  This dev chip's tunnel compresses, so any
    # zero tail travels nearly free HERE and payload MB/s alone would
    # under-credit the packed transport.
    log(f"bench: feed→HBM padded={padded:.1f} (steady {padded_steady:.1f}) "
        f"packed={packed:.1f} (steady {packed_steady:.1f}) "
        f"device_put ceiling={ceiling:.1f} MB/s "
        f"(shipped-eff padded={padded_eff:.2f} packed={packed_eff:.2f}; "
        f"stalls: padded={padded_stalls} packed={packed_stalls}; "
        f"stages: padded={padded_stages} packed={packed_stages})")
    return {"recordio_feed_to_hbm_MBps": round(packed, 1),
            "recordio_feed_to_hbm_MBps_steady": round(packed_steady, 1),
            "recordio_feed_padded_MBps": round(padded, 1),
            "recordio_feed_padded_MBps_steady": round(padded_steady, 1),
            "device_put_ceiling_MBps": round(ceiling, 1),
            "feed_packed_shipped_efficiency": round(packed_eff, 3),
            "feed_padded_shipped_efficiency": round(padded_eff, 3),
            "feed_padded_producer_stall_s":
                padded_stalls.get("producer_stall"),
            "feed_padded_consumer_stall_s":
                padded_stalls.get("consumer_stall"),
            "feed_packed_producer_stall_s":
                packed_stalls.get("producer_stall"),
            "feed_packed_consumer_stall_s":
                packed_stalls.get("consumer_stall"),
            "feed_padded_parse_native_s":
                padded_stages.get("parse_native"),
            "feed_padded_pack_s": padded_stages.get("pack"),
            "feed_padded_crc_s": padded_stages.get("crc"),
            "feed_packed_parse_native_s":
                packed_stages.get("parse_native"),
            "feed_packed_pack_s": packed_stages.get("pack"),
            "feed_packed_crc_s": packed_stages.get("crc")}


def main():
    os.makedirs(WORK, exist_ok=True)
    ensure_data()
    ours = run_ours()
    extra = {}
    baseline = None
    idx_vs = None
    if ensure_refbin():
        baseline = run_reference()
        log(f"bench: ours={ours:.1f} MB/s reference={baseline:.1f} MB/s")
        try:
            ours_idx = run_ours_indexed_shuffled()
            ref_idx = run_reference(indexed=True)
            extra["indexed_shuffled_read_MBps"] = round(ours_idx, 1)
            idx_vs = round(ours_idx / ref_idx, 3) if ref_idx else None
            extra["indexed_shuffled_vs_baseline"] = idx_vs
            log(f"bench: indexed-shuffled ours={ours_idx:.1f} "
                f"reference={ref_idx:.1f} MB/s")
        except Exception as e:  # noqa: BLE001
            log(f"bench: indexed bench failed: {e!r}")
    # step-ledger fallback first: the flagship transformer bench, when
    # it runs (TPU), overwrites the ledger keys with flagship numbers
    for fn in (bench_step_ledger, bench_transformer, bench_feed_to_hbm):
        try:
            r = fn()
            if r:
                extra.update(r)
        except Exception as e:  # noqa: BLE001
            log(f"bench: {fn.__name__} failed: {e!r}")
    # compile-ledger keys across every bench above: a perf PR that adds
    # a recompile per step shows up here before it shows up in step time
    try:
        from dmlc_tpu.telemetry import compute

        if compute.enabled():
            extra["recompiles"] = compute.recompiles_total()
            extra["hbm_peak_bytes"] = compute.sample_hbm(
                publish=False).get("peak_bytes")
    except Exception as e:  # noqa: BLE001
        log(f"bench: compute ledger snapshot failed: {e!r}")
    result = {
        "metric": "recordio_inputsplit_read_MBps",
        "value": round(ours, 1),
        "unit": "MB/s",
        "vs_baseline": round(ours / baseline, 3) if baseline else None,
        "extra_metrics": extra,
    }
    # structured telemetry snapshot (histogram percentiles, span count)
    # accumulated across every bench above — the attribution data later
    # perf PRs cite; update_perf_docs.py renders it into the docs
    try:
        from dmlc_tpu import telemetry

        result["telemetry"] = telemetry.export_json()
    except Exception as e:  # noqa: BLE001
        log(f"bench: telemetry snapshot failed: {e!r}")
    print(json.dumps(result))


if __name__ == "__main__":
    main()
