#!/usr/bin/env python
"""Benchmark: RecordIO InputSplit record-read throughput vs the reference.

Measures the #1 hot path (SURVEY.md §3.1) the way the reference's own
harness does (test/split_read_test.cc): iterate every record of a
RecordIO file through InputSplit and report MB/s.  The baseline is the
reference C++ implementation compiled from /root/reference on this
machine and run on the same file — a true same-hardware, same-data
comparison.  The data file is written by OUR RecordIO writer and read by
the REFERENCE reader, so every run also re-proves bit-exact format
compatibility.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": ..., "unit": "MB/s", "vs_baseline": ...}
"""

import json
import os
import subprocess
import sys
import time

WORK = "/tmp/dmlc_tpu_bench"
DATA = os.path.join(WORK, "data.rec")
REFBIN = os.path.join(WORK, "refbench")
TARGET_PAYLOAD = 128 << 20  # 128 MB
TRIALS = 3

REF_MAIN = r"""
#include <dmlc/io.h>
#include <dmlc/timer.h>
#include <cstdio>
#include <memory>
int main(int argc, char *argv[]) {
  if (argc < 2) { fprintf(stderr, "usage: prog uri\n"); return 1; }
  std::unique_ptr<dmlc::InputSplit> split(
      dmlc::InputSplit::Create(argv[1], 0, 1, "recordio"));
  dmlc::InputSplit::Blob blob;
  double start = dmlc::GetTime();
  size_t bytes = 0, n = 0;
  while (split->NextRecord(&blob)) { bytes += blob.size; ++n; }
  double dt = dmlc::GetTime() - start;
  printf("%.3f %zu %zu\n", bytes / 1.0e6 / dt, bytes, n);
  return 0;
}
"""

REF_SOURCES = [
    "src/io.cc",
    "src/io/input_split_base.cc",
    "src/io/line_split.cc",
    "src/io/recordio_split.cc",
    "src/io/indexed_recordio_split.cc",
    "src/io/local_filesys.cc",
    "src/io/filesys.cc",
    "src/recordio.cc",
]


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def ensure_data():
    if os.path.exists(DATA) and os.path.getsize(DATA) > TARGET_PAYLOAD:
        return
    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dmlc_tpu.io.recordio import RecordIOWriter
    from dmlc_tpu.io.stream import Stream

    log(f"bench: writing {TARGET_PAYLOAD >> 20} MB RecordIO to {DATA}")
    rng = np.random.default_rng(0)
    with Stream.create(DATA, "w") as s:
        w = RecordIOWriter(s)
        total = 0
        while total < TARGET_PAYLOAD:
            n = int(rng.integers(32 << 10, 96 << 10))
            w.write_record(rng.integers(0, 256, n, dtype=np.uint8).tobytes())
            total += n


def ensure_refbin():
    if os.path.exists(REFBIN):
        return True
    main_cc = os.path.join(WORK, "ref_main.cc")
    with open(main_cc, "w") as f:
        f.write(REF_MAIN)
    cmd = (
        ["g++", "-O3", "-std=c++11", "-I/root/reference/include",
         "-DDMLC_USE_HDFS=0", "-DDMLC_USE_S3=0", "-DDMLC_USE_AZURE=0",
         main_cc]
        + [os.path.join("/root/reference", s) for s in REF_SOURCES]
        + ["-o", REFBIN, "-pthread"]
    )
    log("bench: compiling reference baseline harness")
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        log(f"bench: reference build failed:\n{r.stderr[:2000]}")
        return False
    return True


def run_reference():
    best = 0.0
    for _ in range(TRIALS):
        out = subprocess.run(
            [REFBIN, DATA], capture_output=True, text=True, check=True
        ).stdout.split()
        best = max(best, float(out[0]))
    return best


def run_ours():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from dmlc_tpu.io import input_split

    best = 0.0
    for _ in range(TRIALS):
        split = input_split.create(DATA, 0, 1, "recordio")
        t0 = time.perf_counter()
        nbytes = 0
        while True:
            rec = split.next_record()
            if rec is None:
                break
            nbytes += len(rec)
        dt = time.perf_counter() - t0
        split.close()
        best = max(best, nbytes / 1.0e6 / dt)
    return best


def main():
    os.makedirs(WORK, exist_ok=True)
    ensure_data()
    ours = run_ours()
    baseline = None
    if ensure_refbin():
        baseline = run_reference()
        log(f"bench: ours={ours:.1f} MB/s reference={baseline:.1f} MB/s")
    result = {
        "metric": "recordio_inputsplit_read_MBps",
        "value": round(ours, 1),
        "unit": "MB/s",
        "vs_baseline": round(ours / baseline, 3) if baseline else None,
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
