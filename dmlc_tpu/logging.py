"""glog-style leveled logging with a pluggable sink.

Rebuild of reference include/dmlc/logging.h:104-155 (LOG(severity) macros) and
the ``CustomLogMessage`` pluggable sink (logging.h:233-252). Severity FATAL
raises :class:`dmlc_tpu.base.DMLCError` (the ``DMLC_LOG_FATAL_THROW=1``
behavior the reference defaults to for library use) — but only AFTER the
formatted line reaches the sink/stderr, so the last words of a dying rank
are in its log, not just in a traceback some launcher may have swallowed.

Lines carry date, time, thread name, and (when ``DMLC_TASK_ID`` or
``DMLC_RANK`` is set — read once) a rank prefix, so interleaved multi-rank
output stays attributable:

    [2026-08-03 14:02:11] r3 INFO Thread-2: feed: 120 MB to device
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Callable, Optional

from .base import DMLCError, get_env
from .concurrency import make_lock

__all__ = ["log", "info", "warning", "error", "fatal", "set_log_sink", "set_verbosity"]

_LEVELS = {"DEBUG": 0, "INFO": 1, "WARNING": 2, "ERROR": 3, "FATAL": 4}
_lock = make_lock("logging._lock")
_sink: Optional[Callable[[str], None]] = None
_verbosity = 1  # default: INFO and above
_rank_prefix: Optional[str] = None  # lazy: env read once at first format


def set_log_sink(sink: Optional[Callable[[str], None]]) -> None:
    """Install a custom sink receiving fully-formatted lines (analog of
    ``CustomLogMessage::Log``, logging.h:233-252). ``None`` restores stderr."""
    global _sink
    _sink = sink


def set_verbosity(level: str) -> None:
    global _verbosity
    _verbosity = _LEVELS[level.upper()]


def _get_rank_prefix() -> str:
    """Rank tag from DMLC_TASK_ID / DMLC_RANK, resolved once — worker env
    is fixed at launch, and the hot path must not hit os.environ per line."""
    global _rank_prefix
    if _rank_prefix is None:
        rank = get_env("DMLC_TASK_ID", "") or get_env("DMLC_RANK", "")
        _rank_prefix = f"r{rank} " if rank not in ("", "NULL") else ""
    return _rank_prefix


def _reset_rank_prefix_cache() -> None:
    """Drop the cached rank prefix (test hook; workers never need this)."""
    global _rank_prefix
    _rank_prefix = None


def _format(level: str, msg: str) -> str:
    ts = time.strftime("%Y-%m-%d %H:%M:%S")
    thread = threading.current_thread().name
    return f"[{ts}] {_get_rank_prefix()}{level} {thread}: {msg}"


def _emit(line: str) -> None:
    with _lock:
        if _sink is not None:
            _sink(line)
        else:
            print(line, file=sys.stderr, flush=True)


def log(level: str, msg: str) -> None:
    level = level.upper()
    if level != "FATAL" and _LEVELS[level] < _verbosity:
        return
    # FATAL always emits (glog semantics: FATAL cannot be suppressed) and
    # emits BEFORE raising — a FATAL that only surfaced as an exception
    # never reached the installed sink at all
    _emit(_format(level, msg))
    if level == "FATAL":
        # a FATAL is this process's last words: when DMLC_POSTMORTEM_DIR
        # is configured, dump the flight record (snapshot + open spans +
        # event tail) before the raise unwinds anything (no-op + never
        # raises otherwise — dying must not become hanging)
        from .telemetry import postmortem

        postmortem.dump(f"FATAL: {msg}")
        raise DMLCError(msg)


def info(msg: str) -> None:
    log("INFO", msg)


def warning(msg: str) -> None:
    log("WARNING", msg)


def error(msg: str) -> None:
    log("ERROR", msg)


def fatal(msg: str) -> None:
    """Logs the line, then raises DMLCError (DMLC_LOG_FATAL_THROW
    behavior, base.h:20-22)."""
    log("FATAL", msg)
