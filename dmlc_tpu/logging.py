"""glog-style leveled logging with a pluggable sink.

Rebuild of reference include/dmlc/logging.h:104-155 (LOG(severity) macros) and
the ``CustomLogMessage`` pluggable sink (logging.h:233-252). Severity FATAL
raises :class:`dmlc_tpu.base.DMLCError` (the ``DMLC_LOG_FATAL_THROW=1``
behavior the reference defaults to for library use).
"""

from __future__ import annotations

import sys
import time
import threading
from typing import Callable, Optional

from .base import DMLCError

__all__ = ["log", "info", "warning", "error", "fatal", "set_log_sink", "set_verbosity"]

_LEVELS = {"DEBUG": 0, "INFO": 1, "WARNING": 2, "ERROR": 3, "FATAL": 4}
_lock = threading.Lock()
_sink: Optional[Callable[[str], None]] = None
_verbosity = 1  # default: INFO and above


def set_log_sink(sink: Optional[Callable[[str], None]]) -> None:
    """Install a custom sink receiving fully-formatted lines (analog of
    ``CustomLogMessage::Log``, logging.h:233-252). ``None`` restores stderr."""
    global _sink
    _sink = sink


def set_verbosity(level: str) -> None:
    global _verbosity
    _verbosity = _LEVELS[level.upper()]


def _format(level: str, msg: str) -> str:
    ts = time.strftime("%H:%M:%S")
    return f"[{ts}] {level}: {msg}"


def log(level: str, msg: str) -> None:
    level = level.upper()
    if level == "FATAL":
        raise DMLCError(msg)
    if _LEVELS[level] < _verbosity:
        return
    line = _format(level, msg)
    with _lock:
        if _sink is not None:
            _sink(line)
        else:
            print(line, file=sys.stderr, flush=True)


def info(msg: str) -> None:
    log("INFO", msg)


def warning(msg: str) -> None:
    log("WARNING", msg)


def error(msg: str) -> None:
    log("ERROR", msg)


def fatal(msg: str) -> None:
    """Raises DMLCError (DMLC_LOG_FATAL_THROW behavior, base.h:20-22)."""
    raise DMLCError(msg)
