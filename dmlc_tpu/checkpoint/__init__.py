"""Checkpoint/resume: sharded jax.Array pytrees over the Stream/URI layer.

The reference provides the *mechanism* — Serializable + typed
Stream::Write over any URI so models checkpoint straight to object
storage (SURVEY.md §5; S3 multipart writer s3_filesys.cc:551-680).  The
TPU rebuild keeps that split: this module lays orbax-style sharded-array
checkpoints (per-shard files + JSON manifest) on top of Stream.create,
so the same code persists to file:// and gs:// (resumable upload), and
each host writes only its addressable shards.
"""

from .sharded import (  # noqa: F401
    CheckpointManager,
    CorruptCheckpoint,
    MissingLeaf,
    restore_pytree,
    save_pytree,
)
