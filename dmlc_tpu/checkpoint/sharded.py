"""Sharded pytree checkpoints: per-shard blobs + a JSON manifest.

Layout under a checkpoint directory URI:
    manifest.json                       tree/shape/dtype/sharding metadata
    <leaf-key>.<shard-id>               raw little-endian shard bytes

Shard identity is the global index (slice extents) the shard covers, so
restore works on any mesh with the same axis names/sizes via
jax.make_array_from_callback; replicated shards are written once
(replica_id == 0).  All IO goes through Stream.create — local paths and
gs:// behave identically (GCS writes use the resumable-upload stream).
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, List, Optional

import numpy as np

from ..base import DMLCError, check
from ..io.stream import Stream


class CorruptCheckpoint(DMLCError):
    """A checkpoint shard failed its CRC32C digest — the on-disk bytes
    differ from what ``save_pytree`` recorded in the manifest."""


class MissingLeaf(DMLCError):
    """The restore template asks for a leaf the checkpoint's manifest
    does not carry (e.g. a pre-PR checkpoint without the persisted
    stream-position leaf).  Typed so callers can probe for optional
    leaves without matching on message text."""

MANIFEST = "manifest.json"


def _local_path(uri: str) -> Optional[str]:
    """Filesystem path for local URIs, None for object stores."""
    if uri.startswith("file://"):
        return uri[len("file://"):]
    return None if "://" in uri else uri


def _commit_manifest(uri: str, data: bytes) -> None:
    """Write the manifest LAST and ATOMICALLY — the commit record of a
    checkpoint.  Shards without a committed manifest are invisible to
    restore, so a preemption at ANY point mid-save leaves the previous
    committed step as the restore target instead of a torn one.

    Local paths go through write-to-temp + fsync + rename (atomic on
    POSIX); object stores get a plain PUT, which is already all-or-
    nothing at the object level."""
    from ..resilience import fault_point

    fault_point("checkpoint.commit", uri=uri)
    target = _join(uri, MANIFEST)
    path = _local_path(target)
    if path is None:
        with Stream.create(target, "w") as s:
            s.write(data)
        return
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def _leaf_key(path) -> str:
    import jax

    key = jax.tree_util.keystr(path)
    safe = "".join(c if (c.isalnum() or c in "._-") else "_" for c in key)
    return safe.strip("_") or "leaf"


def _index_key(index, shape) -> str:
    """Stable string for a global shard index (tuple of slices)."""
    parts = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        parts.append(f"{start}-{stop}")
    return "_".join(parts) if parts else "scalar"


def _spec_to_json(arr) -> Optional[list]:
    sharding = getattr(arr, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    out = []
    for entry in spec:
        if entry is None:
            out.append(None)
        elif isinstance(entry, (tuple, list)):
            out.append(list(entry))
        else:
            out.append(entry)
    return out


def _spec_from_json(raw):
    from jax.sharding import PartitionSpec as P

    if raw is None:
        return P()
    return P(*[tuple(e) if isinstance(e, list) else e for e in raw])


def _join(base: str, name: str) -> str:
    return base.rstrip("/") + "/" + name


def _read_all(s: Stream, chunk: int = 8 << 20) -> bytes:
    parts = []
    while True:
        d = s.read(chunk)
        if not d:
            return b"".join(parts)
        parts.append(d)


def _ensure_dir(uri: str) -> None:
    """Create the directory for local checkpoint paths (object stores
    have no directories to create)."""
    if "://" in uri and not uri.startswith("file://"):
        return
    import os

    os.makedirs(uri[len("file://"):] if uri.startswith("file://") else uri,
                exist_ok=True)


def save_pytree(uri: str, tree: Any, *, process_index: int = 0) -> None:
    """Write a pytree of jax.Arrays / numpy arrays under ``uri``.

    Multi-host: every process writes its addressable shards; only
    process 0 writes the manifest (call with process_index=jax.process_index()).
    """
    import jax

    from .. import telemetry

    with telemetry.span("checkpoint.save", stage="checkpoint",
                        args={"uri": uri}), \
            telemetry.timed("checkpoint", "save"):
        _ensure_dir(uri)
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        manifest: Dict[str, Any] = {"format": 1, "leaves": {}}
        nbytes = 0
        for path, leaf in leaves:
            key = _leaf_key(path)
            check(key not in manifest["leaves"], f"duplicate leaf key {key}")
            arr = leaf
            entry: Dict[str, Any] = {
                "path": jax.tree_util.keystr(path),
                "shape": list(np.shape(arr)),
                "dtype": str(arr.dtype) if hasattr(arr, "dtype")
                else str(np.asarray(arr).dtype),
                "spec": _spec_to_json(arr),
                "shards": {},
                # per-shard CRC32C digest, recorded at save time and
                # verified on restore: a flipped shard fails restore
                # LOUDLY instead of poisoning the optimizer state, and
                # restore_latest falls back to the previous committed
                # step (additive manifest field: pre-digest checkpoints
                # restore unverified)
                "crc32c": {},
            }
            from ..io.integrity import crc32c

            if hasattr(arr, "addressable_shards"):
                for shard in arr.addressable_shards:
                    if shard.replica_id != 0:
                        continue
                    ikey = _index_key(shard.index, arr.shape)
                    fname = f"{key}.{ikey}"
                    entry["shards"][ikey] = fname
                    raw = np.ascontiguousarray(shard.data).tobytes()
                    entry["crc32c"][ikey] = crc32c(raw)
                    nbytes += len(raw)
                    with Stream.create(_join(uri, fname), "w") as s:
                        s.write(raw)
            else:
                npa = np.asarray(arr)
                ikey = _index_key(tuple(slice(0, d) for d in npa.shape),
                                  npa.shape)
                entry["shards"][ikey] = f"{key}.{ikey}"
                raw = np.ascontiguousarray(npa).tobytes()
                entry["crc32c"][ikey] = crc32c(raw)
                nbytes += len(raw)
                with Stream.create(_join(uri, f"{key}.{ikey}"), "w") as s:
                    s.write(raw)
            manifest["leaves"][key] = entry
        telemetry.inc("checkpoint", "bytes_written", nbytes)
        telemetry.inc("checkpoint", "saves")
        if process_index == 0:
            # shards first, manifest last: the atomic manifest commit is
            # what makes the checkpoint exist at all (crash consistency)
            _commit_manifest(uri,
                             json.dumps(manifest, indent=1).encode())


def _parse_index(ikey: str, shape) -> tuple:
    if ikey == "scalar":
        return ()
    return tuple(
        slice(int(a), int(b))
        for a, b in (p.split("-") for p in ikey.split("_"))
    )


def _try_extents(ikey: str, shape) -> Optional[tuple]:
    """((start, stop), ...) if ikey is a well-formed in-bounds shard key
    for ``shape``, else None (e.g. a suffix captured from another leaf)."""
    if ikey == "scalar":
        return () if shape == () else None
    parts = ikey.split("_")
    if len(parts) != len(shape):
        return None
    out = []
    for p, dim in zip(parts, shape):
        m = p.split("-")
        if len(m) != 2 or not (m[0].isdigit() and m[1].isdigit()):
            return None
        a, b = int(m[0]), int(m[1])
        if not (0 <= a < b <= dim):
            return None
        out.append((a, b))
    return tuple(out)


def _exact_cover(ikeys, shape) -> bool:
    """True iff the shard boxes tile the array exactly: pairwise disjoint
    and total volume == array size (O(#shards) memory, no bool mask)."""
    boxes = [_try_extents(k, shape) for k in ikeys]
    if any(b is None for b in boxes):
        return False
    total = 1
    for d in shape:
        total *= d
    vol = 0
    for b in boxes:
        v = 1
        for a, c in b:
            v *= c - a
        vol += v
    if vol != total:
        return False
    for i in range(len(boxes)):
        for j in range(i + 1, len(boxes)):
            if all(a1 < b2 and a2 < b1
                   for (a1, b1), (a2, b2) in zip(boxes[i], boxes[j])):
                return False  # overlap (scalar duplicates hit vol != total)
    return True


def restore_pytree(uri: str, template: Any, *, mesh=None) -> Any:
    """Restore a pytree saved by save_pytree.

    ``template`` supplies the tree structure (values ignored).  With
    ``mesh``, leaves come back as sharded jax.Arrays per the recorded
    PartitionSpec; without, as host numpy arrays.
    """
    from .. import telemetry

    with telemetry.span("checkpoint.restore", stage="checkpoint",
                        args={"uri": uri}), \
            telemetry.timed("checkpoint", "restore"):
        out = _restore_pytree(uri, template, mesh=mesh)
    telemetry.inc("checkpoint", "restores")
    return out


def _restore_pytree(uri: str, template: Any, *, mesh=None) -> Any:
    import jax

    with Stream.create(_join(uri, MANIFEST), "r") as s:
        raw_manifest = _read_all(s)
    # the manifest is the digest root of trust, so it carries no digest
    # of its own — but a rotted manifest must still cost one checkpoint
    # interval, not the job: parse/shape failures are CorruptCheckpoint
    # (restore_latest falls back), while read errors stay transient
    try:
        manifest = json.loads(raw_manifest)
        if not isinstance(manifest, dict):
            raise ValueError("manifest is not a JSON object")
    except ValueError as e:
        raise CorruptCheckpoint(
            f"checkpoint manifest at {uri} is unparseable ({e}) — "
            f"the checkpoint is corrupt")
    if manifest.get("format") != 1:
        raise CorruptCheckpoint(
            f"checkpoint manifest at {uri} has unknown format "
            f"{manifest.get('format')!r} — the checkpoint is corrupt")
    leaves_meta = manifest.get("leaves")
    if leaves_meta is None:
        raise CorruptCheckpoint(
            f"checkpoint manifest at {uri} lacks its leaves table — "
            f"the checkpoint is corrupt")

    paths, treedef = jax.tree_util.tree_flatten_with_path(template)

    def load_shard_bytes(key: str, ikey: str, dtype, shape,
                         want_crc=None) -> np.ndarray:
        # shard filenames are derived deterministically (f"{key}.{ikey}"),
        # NOT looked up in the manifest: in a multi-host save every process
        # writes its own addressable shards but only process 0 writes the
        # manifest, so the manifest's shards dict covers one process only
        with Stream.create(_join(uri, f"{key}.{ikey}"), "r") as s:
            raw = _read_all(s)
        from .. import telemetry

        telemetry.inc("checkpoint", "bytes_read", len(raw))
        if want_crc is not None:
            from ..io.integrity import crc32c

            got = crc32c(raw)
            if got != int(want_crc):
                telemetry.inc("integrity", "checksum_failures")
                telemetry.record_event("checkpoint_shard_corrupt",
                                       uri=uri, shard=f"{key}.{ikey}")
                raise CorruptCheckpoint(
                    f"checkpoint shard {key}.{ikey} failed its CRC32C "
                    f"digest (manifest {int(want_crc):#010x}, file "
                    f"{got:#010x}) — the checkpoint at {uri} is "
                    f"corrupt")
        return np.frombuffer(raw, dtype=dtype).reshape(shape)

    listing_cache: list = []

    def dir_listing() -> list:
        """Checkpoint-dir file names, listed once per restore (lazy)."""
        if not listing_cache:
            from ..io.filesys import FileSystem
            from ..io.uri import URI

            base = URI(uri if "://" in uri else "file://" + uri)
            fs = FileSystem.get_instance(base)
            listing_cache.append(
                [f.path.name.rsplit("/", 1)[-1]
                 for f in fs.list_directory(base)])
        return listing_cache[0]

    def shard_keys_for(key: str, meta, shape) -> list:
        """Shard ikeys covering the leaf.  The manifest is the fast path;
        when it does not cover the array (multi-host save: each process
        writes its shards but only process 0 writes the manifest), the
        directory listing supplies the rest.  Suffixes are validated as
        ikeys for this shape, so a leaf key that dot-prefixes another
        leaf's key never captures the other leaf's files."""
        ikeys = [k for k in meta["shards"]
                 if _try_extents(k, shape) is not None]
        if _exact_cover(ikeys, shape):
            return ikeys
        prefix = key + "."
        extra = {n[len(prefix):] for n in dir_listing()
                 if n.startswith(prefix)}
        ikeys = sorted(set(ikeys)
                       | {k for k in extra if _try_extents(k, shape)})
        check(_exact_cover(ikeys, shape),
              f"checkpoint leaf {key}: shard files {ikeys} do not tile the "
              f"array exactly (incomplete multi-host save, or stale shards "
              f"from a save with a different sharding layout — clean the "
              f"checkpoint directory)")
        return ikeys

    out_leaves = []
    for path, _ in paths:
        key = _leaf_key(path)
        meta = leaves_meta.get(key)
        if meta is None:
            raise MissingLeaf(f"checkpoint missing leaf {key}")
        shape = tuple(meta["shape"])
        dtype = np.dtype(meta["dtype"])
        crcs = meta.get("crc32c") or {}
        if mesh is not None:
            spec = _spec_from_json(meta["spec"])
            sharding = jax.sharding.NamedSharding(mesh, spec)

            def cb(index, key=key, shape=shape, dtype=dtype, crcs=crcs):
                ikey = _index_key(index, shape)
                extent = tuple(
                    (0 if sl.start is None else sl.start,
                     dim if sl.stop is None else sl.stop)
                    for sl, dim in zip(index, shape))
                sub_shape = tuple(b - a for a, b in extent)
                # digests cover the shards THIS manifest writer saved;
                # other hosts' shards (and resharded reads) verify only
                # when the shard layout matches — absent digest = no
                # verification, never a false failure
                return load_shard_bytes(key, ikey, dtype, sub_shape,
                                        want_crc=crcs.get(ikey))

            out_leaves.append(
                jax.make_array_from_callback(shape, sharding, cb))
        else:
            full = np.zeros(shape, dtype)
            for ikey in shard_keys_for(key, meta, shape):
                idx = _parse_index(ikey, shape)
                sub_shape = tuple(sl.stop - sl.start for sl in idx)
                full[idx] = load_shard_bytes(key, ikey, dtype, sub_shape,
                                             want_crc=crcs.get(ikey))
            out_leaves.append(full)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


class CheckpointManager:
    """Step-numbered checkpoints with crash-consistent restore and
    retention.

    The policy layer the reference leaves to users (SURVEY.md §5),
    matching common trainer needs: save(step, tree), restore latest,
    keep the newest ``max_to_keep`` (local paths only for deletion).

    Crash consistency: a checkpoint EXISTS only once its manifest is
    committed (written last, atomically — see ``_commit_manifest``).
    ``latest_step``/``restore_latest`` scan the step directories and
    skip any without a committed manifest, so a preemption mid-save can
    never be restored from; the ``LATEST`` file is written as a
    human/ops hint but is never trusted as the restore pointer."""

    def __init__(self, base_uri: str, *, max_to_keep: int = 3):
        check(max_to_keep >= 1,
              f"max_to_keep must be >= 1, got {max_to_keep} (0 would "
              f"delete every checkpoint including the one just saved)")
        self.base = base_uri.rstrip("/")
        self.max_to_keep = max_to_keep

    def _step_dir(self, step: int) -> str:
        return f"{self.base}/step_{step:08d}"

    def save(self, step: int, tree: Any, *, process_index: int = 0) -> None:
        save_pytree(self._step_dir(step), tree, process_index=process_index)
        if process_index == 0:
            with Stream.create(_join(self.base, "LATEST"), "w") as s:
                s.write(str(step).encode())
            self._retain()

    def _has_manifest(self, step: int) -> bool:
        s = Stream.create(_join(self._step_dir(step), MANIFEST), "r",
                          allow_null=True)
        if s is None:
            return False
        s.close()
        return True

    def _step_dirs(self) -> Optional[List[int]]:
        """Step numbers with a step_* directory under base (committed
        or not); None when the base cannot be listed (no checkpoint
        yet, or an exotic store)."""
        from ..io.filesys import FileSystem
        from ..io.uri import URI

        base = URI(self.base if "://" in self.base
                   else "file://" + self.base)
        try:
            fs = FileSystem.get_instance(base)
            entries = fs.list_directory(base)
        except OSError:
            return None
        steps = []
        for f in entries:
            name = f.path.name.rstrip("/").rsplit("/", 1)[-1]
            m = re.match(r"^step_(\d+)$", name)
            if m:
                steps.append(int(m.group(1)))
        return steps

    def _committed_steps(self) -> List[int]:
        """Committed step numbers, newest first (empty when the base
        cannot be listed — the LATEST-hint fallback covers that)."""
        steps = self._step_dirs()
        if steps is None:
            return []
        return [s for s in sorted(steps, reverse=True)
                if self._has_manifest(s)]

    def latest_step(self) -> Optional[int]:
        """Newest step with a COMMITTED manifest.  Directory scan, not
        the LATEST pointer: after a preemption mid-save the newest step
        dir is torn (shards, no manifest) and must be skipped."""
        steps = self._step_dirs()
        if steps is None:
            # unlistable store: fall back to the LATEST hint, but still
            # require its manifest to be committed
            s = Stream.create(_join(self.base, "LATEST"), "r",
                              allow_null=True)
            if s is None:
                return None
            with s:
                raw = s.read(64).strip()
            if not raw:
                return None
            step = int(raw)
            return step if self._has_manifest(step) else None
        for step in sorted(steps, reverse=True):
            if self._has_manifest(step):
                return step
        return None

    def restore_latest(self, template: Any, *, mesh=None):
        """Restore the newest committed checkpoint, falling back a step
        when a restore fails its shard digests (a silently flipped shard
        must cost ONE checkpoint interval, not the job): each committed
        step is tried newest-first; a corrupt one is logged and the next
        older committed step restores instead.  Raises only when every
        committed checkpoint is corrupt.  Only :class:`CorruptCheckpoint`
        triggers the fallback — transient read errors and template
        mismatches propagate rather than silently discarding the newest
        committed step."""
        candidates = self._committed_steps()
        if not candidates:
            step = self.latest_step()  # unlistable store: LATEST hint
            if step is None:
                return None, None
            candidates = [step]
        last_err: Optional[DMLCError] = None
        for step in candidates:
            try:
                return step, restore_pytree(self._step_dir(step),
                                            template, mesh=mesh)
            except CorruptCheckpoint as e:
                from ..logging import warning

                last_err = e
                warning(f"checkpoint step {step} failed to restore "
                        f"({e}); falling back to the previous "
                        f"committed step")
        raise DMLCError(
            f"no committed checkpoint under {self.base} restored "
            f"cleanly (last error: {last_err})")

    def _retain(self) -> None:
        import shutil

        if not os.path.isdir(self.base):
            return  # retention is local-only; object stores keep all
        committed, torn = [], []
        for name in os.listdir(self.base):
            m = re.match(r"^step_(\d+)$", name)
            if m:
                step = int(m.group(1))
                (committed if self._has_manifest(step)
                 else torn).append(step)
        # keep the newest max_to_keep COMMITTED checkpoints: a torn dir
        # (preempted save) must never push a restorable step out of the
        # retention window
        for old in sorted(committed)[: -self.max_to_keep or None]:
            shutil.rmtree(self._step_dir(old), ignore_errors=True)
        # torn dirs older than the newest committed step are dead
        # litter (their save will never be completed); newer ones may
        # be another process's save in flight — leave those alone
        if committed:
            for step in torn:
                if step < max(committed):
                    shutil.rmtree(self._step_dir(step),
                                  ignore_errors=True)
