"""Host-side memory pools: recyclable buffers for ingest/feed consumers.

Rebuild of the reference's allocator layer (include/dmlc/memory.h:22-261:
``MemoryPool`` — fixed-size pieces carved from page-sized arenas —
``ThreadlocalAllocator``, and the thread-local object pool behind
``ThreadlocalSharedPtr``).  The TPU-native role is host-buffer
recycling: ingestion and device feeds allocate the same large numpy
buffers every batch, and Python's allocator returns MB-sized blocks to
the OS between uses, so steady-state pipelines pay repeated
page-faulting.  These pools keep hot buffers alive instead.

Design deviations from the reference (deliberate):
  - buffers are numpy uint8 arrays, not raw pointers — every consumer
    here speaks the buffer protocol, and a leaked buffer is garbage
    collected instead of leaked (the reference FreeSpace model cannot
    reclaim a lost pointer);
  - ``BufferPool`` adds power-of-two size classes (the reference pool
    is single-size) because feed/parse buffers vary with batch shape;
  - pools are bounded (``max_bytes``) so a burst cannot pin unbounded
    memory — overflow buffers are simply dropped to the GC.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from .base import check
from .concurrency import make_lock

__all__ = ["MemoryPool", "BufferPool", "ThreadLocalPool"]


class MemoryPool:
    """Fixed-size buffer pool (memory.h:22-77 role).

    ``alloc()`` returns a uint8 array of exactly ``obj_size`` bytes;
    ``free(buf)`` recycles it.  Buffers are carved from arenas of
    ``arena_objects`` pieces so a million small allocs don't mean a
    million numpy allocations — the reference's page-chunk move.
    """

    def __init__(self, obj_size: int, *, arena_objects: int = 64,
                 max_free: int = 1024):
        check(obj_size > 0, "MemoryPool: obj_size must be positive")
        self.obj_size = int(obj_size)
        self._arena_objects = max(1, int(arena_objects))
        self._max_free = int(max_free)
        self._free: List[np.ndarray] = []    # returned via free()
        self._fresh: List[np.ndarray] = []   # carved, never handed out
        self._lock = make_lock("MemoryPool._lock")
        self.allocated = 0   # total pieces handed out over the lifetime
        self.recycled = 0    # pieces that went through free() and back

    def _grow(self) -> None:
        arena = np.empty(self.obj_size * self._arena_objects, np.uint8)
        self._fresh.extend(
            arena[i * self.obj_size:(i + 1) * self.obj_size]
            for i in range(self._arena_objects))

    def alloc(self) -> np.ndarray:
        with self._lock:
            self.allocated += 1
            if self._free:
                self.recycled += 1
                return self._free.pop()
            if not self._fresh:
                self._grow()
            return self._fresh.pop()

    def free(self, buf: np.ndarray) -> None:
        check(buf.nbytes == self.obj_size,
              "MemoryPool.free: buffer is not from this pool")
        with self._lock:
            if len(self._free) < self._max_free:
                self._free.append(buf)


class BufferPool:
    """Size-class buffer recycler for variable-size consumers.

    ``acquire(nbytes)`` returns a uint8 array of AT LEAST nbytes
    (rounded up to the next power of two, so reuse hits are frequent);
    ``release(buf)`` returns it for reuse.  Total retained bytes are
    bounded by ``max_bytes``; anything beyond is dropped to the GC.
    Thread-safe — one pool can serve every parser/feed thread.
    """

    def __init__(self, *, max_bytes: int = 256 << 20):
        self._classes: Dict[int, List[np.ndarray]] = {}
        self._lock = make_lock("BufferPool._lock")
        self._max_bytes = int(max_bytes)
        self._held = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _cls(nbytes: int) -> int:
        return 1 << max(6, (int(nbytes) - 1).bit_length())  # >= 64 B

    def acquire(self, nbytes: int) -> np.ndarray:
        check(nbytes >= 0, "BufferPool.acquire: negative size")
        c = self._cls(max(nbytes, 1))
        with self._lock:
            lst = self._classes.get(c)
            if lst:
                self.hits += 1
                self._held -= c
                return lst.pop()
            self.misses += 1
        return np.empty(c, np.uint8)

    def release(self, buf: np.ndarray) -> None:
        n = buf.nbytes
        # only whole, owning uint8 arrays of a pool size class come
        # back: foreign dtypes would make acquire() hand out wrongly-
        # typed buffers, and a sliced view would pin its entire base
        # array while held_bytes counts only the slice
        if (n & (n - 1) or n < 64 or buf.dtype != np.uint8
                or buf.base is not None or buf.ndim != 1):
            return  # not one of ours: let the GC have it
        with self._lock:
            if self._held + n > self._max_bytes:
                return
            self._held += n
            self._classes.setdefault(n, []).append(buf)

    @property
    def held_bytes(self) -> int:
        with self._lock:
            return self._held


class ThreadLocalPool:
    """Per-thread BufferPool facade (ThreadlocalAllocator role,
    memory.h:85-124): no lock contention on the hot path because every
    thread recycles through its own pool.  Suitable for buffers that do
    not cross threads (parse scratch, per-thread chunk staging)."""

    def __init__(self, *, max_bytes_per_thread: int = 64 << 20):
        self._tls = threading.local()
        self._max = int(max_bytes_per_thread)

    def _pool(self) -> BufferPool:
        p: Optional[BufferPool] = getattr(self._tls, "pool", None)
        if p is None:
            p = BufferPool(max_bytes=self._max)
            self._tls.pool = p
        return p

    def acquire(self, nbytes: int) -> np.ndarray:
        return self._pool().acquire(nbytes)

    def release(self, buf: np.ndarray) -> None:
        self._pool().release(buf)
