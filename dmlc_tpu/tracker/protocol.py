"""Tracker wire protocol + overlay topology math.

Wire format (compatible with the reference tracker protocol,
tracker/dmlc_tracker/tracker.py:24-50): native-endian int32 frames;
strings as [len:int32][utf8 bytes]; sessions open with an exchange of
the magic 0xff99.

Topology (tracker.py:165-252 behavior): a binomial tree over ranks
(heap-shaped: children of r are 2r+1, 2r+2; parent (r+1)//2-1) plus a
ring that shares edges with the tree, found by DFS; ranks are then
relabeled to follow ring order so rank r's ring neighbours are
(r-1, r+1) mod n — which is also what makes the contract line up with
ICI torus neighbours when ranks map to mesh coordinates.
"""

from __future__ import annotations

import socket
import struct
from typing import Dict, List, Tuple

MAGIC = 0xFF99

_INT = struct.Struct("@i")


def recover_cmd(gen: int) -> str:
    """Announce command for an elastic re-rendezvous: ``recover@<gen>``
    where ``<gen>`` is the generation the worker's current rank belongs
    to.  The base announce wire format (rank, world, jobid, cmd) is
    untouched — the generation rides inside the free-form command
    string, so the C-ABI workers (cpp/dmlc_collective.cc speaks the
    plain ``start``/``recover`` protocol byte-for-byte) never see it."""
    return f"recover@{int(gen)}"


def parse_worker_cmd(cmd: str):
    """``(base_cmd, announced_gen)`` for an announce command.

    ``recover@3`` → ``("recover", 3)``; ``shutdown@3`` likewise (an
    elastic worker's rank is meaningful only relative to a generation,
    and a finishing worker may not have re-brokered into the newest
    one).  Every other command (including plain ``recover``, which
    means "my rank is from the CURRENT generation" — the reference
    same-rank restart semantics) parses to ``(cmd, None)``."""
    base, sep, gen = cmd.partition("@")
    if sep and base in ("recover", "shutdown") and gen.isdigit():
        return base, int(gen)
    return cmd, None


class FrameSocket:
    """int32/string framing over a TCP socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock

    def recv_all(self, nbytes: int) -> bytes:
        chunks = []
        got = 0
        while got < nbytes:
            c = self.sock.recv(min(nbytes - got, 65536))
            if not c:
                raise ConnectionError("peer closed mid-frame")
            got += len(c)
            chunks.append(c)
        return b"".join(chunks)

    def recv_int(self) -> int:
        return _INT.unpack(self.recv_all(4))[0]

    def send_int(self, v: int) -> None:
        self.sock.sendall(_INT.pack(v))

    # strings on this protocol are hostnames/jobids/log lines; a length
    # outside this bound is a corrupt or hostile frame, and reading it
    # as a buffer size would stall the tracker mid-allocation
    MAX_STR = 1 << 20

    def send_str(self, s: str) -> None:
        data = s.encode()
        self.send_int(len(data))
        self.sock.sendall(data)

    def recv_str(self) -> str:
        n = self.recv_int()
        if not 0 <= n <= self.MAX_STR:
            raise ConnectionError(f"bad string frame length {n}")
        return self.recv_all(n).decode()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Overlay topology
# ---------------------------------------------------------------------------

def binomial_tree(n: int) -> Tuple[Dict[int, List[int]], Dict[int, int]]:
    """Heap-shaped binomial tree: (neighbor_map, parent_map)."""
    tree: Dict[int, List[int]] = {}
    parent: Dict[int, int] = {}
    for r in range(n):
        nbrs = []
        if r > 0:
            nbrs.append((r + 1) // 2 - 1)
        if 2 * r + 1 < n:
            nbrs.append(2 * r + 1)
        if 2 * r + 2 < n:
            nbrs.append(2 * r + 2)
        tree[r] = nbrs
        parent[r] = (r + 1) // 2 - 1  # -1 for root
    return tree, parent


def _dfs_ring(tree: Dict[int, List[int]], parent: Dict[int, int], r: int) -> List[int]:
    """DFS order that tends to share edges with the tree (tracker.py:193-210
    behavior, including the reversed-last-child walk)."""
    children = [v for v in tree[r] if v != parent[r]]
    order = [r]
    for i, v in enumerate(children):
        sub = _dfs_ring(tree, parent, v)
        if i == len(children) - 1:
            sub.reverse()
        order += sub
    return order


def link_maps(n: int):
    """(tree_map, parent_map, ring_map) with ranks relabeled to ring order.

    After relabeling, ring_map[r] == ((r-1) % n, (r+1) % n); tree edges
    are expressed in the new labels.
    """
    if n == 0:
        # an elastic world can shrink to nothing (every member lost or
        # cleanly finished); an empty overlay is valid, not an error
        return {}, {}, {}
    tree, parent = binomial_tree(n)
    order = _dfs_ring(tree, parent, 0)
    assert len(order) == n
    relabel = {old: new for new, old in enumerate(order)}
    tree2 = {relabel[r]: [relabel[v] for v in vs] for r, vs in tree.items()}
    parent2 = {
        relabel[r]: (relabel[p] if p >= 0 else -1) for r, p in parent.items()
    }
    ring2 = {r: ((r - 1) % n, (r + 1) % n) for r in range(n)}
    return tree2, parent2, ring2


def resolve_ip(host: str) -> str:
    return socket.getaddrinfo(host, None)[0][4][0]
