"""Launch backends: start worker/server processes on a cluster.

Each backend exposes submit(args) and builds per-task environments from
the DMLC env contract (reference §2.7: DMLC_ROLE, DMLC_TASK_ID,
DMLC_NUM_ATTEMPT, DMLC_JOB_CLUSTER, DMLC_NODE_HOST, tracker URI/PORT,
worker/server counts).  Command construction is factored out of
execution so every backend is unit-testable without a cluster.

The ``tpu-vm`` backend is the YARN ApplicationMaster analog
(yarn/src/.../ApplicationMaster.java:49-687 behavior): per-task attempt
counters, restart budget, failing-host blacklist — mapped onto
preemptible TPU VM slices reached by ssh.
"""

from __future__ import annotations

import logging
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional, Sequence

from .rendezvous import submit_job
from ..concurrency import make_lock

logger = logging.getLogger("dmlc_tpu.tracker")

# Env vars forwarded to remote tasks (reference ssh.py:26 plus JAX/TPU
# plus every DMLC_* knob workers must see).  The DMLC_* entries mirror
# config_registry.py's pass_to_workers knobs — a knob a worker reads
# but the launcher does not forward works locally and silently does
# nothing on ssh/tpu-vm (the PR 7/9 gang-uniform DMLC_COLL_* cutovers
# depend on forwarding) — and scripts/dmlc_check.py's knob pass fails
# CI when the two lists drift.  Kept explicit rather than imported:
# the ssh export line is security-sensitive, so what it ships should
# be reviewable here, not computed at launch time.
PASS_ENVS = [
    "OMP_NUM_THREADS", "LD_LIBRARY_PATH", "PYTHONPATH",
    "AWS_ACCESS_KEY_ID", "AWS_SECRET_ACCESS_KEY",
    "GOOGLE_APPLICATION_CREDENTIALS", "JAX_PLATFORMS", "XLA_FLAGS",
    "TPU_WORKER_ID", "TPU_WORKER_HOSTNAMES",
    # -- registry pass_to_workers knobs (config_registry.py order) ----
    "DMLC_INTERFACE", "DMLC_FEED_WORKERS", "DMLC_FEED_DEPTH",
    "DMLC_FEED_AUTOTUNE", "DMLC_FEED_WORKERS_MIN",
    "DMLC_FEED_WORKERS_MAX", "DMLC_FEED_DEPTH_MAX",
    "DMLC_TPU_PARSE_NTHREAD", "DMLC_TPU_DISABLE_NATIVE",
    "DMLC_TPU_DISABLE_MMAP", "DMLC_COLL_ALGO", "DMLC_COLL_BUCKET_MB",
    "DMLC_COLL_RING_MIN_BYTES", "DMLC_COLL_HIER_MIN_BYTES",
    "DMLC_COLL_HIER_GROUPS", "DMLC_COLL_HIER_SETUP_TIMEOUT_S",
    "DMLC_COLL_SHM", "DMLC_COLL_SHM_CHUNK_KB",
    "DMLC_COLL_SHM_JOIN_TIMEOUT_S", "DMLC_COLL_SHM_TIMEOUT_S",
    "DMLC_COLL_OVERLAP", "DMLC_CLIENT_CONNECT_TIMEOUT_S",
    "DMLC_CLIENT_OP_TIMEOUT_S", "DMLC_CLIENT_RETRIES",
    "DMLC_CLIENT_RETRY_BASE_S", "DMLC_ELASTIC", "DMLC_ELASTIC_GRACE_S",
    "DMLC_ELASTIC_RESIZE_TIMEOUT_S", "DMLC_S3_ENDPOINT",
    "DMLC_S3_RETRIES", "DMLC_S3_WRITE_BUFFER_MB", "DMLC_GCS_RETRIES",
    "DMLC_GCS_RETRY_BASE_S", "DMLC_GCS_WRITE_BUFFER_MB",
    "DMLC_AZURE_ENDPOINT", "DMLC_AZURE_RETRIES", "DMLC_AZURE_BLOCK_MB",
    "DMLC_HDFS_USER", "DMLC_HDFS_RETRIES", "DMLC_HDFS_WRITE_BUFFER_MB",
    "DMLC_WEBHDFS_ENDPOINT", "DMLC_WEBHDFS_PORT", "DMLC_HTTP_RETRIES",
    "DMLC_REST_RETRIES", "DMLC_REST_TIMEOUT_S", "DMLC_RETRY_ATTEMPTS",
    "DMLC_RETRY_MAX_S", "DMLC_RETRY_DEADLINE_S",
    "DMLC_RECORDIO_CHECKSUM", "DMLC_INTEGRITY_POLICY",
    "DMLC_INTEGRITY_VERIFY_READS", "DMLC_INTEGRITY_READ_RETRIES",
    "DMLC_SELFHEAL_MAX_SKIPS", "DMLC_SELFHEAL_MAX_ROLLBACKS",
    "DMLC_SELFHEAL_SPIKE_FACTOR", "DMLC_SELFHEAL_WARMUP",
    "DMLC_FAULT_SPEC", "DMLC_TELEMETRY_MAX_SPANS",
    "DMLC_TELEMETRY_MAX_EVENTS", "DMLC_TELEMETRY_SHIP_TRACE",
    "DMLC_TELEMETRY_MAX_BEAT_BYTES", "DMLC_POSTMORTEM_DIR",
    "DMLC_STEP_LEDGER_MAX", "DMLC_PEAK_FLOPS", "DMLC_PEAK_HBM_GBPS",
    "DMLC_COMPUTE_PROFILE", "DMLC_COMPUTE_TRACE_PHASES",
    "DMLC_COMPUTE_STORM_WINDOW_S", "DMLC_COMPUTE_STORM_TRACES",
    "DMLC_TRACE_FLEET", "DMLC_TRACE_EXEMPLARS",
    "DMLC_GOODPUT_MIN_FRACTION", "DMLC_GOODPUT_WINDOW_S",
    "DMLC_GOODPUT_MAX_INTERVALS",
    "DMLC_LOCKCHECK",
    "DMLC_LOCKCHECK_BLOCK_S", "DMLC_RACECHECK",
    "DMLC_RACECHECK_MAX_SITES", "DMLC_FLASH_BH_BLOCK",
    "DMLC_FLASH_BLOCK_Q", "DMLC_FLASH_BLOCK_K",
    "DMLC_FLASH_BWD_BLOCK_Q", "DMLC_FLASH_BWD_BLOCK_K",
]


def _elastic() -> bool:
    from ..base import get_env

    return get_env("DMLC_ELASTIC", False)


_postmortem_scan_lock = make_lock("launch._postmortem_scan_lock")


def collect_postmortems(seen: set, role: str, task_id,
                        log=logger) -> List[str]:
    """Collect postmortem dumps that appeared since the last scan.

    Called after a task attempt fails: any fresh dump in
    ``DMLC_POSTMORTEM_DIR`` is a dead incarnation's flight record — its
    reason, recorded rank, open spans, and event tail are summarized
    into the launcher log (the full JSON stays on disk) and counted as
    ``resilience.postmortems_collected``.  Best-effort: a no-op when no
    directory is configured, and an unreadable dump is reported, not
    fatal.  ``seen`` must be ONE set shared by every task of the job
    (the directory is shared too): the claim under the module lock is
    what keeps concurrent failing tasks from double-counting each
    other's dumps.  Attribution in the log comes from the dump's own
    recorded rank — the scanning task merely noticed it; which rank
    died is the dump's to say."""
    import json as _json

    from .. import telemetry
    from ..telemetry import postmortem

    with _postmortem_scan_lock:
        fresh = [p for p in postmortem.list_dumps() if p not in seen]
        seen.update(fresh)
    for p in fresh:
        summary = ""
        try:
            with open(p) as f:
                doc = _json.load(f)
            open_names = [s.get("name") for s in doc.get("open_spans", [])]
            tail = [e.get("kind") for e in doc.get("events", [])[-5:]]
            summary = (f": rank={doc.get('rank')} "
                       f"reason={doc.get('reason')!r} "
                       f"open_spans={open_names} event_tail={tail}")
        except (OSError, ValueError) as e:
            summary = f" (unreadable: {e})"
        log.warning("postmortem collected (scan after %s %s failed) %s%s",
                    role, task_id, p, summary)
    if fresh:
        telemetry.inc("resilience", "postmortems_collected", len(fresh))
    return fresh


def task_env(base: Dict[str, str], role: str, task_id: Optional[int],
             attempt: int, cluster: str,
             extra: Optional[Dict[str, str]] = None,
             resources: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """Per-task env. task_id=None omits DMLC_TASK_ID — required for
    mpi/slurm where one launch command covers many ranks: a shared task
    id would collapse the tracker's job_map rank keying (every worker
    would present jobid "0" and steal each other's rank on recover)."""
    env = dict(base)
    env.update({
        "DMLC_ROLE": role,
        "DMLC_NUM_ATTEMPT": str(attempt),
        "DMLC_JOB_CLUSTER": cluster,
    })
    if task_id is not None:
        env["DMLC_TASK_ID"] = str(task_id)
    if resources:
        env.update(resources)
    if extra:
        env.update(extra)
    return env


def resource_envs(args, role: str) -> Dict[str, str]:
    """DMLC_{WORKER,SERVER}_{CORES,MEMORY_MB} env contract (the reference
    yarn backend sets these, yarn.py:16-118)."""
    if role == "server":
        return {"DMLC_SERVER_CORES": str(args.server_cores),
                "DMLC_SERVER_MEMORY_MB": str(args.server_memory_mb)}
    return {"DMLC_WORKER_CORES": str(args.worker_cores),
            "DMLC_WORKER_MEMORY_MB": str(args.worker_memory_mb)}


def _roles(n_workers: int, n_servers: int):
    return [("server", i) for i in range(n_servers)] + [
        ("worker", i) for i in range(n_workers)
    ]


# ---------------------------------------------------------------------------
# local
# ---------------------------------------------------------------------------

def _await_job(tracker, failures, threads):
    """Wait for tracker completion, aborting early on task failures.

    A failed task never sends 'shutdown', so a blind tracker join would
    hang forever — poll both."""
    import time

    def abort(msg):
        # a lingering PS scheduler child would hold the launcher's
        # stdio pipes open past our exit — kill it before raising
        if tracker is not None and hasattr(tracker, "terminate"):
            tracker.terminate()
        raise RuntimeError(msg)

    while True:
        if failures:
            abort(f"tasks failed: {failures}")
        if tracker is not None and getattr(tracker, "error", None) is not None:
            abort(f"tracker failed: {tracker.error}")
        tracker_done = tracker is None or not tracker.alive()
        if tracker_done and all(not t.is_alive() for t in threads):
            break
        time.sleep(0.05)
    if failures:
        abort(f"tasks failed: {failures}")
    if tracker is not None and getattr(tracker, "error", None) is not None:
        abort(f"tracker failed: {tracker.error}")
    return tracker


def submit_local(args):
    """Threads × subprocess with per-task retry (reference local.py:12-72)."""
    failures = []
    threads = []
    procs: List[subprocess.Popen] = []

    def fun_submit(n_workers, n_servers, envs):
        collected: set = set()  # shared: ONE claim set for the whole job

        def run_task(role, task_id):
            from .. import telemetry

            for attempt in range(args.max_attempts):
                env = os.environ.copy()
                env.update(task_env(envs, role, task_id, attempt, "local",
                                    args.extra_env,
                                    resource_envs(args, role)))
                p = subprocess.Popen(args.command, env=env)
                procs.append(p)
                ret = p.wait()
                if ret == 0:
                    return
                logger.warning("%s %d attempt %d exited %d", role, task_id,
                               attempt, ret)
                # a failed task may have left its flight record behind
                collect_postmortems(collected, role, task_id)
                if attempt + 1 < args.max_attempts:
                    # supervised restart: visible on the tracker's
                    # /metrics as dmlc_resilience_task_restarts
                    telemetry.inc("resilience", "task_restarts")
                    telemetry.record_event("task_restart", role=role,
                                           task_id=task_id,
                                           attempt=attempt, exit=ret)
            telemetry.inc("resilience", "task_budget_exhausted")
            telemetry.record_event("task_budget_exhausted", role=role,
                                   task_id=task_id,
                                   attempts=args.max_attempts)
            if _elastic():
                # the world already resized past this task (or will at
                # the grace window); the survivors carry the job, so a
                # permanently-lost rank is not a job failure
                logger.warning(
                    "%s %d restart budget exhausted; elastic world "
                    "resizes past it and the job continues", role,
                    task_id)
                return
            failures.append((role, task_id, args.max_attempts))

        for role, tid in _roles(n_workers, n_servers):
            t = threading.Thread(target=run_task, args=(role, tid), daemon=True)
            t.start()
            threads.append(t)

    try:
        tracker = submit_job(args.num_workers, args.num_servers, fun_submit,
                             host_ip=args.host_ip or "127.0.0.1",
                             pscmd=_pscmd(args), join=False)
        return _await_job(tracker, failures, threads)
    except Exception:
        # an aborting job must not orphan still-running task processes
        # (e.g. workers blocking on a scheduler that died at startup)
        for p in procs:
            if p.poll() is None:
                p.terminate()
        raise


def _pscmd(args) -> Optional[str]:
    """PS jobs run the user command as the scheduler too (DMLC_ROLE=
    scheduler), the reference local.py/ssh.py pscmd contract."""
    import shlex

    if args.num_servers > 0:
        return shlex.join(args.command)
    return None


# ---------------------------------------------------------------------------
# ssh / tpu-vm shared machinery
# ---------------------------------------------------------------------------

def read_host_file(path: str) -> List[str]:
    hosts = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                hosts.append(line)
    if not hosts:
        raise ValueError(f"no hosts in {path}")
    return hosts


def build_ssh_cmd(host: str, command: Sequence[str], env: Dict[str, str],
                  sync_dst_dir: Optional[str] = None) -> List[str]:
    """One ssh invocation running `command` on `host` with env exported.

    Forwards the task's DMLC_* contract plus the launcher's own PASS_ENVS
    values from os.environ (reference ssh.py:26 behavior)."""
    hostname, _, port = host.partition(":")
    full = {k: os.environ[k] for k in PASS_ENVS if k in os.environ}
    full.update(env)
    exports = "; ".join(
        f"export {k}={v!r}" for k, v in sorted(full.items())
        if k.startswith("DMLC_") or k in PASS_ENVS
    )
    cd = f"cd {sync_dst_dir}; " if sync_dst_dir else ""
    remote = f"{exports}; {cd}{' '.join(command)}"
    cmd = ["ssh", "-o", "StrictHostKeyChecking=no", hostname]
    if port:
        cmd += ["-p", port]
    cmd.append(remote)
    return cmd


class GangScheduler:
    """Task scheduler with attempt budget + host blacklist (YARN-AM analog).

    ``runner(host, role, task_id, env) -> int`` performs one task attempt
    and returns its exit code; injected so tests (and backends) choose
    the transport.  A host accumulating ``blacklist_after`` failures is
    excluded from future placements (ApplicationMaster.java:554 behavior);
    tasks are re-queued until the per-task attempt budget is exhausted.
    """

    def __init__(self, hosts: List[str], runner: Callable,
                 max_attempts: int = 3, blacklist_after: int = 2):
        self.hosts = list(hosts)
        self.runner = runner
        self.max_attempts = max_attempts
        self.blacklist_after = blacklist_after
        self.host_failures: Dict[str, int] = {}
        self.blacklist: set = set()
        self._collected: set = set()  # postmortems: one claim set per job
        self._lock = make_lock("GangScheduler._lock")

    def _pick_host(self, idx: int) -> str:
        with self._lock:
            live = [h for h in self.hosts if h not in self.blacklist]
            if not live:
                raise RuntimeError("all hosts blacklisted")
            return live[idx % len(live)]

    def _pick_host_for(self, role: str, task_id: int, attempt: int) -> str:
        # worker 0 stays on live[0] across retries: its host is exported
        # to the whole job as DMLC_JAX_COORD_URI before placement, so
        # moving it on a transient failure would strand the
        # jax.distributed coordinator address.  (Blacklisting hosts[0]
        # still shifts it — the coordinator URI then goes stale, the one
        # unrecoverable corner of pre-announced coordination.)  Other
        # tasks rotate hosts on retry.
        if role == "worker" and task_id == 0:
            return self._pick_host(0)
        return self._pick_host(task_id + attempt)

    def _record(self, host: str, ok: bool) -> None:
        with self._lock:
            if ok:
                return
            self.host_failures[host] = self.host_failures.get(host, 0) + 1
            if self.host_failures[host] >= self.blacklist_after \
                    and host not in self.blacklist:
                self.blacklist.add(host)
                logger.warning("blacklisted host %s", host)
                from .. import telemetry

                telemetry.inc("resilience", "hosts_blacklisted")

    def run_task(self, role: str, task_id: int, envs: Dict[str, str],
                 cluster: str, extra_env=None) -> None:
        from .. import telemetry

        for attempt in range(self.max_attempts):
            host = self._pick_host_for(role, task_id, attempt)
            env = task_env(envs, role, task_id, attempt, cluster, extra_env)
            env["DMLC_NODE_HOST"] = host
            ret = self.runner(host, role, task_id, env)
            self._record(host, ret == 0)
            if ret == 0:
                return
            logger.warning("%s %d attempt %d on %s exited %d",
                           role, task_id, attempt, host, ret)
            # only finds dumps on a filesystem this process can see
            # (shared FS, or local-transport tests); remote-only dumps
            # stay on the failing host for manual collection
            collect_postmortems(self._collected, role, task_id)
            if _elastic():
                # elastic job: the WORLD survived this task's loss (the
                # tracker shrinks past it at the grace window); the
                # reschedule below is a gang-reschedule of the lost
                # slice — it re-joins as a same-rank readmission inside
                # grace, or as a scale-up generation after eviction,
                # never by restarting the surviving world
                telemetry.inc("elastic", "gang_reschedules")
                telemetry.record_event(
                    "elastic_gang_reschedule", role=role,
                    task_id=task_id, host=host, attempt=attempt,
                    exit=ret)
            if attempt + 1 < self.max_attempts:
                # supervised restart onto a (possibly different) healthy
                # host; surfaces as dmlc_resilience_task_restarts
                telemetry.inc("resilience", "task_restarts")
                telemetry.record_event("task_restart", role=role,
                                       task_id=task_id, attempt=attempt,
                                       host=host, exit=ret)
        telemetry.inc("resilience", "task_budget_exhausted")
        telemetry.record_event("task_budget_exhausted", role=role,
                               task_id=task_id,
                               attempts=self.max_attempts)
        if _elastic():
            # elastic jobs outlive a permanently-lost slice: the world
            # shrank past it at the grace window, survivors keep going
            logger.warning(
                "%s %d restart budget exhausted; elastic world resizes "
                "past it and the job continues", role, task_id)
            return
        raise RuntimeError(
            f"{role} {task_id} failed after {self.max_attempts} attempts")

    def run_all(self, n_workers: int, n_servers: int, envs, cluster,
                extra_env=None) -> None:
        errors = []

        def run(role, tid):
            try:
                self.run_task(role, tid, envs, cluster, extra_env)
            except Exception as e:
                errors.append((role, tid, e))

        threads = [
            threading.Thread(target=run, args=(role, tid), daemon=True)
            for role, tid in _roles(n_workers, n_servers)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise RuntimeError(f"tasks failed: {errors}")


def _ssh_call(cmd: List[str]) -> int:
    """One transport invocation; module-level so tests can fake it."""
    return subprocess.call(cmd)


def _copy_to_host(host: str, paths: Sequence[str], dest: str) -> None:
    """Ship ``paths`` into ``dest/`` on ``host`` (module-level: fakeable).

    The remote dir is created via --rsync-path (portable back to old
    rsync, unlike --mkpath which needs >= 3.2.3)."""
    hostname = host.partition(":")[0]
    subprocess.check_call(
        ["rsync", "-az", f"--rsync-path=mkdir -p {dest!r} && rsync",
         *paths, f"{hostname}:{dest}/"])


def _copy_to_hosts_excluding(hosts: List[str], paths: Sequence[str],
                             dest: str, what: str) -> List[str]:
    """Ship ``paths`` to every host; a failing host is EXCLUDED with a
    warning rather than fatal (host failure is the GangScheduler
    blacklist's job).  Raises only when every host fails."""
    ok = []
    for h in hosts:
        try:
            _copy_to_host(h, paths, dest)
            ok.append(h)
        except Exception as e:  # noqa: BLE001
            logger.warning("%s to %s failed, excluding host: %s", what, h, e)
    if not ok:
        raise RuntimeError(f"{what} failed on every host: {hosts}")
    return ok


def _make_ssh_runner(command: Sequence[str], sync_dst_dir=None):
    def runner(host, role, task_id, env):
        cmd = build_ssh_cmd(host, command, env, sync_dst_dir)
        return _ssh_call(cmd)
    return runner


def _stage_cache(args, hosts: List[str]):
    """Auto file cache (reference opts.py:6-36,110-124): ship command
    files / --files / --archives plus the bootstrap script to a job
    cache dir on every host; the remote command becomes
    ``python3 ./bootstrap.py <rewritten command>`` running from there.

    Returns (remote_command, remote_dir, extra_env, staged_hosts); a
    no-op (original command, --sync-dst-dir, {}, hosts) when nothing
    needs shipping.  Hosts where staging fails are excluded (with a
    warning) rather than aborting — host failure is the GangScheduler
    blacklist's job; only all-hosts-failed raises.
    """
    from .opts import cache_file_set

    fset, rewritten = cache_file_set(args)
    archives = list(getattr(args, "archives", []))
    for a in archives:
        if not os.path.exists(a):
            raise FileNotFoundError(f"--archives {a!r} does not exist")
    if not fset and not archives:
        return list(args.command), args.sync_dst_dir, {}, hosts
    dest = args.sync_dst_dir or "/tmp/dmlc-cache-{}".format(
        args.jobname or os.getpid())
    bootstrap = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "bootstrap.py")
    paths = sorted(fset) + archives + [bootstrap]
    # the cache dir is flat: ANY staged basename collision (files,
    # archives, or the launcher's own bootstrap.py) is a silent clobber
    by_base: Dict[str, str] = {}
    for p in paths:
        base = os.path.basename(p)
        if base in by_base and by_base[base] != p:
            raise ValueError(
                f"staged files {by_base[base]!r} and {p!r} collide on "
                f"basename {base!r} in the flat job cache dir")
        by_base[base] = p
    ok_hosts = _copy_to_hosts_excluding(hosts, paths, dest,
                                        "file-cache staging")
    extra_env = {"DMLC_JOB_CACHE_DIR": dest}
    if archives:
        extra_env["DMLC_JOB_ARCHIVES"] = ":".join(
            os.path.basename(a) for a in archives)
    return (["python3", "./bootstrap.py", "--"] + rewritten, dest,
            extra_env, ok_hosts)


def submit_ssh(args):
    """ssh backend (reference ssh.py:37-86), via GangScheduler for retry."""
    hosts = read_host_file(args.host_file)
    if args.sync_dst_dir:  # whole-workdir sync (reference ssh.py:13-21)
        hosts = _copy_to_hosts_excluding(
            hosts, [os.getcwd() + "/"], args.sync_dst_dir, "workdir sync")
    command, remote_dir, cache_env, hosts = _stage_cache(args, hosts)
    sched = GangScheduler(hosts, _make_ssh_runner(command, remote_dir),
                          max_attempts=args.max_attempts)
    return _submit_gang(args, sched, "ssh", cache_env, coord_host=hosts[0])


def submit_tpu_vm(args):
    """Gang-schedule onto TPU VM slice hosts with preemption-aware retry.

    The TPU-native stand-in for the YARN backend: slice hosts come from
    --host-file (e.g. `gcloud compute tpus tpu-vm list` output); tasks are
    placed round-robin with attempt counters and failing-host blacklist.

    With ``DMLC_ELASTIC=1`` a preempted slice no longer restarts the
    world: the tracker runs elastic resize generations, so while this
    scheduler gang-reschedules the lost tasks onto healthy hosts
    (``dmlc_elastic_gang_reschedules``), the surviving ranks shrink to
    N-1 at the grace window and keep training; the rescheduled tasks
    re-join as a same-rank readmission (inside grace) or a scale-up
    generation (after eviction).  Every resize lands in the tracker's
    event ring and on /metrics as ``dmlc_elastic_*``.
    """
    hosts = read_host_file(args.host_file)
    command, remote_dir, cache_env, hosts = _stage_cache(args, hosts)
    sched = GangScheduler(hosts, _make_ssh_runner(command, remote_dir),
                          max_attempts=args.max_attempts)
    return _submit_gang(args, sched, "tpu-vm", cache_env, coord_host=hosts[0])


def _submit_gang(args, sched: "GangScheduler", cluster: str,
                 cache_env: Optional[Dict[str, str]] = None,
                 coord_host: Optional[str] = None):
    failures = []
    threads = []
    extra = dict(args.extra_env)
    if cache_env:
        extra.update(cache_env)
    if coord_host and "DMLC_JAX_COORD_URI" not in extra:
        # task 0 (attempt 0) lands on hosts[0] (GangScheduler._pick_host),
        # so the jax.distributed coordinator service lives there, not on
        # the tracker machine
        extra["DMLC_JAX_COORD_URI"] = coord_host.partition(":")[0]

    def fun_submit(n_workers, n_servers, envs):
        def run():
            try:
                sched.run_all(n_workers, n_servers, envs, cluster, extra)
            except Exception as e:
                failures.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        threads.append(t)

    tracker = submit_job(args.num_workers, args.num_servers, fun_submit,
                         host_ip=args.host_ip or "auto",
                         pscmd=_pscmd(args), join=False)
    return _await_job(tracker, failures, threads)


# ---------------------------------------------------------------------------
# mpi / sge / slurm (thin command builders + subprocess)
# ---------------------------------------------------------------------------

def build_mpi_cmd(args, envs: Dict[str, str], n_tasks: int,
                  role: str, mpirun: str = "mpirun",
                  openmpi: bool = True) -> List[str]:
    cmd = [mpirun, "-n", str(n_tasks)]
    if args.host_file:
        cmd += ["--hostfile", args.host_file]
    # task_id=None: one mpirun covers many ranks; per-rank identity comes
    # from the tracker's rank assignment, not the env
    env = task_env(envs, role, None, 0, "mpi", args.extra_env,
                   resource_envs(args, role))
    for k, v in sorted(env.items()):
        if openmpi:
            cmd += ["-x", f"{k}={v}"]
        else:
            cmd += ["-env", k, v]
    return cmd + list(args.command)


def _reap_procs(procs, failures):
    """Wait each Popen; record non-zero exits so _await_job aborts."""
    def wait(p):
        ret = p.wait()
        if ret != 0:
            failures.append((" ".join(p.args[:3]), ret))

    threads = [threading.Thread(target=wait, args=(p,), daemon=True)
               for p in procs]
    for t in threads:
        t.start()
    return threads


def submit_mpi(args):
    failures = []
    threads = []

    def fun_submit(n_workers, n_servers, envs):
        try:
            probe = subprocess.run(["mpirun", "--version"],
                                   capture_output=True, text=True).stdout
        except FileNotFoundError as e:
            raise RuntimeError("mpirun not found on PATH") from e
        openmpi = "Open MPI" in probe
        procs = []
        if n_servers:
            procs.append(subprocess.Popen(
                build_mpi_cmd(args, envs, n_servers, "server",
                              openmpi=openmpi)))
        procs.append(subprocess.Popen(
            build_mpi_cmd(args, envs, n_workers, "worker", openmpi=openmpi)))
        threads.extend(_reap_procs(procs, failures))

    tracker = submit_job(args.num_workers, args.num_servers, fun_submit,
                         host_ip=args.host_ip or "auto",
                         pscmd=_pscmd(args), join=False)
    return _await_job(tracker, failures, threads)


def build_sge_script(args, envs: Dict[str, str], role: str) -> str:
    env = task_env(envs, role, None, 0, "sge", args.extra_env,
                   resource_envs(args, role))
    lines = ["#!/bin/bash", "#$ -S /bin/bash"]
    lines += [f"export {k}={v!r}" for k, v in sorted(env.items())]
    # SGE array task ids are 1-based (reference sge.py runscript)
    lines.append("export DMLC_TASK_ID=$((SGE_TASK_ID - 1))")
    lines.append(" ".join(args.command))
    return "\n".join(lines) + "\n"


def submit_sge(args):
    import tempfile

    def fun_submit(n_workers, n_servers, envs):
        for role, n in (("server", n_servers), ("worker", n_workers)):
            if n == 0:
                continue
            script = build_sge_script(args, envs, role)
            fd, path = tempfile.mkstemp(prefix=f"dmlc_sge_{role}_",
                                        suffix=".sh")
            with os.fdopen(fd, "w") as f:
                f.write(script)
            cmd = ["qsub", "-cwd", "-t", f"1-{n}", "-S", "/bin/bash"]
            if args.jobname:
                cmd += ["-N", args.jobname]
            if args.queue:
                cmd += ["-q", args.queue]
            if args.sge_log_dir:
                cmd += ["-o", args.sge_log_dir, "-e", args.sge_log_dir]
            subprocess.check_call(cmd + [path])

    return submit_job(args.num_workers, args.num_servers, fun_submit,
                      host_ip=args.host_ip or "auto", pscmd=_pscmd(args))


def build_mesos_cmd(args, envs: Dict[str, str], role: str,
                    task_id: int) -> List[str]:
    """One mesos-execute invocation per task (the reference's
    non-pymesos path, tracker/dmlc_tracker/mesos.py:30-57): command is
    run from the current workdir, env ships as a JSON dict, and
    cpus/mem come from the worker/server resource opts."""
    import json
    import shlex
    import uuid

    master = args.mesos_master or os.environ.get("MESOS_MASTER")
    if not master:
        raise RuntimeError("no mesos master: set --mesos-master or "
                           "MESOS_MASTER")
    if ":" not in master:
        master += ":5050"
    env = task_env(envs, role, task_id, 0, "mesos", args.extra_env,
                   resource_envs(args, role))
    # ship the scheduler-discovery whitelist the reference ships
    for k in ("OMP_NUM_THREADS", "KMP_AFFINITY", "LD_LIBRARY_PATH"):
        if k in os.environ:
            env.setdefault(k, os.environ[k])
    if role == "server":
        cores, mem = args.server_cores, args.server_memory_mb
    else:
        cores, mem = args.worker_cores, args.worker_memory_mb
    prog = f"cd {shlex.quote(os.getcwd())} && " \
           + " ".join(shlex.quote(c) for c in args.command)
    return ["mesos-execute", f"--master={master}",
            f"--name=dmlc-{role}-{task_id}-{uuid.uuid4().hex[:8]}",
            f"--command={prog}",
            f"--env={json.dumps({k: str(v) for k, v in env.items()})}",
            f"--resources=cpus:{cores};mem:{mem}"]


def submit_mesos(args):
    """mesos backend: per-task mesos-execute, gated on the binary being
    on PATH (pymesos is not bundled; reference mesos.py falls back to
    mesos-execute the same way)."""
    import shutil

    if shutil.which("mesos-execute") is None:
        raise RuntimeError(
            "mesos-execute not found on PATH (pymesos is not bundled); "
            "install Mesos CLI tools or use --cluster ssh/tpu-vm")
    logger.warning(
        "mesos-execute mode provides no task stdout/stderr here; a failed "
        "task reports only its exit code — check the Mesos agent sandbox "
        "logs for output")
    failures = []
    threads = []

    def fun_submit(n_workers, n_servers, envs):
        procs = [subprocess.Popen(build_mesos_cmd(args, envs, role, tid),
                                  stdout=subprocess.DEVNULL,
                                  stderr=subprocess.STDOUT)
                 for role, tid in _roles(n_workers, n_servers)]
        threads.extend(_reap_procs(procs, failures))

    tracker = submit_job(args.num_workers, args.num_servers, fun_submit,
                         host_ip=args.host_ip or "auto",
                         pscmd=_pscmd(args), join=False)
    return _await_job(tracker, failures, threads)


def build_slurm_cmd(args, envs: Dict[str, str], role: str,
                    n_tasks: int) -> List[str]:
    cmd = ["srun", "-n", str(n_tasks)]
    nodes = (args.slurm_worker_nodes if role == "worker"
             else args.slurm_server_nodes)
    if nodes:
        cmd += ["-N", str(nodes)]
    if args.jobname:
        cmd += ["--job-name", args.jobname]
    env = task_env(envs, role, None, 0, "slurm", args.extra_env,
                   resource_envs(args, role))
    exports = ",".join(f"{k}={v}" for k, v in sorted(env.items()))
    cmd += [f"--export=ALL,{exports}", "--kill-on-bad-exit=1"]
    return cmd + list(args.command)


def submit_slurm(args):
    """slurm backend — actually routed, unlike reference submit.py:42-53."""
    failures = []
    threads = []

    def fun_submit(n_workers, n_servers, envs):
        procs = []
        if n_servers:
            procs.append(subprocess.Popen(
                build_slurm_cmd(args, envs, "server", n_servers)))
        procs.append(subprocess.Popen(
            build_slurm_cmd(args, envs, "worker", n_workers)))
        threads.extend(_reap_procs(procs, failures))

    tracker = submit_job(args.num_workers, args.num_servers, fun_submit,
                         host_ip=args.host_ip or "auto",
                         pscmd=_pscmd(args), join=False)
    return _await_job(tracker, failures, threads)
