"""CLI options for dmlc-submit (reference tracker/dmlc_tracker/opts.py).

Memory strings accept g/m suffixes like the reference (opts.py:39-57).
The cluster list adds ``tpu-vm`` (gang-scheduling onto TPU VM slices —
the YARN-AM role) and actually exposes ssh/slurm, which the reference
parses but never routes (submit.py:42-53)."""

from __future__ import annotations

import argparse
import os

CLUSTERS = ["local", "ssh", "mpi", "sge", "slurm", "mesos", "yarn", "tpu-vm"]


def parse_memory_mb(text: str) -> int:
    t = text.strip().lower()
    if t.endswith("g"):
        return int(float(t[:-1]) * 1024)
    if t.endswith("m"):
        return int(float(t[:-1]))
    return int(t)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="submit a distributed dmlc_tpu job",
    )
    p.add_argument("--cluster", default=os.environ.get("DMLC_SUBMIT_CLUSTER"),
                   choices=CLUSTERS, help="cluster backend")
    p.add_argument("--num-workers", required=True, type=int)
    p.add_argument("--num-servers", default=0, type=int)
    p.add_argument("--worker-cores", default=1, type=int)
    p.add_argument("--server-cores", default=1, type=int)
    p.add_argument("--worker-memory", default="1g")
    p.add_argument("--server-memory", default="1g")
    p.add_argument("--jobname", default=None)
    p.add_argument("--queue", default="default",
                   help="scheduler queue (sge backend only)")
    p.add_argument("--log-level", default="INFO",
                   choices=["INFO", "DEBUG", "WARNING", "ERROR"])
    p.add_argument("--log-file", default=None)
    p.add_argument("--host-ip", default=None,
                   help="tracker bind IP (default: auto-detect)")
    p.add_argument("--host-file", default=None,
                   help="hosts for ssh/mpi/tpu-vm backends, one ip[:port] per line")
    p.add_argument("--sge-log-dir", default=None)
    p.add_argument("--slurm-worker-nodes", default=None, type=int)
    p.add_argument("--slurm-server-nodes", default=None, type=int)
    p.add_argument("--sync-dst-dir", default=None,
                   help="rsync the working dir to this path on each host first")
    p.add_argument("--max-attempts", default=3, type=int,
                   help="per-task restart budget (DMLC_NUM_ATTEMPT contract)")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VALUE", help="extra env passed to every task")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every task")
    return p


def get_opts(argv=None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if args.cluster is None:
        raise SystemExit("--cluster required (or set DMLC_SUBMIT_CLUSTER)")
    if not args.command:
        raise SystemExit("missing command to run")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    args.worker_memory_mb = parse_memory_mb(args.worker_memory)
    args.server_memory_mb = parse_memory_mb(args.server_memory)
    extra = {}
    for kv in args.env:
        k, _, v = kv.partition("=")
        extra[k] = v
    args.extra_env = extra
    return args
