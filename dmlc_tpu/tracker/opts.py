"""CLI options for dmlc-submit (reference tracker/dmlc_tracker/opts.py).

Memory strings accept g/m suffixes like the reference (opts.py:39-57).
The cluster list adds ``tpu-vm`` (gang-scheduling onto TPU VM slices —
the YARN-AM role) and actually exposes ssh/slurm, which the reference
parses but never routes (submit.py:42-53)."""

from __future__ import annotations

import argparse
import os

from ..base import get_env

CLUSTERS = ["local", "ssh", "mpi", "sge", "slurm", "mesos", "yarn", "tpu-vm"]


def parse_memory_mb(text: str) -> int:
    t = text.strip().lower()
    if t.endswith("g"):
        return int(float(t[:-1]) * 1024)
    if t.endswith("m"):
        return int(float(t[:-1]))
    return int(t)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dmlc-submit",
        description="submit a distributed dmlc_tpu job",
    )
    p.add_argument("--cluster",
                   default=get_env("DMLC_SUBMIT_CLUSTER", None, str),
                   choices=CLUSTERS, help="cluster backend")
    p.add_argument("--num-workers", required=True, type=int)
    p.add_argument("--num-servers", default=0, type=int)
    p.add_argument("--worker-cores", default=1, type=int)
    p.add_argument("--server-cores", default=1, type=int)
    p.add_argument("--worker-memory", default="1g")
    p.add_argument("--server-memory", default="1g")
    p.add_argument("--jobname", default=None)
    p.add_argument("--queue", default="default",
                   help="scheduler queue (sge backend only)")
    p.add_argument("--log-level", default="INFO",
                   choices=["INFO", "DEBUG", "WARNING", "ERROR"])
    p.add_argument("--log-file", default=None)
    p.add_argument("--host-ip", default=None,
                   help="tracker bind IP (default: auto-detect)")
    p.add_argument("--host-file", default=None,
                   help="hosts for ssh/mpi/tpu-vm backends, one ip[:port] per line")
    p.add_argument("--sge-log-dir", default=None)
    p.add_argument("--slurm-worker-nodes", default=None, type=int)
    p.add_argument("--slurm-server-nodes", default=None, type=int)
    p.add_argument("--mesos-master", default=None,
                   help="mesos master host[:port]; defaults to "
                        "$MESOS_MASTER (reference mesos.py:97-100)")
    p.add_argument("--sync-dst-dir", default=None,
                   help="rsync the working dir to this path on each host first")
    p.add_argument("--auto-file-cache", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="ship files named in the command to the remote job "
                        "cache dir and rewrite them to ./basename "
                        "(ssh/tpu-vm backends)")
    p.add_argument("--files", action="append", default=[],
                   help="extra files to ship to the job cache dir")
    p.add_argument("--archives", action="append", default=[],
                   help="archives (.zip/.tar[.gz]) shipped and unpacked in "
                        "the job cache dir — python-library shipping")
    p.add_argument("--max-attempts", default=3, type=int,
                   help="per-task attempt budget (DMLC_NUM_ATTEMPT contract)")
    p.add_argument("--max-restarts", default=None, type=int,
                   help="per-task RESTART budget (attempts = restarts + 1); "
                        "overrides --max-attempts when given.  Default: "
                        "--max-attempts 3, i.e. 2 restarts; 0 = fail fast "
                        "on the first crash")
    p.add_argument("--env", action="append", default=[],
                   metavar="KEY=VALUE", help="extra env passed to every task")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="command to run on every task")
    return p


def cache_file_set(args):
    """Files to ship to the execution environment + the rewritten command
    (reference opts.py:6-36): with auto-file-cache on, every command
    token naming an existing file is shipped and rewritten to
    ``./basename``; --files adds extras without rewriting.

    With --sync-dst-dir the whole working tree is already shipped, so
    command rewriting is suppressed (relative paths stay valid there)
    and only --files extras are staged.  A --files path that does not
    exist is an error (a typo surfacing remotely is much harder to
    trace); basename collisions in the flat cache dir are an error too.
    """
    fset = set()
    cmds = []
    auto = (getattr(args, "auto_file_cache", False)
            and not getattr(args, "sync_dst_dir", None))
    if auto:
        for token in args.command:
            if os.path.exists(token):
                fset.add(token)
                cmds.append("./" + os.path.basename(token))
            else:
                cmds.append(token)
    else:
        cmds = list(args.command)
    for fname in getattr(args, "files", []):
        if not os.path.exists(fname):
            raise FileNotFoundError(f"--files {fname!r} does not exist")
        fset.add(fname)
    by_base = {}
    for f in sorted(fset):
        base = os.path.basename(f)
        if base in by_base and by_base[base] != f:
            raise ValueError(
                f"cache files {by_base[base]!r} and {f!r} collide on "
                f"basename {base!r} in the flat job cache dir")
        by_base[base] = f
    return fset, cmds


def get_opts(argv=None) -> argparse.Namespace:
    args = build_parser().parse_args(argv)
    if args.cluster is None:
        raise SystemExit("--cluster required (or set DMLC_SUBMIT_CLUSTER)")
    if not args.command:
        raise SystemExit("missing command to run")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    if args.max_restarts is not None:
        if args.max_restarts < 0:
            raise SystemExit("--max-restarts must be >= 0")
        args.max_attempts = args.max_restarts + 1
    args.worker_memory_mb = parse_memory_mb(args.worker_memory)
    args.server_memory_mb = parse_memory_mb(args.server_memory)
    extra = {}
    for kv in args.env:
        k, _, v = kv.partition("=")
        extra[k] = v
    args.extra_env = extra
    return args
