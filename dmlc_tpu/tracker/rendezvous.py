"""Rank rendezvous server (RabitTracker) + PS scheduler bootstrap.

Behavioral rebuild of tracker/dmlc_tracker/tracker.py:137-433: TCP
server on a scanned port, handshake (magic, rank, world_size, jobid,
cmd ∈ {start, recover, shutdown, print}), batch rank assignment sorted
by host for locality, connection brokering between peers, `recover`
re-issuing topology to restarted workers, job wall-time logging.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import threading
import time
from typing import Dict, List, Optional

from ..base import DMLCError
from .protocol import MAGIC, FrameSocket, link_maps, resolve_ip

logger = logging.getLogger("dmlc_tpu.tracker")


def _sock_timeout() -> Optional[float]:
    """Per-connection timeout for worker sockets.  A worker that dies
    without a FIN (SIGKILL'd host, dropped link) would otherwise leave
    the tracker blocked forever on a dead recv mid-brokering; the
    reference tracker (tracker.py:80-135) hangs exactly this way.
    0 disables (DMLC_TRACKER_TIMEOUT seconds, default 300)."""
    t = float(os.environ.get("DMLC_TRACKER_TIMEOUT", "300"))
    return t if t > 0 else None


class AcceptRegistry:
    """Ranks currently listening for inbound peer dials.

    A worker lands here after its brokering round leaves it with a
    nonzero inbound quota (peers that were not yet assigned when it
    finished, and so will be told to dial IT later).  Each time the
    tracker directs some later worker to dial rank r, r's quota drops;
    at zero the rank stops being a dial target and leaves the registry.

    Lock-protected: the failure detector (a separate thread) may
    ``drop()`` a dead rank while the accept loop brokers.
    """

    def __init__(self):
        self._listening: Dict[int, "WorkerEntry"] = {}
        self._lock = threading.Lock()

    def __contains__(self, rank: int) -> bool:
        with self._lock:
            return rank in self._listening

    def add(self, rank: int, worker: "WorkerEntry") -> None:
        if worker.inbound_quota > 0:
            with self._lock:
                self._listening[rank] = worker

    def drop(self, rank: int) -> None:
        """Remove a rank declared dead: later workers must not be told
        to dial its stale endpoint (they will be counted as accepts and
        satisfied when the replacement re-brokers)."""
        with self._lock:
            self._listening.pop(rank, None)

    def dial_targets(self, ranks) -> Dict[int, tuple]:
        """Atomic snapshot {rank: (host, port)} for the subset of
        ``ranks`` currently listening — membership and endpoint resolve
        under ONE lock hold, so a concurrent ``drop()`` by the failure
        detector can never KeyError the brokering loop between a
        membership check and the endpoint read."""
        with self._lock:
            return {r: (self._listening[r].host, self._listening[r].port)
                    for r in ranks if r in self._listening}

    def note_dialed(self, ranks) -> List[int]:
        """Record that ``ranks`` each just received one inbound link;
        returns those whose quota is now exhausted (and drops them).
        Ranks no longer present (dropped as dead mid-round) are
        skipped."""
        filled = []
        with self._lock:
            for r in ranks:
                w = self._listening.get(r)
                if w is None:
                    continue
                w.inbound_quota -= 1
                if w.inbound_quota == 0:
                    filled.append(r)
                    del self._listening[r]
        return filled


class WorkerEntry:
    """One accepted worker connection (reference SlaveEntry role)."""

    def __init__(self, sock: socket.socket, addr):
        sock.settimeout(_sock_timeout())
        self.sock = FrameSocket(sock)
        self.host = resolve_ip(addr[0])
        magic = self.sock.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.sock.send_int(MAGIC)
        self.rank = self.sock.recv_int()
        self.world_size = self.sock.recv_int()
        self.jobid = self.sock.recv_str()
        self.cmd = self.sock.recv_str()
        self.inbound_quota = 0          # peers that will dial in later
        self.port: Optional[int] = None  # worker's accept port

    def decide_rank(self, job_map: Dict[str, int]) -> int:
        if self.rank >= 0:
            return self.rank
        if self.jobid != "NULL" and self.jobid in job_map:
            return job_map[self.jobid]
        return -1

    def _send_topology(self, rank, tree_map, parent_map, ring_map):
        """Issue rank + overlay neighbours; returns the full set of peer
        ranks this worker must end up linked to (tree ∪ ring)."""
        peers = set(tree_map[rank])
        self.sock.send_int(rank)
        self.sock.send_int(parent_map[rank])
        self.sock.send_int(len(tree_map))
        self.sock.send_int(len(peers))
        for r in peers:
            self.sock.send_int(r)
        for ring_nbr in ring_map[rank]:  # (prev, next)
            if ring_nbr != -1 and ring_nbr != rank:
                peers.add(ring_nbr)
                self.sock.send_int(ring_nbr)
            else:
                self.sock.send_int(-1)
        return peers

    def assign_rank(self, rank, registry: AcceptRegistry, tree_map,
                    parent_map, ring_map) -> List[int]:
        """Send topology, then broker peer links until the worker reports
        a clean round.  Wire format: reference tracker.py:80-135.

        Each round: the worker reports which links it already holds; the
        tracker answers with the endpoints it should DIAL now (peers
        already listening) and the count it should expect to ACCEPT
        later; the worker replies with its dial-error count — nonzero
        restarts the round, zero ends with the worker's accept port.
        Returns ranks whose inbound quota filled during this exchange.
        """
        self.rank = rank
        required = self._send_topology(rank, tree_map, parent_map, ring_map)
        filled: List[int] = []
        debited: set = set()  # dial targets already charged one inbound link
        dialed: set = set()   # every target we have handed out so far
        while True:
            n_held = self.sock.recv_int()
            held = {self.sock.recv_int() for _ in range(n_held)}
            if not held.issubset(required):
                raise DMLCError(
                    f"rank {rank} ({self.host}) reported links "
                    f"{sorted(held - required)} outside its assigned "
                    f"peer set {sorted(required)} — protocol violation")
            # dials that stuck during a FAILED earlier round show up in the
            # worker's held set now — charge their quotas exactly once
            confirmed = (held & dialed) - debited
            filled += registry.note_dialed(confirmed)
            debited |= confirmed
            missing = required - held
            targets = registry.dial_targets(missing)  # one atomic snapshot
            dial_now = sorted(targets)
            n_accept = len(missing) - len(dial_now)
            self.sock.send_int(len(dial_now))
            self.sock.send_int(n_accept)
            for r in dial_now:
                host, port = targets[r]
                self.sock.send_str(host)
                self.sock.send_int(port)
                self.sock.send_int(r)
            dialed |= set(dial_now)
            n_dial_errors = self.sock.recv_int()
            if n_dial_errors != 0:
                continue  # transient dial failures: rebroker from scratch
            self.port = self.sock.recv_int()
            # a clean round means every dial in it succeeded
            filled += registry.note_dialed(set(dial_now) - debited)
            self.inbound_quota = n_accept
            registry.add(rank, self)
            return filled


class RabitTracker:
    """Rendezvous server; one thread accepts workers until all shut down.

    Beyond rendezvous, the tracker is the cluster's telemetry sink:
    workers push periodic heartbeats (``metrics`` command sessions, same
    shape as the ``print`` relay) into a :class:`TelemetryAggregator`,
    and ``metrics_port`` (or ``DMLC_TRACKER_METRICS_PORT``; 0 =
    ephemeral) serves the merged view over HTTP ``/metrics``
    (Prometheus text) + ``/healthz``, with straggler ranks flagged via
    ``logging.warning``.

    Failure detection: with a positive ``miss_window_s`` (or
    ``DMLC_TRACKER_MISS_WINDOW_S``; default 0 = disabled) a monitor
    thread watches the heartbeat stream and declares a rank DEAD once
    its heartbeats go missing for the window: the rank's connection is
    dropped (closed + removed from the dial registry) WITHOUT killing
    the accept loop, the death is logged and counted
    (``resilience.worker_declared_dead``), and /healthz lists the rank
    under ``dead_ranks``.  A replacement worker re-admitted through the
    existing ``recover``/job-map path clears the flag and counts as
    ``resilience.worker_readmitted`` — the tracker's half of supervised
    restart (the launcher's restart budget owns re-running the task).
    """

    def __init__(self, host_ip: str, n_workers: int,
                 port: int = 9091, port_end: int = 9999,
                 metrics_port: Optional[int] = None,
                 miss_window_s: Optional[float] = None):
        family = socket.getaddrinfo(host_ip, None)[0][0]
        sock = socket.socket(family, socket.SOCK_STREAM)
        for p in range(port, port_end):
            try:
                sock.bind((host_ip, p))
                self.port = p
                break
            except OSError:
                continue
        else:
            raise OSError(f"no free tracker port in [{port},{port_end})")
        sock.listen(256)
        self.sock = sock
        self.host_ip = host_ip
        self.n_workers = n_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        if miss_window_s is None:
            miss_window_s = float(
                os.environ.get("DMLC_TRACKER_MISS_WINDOW_S", "0"))
        self.miss_window_s = miss_window_s
        self.dead_ranks: set = set()
        self._finished_ranks: set = set()  # clean shutdowns: never "dead"
        self._dead_lock = threading.Lock()
        self._entries: Dict[int, "WorkerEntry"] = {}
        self._registry: Optional[AcceptRegistry] = None
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        from ..telemetry import (FlightRecorder, TelemetryAggregator,
                                 Watchdog, exporters, spans)

        # local_snapshot: the tracker process IS the launcher for local
        # jobs — its own registry carries restart/retry counters that no
        # worker heartbeat ever will; publish them under rank="tracker"
        self.telemetry = TelemetryAggregator(
            log=logger,
            local_snapshot=lambda: exporters.export_json(
                include_buckets=True))
        self.telemetry.extra_health = lambda: {
            "dead_ranks": self._dead_snapshot(),
            "clock_offsets": self._clock_snapshot()}
        # flight recorder: workers ship span rings incrementally with
        # their heartbeats; /trace serves the clock-corrected merge,
        # with the tracker's own spans riding along as the reference row
        self.flight = FlightRecorder(local_spans=spans, log=logger)
        # anomaly watchdog: consumes the step-ledger records riding the
        # same heartbeats; its dmlc_anomaly_active gauges join /metrics
        # and its verdicts mark the merged /trace timeline
        self.watchdog = Watchdog(log=logger)
        self.telemetry.extra_text = self.watchdog.prometheus_text
        self.flight.marker_source = self.watchdog.trace_markers
        self.metrics_server = None
        self.metrics_port: Optional[int] = None
        if metrics_port is None:
            env = os.environ.get("DMLC_TRACKER_METRICS_PORT")
            metrics_port = int(env) if env else None
        if metrics_port is not None:
            from ..telemetry import TelemetryHTTPServer

            self.metrics_server = TelemetryHTTPServer(
                self.telemetry, host=host_ip, port=metrics_port,
                trace_source=self.flight.to_chrome_trace,
                anomaly_source=self.watchdog.report)
            self.metrics_port = self.metrics_server.port
            logger.info("tracker /metrics + /trace + /anomalies on %s:%d",
                        host_ip, self.metrics_port)
        logger.info("tracker listening on %s:%d", host_ip, self.port)

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": str(self.port),
        }

    def _accept_loop(self, n_workers: int) -> None:
        shutdown: Dict[int, WorkerEntry] = {}
        registry = AcceptRegistry()
        self._registry = registry
        job_map: Dict[str, int] = {}
        pending: List[WorkerEntry] = []
        tree_map = None
        parent_map = ring_map = None
        todo: List[int] = []

        def fail(msg: str) -> DMLCError:
            # protocol violations from REGISTERED workers corrupt the
            # job's rank/link state: fail the whole tracker loudly (the
            # reference dies on a bare assert here; we say why) — the
            # launcher's retry machinery owns restarting the job
            return DMLCError(f"tracker protocol violation: {msg}")

        def broker(entry: "WorkerEntry", rank: int) -> None:
            # a worker dying (or going silent past DMLC_TRACKER_TIMEOUT)
            # mid-brokering leaves the overlay unbuildable: error out so
            # join()/_await_job abort instead of hanging the whole gang
            try:
                entry.assign_rank(rank, registry, tree_map, parent_map,
                                  ring_map)
            except socket.timeout as e:
                raise DMLCError(
                    f"worker rank {rank} ({entry.host}) went silent "
                    f"mid-brokering (DMLC_TRACKER_TIMEOUT="
                    f"{_sock_timeout()}s)") from e
            except OSError as e:
                raise DMLCError(
                    f"worker rank {rank} ({entry.host}) died "
                    f"mid-brokering: {e}") from e
            self._entries[rank] = entry
            self._note_admitted(rank, entry.cmd)

        while len(shutdown) != n_workers:
            fd, addr = self.sock.accept()
            try:
                w = WorkerEntry(fd, addr)
                if w.cmd == "print":
                    logger.info("%s", w.sock.recv_str().strip())
                    continue
                if w.cmd == "metrics":
                    # telemetry heartbeat: latest snapshot for this rank
                    # (short session, like print; never fails the job);
                    # any shipped trace sub-document feeds the flight
                    # recorder's per-rank span store and the anomaly
                    # watchdog's step-record stream.  Parsed ONCE here —
                    # beats run up to DMLC_TELEMETRY_MAX_BEAT_BYTES and
                    # this loop also serves rendezvous/clock traffic, so
                    # three consumers must not mean three json.loads
                    payload = w.sock.recv_str()
                    try:
                        doc = json.loads(payload)
                        if not isinstance(doc, dict):
                            raise TypeError("non-dict telemetry "
                                            f"({type(doc).__name__})")
                    except Exception as e:  # noqa: BLE001 - keep serving
                        logger.warning(
                            "rank %d sent malformed telemetry: %r",
                            w.rank, e)
                        continue
                    self.telemetry.update(w.rank, doc)
                    trace = doc.get("trace")
                    if isinstance(trace, dict):
                        self.flight.ingest(w.rank, trace, host=w.host)
                        steps = trace.get("steps")
                        if steps:
                            self.watchdog.ingest(
                                w.rank, steps,
                                anchor=trace.get("anchor"))
                    continue
                if w.cmd == "clock":
                    # NTP-style ping: stamp receipt (t1) and reply send
                    # (t2) on the tracker's clock; the worker computes
                    # the offset sample and ships it with its next beat
                    w.sock.recv_str()  # worker's t0 (it keeps its own)
                    t1 = time.time()
                    w.sock.send_str(json.dumps(
                        {"t1": t1, "t2": time.time()}))
                    continue
            except (OSError, UnicodeDecodeError) as e:
                # pre-registration garbage (port scans, torn handshakes,
                # bad frames) must not kill the job: reject and serve on
                logger.warning("rejected connection from %s: %s",
                               addr[0], e)
                fd.close()
                continue
            if w.cmd == "shutdown":
                if w.rank < 0 or w.rank >= n_workers or w.rank in shutdown:
                    raise fail(f"shutdown from rank {w.rank} "
                               f"(out of range for {n_workers} workers, "
                               f"already shut down, or never assigned)")
                if w.rank in registry:
                    raise fail(f"rank {w.rank} shut down while peers "
                               f"still expect to dial it")
                shutdown[w.rank] = w
                # a cleanly-finished rank leaves the failure detector's
                # watch: its heartbeat age grows forever from here, and
                # flagging it dead would corrupt the death counters
                self._entries.pop(w.rank, None)
                with self._dead_lock:
                    self._finished_ranks.add(w.rank)
                    self.dead_ranks.discard(w.rank)
                logger.debug("shutdown from rank %d", w.rank)
                continue
            if w.cmd not in ("start", "recover"):
                raise fail(f"unknown command {w.cmd!r} from {w.host}")
            if tree_map is None:
                if w.cmd != "start":
                    raise fail(f"{w.cmd!r} from {w.host} before any "
                               f"worker started")
                if w.world_size > 0:
                    n_workers = w.world_size
                tree_map, parent_map, ring_map = link_maps(n_workers)
                todo = list(range(n_workers))
            elif w.world_size not in (-1, n_workers):
                raise fail(f"{w.host} announced world_size "
                           f"{w.world_size} != {n_workers}")
            if w.cmd == "recover" and w.rank < 0:
                raise fail(f"recover without a rank from {w.host}")

            rank = w.decide_rank(job_map)
            # a client-supplied rank must be a real slot — an out-of-range
            # value would KeyError deep inside the topology send instead
            # of dying diagnosably here
            if rank >= n_workers:
                raise fail(f"{w.cmd!r} from {w.host} announced rank "
                           f"{rank} >= world size {n_workers}")
            if rank == -1:
                if not todo:
                    raise fail(f"{w.host} asked for a rank but all "
                               f"{n_workers} slots are assigned")
                pending.append(w)
                if len(pending) == len(todo):
                    pending.sort(key=lambda x: x.host)  # locality
                    for p in pending:
                        rank = todo.pop(0)
                        if p.jobid != "NULL":
                            job_map[p.jobid] = rank
                        broker(p, rank)
                        logger.debug("assigned rank %d to %s", p.rank, p.host)
                    pending = []
                if not todo:
                    logger.info("@tracker all %d workers started", n_workers)
                    self.start_time = time.time()
            else:
                broker(w, rank)
                logger.debug("%s from rank %d", w.cmd, w.rank)
        self.end_time = time.time()
        if self.start_time is not None:
            logger.info("@tracker %.3f secs between start and finish",
                        self.end_time - self.start_time)

    # ---- heartbeat-driven failure detection ----------------------------
    def _dead_snapshot(self) -> List[int]:
        with self._dead_lock:  # the monitor mutates the set concurrently
            return sorted(self.dead_ranks)

    def _clock_snapshot(self) -> Dict[str, Dict[str, float]]:
        return {str(r): s for r, s in self.flight.clock.snapshot().items()}

    def _note_admitted(self, rank: int, cmd: str) -> None:
        """A worker finished brokering under ``rank``: if that rank was
        declared dead, this is the supervised-restart re-admission."""
        with self._dead_lock:
            was_dead = rank in self.dead_ranks
            self.dead_ranks.discard(rank)
            self._finished_ranks.discard(rank)
        self.telemetry.touch(rank)  # restart the miss-window clock
        if was_dead:
            from .. import telemetry

            telemetry.inc("resilience", "worker_readmitted")
            telemetry.record_event("worker_readmitted", rank=rank, cmd=cmd)
            logger.info("rank %d re-admitted via %r after being declared "
                        "dead", rank, cmd)

    def _declare_dead(self, rank: int, age: float) -> None:
        from .. import telemetry

        with self._dead_lock:
            if rank in self.dead_ranks:
                return
            self.dead_ranks.add(rank)
        telemetry.inc("resilience", "worker_declared_dead")
        telemetry.record_event("declared_dead", rank=rank,
                               age_s=round(age, 3),
                               miss_window_s=self.miss_window_s)
        logger.warning(
            "rank %d declared dead: no heartbeat for %.1fs (miss window "
            "%.1fs); dropping its connection and awaiting a replacement",
            rank, age, self.miss_window_s)
        entry = self._entries.pop(rank, None)
        if entry is not None:
            entry.sock.close()  # usually already closed by the worker
        if self._registry is not None:
            self._registry.drop(rank)
        # the replacement's step baselines start over (fresh process,
        # fresh compile warmup); its anomaly history stays in the ring
        self.watchdog.drop(rank)

    def _monitor_loop(self) -> None:
        interval = max(0.1, min(1.0, self.miss_window_s / 4))
        while not self._monitor_stop.wait(interval):
            with self._dead_lock:
                finished = set(self._finished_ranks)
            for rank, age in self.telemetry.ranks().items():
                if rank in finished:
                    continue  # clean shutdown: silence is expected
                if age > self.miss_window_s:
                    self._declare_dead(rank, age)
                else:
                    # heartbeats resumed (replacement already pushing
                    # before its brokering finished): clear the flag
                    with self._dead_lock:
                        self.dead_ranks.discard(rank)

    def start(self, n_workers: Optional[int] = None) -> None:
        n = self.n_workers if n_workers is None else n_workers
        self.error: Optional[BaseException] = None

        def run():
            try:
                self._accept_loop(n)
            except BaseException as e:  # surfaced by join()/_await_job
                self.error = e
                logger.error("tracker accept loop died: %s", e)
            finally:
                self._monitor_stop.set()

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()
        if self.miss_window_s > 0 and self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="tracker-failure-detector")
            self._monitor.start()

    def join(self, timeout: Optional[float] = None) -> None:
        assert self.thread is not None
        deadline = None if timeout is None else time.time() + timeout
        while self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"tracker failed: {self.error}") from self.error

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def close(self) -> None:
        self._monitor_stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None


def free_port(host_ip: str = "127.0.0.1") -> int:
    """Find a currently-free TCP port on ``host_ip`` without holding it."""
    probe = socket.socket()
    probe.bind((host_ip, 0))
    port = probe.getsockname()[1]
    probe.close()
    return port


class PSTracker:
    """Parameter-server scheduler bootstrap (tracker.py:336-386 analog):
    runs the scheduler process locally with the PS env contract."""

    def __init__(self, host_ip: str, cmd: Optional[str], envs: Dict[str, str],
                 port: int = 9091, port_end: int = 9999):
        self.host_ip = host_ip
        self.cmd = cmd
        self.thread = None
        self.proc: Optional[subprocess.Popen] = None
        self.error: Optional[BaseException] = None
        self._terminated = False
        self.port = free_port(host_ip)
        if cmd is None:
            return
        env = os.environ.copy()
        env.update(envs)
        env.update({
            "DMLC_ROLE": "scheduler",
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        })
        # Popen (not check_call) so an aborting job can terminate() the
        # scheduler: a lingering scheduler child inherits the launcher's
        # stdio and keeps a captured pipe open long after dmlc-submit
        # exits, hanging whoever waits on that pipe.
        self.proc = subprocess.Popen(self.cmd, shell=True, env=env)

        def run():
            # a dead scheduler must abort the job fast, not leave every
            # worker hanging on DMLC_PS_ROOT_PORT — record the failure
            # for _await_job/join instead of losing it in a daemon thread
            try:
                rc = self.proc.wait()
                if rc != 0 and not self._terminated:
                    raise RuntimeError(f"scheduler exited {rc}")
            except BaseException as e:
                self.error = e
                logger.error("PS scheduler died: %s", e)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def terminate(self) -> None:
        """Kill the scheduler process (job abort path).  Flagged first
        so the watcher thread reports the deliberate kill as cleanup,
        not as a scheduler failure."""
        self._terminated = True
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        }

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()
        if self.error is not None:
            raise RuntimeError(
                f"PS scheduler failed: {self.error}") from self.error


def submit_job(n_workers: int, n_servers: int, fun_submit, host_ip: str = "auto",
               pscmd: Optional[str] = None, join: bool = True):
    """Start tracker(s), call fun_submit(n_workers, n_servers, envs), wait.

    The reference's tracker.submit (tracker.py:410-433): rabit path when
    n_servers == 0, PS path otherwise.
    """
    if host_ip == "auto":
        host_ip = os.environ.get("DMLC_TRACKER_URI") or _default_host_ip()
    envs = {"DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}
    # The jax.distributed coordinator is a gRPC service that rank 0 of the
    # JOB must host — it cannot share DMLC_TRACKER_PORT, which is the rabit
    # tracker's own listener in THIS process.  The tracker owns port
    # assignment, so it hands out a distinct free port; the URI defaults to
    # the tracker host (right for local jobs; gang backends override it
    # with the host where task 0 is placed).  The freeness probe runs on
    # THIS machine — for remote coordinators it is only a sane default;
    # override with --env DMLC_JAX_COORD_PORT=... if it collides there.
    envs["DMLC_JAX_COORD_URI"] = host_ip
    envs["DMLC_JAX_COORD_PORT"] = str(free_port(host_ip))
    rabit = ps = None
    if n_servers == 0:
        rabit = RabitTracker(host_ip, n_workers)
        envs.update(rabit.worker_envs())
        rabit.start(n_workers)
    else:
        ps = PSTracker(host_ip, pscmd, envs)
        envs.update(ps.worker_envs())
    fun_submit(n_workers, n_servers, envs)
    if join and rabit is not None:
        rabit.join()
    if join and ps is not None:
        ps.join()  # raises if the scheduler died — sge has no _await_job
    # PS path returns the PSTracker so callers (_await_job) can watch the
    # scheduler's liveness/error the same way they watch the rabit tracker
    return rabit if rabit is not None else ps


def _default_host_ip() -> str:
    """Best-effort local IP (no egress needed: UDP connect is routing-only)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
