"""Rank rendezvous server (RabitTracker) + PS scheduler bootstrap.

Behavioral rebuild of tracker/dmlc_tracker/tracker.py:137-433: TCP
server on a scanned port, handshake (magic, rank, world_size, jobid,
cmd ∈ {start, recover, shutdown, print}), batch rank assignment sorted
by host for locality, connection brokering between peers, `recover`
re-issuing topology to restarted workers, job wall-time logging.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import threading
import time
from typing import Dict, List, Optional

from .protocol import MAGIC, FrameSocket, link_maps, resolve_ip

logger = logging.getLogger("dmlc_tpu.tracker")


class WorkerEntry:
    """One accepted worker connection (SlaveEntry analog)."""

    def __init__(self, sock: socket.socket, addr):
        self.sock = FrameSocket(sock)
        self.host = resolve_ip(addr[0])
        magic = self.sock.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.sock.send_int(MAGIC)
        self.rank = self.sock.recv_int()
        self.world_size = self.sock.recv_int()
        self.jobid = self.sock.recv_str()
        self.cmd = self.sock.recv_str()
        self.wait_accept = 0
        self.port: Optional[int] = None

    def decide_rank(self, job_map: Dict[str, int]) -> int:
        if self.rank >= 0:
            return self.rank
        if self.jobid != "NULL" and self.jobid in job_map:
            return job_map[self.jobid]
        return -1

    def assign_rank(self, rank, wait_conn, tree_map, parent_map, ring_map):
        """Send topology, then broker peer connections until the worker
        reports zero errors.  Returns ranks whose accept quota filled."""
        self.rank = rank
        nnset = set(tree_map[rank])
        rprev, rnext = ring_map[rank]
        self.sock.send_int(rank)
        self.sock.send_int(parent_map[rank])
        self.sock.send_int(len(tree_map))
        self.sock.send_int(len(nnset))
        for r in nnset:
            self.sock.send_int(r)
        if rprev != -1 and rprev != rank:
            nnset.add(rprev)
            self.sock.send_int(rprev)
        else:
            self.sock.send_int(-1)
        if rnext != -1 and rnext != rank:
            nnset.add(rnext)
            self.sock.send_int(rnext)
        else:
            self.sock.send_int(-1)
        while True:
            ngood = self.sock.recv_int()
            goodset = {self.sock.recv_int() for _ in range(ngood)}
            assert goodset.issubset(nnset), (goodset, nnset)
            badset = nnset - goodset
            conset = [r for r in badset if r in wait_conn]
            self.sock.send_int(len(conset))
            self.sock.send_int(len(badset) - len(conset))
            for r in conset:
                self.sock.send_str(wait_conn[r].host)
                self.sock.send_int(wait_conn[r].port)
                self.sock.send_int(r)
            nerr = self.sock.recv_int()
            if nerr != 0:
                continue
            self.port = self.sock.recv_int()
            done = []
            for r in conset:
                wait_conn[r].wait_accept -= 1
                if wait_conn[r].wait_accept == 0:
                    done.append(r)
            for r in done:
                wait_conn.pop(r, None)
            self.wait_accept = len(badset) - len(conset)
            return done


class RabitTracker:
    """Rendezvous server; one thread accepts workers until all shut down."""

    def __init__(self, host_ip: str, n_workers: int,
                 port: int = 9091, port_end: int = 9999):
        family = socket.getaddrinfo(host_ip, None)[0][0]
        sock = socket.socket(family, socket.SOCK_STREAM)
        for p in range(port, port_end):
            try:
                sock.bind((host_ip, p))
                self.port = p
                break
            except OSError:
                continue
        else:
            raise OSError(f"no free tracker port in [{port},{port_end})")
        sock.listen(256)
        self.sock = sock
        self.host_ip = host_ip
        self.n_workers = n_workers
        self.thread: Optional[threading.Thread] = None
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        logger.info("tracker listening on %s:%d", host_ip, self.port)

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": str(self.port),
        }

    def _accept_loop(self, n_workers: int) -> None:
        shutdown: Dict[int, WorkerEntry] = {}
        wait_conn: Dict[int, WorkerEntry] = {}
        job_map: Dict[str, int] = {}
        pending: List[WorkerEntry] = []
        tree_map = None
        parent_map = ring_map = None
        todo: List[int] = []

        while len(shutdown) != n_workers:
            fd, addr = self.sock.accept()
            try:
                w = WorkerEntry(fd, addr)
            except ConnectionError as e:
                logger.warning("rejected connection: %s", e)
                fd.close()
                continue
            if w.cmd == "print":
                logger.info("%s", w.sock.recv_str().strip())
                continue
            if w.cmd == "shutdown":
                assert w.rank >= 0 and w.rank not in shutdown
                assert w.rank not in wait_conn
                shutdown[w.rank] = w
                logger.debug("shutdown from rank %d", w.rank)
                continue
            assert w.cmd in ("start", "recover"), w.cmd
            if tree_map is None:
                assert w.cmd == "start"
                if w.world_size > 0:
                    n_workers = w.world_size
                tree_map, parent_map, ring_map = link_maps(n_workers)
                todo = list(range(n_workers))
            else:
                assert w.world_size in (-1, n_workers)
            if w.cmd == "recover":
                assert w.rank >= 0

            rank = w.decide_rank(job_map)
            if rank == -1:
                assert todo, "no rank slots left"
                pending.append(w)
                if len(pending) == len(todo):
                    pending.sort(key=lambda x: x.host)  # locality
                    for p in pending:
                        rank = todo.pop(0)
                        if p.jobid != "NULL":
                            job_map[p.jobid] = rank
                        p.assign_rank(rank, wait_conn, tree_map, parent_map,
                                      ring_map)
                        if p.wait_accept > 0:
                            wait_conn[rank] = p
                        logger.debug("assigned rank %d to %s", p.rank, p.host)
                    pending = []
                if not todo:
                    logger.info("@tracker all %d workers started", n_workers)
                    self.start_time = time.time()
            else:
                w.assign_rank(rank, wait_conn, tree_map, parent_map, ring_map)
                if w.wait_accept > 0:
                    wait_conn[rank] = w
                logger.debug("%s from rank %d", w.cmd, w.rank)
        self.end_time = time.time()
        if self.start_time is not None:
            logger.info("@tracker %.3f secs between start and finish",
                        self.end_time - self.start_time)

    def start(self, n_workers: Optional[int] = None) -> None:
        n = self.n_workers if n_workers is None else n_workers
        self.error: Optional[BaseException] = None

        def run():
            try:
                self._accept_loop(n)
            except BaseException as e:  # surfaced by join()/_await_job
                self.error = e
                logger.error("tracker accept loop died: %s", e)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        assert self.thread is not None
        deadline = None if timeout is None else time.time() + timeout
        while self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"tracker failed: {self.error}") from self.error

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class PSTracker:
    """Parameter-server scheduler bootstrap (tracker.py:336-386 analog):
    runs the scheduler process locally with the PS env contract."""

    def __init__(self, host_ip: str, cmd: Optional[str], envs: Dict[str, str],
                 port: int = 9091, port_end: int = 9999):
        self.host_ip = host_ip
        self.cmd = cmd
        self.thread = None
        if cmd is None:
            # find a free port for the scheduler without holding it
            probe = socket.socket()
            probe.bind((host_ip, 0))
            self.port = probe.getsockname()[1]
            probe.close()
            return
        probe = socket.socket()
        probe.bind((host_ip, 0))
        self.port = probe.getsockname()[1]
        probe.close()
        env = os.environ.copy()
        env.update(envs)
        env.update({
            "DMLC_ROLE": "scheduler",
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        })

        def run():
            subprocess.check_call(self.cmd, shell=True, env=env)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        }

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()


def submit_job(n_workers: int, n_servers: int, fun_submit, host_ip: str = "auto",
               pscmd: Optional[str] = None, join: bool = True):
    """Start tracker(s), call fun_submit(n_workers, n_servers, envs), wait.

    The reference's tracker.submit (tracker.py:410-433): rabit path when
    n_servers == 0, PS path otherwise.
    """
    if host_ip == "auto":
        host_ip = os.environ.get("DMLC_TRACKER_URI") or _default_host_ip()
    envs = {"DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}
    rabit = None
    if n_servers == 0:
        rabit = RabitTracker(host_ip, n_workers)
        envs.update(rabit.worker_envs())
        rabit.start(n_workers)
    else:
        ps = PSTracker(host_ip, pscmd, envs)
        envs.update(ps.worker_envs())
    fun_submit(n_workers, n_servers, envs)
    if join and rabit is not None:
        rabit.join()
    return rabit


def _default_host_ip() -> str:
    """Best-effort local IP (no egress needed: UDP connect is routing-only)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
