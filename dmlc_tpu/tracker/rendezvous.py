"""Rank rendezvous server (RabitTracker) + PS scheduler bootstrap.

Behavioral rebuild of tracker/dmlc_tracker/tracker.py:137-433: TCP
server on a scanned port, handshake (magic, rank, world_size, jobid,
cmd ∈ {start, recover, shutdown, print}), batch rank assignment sorted
by host for locality, connection brokering between peers, `recover`
re-issuing topology to restarted workers, job wall-time logging.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import threading
import time
from typing import Dict, List, Optional

from ..base import DMLCError, get_env
from ..concurrency import make_lock
from .protocol import MAGIC, FrameSocket, link_maps, parse_worker_cmd, \
    resolve_ip

logger = logging.getLogger("dmlc_tpu.tracker")


def _sock_timeout() -> Optional[float]:
    """Per-connection timeout for worker sockets.  A worker that dies
    without a FIN (SIGKILL'd host, dropped link) would otherwise leave
    the tracker blocked forever on a dead recv mid-brokering; the
    reference tracker (tracker.py:80-135) hangs exactly this way.
    0 disables (DMLC_TRACKER_TIMEOUT seconds, default 300)."""
    t = get_env("DMLC_TRACKER_TIMEOUT", 300.0)
    return t if t > 0 else None


class AcceptRegistry:
    """Ranks currently listening for inbound peer dials.

    A worker lands here after its brokering round leaves it with a
    nonzero inbound quota (peers that were not yet assigned when it
    finished, and so will be told to dial IT later).  Each time the
    tracker directs some later worker to dial rank r, r's quota drops;
    at zero the rank stops being a dial target and leaves the registry.

    Lock-protected: the failure detector (a separate thread) may
    ``drop()`` a dead rank while the accept loop brokers.
    """

    def __init__(self):
        self._listening: Dict[int, "WorkerEntry"] = {}
        self._lock = make_lock("AcceptRegistry._lock")

    def __contains__(self, rank: int) -> bool:
        with self._lock:
            return rank in self._listening

    def add(self, rank: int, worker: "WorkerEntry") -> None:
        if worker.inbound_quota > 0:
            with self._lock:
                self._listening[rank] = worker

    def drop(self, rank: int) -> None:
        """Remove a rank declared dead: later workers must not be told
        to dial its stale endpoint (they will be counted as accepts and
        satisfied when the replacement re-brokers)."""
        with self._lock:
            self._listening.pop(rank, None)

    def dial_targets(self, ranks) -> Dict[int, tuple]:
        """Atomic snapshot {rank: (host, port)} for the subset of
        ``ranks`` currently listening — membership and endpoint resolve
        under ONE lock hold, so a concurrent ``drop()`` by the failure
        detector can never KeyError the brokering loop between a
        membership check and the endpoint read."""
        with self._lock:
            return {r: (self._listening[r].host, self._listening[r].port)
                    for r in ranks if r in self._listening}

    def note_dialed(self, ranks) -> List[int]:
        """Record that ``ranks`` each just received one inbound link;
        returns those whose quota is now exhausted (and drops them).
        Ranks no longer present (dropped as dead mid-round) are
        skipped."""
        filled = []
        with self._lock:
            for r in ranks:
                w = self._listening.get(r)
                if w is None:
                    continue
                w.inbound_quota -= 1
                if w.inbound_quota == 0:
                    filled.append(r)
                    del self._listening[r]
        return filled


class WorkerEntry:
    """One accepted worker connection (reference SlaveEntry role)."""

    def __init__(self, sock: socket.socket, addr):
        sock.settimeout(_sock_timeout())
        self.sock = FrameSocket(sock)
        self.host = resolve_ip(addr[0])
        magic = self.sock.recv_int()
        if magic != MAGIC:
            raise ConnectionError(f"invalid magic {magic:#x} from {self.host}")
        self.sock.send_int(MAGIC)
        self.rank = self.sock.recv_int()
        self.world_size = self.sock.recv_int()
        self.jobid = self.sock.recv_str()
        self.cmd = self.sock.recv_str()
        self.inbound_quota = 0          # peers that will dial in later
        self.port: Optional[int] = None  # worker's accept port

    def decide_rank(self, job_map: Dict[str, int]) -> int:
        if self.rank >= 0:
            return self.rank
        if self.jobid != "NULL" and self.jobid in job_map:
            return job_map[self.jobid]
        return -1

    def _send_topology(self, rank, tree_map, parent_map, ring_map):
        """Issue rank + overlay neighbours; returns the full set of peer
        ranks this worker must end up linked to (tree ∪ ring)."""
        peers = set(tree_map[rank])
        self.sock.send_int(rank)
        self.sock.send_int(parent_map[rank])
        self.sock.send_int(len(tree_map))
        self.sock.send_int(len(peers))
        for r in peers:
            self.sock.send_int(r)
        for ring_nbr in ring_map[rank]:  # (prev, next)
            if ring_nbr != -1 and ring_nbr != rank:
                peers.add(ring_nbr)
                self.sock.send_int(ring_nbr)
            else:
                self.sock.send_int(-1)
        return peers

    def assign_rank(self, rank, registry: AcceptRegistry, tree_map,
                    parent_map, ring_map) -> List[int]:
        """Send topology, then broker peer links until the worker reports
        a clean round.  Wire format: reference tracker.py:80-135.

        Each round: the worker reports which links it already holds; the
        tracker answers with the endpoints it should DIAL now (peers
        already listening) and the count it should expect to ACCEPT
        later; the worker replies with its dial-error count — nonzero
        restarts the round, zero ends with the worker's accept port.
        Returns ranks whose inbound quota filled during this exchange.
        """
        self.rank = rank
        required = self._send_topology(rank, tree_map, parent_map, ring_map)
        filled: List[int] = []
        debited: set = set()  # dial targets already charged one inbound link
        dialed: set = set()   # every target we have handed out so far
        while True:
            n_held = self.sock.recv_int()
            held = {self.sock.recv_int() for _ in range(n_held)}
            if not held.issubset(required):
                raise DMLCError(
                    f"rank {rank} ({self.host}) reported links "
                    f"{sorted(held - required)} outside its assigned "
                    f"peer set {sorted(required)} — protocol violation")
            # dials that stuck during a FAILED earlier round show up in the
            # worker's held set now — charge their quotas exactly once
            confirmed = (held & dialed) - debited
            filled += registry.note_dialed(confirmed)
            debited |= confirmed
            missing = required - held
            targets = registry.dial_targets(missing)  # one atomic snapshot
            dial_now = sorted(targets)
            n_accept = len(missing) - len(dial_now)
            self.sock.send_int(len(dial_now))
            self.sock.send_int(n_accept)
            for r in dial_now:
                host, port = targets[r]
                self.sock.send_str(host)
                self.sock.send_int(port)
                self.sock.send_int(r)
            dialed |= set(dial_now)
            n_dial_errors = self.sock.recv_int()
            if n_dial_errors != 0:
                continue  # transient dial failures: rebroker from scratch
            self.port = self.sock.recv_int()
            # a clean round means every dial in it succeeded
            filled += registry.note_dialed(set(dial_now) - debited)
            self.inbound_quota = n_accept
            registry.add(rank, self)
            return filled


class RabitTracker:
    """Rendezvous server; one thread accepts workers until all shut down.

    Beyond rendezvous, the tracker is the cluster's telemetry sink:
    workers push periodic heartbeats (``metrics`` command sessions, same
    shape as the ``print`` relay) into a :class:`TelemetryAggregator`,
    and ``metrics_port`` (or ``DMLC_TRACKER_METRICS_PORT``; 0 =
    ephemeral) serves the merged view over HTTP ``/metrics``
    (Prometheus text) + ``/healthz``, with straggler ranks flagged via
    ``logging.warning``.

    Failure detection: with a positive ``miss_window_s`` (or
    ``DMLC_TRACKER_MISS_WINDOW_S``; default 0 = disabled) a monitor
    thread watches the heartbeat stream and declares a rank DEAD once
    its heartbeats go missing for the window: the rank's connection is
    dropped (closed + removed from the dial registry) WITHOUT killing
    the accept loop, the death is logged and counted
    (``resilience.worker_declared_dead``), and /healthz lists the rank
    under ``dead_ranks``.  A replacement worker re-admitted through the
    existing ``recover``/job-map path clears the flag and counts as
    ``resilience.worker_readmitted`` — the tracker's half of supervised
    restart (the launcher's restart budget owns re-running the task).

    Elastic mode (``elastic=True`` or ``DMLC_ELASTIC=1``) makes the
    world size a run-time variable via *resize generations*: a rank
    still dead ``elastic_grace_s`` (``DMLC_ELASTIC_GRACE_S``, default 5)
    past its death declaration is evicted — the tracker opens a new
    generation, renumbering survivors into a dense ``[0, N')`` rank
    space, rebuilding the tree+ring overlay, and re-brokering links as
    each survivor re-enters rendezvous (``recover@<gen>`` announces are
    translated through per-generation rank maps).  Scale-up arrives via
    ``POST /resize`` on the metrics server (or implicitly: a join
    announce against a full world grows it by one) and is pushed to
    survivors as the generation id piggybacked on every heartbeat
    reply.  Resizes are applied by the accept-loop thread at session
    boundaries, so generation state needs no extra locking; every
    resize lands in the event ring (``world_resized``) and on /metrics
    (``dmlc_elastic_*``).
    """

    #: generations of rank-translation history kept for stale recovers
    MAX_RANK_MAP_HISTORY = 8

    def __init__(self, host_ip: str, n_workers: int,
                 port: int = 9091, port_end: int = 9999,
                 metrics_port: Optional[int] = None,
                 miss_window_s: Optional[float] = None,
                 elastic: Optional[bool] = None,
                 elastic_grace_s: Optional[float] = None):
        family = socket.getaddrinfo(host_ip, None)[0][0]
        # the accept loop IS the tracker's main loop: blocking forever
        # on accept() between sessions is its designed idle state, and
        # every ACCEPTED connection gets a per-socket timeout in
        # WorkerEntry  # dmlc-check: disable=socket-no-timeout
        sock = socket.socket(family, socket.SOCK_STREAM)
        for p in range(port, port_end):
            try:
                sock.bind((host_ip, p))
                self.port = p
                break
            except OSError:
                continue
        else:
            raise OSError(f"no free tracker port in [{port},{port_end})")
        sock.listen(256)
        self.sock = sock
        self.host_ip = host_ip
        self.n_workers = n_workers
        # dmlc-check: unguarded(start/join control-thread lifecycle)
        self.thread: Optional[threading.Thread] = None
        # dmlc-check: unguarded(accept-loop writes; logged after join)
        self.start_time: Optional[float] = None
        # dmlc-check: unguarded(accept-loop writes; logged after join)
        self.end_time: Optional[float] = None
        if miss_window_s is None:
            miss_window_s = get_env("DMLC_TRACKER_MISS_WINDOW_S", 0.0)
        self.miss_window_s = miss_window_s
        if elastic is None:
            elastic = get_env("DMLC_ELASTIC", False)
        self.elastic = bool(elastic)
        if elastic_grace_s is None:
            elastic_grace_s = get_env("DMLC_ELASTIC_GRACE_S", 5.0)
        self.elastic_grace_s = elastic_grace_s
        # dmlc-check: unguarded(accept-loop-owned; cross-thread int reads are stale-tolerant)
        self.gen = 0
        self._resize_lock = make_lock("RabitTracker._resize_lock")
        self._resize_req: Optional[Dict] = None
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._rank_maps: Dict[int, Dict[int, int]] = {}  # gen -> old->new
        self._dead_since: Dict[int, float] = {}          # rank -> monotonic
        self._evicted_total = 0
        # accept-loop world state (mutated only on the accept thread)
        # dmlc-check: unguarded(accept-loop-owned; cross-thread int reads are stale-tolerant)
        self._world = n_workers
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._tree_map = None
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._parent_map = None
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._ring_map = None
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._job_map: Dict[str, int] = {}
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._todo: List[int] = []
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._pending: List["WorkerEntry"] = []
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._shutdown: Dict[int, "WorkerEntry"] = {}
        self.dead_ranks: set = set()
        self._finished_ranks: set = set()  # clean shutdowns: never "dead"
        self._dead_lock = make_lock("RabitTracker._dead_lock")
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._entries: Dict[int, "WorkerEntry"] = {}
        # dmlc-check: unguarded(accept-loop-confined — class docstring)
        self._registry: Optional[AcceptRegistry] = None
        # dmlc-check: unguarded(start/close control-thread lifecycle)
        self._monitor: Optional[threading.Thread] = None
        self._monitor_stop = threading.Event()
        from ..telemetry import (FlightRecorder, TelemetryAggregator,
                                 Watchdog, exporters, spans)

        # local_snapshot: the tracker process IS the launcher for local
        # jobs — its own registry carries restart/retry counters that no
        # worker heartbeat ever will; publish them under rank="tracker"
        self.telemetry = TelemetryAggregator(
            log=logger,
            local_snapshot=lambda: exporters.export_json(
                include_buckets=True))
        self.telemetry.extra_health = lambda: {
            "dead_ranks": self._dead_snapshot(),
            "clock_offsets": self._clock_snapshot(),
            "elastic": self._elastic_snapshot()}
        # flight recorder: workers ship span rings incrementally with
        # their heartbeats; /trace serves the clock-corrected merge,
        # with the tracker's own spans riding along as the reference row
        self.flight = FlightRecorder(local_spans=spans, log=logger)
        # anomaly watchdog: consumes the step-ledger records riding the
        # same heartbeats; its dmlc_anomaly_active gauges join /metrics
        # and its verdicts mark the merged /trace timeline
        self.watchdog = Watchdog(log=logger)
        # goodput aggregator: consumes the heartbeat ``goodput``
        # sub-docs into the cluster wall-clock decomposition (/goodput,
        # dmlc_goodput_* gauges); the forensics reporter joins its
        # badput intervals with the decision log, the event ring and
        # the watchdog's flags into /incidents
        from ..telemetry import (GoodputAggregator, IncidentReporter,
                                 forensics, tracecontext)
        from ..telemetry.events import events as _events

        self.goodput = GoodputAggregator()
        self.incidents = IncidentReporter(
            intervals_source=self.goodput.badput_intervals,
            decisions_source=lambda: tracecontext.decision_log().tail(256),
            events_source=lambda: _events(),
            anomalies_source=lambda: forensics.watchdog_anomaly_records(
                self.watchdog.report()))
        self.telemetry.extra_text = lambda: (
            self.watchdog.prometheus_text()
            + self.goodput.prometheus_text())
        self.flight.marker_source = self.watchdog.trace_markers
        # dmlc-check: unguarded(built pre-start; closed by the control thread)
        self.metrics_server = None
        self.metrics_port: Optional[int] = None
        if metrics_port is None:
            metrics_port = get_env("DMLC_TRACKER_METRICS_PORT", None, int)
        if metrics_port is not None:
            from ..telemetry import TelemetryHTTPServer

            self.metrics_server = TelemetryHTTPServer(
                self.telemetry, host=host_ip, port=metrics_port,
                trace_source=self.flight.to_chrome_trace,
                anomaly_source=self.watchdog.report,
                resize_handler=self._http_resize,
                compute_source=self.watchdog.compute_report,
                goodput_source=self.goodput.report,
                incidents_source=self.incidents.report)
            self.metrics_port = self.metrics_server.port
            logger.info("tracker /metrics + /trace + /anomalies + "
                        "/compute + /goodput + /incidents on %s:%d",
                        host_ip, self.metrics_port)
        logger.info("tracker listening on %s:%d", host_ip, self.port)

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_TRACKER_URI": self.host_ip,
            "DMLC_TRACKER_PORT": str(self.port),
        }

    def _fail(self, msg: str) -> DMLCError:
        # protocol violations from REGISTERED workers corrupt the
        # job's rank/link state: fail the whole tracker loudly (the
        # reference dies on a bare assert here; we say why) — the
        # launcher's retry machinery owns restarting the job
        return DMLCError(f"tracker protocol violation: {msg}")

    def _reject_announce(self, w: "WorkerEntry", why: str) -> None:
        """A malformed announce (world_size mismatch, recover without a
        rank, rank beyond the world, unknown command) is the announcing
        CONNECTION's problem, not the job's: drop it, count it, keep
        brokering.  The reference tracker dies on a bare assert here and
        takes the whole accept loop — and every other worker — with it."""
        from .. import telemetry

        telemetry.inc("tracker", "rejected_announces")
        telemetry.record_event("announce_rejected", host=w.host,
                               cmd=w.cmd, rank=w.rank, why=why)
        logger.warning("rejected %r announce from %s (rank %d): %s",
                       w.cmd, w.host, w.rank, why)
        w.sock.close()

    def _broker(self, entry: "WorkerEntry", rank: int) -> None:
        # a worker dying (or going silent past DMLC_TRACKER_TIMEOUT)
        # mid-brokering leaves the overlay unbuildable: error out so
        # join()/_await_job abort instead of hanging the whole gang.
        # In elastic mode the job OUTLIVES individual workers: the
        # half-brokered rank is declared dead instead (grace then
        # shrinks the world past it) and the loop keeps serving.
        try:
            entry.assign_rank(rank, self._registry, self._tree_map,
                              self._parent_map, self._ring_map)
        except socket.timeout as e:
            if self.elastic:
                self._broker_casualty(entry, rank, f"went silent: {e}")
                return
            raise DMLCError(
                f"worker rank {rank} ({entry.host}) went silent "
                f"mid-brokering (DMLC_TRACKER_TIMEOUT="
                f"{_sock_timeout()}s)") from e
        except OSError as e:
            if self.elastic:
                self._broker_casualty(entry, rank, f"died: {e}")
                return
            raise DMLCError(
                f"worker rank {rank} ({entry.host}) died "
                f"mid-brokering: {e}") from e
        self._entries[rank] = entry
        if entry.jobid != "NULL":
            self._job_map[entry.jobid] = rank
        self._note_admitted(rank, entry.cmd)

    def _broker_casualty(self, entry: "WorkerEntry", rank: int,
                         why: str) -> None:
        """Elastic-mode brokering failure: the rank is treated as a
        fresh death (registry cull + dead flag), so the grace window
        shrinks the world past it instead of the tracker dying."""
        logger.warning("worker rank %d (%s) %s mid-brokering; declaring "
                       "dead (elastic mode keeps serving)", rank,
                       entry.host, why)
        entry.sock.close()
        self._registry.drop(rank)
        self._declare_dead(rank, 0.0)

    # ---- elastic resize machinery --------------------------------------
    def request_resize(self, world: Optional[int] = None, remove=(),
                       reason: str = "operator") -> int:
        """Record a pending membership change; thread-safe.  The change
        is APPLIED by the accept-loop thread at its next session
        boundary (heartbeats arrive continuously, so that is prompt) —
        resizing between sessions means generation state never needs a
        lock against mid-brokering mutation.  Returns the current
        generation (the resize, once applied, will be a later one)."""
        from .. import telemetry

        if not self.elastic:
            raise RuntimeError(
                "tracker is not elastic; start it with elastic=True or "
                "DMLC_ELASTIC=1 to resize the world at run time")
        remove = set(remove)
        with self._resize_lock:
            req = self._resize_req or {"world": None, "remove": set(),
                                       "reasons": []}
            if world is not None:
                world = int(world)
                req["world"] = max(world, req["world"] or 0)
            req["remove"] |= remove
            if reason not in req["reasons"]:
                req["reasons"].append(reason)
            self._resize_req = req
        telemetry.record_event("resize_requested", world=world,
                               remove=sorted(remove), reason=reason,
                               gen=self.gen)
        logger.info("resize requested (%s): world=%s remove=%s",
                    reason, world, sorted(remove))
        return self.gen

    def _http_resize(self, doc: Dict) -> Dict:
        """POST /resize handler: {'world': N} grows (or re-targets) the
        world; an optional {'remove': [rank, ...]} list names ranks to
        evict from the next generation (the fleet autoscaler's
        preemption path: the victim is killed first, then named here so
        the shrink opens deterministically instead of waiting out the
        miss window).  Survivors learn via the heartbeat generation
        piggyback."""
        world = doc.get("world")
        if world is not None:
            if isinstance(world, bool) or not isinstance(world, int):
                raise ValueError("world must be an integer")
            if not 0 < world <= 65536:
                raise ValueError(f"world {world} out of range")
        remove = doc.get("remove", ())
        if remove:
            if (not isinstance(remove, list)
                    or not all(isinstance(r, int)
                               and not isinstance(r, bool)
                               for r in remove)):
                raise ValueError("remove must be a list of ranks")
            if not all(0 <= r < 65536 for r in remove):
                raise ValueError(f"remove ranks {remove} out of range")
        gen = self.request_resize(world=world, remove=remove,
                                  reason=str(doc.get("reason", "operator")))
        return {"requested": True, "gen": gen, "world_target": world,
                "remove": sorted(set(remove)) if remove else [],
                "current_world": self._world}

    def _apply_pending_resize(self) -> None:
        """Accept-loop thread only: open a new generation if a resize
        request is pending."""
        if not self.elastic:
            return
        with self._resize_lock:
            req, self._resize_req = self._resize_req, None
        if req is None:
            return
        if self._tree_map is None:
            # world not formed yet: just re-target the initial size
            if req["world"]:
                self._world = req["world"]
                logger.info("pre-start resize: initial world now %d",
                            self._world)
            return
        self._open_generation(req)

    def _open_generation(self, req: Dict) -> None:
        """Renumber survivors into a dense [0, N') rank space, rebuild
        the overlay maps, and reset brokering state.  Survivors carry
        their old rank into ``recover@<gen>`` announces and are
        translated through ``_rank_maps``; new ranks (scale-up) fill
        ``todo`` and are assigned to joining workers."""
        from .. import telemetry

        remove = set(req["remove"])
        # a slot still in todo has no worker behind it: carrying it into
        # the new generation would mint a phantom member that never
        # heartbeats and never brokers, wedging everyone else's
        # rendezvous.  Its expected joiner (if any) re-enters through
        # the pending claim / implicit-grow paths instead.
        unassigned = set(self._todo)
        survivors = [r for r in range(self._world)
                     if r not in remove and r not in self._shutdown
                     and r not in unassigned]
        target = req["world"] or len(survivors)
        if target < len(survivors):
            logger.warning(
                "resize target %d below survivor count %d; clamping "
                "(evicting live ranks needs them killed, not resized)",
                target, len(survivors))
            target = len(survivors)
        # joiners parked in _pending keep their claim on a slot across
        # the resize — without this a shrink that rebuilt todo empty
        # would strand them forever (and their presence would suppress
        # the implicit +1 grow for anyone after them)
        target = max(target, len(survivors) + len(self._pending))
        rank_map = {old: new for new, old in enumerate(survivors)}
        self._rank_maps[self.gen] = rank_map
        for g in list(self._rank_maps):
            if g <= self.gen - self.MAX_RANK_MAP_HISTORY:
                del self._rank_maps[g]
        old_world, old_gen = self._world, self.gen
        self.gen += 1
        self._world = target
        self._tree_map, self._parent_map, self._ring_map = \
            link_maps(target)
        self._todo = list(range(len(survivors), target))
        self._job_map = {jid: rank_map[r]
                         for jid, r in self._job_map.items()
                         if r in rank_map}
        self._shutdown = {}
        # stale listeners and rendezvous sockets of the old generation
        # must never be handed out as dial targets again
        self._registry = AcceptRegistry()
        for entry in self._entries.values():
            entry.sock.close()
        self._entries = {}
        with self._dead_lock:
            self._evicted_total += len(remove & self.dead_ranks)
            # dead bookkeeping follows the renumbering too: a rank dead
            # but still inside grace IS a survivor and keeps its flag
            # under the new id; entries for removed ranks drop out (a
            # stale old-generation id left behind would later evict
            # whichever LIVE worker now holds that number)
            self.dead_ranks = {rank_map[r] for r in self.dead_ranks
                               if r in rank_map}
            self._dead_since = {rank_map[r]: t
                                for r, t in self._dead_since.items()
                                if r in rank_map}
            self._finished_ranks.clear()
        # heartbeat bookkeeping follows the renumbering: a survivor's
        # age must not be split between its old and new rank ids (the
        # failure detector would re-declare phantom deaths)
        self.telemetry.remap_ranks(rank_map)
        # span stores + clock relations move with the surviving process
        # too — else /trace renders a survivor's history under a pid a
        # different worker now owns (see FlightRecorder.remap_ranks)
        self.flight.remap_ranks(rank_map)
        # goodput docs are cumulative and re-shipped fully every beat,
        # so the remap is self-correcting — but moving them now keeps
        # /goodput truthful between the renumbering and the next beat
        self.goodput.remap_ranks(rank_map)
        for old, new in rank_map.items():
            if old != new:
                self.watchdog.drop(old)
        for r in remove:
            self.watchdog.drop(r)
            self.goodput.drop(r)
        telemetry.inc("elastic", "resizes_total")
        telemetry.inc("elastic", "shrinks_total"
                      if target < old_world else "grows_total")
        telemetry.set_gauge("elastic", "generation", self.gen)
        telemetry.set_gauge("elastic", "world_size", self._world)
        telemetry.record_event(
            "world_resized", gen=self.gen, world=target,
            old_world=old_world, survivors=len(survivors),
            removed=sorted(remove), new_slots=len(self._todo),
            reasons=req["reasons"])
        logger.info(
            "@tracker generation %d -> %d: world %d -> %d (%d survivors "
            "renumbered, %d removed, %d new slots) [%s]", old_gen,
            self.gen, old_world, target, len(survivors), len(remove),
            len(self._todo), ",".join(req["reasons"]))
        if self._pending and self._todo \
                and len(self._pending) >= len(self._todo):
            self._assign_pending()

    def _translate_rank(self, rank: int, announced_gen: int) -> Optional[int]:
        """Chase a rank from ``announced_gen`` through the per-generation
        maps into the current generation; None once it left membership
        (evicted while away — the caller re-admits it as a scale-up
        join) or the history no longer reaches back that far."""
        if announced_gen > self.gen:
            return None
        for g in range(announced_gen, self.gen):
            m = self._rank_maps.get(g)
            if m is None or rank not in m:
                return None
            rank = m[rank]
        return rank

    def _gen_doc(self) -> str:
        with self._dead_lock:
            n_dead = len(self.dead_ranks)
        return json.dumps({"gen": self.gen, "world": self._world,
                           "elastic": self.elastic, "dead": n_dead})

    def _hosts_doc(self) -> str:
        """Rank → (host, accept-port) snapshot of every fully-brokered
        worker.  Served by the accept-loop thread, which is the only
        mutator of ``_entries``, so no locking.  Clients poll until the
        map covers the whole world (a worker mid-brokering has no port
        yet and is omitted)."""
        hosts = {str(r): [e.host, e.port]
                 for r, e in self._entries.items() if e.port is not None}
        return json.dumps({"gen": self.gen, "world": self._world,
                           "hosts": hosts})

    def _accept_loop(self, n_workers: int) -> None:
        self._world = n_workers
        self._registry = AcceptRegistry()

        while True:
            if self._tree_map is not None \
                    and len(self._shutdown) >= self._world:
                break  # every member of the current generation finished
            fd, addr = self.sock.accept()
            # apply membership changes at the session boundary, BEFORE
            # this session is interpreted: a joiner's announce must see
            # the grown world, and the heartbeat reply below must carry
            # the post-resize generation
            self._apply_pending_resize()
            try:
                w = WorkerEntry(fd, addr)
                if w.cmd == "print":
                    logger.info("%s", w.sock.recv_str().strip())
                    continue
                if w.cmd == "gen":
                    # elastic status probe: resize()'s settle-wait polls
                    # this until the membership change lands
                    w.sock.send_str(self._gen_doc())
                    continue
                if w.cmd == "hosts":
                    # job-map probe: rank -> (host, accept port) of the
                    # current generation — the hier collective's auto
                    # host-grouping and leader-ring dialing read this
                    w.sock.send_str(self._hosts_doc())
                    continue
                if w.cmd == "metrics":
                    # telemetry heartbeat: latest snapshot for this rank
                    # (short session, like print; never fails the job);
                    # any shipped trace sub-document feeds the flight
                    # recorder's per-rank span store and the anomaly
                    # watchdog's step-record stream.  Parsed ONCE here —
                    # beats run up to DMLC_TELEMETRY_MAX_BEAT_BYTES and
                    # this loop also serves rendezvous/clock traffic, so
                    # three consumers must not mean three json.loads
                    payload = w.sock.recv_str()
                    try:
                        doc = json.loads(payload)
                        if not isinstance(doc, dict):
                            raise TypeError("non-dict telemetry "
                                            f"({type(doc).__name__})")
                    except Exception as e:  # noqa: BLE001 - keep serving
                        logger.warning(
                            "rank %d sent malformed telemetry: %r",
                            w.rank, e)
                        doc = None
                    # the reply carries the current generation — the
                    # scale-up push channel (a grow resize severs no
                    # links, so the heartbeat is how survivors learn);
                    # sent even for malformed beats so the sender's
                    # reply read never stalls on its own bad payload
                    w.sock.send_int(self.gen)
                    if doc is None:
                        continue
                    self.telemetry.update(w.rank, doc)
                    sh = doc.get("selfheal")
                    if isinstance(sh, dict):
                        # self-heal remediation status: /anomalies (and
                        # dmlc top) show what the worker DID about a
                        # flagged step, not just that one fired
                        self.watchdog.ingest_remediation(w.rank, sh)
                    comp = doc.get("compute")
                    if isinstance(comp, dict):
                        # compile-ledger status: feeds the watchdog's
                        # recompile_storm flag and the /compute view
                        self.watchdog.ingest_compute(w.rank, comp)
                    gd = doc.get("goodput")
                    if isinstance(gd, dict):
                        # goodput decomposition: /goodput aggregation +
                        # the watchdog's effective-goodput collapse gate
                        self.goodput.ingest(w.rank, gd)
                        self.watchdog.ingest_goodput(w.rank, gd)
                    trace = doc.get("trace")
                    if isinstance(trace, dict):
                        self.flight.ingest(w.rank, trace, host=w.host)
                        steps = trace.get("steps")
                        if steps:
                            self.watchdog.ingest(
                                w.rank, steps,
                                anchor=trace.get("anchor"))
                    continue
                if w.cmd == "clock":
                    # NTP-style ping: stamp receipt (t1) and reply send
                    # (t2) on the tracker's clock; the worker computes
                    # the offset sample and ships it with its next beat
                    w.sock.recv_str()  # worker's t0 (it keeps its own)
                    t1 = time.time()
                    w.sock.send_str(json.dumps(
                        {"t1": t1, "t2": time.time()}))
                    continue
            except (OSError, UnicodeDecodeError) as e:
                # pre-registration garbage (port scans, torn handshakes,
                # bad frames) must not kill the job: reject and serve on
                logger.warning("rejected connection from %s: %s",
                               addr[0], e)
                fd.close()
                continue
            base_cmd, announced_gen = parse_worker_cmd(w.cmd)
            if base_cmd == "shutdown":
                rank = w.rank
                if self.elastic and announced_gen is not None \
                        and announced_gen < self.gen:
                    # the finishing worker may never have re-brokered
                    # into the newest generation: chase its rank through
                    # the maps so the RIGHT completion slot is marked
                    rank = self._translate_rank(w.rank, announced_gen)
                    if rank is None:
                        logger.info(
                            "shutdown from evicted rank %d of gen %d "
                            "(%s); no longer a member — ignored",
                            w.rank, announced_gen, w.host)
                        w.sock.close()
                        continue
                if rank < 0 or rank >= self._world \
                        or rank in self._shutdown:
                    raise self._fail(
                        f"shutdown from rank {rank} "
                        f"(out of range for {self._world} workers, "
                        f"already shut down, or never assigned)")
                if rank in self._registry:
                    raise self._fail(f"rank {rank} shut down while "
                                     f"peers still expect to dial it")
                self._shutdown[rank] = w
                # a cleanly-finished rank leaves the failure detector's
                # watch: its heartbeat age grows forever from here, and
                # flagging it dead would corrupt the death counters
                self._entries.pop(rank, None)
                with self._dead_lock:
                    self._finished_ranks.add(rank)
                    self.dead_ranks.discard(rank)
                logger.debug("shutdown from rank %d", rank)
                continue
            self._handle_announce(w)
        self.end_time = time.time()
        if self.start_time is not None:
            logger.info("@tracker %.3f secs between start and finish",
                        self.end_time - self.start_time)

    def _handle_announce(self, w: "WorkerEntry") -> None:
        """One start/recover announce: resolve the rank (translating
        elastic recovers across generations), then broker."""
        cmd, announced_gen = parse_worker_cmd(w.cmd)
        if cmd not in ("start", "recover"):
            self._reject_announce(w, "unknown command")
            return
        if self._tree_map is None:
            if cmd != "start":
                self._reject_announce(w, "recover before any worker "
                                      "started")
                return
            if w.world_size > 0:
                self._world = w.world_size
            self._tree_map, self._parent_map, self._ring_map = \
                link_maps(self._world)
            self._todo = list(range(self._world))
        elif w.world_size not in (-1, self._world):
            self._reject_announce(
                w, f"announced world_size {w.world_size} != "
                   f"{self._world}")
            return
        if cmd == "recover" and w.rank < 0:
            self._reject_announce(w, "recover without a rank")
            return

        if self.elastic and announced_gen is not None \
                and announced_gen < self.gen:
            # an elastic re-rendezvous carrying a rank from an older
            # generation: chase it through the rank maps; a worker that
            # was evicted while away re-joins as a scale-up
            rank = self._translate_rank(w.rank, announced_gen)
            if rank is None:
                logger.info(
                    "rank %d of gen %d (%s) no longer a member; "
                    "re-admitting as a scale-up join", w.rank,
                    announced_gen, w.host)
                rank = -1
        else:
            rank = w.decide_rank(self._job_map)
        # a client-supplied rank must be a real slot — an out-of-range
        # value would KeyError deep inside the topology send instead
        # of dying diagnosably here
        if rank >= self._world:
            self._reject_announce(
                w, f"rank {rank} >= world size {self._world}")
            return
        if rank == -1:
            if not self._todo and not self._pending:
                if not self.elastic:
                    raise self._fail(
                        f"{w.host} asked for a rank but all "
                        f"{self._world} slots are assigned")
                # elastic: a join against a full world is an implicit
                # scale-up generation of +1 (a gang-rescheduled slice
                # arriving after its old ranks were evicted lands here)
                self.request_resize(world=self._world + 1, reason="join")
                self._apply_pending_resize()
            self._pending.append(w)
            if self._todo and len(self._pending) >= len(self._todo):
                self._assign_pending()
        else:
            self._broker(w, rank)
            logger.debug("%s from rank %d", w.cmd, rank)

    def _assign_pending(self) -> None:
        """Batch-assign waiting joiners to the open ``todo`` slots
        (sorted by host for locality).  A resize can leave more joiners
        waiting than slots; the overflow stays pending for the next
        generation."""
        self._pending.sort(key=lambda x: x.host)
        assign, self._pending = (self._pending[:len(self._todo)],
                                 self._pending[len(self._todo):])
        for p in assign:
            rank = self._todo.pop(0)
            if p.jobid != "NULL":
                self._job_map[p.jobid] = rank
            self._broker(p, rank)
            logger.debug("assigned rank %d to %s", p.rank, p.host)
        if not self._todo:
            logger.info("@tracker all %d workers started", self._world)
            if self.start_time is None:
                self.start_time = time.time()

    # ---- heartbeat-driven failure detection ----------------------------
    def _elastic_snapshot(self) -> Dict:
        """The /healthz elastic block.  ``_evicted_total`` is mutated
        under ``_dead_lock`` so the read takes it too; ``gen``/``_world``
        are accept-loop-owned ints whose stale snapshot a health view
        tolerates (see their declarations)."""
        with self._dead_lock:
            evicted = self._evicted_total
        return {"enabled": self.elastic, "gen": self.gen,
                "world": self._world, "evicted_total": evicted}

    def _dead_snapshot(self) -> List[int]:
        with self._dead_lock:  # the monitor mutates the set concurrently
            return sorted(self.dead_ranks)

    def _clock_snapshot(self) -> Dict[str, Dict[str, float]]:
        return {str(r): s for r, s in self.flight.clock.snapshot().items()}

    def _note_admitted(self, rank: int, cmd: str) -> None:
        """A worker finished brokering under ``rank``: if that rank was
        declared dead, this is the supervised-restart re-admission."""
        with self._dead_lock:
            was_dead = rank in self.dead_ranks
            self.dead_ranks.discard(rank)
            self._dead_since.pop(rank, None)
            self._finished_ranks.discard(rank)
        self.telemetry.touch(rank)  # restart the miss-window clock
        if was_dead:
            from .. import telemetry

            telemetry.inc("resilience", "worker_readmitted")
            telemetry.record_event("worker_readmitted", rank=rank, cmd=cmd)
            logger.info("rank %d re-admitted via %r after being declared "
                        "dead", rank, cmd)

    def _declare_dead(self, rank: int, age: float) -> None:
        from .. import telemetry

        with self._dead_lock:
            if rank in self.dead_ranks:
                return
            self.dead_ranks.add(rank)
            # elastic grace clock: a rank still dead this long past the
            # declaration is evicted via a shrink generation
            self._dead_since.setdefault(rank, time.monotonic())
        telemetry.inc("resilience", "worker_declared_dead")
        telemetry.record_event("declared_dead", rank=rank,
                               age_s=round(age, 3),
                               miss_window_s=self.miss_window_s)
        logger.warning(
            "rank %d declared dead: no heartbeat for %.1fs (miss window "
            "%.1fs); dropping its connection and awaiting a replacement",
            rank, age, self.miss_window_s)
        entry = self._entries.pop(rank, None)
        if entry is not None:
            entry.sock.close()  # usually already closed by the worker
        if self._registry is not None:
            self._registry.drop(rank)
        # the replacement's step baselines start over (fresh process,
        # fresh compile warmup); its anomaly history stays in the ring
        self.watchdog.drop(rank)
        # goodput: the dead rank's wall keeps running as ``preempted``
        # until a relaunched process reports under this rank (or the
        # rank is evicted by a shrink, which drops it)
        self.goodput.mark_dead(rank)

    def _monitor_loop(self) -> None:
        interval = max(0.1, min(1.0, self.miss_window_s / 4))
        while not self._monitor_stop.wait(interval):
            with self._dead_lock:
                finished = set(self._finished_ranks)
            for rank, age in self.telemetry.ranks().items():
                if rank in finished:
                    continue  # clean shutdown: silence is expected
                if rank >= self._world:
                    # a pre-resize rank id lingering in the heartbeat
                    # store (its owner now beats under a renumbered
                    # rank): never a death, just stale bookkeeping
                    continue
                if age > self.miss_window_s:
                    self._declare_dead(rank, age)
                else:
                    # heartbeats resumed (replacement already pushing
                    # before its brokering finished): clear the flag
                    with self._dead_lock:
                        self.dead_ranks.discard(rank)
                        self._dead_since.pop(rank, None)
            if self.elastic:
                now = time.monotonic()
                with self._dead_lock:
                    expired = sorted(
                        r for r, t in self._dead_since.items()
                        if r in self.dead_ranks
                        and now - t > self.elastic_grace_s)
                if expired:
                    # still dead past the grace window: evict via a
                    # shrink generation (idempotent until applied by
                    # the accept loop at its next session)
                    self.request_resize(remove=expired,
                                        reason="grace_expired")

    def start(self, n_workers: Optional[int] = None) -> None:
        n = self.n_workers if n_workers is None else n_workers
        # dmlc-check: unguarded(written before thread exit; join() reads after)
        self.error: Optional[BaseException] = None

        def run():
            try:
                self._accept_loop(n)
            except BaseException as e:  # surfaced by join()/_await_job
                self.error = e
                logger.error("tracker accept loop died: %s", e)
            finally:
                self._monitor_stop.set()

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()
        if self.miss_window_s > 0 and self._monitor is None:
            self._monitor = threading.Thread(
                target=self._monitor_loop, daemon=True,
                name="tracker-failure-detector")
            self._monitor.start()

    def join(self, timeout: Optional[float] = None) -> None:
        assert self.thread is not None
        deadline = None if timeout is None else time.time() + timeout
        while self.thread.is_alive():
            self.thread.join(0.1)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("tracker did not finish in time")
        if self.error is not None:
            raise RuntimeError(f"tracker failed: {self.error}") from self.error

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def close(self) -> None:
        self._monitor_stop.set()
        try:
            self.sock.close()
        except OSError:
            pass
        if self.metrics_server is not None:
            self.metrics_server.close()
            self.metrics_server = None


def free_port(host_ip: str = "127.0.0.1") -> int:
    """Find a currently-free TCP port on ``host_ip`` without holding it."""
    probe = socket.socket()
    probe.settimeout(5.0)  # bind/getsockname never block, but keep the
    probe.bind((host_ip, 0))  # no-unbounded-socket invariant uniform
    port = probe.getsockname()[1]
    probe.close()
    return port


class PSTracker:
    """Parameter-server scheduler bootstrap (tracker.py:336-386 analog):
    runs the scheduler process locally with the PS env contract."""

    def __init__(self, host_ip: str, cmd: Optional[str], envs: Dict[str, str],
                 port: int = 9091, port_end: int = 9999):
        self.host_ip = host_ip
        self.cmd = cmd
        # dmlc-check: unguarded(start/join control-thread lifecycle)
        self.thread = None
        self.proc: Optional[subprocess.Popen] = None
        # dmlc-check: unguarded(written before the watcher thread exits; join() reads after it)
        self.error: Optional[BaseException] = None
        # dmlc-check: unguarded(control-thread terminate latch; watcher read race is benign)
        self._terminated = False
        self.port = free_port(host_ip)
        if cmd is None:
            return
        env = os.environ.copy()
        env.update(envs)
        env.update({
            "DMLC_ROLE": "scheduler",
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        })
        # Popen (not check_call) so an aborting job can terminate() the
        # scheduler: a lingering scheduler child inherits the launcher's
        # stdio and keeps a captured pipe open long after dmlc-submit
        # exits, hanging whoever waits on that pipe.
        self.proc = subprocess.Popen(self.cmd, shell=True, env=env)

        def run():
            # a dead scheduler must abort the job fast, not leave every
            # worker hanging on DMLC_PS_ROOT_PORT — record the failure
            # for _await_job/join instead of losing it in a daemon thread
            try:
                rc = self.proc.wait()
                if rc != 0 and not self._terminated:
                    raise RuntimeError(f"scheduler exited {rc}")
            except BaseException as e:
                self.error = e
                logger.error("PS scheduler died: %s", e)

        self.thread = threading.Thread(target=run, daemon=True)
        self.thread.start()

    def terminate(self) -> None:
        """Kill the scheduler process (job abort path).  Flagged first
        so the watcher thread reports the deliberate kill as cleanup,
        not as a scheduler failure."""
        self._terminated = True
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()

    def worker_envs(self) -> Dict[str, str]:
        return {
            "DMLC_PS_ROOT_URI": str(self.host_ip),
            "DMLC_PS_ROOT_PORT": str(self.port),
        }

    def alive(self) -> bool:
        return self.thread is not None and self.thread.is_alive()

    def join(self) -> None:
        if self.thread is not None:
            self.thread.join()
        if self.error is not None:
            raise RuntimeError(
                f"PS scheduler failed: {self.error}") from self.error


def submit_job(n_workers: int, n_servers: int, fun_submit, host_ip: str = "auto",
               pscmd: Optional[str] = None, join: bool = True):
    """Start tracker(s), call fun_submit(n_workers, n_servers, envs), wait.

    The reference's tracker.submit (tracker.py:410-433): rabit path when
    n_servers == 0, PS path otherwise.
    """
    if host_ip == "auto":
        host_ip = get_env("DMLC_TRACKER_URI", "") or _default_host_ip()
    envs = {"DMLC_NUM_WORKER": str(n_workers),
            "DMLC_NUM_SERVER": str(n_servers)}
    # The jax.distributed coordinator is a gRPC service that rank 0 of the
    # JOB must host — it cannot share DMLC_TRACKER_PORT, which is the rabit
    # tracker's own listener in THIS process.  The tracker owns port
    # assignment, so it hands out a distinct free port; the URI defaults to
    # the tracker host (right for local jobs; gang backends override it
    # with the host where task 0 is placed).  The freeness probe runs on
    # THIS machine — for remote coordinators it is only a sane default;
    # override with --env DMLC_JAX_COORD_PORT=... if it collides there.
    envs["DMLC_JAX_COORD_URI"] = host_ip
    envs["DMLC_JAX_COORD_PORT"] = str(free_port(host_ip))
    rabit = ps = None
    if n_servers == 0:
        rabit = RabitTracker(host_ip, n_workers)
        envs.update(rabit.worker_envs())
        rabit.start(n_workers)
    else:
        ps = PSTracker(host_ip, pscmd, envs)
        envs.update(ps.worker_envs())
    fun_submit(n_workers, n_servers, envs)
    if join and rabit is not None:
        rabit.join()
    if join and ps is not None:
        ps.join()  # raises if the scheduler died — sge has no _await_job
    # PS path returns the PSTracker so callers (_await_job) can watch the
    # scheduler's liveness/error the same way they watch the rabit tracker
    return rabit if rabit is not None else ps


def _default_host_ip() -> str:
    """Best-effort local IP (no egress needed: UDP connect is routing-only)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.settimeout(5.0)
        s.connect(("10.255.255.255", 1))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
