"""Distributed job launcher + rank-rendezvous tracker (L7).

Rebuild of the reference control plane (tracker/dmlc_tracker/): the
tracker assigns ranks, computes the binomial-tree + shared-ring overlay,
and brokers peer connections over a TCP protocol (magic 0xff99); launch
backends start worker/server processes on local, ssh, mpi, sge, slurm
and TPU-VM clusters.  Unlike the reference, the worker-side protocol
client ships here too (tracker.client) so the rendezvous is testable
in-repo, and ssh/slurm are actually routed in the dispatcher (fixing
reference submit.py:42-53 which leaves them unreachable).

On TPU the data plane is XLA collectives (parallel/); this layer remains
the control plane: gang-scheduling, retries, rank contract, env vars.
"""

from .protocol import MAGIC, FrameSocket, link_maps  # noqa: F401
from .rendezvous import PSTracker, RabitTracker, submit_job  # noqa: F401
from .client import TrackerClient, WorldResized  # noqa: F401
