#!/usr/bin/env python3
"""In-container task bootstrap (reference tracker launcher.py:18-77 role).

Runs ON THE REMOTE HOST before the user command, so it is deliberately
standalone — no dmlc_tpu imports (the launcher ships this single file
into the job cache dir next to the user's binaries).  Duties:

  * enforce the DMLC_JOB_CLUSTER contract;
  * derive DMLC_ROLE for SGE array tasks (task_id < num_worker → worker,
    else server — reference launcher.py:42-47);
  * enter DMLC_JOB_CACHE_DIR (where the submitter staged cached files);
  * unpack DMLC_JOB_ARCHIVES (colon-separated .zip/.tar[.gz] names) into
    the workdir, the python-library shipping mechanism;
  * prepend the workdir to PATH and LD_LIBRARY_PATH so `./prog` and
    shipped .so files resolve;
  * exec the user command, propagating its exit code.

Usage: python3 bootstrap.py [--] command args...
"""

import os
import subprocess
import sys


def unpack_archives(names, workdir):
    import tarfile
    import zipfile

    for name in names:
        path = os.path.join(workdir, name)
        if not os.path.exists(path):
            continue
        if name.endswith(".zip"):
            with zipfile.ZipFile(path) as z:
                z.extractall(workdir)
        elif ".tar" in name or name.endswith(".tgz"):
            with tarfile.open(path) as t:
                try:
                    t.extractall(workdir, filter="data")  # no path traversal
                except TypeError:  # Python < 3.12: no filter= kwarg
                    # manual screen: absolute paths, .. components, and
                    # links pointing outside the cache dir are rejected —
                    # a shipped archive must not escape workdir
                    for m in t.getmembers():
                        parts = m.name.split("/")
                        if (m.name.startswith("/") or ".." in parts
                                or not (m.isfile() or m.isdir())):
                            # allow-list plain files/dirs: links escape
                            # the dir, FIFOs/devices hang later readers
                            raise ValueError(
                                f"unsafe archive member {m.name!r} in "
                                f"{name!r}")
                    t.extractall(workdir)


def main(argv):
    if argv and argv[0] == "--":
        argv = argv[1:]
    if not argv:
        print("Usage: bootstrap.py [--] command args...", file=sys.stderr)
        return 2

    env = os.environ.copy()
    if not env.get("DMLC_JOB_CLUSTER"):
        print("bootstrap: DMLC_JOB_CLUSTER must be set", file=sys.stderr)
        return 2

    if env["DMLC_JOB_CLUSTER"] == "sge" and "DMLC_ROLE" not in env:
        task_id = int(env["DMLC_TASK_ID"])
        n_workers = int(env["DMLC_NUM_WORKER"])
        env["DMLC_ROLE"] = "worker" if task_id < n_workers else "server"

    workdir = env.get("DMLC_JOB_CACHE_DIR")
    if workdir and os.path.isdir(workdir):
        os.chdir(workdir)
    workdir = os.getcwd()

    if env.get("DMLC_JOB_ARCHIVES"):
        unpack_archives(env["DMLC_JOB_ARCHIVES"].split(":"), workdir)

    env["PATH"] = workdir + os.pathsep + env.get("PATH", "")
    ld = env.get("LD_LIBRARY_PATH", "")
    env["LD_LIBRARY_PATH"] = (ld + os.pathsep if ld else "") + workdir

    return subprocess.call(argv, env=env)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
