"""dmlc-submit dispatcher (reference tracker/dmlc_tracker/submit.py).

Routes every cluster backend — including ssh and slurm, which the
reference parses but never dispatches (submit.py:42-53)."""

from __future__ import annotations

import logging
import sys

from . import launch
from .opts import get_opts


def _submit_yarn(args):
    raise SystemExit(
        "yarn backend is not supported in the TPU rebuild; use --cluster "
        "tpu-vm for gang-scheduled slices (the YARN-AM role) or ssh/slurm"
    )


DISPATCH = {
    "local": launch.submit_local,
    "ssh": launch.submit_ssh,
    "mpi": launch.submit_mpi,
    "sge": launch.submit_sge,
    "slurm": launch.submit_slurm,
    "mesos": launch.submit_mesos,
    "tpu-vm": launch.submit_tpu_vm,
    "yarn": _submit_yarn,
}


def main(argv=None):
    args = get_opts(argv)
    handlers = None
    if args.log_file:
        handlers = [logging.FileHandler(args.log_file),
                    logging.StreamHandler()]
    logging.basicConfig(
        level=getattr(logging, args.log_level),
        format="%(asctime)s %(levelname)s %(message)s",
        handlers=handlers,
    )
    return DISPATCH[args.cluster](args)


if __name__ == "__main__":
    main(sys.argv[1:])
