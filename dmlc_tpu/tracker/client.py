"""Worker-side rendezvous client + host-side tree collectives.

The reference keeps the worker half of the tracker protocol downstream
(in rabit); shipping it here makes the rendezvous testable in-repo and
gives native consumers a host-side allreduce fallback for control-plane
data (the TPU data plane is XLA collectives, parallel/collectives.py).

Peer links established through tracker brokering are real TCP
connections; peers identify themselves with (MAGIC, rank) frames after
connect.
"""

from __future__ import annotations

import os
import socket
from typing import Dict, Optional

import numpy as np

from .protocol import MAGIC, FrameSocket

__all__ = ["TrackerClient"]


class TrackerClient:
    """One worker's connection to the tracker and its peer overlay."""

    def __init__(self, tracker_uri: Optional[str] = None,
                 tracker_port: Optional[int] = None,
                 jobid: Optional[str] = None):
        self.tracker_uri = tracker_uri or os.environ.get(
            "DMLC_TRACKER_URI", "127.0.0.1")
        self.tracker_port = int(
            tracker_port or os.environ.get("DMLC_TRACKER_PORT", "9091"))
        self.jobid = jobid or os.environ.get("DMLC_TASK_ID", "NULL")
        self.rank = -1
        self.world_size = -1
        self.parent = -1
        self.tree_nbrs = []
        self.ring_prev = -1
        self.ring_next = -1
        self.links: Dict[int, FrameSocket] = {}
        self._listener: Optional[socket.socket] = None

    # ---- tracker session helpers ---------------------------------------
    def _dial(self) -> FrameSocket:
        s = socket.create_connection((self.tracker_uri, self.tracker_port))
        fs = FrameSocket(s)
        fs.send_int(MAGIC)
        assert fs.recv_int() == MAGIC
        return fs

    def _session(self, cmd: str, rank: int, world: int) -> FrameSocket:
        fs = self._dial()
        fs.send_int(rank)
        fs.send_int(world)
        fs.send_str(self.jobid)
        fs.send_str(cmd)
        return fs

    # ---- rendezvous ----------------------------------------------------
    def start(self, world_size: int = -1, cmd: str = "start") -> "TrackerClient":
        """Rendezvous: obtain rank + topology, establish peer links."""
        if self._listener is not None:  # recover: drop the old accept port
            self._listener.close()
        self._listener = socket.socket()
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(16)
        my_port = self._listener.getsockname()[1]

        fs = self._session(cmd, self.rank, world_size)
        self.rank = fs.recv_int()
        self.parent = fs.recv_int()
        self.world_size = fs.recv_int()
        n_nbrs = fs.recv_int()
        self.tree_nbrs = [fs.recv_int() for _ in range(n_nbrs)]
        self.ring_prev = fs.recv_int()
        self.ring_next = fs.recv_int()

        # brokering dance: report already-good links, connect to assigned
        # peers, then report our accept port
        good = sorted(self.links.keys())
        fs.send_int(len(good))
        for r in good:
            fs.send_int(r)
        n_conn = fs.recv_int()
        n_accept = fs.recv_int()
        for _ in range(n_conn):
            host = fs.recv_str()
            port = fs.recv_int()
            peer_rank = fs.recv_int()
            ps = FrameSocket(socket.create_connection((host, port)))
            ps.send_int(MAGIC)
            ps.send_int(self.rank)
            assert ps.recv_int() == MAGIC
            got = ps.recv_int()
            assert got == peer_rank, (got, peer_rank)
            self.links[peer_rank] = ps
        fs.send_int(0)          # nerr
        fs.send_int(my_port)
        fs.close()

        for _ in range(n_accept):
            conn, _ = self._listener.accept()
            ps = FrameSocket(conn)
            assert ps.recv_int() == MAGIC
            peer_rank = ps.recv_int()
            ps.send_int(MAGIC)
            ps.send_int(self.rank)
            self.links[peer_rank] = ps
        return self

    def recover(self) -> "TrackerClient":
        """Reconnect after restart keeping our rank (tracker 'recover')."""
        assert self.rank >= 0
        for fs in self.links.values():
            fs.close()
        self.links = {}
        return self.start(cmd="recover")

    # ---- tracker utility commands --------------------------------------
    def log(self, msg: str) -> None:
        fs = self._session("print", self.rank, -1)
        fs.send_str(msg)
        fs.close()

    def send_metrics(self, payload: str) -> None:
        """Push a telemetry heartbeat (JSON snapshot) to the tracker's
        aggregator over a short ``metrics`` session — same session shape
        as the ``print`` relay.  See telemetry.heartbeat.HeartbeatSender
        for the periodic-push wrapper."""
        fs = self._session("metrics", self.rank, -1)
        fs.send_str(payload)
        fs.close()

    def shutdown(self) -> None:
        fs = self._session("shutdown", self.rank, -1)
        fs.close()
        for ps in self.links.values():
            ps.close()
        self.links = {}
        if self._listener is not None:
            self._listener.close()

    # ---- host-side tree collectives ------------------------------------
    def _send_array(self, fs: FrameSocket, arr: np.ndarray) -> None:
        data = arr.tobytes()
        fs.send_int(len(data))
        fs.sock.sendall(data)

    def _recv_array(self, fs: FrameSocket, like: np.ndarray) -> np.ndarray:
        n = fs.recv_int()
        return np.frombuffer(fs.recv_all(n), dtype=like.dtype).reshape(like.shape)

    def allreduce(self, arr: np.ndarray, op: str = "sum") -> np.ndarray:
        """Binomial-tree allreduce (reduce to root, broadcast back).
        op ∈ {sum, max, min}."""
        fold = {"sum": np.add, "max": np.maximum, "min": np.minimum}[op]
        arr = np.ascontiguousarray(arr)
        if self.world_size <= 1:
            return arr.copy()
        children = [r for r in self.tree_nbrs if r != self.parent]
        acc = arr.astype(arr.dtype, copy=True)
        for c in children:
            acc = fold(acc, self._recv_array(self.links[c], acc))
        if self.parent >= 0:
            self._send_array(self.links[self.parent], acc)
            acc = self._recv_array(self.links[self.parent], acc)
        for c in children:
            self._send_array(self.links[c], acc)
        return acc

    def allreduce_sum(self, arr: np.ndarray) -> np.ndarray:
        return self.allreduce(arr, "sum")

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        """Tree broadcast from root (root's value wins everywhere)."""
        arr = np.ascontiguousarray(arr)
        if self.world_size <= 1:
            return arr.copy()
        assert root == 0, "tree broadcast is rooted at rank 0"
        children = [r for r in self.tree_nbrs if r != self.parent]
        out = arr
        if self.parent >= 0:
            out = self._recv_array(self.links[self.parent], arr)
        for c in children:
            self._send_array(self.links[c], out)
        return out.copy() if out is arr else out
