"""Worker-side rendezvous client + host-side tree collectives.

The reference keeps the worker half of the tracker protocol downstream
(in rabit); shipping it here makes the rendezvous testable in-repo and
gives native consumers a host-side allreduce fallback for control-plane
data (the TPU data plane is XLA collectives, parallel/collectives.py).

Peer links established through tracker brokering are real TCP
connections; peers identify themselves with (MAGIC, rank) frames after
connect.

Elasticity: against an elastic tracker (``DMLC_ELASTIC=1``) the world
size is a run-time variable.  Every host-collective array frame carries
the world *generation* id, so traffic from a stale generation is
rejected instead of folded into the reduction; a collective interrupted
by a peer loss (or by a tracker-announced generation change, delivered
as a piggyback on the heartbeat reply) raises the retryable
:class:`WorldResized` instead of hanging — bounded by the
``DMLC_CLIENT_*`` socket timeouts — and :meth:`TrackerClient.resize`
re-enters rendezvous to learn the new rank/world and rebuild the
overlay.  Against a non-elastic tracker nothing changes: peer loss
stays an ``OSError`` and ``recover()`` keeps the same-rank semantics.
"""

from __future__ import annotations

import json
import logging
import select
import socket
import time
from typing import Dict, Optional

import numpy as np

from ..base import DMLCError, check, get_env
from ..resilience import RetryPolicy, fault_point
from .protocol import MAGIC, FrameSocket, recover_cmd

__all__ = ["TrackerClient", "WorldResized"]

logger = logging.getLogger("dmlc_tpu.tracker")


class WorldResized(DMLCError):
    """The elastic world changed under a collective: a peer was lost, a
    stale-generation frame arrived, or the tracker announced a new
    generation.  Retryable — the raising client has already torn down
    its peer links (waking peers blocked on them, so the whole gang
    cascades out of the dead collective); call
    :meth:`TrackerClient.resize` to re-enter rendezvous, learn the new
    rank/world, restore state from the last checkpoint, and retry."""

    def __init__(self, msg: str, gen: int = -1):
        super().__init__(msg)
        self.gen = gen


def _coll_algo_env() -> str:
    """Default allreduce algorithm (``DMLC_COLL_ALGO``):

    * ``auto`` (default) — the hierarchical shm+ring path (C shm
      collective per host + chunked ring across host leaders) from
      DMLC_COLL_HIER_MIN_BYTES (64 KB) up when it can be set up, the
      flat chunked ring from DMLC_COLL_RING_MIN_BYTES (1 MB) when it
      cannot, the binomial tree below both cutovers.
    * ``tree`` / ``ring`` / ``hier`` — pin the algorithm (``hier``
      still degrades to ``ring`` when no shm segment can be mapped,
      with a one-time warning, so a heterogeneous fleet never hangs).
    """
    algo = get_env("DMLC_COLL_ALGO", "auto").strip().lower()
    if algo not in ("auto", "tree", "ring", "hier"):
        raise ValueError(f"DMLC_COLL_ALGO={algo!r} not in "
                         "tree|ring|hier|auto")
    return algo


class _HierState:
    """Per-generation hierarchical-collective state: host groups, this
    rank's shm group handle, and the leader sub-ring."""

    __slots__ = ("gen", "ok", "shm", "group", "local_rank", "leader",
                 "leaders", "leader_idx", "n_groups", "warned")

    def __init__(self, gen: int):
        self.gen = gen
        self.ok = False
        self.shm = None           # native.shm_collective.ShmCollective
        self.group = []           # my host group's ranks, sorted
        self.local_rank = 0
        self.leader = -1          # my group's leader (min rank)
        self.leaders = []         # every group's leader, group order
        self.leader_idx = 0
        self.n_groups = 0
        self.warned = False


def _hier_min_bytes() -> int:
    """Payload size at which ``auto`` prefers the hierarchical shm+ring
    path (DMLC_COLL_HIER_MIN_BYTES, default 64 KB — bench_collective's
    cutover sweep shows the shm leg already beating both tree and flat
    ring there; below it the tree's 2·log2(n) latency wins).  Negative
    disables hier in auto mode."""
    return get_env("DMLC_COLL_HIER_MIN_BYTES", 64 << 10)


def _ring_min_bytes() -> int:
    """Payload size at which allreduce cuts over from the binomial tree
    to the chunked ring (DMLC_COLL_RING_MIN_BYTES, default 1 MB; 0
    forces the ring whenever links exist, negative disables it).

    The tree finishes in 2·log2(n) hops but moves the FULL payload
    through every tree level — its per-link traffic does not shrink
    with n.  The ring pays 2·(n-1) latency rounds but each rank only
    ever sends 2·(n-1)/n of the payload, all links busy at once, so it
    wins as soon as bandwidth dominates latency.  Small control-plane
    messages stay on the tree."""
    return get_env("DMLC_COLL_RING_MIN_BYTES", 1 << 20)


_RING_PIECE = 1 << 20  # sub-chunk granularity for the duplex transfer


def _connect_timeout() -> Optional[float]:
    """Per-dial connect timeout (DMLC_CLIENT_CONNECT_TIMEOUT_S, default
    15; 0 disables).  Bounds how long one attempt can hang on a dead
    tracker or peer before the reconnect backoff takes over."""
    t = get_env("DMLC_CLIENT_CONNECT_TIMEOUT_S", 15.0)
    return t if t > 0 else None


def _op_timeout() -> Optional[float]:
    """Per-socket operation timeout (DMLC_CLIENT_OP_TIMEOUT_S, default
    300 — the DMLC_TRACKER_TIMEOUT / shm-collective companion; 0
    disables).  A tracker or peer that dies without a FIN raises
    ``socket.timeout`` (an OSError, so the recover path catches it)
    instead of blocking a recv forever."""
    t = get_env("DMLC_CLIENT_OP_TIMEOUT_S", 300.0)
    return t if t > 0 else None


def _resize_timeout() -> float:
    """Upper bound on one resize() re-rendezvous, settle-wait included
    (DMLC_ELASTIC_RESIZE_TIMEOUT_S, default 120)."""
    return get_env("DMLC_ELASTIC_RESIZE_TIMEOUT_S", 120.0)


def _dial_policy() -> RetryPolicy:
    """Reconnect-with-backoff for tracker dials (DMLC_CLIENT_RETRIES,
    default 5): rides out a tracker restart / slow bind instead of
    failing the worker on the first refused connection."""
    return RetryPolicy.from_env(retries_env="DMLC_CLIENT_RETRIES",
                                default_attempts=5,
                                base_env="DMLC_CLIENT_RETRY_BASE_S",
                                default_base=0.3, name="tracker_dial")


class TrackerClient:
    """One worker's connection to the tracker and its peer overlay."""

    def __init__(self, tracker_uri: Optional[str] = None,
                 tracker_port: Optional[int] = None,
                 jobid: Optional[str] = None):
        self.tracker_uri = tracker_uri or get_env(
            "DMLC_TRACKER_URI", "127.0.0.1")
        self.tracker_port = int(
            tracker_port or get_env("DMLC_TRACKER_PORT", "9091"))
        self.jobid = jobid or get_env("DMLC_TASK_ID", "NULL")
        self.rank = -1
        self.world_size = -1
        self.parent = -1
        self.tree_nbrs = []
        self.ring_prev = -1
        self.ring_next = -1
        self.links: Dict[int, FrameSocket] = {}
        self._listener: Optional[socket.socket] = None
        # elastic state: generation of the topology this client holds,
        # and whether the tracker runs elastic at all (learned from the
        # `gen` query after every rendezvous).  _resize_pending is set by
        # the heartbeat thread (gen piggyback on the metrics reply) and
        # consumed on the worker thread at the next collective entry —
        # a plain bool flag, single-writer/single-reader.
        self.gen = 0
        self.elastic = False
        self._resize_pending = False
        self._hier: Optional[_HierState] = None

    # ---- tracker session helpers ---------------------------------------
    def _dial(self) -> FrameSocket:
        """Connect to the tracker with timeouts + backoff: a dead or
        restarting tracker yields a prompt, classified failure (after
        DMLC_CLIENT_RETRIES attempts) instead of an indefinite hang."""

        def attempt() -> FrameSocket:
            fault_point("tracker.dial", host=self.tracker_uri)
            s = socket.create_connection(
                (self.tracker_uri, self.tracker_port),
                timeout=_connect_timeout())
            s.settimeout(_op_timeout())
            fs = FrameSocket(s)
            try:
                fs.send_int(MAGIC)
                if fs.recv_int() != MAGIC:
                    raise ConnectionError("tracker answered bad magic")
            except BaseException:
                fs.close()
                raise
            return fs

        return _dial_policy().call(attempt)

    def _session(self, cmd: str, rank: int, world: int) -> FrameSocket:
        fs = self._dial()
        fs.send_int(rank)
        fs.send_int(world)
        fs.send_str(self.jobid)
        fs.send_str(cmd)
        return fs

    # ---- rendezvous ----------------------------------------------------
    def start(self, world_size: int = -1, cmd: str = "start") -> "TrackerClient":
        """Rendezvous: obtain rank + topology, establish peer links."""
        if self._listener is not None:  # recover: drop the old accept port
            self._listener.close()
        self._listener = socket.socket()
        self._listener.bind(("0.0.0.0", 0))
        self._listener.listen(16)
        # a gang-mate dying before it dials us must not park accept()
        # forever: surface as socket.timeout -> OSError -> recover path
        self._listener.settimeout(_op_timeout())
        my_port = self._listener.getsockname()[1]

        fs = self._session(cmd, self.rank, world_size)
        self.rank = fs.recv_int()
        self.parent = fs.recv_int()
        self.world_size = fs.recv_int()
        n_nbrs = fs.recv_int()
        self.tree_nbrs = [fs.recv_int() for _ in range(n_nbrs)]
        self.ring_prev = fs.recv_int()
        self.ring_next = fs.recv_int()

        # brokering dance: report already-good links, connect to assigned
        # peers, then report our accept port.  A failed peer dial (the
        # peer died, or the tracker handed out a stale endpoint before
        # its failure detector caught the death) is REPORTED as a dial
        # error — the tracker restarts the round — instead of crashing
        # this worker; rounds are bounded so a permanently-dead peer
        # still surfaces as an error rather than a livelock.
        policy = _dial_policy()
        round_no = 0
        while True:
            good = sorted(self.links.keys())
            fs.send_int(len(good))
            for r in good:
                fs.send_int(r)
            n_conn = fs.recv_int()
            n_accept = fs.recv_int()
            n_errors = 0
            for _ in range(n_conn):
                host = fs.recv_str()
                port = fs.recv_int()
                peer_rank = fs.recv_int()
                try:
                    self.links[peer_rank] = self._dial_peer(host, port,
                                                            peer_rank)
                except OSError:
                    n_errors += 1
            fs.send_int(n_errors)
            if n_errors == 0:
                break
            round_no += 1
            if round_no >= policy.attempts:
                fs.close()
                raise ConnectionError(
                    f"rank {self.rank}: peer dials kept failing after "
                    f"{round_no} brokering rounds")
            policy.sleep_for(round_no - 1)  # let dead peers get culled
        fs.send_int(my_port)
        fs.close()

        for _ in range(n_accept):
            conn, _ = self._listener.accept()
            conn.settimeout(_op_timeout())
            ps = FrameSocket(conn)
            assert ps.recv_int() == MAGIC
            peer_rank = ps.recv_int()
            ps.send_int(MAGIC)
            ps.send_int(self.rank)
            self.links[peer_rank] = ps
        # learn the world generation this topology belongs to (and
        # whether the tracker is elastic at all) — a separate short
        # session so the topology wire format stays C-ABI compatible
        info = self._query_gen()
        self.gen = int(info.get("gen", 0))
        self.elastic = bool(info.get("elastic", False))
        self._resize_pending = False
        return self

    def _dial_peer(self, host: str, port: int, peer_rank: int,
                   handshake_timeout: Optional[float] = None) -> FrameSocket:
        """One peer link: connect + (MAGIC, rank) identification.
        ``handshake_timeout`` bounds the identification exchange (the
        hier leader dance uses a short one so a peer that bailed on
        setup cannot stall the gang); the socket reverts to the normal
        op timeout once the link is up."""
        s = socket.create_connection((host, port),
                                     timeout=_connect_timeout())
        s.settimeout(handshake_timeout or _op_timeout())
        ps = FrameSocket(s)
        try:
            ps.send_int(MAGIC)
            ps.send_int(self.rank)
            if ps.recv_int() != MAGIC:
                raise ConnectionError(f"peer {peer_rank} at {host}:{port} "
                                      f"answered bad magic")
            got = ps.recv_int()
            if got != peer_rank:
                raise ConnectionError(f"dialed {host}:{port} expecting "
                                      f"rank {peer_rank}, got {got}")
        except BaseException:
            ps.close()
            raise
        if handshake_timeout is not None:
            s.settimeout(_op_timeout())
        return ps

    def recover(self) -> "TrackerClient":
        """Reconnect after restart keeping our rank (tracker 'recover')."""
        assert self.rank >= 0
        self._links_down()
        return self.start(cmd="recover")

    # ---- elastic world resize ------------------------------------------
    def _links_down(self) -> None:
        """Close every peer link.  Beyond local cleanup this is the
        resize *cascade*: a peer blocked mid-collective on one of these
        sockets wakes with a ConnectionError, raises its own
        WorldResized, closes ITS links — so one lost rank propagates to
        the whole gang without any tracker push channel."""
        for fs in self.links.values():
            fs.close()
        self.links = {}
        # the shm half of the cascade: same-host peers blocked inside a
        # hier shm phase see no socket die, so poison the group too
        self._hier_teardown()

    def _resized(self, why: str, cause: Optional[BaseException] = None):
        from .. import telemetry

        self._links_down()
        telemetry.record_event("world_resized_signal", rank=self.rank,
                               gen=self.gen, why=why)
        err = WorldResized(
            f"rank {self.rank} (gen {self.gen}): {why}; call resize() to "
            f"re-enter rendezvous", gen=self.gen)
        if cause is not None:
            raise err from cause
        raise err

    @property
    def resize_pending(self) -> bool:
        """True once the tracker has announced a newer generation (via
        the heartbeat reply) than the topology this client holds."""
        return self._resize_pending

    def check_resized(self) -> None:
        """Raise :class:`WorldResized` if the tracker announced a new
        generation since the last rendezvous — the cheap per-step check
        for loops that do not touch a host collective every step."""
        if self.elastic and self._resize_pending:
            self._resized("world generation advanced (tracker heartbeat)")

    def _query_gen(self) -> dict:
        """Short ``gen`` session: the tracker's current generation,
        world size, elastic flag and dead-rank count."""
        fs = self._session("gen", self.rank, -1)
        try:
            return json.loads(fs.recv_str())
        finally:
            fs.close()

    def _await_settle(self, old_gen: int, deadline: float) -> int:
        """Wait for the membership change behind a WorldResized to
        settle before re-entering rendezvous: either the tracker opened
        a new generation (gen advances — shrink past grace, or a
        scale-up), or the lost rank was re-admitted at its old rank
        within the grace window (dead count returns to zero — the PR 2
        supervised-restart path, same generation).  Re-entering blind
        would park this worker in a brokering round that waits on a
        peer the tracker has not yet culled."""
        seen_dead = False
        poll = 0.05
        while True:
            try:
                info = self._query_gen()
            except (OSError, ValueError):
                info = None  # tracker mid-restart: keep polling
            if info is not None:
                gen = int(info.get("gen", 0))
                if gen > old_gen:
                    return gen
                if int(info.get("dead", 0)) > 0:
                    seen_dead = True
                elif seen_dead:
                    return gen  # same-gen readmission completed
            if time.monotonic() > deadline:
                logger.warning(
                    "rank %d: resize settle-wait timed out (gen still "
                    "%d); attempting a same-generation recover", self.rank,
                    old_gen)
                return old_gen
            time.sleep(poll)
            poll = min(poll * 1.5, 0.5)

    def resize(self, timeout_s: Optional[float] = None) -> "TrackerClient":
        """Re-enter rendezvous after :class:`WorldResized`.

        Waits for the tracker's membership change to settle, then
        announces ``recover@<old-gen>`` — the tracker translates the
        stale rank through its generation maps into this worker's rank
        in the new dense ``[0, N')`` space (or admits it as a scale-up
        join if it was evicted while away) and re-brokers the overlay.
        On return ``rank``/``world_size``/``gen`` describe the new
        world; the caller owns restoring training state (checkpoint
        restore onto the new mesh) and repartitioning data
        (``DeviceFeed.resize``).  Bounded by ``timeout_s`` (default
        ``DMLC_ELASTIC_RESIZE_TIMEOUT_S``)."""
        from .. import telemetry

        check(self.rank >= 0, "resize() before a successful rendezvous")
        t = _resize_timeout() if timeout_s is None else float(timeout_s)
        deadline = time.monotonic() + t
        rank0, gen0 = self.rank, self.gen
        self._links_down()
        self._resize_pending = False
        last: Optional[BaseException] = None
        while True:
            settled = self._await_settle(gen0, deadline)
            cmd = recover_cmd(gen0) if settled > gen0 else "recover"
            self.rank = rank0  # announce in terms of the OLD identity
            try:
                self.start(cmd=cmd)
            except (OSError, ConnectionError) as e:
                # a racing second resize (another death mid-recovery)
                # can break this rendezvous; retry against the newest
                # generation until the deadline
                last = e
                self._links_down()
                if time.monotonic() > deadline:
                    raise DMLCError(
                        f"rank {rank0}: resize did not complete within "
                        f"{t:.0f}s: {last}") from last
                time.sleep(0.2)
                continue
            telemetry.inc("elastic", "client_resizes")
            telemetry.record_event(
                "client_resized", old_rank=rank0, rank=self.rank,
                old_gen=gen0, gen=self.gen, world=self.world_size)
            logger.info(
                "rank %d (gen %d) resized -> rank %d/%d (gen %d)",
                rank0, gen0, self.rank, self.world_size, self.gen)
            return self

    # ---- tracker utility commands --------------------------------------
    def log(self, msg: str) -> None:
        fs = self._session("print", self.rank, -1)
        fs.send_str(msg)
        fs.close()

    def send_metrics(self, payload: str) -> None:
        """Push a telemetry heartbeat (JSON snapshot) to the tracker's
        aggregator over a short ``metrics`` session — same session shape
        as the ``print`` relay.  See telemetry.heartbeat.HeartbeatSender
        for the periodic-push wrapper.

        The tracker's reply carries its current world generation: the
        heartbeat doubles as the scale-up push channel — when the
        generation advances with no link dying (a grow resize), this is
        how a survivor learns it must re-enter rendezvous."""
        fs = self._session("metrics", self.rank, -1)
        try:
            fs.send_str(payload)
            gen = fs.recv_int()
        finally:
            fs.close()
        if self.elastic and gen > self.gen:
            self._resize_pending = True

    def clock_ping(self) -> tuple:
        """One NTP-style clock exchange with the tracker: returns
        ``(offset_s, rtt_s)`` where ``tracker_time = local_time +
        offset_s``.  The tracker stamps receipt/reply times in its
        accept loop (``clock`` session); the sample ships with the next
        telemetry heartbeat so the tracker can place this rank's spans
        on the cluster timeline (telemetry.clock / telemetry.flight)."""
        from ..telemetry.clock import offset_from_timestamps

        # connect + handshake happen BEFORE t0 is stamped: the dial can
        # pay reconnect backoff and 4 handshake frames, and folding that
        # into the forward path would bias every offset sample positive
        # by ~half the setup cost (the tracker stamps t1 only when the
        # payload frame lands).  t0..t3 must bracket ONLY the ping
        # round-trip itself.
        fs = self._session("clock", self.rank, -1)
        try:
            t0 = time.time()
            fs.send_str(json.dumps({"t0": t0}))
            reply_raw = fs.recv_str()
            t3 = time.time()
        finally:
            fs.close()
        reply = json.loads(reply_raw)
        return offset_from_timestamps(
            t0, float(reply["t1"]), float(reply["t2"]), t3)

    def shutdown(self) -> None:
        # elastic: stamp the generation our rank belongs to — a resize
        # we never re-brokered into may have renumbered it, and the
        # tracker must mark the right completion slot (or ignore us if
        # we were evicted while finishing)
        cmd = f"shutdown@{self.gen}" if self.elastic else "shutdown"
        fs = self._session(cmd, self.rank, -1)
        fs.close()
        for ps in self.links.values():
            ps.close()
        self.links = {}
        self._hier_teardown()
        if self._listener is not None:
            self._listener.close()

    # ---- host-side tree collectives ------------------------------------
    def _send_array(self, fs: FrameSocket, arr: np.ndarray) -> None:
        # every array frame is generation-stamped: (gen, nbytes, data).
        # Python-to-Python only — the C-ABI workers run their own
        # collective framing over their own links, never these.
        data = arr.tobytes()
        fs.send_int(self.gen)
        fs.send_int(len(data))
        fs.sock.sendall(data)

    def _recv_array(self, fs: FrameSocket, like: np.ndarray) -> np.ndarray:
        g = fs.recv_int()
        if g != self.gen:
            # a stale (or future) generation's traffic must never be
            # folded into this reduction — reject the frame and force
            # both sides back through rendezvous
            self._resized(f"stale-generation frame (peer gen {g}, "
                          f"ours {self.gen})")
        n = fs.recv_int()
        return np.frombuffer(fs.recv_all(n), dtype=like.dtype).reshape(like.shape)

    def allreduce(self, arr: np.ndarray, op: str = "sum",
                  algo: Optional[str] = None,
                  out: Optional[np.ndarray] = None) -> np.ndarray:
        """Host-side allreduce, op ∈ {sum, max, min}.

        Small payloads ride the binomial tree (reduce to root, broadcast
        back — 2·log2(n) hops); payloads at or above
        DMLC_COLL_RING_MIN_BYTES cut over to a bandwidth-optimal
        algorithm: the hierarchical ``hier`` path (reduce-scatter inside
        each host through the C shm collective, the chunked ring across
        host LEADERS only, broadcast back intra-host — so only one rank
        per host pays the network) when its setup succeeds, else the
        flat chunked ring over the tracker-brokered
        ``ring_prev``/``ring_next`` links.  ``algo`` ∈ {None, "tree",
        "ring", "hier"} pins the choice (None defers to
        ``DMLC_COLL_ALGO``, default ``auto``); the benchmark reports all
        three side by side.

        Inputs of any shape/contiguity are accepted: the payload is
        flattened to one contiguous 1-D view up front (copying at most
        once) and the result is reshaped back, so >1-D, 0-d and sliced
        arrays all reduce correctly.

        ``out`` (optional) is a preallocated C-contiguous result buffer
        of the same dtype and element count — pass the INPUT itself for
        a true in-place reduction.  This is the steady-state hot path:
        a fresh 64 MB result allocation costs more in page faults than
        the entire shm reduce-scatter on an oversubscribed host, and a
        training loop reducing gradients every step should pay it never
        rather than every step.

        Fully instrumented: a ``collective.allreduce`` span (op/byte/rank
        /algo tags) plus a ``barrier_enter`` event — on the tracker's
        corrected /trace timeline these spans line up across ranks, so
        the rank whose span STARTS last is the straggler by direct
        reading, and the ``barrier_wait_secs`` histogram (time blocked on
        the reduce wave) quantifies how long everyone else paid for it."""
        from .. import telemetry

        if algo not in (None, "tree", "ring", "hier"):
            raise ValueError(f"unknown allreduce algo {algo!r} "
                             "(expected None, 'tree', 'ring' or 'hier')")
        # flatten ONCE up front: a non-C-contiguous or >1-D input is
        # copied exactly here, and every algorithm below (the ring's
        # uint8 reinterpret, the shm path's raw pointer) sees the same
        # flat contiguous 1-D buffer.  0-d inputs become shape (1,).
        orig_shape = np.shape(arr)  # before ascontiguousarray: numpy 2
        arr = np.ascontiguousarray(arr)  # promotes 0-d to (1,)
        flat = arr.reshape(-1)
        if out is None:
            work = None  # lazily copied below (after the world-1 exit)
        else:
            if (not out.flags.c_contiguous or out.dtype != flat.dtype
                    or out.size != flat.size):
                raise ValueError(
                    "allreduce out= must be C-contiguous with the "
                    "input's dtype and element count")
            work = out.reshape(-1)
            if not np.shares_memory(work, flat):
                np.copyto(work, flat)
        if self.world_size <= 1:
            if work is None:
                return flat.copy().reshape(orig_shape)
            return work.reshape(orig_shape)
        if work is None:
            work = flat.copy()
        if algo is None:
            # NB: the cutover must be gang-uniform — every rank has to
            # pick the same algorithm for the same collective or the
            # byte streams desynchronize (the launcher propagates one
            # env to all workers, so the DMLC_COLL_* knobs are uniform
            # unless an operator splits them on purpose).  Selection is
            # therefore a pure function of (env, payload size/dtype); a
            # rank whose ring links are missing fails loudly below
            # instead of silently diverging onto the tree.  The hier
            # path's availability is itself made gang-uniform by the
            # MIN-veto inside _hier_state().
            algo = _coll_algo_env()
            if algo == "auto":
                min_bytes = _ring_min_bytes()
                hier_min = _hier_min_bytes()
                if (hier_min >= 0 and flat.nbytes >= hier_min
                        and self._hier_wanted(flat.dtype)):
                    algo = "hier"
                elif min_bytes >= 0 and flat.nbytes >= min_bytes:
                    algo = "ring"
                else:
                    algo = "tree"
        if algo == "hier":
            try:
                hier_ok = self._hier_ready(flat.dtype)
            except OSError as e:
                # the setup's gang-wide veto is itself a tree collective;
                # a peer preempted during it must surface as the same
                # retryable signal as one lost mid-fold below
                if self.elastic:
                    self._resized(f"peer lost during hier setup: {e}",
                                  cause=e)
                raise
            if not hier_ok:
                # uniform degrade (veto'd setup / bad dtype): to the
                # ring where bandwidth dominates, the tree below its
                # cutover
                min_bytes = _ring_min_bytes()
                algo = ("ring" if min_bytes >= 0
                        and flat.nbytes >= min_bytes else "tree")
        if algo == "ring" and (self.ring_prev not in self.links
                               or self.ring_next not in self.links):
            raise ConnectionError(
                f"rank {self.rank}: ring allreduce selected but ring "
                f"links ({self.ring_prev}, {self.ring_next}) are not "
                "established — topology bug or partial recovery")
        self.check_resized()
        telemetry.record_event("barrier_enter", site="allreduce", op=op,
                               rank=self.rank, bytes=int(flat.nbytes))
        with telemetry.span("collective.allreduce", stage="collective",
                            args={"op": op, "bytes": int(flat.nbytes),
                                  "rank": self.rank, "algo": algo}):
            try:
                if algo == "hier":
                    self._hier_allreduce(work, op)
                elif algo == "ring":
                    self._ring_allreduce(work, op)
                else:
                    self._tree_allreduce(work, op)
                return work.reshape(orig_shape)
            except OSError as e:
                if self.elastic:
                    # peer lost mid-fold (preemption): retryable resize
                    # signal instead of a crash; closing our links below
                    # cascades the wake-up to peers blocked on us
                    self._resized(f"peer lost mid-allreduce: {e}", cause=e)
                raise

    def _tree_allreduce(self, acc: np.ndarray, op: str) -> np.ndarray:
        """Binomial tree, IN PLACE on ``acc`` (the caller owns the
        buffer: ``allreduce`` hands a private copy, or the caller's own
        array via ``out=``)."""
        from .. import telemetry

        fold = {"sum": np.add, "max": np.maximum, "min": np.minimum}[op]
        children = [r for r in self.tree_nbrs if r != self.parent]
        t0 = time.perf_counter()
        for c in children:
            fold(acc, self._recv_array(self.links[c], acc), out=acc)
        if self.parent >= 0:
            self._send_array(self.links[self.parent], acc)
            np.copyto(acc, self._recv_array(self.links[self.parent], acc))
        # the reduce wave completes here: everything this rank spent
        # blocked on slower subtree/parent progress is barrier wait
        telemetry.observe_duration("collective", "barrier_wait",
                                   time.perf_counter() - t0)
        for c in children:
            self._send_array(self.links[c], acc)
        return acc

    def _ring_duplex(self, snd: socket.socket, rcv: socket.socket,
                     send_mv: memoryview, recv_mv: memoryview):
        """Push ``send_mv`` to ``snd`` while pulling ``recv_mv`` from
        ``rcv``, progressing whichever direction is ready — full-duplex
        on blocking sockets without helper threads, and deadlock-free
        when the chunk exceeds the socket buffers (every rank sends and
        receives simultaneously).  The two links are the same socket at
        ring size == 2."""
        # Non-blocking for the duplex, whatever the op-timeout setting:
        # with DMLC_CLIENT_OP_TIMEOUT_S=0 the sockets are fully blocking
        # and send() of a piece larger than the free socket buffer would
        # park until the PEER drains — but every rank is in the same
        # loop, so nobody would ever reach its recv and the whole ring
        # would deadlock.  Non-blocking send() enqueues what fits and
        # returns; progress then strictly follows select() readiness.
        prev_timeouts = (snd.gettimeout(), rcv.gettimeout())
        snd.setblocking(False)
        rcv.setblocking(False)
        ns, ng = len(send_mv), len(recv_mv)
        sent, got = 0, 0
        try:
            while sent < ns or got < ng:
                rs, ws, _ = select.select(
                    [rcv] if got < ng else [],
                    [snd] if sent < ns else [], [],
                    _op_timeout() or None)
                if not rs and not ws:
                    raise socket.timeout("ring allreduce stalled")
                if rs:
                    try:
                        k = rcv.recv_into(recv_mv[got:got + _RING_PIECE])
                    except BlockingIOError:
                        k = None  # spurious readiness; retry via select
                    if k == 0:
                        raise ConnectionError(
                            "ring peer closed mid-collective")
                    if k:
                        got += k
                if ws:
                    try:
                        sent += snd.send(send_mv[sent:sent + _RING_PIECE])
                    except BlockingIOError:
                        pass
        finally:
            snd.settimeout(prev_timeouts[0])
            rcv.settimeout(prev_timeouts[1])

    def _ring_allreduce(self, out: np.ndarray, op: str) -> np.ndarray:
        """Chunked ring over the whole world (the tracker-brokered
        ``ring_prev``/``ring_next`` links), IN PLACE on ``out``."""
        self._ring_pass(out, op, self.ring_prev, self.ring_next,
                        self.world_size, self.rank)
        return out

    def _ring_pass(self, out: np.ndarray, op: str, prev_rank: int,
                   next_rank: int, n: int, idx: int) -> None:
        """In-place chunked ring allreduce over an arbitrary sub-ring:
        n-1 reduce-scatter steps (each member ends up owning the full
        reduction of one payload slice) followed by n-1 allgather steps
        circulating the reduced slices.  The flat world ring
        (``idx``/``n`` = rank/world) and the hier leader ring
        (``idx``/``n`` = leader index/host count) share this code; the
        links must already exist in ``self.links``."""
        from .. import telemetry

        if n <= 1:
            return
        fold = {"sum": np.add, "max": np.maximum, "min": np.minimum}[op]
        nxt, prv = self.links[next_rank], self.links[prev_rank]
        # the ring's bulk transfers are raw (headerless) byte streams,
        # so the generation check happens ONCE up front: exchange gen
        # ids around the ring (a 2-member ring collapses both
        # directions onto one socket, which still works)
        nxt.send_int(self.gen)
        peer_gen = prv.recv_int()
        if peer_gen != self.gen:
            self._resized(f"stale-generation ring peer (gen {peer_gen}, "
                          f"ours {self.gen})")
        flat = out.view(np.uint8).reshape(-1)
        item = out.itemsize
        per = ((out.size + n - 1) // n) * item
        bounds = [min(i * per, flat.size) for i in range(n + 1)]
        scratch = np.empty(per, np.uint8)
        t0 = time.perf_counter()
        for s in range(n - 1):  # reduce-scatter
            si, ri = (idx - s) % n, (idx - s - 1) % n
            slo, shi = bounds[si], bounds[si + 1]
            rlo, rhi = bounds[ri], bounds[ri + 1]
            self._ring_duplex(nxt.sock, prv.sock,
                              memoryview(flat[slo:shi]),
                              memoryview(scratch[: rhi - rlo]))
            if rhi > rlo:
                dst = flat[rlo:rhi].view(out.dtype)
                fold(dst, scratch[: rhi - rlo].view(out.dtype), out=dst)
        # every member now owns the reduced slice (idx+1) % n; the
        # reduce wave completes here (straggler wait, as in the tree)
        telemetry.observe_duration("collective", "barrier_wait",
                                   time.perf_counter() - t0)
        for s in range(n - 1):  # allgather
            si, ri = (idx + 1 - s) % n, (idx - s) % n
            slo, shi = bounds[si], bounds[si + 1]
            rlo, rhi = bounds[ri], bounds[ri + 1]
            self._ring_duplex(nxt.sock, prv.sock,
                              memoryview(flat[slo:shi]),
                              memoryview(flat[rlo:rhi]))

    # ---- hierarchical allreduce (shm intra-host + ring across hosts) ----
    def _hier_wanted(self, dtype) -> bool:
        """Cheap gang-uniform pre-checks for the auto selector: dtype
        foldable by the shm collective and shm not env-disabled.  Library
        availability is deliberately NOT checked here (it can differ per
        host); _hier_state()'s MIN-veto makes the real verdict uniform."""
        from ..native import shm_collective as shmc

        return shmc.supports_dtype(dtype) and get_env("DMLC_COLL_SHM", 1) != 0

    def _hier_ready(self, dtype) -> bool:
        """True when the hier path can run this payload: dtype is
        shm-foldable and the per-generation setup (collective on first
        use) survived the gang-wide veto."""
        from ..native import shm_collective as shmc

        if not shmc.supports_dtype(dtype):
            return False
        return self._hier_state().ok

    def _hier_state(self) -> _HierState:
        """The per-generation hier state, set up collectively on first
        use.  EVERY rank must reach this from the same collective call
        (selection is a pure function of uniform env + payload), because
        setup ends in a MIN-allreduce veto over the tree: one rank that
        failed to map its segment or dial a leader flips the whole gang
        to the flat ring instead of leaving it split across algorithms."""
        st = self._hier
        if st is not None and st.gen == self.gen:
            return st
        self._hier_teardown()
        st = self._hier_setup()
        self._hier = st
        return st

    def _hier_teardown(self) -> None:
        st, self._hier = self._hier, None
        if st is not None and st.shm is not None:
            # abort BEFORE unmap: peers blocked in an shm phase wake
            # with an error instead of spinning out the timeout
            st.shm.abort()
            st.shm.close()
            st.shm = None

    def _query_hostmap(self) -> dict:
        """Short ``hosts`` session: the tracker's rank → (host, port)
        job map for the current generation."""
        fs = self._session("hosts", self.rank, -1)
        try:
            return json.loads(fs.recv_str())
        finally:
            fs.close()

    def _host_groups(self):
        """(groups, hostports): ranks grouped by host (auto, from the
        tracker's job map) or by rank blocks of ``DMLC_COLL_HIER_GROUPS``
        (an explicit topology override, also how CI exercises the
        leader ring on one box).  Polls the tracker until the map covers
        the whole world — a worker still mid-brokering has no accept
        port yet."""
        deadline = time.monotonic() + get_env(
            "DMLC_COLL_HIER_SETUP_TIMEOUT_S", 20.0)
        hostports: Dict[int, tuple] = {}
        while True:
            doc = self._query_hostmap()
            if int(doc.get("gen", 0)) != self.gen:
                raise ValueError("world generation changed during hier "
                                 "setup")
            hosts = doc.get("hosts", {})
            if len(hosts) >= self.world_size:
                hostports = {int(r): (h, int(p))
                             for r, (h, p) in hosts.items()}
                if all(r in hostports for r in range(self.world_size)):
                    break
            if time.monotonic() > deadline:
                raise ValueError(
                    f"tracker job map covers {len(hosts)}/"
                    f"{self.world_size} ranks (workers still brokering?)")
            time.sleep(0.2)
        block = get_env("DMLC_COLL_HIER_GROUPS", 0)
        if block > 0:
            groups = [list(range(i, min(i + block, self.world_size)))
                      for i in range(0, self.world_size, block)]
        else:
            by_host: Dict[str, list] = {}
            for r in range(self.world_size):
                by_host.setdefault(hostports[r][0], []).append(r)
            groups = sorted(by_host.values(), key=lambda g: g[0])
        return groups, hostports

    def _ensure_leader_links(self, need, hostports) -> None:
        """Direct leader-to-leader links for the inter-host ring.  The
        tracker-brokered overlay may already connect some leader pairs
        (tree/ring neighbours) — those sockets are reused; missing pairs
        are dialed directly with the standard (MAGIC, rank) peer
        identification, lower rank dialing higher (a DAG, so the dial/
        accept order can never cycle into a deadlock).  New links land
        in ``self.links`` so teardown and the WorldResized cascade cover
        them like any brokered link."""
        setup_t = get_env("DMLC_COLL_HIER_SETUP_TIMEOUT_S", 20.0)
        to_accept = set()
        for peer in sorted(need):
            if peer == self.rank or peer in self.links:
                continue
            if self.rank < peer:
                host, port = hostports[peer]
                self.links[peer] = self._dial_peer(host, port, peer,
                                                   handshake_timeout=setup_t)
            else:
                to_accept.add(peer)
        # bound the accept wait by the SETUP timeout, not the op
        # timeout: a remote leader that bailed on its own setup (shm
        # veto) never dials, and a 300 s stall here would wedge the
        # whole gang's veto allreduce behind this one rank
        prev_timeout = self._listener.gettimeout()
        if to_accept:
            self._listener.settimeout(setup_t)
        try:
            self._accept_leader_links(to_accept)
        finally:
            self._listener.settimeout(prev_timeout)

    def _accept_leader_links(self, to_accept) -> None:
        while to_accept:
            conn, _ = self._listener.accept()
            conn.settimeout(_op_timeout())
            ps = FrameSocket(conn)
            try:
                if ps.recv_int() != MAGIC:
                    raise ConnectionError("bad magic")
                peer = ps.recv_int()
                if peer not in to_accept:
                    raise ConnectionError(f"unexpected dialer rank {peer}")
                ps.send_int(MAGIC)
                ps.send_int(self.rank)
            except (OSError, ConnectionError):
                ps.close()
                continue  # stray/torn dial: keep waiting for real peers
            self.links[peer] = ps
            to_accept.discard(peer)

    def _hier_setup(self) -> _HierState:
        """Collective hier setup: host grouping from the tracker job
        map, one shm group per multi-rank host, leader-ring links —
        ending in the gang-wide MIN veto that keeps the algorithm
        choice uniform.  Never raises for setup-class failures (those
        veto); link-level OSErrors during the veto itself propagate
        like any collective error."""
        from ..native import shm_collective as shmc

        st = _HierState(self.gen)
        ok = True
        groups = []
        hostports: Dict[int, tuple] = {}
        try:
            groups, hostports = self._host_groups()
        except (OSError, ValueError, ConnectionError) as e:
            logger.warning("rank %d: hier host grouping failed: %s",
                           self.rank, e)
            ok = False
        if ok:
            st.group = next(g for g in groups if self.rank in g)
            st.leaders = [g[0] for g in groups]
            st.n_groups = len(groups)
            st.leader = st.group[0]
            st.local_rank = st.group.index(self.rank)
            st.leader_idx = st.leaders.index(st.leader)
            if all(len(g) == 1 for g in groups):
                ok = False  # no intra-host sharing: hier ≡ ring + overhead
        if ok and len(st.group) > 1:
            try:
                chunk_kb = get_env("DMLC_COLL_SHM_CHUNK_KB", 0)
                st.shm = shmc.ShmCollective(
                    f"dmlc-hier-{self.tracker_port}-{self.gen}-{st.leader}",
                    st.local_rank, len(st.group), chunk_kb=chunk_kb)
            except shmc.ShmGroupError as e:
                logger.warning("rank %d: hier shm group setup failed: %s",
                               self.rank, e)
                ok = False
        if ok and st.n_groups > 1 and self.rank == st.leader:
            try:
                prev = st.leaders[(st.leader_idx - 1) % st.n_groups]
                nxt = st.leaders[(st.leader_idx + 1) % st.n_groups]
                self._ensure_leader_links({prev, nxt}, hostports)
            except (OSError, ConnectionError) as e:
                logger.warning("rank %d: hier leader-link setup failed: "
                               "%s", self.rank, e)
                ok = False
        # gang-wide veto: every rank reaches this allreduce (setup-class
        # failures above only flip `ok`), so the verdict is uniform
        verdict = self._tree_allreduce(
            np.asarray([1 if ok else 0], np.int32), "min")
        st.ok = bool(int(verdict[0]))
        if not st.ok:
            if st.shm is not None:
                st.shm.close()
                st.shm = None
            if not st.warned:
                st.warned = True
                logger.info(
                    "rank %d: hierarchical allreduce unavailable this "
                    "generation; using the flat ring", self.rank)
        return st

    def _hier_allreduce(self, out: np.ndarray, op: str) -> np.ndarray:
        """Hierarchy, IN PLACE on ``out``: reduce-scatter + allgather
        inside the host over the C shm collective (= intra-host
        allreduce, one streaming fold per member), chunked TCP ring
        across host leaders only, then an intra-host shm broadcast of
        the global result — network traffic is one ring's worth per
        HOST instead of per rank."""
        from ..native.shm_collective import ShmGroupError

        st = self._hier
        try:
            if st.shm is not None:
                st.shm.reduce_scatter(out, op)
                st.shm.allgather(out)
            if st.n_groups > 1:
                if self.rank == st.leader:
                    prev = st.leaders[(st.leader_idx - 1) % st.n_groups]
                    nxt = st.leaders[(st.leader_idx + 1) % st.n_groups]
                    self._ring_pass(out, op, prev, nxt, st.n_groups,
                                    st.leader_idx)
                if st.shm is not None:
                    st.shm.broadcast(out, root=0)
        except ShmGroupError as e:
            if self.elastic:
                # a same-host peer bailed (resize cascade reached the
                # group, or it died and the wait timed out): retryable
                self._resized(f"shm group failed mid-allreduce: {e}",
                              cause=e)
            raise ConnectionError(str(e)) from e
        return out

    def allreduce_sum(self, arr: np.ndarray,
                      out: Optional[np.ndarray] = None) -> np.ndarray:
        return self.allreduce(arr, "sum", out=out)

    def broadcast(self, arr: np.ndarray, root: int = 0) -> np.ndarray:
        """Tree broadcast from root (root's value wins everywhere).
        Instrumented like :meth:`allreduce` (span + barrier event)."""
        from .. import telemetry

        arr = np.ascontiguousarray(arr)
        if self.world_size <= 1:
            return arr.copy()
        assert root == 0, "tree broadcast is rooted at rank 0"
        self.check_resized()
        telemetry.record_event("barrier_enter", site="broadcast",
                               rank=self.rank, bytes=int(arr.nbytes))
        with telemetry.span("collective.broadcast", stage="collective",
                            args={"bytes": int(arr.nbytes),
                                  "rank": self.rank}):
            try:
                children = [r for r in self.tree_nbrs if r != self.parent]
                out = arr
                if self.parent >= 0:
                    t0 = time.perf_counter()
                    out = self._recv_array(self.links[self.parent], arr)
                    telemetry.observe_duration("collective", "barrier_wait",
                                               time.perf_counter() - t0)
                for c in children:
                    self._send_array(self.links[c], out)
            except OSError as e:
                if self.elastic:
                    self._resized(f"peer lost mid-broadcast: {e}", cause=e)
                raise
        return out.copy() if out is arr else out
