"""Global name->factory registries with aliases and docs.

Rebuild of reference include/dmlc/registry.h:26-306 (Registry<EntryType>,
DMLC_REGISTRY_ENABLE/REGISTER, FunctionRegEntryBase). Python modules are the
natural link-tag mechanism, so DMLC_REGISTRY_FILE_TAG/LINK_TAG (:259-301)
map to plain imports.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, TypeVar

from .base import DMLCError

__all__ = ["Registry", "RegistryEntry"]

T = TypeVar("T")


class RegistryEntry(Generic[T]):
    """name + factory + metadata (FunctionRegEntryBase, registry.h:184-226)."""

    def __init__(self, name: str, body: Callable[..., T]):
        self.name = name
        self.body = body
        self.description = ""
        self.arguments: List[Dict[str, str]] = []
        self.return_type = ""

    def describe(self, text: str) -> "RegistryEntry[T]":
        self.description = text
        return self

    def add_argument(self, name: str, type_info: str, desc: str) -> "RegistryEntry[T]":
        self.arguments.append({"name": name, "type_info_str": type_info, "description": desc})
        return self

    def set_return_type(self, ty: str) -> "RegistryEntry[T]":
        self.return_type = ty
        return self

    def __call__(self, *args, **kwargs) -> T:
        return self.body(*args, **kwargs)


class Registry(Generic[T]):
    """Per-kind global registry (registry.h:26-181). Use
    ``Registry.get('parser')`` for the singleton of a kind."""

    _registries: Dict[str, "Registry"] = {}

    def __init__(self, kind: str):
        self.kind = kind
        self._entries: Dict[str, RegistryEntry[T]] = {}
        self._canonical: Dict[str, str] = {}  # alias -> canonical name

    @classmethod
    def get(cls, kind: str) -> "Registry":
        reg = cls._registries.get(kind)
        if reg is None:
            reg = cls._registries[kind] = Registry(kind)
        return reg

    def register(self, name: str, body: Optional[Callable[..., T]] = None, override: bool = False):
        """Register a factory; usable as decorator::

            @Registry.get('parser').register('libsvm')
            def make_libsvm(...): ...
        """

        def do_register(fn: Callable[..., T]) -> Callable[..., T]:
            if name in self._entries and not override:
                raise DMLCError(f"{self.kind} registry: {name!r} already registered")
            self._entries[name] = RegistryEntry(name, fn)
            self._canonical[name] = name
            return fn

        if body is None:
            return do_register
        do_register(body)
        return self._entries[name]

    def entry(self, name: str) -> RegistryEntry[T]:
        """Fetch the entry object (to attach description/arguments)."""
        found = self.find(name)
        if found is None:
            raise DMLCError(f"{self.kind} registry: {name!r} not found")
        return found

    def add_alias(self, name: str, alias: str) -> None:
        """registry.h:108-118."""
        if name not in self._entries:
            raise DMLCError(f"{self.kind} registry: cannot alias unknown {name!r}")
        if alias in self._canonical and self._canonical[alias] != name:
            raise DMLCError(f"{self.kind} registry: alias {alias!r} already taken")
        self._canonical[alias] = name

    def find(self, name: str) -> Optional[RegistryEntry[T]]:
        canon = self._canonical.get(name)
        return self._entries.get(canon) if canon else None

    def create(self, name: str, *args, **kwargs) -> T:
        e = self.find(name)
        if e is None:
            raise DMLCError(
                f"{self.kind} registry: unknown entry {name!r}; "
                f"known: {self.list_all_names()}"
            )
        return e.body(*args, **kwargs)

    def list_entries(self) -> List[RegistryEntry[T]]:
        return list(self._entries.values())

    def list_all_names(self) -> List[str]:
        return sorted(self._canonical)
