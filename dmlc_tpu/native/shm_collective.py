"""ctypes binding for the same-host shm collective group
(cpp/dmlc_collective.cc: ``dmlc_shm_coll_*``) — the intra-host leg of
the hierarchical host allreduce in tracker/client.py.

The shared library is compiled on demand with g++ (one-time, cached
next to this package, same pattern as the dmlc_native bindings); the
hier algorithm degrades to the flat ring when the build or the segment
mapping fails, so nothing here is load-bearing for correctness.  Set
``DMLC_TPU_DISABLE_NATIVE=1`` to force that fallback.

Calls release the GIL for their duration (plain ctypes), so a
reduce-scatter on the background collective thread genuinely overlaps
Python-side work on the training thread.
"""

from __future__ import annotations

import ctypes
import os
from typing import Optional

import numpy as np
from ..concurrency import make_lock

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(os.path.dirname(_HERE), "cpp", "dmlc_collective.cc")
_SO = os.path.join(_HERE, "libdmlc_collective.so")

_lib = None
_lib_lock = make_lock("shm_collective._lib_lock")
_tried = False

#: numpy dtype -> dmlc_collective.h dtype code (DMLC_F32..DMLC_I64)
DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
}

#: op name -> dmlc_collective.h op code (DMLC_SUM/MAX/MIN)
OP_CODES = {"sum": 0, "max": 1, "min": 2}


def _build() -> Optional[str]:
    from . import compile_so

    # -lrt: shm_open lives in librt on glibc < 2.34 (a no-op stub after)
    return compile_so(_SRC, _SO, ["-lrt"],
                      "hier allreduce will fall back to the flat ring")


def _load():
    global _lib, _tried
    with _lib_lock:
        if _tried:
            return _lib
        _tried = True
        from ..base import get_env

        if get_env("DMLC_TPU_DISABLE_NATIVE", False):
            return None
        so = _build()
        if so is None:
            return None
        try:
            lib = ctypes.CDLL(so)
        except OSError:
            return None
        c = ctypes
        lib.dmlc_shm_coll_create.restype = c.c_void_p
        lib.dmlc_shm_coll_create.argtypes = [c.c_char_p, c.c_int, c.c_int,
                                             c.c_long]
        lib.dmlc_shm_coll_reduce_scatter.restype = c.c_int
        lib.dmlc_shm_coll_reduce_scatter.argtypes = [
            c.c_void_p, c.c_void_p, c.c_long, c.c_int, c.c_int]
        lib.dmlc_shm_coll_allgather.restype = c.c_int
        lib.dmlc_shm_coll_allgather.argtypes = [
            c.c_void_p, c.c_void_p, c.c_long, c.c_int]
        lib.dmlc_shm_coll_broadcast.restype = c.c_int
        lib.dmlc_shm_coll_broadcast.argtypes = [
            c.c_void_p, c.c_void_p, c.c_long, c.c_int]
        lib.dmlc_shm_coll_allreduce.restype = c.c_int
        lib.dmlc_shm_coll_allreduce.argtypes = [
            c.c_void_p, c.c_void_p, c.c_long, c.c_int, c.c_int]
        lib.dmlc_shm_coll_abort.restype = None
        lib.dmlc_shm_coll_abort.argtypes = [c.c_void_p]
        lib.dmlc_shm_coll_destroy.restype = None
        lib.dmlc_shm_coll_destroy.argtypes = [c.c_void_p]
        lib.dmlc_shm_coll_last_error.restype = c.c_char_p
        lib.dmlc_shm_coll_last_error.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def supports_dtype(dtype) -> bool:
    return np.dtype(dtype) in DTYPE_CODES


class ShmGroupError(RuntimeError):
    """A shm group collective failed (timeout, abort, divergent gang)."""


class ShmCollective:
    """One process's handle on a same-host shm collective group.

    ``name`` must be agreed by every member out of band (the hier path
    derives it from tracker port + world generation + group leader);
    ``rank`` is the dense intra-group rank, with rank 0 creating the
    segment.  Construction is collective — it blocks until the whole
    group attached (``DMLC_COLL_SHM_JOIN_TIMEOUT_S``) and raises
    :class:`ShmGroupError` on failure, after which the caller falls
    back to TCP paths.
    """

    def __init__(self, name: str, rank: int, world: int,
                 chunk_kb: int = 0):
        self._lib = _load()
        self._handle = None
        if self._lib is None:
            raise ShmGroupError("native collective library unavailable")
        self.rank, self.world = rank, world
        h = self._lib.dmlc_shm_coll_create(
            name.encode(), int(rank), int(world), int(chunk_kb))
        if not h:
            err = self._lib.dmlc_shm_coll_last_error(None)
            raise ShmGroupError(
                f"shm group create failed: {err.decode(errors='replace')}")
        self._handle = h

    def _check(self, rc: int, what: str) -> None:
        if rc == 0:
            return
        err = self._lib.dmlc_shm_coll_last_error(self._handle)
        raise ShmGroupError(
            f"shm {what} failed (rc {rc}): {err.decode(errors='replace')}")

    @staticmethod
    def _codes(arr: np.ndarray, op: Optional[str]):
        dt = DTYPE_CODES.get(arr.dtype)
        if dt is None:
            raise ShmGroupError(f"unsupported dtype {arr.dtype}")
        if op is None:
            return dt, None
        return dt, OP_CODES[op]

    def reduce_scatter(self, arr: np.ndarray, op: str = "sum") -> None:
        """In-place: this rank's per-chunk slice becomes the fold of
        every member's values; the rest of ``arr`` is untouched."""
        assert arr.flags.c_contiguous and arr.ndim == 1
        dt, opc = self._codes(arr, op)
        self._check(self._lib.dmlc_shm_coll_reduce_scatter(
            self._handle, arr.ctypes.data, arr.size, dt, opc),
            "reduce_scatter")

    def allgather(self, arr: np.ndarray) -> None:
        """In-place gather of the per-chunk slices reduce_scatter left
        resident — RS followed by AG is a full allreduce."""
        assert arr.flags.c_contiguous and arr.ndim == 1
        dt, _ = self._codes(arr, None)
        self._check(self._lib.dmlc_shm_coll_allgather(
            self._handle, arr.ctypes.data, arr.size, dt), "allgather")

    def broadcast(self, arr: np.ndarray, root: int = 0) -> None:
        assert arr.flags.c_contiguous
        self._check(self._lib.dmlc_shm_coll_broadcast(
            self._handle, arr.ctypes.data, arr.nbytes, int(root)),
            "broadcast")

    def allreduce(self, arr: np.ndarray, op: str = "sum") -> None:
        assert arr.flags.c_contiguous and arr.ndim == 1
        dt, opc = self._codes(arr, op)
        self._check(self._lib.dmlc_shm_coll_allreduce(
            self._handle, arr.ctypes.data, arr.size, dt, opc), "allreduce")

    def abort(self) -> None:
        """Poison the group: members blocked in a collective wake with
        an error instead of spinning to the timeout (the shm half of
        the elastic WorldResized cascade)."""
        if self._handle is not None:
            self._lib.dmlc_shm_coll_abort(self._handle)

    def close(self) -> None:
        if self._handle is not None:
            self._lib.dmlc_shm_coll_destroy(self._handle)
            self._handle = None

    def __del__(self):  # best-effort unmap
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
